"""Measure the in-image CPU baselines that back bench.py's vs_baseline.

The reference publishes no absolute wall-clock numbers (BASELINE.md), so
round-2 benches compared against folklore constants. This script replaces
them with measured-vs-measured comparisons on THIS machine:

1. ``higgs1m_sklearn_hgb_wall_s`` — sklearn HistGradientBoosting on the
   exact HIGGS-shaped config bench.py times for the GBDT engine
   (1M x 28, 63 leaves, 63 bins-ish, 40 iterations, min 50 rows/leaf,
   identical synthetic data seed). sklearn's HGB is the strongest
   CPU histogram-GBDT available in-image (no lightgbm binary exists here).
2. ``cifar_convnet_torch_cpu_imgs_per_sec`` — torch (CPU) training
   throughput of the same notebook-401 ConvNet shape bench.py trains
   (3x conv64-3x3 + maxpool, dense 256, 10 classes, batch 512).

Results land in BASELINE.json under "measured" with the machine + date;
bench.py prefers them over the historical constants automatically.

Run: ``python tools/measure_baseline.py`` (takes a few minutes).
"""

import json
import os
import platform
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

HIGGS_N, HIGGS_F = 1_000_000, 28


def measure_hgb() -> dict:
    from sklearn.ensemble import HistGradientBoostingClassifier

    rng = np.random.default_rng(0)
    X = rng.normal(size=(HIGGS_N, HIGGS_F)).astype(np.float32)
    logit = (X[:, 0] * 1.5 + X[:, 1] * X[:, 2]
             + 0.5 * np.sin(3 * X[:, 3])
             + rng.normal(scale=0.5, size=HIGGS_N))
    y = (logit > 0).astype(np.int64)

    clf = HistGradientBoostingClassifier(
        max_iter=40, max_leaf_nodes=63, max_bins=63,
        min_samples_leaf=50, early_stopping=False, random_state=0)
    t0 = time.time()
    clf.fit(X, y)
    wall = time.time() - t0
    return {"higgs1m_sklearn_hgb_wall_s": round(wall, 1),
            "higgs1m_sklearn_hgb_config":
                "HistGradientBoostingClassifier(max_iter=40, "
                "max_leaf_nodes=63, max_bins=63, min_samples_leaf=50)"}


def measure_torch_convnet() -> dict:
    import torch
    import torch.nn as nn

    torch.manual_seed(0)

    model = nn.Sequential(
        nn.Conv2d(3, 64, 3, padding=1), nn.ReLU(), nn.MaxPool2d(2),
        nn.Conv2d(64, 64, 3, padding=1), nn.ReLU(), nn.MaxPool2d(2),
        nn.Conv2d(64, 64, 3, padding=1), nn.ReLU(), nn.MaxPool2d(2),
        nn.Flatten(), nn.Linear(64 * 4 * 4, 256), nn.ReLU(),
        nn.Linear(256, 10))
    opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
    loss_fn = nn.CrossEntropyLoss()

    batch = 512
    x = torch.randn(batch, 3, 32, 32)
    y = torch.randint(0, 10, (batch,))

    def step():
        opt.zero_grad()
        loss_fn(model(x), y).backward()
        opt.step()

    for _ in range(3):  # warmup
        step()
    steps = 20
    t0 = time.time()
    for _ in range(steps):
        step()
    wall = time.time() - t0
    return {"cifar_convnet_torch_cpu_imgs_per_sec":
                round(steps * batch / wall, 1),
            "cifar_convnet_torch_cpu_config":
                f"batch {batch}, 3x conv64-3x3+pool, dense 256, "
                f"SGD momentum, {os.cpu_count()} cores"}


def main():
    measured = {}
    print("measuring sklearn HistGradientBoosting (1M x 28, 40 iters)...")
    measured.update(measure_hgb())
    print(f"  -> {measured['higgs1m_sklearn_hgb_wall_s']} s")
    print("measuring torch-CPU ConvNet throughput...")
    measured.update(measure_torch_convnet())
    print(f"  -> {measured['cifar_convnet_torch_cpu_imgs_per_sec']} imgs/s")
    measured["machine"] = f"{platform.machine()}, {os.cpu_count()} cores"
    measured["date"] = time.strftime("%Y-%m-%d")

    path = os.path.join(ROOT, "BASELINE.json")
    with open(path) as f:
        doc = json.load(f)
    doc["measured"] = measured
    with open(path, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    print(f"wrote measured baselines to {path}")


if __name__ == "__main__":
    main()
