"""Static audit of every Prometheus family the codebase renders.

The /metrics surface has grown across five PRs (engine counters, swap
state, zoo families, ingress phases, SLO burn rates) and its contracts
are easy to regress one call site at a time: a counter without the
``_total`` suffix breaks downstream PromQL idioms, a family without
HELP text fails strict scrapers, and one unbounded ``model=...`` label
re-opens the cardinality hole the zoo's hard cap closed. The runtime
grammar validator (tests/test_observability.py) only checks what a
given test run happens to render; this checker audits the SOURCE — the
kernel-checker discipline (tools/check_fusion_kernels.py) applied to
the metrics plane.

What it checks, per renderer call site (``r.counter`` / ``r.gauge`` /
``r.histogram`` / ``r.info`` / ``r.sample`` in the audited modules):

1. **HELP present** — the help-text argument is a non-empty string
   literal (the renderer emits ``# HELP``/``# TYPE`` from it; an empty
   or dynamic help is a docs hole at scrape time).
2. **Naming conventions** — counters end ``_total``; histogram
   families end in a unit suffix (``_ms``/``_s``/``_rows``/
   ``_bytes``); gauges/infos must NOT end in ``_total`` or the
   reserved histogram suffixes (``_bucket``/``_sum``/``_count``).
3. **Dynamic names declared** — an f-string family name (e.g.
   ``f"serving_{name}"``) must appear in ``DYNAMIC_OK`` with its full
   expected expansion list, and every expansion passes rule 2: the
   audit must never shrug at a name it cannot see.
4. **Cardinality caps declared** — any family labelled with an
   unbounded-identity key (``model``/``version``/``tenant``) must be
   listed in ``CAPPED_FAMILIES``, whose entries are families documented
   to render under a hard cap (zoo ``label_cardinality_cap``, SLO
   ``label_cap``). A new per-model family is a one-line diff here —
   made consciously, with the cap story written down.
5. **Raw samples continue a family** — ``r.sample`` (header-less) must
   reuse a family name already declared by a headered call in the same
   module.

Run from the repo root::

    python tools/check_metrics.py

Exit 1 + a listing on any violation. Tier-1 runs this from
tests/test_slo.py alongside the kernel checkers, plus
checker-catches-violation tests feeding known-bad snippets through
``audit_source``.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Any, Dict, List, Optional, Set, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# the modules that render Prometheus families
AUDIT_FILES = (
    "mmlspark_tpu/core/prometheus.py",
    "mmlspark_tpu/serving/server.py",
    "mmlspark_tpu/serving/fleet.py",
)

RENDER_METHODS = {"counter", "gauge", "histogram", "info", "sample"}
# receivers that LOOK like renderer calls but aren't (logger.info)
_EXCLUDED_RECEIVERS = {"log", "logger", "logging", "self", "cls"}

HISTOGRAM_SUFFIXES = ("_ms", "_s", "_rows", "_bytes")
RESERVED_SUFFIXES = ("_total", "_bucket", "_sum", "_count")

# label keys that identify an unbounded population: any family carrying
# one must declare its cardinality story in CAPPED_FAMILIES
UNBOUNDED_LABEL_KEYS = {"model", "version", "tenant", "feature"}

# families allowed to carry unbounded-identity labels, because their
# renderers are hard-capped at the source:
CAPPED_FAMILIES = {
    # zoo: resident-first rows capped at label_cardinality_cap;
    # latency overflow folds into model="_other" (docs/model_zoo.md)
    "serving_model_info",
    "serving_model_latency_ms",
    # SLO engine: per-model streams capped at SLOMonitor.label_cap,
    # overflow folds into "_other"; active alerts inherit the same
    # capped identity space (docs/observability.md)
    "serving_slo_model_burn_rate",
    "serving_slo_alert_active",
    # drift exposition: per-feature scores capped at DRIFT_FEATURE_CAP
    # (top-K by score), overflow folds into feature="_other"
    # (core/prometheus.py drift_families)
    "serving_drift_score",
    # placement plane: per-model replica gauges capped at
    # REPLICA_LABEL_CAP, overflow summed into model="_other"
    # (core/prometheus.py placement_families)
    "serving_placement_replicas",
    # variant plane: per-model rung/floor gauges + the info row capped
    # at VARIANT_LABEL_CAP declared ladders (core/prometheus.py
    # variant_families; docs/adaptive_serving.md)
    "serving_variant_rung",
    "serving_variant_floor",
    "serving_variant_info",
}

# dynamic (f-string) family names, with their FULL expected expansions —
# every expansion is suffix-checked like a literal. Key: the template
# with "{}" placeholders, as extracted from the JoinedStr.
DYNAMIC_OK: Dict[str, Tuple[str, ...]] = {
    # engine/fleet per-stage histograms + the warmup family
    "serving_{}": ("serving_queue_wait_ms", "serving_decode_ms",
                   "serving_pipeline_ms", "serving_respond_ms",
                   "serving_batch_rows", "serving_model_warmup_ms"),
    # pipeline_families: the model's own histogram hooks (TPUModel
    # pad/device split)
    "serving_model_{}": ("serving_model_pad_ms",
                         "serving_model_device_ms"),
    # device memory gauges (utils/profiling.device_memory_stats keys)
    "device_memory_{}": ("device_memory_bytes_in_use",
                         "device_memory_bytes_limit",
                         "device_memory_peak_bytes_in_use"),
}


class Violation:
    def __init__(self, filename: str, line: int, message: str):
        self.filename = filename
        self.line = line
        self.message = message

    def __repr__(self) -> str:
        return f"{self.filename}:{self.line}: {self.message}"


def _template_of(node: ast.AST) -> Optional[str]:
    """A Constant string verbatim; a JoinedStr as a "{}" template;
    None for anything the audit cannot see through."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts: List[str] = []
        for value in node.values:
            if isinstance(value, ast.Constant):
                parts.append(str(value.value))
            else:
                parts.append("{}")
        return "".join(parts)
    return None


def _label_keys(node: Optional[ast.AST]) -> Set[str]:
    """String keys of a labels argument: dict literals (including
    ``{**base, "k": v}`` — the spread contributes nothing statically)
    and dict() calls with keyword args."""
    keys: Set[str] = set()
    if node is None:
        return keys
    if isinstance(node, ast.Dict):
        for k in node.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                keys.add(k.value)
    elif isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Name) and node.func.id == "dict":
        for kw in node.keywords:
            if kw.arg is not None:
                keys.add(kw.arg)
    return keys


def _check_name(method: str, name: str, filename: str, line: int,
                out: List[Violation]) -> None:
    if method == "counter" and not name.endswith("_total"):
        out.append(Violation(
            filename, line,
            f"counter {name!r} must end in '_total'"))
    if method == "histogram" and \
            not name.endswith(HISTOGRAM_SUFFIXES):
        out.append(Violation(
            filename, line,
            f"histogram {name!r} must end in a unit suffix "
            f"{HISTOGRAM_SUFFIXES}"))
    if method in ("gauge", "info") and \
            name.endswith(RESERVED_SUFFIXES):
        out.append(Violation(
            filename, line,
            f"{method} {name!r} ends in a reserved suffix "
            f"{RESERVED_SUFFIXES} (counters own '_total'; histograms "
            f"own '_bucket'/'_sum'/'_count')"))


def audit_source(src: str, filename: str = "<string>"
                 ) -> List[Violation]:
    """Audit one module's source. Returns the violation list."""
    out: List[Violation] = []
    tree = ast.parse(src, filename=filename)
    declared: Set[str] = set()     # families with HELP in this module

    # source order, not ast.walk's BFS order: the sample-continues-a-
    # declared-family rule depends on seeing declarations first
    calls = sorted(
        (n for n in ast.walk(tree) if isinstance(n, ast.Call)),
        key=lambda n: (n.lineno, n.col_offset))
    for node in calls:
        func = node.func
        if not isinstance(func, ast.Attribute) or \
                func.attr not in RENDER_METHODS:
            continue
        if not isinstance(func.value, ast.Name) or \
                func.value.id in _EXCLUDED_RECEIVERS:
            continue
        method = func.attr
        line = node.lineno
        if not node.args:
            out.append(Violation(filename, line,
                                 f"{method} call with no name argument"))
            continue
        template = _template_of(node.args[0])
        if template is None:
            out.append(Violation(
                filename, line,
                f"{method} family name is not a (f-)string literal — "
                f"the audit cannot verify it; render through a literal "
                f"or an f-string declared in DYNAMIC_OK"))
            continue
        if "{}" in template:
            expansions = DYNAMIC_OK.get(template)
            if expansions is None:
                out.append(Violation(
                    filename, line,
                    f"dynamic family name {template!r} is not declared "
                    f"in DYNAMIC_OK (tools/check_metrics.py) — list its "
                    f"full expected expansions"))
                names: Tuple[str, ...] = ()
            else:
                names = expansions
        else:
            names = (template,)
        for name in names:
            _check_name(method, name, filename, line, out)
        # HELP text: 2nd positional (or help_text kw) must be a
        # non-empty string literal — except r.sample, which continues
        # an already-declared family (and must not mint one itself)
        if method == "sample":
            for name in names:
                if name not in declared:
                    out.append(Violation(
                        filename, line,
                        f"raw sample {name!r} does not continue a "
                        f"family declared (with HELP) in this module"))
            continue
        declared.update(names)
        help_node: Optional[ast.AST] = None
        if len(node.args) >= 2:
            help_node = node.args[1]
        else:
            for kw in node.keywords:
                if kw.arg == "help_text":
                    help_node = kw.value
        help_text = _template_of(help_node) if help_node is not None \
            else None
        if not help_text or not help_text.strip():
            out.append(Violation(
                filename, line,
                f"{method} family {names or template!r} has no literal "
                f"non-empty HELP text"))
        # cardinality: unbounded-identity labels require a declared cap
        labels_node: Optional[ast.AST] = None
        pos = {"counter": 3, "gauge": 3, "histogram": 3, "info": 2}
        if len(node.args) > pos[method]:
            labels_node = node.args[pos[method]]
        else:
            for kw in node.keywords:
                if kw.arg == "labels":
                    labels_node = kw.value
        hot = _label_keys(labels_node) & UNBOUNDED_LABEL_KEYS
        if hot:
            for name in names:
                if name not in CAPPED_FAMILIES:
                    out.append(Violation(
                        filename, line,
                        f"family {name!r} carries unbounded-identity "
                        f"label(s) {sorted(hot)} but is not declared in "
                        f"CAPPED_FAMILIES — document its hard "
                        f"cardinality cap first"))
    return out


def audit_file(path: str) -> List[Violation]:
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    return audit_source(src, filename=os.path.relpath(path, _REPO))


def main() -> int:
    violations: List[Violation] = []
    audited = 0
    for rel in AUDIT_FILES:
        path = os.path.join(_REPO, rel)
        violations += audit_file(path)
        audited += 1
    if violations:
        print(f"{len(violations)} metrics-exposition violation(s) "
              f"across {audited} audited modules:")
        for v in violations:
            print("  -", v)
        return 1
    print(f"OK: {audited} modules audited — every family has HELP, "
          f"passes naming conventions, and every unbounded label is "
          f"cap-declared")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
