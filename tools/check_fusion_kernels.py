"""Static no-host-round-trip check for fused-segment kernel code.

Fused pipeline programs (core/fusion.py) promise that everything between
a segment's H2D ship and its single D2H fetch stays on device. That
invariant is easy to regress silently: one `np.asarray(...)` inside a
DeviceOp ``fn`` turns the fused program into a trace-time host sync (or
a per-call constant re-ship) and the "one round trip per batch"
guarantee quietly dies while every test still passes.

This checker audits the SOURCE of every registered device kernel
(``core.fusion.KERNEL_REGISTRY`` — populated when ``device_op()`` builds
its DeviceOp) for host-round-trip constructs:

- ``np.*`` / ``numpy.*`` calls or attribute reads (host arrays inside a
  traced function force host<->device syncs or retrace-time constants),
- ``jax.device_get`` / ``device_get``,
- ``.block_until_ready()``,
- ``.item()`` / ``float(x)`` / ``int(x)`` on traced values are caught by
  the np/device_get rules' sibling: explicit ``.item(`` match.

**Quantized kernels** (registered names containing ``:int8`` or under
the ``quantize.`` prefix — core/quantize.py and the int8 device ops)
additionally forbid any ``float64`` reference: the int8 contract is an
i32 accumulator with an **f32** dequant epilogue, and a silent f64
upcast there (an ``astype(jnp.float64)``, a f64 dtype literal) would
halve MXU throughput and quietly change serving numerics vs the
exported AOT programs.

A line may be whitelisted with a trailing ``# fusion:host-ok`` comment
(for genuinely trace-time-only host work, e.g. reading a static shape).

Run from the repo root::

    python tools/check_fusion_kernels.py

Exit status 1 + a violation listing when any kernel touches the host.
The tier-1 test ``tests/test_fusion.py::TestKernelStaticCheck`` builds
one representative pipeline of every fusable stage family and runs this
check against the registered kernels, so CI holds the invariant.
"""

from __future__ import annotations

import ast
import inspect
import os
import sys
import textwrap
from typing import Dict, List, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# names whose attribute access / call inside kernel code means a host
# round trip
_FORBIDDEN_ROOTS = {"np", "numpy"}
_FORBIDDEN_ATTRS = {"device_get", "block_until_ready", "item",
                    "to_py", "tolist"}
_WHITELIST_MARK = "# fusion:host-ok"

# ingress decode kernels (io/columnar.py INGRESS_REGISTRY) promise NO
# per-row Python iteration and NO per-element boxing between the socket
# and device_put: loops/comprehensions and per-element materializers
# are forbidden unless the line carries the explicit acknowledgment
# (per-COLUMN loops and the documented string passes)
_INGRESS_MARK = "# ingress:row-ok"
_INGRESS_ATTRS = {"tolist", "item", "to_py"}


def _kernel_sources() -> List[Tuple[str, str, int, List[str]]]:
    """(name, source, firstlineno, lines) per registered kernel."""
    from mmlspark_tpu.core.fusion import KERNEL_REGISTRY
    out = []
    seen = set()
    for code, name in KERNEL_REGISTRY.items():
        key = (code.co_filename, code.co_firstlineno)
        if key in seen:
            continue
        seen.add(key)
        try:
            lines, first = inspect.getsourcelines(code)
        except OSError:
            continue   # dynamically built (tests); nothing to audit
        out.append((name, textwrap.dedent("".join(lines)), first, lines))
    return out


def is_quantized_kernel(name: str) -> bool:
    """Whether the f64-upcast rule applies: quantize.py helpers, the
    int8 variants of the stage device ops, and the quantized-histogram
    GBDT kernels (hist_bits<32 — integer accumulation must stay
    integer; a silent f64 upcast would both waste the narrow wire and
    break the exact-int reassociation-invariance contract)."""
    return (":int8" in name or name.startswith("quantize.")
            or name.startswith("gbdt.quanthist."))


def _check_source(name: str, src: str, first: int,
                  lines: List[str]) -> List[str]:
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return [f"{name}: unparseable kernel source"]
    violations: List[str] = []
    check_f64 = is_quantized_kernel(name)

    def line_ok(lineno: int) -> bool:
        idx = lineno - 1
        if 0 <= idx < len(lines):
            return _WHITELIST_MARK in lines[idx]
        return False

    for node in ast.walk(tree):
        bad = None
        f64 = None
        if isinstance(node, ast.Attribute):
            root = node.value
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and root.id in _FORBIDDEN_ROOTS:
                bad = f"{root.id}.{node.attr}"
            elif node.attr in _FORBIDDEN_ATTRS:
                bad = f".{node.attr}"
            elif check_f64 and node.attr == "float64":
                f64 = f".{node.attr}"
        elif isinstance(node, ast.Name) and node.id in _FORBIDDEN_ROOTS:
            bad = node.id
        elif check_f64 and isinstance(node, ast.Name) \
                and node.id == "float64":
            f64 = node.id
        elif check_f64 and isinstance(node, ast.Constant) \
                and node.value == "float64":
            f64 = "'float64'"
        if bad is not None and not line_ok(node.lineno):
            violations.append(
                f"{name} (line {first + node.lineno - 1}): host "
                f"round-trip construct {bad!r} inside a fused kernel")
        if f64 is not None and not line_ok(node.lineno):
            violations.append(
                f"{name} (line {first + node.lineno - 1}): silent f64 "
                f"upcast {f64!r} inside a quantized kernel (dequant "
                f"epilogues are f32 by contract)")
    return violations


def check_registered_kernels() -> List[str]:
    """All violations across registered kernels (empty = clean)."""
    violations: List[str] = []
    for name, src, first, lines in _kernel_sources():
        violations.extend(_check_source(name, src, first, lines))
    return violations


# ---------------------------------------------------------------------------
# ingress decode kernels (columnar serving ingress — io/columnar.py)
# ---------------------------------------------------------------------------


def _ingress_sources() -> List[Tuple[str, str, int, List[str]]]:
    from mmlspark_tpu.io.columnar import INGRESS_REGISTRY
    out = []
    seen = set()
    for code, name in INGRESS_REGISTRY.items():
        key = (code.co_filename, code.co_firstlineno)
        if key in seen:
            continue
        seen.add(key)
        try:
            lines, first = inspect.getsourcelines(code)
        except OSError:
            continue   # dynamically built (tests); nothing to audit
        out.append((name, textwrap.dedent("".join(lines)), first, lines))
    return out


_LOOP_NODES = (ast.For, ast.While, ast.ListComp, ast.SetComp,
               ast.DictComp, ast.GeneratorExp)


def _check_ingress_source(name: str, src: str, first: int,
                          lines: List[str]) -> List[str]:
    """Per-row iteration / per-element boxing audit of ONE registered
    ingress decode kernel. Any loop or comprehension must carry the
    ``# ingress:row-ok`` acknowledgment on its first line (per-column
    loops and the documented string-materialization passes); so must
    ``.tolist()``/``.item()`` and ``map()`` calls."""
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return [f"{name}: unparseable ingress kernel source"]
    violations: List[str] = []

    def line_ok(lineno: int) -> bool:
        idx = lineno - 1
        if 0 <= idx < len(lines):
            return _INGRESS_MARK in lines[idx]
        return False

    for node in ast.walk(tree):
        bad = None
        if isinstance(node, _LOOP_NODES):
            bad = ("per-row Python iteration "
                   f"({type(node).__name__.lower()})")
        elif isinstance(node, ast.Attribute) and \
                node.attr in _INGRESS_ATTRS:
            bad = f"per-element boxing '.{node.attr}'"
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and node.func.id == "map":
            bad = "per-element boxing 'map()'"
        if bad is not None and not line_ok(node.lineno):
            violations.append(
                f"{name} (line {first + node.lineno - 1}): {bad} inside "
                f"a registered ingress decode kernel (acknowledge a "
                f"per-column loop with '{_INGRESS_MARK}')")
    return violations


def check_ingress_kernels() -> List[str]:
    """All per-row-iteration violations across registered ingress
    decode kernels (empty = clean)."""
    violations: List[str] = []
    for name, src, first, lines in _ingress_sources():
        violations.extend(_check_ingress_source(name, src, first, lines))
    return violations


# ---------------------------------------------------------------------------
# shared-memory transport hot paths (io/shm.py + the fleet client rung)
# ---------------------------------------------------------------------------

# the shm promise: ONE staged copy per numeric column (np.copyto into
# the segment), zero body bytes. Any other materialization on a
# registered shm hot path — ``.tobytes()``, ``.tolist()``, ``.copy()``,
# a ``bytes(...)`` call — must carry the explicit acknowledgment (the
# string-column contract and the ~150-byte control message are the
# sanctioned cases).
_SHM_MARK = "# shm:copy-ok"
_SHM_COPY_ATTRS = {"tobytes", "tolist", "copy"}

# additional shm hot paths living outside io/shm.py (the fleet client's
# write->post->release rung), audited by (module, qualname)
_SHM_EXTRA_PATHS = (
    ("mmlspark_tpu.serving.fleet", "ServingFleet._post_columns_shm"),
)

# segment owners: every ``SharedMemory(create=True)`` class must also
# hold the matching ``.unlink(`` and ``.close(`` teardown
_SHM_SEGMENT_OWNERS = (
    ("mmlspark_tpu.io.shm", "ShmRing"),
)


def _shm_sources() -> List[Tuple[str, str, int, List[str]]]:
    from mmlspark_tpu.io.shm import SHM_REGISTRY
    out = []
    seen = set()
    for code, name in SHM_REGISTRY.items():
        key = (code.co_filename, code.co_firstlineno)
        if key in seen:
            continue
        seen.add(key)
        try:
            lines, first = inspect.getsourcelines(code)
        except OSError:
            continue   # dynamically built (tests); nothing to audit
        out.append((name, textwrap.dedent("".join(lines)), first, lines))
    for module, qualname in _SHM_EXTRA_PATHS:
        fn = _resolve_qualname(module, qualname)
        if fn is None:
            out.append((f"{module}.{qualname}", "", 0, []))
            continue
        lines, first = inspect.getsourcelines(fn)
        out.append((f"{module}.{qualname}",
                    textwrap.dedent("".join(lines)), first, lines))
    return out


def _check_shm_copy_source(name: str, src: str, first: int,
                           lines: List[str]) -> List[str]:
    """Unacknowledged-copy audit of ONE registered shm hot path:
    ``.tobytes()``/``.tolist()``/``.copy()`` attribute access and
    ``bytes(...)`` calls need ``# shm:copy-ok`` on their line.
    (``np.copyto`` is the ONE intended staged copy — allowed.)"""
    if not src:
        return [f"{name}: shm hot path is missing / unresolvable"]
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return [f"{name}: unparseable shm hot-path source"]
    violations: List[str] = []

    def line_ok(lineno: int) -> bool:
        idx = lineno - 1
        if 0 <= idx < len(lines):
            return _SHM_MARK in lines[idx]
        return False

    for node in ast.walk(tree):
        bad = None
        if isinstance(node, ast.Attribute) and \
                node.attr in _SHM_COPY_ATTRS:
            bad = f"unacknowledged copy '.{node.attr}'"
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id == "bytes":
            bad = "unacknowledged copy 'bytes()'"
        if bad is not None and not line_ok(node.lineno):
            violations.append(
                f"{name} (line {first + node.lineno - 1}): {bad} on a "
                f"registered shm hot path (acknowledge a sanctioned "
                f"materialization with '{_SHM_MARK}')")
    return violations


def _is_slot_acquire(node: ast.Call) -> bool:
    """A slot acquire: ``*._claim_slot(...)`` or ``ring.write(...)``
    (the fleet rung's token-producing call)."""
    f = node.func
    if not isinstance(f, ast.Attribute):
        return False
    if f.attr == "_claim_slot":
        return True
    return (f.attr == "write" and isinstance(f.value, ast.Name)
            and f.value.id == "ring")


def _has_protected_release(fn) -> bool:
    """Does ``fn`` release a slot on its failure paths — a
    ``.release(...)`` call inside a ``finally`` block or an ``except``
    handler?"""
    for t in ast.walk(fn):
        if not isinstance(t, ast.Try):
            continue
        bodies = list(t.finalbody)
        for h in t.handlers:
            bodies.extend(h.body)
        for stmt in bodies:
            for n in ast.walk(stmt):
                if isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        n.func.attr == "release":
                    return True
    return False


def _check_shm_pairing(name: str, src: str, first: int,
                       lines: List[str]) -> List[str]:
    """Acquire/release pairing audit: any function on a registered shm
    hot path that claims a ring slot must release it on every failure
    path (a ``.release(`` inside ``finally`` or an ``except`` handler;
    the success path may hand the token to the caller by contract)."""
    if not src:
        return []   # the missing-source violation already fired
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []
    violations: List[str] = []
    fns = [n for n in ast.walk(tree)
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for fn in fns or [tree]:
        acquires = [n for n in ast.walk(fn)
                    if isinstance(n, ast.Call) and _is_slot_acquire(n)]
        if acquires and not _has_protected_release(fn):
            violations.append(
                f"{name} (line {first + acquires[0].lineno - 1}): slot "
                f"acquire without a '.release(' on the failure paths "
                f"(finally / except handler) — a raised exception "
                f"leaks the slot")
    return violations


def check_shm_transport() -> List[str]:
    """The shared-memory transport audit: no unacknowledged copies on
    registered shm hot paths, every slot acquire released on failure
    paths, and every created segment unlinked (empty = clean)."""
    violations: List[str] = []
    for name, src, first, lines in _shm_sources():
        violations.extend(_check_shm_copy_source(name, src, first, lines))
        violations.extend(_check_shm_pairing(name, src, first, lines))
    for module, qualname in _SHM_SEGMENT_OWNERS:
        obj = _resolve_qualname(module, qualname)
        if obj is None:
            violations.append(
                f"{module}.{qualname}: segment owner is missing")
            continue
        src = textwrap.dedent("".join(inspect.getsourcelines(obj)[0]))
        if "create=True" in src:
            for needed in (".unlink(", ".close("):
                if needed not in src:
                    violations.append(
                        f"{module}.{qualname}: creates a SharedMemory "
                        f"segment but never calls '{needed}' — a "
                        f"leaked /dev/shm file outlives the process")
    return violations


# ---------------------------------------------------------------------------
# out-of-core ingest hot paths (io/ooc.py + the chunked consumers)
# ---------------------------------------------------------------------------

# the chunked-ingest promise: bounded memory. Nothing on a ChunkedTable
# hot path may materialize the whole stream — no ``.materialize()``, no
# ``to_numpy()``/``to_pylist()`` column pulls, no full-stream
# ``np.concatenate``/``vstack``/``stack``/``DataTable.concat`` — unless
# the line carries the explicit acknowledgment (chunk-LOCAL decode and
# the bounded sketch buffers are the sanctioned cases).
_OOC_MARK = "# ooc:materialize-ok"
_OOC_ATTR_CALLS = {"materialize", "to_numpy", "to_pylist", "toarray",
                   "concat"}
_OOC_NP_CALLS = {"concatenate", "vstack", "hstack", "stack"}

# (dotted module, qualname) of every audited hot-path function
_OOC_HOT_PATHS = (
    ("mmlspark_tpu.io.ooc", "ChunkedTable._instrumented"),
    ("mmlspark_tpu.io.ooc", "ChunkedTable.chunks"),
    ("mmlspark_tpu.io.ooc", "ChunkedTable.map"),
    ("mmlspark_tpu.io.ooc", "ChunkedTable.as_xy"),
    ("mmlspark_tpu.io.ooc", "ChunkedTable.materialize"),
    ("mmlspark_tpu.io.ooc", "_record_batch_to_table"),
    ("mmlspark_tpu.core.fusion", "FusionPlan.execute_chunked"),
    ("mmlspark_tpu.core.fusion",
     "FusedPipelineModel.transform_chunked"),
    ("mmlspark_tpu.gbdt.binning", "BinMapper.fit_streaming"),
    ("mmlspark_tpu.gbdt.sketch", "QuantileSketch.update"),
    ("mmlspark_tpu.gbdt.sketch", "QuantileSketch._flush"),
    ("mmlspark_tpu.gbdt.sketch", "QuantileSketch.summary"),
    ("mmlspark_tpu.automl.featurize", "Featurize._fit_streaming"),
    ("mmlspark_tpu.stages.dataprep", "StandardScaler._fit_streaming"),
    ("mmlspark_tpu.stages.dataprep",
     "SummarizeData._transform_chunked"),
)


def _resolve_qualname(module: str, qualname: str):
    import importlib
    obj = importlib.import_module(module)
    for part in qualname.split("."):
        obj = getattr(obj, part)
    return obj


def check_ooc_source(name: str, src: str, first: int,
                     lines: List[str]) -> List[str]:
    """No-materialize audit of ONE chunked hot-path function."""
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return [f"{name}: unparseable ooc hot-path source"]
    violations: List[str] = []

    def line_ok(lineno: int) -> bool:
        idx = lineno - 1
        return 0 <= idx < len(lines) and _OOC_MARK in lines[idx]

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        bad = None
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr in _OOC_ATTR_CALLS:
                bad = f"materializing call '.{func.attr}()'"
            elif func.attr in _OOC_NP_CALLS and \
                    isinstance(func.value, ast.Name) and \
                    func.value.id in ("np", "numpy"):
                bad = f"full-stream 'np.{func.attr}()'"
        elif isinstance(func, ast.Name) and func.id == "list" \
                and node.args:
            # list(...chunks...) would buffer the whole stream; other
            # list() uses (schema names, dict keys) are fine
            arg = node.args[0]
            chunky = (isinstance(arg, ast.Call)
                      and isinstance(arg.func, ast.Attribute)
                      and arg.func.attr == "chunks") or (
                isinstance(arg, ast.Name) and "chunk" in arg.id)
            if chunky:
                bad = "stream buffering 'list()'"
        if bad is not None and not line_ok(node.lineno):
            violations.append(
                f"{name} (line {first + node.lineno - 1}): {bad} on a "
                f"ChunkedTable hot path (chunk-local use is "
                f"acknowledged with '{_OOC_MARK}')")
    return violations


def check_ooc_ingest() -> List[str]:
    """The no-materialize audit across every registered chunked
    hot path (empty = clean)."""
    violations: List[str] = []
    for module, qualname in _OOC_HOT_PATHS:
        try:
            fn = _resolve_qualname(module, qualname)
        except (ImportError, AttributeError) as e:
            violations.append(f"{module}.{qualname}: unresolvable ({e})")
            continue
        fn = inspect.unwrap(fn)
        try:
            lines, first = inspect.getsourcelines(fn)
        except OSError as e:
            violations.append(
                f"{module}.{qualname}: unreadable source ({e})")
            continue
        violations.extend(check_ooc_source(
            f"{module}.{qualname}",
            textwrap.dedent("".join(lines)), first, lines))
    return violations


# ---------------------------------------------------------------------------
# continuous-training control loop (serving/controlplane.py)
# ---------------------------------------------------------------------------

# The control-loop discipline, statically enforced:
#   1. every `self.state` write happens inside the `_transition` funnel
#      (or `__init__`, the pre-loop initial value) — so no state change
#      can skip the timeline;
#   2. `_transition` calls `_record`, and `_record` calls
#      `record_event` — so the funnel actually lands the event on the
#      registry timeline;
#   3. refit/validation work (`refit`/`partial_fit`/`boost_more`/
#      `_run_refit`/`_shadow_and_gate`) is invoked ONLY from the
#      registered trainer-thread callsites;
#   4. the serving hot-path loops (batcher/worker/execute/supervisor)
#      never call into refit/validation — training on the request path
#      is the failure mode the dedicated trainer thread exists to
#      prevent.
_CONTROL_STATE_FUNNEL = "_transition"
_CONTROL_RECORDER = "_record"
_CONTROL_STATE_WRITERS = frozenset({_CONTROL_STATE_FUNNEL, "__init__"})
_REFIT_CALL_NAMES = frozenset({
    "refit", "partial_fit", "boost_more", "_run_refit",
    "_shadow_and_gate",
})
# trainer-thread callsites allowed to invoke refit/validation work
_TRAINER_ALLOWLIST = frozenset({
    "_cycle", "_run_refit", "_shadow_and_gate",
})
# serving hot-path functions that must stay training-free (the
# forbidden set adds the cycle entrypoint + fit: a hot loop must not
# even *start* a training cycle synchronously)
_SERVING_HOT_LOOPS = (
    ("mmlspark_tpu.serving.server", "ServingEngine._batcher_loop"),
    ("mmlspark_tpu.serving.server", "ServingEngine._worker_loop"),
    ("mmlspark_tpu.serving.server", "ServingEngine._execute_batch"),
    ("mmlspark_tpu.serving.server", "ServingEngine._supervise"),
)
_SERVING_FORBIDDEN = _REFIT_CALL_NAMES | {"_cycle", "fit"}


def _call_name(func) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _walk_with_owner(tree):
    """Yield (innermost_function_name, node) over the tree."""
    stack: List[str] = []

    def visit(node):
        is_fn = isinstance(node, (ast.FunctionDef,
                                  ast.AsyncFunctionDef))
        if is_fn:
            stack.append(node.name)
        owner = stack[-1] if stack else "<module>"
        yield owner, node
        for child in ast.iter_child_nodes(node):
            yield from visit(child)
        if is_fn:
            stack.pop()

    yield from visit(tree)


def check_control_loop_source(src: str, first: int = 1,
                              name: str = "serving/controlplane.py",
                              ) -> List[str]:
    """The control-loop discipline audit over ONE module source (rules
    1-3 above). Exposed at source level so the tier-1 tests can feed it
    positive and negative examples."""
    try:
        tree = ast.parse(textwrap.dedent(src))
    except SyntaxError:
        return [f"{name}: unparseable control-loop source"]
    violations: List[str] = []
    record_calls_in: dict = {}    # func name -> set of callee names
    for owner, node in _walk_with_owner(tree):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Attribute) and t.attr == "state" \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self" \
                        and owner not in _CONTROL_STATE_WRITERS:
                    violations.append(
                        f"{name} (line {first + node.lineno - 1}): "
                        f"'self.state' written in {owner!r} — every "
                        f"loop state change must go through "
                        f"{_CONTROL_STATE_FUNNEL!r} so its timeline "
                        f"event is recorded")
        if isinstance(node, ast.Call):
            callee = _call_name(node.func)
            record_calls_in.setdefault(owner, set()).add(callee)
            if callee in _REFIT_CALL_NAMES and \
                    owner not in _TRAINER_ALLOWLIST:
                violations.append(
                    f"{name} (line {first + node.lineno - 1}): "
                    f"refit/validation call {callee!r} from "
                    f"{owner!r} — training work runs only on the "
                    f"trainer thread (allowlist: "
                    f"{sorted(_TRAINER_ALLOWLIST)})")
    funnel_calls = record_calls_in.get(_CONTROL_STATE_FUNNEL, set())
    if _CONTROL_RECORDER not in funnel_calls and \
            "record_event" not in funnel_calls:
        violations.append(
            f"{name}: {_CONTROL_STATE_FUNNEL!r} no longer records its "
            f"event ({_CONTROL_RECORDER!r}/'record_event' not called) "
            f"— transitions would vanish from the registry timeline")
    recorder_calls = record_calls_in.get(_CONTROL_RECORDER, set())
    if recorder_calls and "record_event" not in recorder_calls:
        violations.append(
            f"{name}: {_CONTROL_RECORDER!r} does not call "
            f"'record_event' — events never reach the registry")
    return violations


def check_control_loop() -> List[str]:
    """Rules 1-3 over the real serving/controlplane.py, plus rule 4
    over the engine's serving hot loops (empty = clean)."""
    import importlib
    mod = importlib.import_module("mmlspark_tpu.serving.controlplane")
    src = inspect.getsource(mod)
    violations = check_control_loop_source(src)
    for module, qualname in _SERVING_HOT_LOOPS:
        try:
            fn = _resolve_qualname(module, qualname)
        except (ImportError, AttributeError) as e:
            violations.append(f"{module}.{qualname}: unresolvable "
                              f"({e}) — update _SERVING_HOT_LOOPS")
            continue
        fn = inspect.unwrap(fn)
        try:
            lines, fl = inspect.getsourcelines(fn)
        except OSError as e:
            violations.append(
                f"{module}.{qualname}: unreadable source ({e})")
            continue
        try:
            tree = ast.parse(textwrap.dedent("".join(lines)))
        except SyntaxError:
            violations.append(
                f"{module}.{qualname}: unparseable hot-loop source")
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                callee = _call_name(node.func)
                if callee in _SERVING_FORBIDDEN:
                    violations.append(
                        f"{module}.{qualname} (line "
                        f"{fl + node.lineno - 1}): refit/validation "
                        f"call {callee!r} on a serving hot loop — "
                        f"training must never run on batcher/worker "
                        f"threads")
    return violations


# ---------------------------------------------------------------------------
# SLO-adaptive serving (serving/variants.py + serving/autoscale.py)
# ---------------------------------------------------------------------------

# The adaptive-serving discipline (docs/adaptive_serving.md):
#
# 1. Variant SELECTION never runs on the HTTP handler. The nested
#    ``Handler`` class in serving/server.py must not touch the
#    ``variants`` attribute at all — /healthz reads the selector
#    through the engine's metrics probe, and routing/deciding happen
#    on the batcher thread only: ``variants.tick`` solely in
#    ``_batcher_loop`` (the rate-gated decision point),
#    ``variants.route`` solely in ``_ingest`` (admission), and
#    ``variants.observe`` solely in ``_execute_batch`` (the latency
#    feed).
# 2. Autoscaler scale-down goes ONLY through the drain path:
#    ``fleet.remove_engine`` is called nowhere but
#    ``_drain_and_stop`` (rotation removal precedes process stop),
#    ``_stop_proc`` is reachable only from ``_drain_and_stop`` and
#    the ``_scale_up`` join-failure cleanup (a process that never
#    entered the rotation), and raw ``terminate``/``kill`` live only
#    inside ``_stop_proc``.

_ADAPTIVE_HANDLER_CLASS = "Handler"
_VARIANT_CALL_OWNERS = {
    "tick": {"_batcher_loop"},
    "route": {"_ingest"},
    "observe": {"_execute_batch"},
}
_AUTOSCALE_REMOVE_OWNERS = {"_drain_and_stop"}
_AUTOSCALE_STOP_OWNERS = {"_drain_and_stop", "_scale_up"}
_AUTOSCALE_KILL_OWNERS = {"_stop_proc"}


def _is_variants_method(func) -> bool:
    """``<anything>.variants.<method>(...)`` — the selector surface."""
    return (isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Attribute)
            and func.value.attr == "variants")


def check_adaptive_serving_source(server_src: str, autoscale_src: str,
                                  ) -> List[str]:
    """The adaptive-serving audit over both module sources (rules 1-2
    above). Source-level so the tier-1 tests can feed it positive and
    negative examples."""
    violations: List[str] = []
    try:
        server_tree = ast.parse(textwrap.dedent(server_src))
    except SyntaxError:
        return ["serving/server.py: unparseable source"]
    # rule 1a: the HTTP handler class never touches the variant plane
    for node in ast.walk(server_tree):
        if isinstance(node, ast.ClassDef) and \
                node.name == _ADAPTIVE_HANDLER_CLASS:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Attribute) and \
                        sub.attr == "variants":
                    violations.append(
                        f"serving/server.py (line {sub.lineno}): the "
                        f"HTTP handler touches '.variants' — variant "
                        f"selection/reads belong on the batcher "
                        f"thread; /healthz reads the selector via the "
                        f"engine metrics probe")
    # rule 1b: each selector call lands only on its designated owner
    seen_tick = False
    for owner, node in _walk_with_owner(server_tree):
        if isinstance(node, ast.Call) and \
                _is_variants_method(node.func):
            method = node.func.attr
            allowed = _VARIANT_CALL_OWNERS.get(method)
            if method == "tick":
                seen_tick = True
            if allowed is not None and owner not in allowed:
                violations.append(
                    f"serving/server.py (line {node.lineno}): "
                    f"variants.{method} called from {owner!r} — "
                    f"allowed only in {sorted(allowed)} (selection "
                    f"never runs per-request)")
    if not seen_tick:
        violations.append(
            "serving/server.py: no variants.tick call found in "
            "'_batcher_loop' — the selector's decision point moved; "
            "update check_adaptive_serving_source")
    try:
        auto_tree = ast.parse(textwrap.dedent(autoscale_src))
    except SyntaxError:
        return violations + ["serving/autoscale.py: unparseable source"]
    # rule 2: scale-down only through the drain funnel
    drain_seen = False
    for owner, node in _walk_with_owner(auto_tree):
        if not isinstance(node, ast.Call):
            continue
        callee = _call_name(node.func)
        if callee == "remove_engine":
            drain_seen = True
            if owner not in _AUTOSCALE_REMOVE_OWNERS:
                violations.append(
                    f"serving/autoscale.py (line {node.lineno}): "
                    f"remove_engine called from {owner!r} — engines "
                    f"leave the rotation only inside "
                    f"{sorted(_AUTOSCALE_REMOVE_OWNERS)} (drain "
                    f"before retire)")
        elif callee == "_stop_proc":
            if owner not in _AUTOSCALE_STOP_OWNERS:
                violations.append(
                    f"serving/autoscale.py (line {node.lineno}): "
                    f"_stop_proc called from {owner!r} — processes "
                    f"stop only from {sorted(_AUTOSCALE_STOP_OWNERS)}")
        elif callee in ("terminate", "kill"):
            if owner not in _AUTOSCALE_KILL_OWNERS:
                violations.append(
                    f"serving/autoscale.py (line {node.lineno}): "
                    f"raw {callee} call from {owner!r} — only "
                    f"{sorted(_AUTOSCALE_KILL_OWNERS)} touches the "
                    f"process handle")
    if not drain_seen:
        violations.append(
            "serving/autoscale.py: no remove_engine call found in "
            "'_drain_and_stop' — the drain funnel moved; update "
            "check_adaptive_serving_source")
    return violations


def check_adaptive_serving() -> List[str]:
    """Rules 1-2 over the real serving/server.py +
    serving/autoscale.py sources."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    srcs = []
    for rel in ("mmlspark_tpu/serving/server.py",
                "mmlspark_tpu/serving/autoscale.py"):
        try:
            with open(os.path.join(root, rel)) as f:
                srcs.append(f.read())
        except OSError as e:
            return [f"{rel}: unreadable ({e})"]
    return check_adaptive_serving_source(*srcs)


# ---------------------------------------------------------------------------
# sharded serving programs (mesh-sharded pjit path — serving/sharded.py)
# ---------------------------------------------------------------------------

# every function that builds a mesh-sharded serving jit. The contract:
# each declares BOTH in_shardings and out_shardings explicitly on every
# jax.jit call inside — sharded programs never infer placement from
# operands (an inferred sharding silently changes when an input's
# placement drifts, and the AOT manifest could no longer describe the
# program it serialized).
_SHARDED_JIT_SITES = (
    ("mmlspark_tpu/core/fusion.py", "_jit_sharded"),
    ("mmlspark_tpu/models/tpu_model.py", "_jit_sharded"),
)


def _is_jax_jit(func) -> bool:
    return (isinstance(func, ast.Attribute) and func.attr == "jit"
            and isinstance(func.value, ast.Name)
            and func.value.id == "jax")


def check_sharded_jit_source(site: str, fn_name: str,
                             src: str) -> List[str]:
    """Audit ONE sharded-jit builder's source: at least one
    ``jax.jit`` call, and every such call carries explicit
    ``in_shardings=`` AND ``out_shardings=`` keywords."""
    try:
        tree = ast.parse(textwrap.dedent(src))
    except SyntaxError:
        return [f"{site}: unparseable sharded jit builder {fn_name}"]
    violations: List[str] = []
    fns = [n for n in ast.walk(tree)
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
           and n.name == fn_name]
    if not fns:
        return [f"{site}: sharded jit builder {fn_name!r} not found"]
    for fn in fns:
        jit_calls = 0
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and _is_jax_jit(node.func):
                jit_calls += 1
                kw = {k.arg for k in node.keywords}
                missing = {"in_shardings", "out_shardings"} - kw
                if missing:
                    violations.append(
                        f"{site}:{fn_name} (line {node.lineno}): "
                        f"sharded program jit without explicit "
                        f"{'/'.join(sorted(missing))} — sharded "
                        f"serving shardings must be declared, never "
                        f"inferred")
        if jit_calls == 0:
            violations.append(
                f"{site}:{fn_name}: no jax.jit call found — the "
                f"sharded builder contract moved; update "
                f"_SHARDED_JIT_SITES")
    return violations


def check_sharded_serving() -> List[str]:
    """The sharded-serving audit: (1) every declared sharded-jit
    builder passes ``check_sharded_jit_source``; (2) the sharded
    serving kernels (the seq-parallel LM apply; fused-segment kernels
    are already registered) pass the host-round-trip rules — no
    ``jax.device_get``/host sync inside a sharded serving kernel."""
    import mmlspark_tpu.serving.sharded  # noqa: F401 — registers the
    #                                      seq-LM kernel in the registry
    violations: List[str] = []
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for rel, fn_name in _SHARDED_JIT_SITES:
        path = os.path.join(root, rel)
        try:
            with open(path) as f:
                src = f.read()
        except OSError as e:
            violations.append(f"{rel}: unreadable ({e})")
            continue
        violations.extend(check_sharded_jit_source(rel, fn_name, src))
    return violations


def register_known_callees() -> int:
    """Register the same-repo functions fused kernels CALL (the
    audit's transitive reach): the jitted forest walk and every GBDT
    objective's ``transform``. The top-level kernel fns are closures
    built by ``device_op()``; these callees are where a host sync
    could otherwise hide. (User-supplied ``modelFn``s of TPUModel are
    out of scope by construction — they are the user's code.)"""
    from mmlspark_tpu.core.fusion import register_kernel
    from mmlspark_tpu.gbdt import objectives as OBJ
    from mmlspark_tpu.gbdt import tree as TREE
    walk = getattr(TREE.predict_trees, "__wrapped__", TREE.predict_trees)
    register_kernel(walk, "gbdt.tree.predict_trees")
    count = 1

    def subclasses(cls):
        for sub in cls.__subclasses__():
            yield sub
            yield from subclasses(sub)

    for cls in {OBJ.Objective, *subclasses(OBJ.Objective)}:
        fn = cls.__dict__.get("transform")
        if fn is not None:
            register_kernel(fn, f"gbdt.objectives.{cls.__name__}.transform")
            count += 1
    # the int8 compute kernels every quantized device op calls, plus
    # the flax interception wrapper (core/quantize.py) — these get the
    # additional no-f64-upcast rule
    from mmlspark_tpu.core import quantize as QZ
    QZ._register_audit_kernels()
    register_kernel(QZ.QuantizedFlaxApply.__call__,
                    "quantize.QuantizedFlaxApply.__call__")
    count += 3
    # quantized-histogram GBDT kernels (hist_bits<32): audited for host
    # syncs like every kernel AND for silent f64 upcasts — integer
    # histogram accumulation is the reassociation-invariance contract
    from mmlspark_tpu.gbdt import histogram as HIST
    from mmlspark_tpu.gbdt import pallas_hist as PH
    for fn, qname in (
            (HIST.build_histogram, "gbdt.quanthist.build_histogram"),
            (HIST._hist_scatter, "gbdt.quanthist.hist_scatter"),
            (PH._stats_block, "gbdt.quanthist.stats_block"),
            (PH._hist_kernel, "gbdt.quanthist.hist_kernel"),
            (PH._hist_kernel_nibble, "gbdt.quanthist.hist_kernel_nibble"),
    ):
        register_kernel(fn, qname)
        count += 1
    return count


def register_representative_pipelines() -> int:
    """Build one fitted pipeline per fusable stage family and plan it,
    so KERNEL_REGISTRY holds every shipped kernel. Returns the number
    of registered kernel code objects."""
    import numpy as np
    from mmlspark_tpu.core.fusion import KERNEL_REGISTRY, fuse
    from mmlspark_tpu.core.table import DataTable
    from mmlspark_tpu.core.stage import Pipeline
    from mmlspark_tpu.automl.featurize import Featurize
    from mmlspark_tpu.stages.dataprep import (
        CleanMissingData, FastVectorAssembler, StandardScaler,
        ValueIndexer,
    )
    from mmlspark_tpu.models.linear import (
        TPULinearRegression, TPULogisticRegression,
    )
    from mmlspark_tpu.gbdt.estimators import (
        TPUBoostClassifier, TPUBoostRegressor,
    )
    from mmlspark_tpu.models.tpu_model import TPUModel

    rng = np.random.default_rng(0)
    n = 64
    table = DataTable({
        "a": rng.normal(size=n).astype(np.float32),
        "b": np.where(rng.random(n) < 0.2, np.nan, rng.normal(size=n)),
        "cat": [f"l{int(i)}" for i in rng.integers(0, 4, n)],
        "toks": [[f"w{int(t)}" for t in rng.integers(0, 9, 3)]
                 for _ in range(n)],
        "label": rng.integers(0, 2, n).astype(np.float64),
    })
    pm = Pipeline(stages=[
        CleanMissingData(inputCols=["b"], outputCols=["b"]),
        ValueIndexer(inputCol="cat", outputCol="cat_ix"),
        Featurize(featureColumns=["a", "b", "toks"],
                  numberOfFeatures=8),
        FastVectorAssembler(inputCols=["features", "cat_ix"],
                            outputCol="fv"),
        StandardScaler(inputCol="fv", outputCol="fv"),
        TPULogisticRegression(featuresCol="fv", labelCol="label",
                              maxIter=3),
    ]).fit(table)
    fuse(pm).plan_for(table.schema)

    # the chunked ingest path drives the SAME registered kernels —
    # plan one ChunkedTable pass so the host-sync audit provably
    # covers the feeds the out-of-core path ships per chunk
    from mmlspark_tpu.io.ooc import ChunkedTable
    for _ in fuse(pm).transform_chunked(
            ChunkedTable.from_table(table.drop("label"), chunk_rows=32)):
        pass

    # (N,1) feature matrix via assembler keeps the fit happy
    lin = Pipeline(stages=[
        FastVectorAssembler(inputCols=["a"], outputCol="fv2"),
        TPULinearRegression(featuresCol="fv2", labelCol="label",
                            maxIter=3)]).fit(table)
    fuse(lin).plan_for(table.schema)

    gb = Pipeline(stages=[
        FastVectorAssembler(inputCols=["a", "b"], outputCol="fv3"),
        TPUBoostClassifier(featuresCol="fv3", labelCol="label",
                           numIterations=3, numLeaves=4,
                           minDataInLeaf=2)]).fit(table)
    fuse(gb).plan_for(table.schema)
    gr = Pipeline(stages=[
        FastVectorAssembler(inputCols=["a", "b"], outputCol="fv4"),
        TPUBoostRegressor(featuresCol="fv4", labelCol="label",
                          numIterations=3, numLeaves=4,
                          minDataInLeaf=2)]).fit(table)
    fuse(gr).plan_for(table.schema)

    tm = TPUModel.from_fn(
        lambda w, ins: list(ins.values())[0] @ w["W"],
        {"W": np.eye(2, dtype=np.float32)},
        inputCol="fv5", outputCol="scores")
    asm = FastVectorAssembler(inputCols=["a", "b"], outputCol="fv5")
    from mmlspark_tpu.core.stage import PipelineModel
    fuse(PipelineModel(stages=[asm, tm])).plan_for(table.schema)

    # quantized variants: the int8 device ops of both linear families
    # (":int8"-named kernels — the no-f64-upcast rule applies) and a
    # quantized flax TPUModel forward
    fuse(pm.fused().quantize(table)).plan_for(table.schema)
    fuse(lin.fused().quantize(table)).plan_for(table.schema)
    from mmlspark_tpu.models.networks import build_network
    import jax as _jax
    module = build_network({"type": "mlp", "features": [8],
                            "num_classes": 2})
    x8 = rng.normal(size=(n, 8)).astype(np.float32)
    qtm = TPUModel.from_flax(
        module, module.init(_jax.random.PRNGKey(0), x8[:1]),
        inputCol="qfeat", outputCol="qscores",
    ).quantize({"qfeat": x8})
    qtable = table.with_column("qfeat", x8)
    fuse(PipelineModel(stages=[qtm])).plan_for(qtable.schema)

    return len(KERNEL_REGISTRY)


def main() -> int:
    n = register_representative_pipelines()
    n += register_known_callees()
    sharded_violations = check_sharded_serving()  # also registers the
    #                                               seq-LM kernel
    violations = check_registered_kernels()
    violations += sharded_violations
    from mmlspark_tpu.io.columnar import INGRESS_REGISTRY
    n_ingress = len(INGRESS_REGISTRY)
    violations += check_ingress_kernels()
    violations += check_shm_transport()
    violations += check_ooc_ingest()
    violations += check_control_loop()
    violations += check_adaptive_serving()
    if violations:
        print(f"{len(violations)} kernel violation(s) across {n} fused "
              f"+ {n_ingress} ingress registered kernels:")
        for v in violations:
            print("  -", v)
        return 1
    from mmlspark_tpu.io.shm import SHM_REGISTRY
    print(f"OK: {n} registered fused kernels, no host round trips; "
          f"{n_ingress} ingress kernels, no per-row iteration; "
          f"{len(SHM_REGISTRY)} shm hot paths, one staged copy and no "
          f"leaked slots/segments; "
          f"{len(_SHARDED_JIT_SITES)} sharded jit builders declare "
          f"explicit shardings; {len(_OOC_HOT_PATHS)} chunked hot "
          f"paths never materialize the stream; control loop "
          f"transitions all recorded, {len(_SERVING_HOT_LOOPS)} "
          f"serving hot loops training-free; variant selection off "
          f"the HTTP handler, autoscale retire only via drain")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
