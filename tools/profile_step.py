"""Capture + analyze an xplane profile of a TPULearner train step.

Usage::

    python tools/profile_step.py resnet   # ResNet-20, bench config
    python tools/profile_step.py convnet  # bench ConvNet
    python tools/profile_step.py <dir-or-xplane.pb>  # analyze existing

Runs a short device-feed training (the bench configuration), captures a
``jax.profiler.trace`` xplane, and aggregates device-plane op times
within the LAST (steady-state) XLA-module execution window, by HLO
category. This is the evidence path behind docs/perf_analysis.md: where
every microsecond of the compiled step goes, op by op.

Methodology notes:
- The ``XLA Modules`` line gives each jitted-program execution window;
  the last one is steady-state (first is compile-adjacent/warmup).
- The ``XLA Ops`` line carries leaf op events; scan-body ops appear once
  per scan iteration, so an 8-step chunk shows x8 counts.
- The ``while`` wrapper op spans its children and is excluded from the
  busy-time denominator (its children are themselves on the line).
"""

from __future__ import annotations

import collections
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def capture(kind: str, trace_dir: str, batch: int = 512) -> None:
    import jax
    from mmlspark_tpu.core.table import DataTable
    from mmlspark_tpu.models.learner import TPULearner
    from mmlspark_tpu.parallel import mesh as mesh_lib

    specs = {
        "resnet": {"type": "resnet", "stage_sizes": [3, 3, 3], "width": 16,
                   "num_classes": 10},
        "convnet": {"type": "convnet", "conv_features": [64, 64, 64],
                    "dense_features": [256], "num_classes": 10},
    }
    rng = np.random.default_rng(0)
    n = batch * 8
    x = rng.integers(0, 256, size=(n, 32, 32, 3)).astype(np.float32) / 255.0
    y = rng.integers(0, 10, size=n).astype(np.int64)
    table = DataTable({"features": x.reshape(n, -1), "label": y})
    mesh = mesh_lib.make_mesh({"data": len(jax.devices())})
    learner = TPULearner(
        networkSpec=specs[kind], inputShape=[32, 32, 3], batchSize=batch,
        learningRate=0.1, computeDtype="bfloat16", epochs=2,
        logEvery=10_000, dataFeed="device", profileDir=trace_dir)
    learner.set_mesh(mesh)
    learner.fit(table)
    print(f"# timing: {learner.timing}")


def _load_space(path: str):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    if os.path.isdir(path):
        from mmlspark_tpu.utils.profiling import trace_files
        files = trace_files(path)
        if not files:
            raise SystemExit(f"no xplane.pb under {path}")
        path = files[-1]
    with open(path, "rb") as f:
        return xplane_pb2.XSpace.FromString(f.read())


def analyze(path: str, top: int = 25) -> None:
    """Leaf-op breakdown of the last XLA-module window on the device."""
    space = _load_space(path)
    planes = [p for p in space.planes
              if "TPU" in p.name or "Device" in p.name]
    if not planes:
        raise SystemExit("no device plane in trace")
    for plane in planes:
        lines = {ln.name: ln for ln in plane.lines}
        if "XLA Modules" not in lines or "XLA Ops" not in lines:
            continue
        mods = sorted(lines["XLA Modules"].events,
                      key=lambda e: e.offset_ps)
        if not mods:
            continue
        last = mods[-1]
        w0, w1 = last.offset_ps, last.offset_ps + last.duration_ps
        ev_meta, stat_meta = plane.event_metadata, plane.stat_metadata

        def category(md) -> str:
            for st in md.stats:
                sm = stat_meta.get(st.metadata_id)
                if sm and sm.name == "hlo_category":
                    return st.str_value
            return "?"

        agg = collections.Counter()
        cnt = collections.Counter()
        by_cat = collections.Counter()
        for ev in lines["XLA Ops"].events:
            if ev.offset_ps < w0 or ev.offset_ps >= w1:
                continue
            md = ev_meta.get(ev.metadata_id)
            name = md.name if md else "?"
            cat = category(md) if md else "?"
            if cat == "while":
                continue  # spans its children; they are counted directly
            agg[(name, cat)] += ev.duration_ps
            cnt[(name, cat)] += 1
            by_cat[cat] += ev.duration_ps

        total = sum(agg.values())
        print(f"\n== {plane.name}: steady-state module "
              f"{last.duration_ps / 1e9:.2f} ms, leaf-op busy "
              f"{total / 1e9:.2f} ms "
              f"({total / max(last.duration_ps, 1) * 100:.1f}%) ==")
        print("-- by hlo_category --")
        for c, d in by_cat.most_common():
            print(f"{d / total * 100:6.2f}%  {d / 1e9:8.3f} ms  {c}")
        print(f"-- top {top} ops --")
        for (n, c), d in agg.most_common(top):
            print(f"{d / total * 100:6.2f}%  {d / 1e9:8.3f} ms "
                  f"x{cnt[(n, c)]:<4d} [{c}] {n[:78]}")


def main():
    arg = sys.argv[1] if len(sys.argv) > 1 else "resnet"
    if os.path.exists(arg):
        analyze(arg)
        return
    trace_dir = f"/tmp/profile_{arg}"
    capture(arg, trace_dir)
    analyze(trace_dir)


if __name__ == "__main__":
    main()
