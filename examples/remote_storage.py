"""Remote storage end-to-end: checkpoints, model zoo, binary IO.

The reference stages everything through HDFS/wasb — training data and
checkpoints (ref: CNTKLearner.scala:18-67 ``dataTransfer=hdfs``), the
model zoo (HDFSRepo, ModelDownloader.scala:54-124), and binary readers
(HadoopUtils.scala). The TPU-native seam is the scheme-keyed filesystem
registry with the writable ``webdav://`` backend: this example runs a
real (in-process) WebDAV server and pushes every one of those flows
through it —

1. train with ``checkpointDir`` on the remote store, then RESUME a
   longer run from the remote step;
2. publish the trained weights to a remote zoo repo and fetch them back
   sha256-verified through ModelDownloader's local cache;
3. read a directory of binary blobs straight off the remote store.
"""

import _pathsetup  # noqa: F401 — repo root on sys.path

import tempfile

import numpy as np

from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.downloader import HTTPRepo, ModelDownloader
from mmlspark_tpu.io.binary import read_binary_files
from mmlspark_tpu.models.learner import TPULearner, _latest_checkpoint
from mmlspark_tpu.testing.webdav import serve_webdav
from mmlspark_tpu.utils.filesystem import write_bytes


def main():
    store = tempfile.mkdtemp(prefix="remote_store_")
    server, base = serve_webdav(store)
    print(f"remote store: {base}")
    try:
        # -- 1) checkpoint/resume over the remote scheme ----------------
        rng = np.random.default_rng(0)
        x = rng.normal(size=(128, 8)).astype(np.float32)
        y = (x[:, 0] - x[:, 3] > 0).astype(np.int64)
        table = DataTable({"features": x, "label": y})
        ck = f"{base}/run1/ckpt"

        def learner(epochs):
            return TPULearner(
                networkSpec={"type": "mlp", "features": [16],
                             "num_classes": 2},
                epochs=epochs, batchSize=32, learningRate=0.1,
                computeDtype="float32", logEvery=1000,
                checkpointDir=ck, checkpointEvery=4, resume=True)

        learner(2).fit(table)
        latest = _latest_checkpoint(ck)
        assert latest and latest.startswith("webdav://"), latest
        step = int(latest.rsplit("step_", 1)[1])
        print(f"checkpointed remotely at step {step}")

        model = learner(5).fit(table)              # resumes, continues
        acc = (np.asarray(model.transform(table)["scores"]).argmax(-1)
               == y).mean()
        print(f"resumed run holdout-free accuracy: {acc:.3f}")
        assert acc > 0.85, acc

        # -- 2) remote zoo publish + verified fetch ---------------------
        from flax import serialization
        repo = HTTPRepo(f"{base}/zoo")
        blob = serialization.to_bytes(model.get("weights"))
        schema = repo.publish(
            "mlp_parity", {"type": "mlp", "features": [16],
                           "num_classes": 2},
            blob=blob, model_type="classification")
        cache = tempfile.mkdtemp(prefix="zoo_cache_")
        fetched = ModelDownloader(
            local_path=cache, repo=HTTPRepo(f"{base}/zoo")
        ).download_by_name("mlp_parity")
        got = ModelDownloader(local_path=cache).local.read_blob(fetched)
        assert got == blob
        print(f"zoo round-trip verified ({len(blob)} bytes, "
              f"sha256 {schema.sha256[:12]}...)")

        # -- 3) binary reads off the remote store -----------------------
        for i in range(3):
            write_bytes(f"{base}/blobs/part-{i}.bin", bytes([i]) * 64)
        blobs = read_binary_files(f"{base}/blobs", pattern="*.bin")
        assert blobs.num_rows == 3
        print(f"read {blobs.num_rows} remote binary files")
        print("remote_storage example OK")
    finally:
        server.shutdown()
        server.server_close()


if __name__ == "__main__":
    main()
