"""ONNX-checkpoint inference — framework-neutral model ingestion.

The reference's zoo serves published models behind URI+sha256 schemas
(ref: ModelDownloader.scala:209). ONNX is the dominant neutral
interchange format today, so this example takes an ONNX CNN (a
resnet-architecture graph; here synthesized by the test writer since
the image has no egress — any torchvision/HF ONNX export drops into the
same call), publishes it through ModelDownloader with its structural
manifest, and serves batched predictions through TPUModel.
"""

import _pathsetup  # noqa: F401 — repo root on sys.path

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(_pathsetup.__file__),
                                os.pardir, "tests"))
import onnx_writer  # noqa: E402 — the dependency-free ONNX writer

from mmlspark_tpu.core.table import DataTable  # noqa: E402
from mmlspark_tpu.downloader import LocalRepo  # noqa: E402
from mmlspark_tpu.importers import (  # noqa: E402
    import_onnx_model, onnx_summary,
)


def main():
    tmp = tempfile.mkdtemp(prefix="onnx_example_")
    onnx_path = os.path.join(tmp, "resnet18.onnx")
    onnx_writer.resnet18_onnx(onnx_path, num_classes=10, width=8, seed=7)

    # structural manifest — the validation hook recorded on the schema
    summary = onnx_summary(onnx_path)
    print("ops:", summary["ops"])
    assert summary["ops"]["Conv"] == 20

    # publish through the zoo (blob + sha256), reload, serve
    repo = LocalRepo(os.path.join(tmp, "repo"))
    with open(onnx_path, "rb") as f:
        blob = f.read()
    repo.publish("onnx_resnet18",
                 {"format": "onnx", "onnx_summary": summary},
                 blob=blob, model_type="classification")
    schema = repo.get_schema("onnx_resnet18")
    reload_path = os.path.join(tmp, "reload.onnx")
    with open(reload_path, "wb") as f:
        f.write(repo.read_blob(schema, verify=True))

    model = import_onnx_model(reload_path, batch_size=8,
                              input_shape=[3, 32, 32])
    rng = np.random.default_rng(0)
    images = rng.normal(size=(16, 3 * 32 * 32)).astype(np.float32)
    out = model.transform(DataTable({"images": images}))
    scores = np.asarray(out["scores"])
    assert scores.shape == (16, 10) and np.all(np.isfinite(scores))
    print("predictions:", scores.argmax(1).tolist())
    print("onnx_inference OK")


if __name__ == "__main__":
    main()
