"""Notebook-401 parity: distributed ConvNet training.

The reference stages CIFAR-10 to HDFS and launches `mpirun cntk` over
GPU VMs (ref: notebooks/gpu/401 + CommandBuilders.scala:108-267). Here:
TPULearner trains a ConvNet on real images (sklearn's bundled 8x8
handwritten digits) with the batch sharded over every available device
via the mesh — the same script scales from this host to a TPU pod by
virtue of jax.sharding alone.
"""

import _pathsetup  # noqa: F401 — repo root on sys.path

import numpy as np
import jax

from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.models.learner import TPULearner
from mmlspark_tpu.parallel import mesh as mesh_lib


def main():
    from sklearn.datasets import load_digits
    X, y = load_digits(return_X_y=True)
    X = (X / 16.0).astype(np.float32)
    split = 1400
    table = DataTable({"features": X[:split],
                       "label": y[:split].astype(np.int64)})

    mesh = mesh_lib.make_mesh({"data": len(jax.devices())})
    learner = TPULearner(
        networkSpec={"type": "convnet", "conv_features": [16, 16],
                     "dense_features": [64], "num_classes": 10},
        inputShape=[8, 8, 1], epochs=20, batchSize=128,
        learningRate=0.05, computeDtype="float32", logEvery=50)
    learner.set_mesh(mesh)
    model = learner.fit(table)

    out = model.transform(DataTable({"features": X[split:]}))
    acc = (np.argmax(out["scores"], axis=1) == y[split:]).mean()
    print(f"devices={len(jax.devices())} "
          f"throughput={learner.timing.get('examples_per_sec', 0):.0f} "
          f"examples/sec, holdout accuracy={acc:.3f}")
    assert acc > 0.9


if __name__ == "__main__":
    main()
