"""Notebook-301 parity: pretrained-model inference.

The reference loads a pretrained CNTK ResNet from the model zoo and runs
batched DataFrame inference (ref: notebooks/samples/301 + CNTKModel.scala
:469-514). Here: a ResNet trained in torch (weights this framework did
not produce) is imported to flax, published through the model zoo, and
served batch-inference-style over an image table.
"""

import _pathsetup  # noqa: F401 — repo root on sys.path

import tempfile

import numpy as np
import torch
import torch.nn as tnn

from mmlspark_tpu.core.schema import ImageSchema
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.downloader import LocalRepo, ModelDownloader
from mmlspark_tpu.importers import import_torch_checkpoint
from mmlspark_tpu.models.networks import build_network
from mmlspark_tpu.stages.featurizer import ImageFeaturizer

SPEC = {"type": "resnet", "stage_sizes": [1, 1, 1], "width": 16,
        "num_classes": 10}


class TorchBlock(tnn.Module):
    def __init__(self, cin, cout, stride):
        super().__init__()
        self.conv1 = tnn.Conv2d(cin, cout, 3, stride, 1, bias=False)
        self.bn1 = tnn.BatchNorm2d(cout)
        self.conv2 = tnn.Conv2d(cout, cout, 3, 1, 1, bias=False)
        self.bn2 = tnn.BatchNorm2d(cout)
        self.downsample = None
        if stride != 1 or cin != cout:
            self.downsample = tnn.Sequential(
                tnn.Conv2d(cin, cout, 1, stride, bias=False),
                tnn.BatchNorm2d(cout))

    def forward(self, x):
        idt = x if self.downsample is None else self.downsample(x)
        y = torch.relu(self.bn1(self.conv1(x)))
        return torch.relu(idt + self.bn2(self.conv2(y)))


class TorchResNet(tnn.Module):
    """torchvision-style naming so the importer maps it directly."""

    def __init__(self, width=16, classes=10):
        super().__init__()
        self.conv1 = tnn.Conv2d(3, width, 3, 1, 1, bias=False)
        self.bn1 = tnn.BatchNorm2d(width)
        self.layer1 = tnn.Sequential(TorchBlock(width, width, 1))
        self.layer2 = tnn.Sequential(TorchBlock(width, width * 2, 2))
        self.layer3 = tnn.Sequential(TorchBlock(width * 2, width * 4, 2))
        self.fc = tnn.Linear(width * 4, classes)

    def forward(self, x):
        x = torch.relu(self.bn1(self.conv1(x)))
        x = self.layer3(self.layer2(self.layer1(x)))
        return self.fc(x.mean(dim=(2, 3)))


def main():
    # "pretrained" weights produced outside this framework
    torch.manual_seed(0)
    tmodel = TorchResNet()
    xb = torch.randn(64, 3, 32, 32)
    yb = torch.randint(0, 10, (64,))
    opt = torch.optim.SGD(tmodel.parameters(), lr=0.05)
    for _ in range(5):
        opt.zero_grad()
        tnn.functional.cross_entropy(tmodel(xb), yb).backward()
        opt.step()
    tmodel.eval()

    # import -> publish to the zoo -> download with sha256 verification
    variables = import_torch_checkpoint(
        tmodel.state_dict(), SPEC, validate_input_shape=[32, 32, 3])
    with tempfile.TemporaryDirectory() as root:
        repo = LocalRepo(f"{root}/repo")
        schema = repo.publish(
            "ResNet_pretrained", SPEC, variables, dataset="CIFAR",
            model_type="image", input_shape=[32, 32, 3],
            layer_names=build_network(SPEC).feature_layers())
        downloader = ModelDownloader(f"{root}/cache", repo=repo)

        # batched inference over an image table (cutOutputLayers=0 keeps
        # the classification head)
        rng = np.random.default_rng(0)
        rows = [ImageSchema.make_row(
            f"img{i}", rng.integers(0, 255, (32, 32, 3)).astype(np.uint8),
            "RGB") for i in range(16)]
        table = DataTable({"image": rows})
        model = ImageFeaturizer.from_model_schema(
            schema, downloader, cutOutputLayers=0, outputCol="scores")
        out = model.transform(table)
    pred = np.argmax(out["scores"], axis=1)
    print(f"scored {len(table)} images; logits {out['scores'].shape}, "
          f"predictions {pred.tolist()}")

    # fidelity: the imported graph must reproduce torch's outputs
    xs = np.stack([r[ImageSchema.DATA] for r in rows]).astype(np.float32)
    with torch.no_grad():
        ref = tmodel(torch.tensor(xs).permute(0, 3, 1, 2) / 255.0).numpy()
    np.testing.assert_allclose(out["scores"], ref, rtol=1e-3, atol=1e-4)
    print("imported model matches torch outputs to 1e-4")


if __name__ == "__main__":
    main()
