"""Spark-Serving parity: a fitted pipeline behind an HTTP endpoint.

The reference turns a streaming DataFrame into a web service with
``readStream.server()...writeStream.server()`` (ref: ServingImplicits
.scala:10-50, HTTPSource.scala:48-178). Here: serve_model() parks each
request, micro-batches them through the pipeline, and answers through
the connection that accepted each request (reply-by-uuid). Poison
requests get per-row 500s without failing their batchmates.
"""

import _pathsetup  # noqa: F401 — repo root on sys.path

import json
import urllib.error
import urllib.request

import numpy as np

from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.gbdt import TPUBoostClassifier
from mmlspark_tpu.serving.server import serve_model
from mmlspark_tpu.stages.basic import Lambda


def main():
    # fit a model to serve
    rng = np.random.default_rng(0)
    X = rng.normal(size=(500, 4))
    y = (X[:, 0] + X[:, 1] > 0).astype(float)
    model = TPUBoostClassifier(numIterations=20, maxBin=32).fit(
        DataTable({"features": X, "label": y}))

    # request JSON {"features": [...]} -> reply {"probability": p}
    def handle(table):
        feats = np.stack([
            np.asarray(json.loads(r["entity"].decode())["features"],
                       dtype=np.float64)
            for r in table["request"]])
        scored = model.transform(DataTable({"features": feats}))
        return table.with_column("reply", [
            {"probability": float(p[1])} for p in scored["probability"]])

    engine = serve_model(Lambda.apply(handle), port=18800, batch_size=32)
    print(f"serving on {engine.source.address}")

    try:
        for features in ([2.0, 2.0, 0.0, 0.0], [-2.0, -2.0, 0.0, 0.0]):
            req = urllib.request.Request(
                engine.source.address,
                data=json.dumps({"features": features}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                print(f"features={features} -> {json.loads(r.read())}")
        # malformed request: per-row 500, server stays healthy
        bad = urllib.request.Request(engine.source.address,
                                     data=b"not json")
        try:
            urllib.request.urlopen(bad, timeout=30)
        except urllib.error.HTTPError as e:
            assert e.code == 500, e.code
            print(f"poison request -> {e.code} (server still up)")
        else:
            raise AssertionError("malformed request should have been a 500")
        print(f"answered={engine.source.requests_answered}")
    finally:
        engine.stop()


if __name__ == "__main__":
    main()
