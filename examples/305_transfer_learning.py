"""Notebook-305 parity: transfer learning with ImageFeaturizer.

The reference featurizes flower images through a truncated pretrained CNN
and trains a classical head on the features (ref: notebooks/samples/305 +
ImageFeaturizer.scala:91-141). Here: a zoo ResNet backbone is cut one
layer before the head, the pooled features feed a GBDT classifier, and
the pipeline separates bright-vs-dark image classes.
"""

import _pathsetup  # noqa: F401 — repo root on sys.path

import tempfile

import numpy as np
import jax
import jax.numpy as jnp

from mmlspark_tpu.core.schema import ImageSchema
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.downloader import LocalRepo, ModelDownloader
from mmlspark_tpu.gbdt import TPUBoostClassifier
from mmlspark_tpu.models.networks import build_network
from mmlspark_tpu.stages.featurizer import ImageFeaturizer

SPEC = {"type": "resnet", "stage_sizes": [1, 1, 1], "width": 8,
        "num_classes": 10}


def make_images(n=80, seed=0):
    rng = np.random.default_rng(seed)
    rows, labels = [], []
    for i in range(n):
        base = 60 if i % 2 == 0 else 180
        img = np.clip(rng.normal(base, 35, (32, 32, 3)), 0, 255)
        rows.append(ImageSchema.make_row(f"img{i}",
                                         img.astype(np.uint8), "RGB"))
        labels.append(float(i % 2))
    return DataTable({"image": rows, "label": np.asarray(labels)})


def main():
    # publish a backbone to the zoo (any pretrained weights work; see
    # examples/301 for importing torch checkpoints)
    with tempfile.TemporaryDirectory() as root:
        repo = LocalRepo(f"{root}/repo")
        module = build_network(SPEC)
        variables = module.init(jax.random.PRNGKey(0),
                                jnp.zeros((1, 32, 32, 3)))
        schema = repo.publish("ResNet_backbone", SPEC, variables,
                              input_shape=[32, 32, 3],
                              layer_names=module.feature_layers())
        downloader = ModelDownloader(f"{root}/cache", repo=repo)

        table = make_images()
        featurizer = ImageFeaturizer.from_model_schema(
            schema, downloader, cutOutputLayers=1)   # cut head -> pooled
        feats = featurizer.transform(table)
    print(f"features: {feats['features'].shape}")

    head = TPUBoostClassifier(numIterations=20, maxBin=32).fit(feats)
    scored = head.transform(feats)
    acc = (scored["prediction"] == table["label"]).mean()
    print(f"transfer-learning accuracy: {acc:.3f}")
    assert acc > 0.9


def imagenet_checkpoint_demo():
    """The published-checkpoint flow: a torchvision-resnet18-layout
    checkpoint (here: an in-image torch twin standing in for the real
    download — the layout/numerics are pinned by
    tests/test_torchvision_import.py) imports into the flax ImageNet
    ResNet, publishes through the zoo, and featurizes images via layer
    cutting (ref: ModelDownloader.scala:209, ImageFeaturizer.scala:91)."""
    import torch

    from mmlspark_tpu.importers.torch_import import (
        TORCHVISION_RESNET18_SPEC, import_torchvision_resnet)
    from mmlspark_tpu.testing.torch_models import build_torch_resnet18

    torch.manual_seed(0)
    twin = build_torch_resnet18().eval()
    with tempfile.TemporaryDirectory() as root:
        # "download": a real torchvision/HF file (.pth or .safetensors)
        # drops into this exact call
        ckpt = f"{root}/resnet18.pth"
        torch.save(twin.state_dict(), ckpt)
        variables = import_torchvision_resnet(ckpt)

        repo = LocalRepo(f"{root}/repo")
        module = build_network(TORCHVISION_RESNET18_SPEC)
        schema = repo.publish(
            "ResNet18_ImageNet", TORCHVISION_RESNET18_SPEC, variables,
            dataset="ImageNet", model_type="vision/classification",
            input_shape=[224, 224, 3],
            layer_names=module.feature_layers())
        downloader = ModelDownloader(f"{root}/cache", repo=repo)
        featurizer = ImageFeaturizer.from_model_schema(
            schema, downloader, cutOutputLayers=1)   # 512-d embeddings

        table = make_images(n=24)
        feats = featurizer.transform(table)
    emb = np.asarray(feats["features"])
    print(f"imported-backbone embeddings: {emb.shape}")
    assert emb.shape[1] == 512

    head = TPUBoostClassifier(numIterations=15, maxBin=32,
                              minDataInLeaf=2).fit(feats)
    acc = (head.transform(feats)["prediction"] == table["label"]).mean()
    print(f"imported-backbone transfer accuracy: {acc:.3f}")
    assert acc > 0.9


if __name__ == "__main__":
    main()
    imagenet_checkpoint_demo()
