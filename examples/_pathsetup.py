"""Put the repo root on sys.path so examples run from anywhere
(`import _pathsetup` works because the script's own directory is always
on sys.path, for both direct execution and runpy.run_path)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
