"""Multi-model serving: a zoo of versioned models behind one fleet.

The reference framework existed to serve a *model zoo* (downloader +
Spark Serving); here a ``ModelZoo`` multiplexes many versioned models
through one fleet (docs/model_zoo.md): requests carry
``model=name@version`` (an ``X-Model`` header or a ``/models/...``
path), models activate lazily on first request and evict LRU under a
resident budget, and an admission layer adds per-tenant quotas so one
hot tenant cannot starve the rest.
"""

import _pathsetup  # noqa: F401 — repo root on sys.path

import json
import urllib.error

import numpy as np

from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.serving import (
    AdmissionController, ModelZoo, ServingFleet, TenantQuota,
)
from mmlspark_tpu.stages.basic import Lambda


def linear_scorer(name, w):
    """Factory for one zoo model: scores features against its own
    weights and stamps its identity into every reply."""
    def build():
        def handle(table):
            feats = np.stack([
                np.asarray(json.loads(r["entity"].decode())["features"],
                           dtype=np.float32)
                for r in table["request"]])
            preds = (feats @ w).argmax(-1)
            return table.with_column("reply", [
                {"model": name, "prediction": int(p)} for p in preds])
        return Lambda.apply(handle)
    return build


def main():
    rng = np.random.default_rng(0)

    # a zoo of 16 versioned models, at most 4 resident at once — the
    # rest activate lazily on first request and evict LRU
    zoo = ModelZoo(max_resident=4, memory_probe=None)
    for i in range(16):
        w = rng.normal(size=(4, 3)).astype(np.float32)
        zoo.register_factory(f"scorer{i}", "v1",
                             linear_scorer(f"scorer{i}", w),
                             metadata={"cost_bytes": int(w.nbytes)})

    # the "free" tenant gets 3 requests of burst and nothing sustained
    admission = AdmissionController(
        quotas={"free": TenantQuota(0.0, burst=3)})
    fleet = ServingFleet(n_engines=2, base_port=18820, zoo=zoo,
                         admission=admission, tracing=False)
    try:
        # spray 12 different models through ONE fleet: each activates
        # on first touch; the 4-model cache churns underneath
        for i in range(12):
            body = fleet.post({"features": [0.1 * i, 1.0, -0.5, 0.2]},
                              model=f"scorer{i}", tenant="paid")
            assert body["model"] == f"scorer{i}", body
        stats = zoo.stats()
        print(f"served 12 models; resident={stats['by_state']['resident']}"
              f" activations={stats['activations']}"
              f" evictions={stats['evictions']}")
        assert stats["by_state"]["resident"] <= 4
        assert stats["evictions"] > 0

        # the free tenant burns its burst, then answers 429 — while
        # the paid tenant keeps scoring
        free_ok = free_shed = 0
        for i in range(6):
            try:
                fleet.post({"features": [1, 0, 0, 0]},
                           model="scorer0", tenant="free")
                free_ok += 1
            except urllib.error.HTTPError as e:
                assert e.code == 429, e.code
                free_shed += 1
        body = fleet.post({"features": [1, 0, 0, 0]},
                          model="scorer0", tenant="paid")
        print(f"free tenant: {free_ok} ok / {free_shed} shed(429); "
              f"paid tenant still served by {body['model']}")
        assert free_shed > 0 and body["model"] == "scorer0"

        # the audit trail: every register/activate/evict is an event
        kinds = {}
        for e in zoo.events:
            kinds[e.kind] = kinds.get(e.kind, 0) + 1
        print(f"audit log: {kinds}")
    finally:
        fleet.stop_all()
        zoo.close()
    print("model zoo example OK")


if __name__ == "__main__":
    main()
