"""Notebook-106 parity: quantile regression with the GBDT engine.

The reference trains LightGBMRegressor with objective='quantile' on the
triazines dataset (ref: notebooks/samples/106 + TrainParams.scala:48-61).
Here: TPUBoostRegressor fits the 0.9 quantile of diabetes progression,
checks empirical coverage, and round-trips the model through its string
serialization (the LightGBM modelString analog).
"""

import _pathsetup  # noqa: F401 — repo root on sys.path

import numpy as np

from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.gbdt import Booster, TPUBoostRegressor


def main():
    from sklearn.datasets import load_diabetes
    X, y = load_diabetes(return_X_y=True)
    table = DataTable({"features": X, "label": y})

    reg = TPUBoostRegressor(objective="quantile", alpha=0.9,
                            numIterations=100, minDataInLeaf=10)
    model = reg.fit(table)
    pred = model.transform(table)["prediction"]
    coverage = (y <= pred).mean()
    print(f"target quantile 0.90, empirical coverage {coverage:.3f}")
    assert 0.85 < coverage < 0.95

    # model-string round trip (ref: LightGBMBooster.scala:14-33)
    s = model.get_booster().model_to_string()
    reloaded = Booster.from_string(s)
    np.testing.assert_allclose(reloaded.predict(X), pred, atol=1e-6)
    print(f"model string round-trip OK ({len(s)} bytes)")

    imp = model.get_feature_importances("gain")
    print(f"top features by gain: {np.argsort(-imp)[:3].tolist()}")


if __name__ == "__main__":
    main()
