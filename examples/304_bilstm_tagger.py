"""Notebook-304 parity: Bi-LSTM sequence tagging.

The reference runs a pretrained Keras/CNTK Bi-LSTM medical entity
extractor through CNTKModel (ref: notebooks/samples/304). Here: the
BiLSTMTagger zoo module is trained on a synthetic token-tagging task
(tag = token parity class, requiring context) and produces per-token
predictions through TPULearner/TPUModel.
"""

import _pathsetup  # noqa: F401 — repo root on sys.path

import numpy as np

from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.models.learner import TPULearner

VOCAB, SEQ, TAGS = 50, 12, 3


def make_data(n=512, seed=0):
    rng = np.random.default_rng(seed)
    toks = rng.integers(1, VOCAB, size=(n, SEQ))
    # tag depends on the current and PREVIOUS token — solvable only with
    # sequence context, which is what the recurrence provides
    prev = np.roll(toks, 1, axis=1)
    prev[:, 0] = 0
    tags = ((toks + prev) % TAGS).astype(np.int64)
    return toks.astype(np.int64), tags


def main():
    toks, tags = make_data()
    table = DataTable({"features": toks, "label": tags})

    learner = TPULearner(
        networkSpec={"type": "bilstm", "vocab_size": VOCAB,
                     "embed_dim": 32, "hidden": 64, "num_tags": TAGS},
        loss="token_cross_entropy", epochs=30, batchSize=128,
        learningRate=0.01, optimizer="adam", computeDtype="float32",
        logEvery=50)
    model = learner.fit(table)

    test_toks, test_tags = make_data(n=128, seed=1)
    out = model.transform(DataTable({"features": test_toks}))
    pred = np.argmax(out["scores"], axis=-1)
    acc = (pred == test_tags).mean()
    print(f"per-token tagging accuracy: {acc:.3f}")
    assert acc > 0.9


if __name__ == "__main__":
    main()
