"""Long-context LM training with sequence parallelism.

No reference-notebook twin — this is the capability the TPU build adds
beyond the reference (SURVEY §5 long-context): a decoder-only LM whose
sequence dimension is sharded across the mesh, attention running as a
ppermute ring (exact online-softmax) so the per-device memory stays
O(L/num_shards). The same weights run dense on one device or ring/
Ulysses on a pod; gradients are bit-checked against dense attention in
tests/test_ring_attention.py. On TPU with shards >= 512, every ring hop
runs inside the Pallas flash kernel (ring_flash_attention) so no
(Lq, Lk_local) score tensor exists in forward or backward — L=32k
causal fwd+bwd measures 0.32 s/step on one v5e chip.
"""

import _pathsetup  # noqa: F401 — repo root on sys.path

import numpy as np
import jax
import jax.numpy as jnp
import optax

from mmlspark_tpu.models.networks import Transformer
from mmlspark_tpu.parallel import mesh as mesh_lib
from mmlspark_tpu.parallel.ring_attention import (
    make_seq_parallel_train_step,
)

VOCAB, DIM, DEPTH, HEADS = 64, 32, 2, 4


def make_copy_task(n, length, seed=0):
    """Tokens repeat with period 4 — predictable only from context."""
    rng = np.random.default_rng(seed)
    base = rng.integers(1, VOCAB, size=(n, 4))
    toks = np.tile(base, (1, length // 4))[:, :length]
    targets = np.roll(toks, -1, axis=1)
    return jnp.asarray(toks, jnp.int32), jnp.asarray(targets, jnp.int32)


def main():
    n_dev = len(jax.devices())
    seq_shards = 4 if n_dev % 4 == 0 else (2 if n_dev % 2 == 0 else 1)
    data = n_dev // seq_shards
    mesh = mesh_lib.make_mesh({"data": data, "seq": seq_shards})
    L = 16 * seq_shards    # global sequence, sharded over the seq axis

    module = Transformer(vocab_size=VOCAB, dim=DIM, depth=DEPTH,
                         heads=HEADS, max_len=L, seq_axis="seq",
                         seq_impl="ring")
    dense = Transformer(vocab_size=VOCAB, dim=DIM, depth=DEPTH,
                        heads=HEADS, max_len=L)

    toks, targets = make_copy_task(4 * data, L)
    params = dense.init(jax.random.PRNGKey(0), toks[:1])
    opt = optax.adam(3e-3)
    opt_state = opt.init(params)
    step = make_seq_parallel_train_step(module, mesh, opt)

    first = last = None
    for i in range(60):
        params, opt_state, loss = step(params, opt_state, toks, targets)
        if i == 0:
            first = loss
        last = loss          # device arrays — no per-step host sync
    first, last = float(first), float(last)
    print(f"mesh={dict(mesh.shape)} global_seq={L}: "
          f"loss {first:.3f} -> {last:.3f}")
    assert last < first * 0.5, "LM failed to learn the periodic task"

    # the SAME weights run dense on a single device
    logits = dense.apply(params, toks[:1])
    pred = np.asarray(jnp.argmax(logits[0, :-1], axis=-1))
    acc = float((pred[4:] == np.asarray(toks[0, 5:])).mean())
    print(f"dense single-device decode accuracy on the task: {acc:.2f}")
    assert acc > 0.9


if __name__ == "__main__":
    main()
