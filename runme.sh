#!/usr/bin/env bash
# One-command build/test/package pipeline — the sbt-chain analog
# (ref: src/project/build.scala:86-97 packages + publishes every
# module; runme there drives the full build). Produces an installable
# wheel in dist/ with the native library compiled in.
set -euo pipefail
cd "$(dirname "$0")"

echo "== 1/4 native build =="
cmake -S mmlspark_tpu/native -B mmlspark_tpu/native/build \
      -DCMAKE_BUILD_TYPE=Release
cmake --build mmlspark_tpu/native/build --config Release -j

echo "== 2/4 tests =="
python -m pytest tests/ -q

echo "== 3/4 codegen artifacts =="
python -m mmlspark_tpu.codegen docs/api

echo "== 4/4 wheel =="
rm -rf build dist *.egg-info
python -m pip wheel . -w dist --no-deps --no-build-isolation
ls -l dist/
echo "done: pip install dist/*.whl"
