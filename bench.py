"""Flagship benchmarks: CIFAR-10 ConvNet training throughput (the
cntk-train headline path) + HIGGS-shaped GBDT training wall-clock (the
lightgbm headline path). BASELINE.json names exactly these two.

CIFAR (ref: notebooks/gpu/401 — BrainScript ConvNet on 32x32x3 CIFAR-10,
parallelTrain on a 4-GPU Azure N-series VM). The reference publishes no
absolute numbers, so the primary vs_baseline constant is the
commonly-reported single-K80 CNTK ConvNet throughput for that hardware
class, ~1000 imgs/sec. A measured in-image torch-CPU baseline (run
``python tools/measure_baseline.py``, stored in BASELINE.json under
"measured") is reported alongside when present.

The training feed is DEVICE-RESIDENT (``TPULearner(dataFeed='device')``):
the padded dataset lives in HBM, each epoch is shuffled on device, and the
steady-state step consumes only a scalar index — so the number measures
the chip, not host feed scheduling. MFU is computed from XLA's own
cost-analysis FLOPs of the compiled train step against the chip's bf16
peak (imgs/sec stays the headline; MFU makes it auditable).

A ResNet-20 config (the notebook-301/401 model family) runs as a second
training metric — the model where the MXU actually works.

GBDT (ref: docs/lightgbm.md:16-18 — LightGBM-on-Spark "10-30% faster"
than SparkML GBT on HIGGS, no absolute number). Config mirrors the
LightGBM HIGGS benchmark shape: 1M rows x 28 features, binary objective,
63 leaves, 63 bins, 40 iterations. vs_baseline prefers the MEASURED
in-image sklearn HistGradientBoosting wall-clock on the identical config
(BASELINE.json "measured"); the historical ~35 s LightGBM-CPU constant is
the fallback and stays in the JSON as context. Wall-clock vs_baseline is
baseline/ours, so >= 1.0 means we are faster.

Prints ONE JSON line: the CIFAR headline with the other results under
"secondary". Runs on whatever jax.devices() provides (the real TPU chip
under axon).
"""

import json
import os
import time

import numpy as np

# Azure N-series (K80-class) CNTK ConvNet throughput, imgs/sec/GPU — the
# reference's notebook-401 hardware (no absolute number published; see
# BASELINE.md).
BASELINE_IMGS_PER_SEC_PER_CHIP = 1000.0

# native LightGBM, 16-core CPU node, 1M x 28 HIGGS subsample, 63 leaves /
# 63 bins / 40 iters (docs/lightgbm.md publishes no absolute number; see
# module docstring). Fallback when no measured baseline exists.
BASELINE_HIGGS_WALL_S = 35.0

BATCH = 512
STEPS_TARGET = 320

HIGGS_N, HIGGS_F = 1_000_000, 28
HIGGS_VALID_N = 100_000


def _measured_baselines() -> dict:
    """Measured baselines from BASELINE.json — only if they were measured
    on THIS machine (else a different box's numbers would masquerade as a
    measured-vs-measured comparison; rerun tools/measure_baseline.py)."""
    import platform
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BASELINE.json")
    try:
        with open(path) as f:
            measured = json.load(f).get("measured", {})
    except Exception:
        return {}
    here = f"{platform.machine()}, {os.cpu_count()} cores"
    if measured.get("machine") != here:
        print(f"# measured baselines are from {measured.get('machine')!r}, "
              f"this is {here!r}; falling back to documented constants",
              flush=True)
        return {}
    return measured


def _train_throughput(network_spec: dict, steps_target: int) -> dict:
    """Train on synthetic CIFAR-shaped data with the device-resident feed;
    return imgs/sec/chip + MFU from the learner's own timing."""
    import jax

    from mmlspark_tpu.core.table import DataTable
    from mmlspark_tpu.models.learner import TPULearner
    from mmlspark_tpu.parallel import mesh as mesh_lib

    n_chips = len(jax.devices())
    mesh = mesh_lib.make_mesh({"data": n_chips})

    rng = np.random.default_rng(0)
    # 32 steps/epoch: each epoch is ONE device dispatch, so more steps
    # per epoch amortizes tunnel dispatch latency out of the steady state
    n = BATCH * 32
    x = rng.integers(0, 256, size=(n, 32, 32, 3)).astype(np.float32) / 255.0
    y = rng.integers(0, 10, size=n).astype(np.int64)
    table = DataTable({"features": x.reshape(n, -1), "label": y})

    steps_per_epoch = n // BATCH
    epochs = max(1, steps_target // steps_per_epoch)

    learner = TPULearner(
        networkSpec=network_spec,
        inputShape=[32, 32, 3],
        batchSize=BATCH, learningRate=0.1, computeDtype="bfloat16",
        epochs=epochs, logEvery=10_000, dataFeed="device")
    learner.set_mesh(mesh)
    learner.fit(table)

    t = learner.timing
    out = {
        "imgs_per_sec_per_chip": t["examples_per_sec"] / n_chips,
        "steps_timed": t["steps_timed"],
    }
    if "tflops_per_sec_per_chip" in t:
        out["tflops_per_sec_per_chip"] = round(t["tflops_per_sec_per_chip"], 2)
    if "mfu" in t:
        out["mfu"] = round(t["mfu"], 4)
    return out


def bench_cifar() -> dict:
    # notebook-401 ConvNet shape: 3 conv layers + dense, bf16 on the MXU
    return _train_throughput(
        {"type": "convnet", "conv_features": [64, 64, 64],
         "dense_features": [256], "num_classes": 10}, STEPS_TARGET)


def bench_resnet() -> dict:
    # notebook-301/401 model family: CIFAR ResNet-20 (stage_sizes 3,3,3)
    return _train_throughput(
        {"type": "resnet", "stage_sizes": [3, 3, 3], "width": 16,
         "num_classes": 10}, STEPS_TARGET // 2)


def bench_higgs_gbdt():
    from sklearn.metrics import roc_auc_score

    from mmlspark_tpu.gbdt.booster import train

    rng = np.random.default_rng(0)
    n = HIGGS_N + HIGGS_VALID_N
    X = rng.normal(size=(n, HIGGS_F)).astype(np.float32)
    logit = (X[:, 0] * 1.5 + X[:, 1] * X[:, 2]
             + 0.5 * np.sin(3 * X[:, 3])
             + rng.normal(scale=0.5, size=n))
    y = (logit > 0).astype(np.float64)
    Xtr, ytr = X[:HIGGS_N], y[:HIGGS_N]
    Xte, yte = X[HIGGS_N:], y[HIGGS_N:]

    params = {"objective": "binary", "num_iterations": 40,
              "num_leaves": 63, "max_bin": 63, "min_data_in_leaf": 50}
    # one-iteration warmup at the FULL training shape isolates XLA
    # compile from the measured train (jit caches are shape-keyed)
    train({**params, "num_iterations": 1}, Xtr, ytr)
    t0 = time.time()
    booster = train(params, Xtr, ytr)
    wall = time.time() - t0
    auc = roc_auc_score(yte, booster.predict(Xte))
    return wall, auc, booster.params["hist_method"]


def main():
    measured = _measured_baselines()
    cifar = bench_cifar()
    resnet = bench_resnet()
    higgs_wall, higgs_auc, hist_method = bench_higgs_gbdt()

    per_chip = cifar["imgs_per_sec_per_chip"]
    gbdt_base = measured.get("higgs1m_sklearn_hgb_wall_s")
    gbdt_source = "measured:sklearn_hist_gradient_boosting"
    if not gbdt_base:
        gbdt_base, gbdt_source = BASELINE_HIGGS_WALL_S, "constant:lightgbm_cpu"

    result = {
        "metric": "cifar10_convnet_train_imgs_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "imgs/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMGS_PER_SEC_PER_CHIP, 3),
        "feed": "device-resident",
        "secondary": {
            "metric": "higgs1m_gbdt_train_wall_clock",
            "value": round(higgs_wall, 1),
            "unit": "s",
            "vs_baseline": round(gbdt_base / higgs_wall, 3),
            "baseline_wall_s": gbdt_base,
            "baseline_source": gbdt_source,
            # AUC of the synthetic separable logit, NOT real HIGGS model
            # quality (accuracy gates live in tests/test_benchmarks.py)
            "synthetic_holdout_auc": round(higgs_auc, 4),
            "hist_method": hist_method,
            "config": f"{HIGGS_N}x{HIGGS_F}, 63 leaves, 63 bins, 40 iters",
        },
    }
    for key in ("tflops_per_sec_per_chip", "mfu"):
        if key in cifar:
            result[key] = cifar[key]
    resnet_entry = {
        "metric": "cifar10_resnet20_train_imgs_per_sec_per_chip",
        "value": round(resnet["imgs_per_sec_per_chip"], 1),
        "unit": "imgs/sec/chip",
    }
    for key in ("tflops_per_sec_per_chip", "mfu"):
        if key in resnet:
            resnet_entry[key] = resnet[key]
    result["secondary_resnet"] = resnet_entry
    if measured.get("cifar_convnet_torch_cpu_imgs_per_sec"):
        result["cpu_measured_baseline_imgs_per_sec"] = measured[
            "cifar_convnet_torch_cpu_imgs_per_sec"]

    print(json.dumps(result))


if __name__ == "__main__":
    main()
