"""Flagship benchmark: CIFAR-10 ConvNet training throughput (imgs/sec/chip).

This is the cntk-train headline path (ref: notebooks/gpu/401 — BrainScript
ConvNet on 32x32x3 CIFAR-10, parallelTrain on a 4-GPU Azure N-series VM).
BASELINE.md: the reference publishes no absolute numbers, so the baseline
constant below is the commonly-reported single-K80 CNTK ConvNet throughput
for that hardware class, ~1000 imgs/sec.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Runs on whatever jax.devices() provides (the real TPU chip under axon).
"""

import json

import numpy as np

# Azure N-series (K80-class) CNTK ConvNet throughput, imgs/sec/GPU — the
# reference's notebook-401 hardware (no absolute number published; see
# BASELINE.md).
BASELINE_IMGS_PER_SEC_PER_CHIP = 1000.0

BATCH = 512
STEPS_TARGET = 60


def main():
    import jax

    from mmlspark_tpu.core.table import DataTable
    from mmlspark_tpu.models.learner import TPULearner
    from mmlspark_tpu.parallel import mesh as mesh_lib

    n_chips = len(jax.devices())
    mesh = mesh_lib.make_mesh({"data": n_chips})

    rng = np.random.default_rng(0)
    n = BATCH * 8
    x = rng.integers(0, 256, size=(n, 32, 32, 3)).astype(np.float32) / 255.0
    y = rng.integers(0, 10, size=n).astype(np.int64)
    table = DataTable({"features": x.reshape(n, -1), "label": y})

    steps_per_epoch = n // BATCH
    epochs = max(1, STEPS_TARGET // steps_per_epoch)

    # notebook-401 ConvNet shape: 3 conv layers + dense, bf16 on the MXU
    learner = TPULearner(
        networkSpec={"type": "convnet", "conv_features": [64, 64, 64],
                     "dense_features": [256], "num_classes": 10},
        inputShape=[32, 32, 3],
        batchSize=BATCH, learningRate=0.1, computeDtype="bfloat16",
        epochs=epochs, logEvery=1000)
    learner.set_mesh(mesh)

    learner.fit(table)

    # steady-state throughput measured by the learner itself: device-synced
    # at the first-step boundary (after compile) and at the final state, so
    # async dispatch can't inflate or deflate the number
    per_chip = learner.timing["examples_per_sec"] / n_chips

    print(json.dumps({
        "metric": "cifar10_convnet_train_imgs_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "imgs/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMGS_PER_SEC_PER_CHIP, 3),
    }))


if __name__ == "__main__":
    main()
