"""Flagship benchmarks: CIFAR-10 ConvNet training throughput (the
cntk-train headline path) + HIGGS-shaped GBDT training wall-clock (the
lightgbm headline path). BASELINE.json names exactly these two.

CIFAR (ref: notebooks/gpu/401 — BrainScript ConvNet on 32x32x3 CIFAR-10,
parallelTrain on a 4-GPU Azure N-series VM). The reference publishes no
absolute numbers, so the baseline constant is the commonly-reported
single-K80 CNTK ConvNet throughput for that hardware class, ~1000
imgs/sec.

GBDT (ref: docs/lightgbm.md:16-18 — LightGBM-on-Spark "10-30% faster"
than SparkML GBT on HIGGS, no absolute number). Config mirrors the
LightGBM HIGGS benchmark shape: 1M rows x 28 features, binary objective,
63 leaves, 63 bins, 40 iterations. Baseline constant: native LightGBM on
a 16-core CPU node runs this config in ~35 s wall-clock (the
order-of-magnitude from LightGBM's published experiments, scaled to 1M
rows); no lightgbm binary exists in this image to re-measure. Wall-clock
vs_baseline is baseline/ours, so >= 1.0 means we are faster.

Prints ONE JSON line: the CIFAR headline with the GBDT result under
"secondary". Runs on whatever jax.devices() provides (the real TPU chip
under axon).
"""

import json
import time

import numpy as np

# Azure N-series (K80-class) CNTK ConvNet throughput, imgs/sec/GPU — the
# reference's notebook-401 hardware (no absolute number published; see
# BASELINE.md).
BASELINE_IMGS_PER_SEC_PER_CHIP = 1000.0

# native LightGBM, 16-core CPU node, 1M x 28 HIGGS subsample, 63 leaves /
# 63 bins / 40 iters (docs/lightgbm.md publishes no absolute number; see
# module docstring)
BASELINE_HIGGS_WALL_S = 35.0

BATCH = 512
STEPS_TARGET = 60

HIGGS_N, HIGGS_F = 1_000_000, 28
HIGGS_VALID_N = 100_000


def bench_cifar():
    import jax

    from mmlspark_tpu.core.table import DataTable
    from mmlspark_tpu.models.learner import TPULearner
    from mmlspark_tpu.parallel import mesh as mesh_lib

    n_chips = len(jax.devices())
    mesh = mesh_lib.make_mesh({"data": n_chips})

    rng = np.random.default_rng(0)
    n = BATCH * 8
    x = rng.integers(0, 256, size=(n, 32, 32, 3)).astype(np.float32) / 255.0
    y = rng.integers(0, 10, size=n).astype(np.int64)
    table = DataTable({"features": x.reshape(n, -1), "label": y})

    steps_per_epoch = n // BATCH
    epochs = max(1, STEPS_TARGET // steps_per_epoch)

    # notebook-401 ConvNet shape: 3 conv layers + dense, bf16 on the MXU
    learner = TPULearner(
        networkSpec={"type": "convnet", "conv_features": [64, 64, 64],
                     "dense_features": [256], "num_classes": 10},
        inputShape=[32, 32, 3],
        batchSize=BATCH, learningRate=0.1, computeDtype="bfloat16",
        epochs=epochs, logEvery=1000)
    learner.set_mesh(mesh)

    learner.fit(table)

    # steady-state throughput measured by the learner itself: device-synced
    # at the first-step boundary (after compile) and at the final state, so
    # async dispatch can't inflate or deflate the number
    return learner.timing["examples_per_sec"] / n_chips


def bench_higgs_gbdt():
    from sklearn.metrics import roc_auc_score

    from mmlspark_tpu.gbdt.booster import train

    rng = np.random.default_rng(0)
    n = HIGGS_N + HIGGS_VALID_N
    X = rng.normal(size=(n, HIGGS_F)).astype(np.float32)
    logit = (X[:, 0] * 1.5 + X[:, 1] * X[:, 2]
             + 0.5 * np.sin(3 * X[:, 3])
             + rng.normal(scale=0.5, size=n))
    y = (logit > 0).astype(np.float64)
    Xtr, ytr = X[:HIGGS_N], y[:HIGGS_N]
    Xte, yte = X[HIGGS_N:], y[HIGGS_N:]

    params = {"objective": "binary", "num_iterations": 40,
              "num_leaves": 63, "max_bin": 63, "min_data_in_leaf": 50}
    # one-iteration warmup at the FULL training shape isolates XLA
    # compile from the measured train (jit caches are shape-keyed)
    train({**params, "num_iterations": 1}, Xtr, ytr)
    t0 = time.time()
    booster = train(params, Xtr, ytr)
    wall = time.time() - t0
    auc = roc_auc_score(yte, booster.predict(Xte))
    return wall, auc, booster.params["hist_method"]


def main():
    per_chip = bench_cifar()
    higgs_wall, higgs_auc, hist_method = bench_higgs_gbdt()

    print(json.dumps({
        "metric": "cifar10_convnet_train_imgs_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "imgs/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMGS_PER_SEC_PER_CHIP, 3),
        "secondary": {
            "metric": "higgs1m_gbdt_train_wall_clock",
            "value": round(higgs_wall, 1),
            "unit": "s",
            "vs_baseline": round(BASELINE_HIGGS_WALL_S / higgs_wall, 3),
            "holdout_auc": round(higgs_auc, 4),
            "hist_method": hist_method,
            "config": f"{HIGGS_N}x{HIGGS_F}, 63 leaves, 63 bins, 40 iters",
        },
    }))


if __name__ == "__main__":
    main()
