"""Flagship benchmarks: CIFAR-10 ConvNet training throughput (the
cntk-train headline path) + HIGGS-shaped GBDT training wall-clock (the
lightgbm headline path). BASELINE.json names exactly these two.

CIFAR (ref: notebooks/gpu/401 — BrainScript ConvNet on 32x32x3 CIFAR-10,
parallelTrain on a 4-GPU Azure N-series VM). The reference publishes no
absolute numbers, so the primary vs_baseline constant is the
commonly-reported single-K80 CNTK ConvNet throughput for that hardware
class, ~1000 imgs/sec. A measured in-image torch-CPU baseline (run
``python tools/measure_baseline.py``, stored in BASELINE.json under
"measured") is reported alongside when present.

The training feed is DEVICE-RESIDENT (``TPULearner(dataFeed='device')``):
the padded dataset lives in HBM, each epoch is shuffled on device, and the
steady-state step consumes only a scalar index — so the number measures
the chip, not host feed scheduling. MFU is computed from XLA's own
cost-analysis FLOPs of the compiled train step against the chip's bf16
peak (imgs/sec stays the headline; MFU makes it auditable).

A ResNet-20 config (the notebook-301/401 model family) runs as a second
training metric. Both CIFAR models are structurally MXU-lane-underfilled
(16-64 output channels vs 128 lanes — see docs/perf_analysis.md), so a
Transformer-LM config (dim 2048, 8 layers, seq 1024, vocab 32k, flash
attention, bf16 head) runs as the third: the model where the MXU gets
real work. Its MFU is the headline utilization number.

GBDT (ref: docs/lightgbm.md:16-18 — LightGBM-on-Spark "10-30% faster"
than SparkML GBT on HIGGS, no absolute number). Config mirrors the
LightGBM HIGGS benchmark shape: 1M rows x 28 features, binary objective,
63 leaves, 63 bins, 40 iterations. vs_baseline prefers the MEASURED
in-image sklearn HistGradientBoosting wall-clock on the identical config
(BASELINE.json "measured"); the historical ~35 s LightGBM-CPU constant is
the fallback and stays in the JSON as context. Wall-clock vs_baseline is
baseline/ours, so >= 1.0 means we are faster.

Prints ONE JSON line: the CIFAR headline with the other results under
"secondary". Runs on whatever jax.devices() provides (the real TPU chip
under axon).
"""

import json
import os
import time

import numpy as np

# Azure N-series (K80-class) CNTK ConvNet throughput, imgs/sec/GPU — the
# reference's notebook-401 hardware (no absolute number published; see
# BASELINE.md).
BASELINE_IMGS_PER_SEC_PER_CHIP = 1000.0

# native LightGBM, 16-core CPU node, 1M x 28 HIGGS subsample, 63 leaves /
# 63 bins / 40 iters (docs/lightgbm.md publishes no absolute number; see
# module docstring). Fallback when no measured baseline exists.
BASELINE_HIGGS_WALL_S = 35.0

BATCH = 1024
# 128 steps/epoch: each epoch is ONE device dispatch (lax.scan chunk).
# Chunk dispatches QUEUE asynchronously with no per-chunk overhead
# (measured: 4 queued chunks = 4x one chunk's exec, vs ~335 ms extra
# per chunk when syncing between them) — the only fixed cost in the
# timed window is the FINAL value-readback RTT, so more timed chunks
# amortize it: 2 timed chunks lose ~10% to it, 8 lose ~3%
# (epochs=3 -> MFU 0.166, 9 -> 0.181, 17 -> 0.184 asymptote on the
# bench ResNet). 9 epochs = 1 warmup (compile+sync) + 8 timed chunks.
STEPS_PER_EPOCH = 128
EPOCHS = 9

HIGGS_N, HIGGS_F = 1_000_000, 28
HIGGS_VALID_N = 100_000


def _enable_compile_cache() -> None:
    """Persistent XLA compilation cache (verified to work through the
    tunnel backend): repeat bench runs skip the multi-minute LM compile."""
    import jax
    cache_dir = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # older jax without the knobs: bench still runs


def _measured_baselines() -> dict:
    """Measured baselines from BASELINE.json — only if they were measured
    on THIS machine (else a different box's numbers would masquerade as a
    measured-vs-measured comparison; rerun tools/measure_baseline.py)."""
    import platform
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BASELINE.json")
    try:
        with open(path) as f:
            measured = json.load(f).get("measured", {})
    except Exception:
        return {}
    here = f"{platform.machine()}, {os.cpu_count()} cores"
    if measured.get("machine") != here:
        print(f"# measured baselines are from {measured.get('machine')!r}, "
              f"this is {here!r}; falling back to documented constants",
              flush=True)
        return {}
    return measured


def _train_throughput(network_spec: dict) -> dict:
    """Train on synthetic CIFAR-shaped data with the device-resident feed;
    return imgs/sec/chip + MFU from the learner's own timing."""
    import jax

    from mmlspark_tpu.core.table import DataTable
    from mmlspark_tpu.models.learner import TPULearner
    from mmlspark_tpu.parallel import mesh as mesh_lib

    n_chips = len(jax.devices())
    mesh = mesh_lib.make_mesh({"data": n_chips})

    rng = np.random.default_rng(0)
    n = BATCH * STEPS_PER_EPOCH
    x = rng.integers(0, 256, size=(n, 32, 32, 3)).astype(np.float32) / 255.0
    y = rng.integers(0, 10, size=n).astype(np.int64)
    table = DataTable({"features": x.reshape(n, -1), "label": y})

    learner = TPULearner(
        networkSpec=network_spec,
        inputShape=[32, 32, 3],
        batchSize=BATCH, learningRate=0.1, computeDtype="bfloat16",
        epochs=EPOCHS, logEvery=10_000, dataFeed="device")
    learner.set_mesh(mesh)
    learner.fit(table)

    t = learner.timing
    out = {
        "imgs_per_sec_per_chip": t["examples_per_sec"] / n_chips,
        "steps_timed": t["steps_timed"],
    }
    if "tflops_per_sec_per_chip" in t:
        out["tflops_per_sec_per_chip"] = round(t["tflops_per_sec_per_chip"], 2)
    if "mfu" in t:
        out["mfu"] = round(t["mfu"], 4)
    return out


def bench_cifar() -> dict:
    # notebook-401 ConvNet shape: 3 conv layers + dense, bf16 on the MXU
    return _train_throughput(
        {"type": "convnet", "conv_features": [64, 64, 64],
         "dense_features": [256], "num_classes": 10})


def bench_resnet() -> dict:
    # notebook-301/401 model family: CIFAR ResNet-20 (stage_sizes 3,3,3)
    return _train_throughput(
        {"type": "resnet", "stage_sizes": [3, 3, 3], "width": 16,
         "num_classes": 10})


# LM config: GPT-2-medium-class width. dim 2048 fills the MXU's 128
# lanes 16x over; the vocab projection runs bf16 (head_dtype) and the
# attention path is the Pallas flash kernel (L=1024 >= FLASH_MIN_LEN).
LM_BATCH, LM_SEQ = 8, 1024
LM_SPEC = {"type": "transformer", "vocab_size": 32000, "dim": 2048,
           "depth": 8, "heads": 16, "max_len": LM_SEQ,
           "head_dtype": "bfloat16"}


def bench_lm() -> dict:
    """Decoder-only LM training — the config where the MXU gets real
    work (docs/perf_analysis.md §4). Next-token prediction on synthetic
    token streams; the quality gates for the transformer live in
    tests/test_benchmarks.py, this measures the chip."""
    import jax

    from mmlspark_tpu.core.table import DataTable
    from mmlspark_tpu.models.learner import TPULearner
    from mmlspark_tpu.parallel import mesh as mesh_lib

    n_chips = len(jax.devices())
    mesh = mesh_lib.make_mesh({"data": n_chips})
    rng = np.random.default_rng(0)
    n = LM_BATCH * 16
    toks = rng.integers(0, LM_SPEC["vocab_size"],
                        size=(n, LM_SEQ)).astype(np.float32)
    tgts = np.roll(toks.astype(np.int64), -1, axis=1)
    table = DataTable({"features": toks, "label": tgts})
    learner = TPULearner(
        networkSpec=LM_SPEC, loss="token_cross_entropy",
        batchSize=LM_BATCH, learningRate=1e-3, optimizer="adamw",
        computeDtype="bfloat16", epochs=5, logEvery=10_000,
        dataFeed="device")  # 4 timed chunks: the final-sync RTT is
    #                         ~5% of a 2-chunk window, ~2.5% of 4
    learner.set_mesh(mesh)
    learner.fit(table)
    t = learner.timing
    out = {
        "tokens_per_sec_per_chip": t["examples_per_sec"] * LM_SEQ / n_chips,
        "steps_timed": t["steps_timed"],
    }
    if "tflops_per_sec_per_chip" in t:
        out["tflops_per_sec_per_chip"] = round(t["tflops_per_sec_per_chip"], 2)
    if "mfu" in t:
        out["mfu"] = round(t["mfu"], 4)
    return out


def bench_higgs_gbdt():
    """Timed HIGGS-shaped training at BOTH 63 bins (the LightGBM HIGGS
    benchmark config, headline) and 255 bins (the engine default —
    exercises the Pallas kernel's larger VMEM tiling band). Each wall
    comes with the booster's per-phase breakdown (bin/ship[/bin_device]/
    first_iter/boost/fetch) plus the ingest path (bin_device vs
    bin_host) and fused-chunk length, so driver-side drift is
    attributable to a phase. The 63-bin config also runs once with
    device binning forced OFF so the device-vs-host ingest saving is
    measured, not assumed."""
    from sklearn.metrics import roc_auc_score

    from mmlspark_tpu.gbdt.booster import train

    rng = np.random.default_rng(0)
    n = HIGGS_N + HIGGS_VALID_N
    X = rng.normal(size=(n, HIGGS_F)).astype(np.float32)
    logit = (X[:, 0] * 1.5 + X[:, 1] * X[:, 2]
             + 0.5 * np.sin(3 * X[:, 3])
             + rng.normal(scale=0.5, size=n))
    y = (logit > 0).astype(np.float64)
    Xtr, ytr = X[:HIGGS_N], y[:HIGGS_N]
    Xte, yte = X[HIGGS_N:], y[HIGGS_N:]

    def _timed(params):
        # one-chunk warmup at the FULL training shape isolates XLA
        # compile from the measured train (jit caches are shape-keyed;
        # the explicit boost_chunk=8 compiles the SAME fused-chunk
        # program the 40-iteration measured run dispatches — a 1-iter
        # warmup would compile the length-1 chunk instead and leave the
        # measured wall paying the length-8 compile)
        train({**params, "num_iterations": 8, "boost_chunk": 8},
              Xtr, ytr)
        t0 = time.time()
        booster = train(params, Xtr, ytr)
        wall = time.time() - t0
        entry = {"wall_s": round(wall, 2),
                 "phases": booster.train_timing,
                 "bin_path": booster.train_info.get("bin_path"),
                 "boost_chunk": booster.train_info.get("boost_chunk")}
        return entry, booster

    out = {}
    auc = None
    for max_bin in (63, 255):
        params = {"objective": "binary", "num_iterations": 40,
                  "num_leaves": 63, "max_bin": max_bin,
                  "min_data_in_leaf": 50}
        out[max_bin], booster = _timed(params)
        if max_bin == 63:
            auc = roc_auc_score(yte, booster.predict(Xte))
            hist_method = booster.params["hist_method"]
            # host-binning comparison point: same config, ingest forced
            # to the host kernels (bin+ship delta = the device saving)
            out["host_bin_63"], _ = _timed(
                {**params, "device_binning": "off"})
    return out, auc, hist_method


AUTOML_N = 1_000_000
AUTOML_HASH_WIDTH = 64     # dense hashed block: 1M x 64 f32 = 256 MB
AUTOML_CANDIDATES = 8
AUTOML_TUNE_ROWS = 200_000  # CV sweep on a subsample (standard AutoML
#                             practice; featurization is the 1M headline)


def bench_automl() -> dict:
    """AutoML hot path: a 1M-row mixed numeric/string/token table runs
    Featurize (columnar kernels) against the RETAINED row-loop
    reference — both measured, outputs bit-compared — then a
    random-search tune of a linear model over the featurized table
    exercises the fold-cached, device-batched CV sweep. Reports walls,
    the vectorization speedup, the tune search path (vmap dispatches vs
    serial), and the automl phase-histogram breakdown."""
    from mmlspark_tpu.automl.featurize import Featurize
    from mmlspark_tpu.automl.tuning import (
        HyperparamBuilder, RandomSpace, RangeHyperParam,
        TuneHyperparameters,
    )
    from mmlspark_tpu.core import metrics as MCmod
    from mmlspark_tpu.core.table import DataTable
    from mmlspark_tpu.models.linear import TPULogisticRegression

    rng = np.random.default_rng(0)
    n = AUTOML_N
    x1 = rng.normal(size=n)
    x1[rng.random(n) < 0.01] = np.nan       # NaN-imputation path engaged
    x2 = rng.uniform(size=n)
    colors = [f"c{i:02d}" for i in range(12)]
    color = [colors[i] for i in rng.integers(0, 12, n)]
    words = [f"token{i:04d}" for i in range(2000)]
    lens = rng.integers(5, 13, n)
    tok_ids = rng.integers(0, len(words), int(lens.sum()))
    toks, pos = [], 0
    for ln in lens:
        toks.append([words[j] for j in tok_ids[pos:pos + ln]])
        pos += int(ln)
    label = ((np.nan_to_num(x1) + x2) > 0.5).astype(np.float64)
    table = DataTable({"x1": x1, "x2": x2, "color": color, "toks": toks,
                       "label": label})

    feat = Featurize(featureColumns=["x1", "x2", "color", "toks"],
                     numberOfFeatures=AUTOML_HASH_WIDTH)
    t0 = time.time()
    model = feat.fit(table)
    fit_s = time.time() - t0
    # warm both paths on a small slice (pyarrow's first conversion
    # lazily initializes ~1.5s of machinery; measure kernels, not init)
    warm = DataTable({c: table[c][:4096] for c in table.column_names})
    model.transform(warm)
    model.transform_rowloop(warm)
    # min of 2 reps per path: this shared host class swings 1.2-1.5x
    # run to run, and min-of-reps is the standard de-noising for both
    # sides of the ratio
    vec_s, out = 1e18, None
    for _ in range(2):
        t0 = time.time()
        out = model.transform(table)
        vec_s = min(vec_s, time.time() - t0)
    rowloop_s, ref = 1e18, None
    for _ in range(2):
        t0 = time.time()
        ref = model.transform_rowloop(table)
        rowloop_s = min(rowloop_s, time.time() - t0)
    bit_identical = bool(np.array_equal(out["features"],
                                        ref["features"]))
    del ref

    space = (HyperparamBuilder()
             .add_hyperparam("stepSize",
                             RangeHyperParam(0.05, 1.0, log=True))
             .add_hyperparam("regParam",
                             RangeHyperParam(1e-5, 1e-2, log=True))
             .build())
    tuner = TuneHyperparameters(
        models=[TPULogisticRegression(maxIter=20)],
        paramSpace=RandomSpace(space, seed=0),
        evaluationMetric="accuracy", numFolds=3,
        numRuns=AUTOML_CANDIDATES, seed=0)
    k = AUTOML_TUNE_ROWS
    tune_table = DataTable({"features": out["features"][:k],
                            "label": label[:k]})
    t0 = time.time()
    tuned = tuner.fit(tune_table)
    tune_s = time.time() - t0

    phases = {k: h.summary()
              for k, h in MCmod.automl_histograms().items()}
    return {
        "metric": "automl_featurize_1m_vectorization_speedup",
        "value": round(rowloop_s / vec_s, 1) if vec_s else None,
        "unit": "x (rowloop wall / columnar wall, same table)",
        "featurize_fit_s": round(fit_s, 2),
        "featurize_transform_s": round(vec_s, 2),
        "featurize_rowloop_s": round(rowloop_s, 2),
        "bit_identical": bit_identical,
        "tune_wall_s": round(tune_s, 2),
        "tune_search": tuned.search_info,
        "tune_best_metric": round(float(tuned.get("bestMetric")), 4),
        "phases": phases,
        "config": (f"{n} rows x (2 numeric + 12-level string + 5-12 "
                   f"token lists of 9-char words), hash width "
                   f"{AUTOML_HASH_WIDTH}, {AUTOML_CANDIDATES} logistic "
                   f"candidates x 3 folds on {k} rows"),
    }


PIPELINE_N = 1_000_000
PIPELINE_FIT_N = 100_000
PIPELINE_HASH_WIDTH = 32
# one-hot string block: the wide part. 128 levels is an ordinary
# categorical width, and it is exactly where stage-at-a-time hurts: the
# host path materializes the (N, 128) one-hot + the assembled + the
# scaled + the f64 copies, while the fused program ships a 4 MB i32
# code vector and keeps every wide intermediate an XLA buffer.
PIPELINE_LEVELS = 128


def bench_pipeline() -> dict:
    """Whole-pipeline fusion (core/fusion.py): 1M raw rows (numerics
    with NaN, a 128-level string, token lists) scored through
    Featurize -> StandardScaler -> logistic -> DropColumns(features),
    three ways:

    - **staged_host** — ``PipelineModel.transform``: the legacy
      stage-at-a-time path (host columnar featurize, f64 numpy model
      math, full intermediate materialization between stages);
    - **staged_device** — the SAME device kernels dispatched one stage
      at a time with a host round trip between every stage;
    - **fused** — one XLA program per device-capable run, host kernels
      (string codes / token hashing) feeding it directly, ONE D2H round
      trip. Measured COLD (fresh table identity: host feed kernels +
      H2D paid every rep) and WARM (device-resident DeviceTable:
      columns/feeds shipped once, repeats pay dispatch + fetch only).

    Parity is checked in-line: fused == staged_device bit-identical,
    predictions == staged_host exactly. Recompiles across reps and
    device round trips per transform are reported (the zero-retrace /
    one-round-trip acceptance evidence)."""
    from mmlspark_tpu.automl.featurize import Featurize
    from mmlspark_tpu.core import metrics as MCmod
    from mmlspark_tpu.core.table import DataTable
    from mmlspark_tpu.models.linear import TPULogisticRegression
    from mmlspark_tpu.core.stage import Pipeline
    from mmlspark_tpu.stages.basic import DropColumns
    from mmlspark_tpu.stages.dataprep import StandardScaler

    rng = np.random.default_rng(0)
    n = PIPELINE_N
    x1 = rng.normal(size=n)
    x1[rng.random(n) < 0.01] = np.nan
    x2 = rng.uniform(size=n)
    colors = [f"c{i:02d}" for i in range(PIPELINE_LEVELS)]
    color = [colors[i] for i in rng.integers(0, PIPELINE_LEVELS, n)]
    words = [f"tok{i:04d}" for i in range(800)]
    lens = rng.integers(3, 7, n)
    tok_ids = rng.integers(0, len(words), int(lens.sum()))
    toks, pos = [], 0
    for ln in lens:
        toks.append([words[j] for j in tok_ids[pos:pos + ln]])
        pos += int(ln)
    label = ((np.nan_to_num(x1) + x2) > 0.5).astype(np.float64)
    table = DataTable({"x1": x1, "x2": x2, "color": color,
                       "toks": toks, "label": label})

    t0 = time.time()
    pm = Pipeline(stages=[
        Featurize(featureColumns=["x1", "x2", "color", "toks"],
                  numberOfFeatures=PIPELINE_HASH_WIDTH,
                  oneHotEncodeCategoricals=True),
        StandardScaler(inputCol="features", outputCol="features"),
        TPULogisticRegression(featuresCol="features", labelCol="label",
                              maxIter=40),
        DropColumns(cols=["features"]),
    ]).fit(table.slice(0, PIPELINE_FIT_N))
    fit_s = time.time() - t0
    fused = pm.fused()

    # warm every path on a small slice: compiles + pyarrow lazy init
    # are measured nowhere below
    warm = table.slice(0, 4096)
    pm.transform(warm)
    fused.transform(warm)
    fused.transform_staged(warm)

    def fresh_view(t):
        # same column buffers, NEW table identity: the DeviceTable is
        # cold, so the rep pays host feed kernels + H2D like a fresh
        # batch of data would
        return DataTable({c: t.column(c) for c in t.column_names},
                         t.schema)

    # one untimed full-shape fused run: the 1M-row executable compiles
    # HERE, so the timed reps below prove zero steady-state recompiles
    fused.transform(fresh_view(table))

    def best(fn, reps=2):
        w, out = 1e18, None
        for _ in range(reps):
            t1 = time.time()
            out = fn()
            w = min(w, time.time() - t1)
        return w, out

    host_s, out_h = best(lambda: pm.transform(fresh_view(table)))
    staged_s, out_d = best(
        lambda: fused.transform_staged(fresh_view(table)))
    misses_before = fused.jit_cache_misses
    cold_s, out_f = best(lambda: fused.transform(fresh_view(table)))
    warm_s, _ = best(lambda: fused.transform(table), reps=3)
    recompiles = fused.jit_cache_misses - misses_before
    plan = fused.plan_for(table.schema)

    check_cols = ("rawPrediction", "probability", "prediction")
    bit_identical = all(
        np.array_equal(np.asarray(out_f[c]), np.asarray(out_d[c]))
        for c in check_cols)
    pred_equal_host = bool(np.array_equal(
        np.asarray(out_f["prediction"]), np.asarray(out_h["prediction"])))
    phases = {k: h.summary()
              for k, h in MCmod.pipeline_histograms().items()}
    return {
        "metric": "pipeline_fusion_speedup_vs_stage_at_a_time",
        "value": round(host_s / cold_s, 2) if cold_s else None,
        "unit": "x (legacy staged wall / fused COLD wall, same rows)",
        "warm_speedup": round(host_s / warm_s, 2) if warm_s else None,
        "staged_host_s": round(host_s, 2),
        "staged_device_s": round(staged_s, 2),
        "fused_cold_s": round(cold_s, 2),
        "fused_warm_s": round(warm_s, 2),
        "fit_s": round(fit_s, 2),
        "bit_identical_vs_staged_device": bit_identical,
        "prediction_equal_vs_staged_host": pred_equal_host,
        "steady_state_recompiles": recompiles,
        "device_roundtrips_per_transform": plan.last_roundtrips,
        "fusion_plan": plan.describe(),
        "phases": phases,
        "config": (f"{n} raw rows x (2 numeric w/ NaN + "
                   f"{PIPELINE_LEVELS}-level one-hot string + 3-6 token "
                   f"lists, hash {PIPELINE_HASH_WIDTH}) -> Featurize -> "
                   f"StandardScaler -> logistic(40 iters) -> "
                   f"drop(features); fit on {PIPELINE_FIT_N} rows"),
    }


SERVING_REQUESTS = 400
SERVING_CLIENTS = 16
SERVING_FEATURE_DIM = 128


# batching deadline: on a saturated small host, 6 ms collects 2-3x the
# rows of a 3 ms window and LOWERS p50 (fewer, fuller batches cost less
# total CPU per request); idle-path latency stays ~wait + service
SERVING_MAX_WAIT_MS = 6.0


def bench_serving() -> dict:
    """Model serving QPS + latency percentiles: a TPUModel (MLP scorer)
    behind a 2-engine ServingFleet, sprayed by concurrent clients — the
    reference's headline streaming/serving capability measured, not just
    proven correct (ref: DistributedHTTPSource.scala:96-266).

    The hot path under test: adaptive micro-batching (flush on
    batch-full OR 3 ms deadline), shape-bucketed pre-compiled
    executables (explicit warmup, zero steady-state recompiles), and
    the batcher-thread decode/pad stage overlapping device execution.
    Reports the per-stage latency breakdown from the engines' own
    histograms plus the steady-state recompile count."""
    import concurrent.futures

    from mmlspark_tpu.models.networks import build_network
    from mmlspark_tpu.models.tpu_model import TPUModel
    from mmlspark_tpu.serving.fleet import ServingFleet, json_scoring_pipeline

    import jax

    module = build_network({"type": "mlp", "features": [256, 128],
                            "num_classes": 10})
    rng = np.random.default_rng(0)
    x0 = np.zeros((1, SERVING_FEATURE_DIM), np.float32)
    weights = {"params": module.init(
        jax.random.PRNGKey(0), x0)["params"]}
    model = TPUModel(modelFn=lambda w, ins: module.apply(
        {"params": w["params"]}, list(ins.values())[0]),
        weights=weights, inputCol="features", outputCol="scores",
        batchSize=256, computeDtype="float32")

    # explicit warmup: every shape bucket compiles BEFORE the fleet
    # takes traffic, so no live request pays an XLA compile
    model.warmup({"features": x0})

    fleet = ServingFleet(json_scoring_pipeline(model), n_engines=2,
                         base_port=18800, batch_size=256, workers=2,
                         max_wait_ms=SERVING_MAX_WAIT_MS)
    # encode ONCE: a 128-float json.dumps per request would bill ~0.5 ms
    # of client-side CPU to the serving number on a small host
    payload = json.dumps(
        {"features": rng.normal(size=SERVING_FEATURE_DIM).tolist()}
    ).encode()

    def post(_i):
        t0 = time.perf_counter()
        body = fleet.post(payload, timeout=60)   # round-robin client
        assert "prediction" in body, body
        return (time.perf_counter() - t0) * 1e3

    try:
        for _ in fleet.addresses:            # warmup: first live batches
            post(0)
        misses_before = model.jit_cache_misses
        lat = []
        t0 = time.perf_counter()
        with concurrent.futures.ThreadPoolExecutor(SERVING_CLIENTS) as ex:
            futs = [ex.submit(post, i) for i in range(SERVING_REQUESTS)]
            for f in concurrent.futures.as_completed(futs):
                lat.append(f.result())
        wall = time.perf_counter() - t0
        recompiles = model.jit_cache_misses - misses_before
        agg = fleet.metrics()["aggregate"]
    finally:
        fleet.stop_all()
    lat = np.asarray(lat)

    def _p50(name):
        return agg.get(name, {}).get("p50", None)

    stage = agg.get("pipeline_stage", {})
    return {
        "metric": "serving_fleet_qps",
        "value": round(SERVING_REQUESTS / wall, 1),
        "unit": "requests/sec",
        "p50_ms": round(float(np.percentile(lat, 50)), 1),
        "p99_ms": round(float(np.percentile(lat, 99)), 1),
        "steady_state_recompiles": recompiles,
        "buckets": model.bucket_sizes(),
        "breakdown_p50_ms": {
            "queue_wait": _p50("queue_wait_ms"),
            "decode": _p50("decode_ms"),
            "pad": stage.get("pad_ms", {}).get("p50", None),
            "device": stage.get("device_ms", {}).get("p50", None),
            "pipeline": _p50("pipeline_ms"),
            "respond": _p50("respond_ms"),
            "batch_rows": _p50("batch_rows"),
        },
        "config": (f"{SERVING_REQUESTS} reqs, {SERVING_CLIENTS} clients, "
                   f"2 engines x 2 workers, MLP-{SERVING_FEATURE_DIM} "
                   f"TPUModel, batch 256, max_wait "
                   f"{SERVING_MAX_WAIT_MS} ms"),
    }


INGRESS_ROWS = 1_000_000
INGRESS_DIM = 16
INGRESS_CHUNK = 1024
INGRESS_SERVE_ROWS = 16_384
INGRESS_ROWS_PER_REQ = 64


def bench_ingress() -> dict:
    """Columnar ingress vs the JSON oracle (io/columnar.py) — the
    wire-to-device zero-copy scenario.

    Two measurements, both on THIS container (backend-labeled):

    1. **Codec microbench, 1M rows**: the server-side host work
       (decode + batch assembly) of 1M feature rows arriving as
       1024-row requests, per codec — JSON rows (the oracle's
       ``json.loads`` + stack), msgpack-columns (zero-copy
       ``np.frombuffer`` views), Arrow IPC. Pure ingress cost, no
       model, no HTTP.

    2. **Single-replica serving**: the same TPUModel MLP behind ONE
       engine, sprayed by concurrent clients — JSON one-row requests
       (the pre-existing protocol) vs msgpack-columns 64-row record
       batches (the columnar client, ``fleet.post_columns``). Reports
       rows/sec both ways, the speedup, the ingress phase breakdown
       (negotiate/decode/assemble/pad p50s from /metrics), the host
       fraction of request p50, and the steady-state recompile count
       on the columnar path."""
    import concurrent.futures

    from mmlspark_tpu.core.metrics import (
        ingress_decode_histograms, ingress_histograms,
    )
    from mmlspark_tpu.io import columnar as CIN
    from mmlspark_tpu.models.networks import build_network
    from mmlspark_tpu.models.tpu_model import TPUModel
    from mmlspark_tpu.serving.fleet import (
        ServingFleet, json_scoring_pipeline,
    )

    import jax

    rng = np.random.default_rng(7)

    # -- 1. codec microbench at 1M rows ---------------------------------
    n_chunks = INGRESS_ROWS // INGRESS_CHUNK
    n_rows = n_chunks * INGRESS_CHUNK     # whole requests only
    feats = rng.normal(size=(n_rows, INGRESS_DIM))
    chunks = [feats[i * INGRESS_CHUNK:(i + 1) * INGRESS_CHUNK]
              for i in range(n_chunks)]

    def decode_json(bodies):
        # the oracle's decode: one row object per request
        return np.stack([
            np.asarray(json.loads(b.decode())["features"],
                       dtype=np.float32)
            for b in bodies])

    def decode_columnar(codec, bodies):
        return np.concatenate([
            np.asarray(CIN.decode_columnar(codec, b)
                       .columns["features"], dtype=np.float32)
            for b in bodies])

    codec_results = {}
    json_bodies = [json.dumps({"features": row.tolist()}).encode()
                   for row in feats[:INGRESS_CHUNK]]  # 1 chunk as rows
    t0 = time.perf_counter()
    ref = decode_json(json_bodies)
    json_row_wall = (time.perf_counter() - t0) * n_chunks  # scaled to 1M
    codec_results["json_rows"] = {
        "decode_assemble_s": round(json_row_wall, 2),
        "rows_per_s": round(n_rows / json_row_wall),
        "note": f"measured on {INGRESS_CHUNK} rows, scaled x{n_chunks}",
    }
    codecs = ["msgpack"] + (["arrow"] if CIN._pyarrow() else [])
    for codec in codecs:
        bodies = [CIN.encode_columns({"features": c}, codec=codec)[0]
                  for c in chunks]
        t0 = time.perf_counter()
        out = decode_columnar(codec, bodies)
        wall = time.perf_counter() - t0
        assert out.shape == (n_rows, INGRESS_DIM)
        np.testing.assert_array_equal(
            out[:INGRESS_CHUNK], ref)   # bit parity with the oracle
        codec_results[codec] = {
            "decode_assemble_s": round(wall, 3),
            "rows_per_s": round(n_rows / wall),
            "speedup_vs_json": round(json_row_wall / wall, 1),
        }
    del feats, chunks

    # -- 2. single-replica serving, JSON rows vs columnar batches -------
    module = build_network({"type": "mlp", "features": [256, 128],
                            "num_classes": 10})
    x0 = np.zeros((1, SERVING_FEATURE_DIM), np.float32)
    weights = {"params": module.init(
        jax.random.PRNGKey(0), x0)["params"]}
    model = TPUModel(modelFn=lambda w, ins: module.apply(
        {"params": w["params"]}, list(ins.values())[0]),
        weights=weights, inputCol="features", outputCol="scores",
        batchSize=256, computeDtype="float32")
    model.warmup({"features": x0})
    fleet = ServingFleet(json_scoring_pipeline(model), n_engines=1,
                         base_port=19000, batch_size=256, workers=2,
                         max_wait_ms=SERVING_MAX_WAIT_MS)
    x = rng.normal(size=(INGRESS_ROWS_PER_REQ, SERVING_FEATURE_DIM))
    json_payload = json.dumps(
        {"features": x[0].tolist()}).encode()
    col_payload, col_ct = CIN.encode_columns({"features": x})

    def run_side(post_one, n_requests, rows_per_req):
        lat = []

        def post(_i):
            t0 = time.perf_counter()
            body = post_one()
            assert "prediction" in body, body
            return (time.perf_counter() - t0) * 1e3

        for _ in range(4):
            post(0)     # warm the live path
        t0 = time.perf_counter()
        with concurrent.futures.ThreadPoolExecutor(SERVING_CLIENTS) as ex:
            futs = [ex.submit(post, i) for i in range(n_requests)]
            for f in concurrent.futures.as_completed(futs):
                lat.append(f.result())
        wall = time.perf_counter() - t0
        lat = np.asarray(lat)
        return {
            "rows_per_s": round(n_requests * rows_per_req / wall, 1),
            "qps": round(n_requests / wall, 1),
            "p50_ms": round(float(np.percentile(lat, 50)), 2),
            "p99_ms": round(float(np.percentile(lat, 99)), 2),
        }

    def _p50(hist):
        return round(hist.summary().get("p50", 0.0), 4)

    try:
        json_side = run_side(
            lambda: fleet.post(json_payload, timeout=60),
            SERVING_REQUESTS, 1)
        misses_before = model.jit_cache_misses
        # the phase histograms are process-wide: RESET between sides
        # so the columnar host-fraction is measured on the columnar
        # workload alone, not diluted by the JSON side's samples
        for h in ingress_histograms().values():
            h.reset()
        for h in ingress_decode_histograms().values():
            h.reset()
        model._hists["pad_ms"].reset()
        # pre-encoded payload, like the JSON side: the server-side
        # ingress is under test, not client-side encode CPU
        col_side = run_side(
            lambda: fleet.post(col_payload, timeout=60,
                               content_type=col_ct),
            INGRESS_SERVE_ROWS // INGRESS_ROWS_PER_REQ,
            INGRESS_ROWS_PER_REQ)
        recompiles = model.jit_cache_misses - misses_before
        ih = ingress_histograms()
        dh = ingress_decode_histograms()
        phases = {
            "negotiate": _p50(ih["negotiate"]),
            "assemble": _p50(ih["assemble"]),
            "decode": {c: _p50(h) for c, h in dh.items()},
        }
        stage = fleet.metrics()["aggregate"].get("pipeline_stage", {})
        pad_p50 = stage.get("pad_ms", {}).get("p50", 0.0) or 0.0
        phases["pad"] = round(pad_p50, 4)
        host_ms = (phases["negotiate"] + phases["assemble"]
                   + phases["decode"].get("msgpack", 0.0) + pad_p50)
        host_fraction = (host_ms / col_side["p50_ms"]
                         if col_side["p50_ms"] else 0.0)
    finally:
        fleet.stop_all()

    return {
        "metric": "columnar_ingress_rows_per_s",
        "value": col_side["rows_per_s"],
        "unit": "rows/sec (single replica, msgpack-columns, "
                f"{INGRESS_ROWS_PER_REQ}-row requests)",
        "codec_1m_rows": codec_results,
        "serving_json_rows": json_side,
        "serving_columnar": col_side,
        "serving_speedup_rows_per_s": round(
            col_side["rows_per_s"] / json_side["rows_per_s"], 2),
        "ingress_phase_p50_ms": phases,
        "host_fraction_of_p50": round(host_fraction, 4),
        "steady_state_recompiles": recompiles,
        "config": (f"codec bench {INGRESS_ROWS} rows x {INGRESS_DIM} f64"
                   f" in {INGRESS_CHUNK}-row requests; serving 1 engine"
                   f" x 2 workers, MLP-{SERVING_FEATURE_DIM}, "
                   f"{SERVING_REQUESTS} JSON 1-row reqs vs "
                   f"{INGRESS_SERVE_ROWS // INGRESS_ROWS_PER_REQ} "
                   f"msgpack {INGRESS_ROWS_PER_REQ}-row reqs, "
                   f"{SERVING_CLIENTS} clients"),
    }


OBS_REQUESTS = 400
OBS_REPS = 2


def bench_observability() -> dict:
    """Telemetry overhead on the serving hot path, three interleaved
    modes (best-of per mode so shared-host noise hits every side):

    - ``off``   — tracing, SLO engine, and flight recorder all off
      (the bare PR 2 hot path);
    - ``tracing`` — request tracing only (the PR 7 contract);
    - ``telemetry`` — the FULL default-on plane: tracing + windowed
      SLO recording/burn-rate evaluation + the always-on flight
      recorder (the PR 13 contract: ≤3% vs off, pinned by
      tests/test_perf_floors.py::TestTelemetryOverheadFloor alongside
      the tracing floor).

    Reports qps per mode, both overhead percentages, buffer/SLO/
    recorder state from the telemetry run, one exported trace's span
    coverage, and the /metrics exposition size."""
    import concurrent.futures

    from mmlspark_tpu.core.flightrecorder import FlightRecorder
    from mmlspark_tpu.core.trace import Tracer, to_chrome_trace
    from mmlspark_tpu.models.networks import build_network
    from mmlspark_tpu.models.tpu_model import TPUModel
    from mmlspark_tpu.serving.fleet import ServingFleet, json_scoring_pipeline

    import jax

    module = build_network({"type": "mlp", "features": [256, 128],
                            "num_classes": 10})
    rng = np.random.default_rng(0)
    x0 = np.zeros((1, SERVING_FEATURE_DIM), np.float32)
    weights = {"params": module.init(
        jax.random.PRNGKey(0), x0)["params"]}
    model = TPUModel(modelFn=lambda w, ins: module.apply(
        {"params": w["params"]}, list(ins.values())[0]),
        weights=weights, inputCol="features", outputCol="scores",
        batchSize=256, computeDtype="float32")
    model.warmup({"features": x0})
    payload = json.dumps(
        {"features": rng.normal(size=SERVING_FEATURE_DIM).tolist()}
    ).encode()

    def run_once(mode: str, base_port: int):
        tracing = mode in ("tracing", "telemetry")
        telemetry = mode == "telemetry"
        tracer = Tracer(enabled=True) if tracing else None
        recorder = FlightRecorder() if telemetry else False
        fleet = ServingFleet(json_scoring_pipeline(model), n_engines=2,
                             base_port=base_port, batch_size=256,
                             workers=2,
                             max_wait_ms=SERVING_MAX_WAIT_MS,
                             tracer=tracer, tracing=tracing,
                             slo=None if telemetry else False,
                             flight_recorder=recorder)
        try:
            def post(_i):
                body = fleet.post(payload, timeout=60)
                assert "prediction" in body, body
            for _ in fleet.addresses:
                post(0)
            t0 = time.perf_counter()
            with concurrent.futures.ThreadPoolExecutor(
                    SERVING_CLIENTS) as ex:
                list(ex.map(post, range(OBS_REQUESTS)))
            wall = time.perf_counter() - t0
            extras = {}
            if tracing:
                extras["buffer"] = tracer.buffer.stats()
                traces = [t for t in tracer.buffer.traces()
                          if t.root.name == "request"
                          and t.root.end is not None]
                if traces:
                    tr = traces[-1]
                    child = [s for s in tr.spans()
                             if s is not tr.root and s.end is not None]
                    extras["sample_trace"] = {
                        "trace_id": tr.trace_id,
                        "wall_ms": round(tr.duration_ms, 3),
                        "spans": {s.name: round(s.duration_ms, 3)
                                  for s in child},
                        "span_coverage": round(
                            sum(s.duration_ms for s in child)
                            / max(tr.duration_ms, 1e-9), 3),
                        "chrome_events": len(to_chrome_trace(
                            [tr])["traceEvents"]),
                    }
                extras["metrics_exposition_lines"] = len(
                    fleet.metrics_text().splitlines())
            if telemetry:
                slo = fleet.engines[0].slo
                status = slo.status()
                extras["slo"] = {
                    "degraded": status["degraded"],
                    "error_rate_1m": status.get("error_rate_1m"),
                    "p99_ms_1m": status.get("p99_ms_1m"),
                    "requests_1m": status.get("requests_1m"),
                }
                extras["flight_recorder"] = recorder.stats()
                bundle = recorder.dump_bundle("bench")
                extras["bundle_trace_events"] = len(
                    bundle["traces"].get("traceEvents", []))
        finally:
            fleet.stop_all()
            if telemetry:
                recorder.close()
        return OBS_REQUESTS / wall, extras

    qps = {"off": 0.0, "tracing": 0.0, "telemetry": 0.0}
    extras_best: dict = {}
    port = 19000
    for _ in range(OBS_REPS):     # interleaved: noise hits every mode
        for mode in ("off", "tracing", "telemetry"):
            q, extras = run_once(mode, port)
            port += 40
            if q > qps[mode]:
                qps[mode] = q
                if mode == "telemetry":
                    extras_best = extras

    def pct(off, on):
        return round((off - on) / off * 100, 2) if off else None

    return {
        "metric": "serving_telemetry_overhead",
        "value": pct(qps["off"], qps["telemetry"]),
        "unit": "% qps lost with FULL telemetry on (tracing + "
                "windowed SLO + flight recorder; best-of interleaved "
                "reps)",
        "qps_tracing_off": round(qps["off"], 1),
        "qps_tracing_on": round(qps["tracing"], 1),
        "qps_telemetry_on": round(qps["telemetry"], 1),
        "tracing_overhead_pct": pct(qps["off"], qps["tracing"]),
        "telemetry_overhead_pct": pct(qps["off"], qps["telemetry"]),
        **extras_best,
        "config": (f"{OBS_REQUESTS} reqs x {OBS_REPS} reps per mode, "
                   f"{SERVING_CLIENTS} clients, 2 engines x 2 workers, "
                   f"MLP-{SERVING_FEATURE_DIM}, batch 256"),
    }


SWAP_REQUESTS = 600
SWAP_CLIENTS = 12


def bench_swap() -> dict:
    """Zero-downtime model lifecycle under steady load: a 2-engine
    fleet serving an MLP scorer takes one ROLLING SWAP to a refreshed
    model mid-run (warmup-before-cutover, canary, drain — see
    serving/lifecycle.py). Reports availability across the run, p99
    both overall and DURING the swap window, and the recompile count
    outside the two models' warmups (the zero-steady-state-recompiles
    contract must hold straight through a swap)."""
    import concurrent.futures
    import threading

    from mmlspark_tpu.models.networks import build_network
    from mmlspark_tpu.models.tpu_model import TPUModel
    from mmlspark_tpu.serving.fleet import ServingFleet, json_scoring_pipeline
    from mmlspark_tpu.serving.lifecycle import CanaryPolicy

    import jax

    module = build_network({"type": "mlp", "features": [256, 128],
                            "num_classes": 10})
    rng = np.random.default_rng(0)
    x0 = np.zeros((1, SERVING_FEATURE_DIM), np.float32)

    def make_model(seed):
        weights = {"params": module.init(
            jax.random.PRNGKey(seed), x0)["params"]}
        return TPUModel(modelFn=lambda w, ins: module.apply(
            {"params": w["params"]}, list(ins.values())[0]),
            weights=weights, inputCol="features", outputCol="scores",
            batchSize=256, computeDtype="float32")

    m1, m2 = make_model(0), make_model(1)
    m1.warmup({"features": x0})     # v1 pre-compiled before traffic
    fleet = ServingFleet(json_scoring_pipeline(m1), n_engines=2,
                         base_port=18900, batch_size=256, workers=2,
                         max_wait_ms=SERVING_MAX_WAIT_MS)
    payload = json.dumps(
        {"features": rng.normal(size=SERVING_FEATURE_DIM).tolist()}
    ).encode()
    swap_window = {}
    failures = [0]
    fail_lock = threading.Lock()

    def post(_i):
        t0 = time.perf_counter()
        try:
            body = fleet.post(payload, timeout=60)
            assert "prediction" in body, body
        except Exception:  # noqa: BLE001 — availability metric
            with fail_lock:
                failures[0] += 1
            return None
        return (t0, (time.perf_counter() - t0) * 1e3)

    try:
        for _ in fleet.addresses:
            post(0)
        failures[0] = 0   # priming posts don't count against the
        #                   measured window's availability
        misses_before = m1.jit_cache_misses + m2.jit_cache_misses
        lat = []
        t0 = time.perf_counter()
        with concurrent.futures.ThreadPoolExecutor(SWAP_CLIENTS) as ex:
            futs = [ex.submit(post, i) for i in range(SWAP_REQUESTS)]
            time.sleep(0.3)          # steady load established
            swap_t0 = time.perf_counter()
            report = fleet.rolling_swap(
                json_scoring_pipeline(m2), "v2",
                warmup_example={"features": x0},
                policy=CanaryPolicy(fraction=0.25, min_batches=4,
                                    decision_timeout_s=30))
            swap_t1 = time.perf_counter()
            for f in concurrent.futures.as_completed(futs):
                if f.result() is not None:
                    lat.append(f.result())
        wall = time.perf_counter() - t0
        # m2's warmup compiles are part of the SWAP (off the hot path);
        # subtract them via the model's own warmup-time counter delta
        recompiles = (m1.jit_cache_misses + m2.jit_cache_misses
                      - misses_before)
        warm_compiles = len(m2.bucket_sizes())
        swap_window.update(report)
    finally:
        fleet.stop_all()
    all_ms = np.asarray([ms for _, ms in lat])
    during = np.asarray([ms for t, ms in lat
                         if swap_t0 <= t <= swap_t1]) \
        if len(lat) else np.asarray([])
    total = SWAP_REQUESTS
    return {
        "metric": "serving_rolling_swap",
        "availability": round((total - failures[0]) / total, 4),
        "qps": round(total / wall, 1),
        "p99_ms": round(float(np.percentile(all_ms, 99)), 1)
        if len(all_ms) else None,
        "p99_during_swap_ms": round(float(np.percentile(during, 99)), 1)
        if len(during) else None,
        "swap_wall_s": round(swap_t1 - swap_t0, 2),
        "swap_report": {"ok": swap_window.get("ok"),
                        "completed": swap_window.get("completed"),
                        "rolled_back": swap_window.get("rolled_back")},
        "recompiles_total": recompiles,
        "recompiles_beyond_new_model_warmup": recompiles - warm_compiles,
        "config": (f"{SWAP_REQUESTS} reqs, {SWAP_CLIENTS} clients, "
                   f"2 engines, rolling swap mid-run, canary 25% / "
                   f"4 batches, MLP-{SERVING_FEATURE_DIM}"),
    }


QUANT_ROWS = 200_000
QUANT_DIM = 128


def bench_quant() -> dict:
    """Int8 post-training quantization (core/quantize.py): batch
    scoring throughput f32 vs int8 on (a) the serving-bench MLP
    TPUModel and (b) a fused StandardScaler->logistic pipeline, plus
    the accuracy cost (top-1 agreement, probability max-abs-err).

    HONESTY NOTE: the int8 win is an MXU-class claim — integer matmul
    doubles effective per-chip batch throughput where the hardware has
    an int8 systolic path. This container's CPU backend has no integer
    matmul advantage (XLA's CPU int8 dot is often SLOWER than its
    oneDNN f32 gemm), so the JSON records the measured ratio with the
    backend labeled instead of asserting a win the hardware can't
    show; the accuracy floors are backend-independent and pinned in
    tests/test_quantize.py."""
    import jax

    from mmlspark_tpu.core.stage import Pipeline
    from mmlspark_tpu.core.table import DataTable
    from mmlspark_tpu.models.linear import TPULogisticRegression
    from mmlspark_tpu.models.networks import build_network
    from mmlspark_tpu.models.tpu_model import TPUModel
    from mmlspark_tpu.stages.dataprep import StandardScaler

    rng = np.random.default_rng(0)
    n = QUANT_ROWS

    # (a) MLP TPUModel — the serving-bench scorer shape
    module = build_network({"type": "mlp", "features": [256, 128],
                            "num_classes": 10})
    x0 = np.zeros((1, QUANT_DIM), np.float32)
    model = TPUModel.from_flax(
        module, module.init(jax.random.PRNGKey(0), x0),
        inputCol="features", outputCol="scores", batchSize=1024)
    X = rng.normal(size=(n, QUANT_DIM)).astype(np.float32)
    calib = X[:2048]
    qmodel = model.quantize({"features": calib})
    table = DataTable({"features": X})

    def best(fn, reps=3):
        w, out = 1e18, None
        for _ in range(reps):
            t0 = time.time()
            out = fn()
            w = min(w, time.time() - t0)
        return w, out

    model.transform(DataTable({"features": X[:4096]}))   # warm compiles
    qmodel.transform(DataTable({"features": X[:4096]}))
    f32_s, out_f = best(lambda: model.transform(table))
    int8_s, out_q = best(lambda: qmodel.transform(table))
    sf = np.asarray(out_f["scores"])
    sq = np.asarray(out_q["scores"])
    mlp_agree = float((sf.argmax(-1) == sq.argmax(-1)).mean())

    # (b) fused pipeline — scaler + logistic, the PR 9 serving shape
    y = (X[:, 0] - 0.5 * X[:, 3] > 0).astype(np.float64)
    pt = DataTable({"features": X, "label": y})
    pm = Pipeline(stages=[
        StandardScaler(inputCol="features", outputCol="features"),
        TPULogisticRegression(featuresCol="features", labelCol="label",
                              maxIter=40),
    ]).fit(pt.slice(0, 50_000))
    fused = pm.fused(batch_size=1024)
    qfused = fused.quantize(pt.slice(0, 2048))
    fused.transform(pt.slice(0, 4096))
    qfused.transform(pt.slice(0, 4096))
    pf32_s, pout_f = best(lambda: fused.transform(pt))
    pint8_s, pout_q = best(lambda: qfused.transform(pt))
    pipe_agree = float(
        (np.asarray(pout_f["prediction"])
         == np.asarray(pout_q["prediction"])).mean())
    prob_err = float(np.abs(np.asarray(pout_f["probability"])
                            - np.asarray(pout_q["probability"])).max())

    return {
        "metric": "int8_vs_f32_batch_scoring",
        "value": round(f32_s / int8_s, 3) if int8_s else None,
        "unit": "x (f32 wall / int8 wall, MLP TPUModel; >1 = int8 "
                "faster — only expected where the backend has an "
                "integer matmul advantage)",
        "backend": jax.default_backend(),
        "mlp_f32_s": round(f32_s, 3),
        "mlp_int8_s": round(int8_s, 3),
        "mlp_top1_agreement": round(mlp_agree, 5),
        "pipeline_f32_s": round(pf32_s, 3),
        "pipeline_int8_s": round(pint8_s, 3),
        "pipeline_int8_speedup": round(pf32_s / pint8_s, 3)
        if pint8_s else None,
        "pipeline_pred_agreement": round(pipe_agree, 5),
        "pipeline_prob_max_abs_err": round(prob_err, 5),
        "config": (f"{n} rows x {QUANT_DIM} feats; MLP-256/128 "
                   f"TPUModel + fused scaler->logistic(40); "
                   f"per-channel weight scales, per-tensor activation "
                   f"clip on 2048 calib rows, int8xint8->i32 dot + "
                   f"f32 dequant epilogue"),
    }


# the cold-start subject: a compile-bound transformer classifier — the
# model class where trace-at-startup actually hurts (a small MLP's
# compile is noise next to the interpreter+jax import both modes pay)
COLDSTART_SPEC = {"type": "transformer", "vocab_size": 2000, "dim": 128,
                  "depth": 4, "heads": 4, "max_len": 64,
                  "num_classes": 8}
COLDSTART_REPS = 2


def bench_coldstart() -> dict:
    """Replica cold-start (serving/aot.py): export one AOT artifact,
    then start FRESH serving-replica processes in both modes —
    ``trace`` (rebuild model, per-bucket trace+compile warmup: today's
    replica) and ``aot`` (deserialize pre-compiled executables, XLA
    cache seeded at export) — measuring process start -> first HTTP
    200 (``cold_start_to_first_200_ms``). Also proves the AOT replica
    never traces: jit_traces_total == 0 through load, warmup, and the
    request. Floor-pinned >= 3x in tests/test_perf_floors.py."""
    import subprocess
    import sys
    import tempfile

    import jax

    from mmlspark_tpu.models.networks import build_network
    from mmlspark_tpu.models.tpu_model import TPUModel
    from mmlspark_tpu.serving import aot

    module = build_network(dict(COLDSTART_SPEC))
    x0 = np.zeros((1, COLDSTART_SPEC["max_len"]), np.int32)
    model = TPUModel.from_flax(
        module, module.init(jax.random.PRNGKey(0), x0),
        inputCol="features", outputCol="scores", batchSize=64)
    art = tempfile.mkdtemp(prefix="mmlspark_aot_bench_")
    t0 = time.time()
    manifest = aot.export_model(model, {"features": x0}, art,
                                version="bench-v1")
    export_s = time.time() - t0

    def run(mode: str, port: int) -> dict:
        proc = subprocess.run(
            [sys.executable, "-m", "mmlspark_tpu.serving.aot", art,
             "--mode", mode, "--port", str(port)],
            capture_output=True, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            timeout=600)
        if proc.returncode != 0:
            raise RuntimeError(f"coldstart runner failed: "
                               f"{proc.stderr[-2000:]}")
        return json.loads(proc.stdout.strip().splitlines()[-1])

    best = {"trace": None, "aot": None}
    port = 19940
    for _ in range(COLDSTART_REPS):   # interleaved: noise hits both
        for mode in ("trace", "aot"):
            r = run(mode, port)
            port += 3
            if (best[mode] is None
                    or r["cold_start_to_first_200_ms"]
                    < best[mode]["cold_start_to_first_200_ms"]):
                best[mode] = r
    trace_ms = best["trace"]["cold_start_to_first_200_ms"]
    aot_ms = best["aot"]["cold_start_to_first_200_ms"]
    return {
        "metric": "cold_start_to_first_200_ms",
        "value": round(trace_ms / aot_ms, 2) if aot_ms else None,
        "unit": "x (trace-at-startup / AOT-loaded, fresh replica "
                "processes, best-of-interleaved reps)",
        "trace_ms": trace_ms,
        "aot_ms": aot_ms,
        "trace_detail": best["trace"],
        "aot_detail": best["aot"],
        "aot_zero_traces": best["aot"]["jit_traces_total"] == 0,
        "artifact_format": manifest["format"],
        "export_wall_s": round(export_s, 2),
        "backend": jax.default_backend(),
        "config": (f"transformer dim {COLDSTART_SPEC['dim']} depth "
                   f"{COLDSTART_SPEC['depth']} seq "
                   f"{COLDSTART_SPEC['max_len']}, "
                   f"{len(manifest['buckets'])} buckets, "
                   f"{COLDSTART_REPS} reps/mode"),
    }


ZOO_MODELS = 256
ZOO_MAX_RESIDENT = 32
ZOO_REQUESTS = 2000
ZOO_CLIENTS = 16


def bench_zoo() -> dict:
    """The multi-model serving plane (serving/zoo.py): ZOO_MODELS
    distinct versioned models behind one 2-engine fleet, mixed-tenant
    load over a skewed model distribution with only ZOO_MAX_RESIDENT
    resident at once — so the run measures p99 UNDER CHURN (activations
    and LRU evictions happening mid-traffic), availability, and the
    cold-model activation wall through the AOT load path (export one
    real artifact, activate it cold, report the audit event's ms)."""
    import concurrent.futures
    import tempfile
    import threading
    import urllib.error

    import jax

    from mmlspark_tpu.core.table import DataTable
    from mmlspark_tpu.models.networks import build_network
    from mmlspark_tpu.models.tpu_model import TPUModel
    from mmlspark_tpu.serving import (
        AdmissionController, ModelZoo, ServingFleet,
        ServingUnavailable, aot,
    )
    from mmlspark_tpu.stages.basic import Lambda

    rng = np.random.default_rng(0)

    def scoring_stage(tag, w):
        # a real (host numpy) per-model compute so batches cost
        # something; distinct weights per model
        def handle(table):
            feats = np.asarray(
                [json.loads(r["entity"].decode())["features"]
                 for r in table["request"]], np.float32)
            scores = feats @ w
            return table.with_column("reply", [
                {"model": tag, "prediction": int(s.argmax())}
                for s in scores])
        return Lambda.apply(handle)

    zoo = ModelZoo(max_resident=ZOO_MAX_RESIDENT, memory_probe=None)
    dim, classes = 16, 8
    for i in range(ZOO_MODELS):
        w = rng.normal(size=(dim, classes)).astype(np.float32)
        zoo.register_factory(
            f"m{i:03d}", f"v{i % 8}",
            (lambda i=i, w=w: scoring_stage(f"m{i:03d}", w)),
            metadata={"cost_bytes": int(w.nbytes)})

    # ONE real AOT artifact: the cold-activation-in-hundreds-of-ms
    # claim is measured on the genuine load path, not a factory
    module = build_network({"type": "mlp", "features": [64, 32],
                            "num_classes": classes})
    x0 = np.zeros((1, dim), np.float32)
    tpu_model = TPUModel.from_flax(
        module, module.init(jax.random.PRNGKey(0), x0),
        inputCol="features", outputCol="scores", batchSize=64)
    art = tempfile.mkdtemp(prefix="mmlspark_zoo_bench_")
    aot.export_model(tpu_model, {"features": x0}, art, version="v1")
    zoo.register_artifact("aot_scorer", "v1", art)

    admission = AdmissionController()   # default tiers, no quotas
    fleet = ServingFleet(n_engines=2, base_port=19860, batch_size=64,
                         workers=2, max_wait_ms=3.0, zoo=zoo,
                         admission=admission, tracing=False)
    # skewed popularity (zipf-ish): a hot head keeps the cache busy
    # while a long tail forces continuous activations + evictions
    ranks = np.arange(1, ZOO_MODELS + 1, dtype=np.float64)
    probs = (1.0 / ranks ** 1.1)
    probs /= probs.sum()
    picks = rng.choice(ZOO_MODELS, size=ZOO_REQUESTS, p=probs)
    payload = json.dumps(
        {"features": rng.normal(size=dim).tolist()}).encode()
    lock = threading.Lock()
    lat, failures = [], []

    def post(i):
        model = f"m{picks[i]:03d}"
        tenant = f"t{i % 4}"
        t0 = time.perf_counter()
        try:
            body = fleet.post(payload, model=model, tenant=tenant,
                              timeout=120)
            assert body["model"] == model, (model, body)   # no mixing
            ok = True
        except urllib.error.HTTPError as e:
            with lock:
                failures.append(e.code)
            ok = False
        except ServingUnavailable:
            # fleet-level unavailability (both circuits open) is a
            # FAILED request in the availability metric, not a
            # crashed bench
            with lock:
                failures.append(503)
            ok = False
        dt = (time.perf_counter() - t0) * 1e3
        with lock:
            lat.append(dt)
        return ok

    try:
        # cold AOT activation measured through live HTTP: first
        # request to the never-loaded artifact model
        t0 = time.perf_counter()
        body = fleet.post(payload, model="aot_scorer", timeout=300)
        aot_first_request_ms = (time.perf_counter() - t0) * 1e3
        assert "prediction" in body
        activate_ev = [e for e in zoo.events if e.kind == "activate"
                       and e.model == "aot_scorer"][0]
        t0 = time.perf_counter()
        with concurrent.futures.ThreadPoolExecutor(ZOO_CLIENTS) as ex:
            results = list(ex.map(post, range(ZOO_REQUESTS)))
        wall = time.perf_counter() - t0
        stats = zoo.stats()
        distinct_served = len({f"m{p:03d}" for p in picks})
    finally:
        fleet.stop_all()
        zoo.close()
    lat_arr = np.asarray(sorted(lat))
    availability = sum(results) / len(results)
    return {
        "metric": "zoo_p99_ms_under_churn",
        "value": round(float(np.percentile(lat_arr, 99)), 1),
        "unit": "ms",
        "models_registered": ZOO_MODELS + 1,
        "distinct_models_requested": distinct_served,
        "max_resident": ZOO_MAX_RESIDENT,
        "qps": round(ZOO_REQUESTS / wall, 1),
        "p50_ms": round(float(np.percentile(lat_arr, 50)), 1),
        "availability": round(availability, 4),
        "failure_codes": sorted(set(failures)),
        "activations": stats["activations"],
        "evictions": stats["evictions"],
        "evictions_with_outstanding":
            stats["evictions_with_outstanding"],
        "cold_aot_activation_ms": round(activate_ev.stats["ms"], 1),
        "cold_aot_first_request_ms": round(aot_first_request_ms, 1),
        "backend": jax.default_backend(),
        "config": (f"{ZOO_MODELS} factory models + 1 AOT artifact, "
                   f"cache {ZOO_MAX_RESIDENT}, zipf(1.1) picks, "
                   f"{ZOO_REQUESTS} reqs x {ZOO_CLIENTS} clients, "
                   f"4 tenants, 2 engines x 2 workers"),
    }


# sharded serving bench (docs/sharded_serving.md): a Transformer
# classifier big enough that 8-way tensor sharding visibly splits the
# weights, served tensor-parallel over the virtual mesh
SHARDED_SPEC = {"type": "transformer", "vocab_size": 8192, "dim": 256,
                "depth": 2, "heads": 8, "max_len": 64,
                "num_classes": 16}
SHARDED_MESH_DEVICES = 8


def bench_sharded() -> dict:
    """Mesh-sharded serving (serving/sharded.py): a Transformer whose
    weights shard 8-way across the (virtual) mesh — per-device
    residency evidence for the too-big-for-one-device example, parity
    vs the unsharded oracle, zero steady-state recompiles, and the
    sharded AOT artifact's fresh-process cold-start ratio (trace-mode
    sharded startup vs AOT-loaded sharded startup)."""
    import subprocess
    import sys
    import tempfile

    import jax

    from mmlspark_tpu.core.table import DataTable
    from mmlspark_tpu.models.networks import build_network
    from mmlspark_tpu.models.tpu_model import TPUModel
    from mmlspark_tpu.serving import aot, sharded as SH
    from mmlspark_tpu.utils.jax_compat import set_cpu_device_count

    if len(jax.devices()) < SHARDED_MESH_DEVICES:
        if jax.default_backend() != "cpu":
            raise RuntimeError(
                f"sharded scenario needs {SHARDED_MESH_DEVICES} "
                f"devices; this {jax.default_backend()} host has "
                f"{len(jax.devices())}")
        # forcing virtual CPU devices only works BEFORE first backend
        # use — by the time a scenario runs, main() has initialized
        # the backend, so the pre-init in main() (gated on
        # JAX_PLATFORMS=cpu) is the only working path. A late
        # set_cpu_device_count here would silently no-op; fail with
        # the recipe instead.
        set_cpu_device_count(SHARDED_MESH_DEVICES)
        if len(jax.devices()) < SHARDED_MESH_DEVICES:
            raise RuntimeError(
                "sharded scenario needs a virtual "
                f"{SHARDED_MESH_DEVICES}-device mesh but the backend "
                "already initialized with "
                f"{len(jax.devices())} device(s); run with "
                "JAX_PLATFORMS=cpu (bench pre-forces the device count "
                "before backend init) or export XLA_FLAGS="
                f"--xla_force_host_platform_device_count="
                f"{SHARDED_MESH_DEVICES}")
    module = build_network(dict(SHARDED_SPEC))
    rng = np.random.default_rng(0)
    batch = 64
    toks = rng.integers(0, SHARDED_SPEC["vocab_size"],
                        size=(batch, 32)).astype(np.int32)
    variables = module.init(jax.random.PRNGKey(0), toks[:1])
    oracle = TPUModel.from_flax(module, variables, inputCol="tokens",
                                outputCol="scores", batchSize=batch)
    model = TPUModel.from_flax(module, variables, inputCol="tokens",
                               outputCol="scores", batchSize=batch)
    mesh = SH.serving_mesh({"model": SHARDED_MESH_DEVICES})
    SH.tensor_shard_model(model, mesh)

    table = DataTable({"tokens": toks})
    ref = np.asarray(oracle.transform(table)["scores"])
    out = np.asarray(model.transform(table)["scores"])
    parity = float(np.abs(ref - out).max())

    res = SH.device_residency(model)
    # raises if any single device holds the full weight set — the
    # same assertion the tests pin; returns (max/device, total)
    _, total_logical = SH.assert_serves_from_mesh(model)

    # steady-state sharded batch latency (+ the recompile guard)
    for _ in range(2):
        model.transform(table)
    misses = model.jit_cache_misses
    t0 = time.perf_counter()
    reps = 10
    for _ in range(reps):
        model.transform(table)
    sharded_ms = (time.perf_counter() - t0) / reps * 1e3
    recompiles = model.jit_cache_misses - misses
    t0 = time.perf_counter()
    for _ in range(reps):
        oracle.transform(table)
    oracle_ms = (time.perf_counter() - t0) / reps * 1e3

    # sharded AOT artifact: fresh-process cold start, trace vs aot
    art = tempfile.mkdtemp(prefix="mmlspark_sharded_aot_")
    t0 = time.time()
    manifest = aot.export_model(model, {"tokens": toks[:2]}, art,
                                version="bench-v1")
    export_s = time.time() - t0

    def run(mode: str, port: int) -> dict:
        proc = subprocess.run(
            [sys.executable, "-m", "mmlspark_tpu.serving.aot", art,
             "--mode", mode, "--port", str(port)],
            capture_output=True, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            timeout=600,
            env={**os.environ, "JAX_PLATFORMS": "cpu"}
            if jax.default_backend() == "cpu" else None)
        if proc.returncode != 0:
            raise RuntimeError(f"sharded coldstart runner failed: "
                               f"{proc.stderr[-2000:]}")
        return json.loads(proc.stdout.strip().splitlines()[-1])

    best = {"trace": None, "aot": None}
    port = 19840
    for _ in range(2):                # interleaved: noise hits both
        for mode in ("trace", "aot"):
            r = run(mode, port)
            port += 3
            if (best[mode] is None
                    or r["cold_start_to_first_200_ms"]
                    < best[mode]["cold_start_to_first_200_ms"]):
                best[mode] = r
    trace_ms = best["trace"]["cold_start_to_first_200_ms"]
    aot_ms = best["aot"]["cold_start_to_first_200_ms"]

    per_dev = res["per_device_bytes"]
    return {
        "metric": "sharded_coldstart_trace_over_aot",
        "value": round(trace_ms / aot_ms, 2) if aot_ms else None,
        "unit": "x (traced sharded startup / sharded-AOT startup, "
                "fresh replica processes, best-of-2 interleaved)",
        "mesh": {k: int(v) for k, v in mesh.shape.items()},
        "parity_max_abs_err_vs_unsharded": parity,
        "weights_total_bytes": total_logical,
        "max_device_bytes": res["max_device_bytes"],
        "max_device_fraction_of_total": round(
            res["max_device_bytes"] / total_logical, 4),
        "per_device_bytes": {k: int(v) for k, v in
                             sorted(per_dev.items())},
        "fits_one_device": res["max_device_bytes"] >= total_logical,
        "steady_state_recompiles": int(recompiles),
        "sharded_batch_ms": round(sharded_ms, 1),
        "single_device_batch_ms": round(oracle_ms, 1),
        "coldstart_trace_ms": trace_ms,
        "coldstart_aot_ms": aot_ms,
        "aot_zero_traces": best["aot"]["jit_traces_total"] == 0,
        "artifact_format": manifest["format"],
        "export_wall_s": round(export_s, 2),
        "backend": jax.default_backend(),
        "config": (f"transformer dim {SHARDED_SPEC['dim']} depth "
                   f"{SHARDED_SPEC['depth']} vocab "
                   f"{SHARDED_SPEC['vocab_size']}, batch {batch}, "
                   f"{SHARDED_MESH_DEVICES}-way tensor sharding; NOTE "
                   f"8 VIRTUAL devices timeshare this host's CPU — "
                   f"the latency comparison measures overhead, the "
                   f"residency/parity/cold-start numbers are the "
                   f"point"),
    }


OOC_ROWS = 10_000_000
OOC_CHUNK = 262_144
OOC_BUDGET_BYTES = 1_500_000_000    # 1.5 GB host budget for the
#                                     streamed pass (RSS growth AND
#                                     tracked bytes) — the materialized
#                                     path provably exceeds it
OOC_PREFETCH = 3


def bench_ooc() -> dict:
    """Out-of-core ingest (io/ooc.py + gbdt/sketch.py): a 10M-row
    Featurize -> StandardScaler -> logistic scoring pass streamed
    chunk-at-a-time through the fused pipeline under an ENFORCED host
    memory budget — asserted from both peak-RSS growth and tracked
    bytes — against the fully-materialized baseline (which provably
    exceeds the budget); ingest/compute overlap fraction from the
    ooc phase histograms; mergeable-sketch bin boundaries vs the exact
    one-shot fit (rank drift + the measured certificate) on a
    HIGGS-shaped 1M x 28 block; and a sketch-binned chunked GBDT train
    vs the reservoir-sample path."""
    import gc

    from mmlspark_tpu.automl.featurize import Featurize
    from mmlspark_tpu.core import metrics as MC
    from mmlspark_tpu.core.fusion import fuse
    from mmlspark_tpu.core.stage import PipelineModel
    from mmlspark_tpu.core.table import DataTable
    from mmlspark_tpu.gbdt.binning import BinMapper
    from mmlspark_tpu.io.ooc import (
        ChunkedTable, current_rss_bytes, peak_rss_bytes, table_nbytes,
    )
    from mmlspark_tpu.models.linear import TPULogisticRegression
    from mmlspark_tpu.stages.dataprep import StandardScaler

    levels = np.asarray([f"l{i}" for i in range(8)])
    vocab = np.asarray([f"w{i:02d}" for i in range(64)])

    def make_chunk(i: int, rows: int) -> DataTable:
        rng = np.random.default_rng(1000 + i)
        a = rng.normal(size=rows).astype(np.float32)
        b = np.where(rng.random(rows) < 0.1, np.nan,
                     rng.normal(size=rows)).astype(np.float32)
        cat = levels[rng.integers(0, len(levels), rows)].tolist()
        toks = vocab[rng.integers(0, len(vocab),
                                  size=(rows, 3))].tolist()
        return DataTable({"a": a, "b": b, "cat": cat, "toks": toks})

    def factory():
        done, i = 0, 0
        while done < OOC_ROWS:
            rows = min(OOC_CHUNK, OOC_ROWS - done)
            yield make_chunk(i, rows)
            done += rows
            i += 1

    def fresh_source(depth: int = OOC_PREFETCH) -> ChunkedTable:
        return ChunkedTable.from_generator(factory, num_rows=OOC_ROWS,
                                           prefetch_depth=depth)

    # -- fit: streaming Featurize + scaler + a sample-fitted model ------
    print("# ooc: streaming featurize fit ...", flush=True)
    t0 = time.perf_counter()
    fz_model = Featurize(featureColumns=["a", "b", "cat", "toks"],
                         numberOfFeatures=32).fit(fresh_source())
    fit_wall = time.perf_counter() - t0
    sample = DataTable.concat([make_chunk(0, OOC_CHUNK),
                               make_chunk(1, OOC_CHUNK)])
    feat_sample = fz_model.transform(sample)
    scaler = StandardScaler(inputCol="features").fit(
        ChunkedTable.from_table(feat_sample, chunk_rows=OOC_CHUNK))
    scaled = scaler.transform(feat_sample)
    rng = np.random.default_rng(0)
    a_col = np.asarray(sample["a"], np.float64)
    y = (a_col + rng.normal(scale=0.5, size=len(a_col)) > 0).astype(
        np.float64)
    logit = TPULogisticRegression(
        featuresCol="features", labelCol="label", maxIter=10).fit(
        scaled.with_column("label", y))
    fused = fuse([fz_model, scaler, logit], batch_size=OOC_CHUNK)

    # -- streamed pass under the budget --------------------------------
    print("# ooc: streamed scoring pass ...", flush=True)
    for h in MC.ooc_histograms().values():
        h.reset()
    gc.collect()
    src = fresh_source()
    rss_before = current_rss_bytes()
    peak_before = peak_rss_bytes()
    t0 = time.perf_counter()
    rows = 0
    pred_sum = 0.0
    first_chunk_pred = None
    for out in fused.transform_chunked(src):
        p = np.asarray(out["prediction"])
        if first_chunk_pred is None:
            first_chunk_pred = p.copy()
        rows += len(p)
        pred_sum += float(p.sum())
    streamed_wall = time.perf_counter() - t0
    assert rows == OOC_ROWS
    streamed_rss_growth = max(peak_rss_bytes(), peak_before) - rss_before
    streamed_tracked = src.stats.tracked_peak_bytes()
    phases = {k: h.snapshot() for k, h in MC.ooc_histograms().items()}
    worker_s = (phases["decode"]["sum"] + phases["prepare"]["sum"]) / 1e3
    consumer_s = phases["dispatch"]["sum"] / 1e3
    wait_s = phases["wait"]["sum"] / 1e3
    overlap = 0.0
    if min(worker_s, consumer_s) > 0:
        overlap = max(0.0, min(1.0, (worker_s + consumer_s
                                     - streamed_wall)
                               / min(worker_s, consumer_s)))
    # the 1-core-visible pipelining signal: what fraction of the decode
    # wall the consumer did NOT block for (the prefetcher ran decode
    # while the consumer was busy — time-sliced here, truly parallel on
    # a multi-core/TPU host where `overlap` itself becomes nonzero)
    decode_hidden = 0.0
    if phases["decode"]["sum"] > 0:
        decode_hidden = max(0.0, min(1.0, 1.0 - phases["wait"]["sum"]
                                     / phases["decode"]["sum"]))

    # the budget holds on BOTH trackers, or the scenario fails loudly
    assert streamed_tracked < OOC_BUDGET_BYTES, (
        f"streamed tracked bytes {streamed_tracked} over budget")
    assert streamed_rss_growth < OOC_BUDGET_BYTES, (
        f"streamed RSS growth {streamed_rss_growth} over budget")

    # -- materialized baseline (provably over the budget) --------------
    print("# ooc: materialized baseline ...", flush=True)
    gc.collect()
    rss_mat0 = current_rss_bytes()
    t0 = time.perf_counter()
    mat = fresh_source(depth=0).materialize()
    feats_mat = fused.transform(mat)
    mat_wall = time.perf_counter() - t0
    mat_pred = np.asarray(feats_mat["prediction"])
    mat_rss_growth = peak_rss_bytes() - rss_mat0
    mat_tracked = table_nbytes(mat) + table_nbytes(feats_mat)
    assert np.array_equal(first_chunk_pred, mat_pred[:OOC_CHUNK]), \
        "streamed scoring diverged from the materialized oracle"
    assert mat_tracked > OOC_BUDGET_BYTES, (
        f"materialized path unexpectedly fit the budget: {mat_tracked}")
    assert mat_rss_growth > OOC_BUDGET_BYTES, (
        f"materialized RSS growth under budget: {mat_rss_growth}")
    pred_match = bool(abs(mat_pred.sum() - pred_sum) < 1e-6 * OOC_ROWS)
    del mat, feats_mat, mat_pred
    gc.collect()

    # -- sketch-vs-exact bin boundaries (HIGGS-shaped 1M x 28) ----------
    print("# ooc: sketch-vs-exact boundaries ...", flush=True)
    hn, hf = 1_000_000, 28
    hrng = np.random.default_rng(7)
    H = hrng.normal(size=(hn, hf)).astype(np.float32)
    h_chunks = [H[i:i + OOC_CHUNK] for i in range(0, hn, OOC_CHUNK)]
    t0 = time.perf_counter()
    m_sketch = BinMapper.fit_streaming(iter(h_chunks), max_bin=255)
    sketch_fit_wall = time.perf_counter() - t0
    t0 = time.perf_counter()
    m_exact = BinMapper.fit(H, max_bin=255, sample_cnt=hn)
    exact_fit_wall = time.perf_counter() - t0
    drift = 0.0
    for j in range(hf):
        xs = np.sort(H[:, j].astype(np.float64))
        ca, cb = m_sketch.upper_bounds[j], m_exact.upper_bounds[j]
        k = min(len(ca), len(cb))
        ra = np.searchsorted(xs, ca[:k], side="left") / hn
        rb = np.searchsorted(xs, cb[:k], side="left") / hn
        drift = max(drift, float(np.max(np.abs(ra - rb))))
    assert drift <= 2 * m_sketch.sketch_eps + 2.0 / 255, (
        f"cut drift {drift} exceeds the certificate bound")

    # -- chunked sketch-binned GBDT vs the reservoir-sample path --------
    # (HIGGS-shaped but shortened: this 1-core container pays ~15s per
    # boosting iteration at 1M rows — the full-length wall lives in the
    # higgs scenario; here the comparison is the BINNING path)
    from mmlspark_tpu.gbdt.booster import train
    gn = min(hn, 400_000)
    hy = (H[:gn, 0] + 0.6 * H[:gn, 1] * H[:gn, 2]
          + hrng.normal(scale=0.7, size=gn) > 0).astype(np.float64)

    def gbdt_factory():
        for i in range(0, gn, OOC_CHUNK):
            yield H[i:min(i + OOC_CHUNK, gn)], hy[i:i + OOC_CHUNK]

    gbdt = {"rows": gn, "iterations": 8}
    for mode in ("sketch", "sample"):
        print(f"# ooc: gbdt bin_fit={mode} ...", flush=True)
        params = {"objective": "binary", "num_iterations": 8,
                  "num_leaves": 63, "max_bin": 63, "seed": 0,
                  "bin_fit": mode}
        t0 = time.perf_counter()
        booster = train(params, gbdt_factory, y=None)
        wall = time.perf_counter() - t0
        p = booster.predict(H[:200_000])
        ys = hy[:200_000]
        order = np.argsort(p)
        ranks = np.empty(len(p))
        ranks[order] = np.arange(len(p))
        pos = ys == 1
        auc = ((ranks[pos].sum() - pos.sum() * (pos.sum() - 1) / 2)
               / (pos.sum() * (len(p) - pos.sum())))
        gbdt[mode] = {"train_wall_s": round(wall, 2),
                      "holdout_auc": round(float(auc), 4)}

    import jax
    return {
        "metric": "ooc_streamed_10m_featurize_model",
        "backend": jax.default_backend(),
        "rows": OOC_ROWS,
        "chunk_rows": OOC_CHUNK,
        "prefetch_depth": OOC_PREFETCH,
        "budget_bytes": OOC_BUDGET_BYTES,
        "featurize_fit_streaming_wall_s": round(fit_wall, 2),
        "streamed": {
            "wall_s": round(streamed_wall, 2),
            "rss_growth_bytes": int(streamed_rss_growth),
            "tracked_peak_bytes": int(streamed_tracked),
            "under_budget": True,
            "phase_s": {"decode": round(phases["decode"]["sum"] / 1e3, 2),
                        "prepare": round(
                            phases["prepare"]["sum"] / 1e3, 2),
                        "dispatch": round(consumer_s, 2),
                        "wait": round(wait_s, 2)},
            "ingest_compute_overlap_fraction": round(overlap, 3),
            "decode_hidden_fraction": round(decode_hidden, 3),
        },
        "materialized": {
            "wall_s": round(mat_wall, 2),
            "rss_growth_bytes": int(mat_rss_growth),
            "tracked_bytes": int(mat_tracked),
            "over_budget": True,
            "prediction_sum_matches": pred_match,
        },
        "streamed_vs_materialized_wall": round(
            mat_wall / max(streamed_wall, 1e-9), 3),
        "sketch_binning_1m_x28": {
            "sketch_eps_certificate": round(m_sketch.sketch_eps, 6),
            "max_cut_rank_drift_vs_exact": round(drift, 6),
            "bound_2eps": round(2 * m_sketch.sketch_eps, 6),
            "fit_streaming_wall_s": round(sketch_fit_wall, 2),
            "fit_exact_wall_s": round(exact_fit_wall, 2),
            "f32_cuts_exact": bool(m_sketch.f32_cuts_exact),
        },
        "gbdt_chunked_1m_x28_8iter": gbdt,
        "notes": ("CPU container, single usable core: overlap is "
                  "bounded by the decode thread and XLA's compute "
                  "threads timesharing one core — the phase sums and "
                  "the budget assertions are the point; a TPU host "
                  "overlaps host decode with device compute for real"),
    }


FLEET_PROCS = 4
FLEET_LOAD_S = 10.0
FLEET_CLIENTS = 16
FLEET_ROWS_PER_REQ = 64


def bench_fleet_procs() -> dict:
    """The REAL multi-process fleet: N serving engines as OS processes
    (tests/serving_worker.py --scorer linear) behind
    ``ServingFleet.connect`` with the startup probe, driven by a
    columnar load generator (``post_columns`` — msgpack record
    batches); throughput scaling vs ONE process, plus the chaos drill
    (SIGKILL one engine mid-load, availability floor). Replaces the
    threads-in-one-process fleet numbers for the multi-process story."""
    import signal as _signal
    import subprocess
    import sys
    import threading

    import jax

    from mmlspark_tpu.serving.fleet import ServingFleet

    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tests", "serving_worker.py")
    dim = 16
    rng = np.random.default_rng(0)
    rows = rng.normal(size=(FLEET_ROWS_PER_REQ, dim)).astype(np.float32)

    def spawn(n):
        procs, addrs = [], []
        for wid in range(n):
            import socket
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                port = s.getsockname()[1]
            p = subprocess.Popen(
                [sys.executable, worker, str(port), str(wid),
                 "--scorer", "linear", "--dim", str(dim),
                 "--batch-size", "64", "--workers", "1"],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True)
            procs.append(p)
        for p in procs:
            line = p.stdout.readline().strip()
            addrs.append(line.split()[2])
        return procs, addrs

    def drive(fleet, duration_s, kill=None, procs=None):
        """Closed-loop columnar load; optionally SIGKILL one worker
        mid-window. Returns (rows_ok, requests_ok, failed, wall_s)."""
        stats = {"ok": 0, "failed": 0}
        lock = threading.Lock()
        stop = threading.Event()

        def client():
            while not stop.is_set():
                try:
                    rep = fleet.post_columns({"features": rows},
                                             timeout=30)
                    n = len(rep["prediction"])
                    with lock:
                        stats["ok"] += n
                except Exception:  # noqa: BLE001
                    with lock:
                        stats["failed"] += 1

        threads = [threading.Thread(target=client, daemon=True)
                   for _ in range(FLEET_CLIENTS)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        if kill is not None:
            time.sleep(duration_s * 0.4)
            procs[kill].send_signal(_signal.SIGKILL)
            time.sleep(duration_s * 0.6)
        else:
            time.sleep(duration_s)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        wall = time.perf_counter() - t0
        reqs_ok = stats["ok"] // FLEET_ROWS_PER_REQ
        return stats["ok"], reqs_ok, stats["failed"], wall

    out = {}
    for n in (1, FLEET_PROCS):
        procs, addrs = spawn(n)
        try:
            fleet = ServingFleet.connect(addrs, wait_ready_s=120.0,
                                         tracing=False)
            drive(fleet, 1.5)                      # warm connections
            rows_ok, reqs, failed, wall = drive(fleet, FLEET_LOAD_S)
            out[n] = {"rows_per_s": round(rows_ok / wall, 1),
                      "requests_ok": reqs, "failed": failed}
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                p.wait(timeout=30)

    # chaos: fresh N-process fleet, SIGKILL one engine mid-load
    procs, addrs = spawn(FLEET_PROCS)
    try:
        fleet = ServingFleet.connect(addrs, wait_ready_s=120.0,
                                     failure_threshold=2,
                                     breaker_cooldown=1.0,
                                     tracing=False)
        drive(fleet, 1.5)
        rows_ok, reqs, failed, wall = drive(
            fleet, FLEET_LOAD_S, kill=0, procs=procs)
        availability = reqs / max(1, reqs + failed)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=30)

    usable_cores = len(os.sched_getaffinity(0))
    scaling = (out[FLEET_PROCS]["rows_per_s"]
               / max(1e-9, out[1]["rows_per_s"]))
    return {
        "metric": "fleet_procs_throughput_scaling",
        "value": round(scaling, 2),
        "unit": f"x ({FLEET_PROCS} engine processes vs 1, columnar "
                f"load generator)",
        "one_proc": out[1],
        "n_procs": out[FLEET_PROCS],
        "engine_processes": FLEET_PROCS,
        "clients": FLEET_CLIENTS,
        "rows_per_request": FLEET_ROWS_PER_REQ,
        "chaos_kill_one": {
            "availability": round(availability, 4),
            "requests_ok": reqs, "failed": failed,
            "rows_per_s": round(rows_ok / wall, 1),
        },
        "usable_cores": usable_cores,
        "scaling_note": (
            "process scaling is bounded by usable cores: the >=2.5x "
            "floor is a multi-core claim (tests/test_sharded.py gates "
            "it on >=4 cores), this container exposes "
            f"{usable_cores}"),
        "backend": jax.default_backend(),
    }


FABRIC_PROCS = 4
FABRIC_LOAD_S = 6.0
FABRIC_CLIENTS = 4
FABRIC_ROWS_PER_REQ = 512


def bench_fabric() -> dict:
    """The multi-host fabric (PR 17): (1) co-located shared-memory
    columnar transport vs HTTP+msgpack over the SAME 4-process fleet —
    rows/s and request p50/p99 at equal availability; (2) the
    placement-plane churn drill — a hot model earns replicas, demand
    flips mid-window, rebuild latency and assignment-event counts from
    the controller's own histogram; (3) a REAL 2-process
    ``jax.distributed`` group (tests/multihost_worker.py) running the
    sketch-binned multi-host GBDT fit, wall clock from spawn to OK with
    the bit-identical forest digest asserted across members."""
    import signal as _signal  # noqa: F401  (parity with fleet bench)
    import subprocess
    import sys
    import threading

    import jax

    from mmlspark_tpu.core.metrics import LatencyHistogram
    from mmlspark_tpu.serving.fleet import ServingFleet

    tests_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "tests")
    worker = os.path.join(tests_dir, "serving_worker.py")
    dim = 16
    rng = np.random.default_rng(0)
    rows = rng.normal(size=(FABRIC_ROWS_PER_REQ, dim)).astype(np.float32)

    def _free_port():
        import socket
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def spawn(n):
        procs, addrs = [], []
        for wid in range(n):
            port = _free_port()
            p = subprocess.Popen(
                [sys.executable, worker, str(port), str(wid),
                 "--scorer", "linear", "--dim", str(dim),
                 "--batch-size", "64", "--workers", "1"],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True)
            procs.append(p)
        for p in procs:
            line = p.stdout.readline().strip()
            addrs.append(line.split()[2])
        return procs, addrs

    def drive(fleet, duration_s):
        """Closed-loop columnar load with per-request latency capture.
        Returns (rows_ok, requests_ok, failed, wall_s, hist)."""
        stats = {"ok": 0, "failed": 0}
        hist = LatencyHistogram(unit="ms")
        lock = threading.Lock()
        stop = threading.Event()

        def client():
            while not stop.is_set():
                t0 = time.perf_counter()
                try:
                    rep = fleet.post_columns({"features": rows},
                                             timeout=30)
                    ms = (time.perf_counter() - t0) * 1e3
                    n = len(rep["prediction"])
                    with lock:
                        stats["ok"] += n
                        hist.observe(ms)
                except Exception:  # noqa: BLE001
                    with lock:
                        stats["failed"] += 1

        threads = [threading.Thread(target=client, daemon=True)
                   for _ in range(FABRIC_CLIENTS)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        time.sleep(duration_s)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        wall = time.perf_counter() - t0
        reqs_ok = stats["ok"] // FABRIC_ROWS_PER_REQ
        return stats["ok"], reqs_ok, stats["failed"], wall, hist

    # --- (1) shm vs HTTP+msgpack over the SAME worker processes ---
    transports = {}
    procs, addrs = spawn(FABRIC_PROCS)
    try:
        for label, use_shm in (("shm", True), ("http_msgpack", False)):
            fleet = ServingFleet.connect(addrs, wait_ready_s=120.0,
                                         tracing=False,
                                         shm_transport=use_shm)
            try:
                drive(fleet, 1.5)                  # warm + negotiate
                rows_ok, reqs, failed, wall, hist = drive(
                    fleet, FABRIC_LOAD_S)
                entry = {
                    "rows_per_s": round(rows_ok / wall, 1),
                    "requests_ok": reqs, "failed": failed,
                    "p50_ms": round(hist.percentile(50), 2),
                    "p99_ms": round(hist.percentile(99), 2),
                    "availability": round(
                        reqs / max(1, reqs + failed), 4),
                }
                if use_shm:
                    from mmlspark_tpu.io import shm as shm_mod
                    s = shm_mod.stats()
                    entry["negotiated"] = bool(fleet._shm_ok)
                    entry["fallbacks"] = fleet._shm_fallbacks
                    entry["shm_batches"] = s.get("batches", 0)
                    entry["shm_bytes"] = s.get("bytes", 0)
                    entry["gen_mismatch"] = s.get("gen_mismatch", 0)
            finally:
                # close the ring but leave the shared workers alive
                # for the second transport's run
                fleet.close_shm()
            transports[label] = entry
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=30)

    shm_vs_http = (transports["shm"]["rows_per_s"]
                   / max(1e-9, transports["http_msgpack"]["rows_per_s"]))

    # --- (2) placement-plane churn drill (in-process 2-engine fleet
    # sharing ONE zoo, demand flip mid-window) ---
    from mmlspark_tpu.serving.placement import PlacementEvent
    from mmlspark_tpu.serving.zoo import ModelZoo
    from mmlspark_tpu.stages.basic import Lambda

    def _echo(tag):
        def handle(table):
            replies = []
            for r in table["request"]:
                replies.append({"served_by": tag})
            return table.with_column("reply", replies)
        return Lambda.apply(handle)

    zoo = ModelZoo(memory_probe=None)
    for i in range(4):
        zoo.register_factory(f"m{i}", "v1",
                             (lambda i=i: _echo(f"m{i}")))
    pfleet = ServingFleet(n_engines=2, base_port=21510, zoo=zoo,
                          tracing=False)
    ctl = pfleet.attach_placement(rebuild_min_interval_s=0.0)
    churn = {}
    try:
        ok = failed = 0
        t0 = time.perf_counter()
        # phase A: m0 hot, m1..m3 cold
        for i in range(30):
            model = "m0" if i % 5 else f"m{1 + (i // 5) % 3}"
            try:
                pfleet.post({"x": i}, model=model)
                ok += 1
            except Exception:  # noqa: BLE001
                failed += 1
        ctl.rebuild(force=True)
        replicas_a = dict(ctl.replica_counts())
        # phase B: demand flips to m2 (hot enough to cross hot_share
        # against phase A's still-windowed m0 demand)
        for i in range(40):
            model = "m2"
            try:
                pfleet.post({"x": i}, model=model)
                ok += 1
            except Exception:  # noqa: BLE001
                failed += 1
        ctl.rebuild(force=True)
        replicas_b = dict(ctl.replica_counts())
        churn_wall = time.perf_counter() - t0
        st = ctl.stats()
        kinds = {}
        for ev in zoo.events:
            if isinstance(ev, PlacementEvent):
                kinds[ev.kind] = kinds.get(ev.kind, 0) + 1
        churn = {
            "hot_replicas_phase_a": replicas_a,
            "hot_replicas_phase_b": replicas_b,
            "rebuilds": st["rebuilds"],
            "stale_routes": st["stale_routes"],
            "placement_events": kinds,
            "rebuild_p50_ms": round(ctl.rebuild_hist.percentile(50), 3),
            "rebuild_p99_ms": round(ctl.rebuild_hist.percentile(99), 3),
            "availability": round(ok / max(1, ok + failed), 4),
            "wall_s": round(churn_wall, 2),
        }
    finally:
        pfleet.stop_all()
        zoo.close()

    # --- (3) 2-process jax.distributed sketch-GBDT fit wall ---
    mh_worker = os.path.join(tests_dir, "multihost_worker.py")
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    port = _free_port()
    t0 = time.perf_counter()
    mh_procs = [subprocess.Popen(
        [sys.executable, mh_worker, str(port), str(pid), "2"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env) for pid in range(2)]
    digests, mh_rcs = {}, []
    try:
        for p in mh_procs:
            out_txt, _err = p.communicate(timeout=300)
            mh_rcs.append(p.returncode)
            for line in out_txt.splitlines():
                if line.startswith("DIGEST"):
                    _, pid, digest, _bdig, _acc = line.split()
                    digests[int(pid)] = digest
    except subprocess.TimeoutExpired:
        for p in mh_procs:
            p.kill()
    group_wall = time.perf_counter() - t0
    group = {
        "wall_s": round(group_wall, 2),
        "rcs": mh_rcs,
        "forest_digest": digests.get(0),
        "bit_identical": (len(digests) == 2
                          and len(set(digests.values())) == 1),
    }

    usable_cores = len(os.sched_getaffinity(0))
    return {
        "metric": "fabric_shm_vs_http_rows_per_s",
        "value": round(shm_vs_http, 2),
        "unit": f"x (shm columnar vs HTTP+msgpack, {FABRIC_PROCS} "
                f"engine processes, {FABRIC_ROWS_PER_REQ} rows/req)",
        "transports": transports,
        "placement_churn": churn,
        "process_group_gbdt": group,
        "engine_processes": FABRIC_PROCS,
        "clients": FABRIC_CLIENTS,
        "rows_per_request": FABRIC_ROWS_PER_REQ,
        "usable_cores": usable_cores,
        "uplift_note": (
            "shm removes the msgpack encode/decode and the HTTP body "
            "copy from the numeric path (one staged copy into the "
            "segment remains); on this container client and engines "
            f"timeshare {usable_cores} core(s), so the uplift is "
            "serialization savings only — the >=1.3x floor is a "
            "multi-core claim (tests/test_perf_floors.py gates it)"),
        "backend": jax.default_backend(),
    }


GBDT_DIST_ROWS = 100_000      # per host (2 hosts -> 200k global rows,
#                               HIGGS shape: the 100M-row flagship
#                               methodology at container scale)
GBDT_DIST_FEATS = 28          # the HIGGS feature width
GBDT_DIST_ITERS = 10


def bench_gbdt_dist() -> dict:
    """The PR 19 flagship: comm-efficient quantized-histogram
    distributed GBDT on the HIGGS-100M shape. Two REAL 2-process
    ``jax.distributed`` groups (tests/multihost_worker.py --bench-rows)
    each stream a per-host Arrow IPC row shard as memory-mapped
    ChunkedTable chunks through sketch binning — the raw f32 matrix
    never rematerializes — and train data-parallel over the group:

    - run A: the f32 psum engine (hist_bits=32, the pre-PR wire);
    - run B: quantized reduce-scatter (hist_bits=16, int16 wire,
      feature-partitioned split search).

    Reports per-phase walls, the modeled per-device collective bytes
    (ring model — the collectives run inside jit, so bytes are modeled
    from the static schedule, see docs/distributed_gbdt.md), the
    comm reduction (floor: >=2x), the ASSERTED streaming memory budget,
    and the hot-loop phase micro-timings observed through the
    ``gbdt_hist_phase_ms`` metric family and rendered through the real
    Prometheus exposition."""
    import subprocess
    import sys

    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.core import metrics as MC
    from mmlspark_tpu.core.prometheus import PromRenderer, \
        process_families

    tests_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "tests")
    mh_worker = os.path.join(tests_dir, "multihost_worker.py")
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}

    def _free_port():
        import socket
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def run_group(hist_bits, hist_comm):
        port = _free_port()
        t0 = time.perf_counter()
        procs = [subprocess.Popen(
            [sys.executable, mh_worker, str(port), str(pid), "2",
             "--timeout-s", "120",
             "--bench-rows", str(GBDT_DIST_ROWS),
             "--bench-feats", str(GBDT_DIST_FEATS),
             "--bench-iters", str(GBDT_DIST_ITERS),
             "--hist-bits", str(hist_bits), "--hist-comm", hist_comm],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env) for pid in range(2)]
        phases, comm, stat, rcs = {}, {}, {}, []
        for p in procs:
            out_txt, err_txt = p.communicate(timeout=1800)
            rcs.append(p.returncode)
            if p.returncode != 0:
                raise RuntimeError(
                    f"gbdt_dist worker failed:\n{out_txt}\n{err_txt}")
            for line in out_txt.splitlines():
                parts = line.split()
                if line.startswith("BENCH_PHASE") and parts[1] == "0":
                    phases[parts[2]] = float(parts[3])
                elif line.startswith("BENCH_COMM") and parts[1] == "0":
                    comm[parts[2]] = float(parts[3])
                elif line.startswith("BENCH_STAT") and parts[1] == "0":
                    stat = {"auc": float(parts[2]),
                            "raw_mb": float(parts[3]),
                            "peak_chunk_mb": float(parts[4]),
                            "maxrss_mb": float(parts[5])}
        wall = time.perf_counter() - t0
        # the streaming memory budget the scenario ASSERTS: chunks in
        # flight stay far under the raw shard (the matrix never
        # rematerializes between the Arrow mmap and the binned int8)
        assert stat["peak_chunk_mb"] * 4 < stat["raw_mb"], stat
        return {"wall_s": round(wall, 2), "phases": phases,
                "comm_bytes_per_device": comm, **stat}

    run_f32 = run_group(32, "psum")
    run_q16 = run_group(16, "reduce_scatter")
    tot_f32 = sum(run_f32["comm_bytes_per_device"].values())
    tot_q16 = sum(run_q16["comm_bytes_per_device"].values())
    reduction = tot_f32 / max(tot_q16, 1.0)
    assert reduction >= 2.0, (tot_f32, tot_q16)

    # hot-loop phase micro-timings (build/reduce/split): the phases
    # fuse inside one jitted program in the real engine, so they are
    # micro-timed here as standalone jits at the training shape and
    # observed through the gbdt_hist_phase_ms metric family
    from mmlspark_tpu.gbdt.histogram import build_histogram
    L, B, n_micro = 31, 63, 65536
    rng = np.random.default_rng(0)
    bins = jnp.asarray(rng.integers(
        0, B, size=(GBDT_DIST_FEATS, n_micro)), dtype=jnp.int32)
    qg = jnp.asarray(rng.integers(-16384, 16384, size=n_micro),
                     dtype=jnp.int16)
    qh = jnp.asarray(rng.integers(0, 16384, size=n_micro),
                     dtype=jnp.int16)
    w = jnp.ones(n_micro, jnp.int16)
    leaf = jnp.asarray(rng.integers(0, L, size=n_micro), jnp.int32)

    build = jax.jit(lambda: build_histogram(
        bins, qg, qh, w, leaf, L, B, method="scatter",
        count_values=w))
    hist = build().block_until_ready()

    reduce_ = jax.jit(lambda a, b: (
        a.astype(jnp.int16) + b.astype(jnp.int16)).astype(jnp.int32))
    half = (hist // 2).astype(jnp.int32)

    def _split(h):
        # the split-search core at gain time: dequantize once, cumsum,
        # gain table, flat argmax
        hf = h.astype(jnp.float32) * 1e-4
        gl = jnp.cumsum(hf[0], axis=-1)
        hl = jnp.cumsum(hf[1], axis=-1)
        gt, ht = gl[..., -1:], hl[..., -1:]
        gain = (gl ** 2 / (hl + 1.0)
                + (gt - gl) ** 2 / (ht - hl + 1.0))
        return jnp.argmax(gain.reshape(gain.shape[0], -1), axis=-1)

    split = jax.jit(_split)
    split(hist).block_until_ready()
    reduce_(half, half).block_until_ready()
    hists = MC.gbdt_hist_histograms()
    for _ in range(10):
        t0 = time.perf_counter()
        build().block_until_ready()
        hists["build"].observe((time.perf_counter() - t0) * 1e3)
        t0 = time.perf_counter()
        reduce_(half, half).block_until_ready()
        hists["reduce"].observe((time.perf_counter() - t0) * 1e3)
        t0 = time.perf_counter()
        split(hist).block_until_ready()
        hists["split"].observe((time.perf_counter() - t0) * 1e3)
    for coll, nb in run_q16["comm_bytes_per_device"].items():
        if nb:
            MC.gbdt_comm_add(coll, nb)
    r = PromRenderer()
    process_families(r)
    text = r.render()
    assert "gbdt_comm_bytes_total" in text
    assert "gbdt_hist_phase_ms_bucket" in text
    phase_ms = {ph: round(h.percentile(50), 3)
                for ph, h in hists.items()}

    usable_cores = len(os.sched_getaffinity(0))
    return {
        "metric": "gbdt_dist_quantized_comm_reduction",
        "value": round(reduction, 2),
        "unit": "x (modeled per-device collective bytes, f32 psum vs "
                "hist_bits=16 reduce_scatter, ring model)",
        "config": f"2 processes x {GBDT_DIST_ROWS} rows x "
                  f"{GBDT_DIST_FEATS} feats (HIGGS shape), "
                  f"{GBDT_DIST_ITERS} iters, 31 leaves, 63 bins, "
                  "Arrow ChunkedTable + sketch binning",
        "f32_psum": run_f32,
        "q16_reduce_scatter": run_q16,
        "auc_delta_q16_vs_f32": round(
            run_q16["auc"] - run_f32["auc"], 4),
        "hist_phase_ms_p50": phase_ms,
        "memory_budget": "asserted: peak in-flight chunk bytes * 4 < "
                         "raw per-host shard bytes (streamed, never "
                         "rematerialized)",
        "usable_cores": usable_cores,
        "backend": jax.default_backend(),
        "honesty_note": (
            "comm bytes are MODELED from the static collective "
            "schedule (ring costs; the collectives run inside jit on "
            "gloo CPU process groups here, not ICI) — the >=2x floor "
            "is the wire-payload contract, wall-clock uplift is a "
            f"TPU/multi-NIC claim; both processes timeshare "
            f"{usable_cores} core(s) on this container. MXU int8 "
            "histogram throughput claims are gated on TPU backends "
            "(tests/test_perf_floors.py)"),
    }


def bench_continuous() -> dict:
    """Closed-loop continuous training under drift (ref: TFX/Baylor
    continuous pipelines, KDD'17): a served logistic scorer, an
    injected distribution shift, and the ContinuousTrainer running the
    full drift -> refit -> shadow -> canary -> cutover loop
    autonomously while clients hammer the engine.

    Reports the numbers the robustness claim hangs on: loop reaction
    time (drift onset -> candidate serving), serving p99 during the
    refit/cutover window vs steady state (training must not perturb
    the request path), and the shadow-gate quality delta that justified
    the promotion."""
    import threading
    import urllib.request

    from mmlspark_tpu.core.metrics import DriftMonitor
    from mmlspark_tpu.core.table import DataTable
    from mmlspark_tpu.models.linear import TPULogisticRegression
    from mmlspark_tpu.serving import (
        CanaryPolicy, ContinuousTrainer, GatePolicy, ModelRegistry,
        TriggerPolicy, json_scoring_pipeline, serve_model,
    )

    import jax

    d, shift = 8, 3.0
    rng = np.random.default_rng(0)
    w_true = np.linspace(1.0, -1.0, d)

    def blobs(n, mu):
        X = rng.normal(size=(n, d)) + mu
        y = (X @ w_true > mu * w_true.sum()).astype(np.float64)
        return X, y

    X0, y0 = blobs(2000, 0.0)
    est = TPULogisticRegression(maxIter=80)
    base = est.fit(DataTable({"features": X0, "label": y0}))
    dm = DriftMonitor.from_matrix(
        X0, feature_names=[f"f{i}" for i in range(d)])
    engine = serve_model(json_scoring_pipeline(base, drift_monitor=dm),
                         port=21900, batch_size=32, workers=2,
                         version="base")
    registry = ModelRegistry()

    def refit(window, active):
        tab = window.materialize()
        m = est.partial_fit(tab, getattr(active, "model", None))
        ndm = DriftMonitor.from_matrix(
            np.asarray(tab["features"]),
            feature_names=[f"f{i}" for i in range(d)])
        return json_scoring_pipeline(m, drift_monitor=ndm)

    trainer = ContinuousTrainer(
        engine, refit, registry=registry,
        triggers=TriggerPolicy(max_mean_delta_sigma=2.0,
                               min_window_rows=256, cooldown_s=1.0,
                               watch_slo_alerts=False),
        gate=GatePolicy(shadow_rows=512),
        canary=CanaryPolicy(fraction=0.5, min_batches=3,
                            decision_timeout_s=30),
        warmup_example={"features": [0.0] * d},
        poll_interval_s=0.05)

    lat_steady, lat_refit = [], []
    errors = [0]
    phase = {"mu": 0.0, "sink": lat_steady}
    stop = threading.Event()
    lock = threading.Lock()

    def client(tid):
        crng = np.random.default_rng(100 + tid)
        while not stop.is_set():
            x = crng.normal(size=d) + phase["mu"]
            body = json.dumps({"features": list(x)}).encode()
            req = urllib.request.Request(
                engine.source.address, data=body,
                headers={"Content-Type": "application/json"})
            t0 = time.perf_counter()
            try:
                with urllib.request.urlopen(req, timeout=10) as r:
                    r.read()
                dt = (time.perf_counter() - t0) * 1e3
                with lock:
                    phase["sink"].append(dt)
            except Exception:  # noqa: BLE001 — availability metric
                errors[0] += 1
            time.sleep(0.001)

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(4)]
    trainer.start()
    for t in threads:
        t.start()
    time.sleep(3.0)    # steady state on the base model

    # -- drift onset: traffic shifts, labeled rows reach the window ----
    with lock:
        phase["mu"] = shift
        phase["sink"] = lat_refit
    Xs, ys = blobs(2000, shift)
    drift_onset = time.perf_counter()
    for lo in range(0, 2000, 250):
        trainer.ingest(DataTable({"features": Xs[lo:lo + 250],
                                  "label": ys[lo:lo + 250]}))
    deadline = time.monotonic() + 120
    while trainer.promotions < 1 and time.monotonic() < deadline:
        time.sleep(0.05)
    reaction_s = time.perf_counter() - drift_onset
    time.sleep(1.0)    # tail of the cutover window
    stop.set()
    for t in threads:
        t.join(timeout=10)
    promoted = trainer.promotions >= 1
    shadow = next((e for e in registry.events
                   if getattr(e, "kind", "") == "shadow_pass"), None)
    verdict = dict(shadow.stats) if shadow is not None else {}
    status = trainer.status()
    trainer.stop()
    engine.stop()

    def p(v, q):
        return float(np.percentile(v, q)) if v else float("nan")

    return {
        "metric": "continuous_loop_reaction_s",
        "value": round(reaction_s, 2),
        "unit": "s (drift onset -> refit candidate serving live)",
        "promoted": promoted,
        "active_version": "ct-1" if promoted else "base",
        "serving_p99_ms": {
            "steady": round(p(lat_steady, 99), 2),
            "during_refit_cutover": round(p(lat_refit, 99), 2),
        },
        "serving_p50_ms": {
            "steady": round(p(lat_steady, 50), 2),
            "during_refit_cutover": round(p(lat_refit, 50), 2),
        },
        "requests": {"steady": len(lat_steady),
                     "during_refit_cutover": len(lat_refit),
                     "failed": errors[0]},
        "gate": {k: verdict.get(k) for k in
                 ("quality_candidate", "quality_baseline",
                  "quality_delta", "divergence", "nan_rate",
                  "shadow_rows")},
        "trigger": status.get("last_trigger"),
        "cycles": status.get("cycles"),
        "backend": jax.default_backend(),
    }


ADAPTIVE_DIM = 16
ADAPTIVE_CLASSES = 4
ADAPTIVE_FILLERS = 6          # zipf tail behind the hot 2-variant head
ADAPTIVE_CLIENTS = 6
ADAPTIVE_ROUNDS = 40          # lockstep rounds for the drain cadence


def bench_adaptive() -> dict:
    """SLO-adaptive serving (serving/variants.py + the continuous
    batcher): a zipf-weighted ramp over a 2-variant model (full f32 +
    quantized int8 behind one logical name), measuring

    - per-variant measured cost (ms/row) and the declared-cost ratio
      the selector trades on at equal SLO,
    - reply p99 ACROSS a forced variant flip (fast-burn injected, then
      cleared -> step_down, select, step_up on the timeline) with
      availability + zero cross-model replies over the whole run,
    - batcher occupancy: the same offered load driven in drain-cadence
      lockstep (every client waits for the whole round to drain — the
      old drain-then-block arrival shape) vs free-running continuous
      admission.

    CPU-honesty: on this container every engine thread timeshares the
    same core(s) and int8 matmuls run SLOWER than f32 (no MXU), so the
    cost/qps reduction is reported from the DECLARED TPU-relative
    costs while measured ms/row carries what this box actually did;
    the >=1x occupancy floor is the only claim asserted here."""
    import threading
    import urllib.error
    import urllib.request

    import jax

    from mmlspark_tpu.models.networks import build_network
    from mmlspark_tpu.models.tpu_model import TPUModel
    from mmlspark_tpu.serving import (
        HTTPSource, ModelZoo, ServingEngine, VariantSelector,
    )
    from mmlspark_tpu.serving.fleet import json_scoring_pipeline
    from mmlspark_tpu.stages.basic import Lambda

    rng = np.random.default_rng(11)
    x_warm = np.zeros((1, ADAPTIVE_DIM), np.float32)
    x_cal = rng.normal(size=(64, ADAPTIVE_DIM)).astype(np.float32)
    module = build_network({"type": "mlp", "features": [32],
                            "num_classes": ADAPTIVE_CLASSES})
    f32 = TPUModel.from_flax(
        module, module.init(jax.random.PRNGKey(0), x_warm),
        inputCol="features", outputCol="scores", batchSize=8)
    int8 = f32.quantize({"features": x_cal})

    zoo = ModelZoo(memory_probe=None)
    zoo.register_factory(
        "clf", "v1", lambda: json_scoring_pipeline(f32),
        metadata={"precision": "f32",
                  "warmup_example": {"features": x_warm}})
    zoo.register_factory(
        "clf_int8", "v1", lambda: json_scoring_pipeline(int8),
        metadata={"precision": "int8",
                  "warmup_example": {"features": x_warm}})

    def filler_stage(tag):
        def handle(table):
            return table.with_column(
                "reply", [{"model": tag} for _ in table["request"]])
        return Lambda.apply(handle)

    for i in range(ADAPTIVE_FILLERS):
        zoo.register_factory(f"f{i}", "v1",
                             (lambda i=i: filler_stage(f"f{i}")))

    class _BurnToggle:
        """The selector's fast-burn input, injectable on demand."""

        def __init__(self):
            self.burning = False
            self.alerts = self

        def active(self):
            if not self.burning:
                return []
            a = type("A", (), {})()
            a.rule, a.slo = "fast_burn", "latency"
            return [a]

    toggle = _BurnToggle()
    sel = VariantSelector(zoo, slo=toggle, decide_interval_s=0.1,
                          hold_s=1.0, pressure_limit=10_000)
    sel.declare("clf", ["clf", "clf_int8"], slo_ms=100.0,
                costs={"clf": 1.0, "clf_int8": 0.25})
    source = HTTPSource(port=0)
    engine = ServingEngine(source, zoo=zoo, variants=sel, batch_size=8,
                           max_wait_ms=2.0, workers=1, tracing=False,
                           slo=False).start()
    addr = source.address

    # zipf-weighted picks: the 2-variant head stays hot, fillers tail
    names = ["clf"] + [f"f{i}" for i in range(ADAPTIVE_FILLERS)]
    ranks = np.arange(1, len(names) + 1, dtype=np.float64)
    probs = 1.0 / ranks ** 1.2
    probs /= probs.sum()
    payload = json.dumps(
        {"features": rng.normal(size=ADAPTIVE_DIM).tolist()}).encode()
    lock = threading.Lock()
    wrong, failures = [], []

    def post_one(model):
        req = urllib.request.Request(
            addr, data=payload,
            headers={"Content-Type": "application/json",
                     "X-Model": model})
        t0 = time.perf_counter()
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                served = r.headers.get("X-Model", "")
                r.read()
            if model == "clf":
                if served not in ("clf@v1", "clf_int8@v1"):
                    with lock:
                        wrong.append(served)
            elif not served.startswith(model):
                with lock:
                    wrong.append((model, served))
        except Exception as e:  # noqa: BLE001 — availability metric
            with lock:
                failures.append(str(e))
        return (time.perf_counter() - t0) * 1e3

    def run_phase(n_per_client, lockstep):
        """ADAPTIVE_CLIENTS clients x n_per_client zipf requests.
        ``lockstep`` reproduces the drain-then-block cadence: nobody
        starts round i+1 until the whole round i drained."""
        lats: list = []
        picks = rng.choice(names, size=(ADAPTIVE_CLIENTS,
                                        n_per_client), p=probs)
        barrier = threading.Barrier(ADAPTIVE_CLIENTS)

        def client(c):
            out = []
            for i in range(n_per_client):
                if lockstep:
                    barrier.wait()
                out.append(post_one(str(picks[c][i])))
            with lock:
                lats.extend(out)

        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(ADAPTIVE_CLIENTS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        total = ADAPTIVE_CLIENTS * n_per_client
        return {"qps": round(total / wall, 1),
                "p50_ms": round(float(np.percentile(lats, 50)), 2),
                "p99_ms": round(float(np.percentile(lats, 99)), 2),
                "requests": total}

    try:
        for _ in range(4):                      # warm both rungs' path
            post_one("clf")
        # occupancy: drain-cadence lockstep vs continuous admission of
        # the SAME offered load
        drain = run_phase(ADAPTIVE_ROUNDS, lockstep=True)
        cont = run_phase(ADAPTIVE_ROUNDS, lockstep=False)
        occupancy_ratio = round(cont["qps"] / drain["qps"], 2)

        # steady f32, then the forced flip under continuous load
        steady = run_phase(20, lockstep=False)
        active_before = sel.status()["clf"]["active"]
        stop = threading.Event()
        flip_lats: list = []

        def hammer():
            while not stop.is_set():
                dt = post_one("clf")
                with lock:
                    flip_lats.append(dt)

        threads = [threading.Thread(target=hammer)
                   for _ in range(ADAPTIVE_CLIENTS)]
        for t in threads:
            t.start()
        toggle.burning = True
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            if sel.status()["clf"]["active"] != active_before:
                break
            time.sleep(0.05)
        flipped_to = sel.status()["clf"]["active"]
        time.sleep(1.0)              # degraded tier under load
        toggle.burning = False
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            if sel.status()["clf"]["active"] == active_before:
                break
            time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join()
        recovered = sel.status()["clf"]["active"] == active_before
        st = sel.status()["clf"]
        profiles = {}
        for v in st["variants"]:
            prof = sel._profiles[v["variant"]]
            measured = prof.ms_per_row(sel.window_s)
            profiles[v["variant"]] = {
                "declared_cost": (v["cost"]
                                  if v["cost_source"] == "declared"
                                  else None),
                "measured_ms_per_row": (round(measured, 4)
                                        if measured is not None
                                        else None),
                "p99_ms": v["p99_ms"],
                "cost_source": v["cost_source"],
            }
        events = [e.kind for e in sel.events]
    finally:
        engine.stop()
        zoo.close()

    usable_cores = len(os.sched_getaffinity(0))
    total_reqs = (drain["requests"] + cont["requests"]
                  + steady["requests"] + len(flip_lats) + 4)
    availability = 1.0 - len(failures) / max(1, total_reqs)
    return {
        "metric": "adaptive_occupancy_continuous_vs_drain",
        "value": occupancy_ratio,
        "unit": "x (free-running continuous admission qps vs "
                "drain-then-block lockstep cadence, same offered "
                "load)",
        "occupancy": {"drain_cadence": drain, "continuous": cont},
        "steady": steady,
        "forced_flip": {
            "flipped_to": flipped_to,
            "recovered_to_preferred": recovered,
            "p99_ms_across_flip": round(
                float(np.percentile(flip_lats, 99)), 2) if flip_lats
                else None,
            "requests_during_flip": len(flip_lats),
            "events": events,
        },
        "variant_profiles": profiles,
        "declared_cost_ratio_int8_vs_f32": 0.25,
        "availability": round(availability, 4),
        "wrong_replies": len(wrong),
        "zipf_models": len(names),
        "clients": ADAPTIVE_CLIENTS,
        "usable_cores": usable_cores,
        "honesty_note": (
            "int8 on this CPU container is SLOWER than f32 (no MXU; "
            "PR 10 measured ~0.19x), so the cost/qps reduction at "
            "equal SLO rides the DECLARED TPU-relative costs "
            "(0.25x); measured ms/row above records what this box "
            f"did on {usable_cores} timeshared core(s). The >=1x "
            "occupancy floor and the flip-window p99 are the "
            "hardware-independent claims"),
        "backend": jax.default_backend(),
    }


# scenario registry for --scenarios (cheap subsets of the full bench:
# the serving/lifecycle numbers are measurable on any backend, the
# training-throughput scenarios only mean anything on the TPU chip)
SCENARIOS = {
    "cifar": lambda: ("secondary_cifar", bench_cifar()),
    "resnet": lambda: ("secondary_resnet", bench_resnet()),
    "lm": lambda: ("secondary_lm", bench_lm()),
    "serving": lambda: ("secondary_serving", bench_serving()),
    "swap": lambda: ("secondary_swap", bench_swap()),
    "automl": lambda: ("secondary_automl", bench_automl()),
    "pipeline": lambda: ("secondary_pipeline", bench_pipeline()),
    "observability": lambda: ("secondary_observability",
                              bench_observability()),
    "quant": lambda: ("secondary_quant", bench_quant()),
    "coldstart": lambda: ("secondary_coldstart", bench_coldstart()),
    "ingress": lambda: ("secondary_ingress", bench_ingress()),
    "zoo": lambda: ("secondary_zoo", bench_zoo()),
    "sharded": lambda: ("secondary_sharded", bench_sharded()),
    "fleet_procs": lambda: ("secondary_fleet_procs",
                            bench_fleet_procs()),
    "fabric": lambda: ("secondary_fabric", bench_fabric()),
    "gbdt_dist": lambda: ("secondary_gbdt_dist", bench_gbdt_dist()),
    "ooc": lambda: ("secondary_ooc", bench_ooc()),
    "continuous": lambda: ("secondary_continuous",
                           bench_continuous()),
    "adaptive": lambda: ("secondary_adaptive", bench_adaptive()),
}


def main():
    import argparse
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--scenarios", default="all",
        help="comma list from {cifar,resnet,lm,higgs,serving,swap,"
             "automl,pipeline,observability,quant,coldstart,ingress,"
             "zoo,sharded,fleet_procs,fabric,gbdt_dist,ooc,continuous} "
             "or 'all' (the full flagship bench)")
    args = ap.parse_args()
    if args.scenarios != "all":
        if "sharded" in args.scenarios.split(",") and \
                os.environ.get("JAX_PLATFORMS", "") == "cpu":
            # the forced-host-device-count recipe must run BEFORE the
            # first backend use (jax.default_backend() below
            # initializes it); real accelerators keep their topology
            from mmlspark_tpu.utils.jax_compat import \
                set_cpu_device_count
            set_cpu_device_count(SHARDED_MESH_DEVICES)
        _enable_compile_cache()
        import jax
        out = {"backend": jax.default_backend(),
               "scenarios_run": sorted(args.scenarios.split(","))}
        for name in args.scenarios.split(","):
            name = name.strip()
            if name == "higgs":
                higgs, auc, hist_method = bench_higgs_gbdt()
                out["secondary"] = {
                    "metric": "higgs1m_gbdt_train_wall_clock",
                    "value": higgs[63]["wall_s"], "unit": "s",
                    "hist_method": hist_method,
                    "synthetic_holdout_auc": round(auc, 4),
                    "phases": higgs[63]["phases"],
                    "bin_path": higgs[63]["bin_path"],
                    "host_bin_63": higgs["host_bin_63"],
                    "max_bin_255": higgs[255],
                }
                continue
            if name not in SCENARIOS:
                raise SystemExit(f"unknown scenario {name!r}")
            key, result = SCENARIOS[name]()
            out[key] = result
        print(json.dumps(out))
        return
    _run_full()


def _run_full():
    _enable_compile_cache()
    measured = _measured_baselines()
    cifar = bench_cifar()
    resnet = bench_resnet()
    lm = bench_lm()
    higgs, higgs_auc, hist_method = bench_higgs_gbdt()
    higgs_wall = higgs[63]["wall_s"]
    serving = bench_serving()
    automl = bench_automl()
    pipeline = bench_pipeline()

    per_chip = cifar["imgs_per_sec_per_chip"]
    gbdt_base = measured.get("higgs1m_sklearn_hgb_wall_s")
    gbdt_source = "measured:sklearn_hist_gradient_boosting"
    if not gbdt_base:
        gbdt_base, gbdt_source = BASELINE_HIGGS_WALL_S, "constant:lightgbm_cpu"

    result = {
        "metric": "cifar10_convnet_train_imgs_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "imgs/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMGS_PER_SEC_PER_CHIP, 3),
        "feed": "device-resident",
        "secondary": {
            "metric": "higgs1m_gbdt_train_wall_clock",
            "value": round(higgs_wall, 1),
            "unit": "s",
            "vs_baseline": round(gbdt_base / higgs_wall, 3),
            "baseline_wall_s": gbdt_base,
            "baseline_source": gbdt_source,
            # a native-LightGBM wall on THIS machine is not measurable:
            # lightgbm is not in the image and the environment has no
            # network egress (pip resolves no distribution). The sklearn
            # HistGradientBoosting baseline above is measured HERE and
            # clearly labeled; docs/lightgbm.md's own claim is relative
            # ("10-30% faster than SparkML GBT"), not absolute.
            "vs_lightgbm": "unmeasurable:no_lightgbm_in_image_no_egress",
            # AUC of the synthetic separable logit, NOT real HIGGS model
            # quality (accuracy gates live in tests/test_benchmarks.py)
            "synthetic_holdout_auc": round(higgs_auc, 4),
            "hist_method": hist_method,
            "config": f"{HIGGS_N}x{HIGGS_F}, 63 leaves, 63 bins, 40 iters",
            "phases": higgs[63]["phases"],
            "bin_path": higgs[63]["bin_path"],
            "boost_chunk": higgs[63]["boost_chunk"],
            "host_bin_63": higgs["host_bin_63"],
            "max_bin_255": higgs[255],
        },
    }
    for key in ("tflops_per_sec_per_chip", "mfu"):
        if key in cifar:
            result[key] = cifar[key]
    resnet_entry = {
        "metric": "cifar10_resnet20_train_imgs_per_sec_per_chip",
        "value": round(resnet["imgs_per_sec_per_chip"], 1),
        "unit": "imgs/sec/chip",
    }
    for key in ("tflops_per_sec_per_chip", "mfu"):
        if key in resnet:
            resnet_entry[key] = resnet[key]
    result["secondary_resnet"] = resnet_entry
    lm_entry = {
        "metric": "lm2048x8_train_tokens_per_sec_per_chip",
        "value": round(lm["tokens_per_sec_per_chip"], 1),
        "unit": "tokens/sec/chip",
        "config": (f"dim {LM_SPEC['dim']}, depth {LM_SPEC['depth']}, "
                   f"seq {LM_SEQ}, vocab {LM_SPEC['vocab_size']}, "
                   f"flash attention, bf16"),
    }
    for key in ("tflops_per_sec_per_chip", "mfu"):
        if key in lm:
            lm_entry[key] = lm[key]
    result["secondary_lm"] = lm_entry
    result["secondary_serving"] = serving
    result["secondary_automl"] = automl
    result["secondary_pipeline"] = pipeline
    if measured.get("cifar_convnet_torch_cpu_imgs_per_sec"):
        result["cpu_measured_baseline_imgs_per_sec"] = measured[
            "cifar_convnet_torch_cpu_imgs_per_sec"]

    print(json.dumps(result))


if __name__ == "__main__":
    main()
