"""GENERATED smoke tests — python -m mmlspark_tpu.codegen."""


def test_assemblefeatures_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.automl.featurize import AssembleFeatures
    stage = AssembleFeatures()
    assert stage.uid.startswith("AssembleFeatures")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is AssembleFeatures
    assert clone.uid == stage.uid
    for p in AssembleFeatures.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_bestmodel_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.automl.tuning import BestModel
    stage = BestModel()
    assert stage.uid.startswith("BestModel")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is BestModel
    assert clone.uid == stage.uid
    for p in BestModel.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_cacher_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.basic import Cacher
    stage = Cacher()
    assert stage.uid.startswith("Cacher")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is Cacher
    assert clone.uid == stage.uid
    for p in Cacher.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_checkpointdata_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.basic import CheckpointData
    stage = CheckpointData()
    assert stage.uid.startswith("CheckpointData")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is CheckpointData
    assert clone.uid == stage.uid
    for p in CheckpointData.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_classbalancer_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.basic import ClassBalancer
    stage = ClassBalancer()
    assert stage.uid.startswith("ClassBalancer")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is ClassBalancer
    assert clone.uid == stage.uid
    for p in ClassBalancer.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_classbalancermodel_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.basic import ClassBalancerModel
    stage = ClassBalancerModel()
    assert stage.uid.startswith("ClassBalancerModel")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is ClassBalancerModel
    assert clone.uid == stage.uid
    for p in ClassBalancerModel.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_cleanmissingdata_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.dataprep import CleanMissingData
    stage = CleanMissingData()
    assert stage.uid.startswith("CleanMissingData")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is CleanMissingData
    assert clone.uid == stage.uid
    for p in CleanMissingData.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_cleanmissingdatamodel_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.dataprep import CleanMissingDataModel
    stage = CleanMissingDataModel()
    assert stage.uid.startswith("CleanMissingDataModel")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is CleanMissingDataModel
    assert clone.uid == stage.uid
    for p in CleanMissingDataModel.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_computemodelstatistics_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.automl.statistics import ComputeModelStatistics
    stage = ComputeModelStatistics()
    assert stage.uid.startswith("ComputeModelStatistics")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is ComputeModelStatistics
    assert clone.uid == stage.uid
    for p in ComputeModelStatistics.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_computeperinstancestatistics_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.automl.statistics import ComputePerInstanceStatistics
    stage = ComputePerInstanceStatistics()
    assert stage.uid.startswith("ComputePerInstanceStatistics")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is ComputePerInstanceStatistics
    assert clone.uid == stage.uid
    for p in ComputePerInstanceStatistics.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_countvectorizer_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.text import CountVectorizer
    stage = CountVectorizer()
    assert stage.uid.startswith("CountVectorizer")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is CountVectorizer
    assert clone.uid == stage.uid
    for p in CountVectorizer.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_countvectorizermodel_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.text import CountVectorizerModel
    stage = CountVectorizerModel()
    assert stage.uid.startswith("CountVectorizerModel")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is CountVectorizerModel
    assert clone.uid == stage.uid
    for p in CountVectorizerModel.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_custominputparser_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.io.http import CustomInputParser
    stage = CustomInputParser()
    assert stage.uid.startswith("CustomInputParser")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is CustomInputParser
    assert clone.uid == stage.uid
    for p in CustomInputParser.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_customoutputparser_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.io.http import CustomOutputParser
    stage = CustomOutputParser()
    assert stage.uid.startswith("CustomOutputParser")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is CustomOutputParser
    assert clone.uid == stage.uid
    for p in CustomOutputParser.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_dataconversion_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.dataprep import DataConversion
    stage = DataConversion()
    assert stage.uid.startswith("DataConversion")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is DataConversion
    assert clone.uid == stage.uid
    for p in DataConversion.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_dropcolumns_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.basic import DropColumns
    stage = DropColumns()
    assert stage.uid.startswith("DropColumns")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is DropColumns
    assert clone.uid == stage.uid
    for p in DropColumns.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_dynamicminibatchtransformer_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.io.minibatch import DynamicMiniBatchTransformer
    stage = DynamicMiniBatchTransformer()
    assert stage.uid.startswith("DynamicMiniBatchTransformer")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is DynamicMiniBatchTransformer
    assert clone.uid == stage.uid
    for p in DynamicMiniBatchTransformer.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_ensemblebykey_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.dataprep import EnsembleByKey
    stage = EnsembleByKey()
    assert stage.uid.startswith("EnsembleByKey")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is EnsembleByKey
    assert clone.uid == stage.uid
    for p in EnsembleByKey.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_explode_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.basic import Explode
    stage = Explode()
    assert stage.uid.startswith("Explode")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is Explode
    assert clone.uid == stage.uid
    for p in Explode.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_fastvectorassembler_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.dataprep import FastVectorAssembler
    stage = FastVectorAssembler()
    assert stage.uid.startswith("FastVectorAssembler")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is FastVectorAssembler
    assert clone.uid == stage.uid
    for p in FastVectorAssembler.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_featurize_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.automl.featurize import Featurize
    stage = Featurize()
    assert stage.uid.startswith("Featurize")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is Featurize
    assert clone.uid == stage.uid
    for p in Featurize.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_featurizemodel_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.automl.featurize import FeaturizeModel
    stage = FeaturizeModel()
    assert stage.uid.startswith("FeaturizeModel")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is FeaturizeModel
    assert clone.uid == stage.uid
    for p in FeaturizeModel.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_findbestmodel_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.automl.tuning import FindBestModel
    stage = FindBestModel()
    assert stage.uid.startswith("FindBestModel")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is FindBestModel
    assert clone.uid == stage.uid
    for p in FindBestModel.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_fixedminibatchtransformer_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.io.minibatch import FixedMiniBatchTransformer
    stage = FixedMiniBatchTransformer()
    assert stage.uid.startswith("FixedMiniBatchTransformer")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is FixedMiniBatchTransformer
    assert clone.uid == stage.uid
    for p in FixedMiniBatchTransformer.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_flattenbatch_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.io.minibatch import FlattenBatch
    stage = FlattenBatch()
    assert stage.uid.startswith("FlattenBatch")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is FlattenBatch
    assert clone.uid == stage.uid
    for p in FlattenBatch.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_httptransformer_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.io.http import HTTPTransformer
    stage = HTTPTransformer()
    assert stage.uid.startswith("HTTPTransformer")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is HTTPTransformer
    assert clone.uid == stage.uid
    for p in HTTPTransformer.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_hashingtf_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.text import HashingTF
    stage = HashingTF()
    assert stage.uid.startswith("HashingTF")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is HashingTF
    assert clone.uid == stage.uid
    for p in HashingTF.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_idf_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.text import IDF
    stage = IDF()
    assert stage.uid.startswith("IDF")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is IDF
    assert clone.uid == stage.uid
    for p in IDF.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_idfmodel_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.text import IDFModel
    stage = IDFModel()
    assert stage.uid.startswith("IDFModel")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is IDFModel
    assert clone.uid == stage.uid
    for p in IDFModel.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_imagefeaturizer_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.featurizer import ImageFeaturizer
    stage = ImageFeaturizer()
    assert stage.uid.startswith("ImageFeaturizer")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is ImageFeaturizer
    assert clone.uid == stage.uid
    for p in ImageFeaturizer.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_imagesetaugmenter_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.image import ImageSetAugmenter
    stage = ImageSetAugmenter()
    assert stage.uid.startswith("ImageSetAugmenter")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is ImageSetAugmenter
    assert clone.uid == stage.uid
    for p in ImageSetAugmenter.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_imagetransformer_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.image import ImageTransformer
    stage = ImageTransformer()
    assert stage.uid.startswith("ImageTransformer")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is ImageTransformer
    assert clone.uid == stage.uid
    for p in ImageTransformer.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_jsoninputparser_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.io.http import JSONInputParser
    stage = JSONInputParser()
    assert stage.uid.startswith("JSONInputParser")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is JSONInputParser
    assert clone.uid == stage.uid
    for p in JSONInputParser.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_jsonoutputparser_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.io.http import JSONOutputParser
    stage = JSONOutputParser()
    assert stage.uid.startswith("JSONOutputParser")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is JSONOutputParser
    assert clone.uid == stage.uid
    for p in JSONOutputParser.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_lambda_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.basic import Lambda
    stage = Lambda()
    assert stage.uid.startswith("Lambda")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is Lambda
    assert clone.uid == stage.uid
    for p in Lambda.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_multicolumnadapter_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.dataprep import MultiColumnAdapter
    stage = MultiColumnAdapter()
    assert stage.uid.startswith("MultiColumnAdapter")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is MultiColumnAdapter
    assert clone.uid == stage.uid
    for p in MultiColumnAdapter.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_multicolumnadaptermodel_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.dataprep import MultiColumnAdapterModel
    stage = MultiColumnAdapterModel()
    assert stage.uid.startswith("MultiColumnAdapterModel")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is MultiColumnAdapterModel
    assert clone.uid == stage.uid
    for p in MultiColumnAdapterModel.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_ngram_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.text import NGram
    stage = NGram()
    assert stage.uid.startswith("NGram")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is NGram
    assert clone.uid == stage.uid
    for p in NGram.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_partitionconsolidator_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.serving.fleet import PartitionConsolidator
    stage = PartitionConsolidator()
    assert stage.uid.startswith("PartitionConsolidator")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is PartitionConsolidator
    assert clone.uid == stage.uid
    for p in PartitionConsolidator.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_partitionsample_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.dataprep import PartitionSample
    stage = PartitionSample()
    assert stage.uid.startswith("PartitionSample")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is PartitionSample
    assert clone.uid == stage.uid
    for p in PartitionSample.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_pipeline_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.core.stage import Pipeline
    stage = Pipeline()
    assert stage.uid.startswith("Pipeline")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is Pipeline
    assert clone.uid == stage.uid
    for p in Pipeline.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_pipelinemodel_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.core.stage import PipelineModel
    stage = PipelineModel()
    assert stage.uid.startswith("PipelineModel")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is PipelineModel
    assert clone.uid == stage.uid
    for p in PipelineModel.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_renamecolumn_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.basic import RenameColumn
    stage = RenameColumn()
    assert stage.uid.startswith("RenameColumn")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is RenameColumn
    assert clone.uid == stage.uid
    for p in RenameColumn.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_renameto_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.text import RenameTo
    stage = RenameTo()
    assert stage.uid.startswith("RenameTo")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is RenameTo
    assert clone.uid == stage.uid
    for p in RenameTo.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_repartition_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.basic import Repartition
    stage = Repartition()
    assert stage.uid.startswith("Repartition")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is Repartition
    assert clone.uid == stage.uid
    for p in Repartition.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_selectcolumns_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.basic import SelectColumns
    stage = SelectColumns()
    assert stage.uid.startswith("SelectColumns")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is SelectColumns
    assert clone.uid == stage.uid
    for p in SelectColumns.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_simplehttptransformer_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.io.http import SimpleHTTPTransformer
    stage = SimpleHTTPTransformer()
    assert stage.uid.startswith("SimpleHTTPTransformer")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is SimpleHTTPTransformer
    assert clone.uid == stage.uid
    for p in SimpleHTTPTransformer.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_standardscaler_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.dataprep import StandardScaler
    stage = StandardScaler()
    assert stage.uid.startswith("StandardScaler")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is StandardScaler
    assert clone.uid == stage.uid
    for p in StandardScaler.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_standardscalermodel_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.dataprep import StandardScalerModel
    stage = StandardScalerModel()
    assert stage.uid.startswith("StandardScalerModel")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is StandardScalerModel
    assert clone.uid == stage.uid
    for p in StandardScalerModel.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_stopwordsremover_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.text import StopWordsRemover
    stage = StopWordsRemover()
    assert stage.uid.startswith("StopWordsRemover")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is StopWordsRemover
    assert clone.uid == stage.uid
    for p in StopWordsRemover.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_summarizedata_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.dataprep import SummarizeData
    stage = SummarizeData()
    assert stage.uid.startswith("SummarizeData")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is SummarizeData
    assert clone.uid == stage.uid
    for p in SummarizeData.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_tpuboostclassificationmodel_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.gbdt.estimators import TPUBoostClassificationModel
    stage = TPUBoostClassificationModel()
    assert stage.uid.startswith("TPUBoostClassificationModel")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is TPUBoostClassificationModel
    assert clone.uid == stage.uid
    for p in TPUBoostClassificationModel.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_tpuboostclassifier_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.gbdt.estimators import TPUBoostClassifier
    stage = TPUBoostClassifier()
    assert stage.uid.startswith("TPUBoostClassifier")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is TPUBoostClassifier
    assert clone.uid == stage.uid
    for p in TPUBoostClassifier.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_tpuboostregressionmodel_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.gbdt.estimators import TPUBoostRegressionModel
    stage = TPUBoostRegressionModel()
    assert stage.uid.startswith("TPUBoostRegressionModel")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is TPUBoostRegressionModel
    assert clone.uid == stage.uid
    for p in TPUBoostRegressionModel.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_tpuboostregressor_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.gbdt.estimators import TPUBoostRegressor
    stage = TPUBoostRegressor()
    assert stage.uid.startswith("TPUBoostRegressor")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is TPUBoostRegressor
    assert clone.uid == stage.uid
    for p in TPUBoostRegressor.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_tpulearner_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.models.learner import TPULearner
    stage = TPULearner()
    assert stage.uid.startswith("TPULearner")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is TPULearner
    assert clone.uid == stage.uid
    for p in TPULearner.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_tpulinearregression_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.models.linear import TPULinearRegression
    stage = TPULinearRegression()
    assert stage.uid.startswith("TPULinearRegression")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is TPULinearRegression
    assert clone.uid == stage.uid
    for p in TPULinearRegression.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_tpulinearregressionmodel_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.models.linear import TPULinearRegressionModel
    stage = TPULinearRegressionModel()
    assert stage.uid.startswith("TPULinearRegressionModel")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is TPULinearRegressionModel
    assert clone.uid == stage.uid
    for p in TPULinearRegressionModel.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_tpulogisticregression_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.models.linear import TPULogisticRegression
    stage = TPULogisticRegression()
    assert stage.uid.startswith("TPULogisticRegression")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is TPULogisticRegression
    assert clone.uid == stage.uid
    for p in TPULogisticRegression.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_tpulogisticregressionmodel_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.models.linear import TPULogisticRegressionModel
    stage = TPULogisticRegressionModel()
    assert stage.uid.startswith("TPULogisticRegressionModel")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is TPULogisticRegressionModel
    assert clone.uid == stage.uid
    for p in TPULogisticRegressionModel.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_tpumodel_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.models.tpu_model import TPUModel
    stage = TPUModel()
    assert stage.uid.startswith("TPUModel")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is TPUModel
    assert clone.uid == stage.uid
    for p in TPUModel.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_textfeaturizer_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.text import TextFeaturizer
    stage = TextFeaturizer()
    assert stage.uid.startswith("TextFeaturizer")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is TextFeaturizer
    assert clone.uid == stage.uid
    for p in TextFeaturizer.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_textfeaturizermodel_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.text import TextFeaturizerModel
    stage = TextFeaturizerModel()
    assert stage.uid.startswith("TextFeaturizerModel")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is TextFeaturizerModel
    assert clone.uid == stage.uid
    for p in TextFeaturizerModel.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_textpreprocessor_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.basic import TextPreprocessor
    stage = TextPreprocessor()
    assert stage.uid.startswith("TextPreprocessor")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is TextPreprocessor
    assert clone.uid == stage.uid
    for p in TextPreprocessor.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_timeintervalminibatchtransformer_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.io.minibatch import TimeIntervalMiniBatchTransformer
    stage = TimeIntervalMiniBatchTransformer()
    assert stage.uid.startswith("TimeIntervalMiniBatchTransformer")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is TimeIntervalMiniBatchTransformer
    assert clone.uid == stage.uid
    for p in TimeIntervalMiniBatchTransformer.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_timer_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.basic import Timer
    stage = Timer()
    assert stage.uid.startswith("Timer")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is Timer
    assert clone.uid == stage.uid
    for p in Timer.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_timermodel_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.basic import TimerModel
    stage = TimerModel()
    assert stage.uid.startswith("TimerModel")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is TimerModel
    assert clone.uid == stage.uid
    for p in TimerModel.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_tokenizer_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.text import Tokenizer
    stage = Tokenizer()
    assert stage.uid.startswith("Tokenizer")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is Tokenizer
    assert clone.uid == stage.uid
    for p in Tokenizer.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_trainclassifier_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.automl.train import TrainClassifier
    stage = TrainClassifier()
    assert stage.uid.startswith("TrainClassifier")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is TrainClassifier
    assert clone.uid == stage.uid
    for p in TrainClassifier.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_trainregressor_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.automl.train import TrainRegressor
    stage = TrainRegressor()
    assert stage.uid.startswith("TrainRegressor")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is TrainRegressor
    assert clone.uid == stage.uid
    for p in TrainRegressor.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_trainedclassifiermodel_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.automl.train import TrainedClassifierModel
    stage = TrainedClassifierModel()
    assert stage.uid.startswith("TrainedClassifierModel")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is TrainedClassifierModel
    assert clone.uid == stage.uid
    for p in TrainedClassifierModel.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_trainedregressormodel_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.automl.train import TrainedRegressorModel
    stage = TrainedRegressorModel()
    assert stage.uid.startswith("TrainedRegressorModel")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is TrainedRegressorModel
    assert clone.uid == stage.uid
    for p in TrainedRegressorModel.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_tunehyperparameters_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.automl.tuning import TuneHyperparameters
    stage = TuneHyperparameters()
    assert stage.uid.startswith("TuneHyperparameters")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is TuneHyperparameters
    assert clone.uid == stage.uid
    for p in TuneHyperparameters.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_tunehyperparametersmodel_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.automl.tuning import TuneHyperparametersModel
    stage = TuneHyperparametersModel()
    assert stage.uid.startswith("TuneHyperparametersModel")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is TuneHyperparametersModel
    assert clone.uid == stage.uid
    for p in TuneHyperparametersModel.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_udftransformer_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.basic import UDFTransformer
    stage = UDFTransformer()
    assert stage.uid.startswith("UDFTransformer")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is UDFTransformer
    assert clone.uid == stage.uid
    for p in UDFTransformer.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_unrollimage_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.image import UnrollImage
    stage = UnrollImage()
    assert stage.uid.startswith("UnrollImage")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is UnrollImage
    assert clone.uid == stage.uid
    for p in UnrollImage.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_valueindexer_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.dataprep import ValueIndexer
    stage = ValueIndexer()
    assert stage.uid.startswith("ValueIndexer")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is ValueIndexer
    assert clone.uid == stage.uid
    for p in ValueIndexer.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)


def test_valueindexermodel_smoke():
    """GENERATED — do not edit (ref: codegen PySparkWrapperTest)."""
    from mmlspark_tpu.stages.dataprep import ValueIndexerModel
    stage = ValueIndexerModel()
    assert stage.uid.startswith("ValueIndexerModel")
    stage.explain_params()
    clone = stage.copy()
    assert type(clone) is ValueIndexerModel
    assert clone.uid == stage.uid
    for p in ValueIndexerModel.params():
        if p.has_default and not p.is_complex:
            assert clone.get(p) == stage.get(p)
