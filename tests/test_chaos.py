"""Chaos-injection suite: the fleet's availability properties under
injected faults (Basiri et al., *Chaos Engineering* — verify the
property by injecting the faults that threaten it; Dean & Barroso,
*The Tail at Scale* — failover + circuit breaking bound the damage a
dead or stalled replica can do).

All faults are seeded and deterministic (see
mmlspark_tpu/testing/chaos.py); nothing here depends on wall-clock
beyond generous upper bounds, so the suite runs in tier-1 under the
``chaos`` marker.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from mmlspark_tpu.serving import CanaryPolicy, ServingFleet, ServingUnavailable
from mmlspark_tpu.serving.server import serve_model
from mmlspark_tpu.stages.basic import Lambda
from mmlspark_tpu.testing.chaos import (
    ChaosError, FaultInjector, PoisonedModel, StalledWarmupModel,
)
from mmlspark_tpu.utils.resilience import CircuitBreaker

pytestmark = pytest.mark.chaos


def echo_pipeline(version=None):
    def handle(table):
        reply = [{"echo": json.loads(r["entity"].decode())["x"]}
                 for r in table["request"]]
        if version is not None:
            for r in reply:
                r["v"] = version
        return table.with_column("reply", reply)
    return Lambda.apply(handle)


def _post(addr, payload, timeout=5.0):
    req = urllib.request.Request(
        addr, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


class TestFaultInjectorDeterminism:
    def test_same_seed_same_decisions(self):
        a = FaultInjector(seed=42, error_rate=0.3, drop_rate=0.2)
        b = FaultInjector(seed=42, error_rate=0.3, drop_rate=0.2)
        keys = [json.dumps({"x": i}).encode() for i in range(200)]
        assert [a.decide("error", k) for k in keys] == \
               [b.decide("error", k) for k in keys]
        assert [a.decide("drop", k) for k in keys] == \
               [b.decide("drop", k) for k in keys]
        # the rate is actually realized (hash uniformity sanity)
        frac = sum(a.decide("error", k) for k in keys) / len(keys)
        assert 0.15 < frac < 0.45

    def test_different_seed_different_decisions(self):
        keys = [json.dumps({"x": i}).encode() for i in range(200)]
        a = FaultInjector(seed=1, error_rate=0.3)
        b = FaultInjector(seed=2, error_rate=0.3)
        assert [a.decide("error", k) for k in keys] != \
               [b.decide("error", k) for k in keys]

    def test_decisions_independent_of_batching(self):
        # the same request key gets the same fate no matter how the
        # engine batched it — the property that makes poison-row
        # isolation deterministic under retry
        inj = FaultInjector(seed=7, error_rate=0.5)
        k = json.dumps({"x": 3}).encode()
        assert len({inj.decide("error", k) for _ in range(10)}) == 1


class TestInjectedFaults:
    def test_injected_errors_500_only_the_poisoned_rows(self):
        inj = FaultInjector(seed=11, error_rate=0.3)
        engine = serve_model(inj.wrap(echo_pipeline()), port=19400,
                             batch_size=8)
        try:
            results = {}
            for i in range(20):
                payload = {"x": i}
                try:
                    results[i] = _post(engine.source.address, payload)[1]
                except urllib.error.HTTPError as e:
                    results[i] = e.code
            expect_poison = {
                i for i in range(20)
                if inj.decide("error", json.dumps({"x": i}).encode())}
            assert expect_poison, "seed 11 should poison some of 0..19"
            assert expect_poison != set(range(20))
            for i in range(20):
                if i in expect_poison:
                    assert results[i] == 500, (i, results[i])
                else:
                    assert results[i] == {"echo": i}, (i, results[i])
            assert inj.injected_errors > 0
        finally:
            engine.stop()

    def test_injected_drops_500_only_the_dropped_rows(self):
        inj = FaultInjector(seed=5, drop_rate=0.3)
        engine = serve_model(inj.wrap(echo_pipeline()), port=19410,
                             batch_size=8)
        try:
            dropped, ok = 0, 0
            for i in range(20):
                expect_drop = inj.decide(
                    "drop", json.dumps({"x": i}).encode())
                try:
                    status, body = _post(engine.source.address, {"x": i})
                    assert not expect_drop and body == {"echo": i}
                    ok += 1
                except urllib.error.HTTPError as e:
                    assert expect_drop and e.code == 500
                    dropped += 1
            assert dropped > 0 and ok > 0
            assert inj.injected_drops == dropped
        finally:
            engine.stop()

    def test_injected_latency_slows_the_batch(self):
        inj = FaultInjector(seed=3, latency_s=0.3, latency_rate=1.0)
        engine = serve_model(inj.wrap(echo_pipeline()), port=19420,
                             batch_size=8)
        try:
            t0 = time.perf_counter()
            status, body = _post(engine.source.address, {"x": 1})
            dt = time.perf_counter() - t0
            assert status == 200 and body == {"echo": 1}
            assert dt >= 0.3
            assert inj.injected_latency_rows >= 1
        finally:
            engine.stop()

    @pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning")
    def test_worker_kill_supervisor_restarts_and_recovers(self):
        inj = FaultInjector(seed=1)
        engine = serve_model(inj.wrap(echo_pipeline()), port=19430,
                             batch_size=4)
        try:
            assert _post(engine.source.address, {"x": 0})[1] == {"echo": 0}
            inj.arm_worker_kill(1)
            # this request's worker dies mid-batch; the client times out
            with pytest.raises(Exception):
                _post(engine.source.address, {"x": 1}, timeout=1.0)
            deadline = time.time() + 5
            while engine.workers_restarted == 0 and time.time() < deadline:
                time.sleep(0.05)
            assert engine.workers_restarted >= 1
            assert engine.is_alive()
            # service recovered: the restarted worker drains new requests
            assert _post(engine.source.address, {"x": 2})[1] == {"echo": 2}
            assert inj.worker_kills_fired == 1
        finally:
            engine.stop()


class TestFleetAvailability:
    def test_99pct_availability_with_engine_killed_mid_load(self):
        """The acceptance drill: 1 of 3 engines hard-killed mid-load
        under concurrent clients — >=99% of all requests (in-flight and
        subsequent) succeed via circuit-breaking failover."""
        fleet = ServingFleet(echo_pipeline(), n_engines=3,
                             base_port=19500, batch_size=8, workers=1,
                             failure_threshold=2, breaker_cooldown=30.0)
        n_clients, per_client = 6, 30
        kill_after = 30            # requests completed before the kill
        results = {}
        completed = threading.Event()
        count_lock = threading.Lock()
        done_count = [0]

        def client(cid):
            for j in range(per_client):
                key = cid * per_client + j
                try:
                    body = fleet.post({"x": key}, timeout=5.0)
                    results[key] = (body == {"echo": key})
                except Exception:  # noqa: BLE001 — availability metric
                    results[key] = False
                with count_lock:
                    done_count[0] += 1
                    if done_count[0] >= kill_after:
                        completed.set()

        try:
            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(n_clients)]
            for t in threads:
                t.start()
            assert completed.wait(timeout=30)
            FaultInjector.kill_engine(fleet, 1)     # mid-load crash
            for t in threads:
                t.join(timeout=60)
            assert not any(t.is_alive() for t in threads)
        finally:
            fleet.stop_all()
        total = n_clients * per_client
        successes = sum(results.values())
        assert len(results) == total
        assert successes / total >= 0.99, (
            f"availability {successes}/{total} under 1-of-3 engine kill")
        # the dead engine's circuit opened: failures stopped burning time
        assert fleet.breakers[1].state == CircuitBreaker.OPEN
        c = fleet.counters()
        assert c["transport_errors"] >= 1

    def test_stalled_engine_bounded_timeout_waits(self):
        """A STALLED engine (accepts, never replies) is the expensive
        failure: clients burn their full timeout against it. The circuit
        must open after ``failure_threshold`` timeouts, and no single
        request may wait out the client timeout against the dead engine
        more than once (its failover attempt answers)."""
        client_timeout = 1.0
        fleet = ServingFleet(echo_pipeline(), n_engines=3,
                             base_port=19520, batch_size=8,
                             failure_threshold=2, breaker_cooldown=60.0)
        durations = []
        try:
            for i in range(5):      # warm + deterministic rotation
                assert fleet.post({"x": i})["echo"] == i
            FaultInjector.stall_engine(fleet, 0)
            for i in range(30):
                t0 = time.perf_counter()
                body = fleet.post({"x": 100 + i}, timeout=client_timeout)
                durations.append(time.perf_counter() - t0)
                assert body == {"echo": 100 + i}
        finally:
            fleet.stop_all()
        # every request succeeded; none paid the stall timeout twice
        assert max(durations) < 2 * client_timeout
        # once the circuit opened (<= threshold timeout-burns), the
        # stalled engine stopped costing anyone anything
        slow = [d for d in durations if d > 0.9 * client_timeout]
        assert len(slow) <= 2, (
            f"{len(slow)} requests burned a timeout on the stalled "
            f"engine; circuit should have opened after 2")
        assert fleet.breakers[0].state == CircuitBreaker.OPEN

    def test_shedding_503_retry_after_then_recovery(self):
        """Overfill the bounded parked-request table: extra load is shed
        with 503 + Retry-After instead of queuing unboundedly, and the
        engine returns to normal service once drained."""
        gate = threading.Event()

        def gated(table):
            gate.wait(10)
            return table.with_column(
                "reply", [{"ok": 1} for _ in table["request"]])

        fleet = ServingFleet(Lambda.apply(gated), n_engines=1,
                             base_port=19540, batch_size=1,
                             max_parked=3)
        addr = fleet.addresses[0]
        codes, retry_afters = [], []
        lock = threading.Lock()

        def raw_post():
            req = urllib.request.Request(
                addr, data=b'{"x": 0}',
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=15) as r:
                    with lock:
                        codes.append(r.status)
            except urllib.error.HTTPError as e:
                with lock:
                    codes.append(e.code)
                    retry_afters.append(e.headers.get("Retry-After"))

        try:
            threads = [threading.Thread(target=raw_post)
                       for _ in range(10)]
            for t in threads:
                t.start()
            deadline = time.time() + 5
            while time.time() < deadline and \
                    fleet.engines[0].source.requests_rejected == 0:
                time.sleep(0.02)
            gate.set()              # drain
            for t in threads:
                t.join(timeout=30)
            shed = [c for c in codes if c == 503]
            served = [c for c in codes if c == 200]
            assert shed, f"expected shedding, got {codes}"
            assert served, f"expected some service, got {codes}"
            assert all(ra is not None and int(ra) >= 1
                       for ra in retry_afters)
            assert fleet.counters()["rejected"] == len(shed)
            # recovery: drained engine serves normally again
            status, body = _post(addr, {"x": 1})
            assert status == 200 and body == {"ok": 1}
        finally:
            fleet.stop_all()

    def test_hedged_request_beats_slow_replica(self):
        """Tail-at-Scale hedging: when one replica turns slow, a hedge
        fired after the observed latency percentile answers from a fast
        replica well before the slow one would."""
        inj = FaultInjector(seed=9, latency_s=1.5, latency_rate=1.0)
        fleet = ServingFleet(echo_pipeline(), n_engines=2,
                             base_port=19560, batch_size=8,
                             hedge_percentile=95, hedge_min_s=0.05)
        try:
            for i in range(20):     # prime the latency window (fast)
                assert fleet.post({"x": i})["echo"] == i
            # engine 0 turns slow (still alive, still answers — just
            # pathologically late)
            fleet.engines[0].pipeline = inj.wrap(echo_pipeline())
            t_slow = []
            for i in range(6):
                t0 = time.perf_counter()
                assert fleet.post({"x": 100 + i}, timeout=10.0)[
                    "echo"] == 100 + i
                t_slow.append(time.perf_counter() - t0)
            assert fleet.hedged_requests >= 1
            # every request beat the 1.5s injected latency via its hedge
            assert max(t_slow) < 1.4, t_slow
        finally:
            fleet.stop_all()

    def test_all_engines_down_raises_typed_error(self):
        fleet = ServingFleet(echo_pipeline(), n_engines=2,
                             base_port=19580, batch_size=4,
                             failure_threshold=1, breaker_cooldown=30.0)
        try:
            assert fleet.post({"x": 1})["echo"] == 1
            FaultInjector.kill_engine(fleet, 0)
            FaultInjector.kill_engine(fleet, 1)
            with pytest.raises(ServingUnavailable) as ei:
                fleet.post({"x": 2}, timeout=2.0)
            # the attempt log names every engine tried
            assert len(ei.value.attempts) >= 1
            assert all("address" in a and "error" in a
                       for a in ei.value.attempts)
            # subsequent calls fail FAST (circuits open -> last-resort
            # probe against one engine, not a full sweep)
            t0 = time.perf_counter()
            with pytest.raises(ServingUnavailable):
                fleet.post({"x": 3}, timeout=2.0)
            assert time.perf_counter() - t0 < 2.0
            c = fleet.counters()
            assert c["transport_errors"] >= 2
        finally:
            fleet.stop_all()


class TestChaosWrapperUnit:
    def test_wrap_raises_chaos_error_for_poisoned_batch(self):
        from mmlspark_tpu.core.table import DataTable
        from mmlspark_tpu.io.http import HTTPSchema
        inj = FaultInjector(seed=13, error_rate=1.0)
        wrapped = inj.wrap(echo_pipeline())
        table = DataTable({
            "id": ["a"],
            "request": [HTTPSchema.request("/", "POST", b'{"x": 1}')]})
        with pytest.raises(ChaosError):
            wrapped.transform(table)


def _fleet_load(fleet, n_clients, per_client, results, timeout=5.0):
    """Spray the fleet from n_clients threads; record per-request
    (ok, version) into ``results``. Returns the started threads."""
    def client(cid):
        for j in range(per_client):
            key = cid * per_client + j
            try:
                body = fleet.post({"x": key}, timeout=timeout)
                results[key] = (body.get("echo") == key, body.get("v"))
            except Exception:  # noqa: BLE001 — availability metric
                results[key] = (False, None)
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_clients)]
    for t in threads:
        t.start()
    return threads


class TestRollingSwapChaos:
    """The model-lifecycle acceptance drills: a fleet under seeded load
    completes a rolling swap with >=99% availability and never serves a
    mixed-version reply batch; a poisoned canary auto-rolls-back
    without breaching the error floor; a stalled warmup and an engine
    killed mid-swap roll back instead of wedging the rollout."""

    def test_rolling_swap_under_load_99pct_availability(self):
        fleet = ServingFleet(echo_pipeline("v1"), n_engines=3,
                             base_port=19600, batch_size=8, workers=1,
                             max_wait_ms=2.0, version="v1",
                             failure_threshold=3, breaker_cooldown=30.0)
        n_clients, per_client = 6, 40
        results = {}
        try:
            threads = _fleet_load(fleet, n_clients, per_client, results)
            time.sleep(0.2)          # load established before the swap
            report = fleet.rolling_swap(
                echo_pipeline("v2"), "v2",
                policy=CanaryPolicy(fraction=0.5, min_batches=3,
                                    decision_timeout_s=20))
            for t in threads:
                t.join(timeout=60)
            assert not any(t.is_alive() for t in threads)
            assert report["ok"], report
            assert report["completed"] == 3
            # every engine cut over; post-swap traffic is all-new-version
            for e in fleet.engines:
                assert e.model_version == "v2"
                assert e.swap_state == "idle"
            post = fleet.post({"x": -1})
            assert post == {"echo": -1, "v": "v2"}
            c = fleet.counters()
            assert c["swaps_completed"] == 3
            assert c["swaps_rolled_back"] == 0
            agg = fleet.metrics()["aggregate"]
            assert agg["model_versions"] == ["v2", "v2", "v2"]
        finally:
            fleet.stop_all()
        total = n_clients * per_client
        ok = sum(v[0] for v in results.values())
        assert len(results) == total
        assert ok / total >= 0.99, f"availability {ok}/{total}"
        # replies only ever carry a real version — each batch executed
        # wholly on the handle it was built with
        assert {v for _, v in results.values() if v} <= {"v1", "v2"}

    def test_swap_zero_steady_state_recompiles(self):
        """The warmup-before-cutover contract, measured through the
        models' own trace counters: after the incoming model's bucket
        warmup (inside the swap, off the hot path), serving across and
        beyond the swap adds ZERO jit cache misses."""
        import jax
        from mmlspark_tpu.models.networks import build_network
        from mmlspark_tpu.models.tpu_model import TPUModel
        from mmlspark_tpu.serving.fleet import json_scoring_pipeline
        import numpy as np

        module = build_network({"type": "mlp", "features": [16],
                                "num_classes": 4})
        x0 = np.zeros((1, 8), np.float32)

        def make_model(seed):
            weights = {"params": module.init(
                jax.random.PRNGKey(seed), x0)["params"]}
            return TPUModel(
                modelFn=lambda w, ins: module.apply(
                    {"params": w["params"]}, list(ins.values())[0]),
                weights=weights, inputCol="features",
                outputCol="scores", batchSize=16)

        m1, m2 = make_model(0), make_model(1)
        m1.warmup({"features": x0})
        fleet = ServingFleet(json_scoring_pipeline(m1), n_engines=2,
                             base_port=19620, batch_size=16,
                             max_wait_ms=2.0)
        payload = {"features": [0.1] * 8}
        results = {}
        try:
            for _ in range(8):       # steady state on v1
                assert "prediction" in fleet.post(payload)
            misses_v1 = m1.jit_cache_misses

            def client(cid):
                for j in range(30):
                    try:
                        results[(cid, j)] = "prediction" in fleet.post(
                            payload, timeout=10)
                    except Exception:  # noqa: BLE001
                        results[(cid, j)] = False
            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            report = fleet.rolling_swap(
                json_scoring_pipeline(m2), "v2",
                warmup_example={"features": x0},
                policy=CanaryPolicy(fraction=0.5, min_batches=2,
                                    decision_timeout_s=30))
            for t in threads:
                t.join(timeout=60)
            assert report["ok"], report
            misses_v2 = m2.jit_cache_misses
            # post-swap steady state: more traffic, zero new compiles
            # on either model
            for _ in range(8):
                assert "prediction" in fleet.post(payload)
            assert m1.jit_cache_misses == misses_v1, \
                "old model recompiled during the swap"
            assert m2.jit_cache_misses == misses_v2, \
                "new model compiled on the hot path after its warmup"
            assert misses_v2 > 0    # warmup really compiled the buckets
        finally:
            fleet.stop_all()
        ok = sum(results.values())
        assert ok / len(results) >= 0.99

    def test_poisoned_canary_auto_rolls_back_under_error_floor(self):
        """A canary that passes warmup but errors on live batches must
        roll back via the breach detector while clients stay whole:
        failed canary batches rescue onto the stable version."""
        fleet = ServingFleet(echo_pipeline("v1"), n_engines=2,
                             base_port=19640, batch_size=8, workers=1,
                             max_wait_ms=2.0, version="v1")
        poisoned = PoisonedModel(echo_pipeline("v2"))
        n_clients, per_client = 4, 40
        results = {}
        try:
            threads = _fleet_load(fleet, n_clients, per_client, results)
            time.sleep(0.1)
            report = fleet.rolling_swap(
                poisoned, "v2",
                policy=CanaryPolicy(fraction=0.5, min_batches=4,
                                    consecutive_failures=3,
                                    decision_timeout_s=20))
            for t in threads:
                t.join(timeout=60)
            assert not report["ok"]
            assert report["rolled_back"] == 1
            assert report["completed"] == 0    # rollout halted at once
            assert poisoned.batches_poisoned >= 1
            # the fleet never left v1, and keeps serving
            for e in fleet.engines:
                assert e.model_version == "v1"
            assert fleet.post({"x": -5}) == {"echo": -5, "v": "v1"}
            assert fleet.counters()["swaps_rolled_back"] == 1
        finally:
            fleet.stop_all()
        total = n_clients * per_client
        ok = sum(v[0] for v in results.values())
        # the error floor: canary faults were rescued, not surfaced
        assert ok / total >= 0.99, f"error floor breached {ok}/{total}"
        assert {v for _, v in results.values() if v} == {"v1"}

    def test_stalled_warmup_rolls_back_without_touching_traffic(self):
        fleet = ServingFleet(echo_pipeline("v1"), n_engines=2,
                             base_port=19660, batch_size=4, version="v1")
        stalled = StalledWarmupModel(echo_pipeline("v2"), stall_s=60.0)
        results = {}
        try:
            threads = _fleet_load(fleet, 2, 20, results)
            t0 = time.perf_counter()
            report = fleet.rolling_swap(
                stalled, "v2",
                policy=CanaryPolicy(warmup_timeout_s=0.5,
                                    decision_timeout_s=5))
            dt = time.perf_counter() - t0
            for t in threads:
                t.join(timeout=30)
            assert not report["ok"]
            assert "warmup_timeout" in report["engines"][0]["reason"]
            assert stalled.warmup_started.is_set()
            assert dt < 10, f"stalled warmup wedged the rollout {dt:.1f}s"
            assert fleet.engines[0].model_version == "v1"
            assert fleet.post({"x": -7})["v"] == "v1"
        finally:
            fleet.stop_all()
        ok = sum(v[0] for v in results.values())
        assert ok / len(results) >= 0.99

    @pytest.mark.slow   # two fault classes + full fleet load — the
    #                     tier-1 acceptance drills above cover the
    #                     individual mechanisms
    def test_engine_killed_mid_rolling_swap(self):
        """Hard-kill one engine while the rollout is in flight: the
        dead engine's swap must resolve (skip or timeout-rollback, not
        a wedge) and the fleet must keep its availability floor via
        circuit-breaking failover."""
        fleet = ServingFleet(echo_pipeline("v1"), n_engines=3,
                             base_port=19680, batch_size=8, workers=1,
                             max_wait_ms=2.0, version="v1",
                             failure_threshold=2, breaker_cooldown=30.0)
        n_clients, per_client = 6, 30
        results = {}
        try:
            threads = _fleet_load(fleet, n_clients, per_client, results)
            time.sleep(0.2)
            FaultInjector.kill_engine_after(fleet, 1, 0.15)
            t0 = time.perf_counter()
            report = fleet.rolling_swap(
                echo_pipeline("v2"), "v2",
                policy=CanaryPolicy(fraction=0.5, min_batches=3,
                                    decision_timeout_s=2.0),
                pressure_timeout_s=3.0)
            dt = time.perf_counter() - t0
            for t in threads:
                t.join(timeout=60)
            # the rollout RESOLVED (no wedge) and made progress
            assert dt < 30, f"rollout wedged for {dt:.1f}s"
            assert report["completed"] >= 1, report
            outcomes = {e["outcome"] for e in report["engines"]}
            assert outcomes <= {"completed", "rolled_back",
                                "skipped_dead", "error"}
            # engines that completed really serve the new version
            for entry in report["engines"]:
                if entry["outcome"] == "completed":
                    assert fleet.engines[
                        entry["engine"]].model_version == "v2"
        finally:
            fleet.stop_all()
        total = n_clients * per_client
        ok = sum(v[0] for v in results.values())
        assert len(results) == total
        assert ok / total >= 0.99, f"availability {ok}/{total}"
        assert {v for _, v in results.values() if v} <= {"v1", "v2"}


class TestAdaptiveBatcherChaos:
    """Satellite: the adaptive batcher + pipelined dispatch must
    coexist with the chaos harness — a worker-thread kill mid-batch and
    a hard engine kill mid-load while the batcher is actively forming
    batches under its deadline policy."""

    def test_batcher_pipeline_survives_worker_kill_and_engine_kill(self):
        inj = FaultInjector(seed=7)
        fleet = ServingFleet(inj.wrap(echo_pipeline()), n_engines=2,
                             base_port=19560, batch_size=4, workers=1,
                             max_wait_ms=2.0,
                             failure_threshold=3, breaker_cooldown=30.0)
        results = {}
        stop_load = threading.Event()

        def client(cid, n=40):
            for j in range(n):
                key = cid * 1000 + j
                try:
                    body = fleet.post({"x": key}, timeout=5.0)
                    results[key] = (body == {"echo": key})
                except Exception:  # noqa: BLE001 — availability metric
                    results[key] = False
                if stop_load.is_set():
                    break
        try:
            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            # mid-load: kill one worker thread (supervisor must restart
            # it under the batcher's nose)...
            time.sleep(0.3)
            inj.arm_worker_kill(1)
            # ...then hard-kill a whole engine (failover absorbs it)
            time.sleep(0.3)
            FaultInjector.kill_engine(fleet, 0)
            for t in threads:
                t.join(timeout=60)
            assert not any(t.is_alive() for t in threads)
            # capture BEFORE stop_all flips every engine to not-alive
            survivor_alive = fleet.engines[1].is_alive()
        finally:
            stop_load.set()
            fleet.stop_all()
        total = len(results)
        ok = sum(results.values())
        assert total >= 140
        # damage budget for TWO simultaneous fault classes: the worker
        # kill forfeits at most its in-flight batch (<= batch_size=4)
        # and the engine kill's parked requests (<= 4 more) burn their
        # client timeout before failing over; everything else must
        # succeed. 0.90 of 160 = that worst case with breaker-cascade
        # slack (1-of-3-engines-killed alone is held to 0.99 above).
        assert ok / total >= 0.90, f"availability {ok}/{total}"
        assert inj.worker_kills_fired == 1
        # the surviving engine kept its batcher + worker alive
        # (supervisor-restart bookkeeping itself is pinned by
        # test_worker_kill_supervisor_restarts_and_recovers — here the
        # kill may land on the engine that is then hard-killed, so a
        # restart-count assertion would be nondeterministic)
        assert survivor_alive
