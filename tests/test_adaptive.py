"""SLO-adaptive serving suite (serving/variants.py + the continuous
batcher in serving/server.py + serving/autoscale.py): variant-ladder
declaration and cached routing, fidelity-floor degradation with
hysteretic recovery, the dynamic Retry-After drain estimate,
continuous-batcher fairness (bounded wait behind a hot model, reply/
model integrity under concurrency), the watermark autoscaler's
bounded scale rates and drain-before-retire discipline, and the
``check_adaptive_serving`` static audit.

The full chaos acceptance drill (SLO ramp over real HTTP -> step_down
-> availability/correctness/recompile floors -> recovery step_up) and
the real-OS-process autoscaler round trip are slow-marked;
``bench.py adaptive`` runs the measured cost/occupancy comparison.
"""

import importlib.util
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from mmlspark_tpu.serving import (
    FleetAutoscaler, HTTPSource, ModelZoo, ServingEngine, ServingFleet,
    VariantSelector,
)
from mmlspark_tpu.stages.basic import Lambda

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def echo_stage(tag, delay=0.0):
    """A serving stage that stamps its variant tag into every reply."""
    def handle(table):
        if delay:
            time.sleep(delay)
        replies = []
        for r in table["request"]:
            row = json.loads(r["entity"].decode()) if r.get("entity") \
                else {}
            replies.append({"served_by": tag, "x": row.get("x")})
        return table.with_column("reply", replies)
    return Lambda.apply(handle)


def post(addr, body, headers=None, path="/", timeout=30.0):
    """(status, parsed body, response headers) — HTTPError unwrapped."""
    req = urllib.request.Request(
        addr + path, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read())
        except Exception:  # noqa: BLE001
            body = {}
        return e.code, body, dict(e.headers)


def two_variant_zoo(slow=0.0, fast=0.0):
    """One logical model as a 2-rung ladder: full-fidelity ``clf`` and
    the cheap ``clf_int8`` tier."""
    zoo = ModelZoo(memory_probe=None)
    zoo.register_factory("clf", "v1",
                         lambda: echo_stage("clf", delay=slow),
                         metadata={"precision": "f32"})
    zoo.register_factory("clf_int8", "v1",
                         lambda: echo_stage("clf_int8", delay=fast),
                         metadata={"precision": "int8"})
    return zoo


class _FakeAlert:
    def __init__(self, rule, slo="latency_p99"):
        self.rule, self.slo = rule, slo


class _FakeMonitor:
    """Just the ``alerts.active()`` surface the selector reads."""

    def __init__(self):
        self.active_alerts = []
        self.alerts = self

    def active(self):
        return list(self.active_alerts)


# ---------------------------------------------------------------------------
# the variant selector (unit: now-controlled ticks, no HTTP)
# ---------------------------------------------------------------------------


class TestVariantSelector:
    def _selector(self, mon=None, **kw):
        zoo = two_variant_zoo()
        kw.setdefault("hold_s", 5.0)
        kw.setdefault("pressure_limit", 32)
        sel = VariantSelector(zoo, slo=mon, **kw)
        sel.declare("clf", ["clf", "clf_int8"], slo_ms=50.0,
                    costs={"clf": 1.0, "clf_int8": 0.25})
        return sel, zoo

    def test_declare_validates_and_routes_to_preferred(self):
        sel, zoo = self._selector()
        # bare logical name AND every rung key route to the active rung
        assert sel.route("clf") == "clf@v1"
        assert sel.route("clf@v1") == "clf@v1"
        assert sel.route("clf_int8@v1") == "clf@v1"
        assert sel.route("unrelated") == "unrelated"   # passthrough
        assert sel.route(None) is None
        with pytest.raises(ValueError):
            sel.declare("clf", ["clf"], slo_ms=50.0)   # dup ladder
        with pytest.raises(KeyError):
            sel.declare("other", ["ghost"], slo_ms=50.0)
        kinds = [e.kind for e in sel.events]
        assert kinds == ["declare"]
        zoo.close()

    def test_route_is_a_pure_cache_read(self):
        sel, zoo = self._selector()
        before = len(sel.events)
        for _ in range(100):
            sel.route("clf")
        assert len(sel.events) == before
        assert sel.stats()["selects"] == 0
        zoo.close()

    def test_pressure_opens_floor_and_picks_cheapest(self):
        sel, zoo = self._selector()
        assert sel.tick(pressure=64, now=10.0, min_interval_s=0.0)
        st = sel.status()["clf"]
        assert st["floor"] == 1 and st["active"] == "clf_int8@v1"
        assert st["last_step_down_reason"] == "queue_pressure"
        assert sel.route("clf") == "clf_int8@v1"
        kinds = [e.kind for e in sel.events]
        assert "step_down" in kinds and "select" in kinds
        # floor is bounded by the ladder: another degraded tick
        # cannot open a rung that does not exist
        sel.tick(pressure=64, now=11.0, min_interval_s=0.0)
        assert sel.status()["clf"]["floor"] == 1
        zoo.close()

    def test_fast_burn_steps_down_slow_burn_does_not(self):
        mon = _FakeMonitor()
        sel, zoo = self._selector(mon=mon)
        mon.active_alerts = [_FakeAlert("slow_burn")]
        sel.tick(pressure=0, now=10.0, min_interval_s=0.0)
        assert sel.status()["clf"]["floor"] == 0
        mon.active_alerts = [_FakeAlert("fast_burn")]
        sel.tick(pressure=0, now=11.0, min_interval_s=0.0)
        st = sel.status()["clf"]
        assert st["floor"] == 1
        assert st["last_step_down_reason"] == "fast_burn:latency_p99"
        zoo.close()

    def test_hysteretic_recovery_one_rung_per_hold(self):
        sel, zoo = self._selector(hold_s=5.0)
        sel.tick(pressure=64, now=10.0, min_interval_s=0.0)
        assert sel.status()["clf"]["floor"] == 1
        # clean air, but not for hold_s yet: floor stays open
        sel.tick(pressure=0, now=12.0, min_interval_s=0.0)
        assert sel.status()["clf"]["floor"] == 1
        sel.tick(pressure=0, now=17.5, min_interval_s=0.0)
        st = sel.status()["clf"]
        assert st["floor"] == 0 and st["active"] == "clf@v1"
        assert any(e.kind == "step_up" and e.reason == "recovered"
                   for e in sel.events)
        zoo.close()

    def test_slo_breaching_rung_skipped_on_profile(self):
        sel, zoo = self._selector()
        # profile rung 0 as breaching (p99 way over the 50ms SLO) and
        # rung 1 as meeting: once pressure opens the floor the choice
        # is SLO-driven, not just declared-cost-driven
        for _ in range(20):
            sel.observe("clf@v1", 200.0, rows=1)
            sel.observe("clf_int8@v1", 2.0, rows=1)
        sel.tick(pressure=64, now=100.0, min_interval_s=0.0)
        st = sel.status()["clf"]
        assert st["active"] == "clf_int8@v1"
        rungs = {v["variant"]: v for v in st["variants"]}
        assert rungs["clf@v1"]["p99_ms"] > 50.0
        assert rungs["clf@v1"]["cost_source"] == "declared"
        zoo.close()

    def test_measured_cost_source_without_declared(self):
        zoo = two_variant_zoo()
        sel = VariantSelector(zoo)
        sel.declare("clf", ["clf", "clf_int8"], slo_ms=50.0)
        rungs = {v["variant"]: v
                 for v in sel.status()["clf"]["variants"]}
        assert rungs["clf@v1"]["cost_source"] == "unprofiled"
        sel.observe("clf@v1", 8.0, rows=4)
        rungs = {v["variant"]: v
                 for v in sel.status()["clf"]["variants"]}
        assert rungs["clf@v1"]["cost_source"] == "measured"
        assert rungs["clf@v1"]["cost"] == pytest.approx(2.0)
        zoo.close()

    def test_tick_rate_gate(self):
        sel, zoo = self._selector(decide_interval_s=0.5)
        assert sel.tick(now=10.0)
        assert not sel.tick(now=10.2)     # gated
        assert sel.tick(now=10.6)
        zoo.close()


# ---------------------------------------------------------------------------
# dynamic Retry-After (unit over an unstarted engine)
# ---------------------------------------------------------------------------


class TestDynamicRetryAfter:
    @pytest.fixture
    def eng(self):
        source = HTTPSource(port=0)
        engine = ServingEngine(source, echo_stage("m"), tracing=False,
                               slo=False, retry_after_max_s=30)
        yield engine
        source.close()

    def test_estimate_backlog_over_drain_rate(self, eng):
        assert eng._retry_after_s == 1
        # 40 rows backed up, draining at ~8 rows/s -> ceil(5) = 5s
        eng._drained_rows.inc(80.0)        # 80 rows in the 10s window
        for i in range(40):
            eng.source.queue.put(object())
        eng._update_retry_after(now=100.0)
        assert eng._retry_after_s == 5
        assert eng.source.retry_after_s == 5
        assert eng._retry_header() == "5"
        assert eng._retry_header(floor=9) == "9"

    def test_no_drain_rate_quotes_the_cap(self, eng):
        eng.source.queue.put(object())
        eng._update_retry_after(now=100.0)
        assert eng._retry_after_s == 30

    def test_clamped_to_window_and_rate_gated(self, eng):
        eng._drained_rows.inc(1.0)         # 0.1 rows/s
        for i in range(900):
            eng.source.queue.put(object())
        eng._update_retry_after(now=100.0)
        assert eng._retry_after_s == 30    # 9000s clamps to the cap
        while not eng.source.queue.empty():
            eng.source.queue.get_nowait()
        eng._update_retry_after(now=100.2)   # inside the 0.5s gate
        assert eng._retry_after_s == 30
        eng._update_retry_after(now=100.8)
        assert eng._retry_after_s == 1


# ---------------------------------------------------------------------------
# continuous-batcher fairness (real HTTP)
# ---------------------------------------------------------------------------


@pytest.fixture
def adaptive_engine():
    zoo = two_variant_zoo()
    zoo.register_factory("hot", "v1",
                         lambda: echo_stage("hot", delay=0.03))
    sel = VariantSelector(zoo, decide_interval_s=0.05, hold_s=0.5,
                          pressure_limit=24)
    sel.declare("clf", ["clf", "clf_int8"], slo_ms=50.0,
                costs={"clf": 1.0, "clf_int8": 0.25})
    source = HTTPSource(port=0)
    engine = ServingEngine(source, zoo=zoo, variants=sel, batch_size=4,
                           max_wait_ms=2.0, tracing=False,
                           slo=False).start()
    yield engine, sel, zoo, source.address
    engine.stop()
    zoo.close()


class TestContinuousBatcherFairness:
    def test_reply_and_model_integrity_under_concurrency(
            self, adaptive_engine):
        engine, sel, zoo, addr = adaptive_engine
        results, lock = [], threading.Lock()

        def client(model, tid):
            for i in range(10):
                x = tid * 1000 + i
                code, body, headers = post(addr, {"x": x},
                                           {"X-Model": model})
                with lock:
                    results.append((model, x, code, body, headers))

        threads = [threading.Thread(target=client, args=(m, t))
                   for t, m in enumerate(["clf", "clf_int8", "hot"])]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results) == 30
        for model, x, code, body, headers in results:
            assert code == 200
            assert body["x"] == x                  # reply is MINE
            served = headers.get("X-Model", "")
            if model == "hot":
                assert served == "hot@v1"
            else:
                # ladder members may be re-routed, but never off the
                # ladder — zero cross-model replies
                assert served in ("clf@v1", "clf_int8@v1"), served
                assert body["served_by"] in ("clf", "clf_int8")

    def test_bounded_wait_behind_hot_model(self, adaptive_engine):
        engine, sel, zoo, addr = adaptive_engine
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                post(addr, {"x": 0}, {"X-Model": "hot"})

        threads = [threading.Thread(target=hammer) for _ in range(3)]
        for t in threads:
            t.start()
        try:
            time.sleep(0.2)                     # hot stream saturates
            t0 = time.perf_counter()
            code, body, _ = post(addr, {"x": 7}, {"X-Model": "clf"})
            waited = time.perf_counter() - t0
            assert code == 200 and body["x"] == 7
            # continuous admission: the cold model's single request is
            # dispatched within a few slots, not after the hot stream
            assert waited < 3.0, f"starved for {waited:.2f}s"
        finally:
            stop.set()
            for t in threads:
                t.join()

    def test_healthz_exposes_variant_plane_and_retry_after(
            self, adaptive_engine):
        engine, sel, zoo, addr = adaptive_engine
        post(addr, {"x": 1}, {"X-Model": "clf"})
        with urllib.request.urlopen(addr + "/healthz", timeout=10) as r:
            health = json.loads(r.read())
        v = health["metrics"]["variants"]["clf"]
        assert v["active"] == "clf@v1" and v["rung"] == 0
        assert "last_step_down_reason" in v
        assert all("cost_source" in rung for rung in v["variants"])
        assert 1 <= health["metrics"]["retry_after_s"] <= 30
        text = engine.metrics_text()
        assert "serving_variant_rung" in text
        assert "serving_retry_after_s" in text


class TestSwapUnderContinuousLoad:
    def test_swap_drains_and_flips_under_load(self):
        from mmlspark_tpu.serving.lifecycle import CanaryPolicy
        source = HTTPSource(port=0)
        engine = ServingEngine(source, echo_stage("v1"), batch_size=4,
                               max_wait_ms=2.0, tracing=False,
                               slo=False).start()
        stop = threading.Event()
        seen, lock = [], threading.Lock()

        def load():
            i = 0
            while not stop.is_set():
                code, body, _ = post(source.address, {"x": i})
                with lock:
                    seen.append((code, body))
                i += 1

        threads = [threading.Thread(target=load) for _ in range(2)]
        for t in threads:
            t.start()
        try:
            time.sleep(0.2)
            result = engine.swap(
                echo_stage("v2"), "v2",
                policy=CanaryPolicy(fraction=0.2, min_batches=4))
            assert result.completed, result
            time.sleep(0.3)
        finally:
            stop.set()
            for t in threads:
                t.join()
        engine.stop()
        assert len(seen) > 20
        tags = {body["served_by"] for code, body in seen if code == 200}
        # every reply came from a real version; post-swap traffic runs v2
        assert tags <= {"v1", "v2"} and "v2" in tags
        assert all(code == 200 for code, _ in seen)


# ---------------------------------------------------------------------------
# the fleet autoscaler (unit: fake fleet + fake spawner)
# ---------------------------------------------------------------------------


class _FakeFleet:
    def __init__(self, base=1):
        self.addresses = [f"http://127.0.0.1:{9}" for _ in range(base)]
        self.rate = 0.0
        self.autoscaler = None
        self.added, self.removed = [], []

    def demand_rate(self, window_s=30.0):
        return self.rate

    def add_engine(self, address, wait_ready_s=0.0):
        self.addresses.append(address)
        self.added.append(address)
        return len(self.addresses) - 1

    def remove_engine(self, address):
        if address not in self.addresses:
            raise ValueError(address)
        self.addresses.remove(address)
        self.removed.append(address)


class TestFleetAutoscaler:
    def _autoscaler(self, fleet=None, **kw):
        fleet = fleet or _FakeFleet()
        stopped = []
        n = [0]

        def spawner():
            n[0] += 1
            addr = f"http://127.0.0.1:{7000 + n[0]}"
            stopped.append([])
            idx = len(stopped) - 1
            return addr, (lambda: stopped[idx].append(addr))

        kw.setdefault("up_rate", 100.0)
        kw.setdefault("window_s", 2.0)
        auto = FleetAutoscaler(fleet, spawner, **kw)
        return auto, fleet, stopped

    def test_watermark_validation(self):
        fleet = _FakeFleet()
        with pytest.raises(ValueError):
            FleetAutoscaler(fleet, lambda: None, min_engines=0)
        with pytest.raises(ValueError):
            FleetAutoscaler(fleet, lambda: None, min_engines=3,
                            max_engines=2)
        with pytest.raises(ValueError):
            FleetAutoscaler(fleet, lambda: None, up_rate=10.0,
                            down_rate=10.0)

    def test_scale_up_bounded_by_cooldown_and_max(self):
        auto, fleet, _ = self._autoscaler(max_engines=3, cooldown_s=5.0)
        fleet.rate = 500.0
        assert auto.tick(now=100.0) == "scale_up"
        assert len(fleet.addresses) == 2
        assert auto.tick(now=101.0) is None       # cooldown
        assert auto.tick(now=106.0) == "scale_up"
        assert len(fleet.addresses) == 3
        assert auto.tick(now=120.0) is None       # at max_engines
        assert auto.stats()["scale_ups"] == 2
        kinds = [e.kind for e in auto.events]
        assert kinds == ["scale_up", "scale_up"]

    def test_scale_down_only_owned_through_drain(self):
        auto, fleet, stopped = self._autoscaler(
            max_engines=3, cooldown_s=0.0, down_cooldown_s=0.0,
            drain_timeout_s=1.0)
        fleet.rate = 500.0
        auto.tick(now=100.0)
        auto.tick(now=101.0)
        assert len(fleet.addresses) == 3
        fleet.rate = 1.0
        assert auto.tick(now=200.0) == "scale_down"
        # newest-first retire; rotation removal happened (drain path)
        assert fleet.removed == [fleet.added[-1]]
        assert stopped[1] == [fleet.added[-1]]    # its stopper ran
        assert auto.tick(now=300.0) == "scale_down"
        # only the baseline engine is left: NOT ours, never retired
        assert auto.tick(now=400.0) is None
        assert len(fleet.addresses) == 1
        assert auto.stats()["scale_downs"] == 2

    def test_never_below_min_engines(self):
        fleet = _FakeFleet(base=1)
        auto, fleet, _ = self._autoscaler(
            fleet=fleet, min_engines=1, cooldown_s=0.0,
            down_cooldown_s=0.0)
        fleet.rate = 0.0
        assert auto.tick(now=100.0) is None
        assert len(fleet.addresses) == 1

    def test_spawn_failure_keeps_width(self):
        fleet = _FakeFleet()

        def bad_spawner():
            raise RuntimeError("no capacity")

        auto = FleetAutoscaler(fleet, bad_spawner, up_rate=10.0)
        fleet.rate = 500.0
        assert auto.tick(now=100.0) is None
        assert len(fleet.addresses) == 1
        assert auto.stats()["spawn_failures"] == 1

    def test_join_failure_stops_orphan_process(self):
        class RejectingFleet(_FakeFleet):
            def add_engine(self, address, wait_ready_s=0.0):
                raise RuntimeError("probe timed out")

        auto, fleet, stopped = self._autoscaler(fleet=RejectingFleet())
        fleet.rate = 500.0
        assert auto.tick(now=100.0) is None
        assert stopped[0]           # the never-joined process was stopped
        assert auto.stats()["spawn_failures"] == 1

    def test_stats_render_as_prometheus_families(self):
        from mmlspark_tpu.core.prometheus import (
            PromRenderer, autoscale_families,
        )
        auto, fleet, _ = self._autoscaler()
        r = PromRenderer()
        autoscale_families(r, auto)
        text = r.render()
        for family in ("serving_autoscale_engines",
                       "serving_autoscale_demand_rate",
                       "serving_autoscale_scale_ups_total",
                       "serving_autoscale_scale_downs_total"):
            assert family in text, family
        assert fleet.autoscaler is auto


# ---------------------------------------------------------------------------
# the static audit (check_adaptive_serving)
# ---------------------------------------------------------------------------


def _load_checker(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "tools", "check_fusion_kernels.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_GOOD_AUTOSCALE = (
    "class A:\n"
    "    def _drain_and_stop(self, addr):\n"
    "        self.fleet.remove_engine(addr)\n"
    "        self._stop_proc(addr)\n"
    "    def _stop_proc(self, p):\n"
    "        p.terminate()\n")

_GOOD_SERVER = (
    "class E:\n"
    "    def _batcher_loop(self):\n"
    "        self.variants.tick(pressure=0)\n"
    "    def _ingest(self, parked):\n"
    "        key = self.variants.route(key)\n"
    "    def _execute_batch(self):\n"
    "        self.variants.observe(k, ms, n)\n"
    "class Handler:\n"
    "    def do_POST(self):\n"
    "        pass\n")


class TestAdaptiveServingAudit:
    def test_shipped_sources_clean(self):
        mod = _load_checker("cfk_adaptive_pos")
        assert mod.check_adaptive_serving() == []

    def test_good_shapes_pass(self):
        mod = _load_checker("cfk_adaptive_pos2")
        assert mod.check_adaptive_serving_source(
            _GOOD_SERVER, _GOOD_AUTOSCALE) == []

    def test_selection_in_http_handler_flagged(self):
        mod = _load_checker("cfk_adaptive_neg1")
        bad = _GOOD_SERVER.replace(
            "    def do_POST(self):\n        pass\n",
            "    def do_POST(self):\n"
            "        key = self.engine.variants.route(key)\n")
        v = mod.check_adaptive_serving_source(bad, _GOOD_AUTOSCALE)
        assert any("HTTP handler touches '.variants'" in m for m in v)

    def test_tick_off_the_batcher_thread_flagged(self):
        mod = _load_checker("cfk_adaptive_neg2")
        bad = _GOOD_SERVER + (
            "class F:\n"
            "    def _pump(self):\n"
            "        self.variants.tick(pressure=1)\n")
        v = mod.check_adaptive_serving_source(bad, _GOOD_AUTOSCALE)
        assert any("variants.tick called from '_pump'" in m for m in v)

    def test_scale_down_outside_drain_funnel_flagged(self):
        mod = _load_checker("cfk_adaptive_neg3")
        bad = _GOOD_AUTOSCALE + (
            "class B:\n"
            "    def tick(self):\n"
            "        self.fleet.remove_engine(a)\n"
            "        self.proc.kill()\n")
        v = mod.check_adaptive_serving_source(_GOOD_SERVER, bad)
        assert any("remove_engine called from 'tick'" in m for m in v)
        assert any("raw kill call from 'tick'" in m for m in v)


# ---------------------------------------------------------------------------
# chaos acceptance: SLO ramp -> step_down -> recovery (slow)
# ---------------------------------------------------------------------------


class _BucketStage:
    """An echo scorer with TPUModel-shaped pow-2 bucket accounting:
    ``jit_cache_misses`` counts distinct padded bucket sizes, with the
    serving buckets pre-warmed (the AOT/warmup contract) — so any
    batch the engine dispatches OUTSIDE the warmed pow-2 set counts
    as a steady-state recompile."""

    def __init__(self, tag, delay=0.0, max_bucket=8):
        self.tag, self.delay = tag, delay
        self.warmed = set()
        b = 1
        while b <= max_bucket:
            self.warmed.add(b)
            b *= 2
        self.jit_cache_misses = 0

    def transform(self, table):
        n = len(table["request"])
        bucket = 1
        while bucket < n:
            bucket *= 2
        if bucket not in self.warmed:
            self.jit_cache_misses += 1
            self.warmed.add(bucket)
        if self.delay:
            time.sleep(self.delay)
        replies = []
        for r in table["request"]:
            row = json.loads(r["entity"].decode()) if r.get("entity") \
                else {}
            replies.append({"served_by": self.tag, "x": row.get("x")})
        return table.with_column("reply", replies)


@pytest.mark.slow
class TestChaosAdaptiveServing:
    def test_ramp_step_down_availability_and_recovery(self):
        """The tentpole acceptance drill over REAL HTTP: a load ramp
        breaches the latency SLO -> fast burn -> the selector steps
        the ladder down to int8 (a VariantEvent on the timeline) while
        availability stays >= 99%, zero replies cross models, and
        neither variant sees an unwarmed pow-2 bucket; after the ramp
        stops, sustained clean air steps fidelity back up."""
        from mmlspark_tpu.core.slo import BurnRateRule, SLO, SLOMonitor

        f32 = _BucketStage("clf", delay=0.08)
        int8 = _BucketStage("clf_int8", delay=0.002)
        zoo = ModelZoo(memory_probe=None)
        zoo.register_factory("clf", "v1", lambda: f32,
                             metadata={"precision": "f32"})
        zoo.register_factory("clf_int8", "v1", lambda: int8,
                             metadata={"precision": "int8"})
        mon = SLOMonitor(
            slos=[SLO("latency", "latency", target=0.99,
                      latency_threshold_ms=40.0)],
            rules=[BurnRateRule("fast_burn", 8.0, 2.0, 14.4,
                                min_events=5)],
            horizon_s=60.0)
        sel = VariantSelector(zoo, slo=mon, decide_interval_s=0.1,
                              hold_s=1.0, window_s=30.0,
                              pressure_limit=10_000)
        sel.declare("clf", ["clf", "clf_int8"], slo_ms=40.0,
                    costs={"clf": 1.0, "clf_int8": 0.25})
        source = HTTPSource(port=0)
        engine = ServingEngine(source, zoo=zoo, variants=sel,
                               batch_size=8, max_wait_ms=2.0,
                               tracing=False, slo=mon).start()
        addr = source.address
        results, lock = [], threading.Lock()
        stop = threading.Event()

        def client():
            i = 0
            while not stop.is_set():
                x = id(threading.current_thread()) % 10_000 + i * 10_000
                code, body, headers = post(addr, {"x": x},
                                           {"X-Model": "clf"})
                with lock:
                    results.append((x, code, body,
                                    headers.get("X-Model", "")))
                i += 1

        try:
            # steady state: preferred rung serves
            code, body, headers = post(addr, {"x": 1},
                                       {"X-Model": "clf"})
            assert code == 200 and headers["X-Model"] == "clf@v1"

            # the ramp: enough concurrency that every f32 reply
            # breaches the 40ms objective
            threads = [threading.Thread(target=client)
                       for _ in range(6)]
            for t in threads:
                t.start()
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if any(e.kind == "step_down" for e in sel.events):
                    break
                time.sleep(0.1)
            assert any(e.kind == "step_down" and "fast_burn" in e.reason
                       for e in sel.events), \
                f"no step_down; events={sel.events} " \
                f"alerts={[a.name for a in mon.alerts.active()]}"
            # let the cheap tier serve for a bit under the same load
            time.sleep(1.5)
            stop.set()
            for t in threads:
                t.join()

            with lock:
                total = len(results)
                ok = sum(1 for _, code, _, _ in results if code == 200)
            assert total > 30
            assert ok / total >= 0.99, f"{ok}/{total}"
            for x, code, body, served in results:
                if code != 200:
                    continue
                assert body["x"] == x              # zero wrong replies
                assert served in ("clf@v1", "clf_int8@v1"), served
            assert sel.status()["clf"]["active"] == "clf_int8@v1"
            # zero steady-state recompiles: no batch ever left the
            # warmed pow-2 bucket set on either variant
            assert f32.jit_cache_misses == 0
            assert int8.jit_cache_misses == 0

            # recovery: clean air (fast int8 replies) resolves the
            # burn, and hold_s later the ladder steps back up
            deadline = time.monotonic() + 30.0
            stepped_up = False
            while time.monotonic() < deadline:
                code, _, _ = post(addr, {"x": 2}, {"X-Model": "clf"})
                assert code == 200
                if any(e.kind == "step_up" for e in sel.events):
                    stepped_up = True
                    break
                time.sleep(0.2)
            assert stepped_up, \
                f"no step_up; alerts=" \
                f"{[a.name for a in mon.alerts.active()]}"
            assert sel.status()["clf"]["active"] == "clf@v1"
            # the drill landed on the registry timeline
            kinds = [getattr(e, "kind", "") for e in zoo.events]
            assert "step_down" in kinds and "step_up" in kinds
        finally:
            stop.set()
            engine.stop()
            zoo.close()


# ---------------------------------------------------------------------------
# autoscaler over real OS processes (slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestAutoscalerRealProcesses:
    def test_scale_up_serve_drain_retire(self):
        """The full loop with tests/serving_worker.py engines: demand
        ramp spawns + probes + joins a second process, the fleet
        serves across both, demand decay retires it through the drain
        path, and the retired process actually exits."""
        worker = os.path.join(_REPO, "tests", "serving_worker.py")
        procs = []

        def spawn_worker(wid, port):
            p = subprocess.Popen(
                [sys.executable, worker, str(port), str(wid)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True)
            procs.append(p)
            line = p.stdout.readline().strip()
            tag, _, addr = line.split()
            assert tag == "READY", line
            return addr, p

        try:
            base_addr, base_proc = spawn_worker(0, 0)
            fleet = ServingFleet.connect([base_addr], wait_ready_s=30)
            wid = [0]

            def spawner():
                wid[0] += 1
                return spawn_worker(wid[0], 0)

            auto = FleetAutoscaler(
                fleet, spawner, min_engines=1, max_engines=2,
                up_rate=5.0, down_rate=2.0, window_s=2.0,
                cooldown_s=0.0, down_cooldown_s=0.0,
                startup_probe_s=30.0, drain_timeout_s=5.0)

            for i in range(40):
                assert fleet.post({"x": i})["echo"] == i
            assert fleet.demand_rate(2.0) > 5.0
            assert auto.tick() == "scale_up"
            assert len(fleet.addresses) == 2

            # both engines serve through the widened rotation
            for i in range(40, 60):
                assert fleet.post({"x": i})["echo"] == i

            time.sleep(2.5)                 # demand window decays
            assert fleet.demand_rate(2.0) < 2.0
            assert auto.tick() == "scale_down"
            assert len(fleet.addresses) == 1
            grown = procs[1]
            grown.wait(timeout=10)          # retired process exited
            assert grown.poll() is not None
            # the survivor still serves
            assert fleet.post({"x": 99})["echo"] == 99
            assert auto.stats()["scale_ups"] == 1
            assert auto.stats()["scale_downs"] == 1
            assert "serving_autoscale_engines" in fleet.metrics_text()
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
