"""Mesh-sharded serving (serving/sharded.py, docs/sharded_serving.md).

Runs on the conftest-forced 8-virtual-CPU-device mesh (the
forced-host-device-count recipe): sharded-vs-single-device parity,
too-big-for-one-device residency, sharded AOT artifact roundtrips
(fresh process, zero traces), the multi-process fleet (startup probe +
chaos kill drill), the zoo's measured device-memory accounting, and the
sharded-program static audit.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax

from mmlspark_tpu.core.stage import Pipeline
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.core.fusion import fuse
from mmlspark_tpu.models.networks import build_network
from mmlspark_tpu.models.tpu_model import TPUModel
from mmlspark_tpu.serving import aot as AOT
from mmlspark_tpu.serving import sharded as SH
from mmlspark_tpu.serving.fleet import ServingFleet, json_scoring_pipeline
from mmlspark_tpu.serving.server import HTTPSource, ServingEngine
from mmlspark_tpu.serving.zoo import ModelZoo
from mmlspark_tpu.stages.dataprep import (
    CleanMissingData, FastVectorAssembler, StandardScaler,
)
from mmlspark_tpu.models.linear import TPULogisticRegression

_WORKER = os.path.join(os.path.dirname(__file__), "serving_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _fitted_pipeline(n: int = 64):
    rng = np.random.default_rng(0)
    table = DataTable({
        "a": rng.normal(size=n).astype(np.float32),
        "b": np.where(rng.random(n) < 0.2, np.nan,
                      rng.normal(size=n)),
        "label": rng.integers(0, 2, n).astype(np.float64),
    })
    pm = Pipeline(stages=[
        CleanMissingData(inputCols=["b"], outputCols=["b"]),
        FastVectorAssembler(inputCols=["a", "b"], outputCol="fv"),
        StandardScaler(inputCol="fv", outputCol="fv"),
        TPULogisticRegression(featuresCol="fv", labelCol="label",
                              maxIter=3),
    ]).fit(table)
    return pm, table


_TP_SPEC = {"type": "transformer", "vocab_size": 2048, "dim": 64,
            "depth": 1, "heads": 4, "max_len": 32, "num_classes": 4}


def _tp_model(batch_size: int = 16):
    """A Transformer classifier + its unsharded oracle twin (same
    weights)."""
    module = build_network(_TP_SPEC)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, _TP_SPEC["vocab_size"],
                        size=(batch_size, 16)).astype(np.int32)
    variables = module.init(jax.random.PRNGKey(0), toks[:1])
    oracle = TPUModel.from_flax(module, variables, inputCol="tokens",
                                outputCol="scores",
                                batchSize=batch_size)
    sharded = TPUModel.from_flax(module, variables, inputCol="tokens",
                                 outputCol="scores",
                                 batchSize=batch_size)
    return oracle, sharded, toks


class TestShardedFusedPipeline:
    """Batch-dim data sharding of fused pipeline programs."""

    def test_bit_identical_to_single_device(self,
                                            forced_host_device_count):
        pm, table = _fitted_pipeline()
        plain = fuse(pm)
        out_plain = plain.transform(table)
        sharded = SH.data_shard_pipeline(pm, SH.serving_mesh())
        out_sh = sharded.transform(table)
        # batch-dim sharding never changes a row's math: f32 fused
        # pipeline programs are BIT-identical to the 1-device oracle
        for col in ("prediction", "probability"):
            assert np.array_equal(np.asarray(out_plain[col]),
                                  np.asarray(out_sh[col])), col
        m = sharded.metrics()
        assert m["sharded"] and m["mesh"] == {"data": 8}

    def test_env_buffers_land_data_sharded(self,
                                           forced_host_device_count):
        pm, table = _fitted_pipeline()
        sharded = SH.data_shard_pipeline(pm, SH.serving_mesh())
        sharded.transform(table)
        plan = next(iter(sharded._plans.values()))
        seg = plan.segments[0]
        env = seg.build_env(table, plan.device_table)
        arr = env[seg.external_reads[0]]
        # 64 rows over 8 devices: every shard holds 8 rows
        shards = arr.addressable_shards
        assert len(shards) == 8
        assert all(s.data.shape[0] == len(table) // 8 for s in shards)

    def test_indivisible_batch_falls_back(self,
                                          forced_host_device_count):
        pm, table = _fitted_pipeline()
        plain_out = fuse(pm).transform(table)
        sharded = SH.data_shard_pipeline(pm, SH.serving_mesh())
        idx = np.arange(37)          # 37 % 8 != 0
        out = sharded.transform(table._take_indices(idx))
        assert np.array_equal(
            np.asarray(out["prediction"]),
            np.asarray(plain_out["prediction"])[:37])

    def test_non_dividing_data_axis_refused(self,
                                            forced_host_device_count):
        # a 6-wide axis passes a naive <=MIN_BUCKET check but no pow-2
        # bucket ever divides it — every batch would silently serve
        # through the unsharded fallback while metrics claim sharded
        pm, _ = _fitted_pipeline()
        from mmlspark_tpu.parallel import mesh as mesh_lib
        mesh6 = mesh_lib.make_mesh({"data": 6},
                                   devices=jax.devices()[:6])
        with pytest.raises(ValueError, match="does not divide"):
            fuse(pm).shard(mesh6)
        _, model, _ = _tp_model()
        with pytest.raises(ValueError, match="smallest serving bucket"):
            from jax.sharding import PartitionSpec as P
            model.set_sharding(mesh6, in_spec=P("data"))

    def test_mesh_wider_than_min_bucket_refused(
            self, forced_host_device_count):
        pm, _ = _fitted_pipeline()
        # a 16-shard data axis could not divide the smallest bucket
        fake_axes = {"data": 16}
        try:
            mesh = SH.serving_mesh(fake_axes)
        except ValueError:
            pytest.skip("host exposes exactly 8 virtual devices")
        with pytest.raises(ValueError, match="MIN_BUCKET"):
            fuse(pm).shard(mesh)


class TestTensorShardedModel:
    """Tensor parallelism: a model too big for one (simulated) device
    serving from the mesh."""

    def test_too_big_model_serves_through_engine(
            self, forced_host_device_count):
        oracle, model, toks = _tp_model()
        table = DataTable({"tokens": toks})
        ref = np.asarray(oracle.transform(table)["scores"])
        SH.tensor_shard_model(model, SH.serving_mesh({"model": 8}))
        out = np.asarray(model.transform(table)["scores"])
        # partitioned contractions reorder float adds: pinned tolerance
        assert np.allclose(ref, out, atol=1e-5), np.abs(ref - out).max()
        # the too-big-for-one-device proof: no single device holds the
        # full weight set
        max_dev, total = SH.assert_serves_from_mesh(model)
        assert max_dev < total
        assert max_dev < 0.5 * total   # 8-way: far below, not epsilon

        # ...and the ENGINE hot path serves it with zero steady-state
        # recompiles through a swap under live sharded load
        stage = json_scoring_pipeline(model, field="tokens")
        example = {"tokens": toks[:2]}
        stage.warmup(example)
        source = HTTPSource(port=_free_port())
        engine = ServingEngine(source, stage, batch_size=16,
                               tracing=False, slo=False,
                               flight_recorder=False).start()
        try:
            import urllib.request

            def post_one(i):
                body = json.dumps(
                    {"tokens": [int(t) for t in toks[i % len(toks)]]}
                ).encode()
                req = urllib.request.Request(
                    source.address, data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=30) as r:
                    return json.loads(r.read())

            for i in range(6):
                rep = post_one(i)
                assert rep["prediction"] == int(ref[i % len(toks)
                                                    ].argmax())
            misses_before_swap = model.jit_cache_misses

            # swap to a SECOND sharded version (fresh weights) while
            # requests keep flowing
            oracle2, model2, _ = _tp_model()
            SH.tensor_shard_model(model2,
                                  SH.serving_mesh({"model": 8}))
            stage2 = json_scoring_pipeline(model2, field="tokens")
            stop = threading.Event()
            errors = []

            def load():
                i = 0
                while not stop.is_set():
                    try:
                        post_one(i)
                    except Exception as e:  # noqa: BLE001
                        errors.append(e)
                    i += 1

            t = threading.Thread(target=load, daemon=True)
            t.start()
            res = engine.swap(stage2, "v2", warmup_example=example)
            stop.set()
            t.join(timeout=10)
            assert res.completed, res.reason
            assert not errors, errors[:3]
            # zero steady-state recompiles: the OLD model compiled
            # nothing after the swap started, and the NEW model's
            # compiles all happened in warmup (before cutover)
            assert model.jit_cache_misses == misses_before_swap
            misses_after = model2.jit_cache_misses
            for i in range(4):
                post_one(i)
            assert model2.jit_cache_misses == misses_after
        finally:
            engine.stop()

    def test_auto_weight_specs(self, forced_host_device_count):
        mesh = SH.serving_mesh({"model": 8})
        weights = {
            "big": np.zeros((2048, 24), np.float32),   # rows divide
            "tiny": np.zeros((8,), np.float32),        # under min bytes
            "odd": np.zeros((2049, 3), np.float32),    # nothing divides
        }
        specs = SH.auto_weight_specs(weights, mesh, axis="model")
        from jax.sharding import PartitionSpec as P
        assert specs["big"] == P("model", None)
        assert specs["tiny"] == P()
        assert specs["odd"] == P()

    def test_batch_size_must_divide_data_axis(
            self, forced_host_device_count):
        oracle, model, _ = _tp_model()
        mesh = SH.serving_mesh()
        model.set("batchSize", 12)     # 12 % 8 != 0
        with pytest.raises(ValueError, match="does not divide"):
            model.set_sharding(mesh)


class TestSeqShardedLM:
    """Sequence parallelism: the Transformer-LM scoring long context
    through the ring/Ulysses attention collective."""

    @pytest.mark.parametrize("impl", ["ring", "ulysses"])
    def test_lm_parity_pinned(self, forced_host_device_count, impl):
        # heads divisible by the seq axis (8) — the Ulysses all_to_all
        # shards heads after the transpose
        spec = {"type": "transformer", "vocab_size": 256, "dim": 32,
                "depth": 1, "heads": 8, "max_len": 128,
                "num_classes": 0, "seq_impl": impl}
        dense_mod = build_network(spec)
        seq_mod = build_network({**spec, "seq_axis": "seq"})
        rng = np.random.default_rng(1)
        toks = rng.integers(0, 256, size=(8, 64)).astype(np.int32)
        variables = dense_mod.init(jax.random.PRNGKey(1), toks[:1])
        lm = SH.seq_shard_lm(seq_mod, variables,
                             SH.serving_mesh({"seq": 8}),
                             inputCol="tokens", outputCol="logits",
                             batchSize=8)
        table = DataTable({"tokens": toks})
        out_seq = np.asarray(lm.transform(table)["logits"])
        dense = TPUModel.from_flax(dense_mod, variables,
                                   inputCol="tokens",
                                   outputCol="logits", batchSize=8)
        out_dense = np.asarray(dense.transform(table)["logits"])
        # ring/Ulysses reorder the attention reduction: the pinned
        # serving tolerance for the f32 LM (bf16 would widen it)
        assert np.allclose(out_seq, out_dense, atol=5e-5), \
            np.abs(out_seq - out_dense).max()

    def test_wrong_module_refused(self, forced_host_device_count):
        dense_mod = build_network({"type": "transformer",
                                   "vocab_size": 64, "dim": 16,
                                   "depth": 1, "heads": 2,
                                   "max_len": 32})
        variables = dense_mod.init(
            jax.random.PRNGKey(0),
            np.zeros((1, 8), np.int32))
        with pytest.raises(ValueError, match="seq_axis"):
            SH.seq_shard_lm(dense_mod, variables,
                            SH.serving_mesh({"seq": 8}))


class TestShardedAOT:
    """Sharded AOT artifacts: export on a mesh, load in a fresh
    process, serve with zero JIT traces at request time."""

    def test_pipeline_artifact_roundtrip(self, tmp_path,
                                         forced_host_device_count):
        pm, table = _fitted_pipeline()
        fused = SH.data_shard_pipeline(pm, SH.serving_mesh(),
                                       batch_size=64)
        ref = np.asarray(fuse(pm).transform(table)["prediction"])
        example = DataTable({"a": table["a"][:2], "b": table["b"][:2]})
        art = str(tmp_path / "sharded_pipe")
        man = AOT.export_model(fused, example, art, version="v1")
        assert man["sharded"] and man["mesh"] == {"data": 8}

        loaded = AOT.load_model(art)
        assert loaded.aot and loaded.sharding is not None
        stage = json_scoring_pipeline(loaded)
        reqs = [{"entity": json.dumps(
            {"a": float(table["a"][i]),
             "b": float(np.nan_to_num(table["b"][i]))}).encode()}
            for i in range(8)]
        rt = DataTable({"id": [str(i) for i in range(8)],
                        "request": reqs})
        out = stage.transform(rt)
        got = [r["prediction"] for r in out["reply"]]
        # parity against the single-device oracle, via the AOT programs
        # with ZERO jit traces (nan rows re-impute identically)
        ref_rows = [int(ref[i]) for i in range(8)]
        assert got == ref_rows
        assert loaded.jit_cache_misses == 0

    def test_fresh_process_zero_traces_and_coldstart(
            self, tmp_path, forced_host_device_count):
        oracle, model, toks = _tp_model()
        SH.tensor_shard_model(model, SH.serving_mesh({"model": 8}))
        art = str(tmp_path / "sharded_tp")
        man = AOT.export_model(model, {"tokens": toks[:2]}, art,
                               version="v1")
        assert man["sharded"]

        def run(mode):
            out = subprocess.run(
                [sys.executable, "-m", "mmlspark_tpu.serving.aot",
                 art, "--mode", mode, "--port", str(_free_port())],
                capture_output=True, text=True, timeout=240,
                cwd=os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))))
            assert out.returncode == 0, out.stderr[-2000:]
            return json.loads(out.stdout.strip().splitlines()[-1])

        aot_res = run("aot")
        assert aot_res["ok"]
        # the acceptance bar: a multi-chip replica cold-starts with
        # zero Python traces — at load AND at request time
        assert aot_res["jit_traces_total"] == 0
        assert aot_res["jit_traces_at_request_time"] == 0

    def test_zoo_accepts_sharded_manifest_and_measures_device_cost(
            self, tmp_path, forced_host_device_count):
        oracle, model, toks = _tp_model()
        SH.tensor_shard_model(model, SH.serving_mesh({"model": 8}))
        art_root = tmp_path / "zoo"
        art = art_root / "lm" / "v1"
        AOT.export_model(model, {"tokens": toks[:2]}, str(art),
                         version="v1")
        zoo = ModelZoo(artifact_root=str(art_root), memory_probe=None)
        try:
            assert zoo.resolve("lm") == "lm@v1"
            meta = zoo.lookup("lm@v1")[2]
            assert meta.get("sharded") and meta.get("mesh") == \
                {"model": 8}
            zoo.get("lm@v1")       # activate (loader thread)
            stats = zoo.stats()
            row = next(r for r in stats["models"]
                       if r["model"] == "lm")
            assert row["state"] == "resident"
            # cost = MEASURED per-device residency summed across the
            # mesh, not the manifest file bytes
            meta = zoo.lookup("lm@v1")[2]
            assert meta["cost_source"] == "device"
            total_logical = sum(
                int(np.asarray(a).nbytes) for a in
                jax.tree_util.tree_leaves(model.get("weights")))
            # replicated small leaves count once per device, so the
            # measured mesh-wide residency is at least the logical size
            assert row["cost_bytes"] >= total_logical
        finally:
            zoo.close()


class TestFleetStartupProbe:
    """connect() must tolerate a not-yet-listening engine process."""

    def test_slow_starting_worker_does_not_open_circuit(self):
        port = _free_port()
        p = subprocess.Popen(
            [sys.executable, _WORKER, str(port), "0",
             "--scorer", "echo", "--start-delay", "2.0"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        try:
            fleet = ServingFleet.connect(
                [f"http://127.0.0.1:{port}"],
                failure_threshold=3, wait_ready_s=60.0,
                tracing=False)
            # the startup probe burned NO breaker budget: first post
            # succeeds and the circuit never opened
            rep = fleet.post({"x": 1}, timeout=30)
            assert rep == {"echo": 1, "worker": 0}
            assert fleet.breakers[0].state == "closed"
            assert fleet.breakers[0].times_opened == 0
            assert fleet.transport_errors == 0
            fleet.post({"__shutdown__": True})
        finally:
            p.terminate()
            p.wait(timeout=30)

    def test_wait_ready_budget_bounded(self):
        # nothing ever listens: the probe gives up within its budget
        # instead of hanging, and the fleet still constructs
        dead = f"http://127.0.0.1:{_free_port()}"
        t0 = time.monotonic()
        fleet = ServingFleet.connect([dead], wait_ready_s=1.0,
                                     tracing=False)
        assert time.monotonic() - t0 < 10.0
        assert fleet.breakers[0].state == "closed"


class TestMultiProcessFleet:
    """Real engine processes behind ServingFleet.connect: identical
    predictions across workers, chaos kill under load."""

    def _spawn_workers(self, n, dim=8):
        procs, addrs = [], []
        for wid in range(n):
            port = _free_port()
            p = subprocess.Popen(
                [sys.executable, _WORKER, str(port), str(wid),
                 "--scorer", "linear", "--dim", str(dim),
                 "--batch-size", "32"],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True)
            procs.append(p)
            addrs.append(None)
        for wid, p in enumerate(procs):
            line = p.stdout.readline().strip()
            parts = line.split()
            assert parts and parts[0] == "READY", line
            addrs[wid] = parts[2]
        return procs, addrs

    def test_chaos_kill_one_engine_under_columnar_load(self):
        from mmlspark_tpu.core.trace import Tracer
        nworkers, dim = 3, 8
        procs, addrs = self._spawn_workers(nworkers, dim=dim)
        tracer = Tracer(enabled=True)
        try:
            fleet = ServingFleet.connect(addrs, wait_ready_s=60.0,
                                         failure_threshold=2,
                                         breaker_cooldown=1.0,
                                         tracer=tracer, tracing=True)
            rng = np.random.default_rng(3)
            rows = rng.normal(size=(4, dim)).astype(np.float32)
            # every worker computes the same seeded weights: establish
            # the expected reply once
            expected = fleet.post_columns({"features": rows})
            assert len(expected["prediction"]) == 4

            results = {"ok": 0, "failed": 0, "wrong": 0}
            lock = threading.Lock()
            stop = threading.Event()

            def client():
                while not stop.is_set():
                    try:
                        rep = fleet.post_columns({"features": rows},
                                                 timeout=30)
                        ok = rep == expected
                        with lock:
                            results["ok" if ok else "wrong"] += 1
                    except Exception:  # noqa: BLE001
                        with lock:
                            results["failed"] += 1

            threads = [threading.Thread(target=client, daemon=True)
                       for _ in range(4)]
            for t in threads:
                t.start()
            time.sleep(1.0)
            # SIGKILL one engine process mid-load — the crashed-
            # process chaos shape, across a REAL process boundary
            procs[0].send_signal(signal.SIGKILL)
            time.sleep(3.0)
            stop.set()
            for t in threads:
                t.join(timeout=10)
            total = sum(results.values())
            assert total > 20, results
            availability = results["ok"] / total
            # the acceptance floor: kill one of three engines under
            # load, availability holds >= 99% via breaker + failover
            assert availability >= 0.99, (availability, results)
            assert results["wrong"] == 0, results

            # one trace id across the surviving legs: some logical
            # post failed over — its trace holds BOTH the failed leg
            # and the winning sibling under one trace id
            traces = tracer.buffer.traces()
            multi = [tr for tr in traces
                     if len([s for s in tr.spans()
                             if s.name == "client.post"]) >= 2]
            assert multi, "no failover trace captured"
            tr = multi[0]
            assert len({s.trace_id for s in tr.spans()}) == 1
            legs = [s for s in tr.spans() if s.name == "client.post"]
            assert len({s.attrs.get("address") for s in legs}) >= 2
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                p.wait(timeout=30)


class TestShardedAudit:
    """tools/check_fusion_kernels.py sharded-serving audit."""

    def test_shipped_builders_clean(self):
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        import check_fusion_kernels as CK
        assert CK.check_sharded_serving() == []

    def test_catches_inferred_shardings(self):
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        import check_fusion_kernels as CK
        bad = (
            "def _jit_sharded(self, donate):\n"
            "    return jax.jit(fn, donate_argnums=(1,))\n")
        vs = CK.check_sharded_jit_source("x.py", "_jit_sharded", bad)
        assert vs and "in_shardings" in vs[0]
        partial = (
            "def _jit_sharded(self, donate):\n"
            "    return jax.jit(fn, in_shardings=(a, b))\n")
        vs = CK.check_sharded_jit_source("x.py", "_jit_sharded",
                                         partial)
        assert vs and "out_shardings" in vs[0]
        good = (
            "def _jit_sharded(self, donate):\n"
            "    return jax.jit(fn, in_shardings=(a, b),\n"
            "                   out_shardings=c)\n")
        assert CK.check_sharded_jit_source("x.py", "_jit_sharded",
                                           good) == []
