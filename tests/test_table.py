import numpy as np
import pytest

from mmlspark_tpu.core import schema as S
from mmlspark_tpu.core.schema import Field, ImageSchema, Schema
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.testing.datagen import generate_table, make_basic_table
from mmlspark_tpu.testing.equality import assert_table_equal


def test_construction_and_types():
    t = make_basic_table()
    assert len(t) == 4
    assert t.schema["numbers"].tag == S.I64
    assert t.schema["words"].tag == S.STRING
    assert isinstance(t["numbers"], np.ndarray)


def test_row_count_mismatch():
    with pytest.raises(ValueError):
        DataTable({"a": [1, 2, 3], "b": [1, 2]})


def test_vector_column_dense():
    t = DataTable({"v": np.ones((5, 3))})
    assert t.schema["v"].tag == S.VECTOR
    assert t["v"].shape == (5, 3)


def test_ragged_vector_column():
    t = DataTable({"v": [np.ones(2), np.ones(3)]})
    assert t.schema["v"].tag == S.VECTOR
    assert isinstance(t["v"], list)


def test_with_column_drop_select_rename():
    t = make_basic_table()
    t2 = t.with_column("doubled", t["numbers"] * 2)
    assert list(t2["doubled"]) == [0, 2, 4, 6]
    t3 = t2.drop("words")
    assert "words" not in t3.column_names
    t4 = t3.select("numbers", "doubled")
    assert t4.column_names == ["numbers", "doubled"]
    t5 = t4.rename({"doubled": "x2"})
    assert "x2" in t5.column_names
    # original untouched
    assert "doubled" not in t.column_names


def test_filter_slice_sort_shuffle():
    t = make_basic_table()
    f = t.filter(t["numbers"] > 1)
    assert list(f["numbers"]) == [2, 3]
    f2 = t.filter(lambda r: r["words"] == "bass")
    assert len(f2) == 1
    s = t.sort_by("numbers", ascending=False)
    assert list(s["numbers"]) == [3, 2, 1, 0]
    sh = t.shuffle(seed=42)
    assert sorted(sh["numbers"]) == [0, 1, 2, 3]


def test_rows_roundtrip():
    t = make_basic_table()
    t2 = DataTable.from_rows(t.to_rows())
    assert_table_equal(t, t2)


def test_concat_and_shards():
    t = make_basic_table()
    c = DataTable.concat([t, t])
    assert len(c) == 8
    shards = c.repartition(3).shards()
    assert len(shards) == 3
    assert sum(len(s) for s in shards) == 8


def test_batches():
    t = generate_table(n_rows=10)
    bs = list(t.batches(3))
    assert [len(b) for b in bs] == [3, 3, 3, 1]


def test_image_struct_inference():
    row = ImageSchema.make_row("a.png", np.zeros((4, 5, 3), dtype=np.uint8))
    t = DataTable({"image": [row]})
    f = t.schema["image"]
    assert ImageSchema.is_image(f)


def test_pandas_roundtrip():
    t = make_basic_table()
    df = t.to_pandas()
    t2 = DataTable.from_pandas(df)
    assert_table_equal(t, t2, check_schema=False)


def test_save_load(tmp_path):
    t = make_basic_table().with_column("vec", np.arange(8).reshape(4, 2) * 1.0)
    p = str(tmp_path / "table")
    t.save(p)
    t2 = DataTable.load(p)
    assert_table_equal(t, t2)


def test_find_unused_name():
    t = make_basic_table()
    assert t.schema.find_unused_name("numbers") == "numbers_1"
    assert t.schema.find_unused_name("fresh") == "fresh"


def test_categorical_metadata():
    t = make_basic_table()
    f = S.set_categorical_levels(t.schema["words"], ["a", "b"])
    t2 = t.with_field(f)
    assert S.get_categorical_levels(t2.schema["words"]) == ["a", "b"]
    # json roundtrip preserves meta
    s2 = Schema.from_json(t2.schema.to_json())
    assert S.get_categorical_levels(s2["words"]) == ["a", "b"]


def test_distinct_values():
    t = DataTable({"a": [1, 2, 2, 3], "b": ["x", "x", "y", "z"]})
    assert sorted(t.distinct_values("a")) == [1, 2, 3]
    assert sorted(t.distinct_values("b")) == ["x", "y", "z"]


class TestFluentAPI:
    """df.mlTransform sugar (ref: core/spark FluentAPI.scala:12-24)."""

    def test_ml_transform_chain(self):
        import numpy as np
        from mmlspark_tpu.stages import DropColumns, RenameColumn
        t = DataTable({"a": np.arange(4.0), "b": np.arange(4.0) * 2})
        out = t.ml_transform(RenameColumn(inputCol="a", outputCol="a2"),
                             DropColumns(cols=["b"]))
        assert out.column_names == ["a2"]

    def test_ml_transform_fits_estimators_inline(self):
        import numpy as np
        from mmlspark_tpu.stages import ValueIndexer
        t = DataTable({"cat": ["x", "y", "x", "z"]})
        out = t.ml_transform(ValueIndexer(inputCol="cat", outputCol="ci"))
        assert sorted(set(out["ci"])) == [0.0, 1.0, 2.0]

    def test_ml_fit(self):
        import numpy as np
        from mmlspark_tpu.stages import ValueIndexer
        t = DataTable({"cat": ["x", "y"]})
        model = t.ml_fit(ValueIndexer(inputCol="cat", outputCol="ci"))
        assert len(model.transform(t)) == 2
