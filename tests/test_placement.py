"""Fleet-wide placement plane (serving/placement.py): demand-driven
replica counts, residency-aware balanced packing, sticky assignments,
stale-route fallback, fleet-global eviction through the zoo's
invariants, and the placement events on the registry timeline."""

import json
import time

import pytest

from mmlspark_tpu.serving.placement import (
    PlacementController, PlacementEvent,
)
from mmlspark_tpu.serving.zoo import RESIDENT, UNLOADED, ModelZoo, ZooEvent
from mmlspark_tpu.stages.basic import Lambda


def echo_stage(tag):
    def handle(table):
        replies = []
        for r in table["request"]:
            row = (json.loads(r["entity"].decode())
                   if r.get("entity") else {})
            replies.append({"served_by": tag, "x": row.get("x")})
        return table.with_column("reply", replies)
    return Lambda.apply(handle)


def fresh_zoo(n_models=4, **kw):
    kw.setdefault("memory_probe", None)
    zoo = ModelZoo(**kw)
    for i in range(n_models):
        zoo.register_factory(f"m{i}", "v1",
                             (lambda i=i: echo_stage(f"m{i}")))
    return zoo


class _Recorder:
    """A zoo stand-in: the event timeline plus residency-cost rows."""

    def __init__(self, costs=None):
        self.events = []
        self._costs = dict(costs or {})

    def record_event(self, event):
        self.events.append(event)

    def stats(self):
        rows = [{"model": k.partition("@")[0],
                 "version": k.partition("@")[2] or "v1",
                 "cost_bytes": v} for k, v in self._costs.items()]
        return {"models": rows}


def _drive(ctl, model, n):
    for _ in range(n):
        ctl.record_request(model)


class TestPlacementController:
    def test_hot_gets_replicas_cold_gets_one(self):
        ctl = PlacementController(None, n_engines=4, hot_share=0.5)
        _drive(ctl, "hot", 30)
        _drive(ctl, "cold", 1)
        ctl.rebuild(force=True)
        counts = ctl.replica_counts()
        assert counts["hot"] >= 2
        assert counts["cold"] == 1

    def test_every_demanded_model_stays_servable(self):
        ctl = PlacementController(None, n_engines=2)
        for m in ("a", "b", "c", "d"):
            _drive(ctl, m, 3)
        plan = ctl.rebuild(force=True)
        assert set(plan) == {"a", "b", "c", "d"}
        assert all(len(v) >= 1 for v in plan.values())
        assert all(0 <= i < 2 for v in plan.values() for i in v)

    def test_max_replicas_caps_hot_models(self):
        ctl = PlacementController(None, n_engines=4, max_replicas=1)
        _drive(ctl, "hot", 50)
        ctl.rebuild(force=True)
        assert ctl.replica_counts()["hot"] == 1

    def test_residency_aware_packing_spreads_cost(self):
        rec = _Recorder(costs={"a@v1": 100, "b@v1": 100})
        ctl = PlacementController(rec, n_engines=2, hot_share=0.9)
        _drive(ctl, "a", 5)
        _drive(ctl, "b", 5)
        plan = ctl.rebuild(force=True)
        # two equal-cost single-replica models land on DIFFERENT
        # engines (balanced packing), not both on engine 0
        assert plan["a"] != plan["b"]

    def test_assignments_are_sticky_across_rebuilds(self):
        rec = _Recorder()
        ctl = PlacementController(rec, n_engines=3)
        _drive(ctl, "a", 5)
        _drive(ctl, "b", 5)
        first = ctl.rebuild(force=True)
        n_events = len(rec.events)
        second = ctl.rebuild(force=True)
        assert second == first
        # no assign/unassign churn — only the rebuild summary lands
        new = rec.events[n_events:]
        assert [e.kind for e in new] == ["rebuild"]

    def test_rebuild_is_rate_limited(self):
        ctl = PlacementController(None, n_engines=2,
                                  rebuild_min_interval_s=600.0)
        _drive(ctl, "a", 3)
        ctl.rebuild(force=True)
        n = ctl.rebuilds
        _drive(ctl, "b", 30)
        plan = ctl.rebuild()               # inside the min interval
        assert ctl.rebuilds == n
        assert "b" not in plan             # the frozen plan, unchanged

    def test_mark_engine_dead_reassigns_immediately(self):
        ctl = PlacementController(None, n_engines=2, hot_share=0.1)
        _drive(ctl, "hot", 20)
        ctl.rebuild(force=True)
        assert ctl.replica_counts()["hot"] == 2
        ctl.mark_engine_dead(0)
        plan = ctl.assignments()
        assert 0 not in plan["hot"] and plan["hot"] == (1,)
        ctl.mark_engine_alive(0)
        assert ctl.rebuild(force=True)["hot"] == (0, 1)

    def test_stale_route_counted_for_unknown_model(self):
        ctl = PlacementController(None, n_engines=2)
        assert ctl.engines_for("never-seen") == []
        assert ctl.stale_routes == 1

    def test_timeline_events_carry_engine_deltas(self):
        rec = _Recorder()
        ctl = PlacementController(rec, n_engines=2, hot_share=0.1)
        _drive(ctl, "hot", 20)
        ctl.rebuild(force=True, reason="demand")
        kinds = [e.kind for e in rec.events]
        assert kinds == ["assign", "rebuild"]
        assign = rec.events[0]
        assert isinstance(assign, PlacementEvent)
        assert assign.model == "hot"
        assert assign.stats["engines"] == [0, 1]
        assert rec.events[1].stats["models"] == 1
        ctl.mark_engine_dead(1)
        unassigns = [e for e in rec.events if e.kind == "unassign"]
        assert unassigns and unassigns[0].stats["engines"] == [1]
        assert unassigns[0].reason == "engine1_dead"


class TestPlacementEviction:
    def test_evict_coldest_offers_coldest_first(self):
        zoo = fresh_zoo(n_models=3)
        ctl = PlacementController(zoo, n_engines=2)
        try:
            zoo.get("m0")
            zoo.get("m1")
            _drive(ctl, "m0", 30)
            _drive(ctl, "m1", 1)
            assert ctl.evict_coldest(keep=1) == "m1"
            assert zoo.lookup("m1@v1")[1] == UNLOADED
            assert zoo.lookup("m0@v1")[1] == RESIDENT
        finally:
            zoo.close()

    def test_zoo_invariants_arbitrate_every_offer(self):
        zoo = fresh_zoo(n_models=3)
        ctl = PlacementController(zoo, n_engines=2)
        try:
            zoo.get("m0")
            zoo.get("m1")
            _drive(ctl, "m0", 30)
            _drive(ctl, "m1", 1)
            # the coldest model has parked waiters somewhere in the
            # fleet: the zoo refuses; the NEXT coldest is offered, but
            # keep=1 protects the hottest — nothing is evicted
            zoo.add_waiter("m1")
            assert ctl.evict_coldest(keep=1) is None
            assert zoo.lookup("m1@v1")[1] == RESIDENT
            # outstanding batches refuse the same way
            zoo.remove_waiter("m1")
            handle, state, _ = zoo.acquire("m1")
            assert state == RESIDENT
            assert ctl.evict_coldest(keep=1) is None
            handle.release()
            assert ctl.evict_coldest(keep=1) == "m1"
        finally:
            zoo.close()

    def test_demand_for_unregistered_spec_is_skipped(self):
        zoo = fresh_zoo(n_models=1)
        ctl = PlacementController(zoo, n_engines=1)
        try:
            _drive(ctl, "ghost", 1)
            _drive(ctl, "m0", 5)
            assert ctl.evict_coldest(keep=1) is None
        finally:
            zoo.close()


class TestFleetPlacement:
    def _fleet(self, base_port, n_models=3, **kw):
        from mmlspark_tpu.serving.fleet import ServingFleet
        zoo = fresh_zoo(n_models=n_models)
        fleet = ServingFleet(n_engines=2, base_port=base_port, zoo=zoo,
                             tracing=False)
        ctl = fleet.attach_placement(**kw)
        return fleet, zoo, ctl

    def test_hot_cold_plan_with_one_activation(self):
        fleet, zoo, ctl = self._fleet(20410, rebuild_min_interval_s=0.0)
        try:
            for i in range(20):
                assert fleet.post({"x": i},
                                  model="m0")["served_by"] == "m0"
            for i in range(2):
                assert fleet.post({"x": i},
                                  model="m1")["served_by"] == "m1"
            ctl.rebuild(force=True)
            counts = ctl.replica_counts()
            assert counts["m0"] == 2 and counts["m1"] == 1
            # the engines share ONE zoo: replicating m0 across both
            # engines never re-loaded it
            rows = {r["model"]: r for r in zoo.stats()["models"]}
            assert rows["m0"]["loads"] == 1
            text = fleet.metrics_text()
            assert "serving_placement_rebuilds_total" in text
            assert 'serving_placement_replicas{model="m0"} 2' in text
            assert "serving_placement_rebuild_ms_bucket" in text
        finally:
            fleet.stop_all()
            zoo.close()

    def test_stale_route_falls_back_and_lazily_activates(self):
        fleet, zoo, ctl = self._fleet(20430,
                                      rebuild_min_interval_s=600.0)
        try:
            ctl.rebuild(force=True)        # empty plan, then frozen
            sr0 = ctl.stale_routes
            out = fleet.post({"x": 9}, model="m2")
            assert out["served_by"] == "m2"     # any engine + lazy load
            assert ctl.stale_routes > sr0
        finally:
            fleet.stop_all()
            zoo.close()

    def test_routes_prefer_assigned_engines(self):
        fleet, zoo, ctl = self._fleet(20450,
                                      rebuild_min_interval_s=600.0)
        try:
            ctl.rebuild(force=True)
            with ctl._lock:
                ctl._assignments = {"m1": (1,)}
            seen0 = [e.source.requests_seen for e in fleet.engines]
            for i in range(6):
                assert fleet.post({"x": i},
                                  model="m1")["served_by"] == "m1"
            seen1 = [e.source.requests_seen for e in fleet.engines]
            assert seen1[1] - seen0[1] == 6
            assert seen1[0] - seen0[0] == 0
            # the engine dies (placement-plane view): the plan
            # reassigns and traffic follows without a config change
            ctl.mark_engine_dead(1)
            assert ctl.assignments()["m1"] == (0,)
            for i in range(3):
                assert fleet.post({"x": i},
                                  model="m1")["served_by"] == "m1"
            seen2 = [e.source.requests_seen for e in fleet.engines]
            assert seen2[0] - seen1[0] == 3
        finally:
            fleet.stop_all()
            zoo.close()

    def test_timeline_interleaves_zoo_and_placement_events(self):
        fleet, zoo, ctl = self._fleet(20470, rebuild_min_interval_s=0.0)
        try:
            fleet.post({"x": 0}, model="m0")
            fleet.post({"x": 1}, model="m1")
            ctl.rebuild(force=True)
            classes = {type(e).__name__ for e in zoo.events}
            assert {"ZooEvent", "PlacementEvent"} <= classes
            stamps = [e.at for e in zoo.events]
            assert stamps == sorted(stamps)
        finally:
            fleet.stop_all()
            zoo.close()


class TestFabricLazyExports:
    def test_import_serving_does_not_load_the_fabric(self):
        """`import mmlspark_tpu.serving` must stay host-only cheap:
        the placement plane and the shm transport load only when an
        export is actually touched (PEP 562)."""
        import os
        import subprocess
        import sys
        code = (
            "import sys\n"
            "import mmlspark_tpu.serving as sv\n"
            "assert 'mmlspark_tpu.serving.placement' not in sys.modules\n"
            "assert 'mmlspark_tpu.io.shm' not in sys.modules\n"
            "ctl = sv.PlacementController(None, n_engines=2)\n"
            "assert 'mmlspark_tpu.serving.placement' in sys.modules\n"
            "assert sv.shm_available() in (True, False)\n"
            "assert 'mmlspark_tpu.io.shm' in sys.modules\n"
            "ring = sv.ShmRing(nslots=1, slot_bytes=4096)\n"
            "ring.close()\n"
            "print('LAZY_OK')\n"
        )
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        out = subprocess.run(
            [sys.executable, "-c", code], cwd=repo, text=True,
            capture_output=True, timeout=240,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 0, out.stderr
        assert "LAZY_OK" in out.stdout
