"""Out-of-core ingest (io/ooc.py), the mergeable quantile sketch
(gbdt/sketch.py), streaming fits (BinMapper.fit_streaming, Featurize /
StandardScaler / ValueIndexer), chunked fused execution, sketch-backed
SummarizeData, and the no-materialize static audit."""

import os

import numpy as np
import pytest

from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.gbdt.binning import BinMapper
from mmlspark_tpu.gbdt.sketch import (
    QuantileSketch, merge_sketch_lists,
)
from mmlspark_tpu.io.ooc import ChunkedTable, table_nbytes, write_arrow_ipc


def _cdf(sorted_x, v):
    return np.searchsorted(sorted_x, v, side="left") / len(sorted_x)


def _pair_drift(sorted_x, cuts_a, cuts_b):
    """Max |F(a_k) - F(b_k)| over paired cuts (the rank-space distance
    between two boundary sets)."""
    m = min(len(cuts_a), len(cuts_b))
    assert m > 0
    return max(abs(_cdf(sorted_x, a) - _cdf(sorted_x, b))
               for a, b in zip(cuts_a[:m], cuts_b[:m]))


class TestQuantileSketch:
    N = 200_000

    def _data(self, seed=0):
        return np.random.default_rng(seed).normal(size=self.N)

    def test_cuts_within_certificate_of_exact_fit(self):
        x = self._data()
        sk = QuantileSketch(b=512)
        for i in range(0, len(x), 23_000):
            sk.update(x[i:i + 23_000])
        assert not sk.exact and 0 < sk.eps() < 0.01
        exact = BinMapper.fit(x.reshape(-1, 1), max_bin=255,
                              sample_cnt=len(x)).upper_bounds[0]
        cuts = sk.cuts(255)
        assert len(cuts) == len(exact)
        xs = np.sort(x)
        # each sketch cut within the measured certificate (plus the
        # exact walk's own discreteness slack) of its exact counterpart
        # cut-placement bound: 2x the query certificate (gap
        # midpoints) plus the exact walk's own discreteness slack
        assert _pair_drift(xs, cuts, exact) <= 2 * sk.eps() + 2.0 / 255

    def test_merge_equals_concatenation_within_bound(self):
        x = self._data(1)
        a = QuantileSketch(b=512).update(x[:120_000])
        b = QuantileSketch(b=512).update(x[120_000:])
        a.merge(b)
        assert a.count == len(x)
        one = QuantileSketch(b=512).update(x)
        xs = np.sort(x)
        bound = 2 * (a.eps() + one.eps()) + 1e-9
        assert _pair_drift(xs, a.cuts(255), one.cuts(255)) <= bound
        for q in (0.01, 0.25, 0.5, 0.75, 0.99):
            assert abs(_cdf(xs, a.query(q)) - q) <= a.eps() + 1e-4

    def test_order_invariance_across_chunk_permutations(self):
        x = self._data(2)
        chunks = [x[i:i + 17_000] for i in range(0, len(x), 17_000)]
        fwd = QuantileSketch(b=512)
        rev = QuantileSketch(b=512)
        for c in chunks:
            fwd.update(c)
        perm = np.random.default_rng(3).permutation(len(chunks))
        for i in perm:
            rev.update(chunks[i])
        assert fwd.count == rev.count == len(x)
        xs = np.sort(x)
        bound = 2 * (fwd.eps() + rev.eps()) + 1e-9
        assert _pair_drift(xs, fwd.cuts(255), rev.cuts(255)) <= bound

    def test_nan_inf_routing_matches_binmapper_fit(self):
        # fit drops non-finite values before choosing boundaries; the
        # sketch must do exactly the same (and count the drops)
        rng = np.random.default_rng(4)
        clean = rng.normal(size=5000)
        dirty = np.concatenate([clean, [np.nan] * 7, [np.inf] * 3,
                                [-np.inf] * 2])
        rng.shuffle(dirty)
        sk = QuantileSketch().update(dirty)
        assert sk.dropped == 12 and sk.count == 5000
        exact = BinMapper.fit(clean.reshape(-1, 1), max_bin=63,
                              sample_cnt=6000).upper_bounds[0]
        assert np.array_equal(sk.cuts(63), exact)
        # transform-time routing is untouched: NaN -> bin 0, ±inf edges
        m = BinMapper.fit_streaming([dirty.reshape(-1, 1)], max_bin=63)
        probe = np.asarray([[np.nan], [np.inf], [-np.inf]])
        bins = m.transform(probe)[:, 0]
        ref = BinMapper(
            [np.asarray(exact)], 63).transform(probe)[:, 0]
        assert np.array_equal(bins, ref)

    def test_degenerate_empty_and_single_chunk(self):
        empty = QuantileSketch()
        assert empty.count == 0 and empty.eps() == 0.0
        assert len(empty.cuts(255)) == 0
        assert np.isnan(empty.query(0.5))
        one = QuantileSketch().update(np.asarray([3.0]))
        assert len(one.cuts(255)) == 0      # <=1 distinct: no cuts
        const = QuantileSketch().update(np.full(1000, 2.5))
        assert len(const.cuts(255)) == 0
        # single small chunk stays EXACT: bit-equal to one-shot fit
        x = np.random.default_rng(5).normal(size=4000)
        sk = QuantileSketch().update(x)
        assert sk.exact and sk.eps() == 0.0
        exact = BinMapper.fit(x.reshape(-1, 1), max_bin=255,
                              sample_cnt=5000).upper_bounds[0]
        assert np.array_equal(sk.cuts(255), exact)

    def test_wire_roundtrip_and_multihost_merge(self):
        x = np.random.default_rng(6).normal(size=60_000)
        host_a = [QuantileSketch().update(x[:30_000])]
        host_b = [QuantileSketch().update(x[30_000:])]
        wires = [host_a[0].to_wire(512), host_b[0].to_wire(512)]
        rebuilt = [[QuantileSketch.from_wire(w)] for w in wires]
        merged = merge_sketch_lists(rebuilt)
        assert merged[0].count == len(x)
        xs = np.sort(x)
        ref = QuantileSketch().update(x)
        bound = 2 * (merged[0].eps() + ref.eps()) + 1e-9
        assert _pair_drift(xs, merged[0].cuts(255),
                           ref.cuts(255)) <= bound
        # determinism: same inputs, same order -> identical cuts
        again = merge_sketch_lists(
            [[QuantileSketch.from_wire(w)] for w in wires])
        assert np.array_equal(merged[0].cuts(255), again[0].cuts(255))


class TestFitStreaming:
    def test_streaming_cuts_within_certificate(self):
        rng = np.random.default_rng(7)
        X = np.column_stack([rng.normal(size=150_000),
                             rng.lognormal(size=150_000)])
        chunks = [X[i:i + 20_000] for i in range(0, len(X), 20_000)]
        m = BinMapper.fit_streaming(iter(chunks), max_bin=127)
        exact = BinMapper.fit(X, max_bin=127, sample_cnt=len(X))
        assert 0 < m.sketch_eps < 0.01
        for j in range(X.shape[1]):
            xs = np.sort(X[:, j])
            assert _pair_drift(xs, m.upper_bounds[j],
                               exact.upper_bounds[j]) \
                <= 2 * m.sketch_eps + 2.0 / 127

    def test_f32_stream_keeps_device_binning_eligible(self):
        rng = np.random.default_rng(8)
        X = rng.normal(size=(30_000, 3)).astype(np.float32)
        m = BinMapper.fit_streaming(
            [X[:10_000], X[10_000:]], max_bin=63)
        assert m.f32_cuts_exact and m.f32_safe()
        # snapped cuts: f32 binning == f64 binning for every row
        b64 = m.transform(X.astype(np.float64))
        from mmlspark_tpu.gbdt import binning as B
        import jax.numpy as jnp
        dev = np.asarray(B.bucketize_fm_device(
            jnp.asarray(X), jnp.asarray(m.bounds_matrix())))
        assert np.array_equal(dev, b64.T)

    def test_single_small_chunk_bit_equal_to_fit(self):
        rng = np.random.default_rng(9)
        X = rng.normal(size=(8_000, 2))
        m = BinMapper.fit_streaming([X], max_bin=255)
        exact = BinMapper.fit(X, max_bin=255, sample_cnt=10_000)
        for a, b in zip(m.upper_bounds, exact.upper_bounds):
            assert np.array_equal(a, b)
        assert m.sketch_eps == 0.0

    def test_empty_stream_raises(self):
        with pytest.raises(ValueError, match="empty chunk stream"):
            BinMapper.fit_streaming(iter([]))

    def test_sketch_json_roundtrip_via_mapper(self):
        X = np.random.default_rng(10).normal(size=(5_000, 2))
        m = BinMapper.fit_streaming([X], max_bin=63)
        rt = BinMapper.from_json(m.to_json())
        assert rt.sketch_eps == m.sketch_eps
        for a, b in zip(m.upper_bounds, rt.upper_bounds):
            assert np.array_equal(a, b)


class TestChunkedTable:
    def _table(self, n=3000, seed=0):
        rng = np.random.default_rng(seed)
        return DataTable({
            "a": rng.normal(size=n),
            "b": rng.normal(size=n).astype(np.float32),
            "cat": [f"l{int(i)}" for i in rng.integers(0, 5, n)],
            "toks": [[f"w{int(t)}" for t in rng.integers(0, 9, 3)]
                     for _ in range(n)],
            "vec": rng.normal(size=(n, 4)).astype(np.float32),
        })

    def test_from_table_replay_and_stats(self):
        t = self._table()
        ct = ChunkedTable.from_table(t, chunk_rows=512)
        assert sum(len(c) for c in ct) == len(t)
        # replayable: a second pass sees everything again
        assert sum(len(c) for c in ct.chunks()) == len(t)
        s = ct.stats.snapshot()
        assert s["rows"] == 2 * len(t) and s["peak_chunk_bytes"] > 0
        assert ct.stats.tracked_peak_bytes() >= s["peak_chunk_bytes"]
        assert ct.num_rows == len(t)
        assert list(ct.schema.names) == list(t.schema.names)

    def test_arrow_ipc_roundtrip(self, tmp_path):
        t = self._table()
        path = os.path.join(tmp_path, "t.arrow")
        assert write_arrow_ipc(t, path, chunk_rows=700) == len(t)
        ct = ChunkedTable.from_arrow_ipc(path, chunk_rows=500)
        out = ct.materialize()
        assert np.array_equal(out["a"], t["a"])
        assert np.array_equal(out["b"], t["b"])
        assert np.array_equal(out["vec"], t["vec"])
        assert list(out["cat"]) == list(t["cat"])
        assert [list(x) for x in out["toks"]] == list(t["toks"])

    def test_npy_mmap_chunks(self, tmp_path):
        t = self._table()
        pa_ = os.path.join(tmp_path, "a.npy")
        pb_ = os.path.join(tmp_path, "b.npy")
        np.save(pa_, np.asarray(t["a"]))
        np.save(pb_, np.asarray(t["b"]))
        ct = ChunkedTable.from_npy({"a": pa_, "b": pb_}, chunk_rows=999)
        out = ct.materialize()
        assert np.array_equal(out["a"], t["a"])
        assert ct.stats.snapshot()["chunks"] == 4

    def test_generator_factory_and_map(self):
        def factory():
            for i in range(4):
                yield {"x": np.full(10, float(i))}

        ct = ChunkedTable.from_generator(factory)
        doubled = ct.map(lambda c: c.with_column(
            "y", np.asarray(c["x"]) * 2))
        vals = [float(c["y"][0]) for c in doubled]
        assert vals == [0.0, 2.0, 4.0, 6.0]
        # map is lazy + replayable
        assert [float(c["y"][0]) for c in doubled] == vals

    def test_one_shot_generator_rejected(self):
        with pytest.raises(TypeError, match="ZERO-ARG factory"):
            ChunkedTable(iter([DataTable({"x": [1.0]})]))

    def test_prefetch_decodes_ahead(self):
        import threading
        seen = []

        def factory():
            for i in range(6):
                seen.append((i, threading.current_thread().name))
                yield {"x": np.full(100, float(i))}

        ct = ChunkedTable.from_generator(factory, prefetch_depth=2)
        it = ct.chunks()
        first = next(it)
        assert float(first["x"][0]) == 0.0
        # the worker thread decoded ahead of the consumer
        assert any("MainThread" not in name for _, name in seen)
        rest = [c for c in it]
        assert len(rest) == 5

    def test_nbytes_accounting(self):
        t = self._table(100)
        nb = table_nbytes(t)
        assert nb > 100 * (8 + 4 + 16)   # arrays alone exceed this


class TestChunkedPipelines:
    def _fitted(self, n=4096, seed=0):
        rng = np.random.default_rng(seed)
        t = DataTable({
            "a": rng.normal(size=n).astype(np.float32),
            "b": np.where(rng.random(n) < 0.2, np.nan,
                          rng.normal(size=n)),
            "cat": [f"l{int(i)}" for i in rng.integers(0, 8, n)],
            "toks": [[f"w{int(x)}" for x in rng.integers(0, 30, 4)]
                     for _ in range(n)],
            "label": rng.integers(0, 2, n).astype(np.float64),
        })
        from mmlspark_tpu.core.stage import Pipeline
        from mmlspark_tpu.automl.featurize import Featurize
        from mmlspark_tpu.stages.dataprep import StandardScaler
        from mmlspark_tpu.models.linear import TPULogisticRegression
        pm = Pipeline(stages=[
            Featurize(featureColumns=["a", "b", "cat", "toks"],
                      numberOfFeatures=16),
            StandardScaler(inputCol="features"),
            TPULogisticRegression(featuresCol="features",
                                  labelCol="label", maxIter=5),
        ]).fit(t)
        return t, pm

    def test_fused_chunked_bit_identical(self):
        t, pm = self._fitted()
        fused = pm.fused() if hasattr(pm, "fused") else None
        from mmlspark_tpu.core.fusion import fuse
        fused = fuse(pm)
        full = fused.transform(t.drop("label"))
        ct = ChunkedTable.from_table(t.drop("label"), chunk_rows=512)
        parts = list(fused.transform_chunked(ct))
        assert len(parts) == 8
        for col in ("prediction", "probability"):
            got = np.concatenate([np.asarray(p[col]) for p in parts])
            assert np.array_equal(got, np.asarray(full[col]))

    def test_fused_chunked_zero_recompiles_on_replay(self):
        t, pm = self._fitted()
        from mmlspark_tpu.core.fusion import fuse
        fused = fuse(pm)
        ct = ChunkedTable.from_table(t.drop("label"), chunk_rows=1024)
        out = fused.transform_chunked(ct)
        for _ in out:
            pass
        misses = fused.jit_cache_misses
        for _ in out:      # replay: same shapes, zero new traces
            pass
        assert fused.jit_cache_misses == misses

    def test_pipeline_model_chunked_transform(self):
        t, pm = self._fitted()
        full = pm.transform(t.drop("label"))
        ct = ChunkedTable.from_table(t.drop("label"), chunk_rows=777)
        got = pm.transform(ct).materialize()
        assert np.array_equal(np.asarray(got["prediction"]),
                              np.asarray(full["prediction"]))

    def test_featurize_streaming_fit_parity(self):
        t, _ = self._fitted(seed=3)
        from mmlspark_tpu.automl.featurize import Featurize
        fz = Featurize(featureColumns=["a", "b", "cat", "toks"],
                       numberOfFeatures=16)
        me = fz.fit(t)
        ms = fz.fit(ChunkedTable.from_table(t, chunk_rows=600))
        se, ss = me.get("specs"), ms.get("specs")
        assert len(se) == len(ss)
        for e, s in zip(se, ss):
            assert e["kind"] == s["kind"]
            assert e.get("levels") == s.get("levels")
            if "fill" in e:
                assert abs(e["fill"] - s["fill"]) < 1e-12
        out_e = me.transform(t)
        out_s = ms.transform(
            ChunkedTable.from_table(t, chunk_rows=600)).materialize()
        assert np.array_equal(out_e["features"], out_s["features"])

    def test_scaler_streaming_fit_parity(self):
        t, _ = self._fitted(seed=4)
        from mmlspark_tpu.automl.featurize import Featurize
        from mmlspark_tpu.stages.dataprep import StandardScaler
        feat = Featurize(featureColumns=["a", "b", "cat"],
                         ).fit(t).transform(t)
        sc = StandardScaler(inputCol="features")
        me = sc.fit(feat)
        ms = sc.fit(ChunkedTable.from_table(feat, chunk_rows=500))
        assert np.allclose(me.get("mu"), ms.get("mu"), atol=1e-5)
        assert np.allclose(me.get("sd"), ms.get("sd"), atol=1e-5)

    def test_learner_fit_chunked(self):
        # a ChunkedTable IS a replayable shard stream for TPULearner
        import jax
        from mmlspark_tpu.models.learner import TPULearner
        rng = np.random.default_rng(5)
        n = 256
        t = DataTable({
            "features": rng.normal(size=(n, 8)).astype(np.float32),
            "label": rng.integers(0, 2, n).astype(np.int64)})
        ct = ChunkedTable.from_table(t, chunk_rows=64)
        learner = TPULearner(
            networkSpec={"type": "mlp", "features": [8],
                         "num_classes": 2},
            inputShape=[8], batchSize=64, epochs=2, logEvery=1000)
        model = learner.fit(ct)
        out = model.transform(t)
        assert len(np.asarray(out["scores"])) == n

    def test_gbdt_chunked_sketch_quality_floor(self):
        # HIGGS-shaped: sketch-binned AUC within epsilon of exact-binned
        rng = np.random.default_rng(6)
        n, f = 20_000, 8
        X = rng.normal(size=(n, f))
        logits = X[:, 0] + 0.7 * X[:, 1] * X[:, 2] + 0.5 * X[:, 3]
        y = (logits + rng.normal(scale=0.7, size=n) > 0).astype(
            np.float64)
        t = DataTable({"features": X.astype(np.float32), "label": y})
        from mmlspark_tpu.gbdt.estimators import TPUBoostClassifier

        def auc_of(model):
            pred = model.transform(t)
            p = np.asarray(pred["probability"])[:, 1]
            order = np.argsort(p)
            ranks = np.empty(n)
            ranks[order] = np.arange(n)
            pos = y == 1
            np_, nn_ = pos.sum(), n - pos.sum()
            return (ranks[pos].sum() - np_ * (np_ - 1) / 2) / (np_ * nn_)

        # <16 iterations: the auto boost_chunk stays per-iteration, so
        # only the (lru-shared) length-1 chunk program compiles — the
        # tier-1 budget discipline every GBDT suite follows
        kw = dict(featuresCol="features", labelCol="label",
                  numIterations=12, numLeaves=15, maxBin=63, seed=0)
        exact = TPUBoostClassifier(**kw).fit(t)
        sketch = TPUBoostClassifier(binFit="sketch", **kw).fit(
            ChunkedTable.from_table(t, chunk_rows=4096))
        a_e, a_s = auc_of(exact), auc_of(sketch)
        # pinned forest-quality floor: sketch binning costs at most
        # 0.01 AUC vs the exact-binned fit on the same rows
        assert a_s >= a_e - 0.01, (a_s, a_e)
        assert a_e > 0.8   # the fit itself learned something

    def test_summarize_chunked_via_sketch(self):
        rng = np.random.default_rng(7)
        n = 50_000
        t = DataTable({"x": rng.lognormal(size=n),
                       "s": [f"v{i % 3}" for i in range(n)]})
        from mmlspark_tpu.stages.dataprep import SummarizeData
        sd = SummarizeData()
        exact = sd.transform(t)
        chunked = sd.transform(ChunkedTable.from_table(
            t, chunk_rows=8_000))
        ix = list(exact["Feature"]).index("x")
        for k in ("Count", "Mean", "Min", "Max", "Sample_Variance",
                  "Sample_Skewness", "Sample_Kurtosis",
                  "Unique_Value_Count", "Missing_Value_Count"):
            a = float(exact[k][ix])
            b = float(chunked[k][ix])
            assert abs(a - b) <= 1e-6 * (1.0 + abs(a)), (k, a, b)
        # percentiles through the sketch: within rank-error of exact
        xs = np.sort(np.asarray(t["x"]))
        for label, q in (("Median", 0.5), ("P25", 0.25), ("P75", 0.75),
                         ("P5", 0.05), ("P95", 0.95)):
            v = float(chunked[label][ix])
            assert abs(_cdf(xs, v) - q) < 0.005, (label, v)

    def test_summarize_chunked_nan_unique_count_matches_exact(self):
        # regression: per-chunk np.unique yields fresh NaN objects that
        # a set treats as distinct (nan != nan) — the chunked count was
        # inflated by one per chunk
        t = DataTable({"x": np.asarray(
            [1.0, np.nan, 2.0, np.nan, 3.0, np.nan, 4.0, np.nan])})
        from mmlspark_tpu.stages.dataprep import SummarizeData
        sd = SummarizeData()
        exact = float(sd.transform(t)["Unique_Value_Count"][0])
        chunked = float(sd.transform(ChunkedTable.from_table(
            t, chunk_rows=2))["Unique_Value_Count"][0])
        assert chunked == exact == 5.0

    def test_transform_chunked_tracks_prefetch_depth(self):
        # regression: the fused path iterates its source with
        # prefetch_depth=0 but buffers `depth` prepared chunks in its
        # own prefetcher — the source's tracked-bytes certificate must
        # count them
        t, pm = self._fitted(seed=9, n=2048)
        from mmlspark_tpu.core.fusion import fuse
        fused = fuse(pm)
        ct = ChunkedTable.from_table(t.drop("label"), chunk_rows=256,
                                     prefetch_depth=3)
        for _ in fused.transform_chunked(ct):
            pass
        assert ct.stats.depth == 3
        s = ct.stats.snapshot()
        assert s["tracked_peak_bytes"] == 5 * s["peak_chunk_bytes"]

    def test_gbdt_train_accepts_chunked_table(self):
        rng = np.random.default_rng(8)
        n = 6_000
        X = rng.normal(size=(n, 4))
        y = (X[:, 0] > 0).astype(np.float64)
        t = DataTable({"features": X, "label": y})
        from mmlspark_tpu.gbdt.booster import train
        booster = train({"objective": "binary", "num_iterations": 5,
                         "num_leaves": 7, "bin_fit": "sketch"},
                        ChunkedTable.from_table(t, chunk_rows=1500))
        acc = ((booster.predict(X) > 0.5) == (y == 1)).mean()
        assert acc > 0.9


class TestOOCChecker:
    def test_shipped_hot_paths_clean(self):
        import importlib
        spec = importlib.util.spec_from_file_location(
            "check_fusion_kernels",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
                "tools", "check_fusion_kernels.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.check_ooc_ingest() == []

    def test_checker_catches_materialization(self):
        import importlib
        spec = importlib.util.spec_from_file_location(
            "check_fusion_kernels2",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
                "tools", "check_fusion_kernels.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        bad = (
            "def hot(chunked):\n"
            "    rows = list(chunked.chunks())\n"
            "    big = np.concatenate([c['x'] for c in rows])\n"
            "    return chunked.materialize()\n")
        v = mod.check_ooc_source("bad", bad, 1, bad.splitlines())
        kinds = "\n".join(v)
        assert "list()" in kinds
        assert "np.concatenate" in kinds
        assert ".materialize()" in kinds

    def test_checker_honors_acknowledgment(self):
        import importlib
        spec = importlib.util.spec_from_file_location(
            "check_fusion_kernels3",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
                "tools", "check_fusion_kernels.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        ok = ("def hot(chunked):\n"
              "    return chunked.materialize()  "
              "# ooc:materialize-ok\n")
        assert mod.check_ooc_source("ok", ok, 1, ok.splitlines()) == []
