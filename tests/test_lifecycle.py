"""Model-lifecycle suite: versioned registry, zero-downtime hot swap,
canary/rollback, incremental refresh (partial_fit), and drift counters.

The chaos-under-load variants (rolling swap on a fleet with seeded
faults) live in tests/test_chaos.py; this file pins the protocol and
the incremental-update math deterministically.
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from mmlspark_tpu.core.metrics import DriftMonitor
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.models.linear import (
    TPULinearRegression, TPULogisticRegression,
)
from mmlspark_tpu.serving import (
    CanaryPolicy, ModelRegistry, SwapInProgress, serve_model,
)
from mmlspark_tpu.stages.basic import Lambda


def versioned_pipeline(version):
    """Echo pipeline that stamps its version into every reply — the
    instrument for no-mixed-version and cutover assertions."""
    def handle(table):
        return table.with_column("reply", [
            {"echo": json.loads(r["entity"].decode())["x"], "v": version}
            for r in table["request"]])
    return Lambda.apply(handle)


def _post(addr, payload, timeout=5.0):
    req = urllib.request.Request(
        addr, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


class _Load:
    """Background request stream against one engine; collects
    (status, version) per reply."""

    def __init__(self, addr, n_threads=2):
        self.addr = addr
        self.results = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads = [threading.Thread(target=self._run, args=(i,),
                                          daemon=True)
                         for i in range(n_threads)]

    def _run(self, tid):
        i = 0
        while not self._stop.is_set():
            try:
                status, body = _post(self.addr,
                                     {"x": tid * 100000 + i}, timeout=5)
                out = (status, body.get("v"))
            except Exception as e:  # noqa: BLE001 — availability metric
                out = (0, f"{type(e).__name__}")
            with self._lock:
                self.results.append(out)
            i += 1

    def __enter__(self):
        for t in self._threads:
            t.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=10)


class TestModelRegistry:
    def test_register_get_order(self):
        reg = ModelRegistry()
        reg.register("v1", "model-one", metadata={"auc": 0.9})
        reg.register("v2", "model-two")
        assert reg.get("v1") == "model-one"
        assert reg.versions() == ["v1", "v2"]
        assert reg.latest() == "v2"
        assert reg.previous("v2") == "v1"
        assert reg.previous("v1") is None
        # explicit metadata survives; precision/aot auto-recorded at
        # registration (the quantized/AOT rollout audit trail)
        assert reg.metadata("v1") == {"auc": 0.9, "precision": "f32",
                                      "aot": False}

    def test_duplicate_and_unknown_version(self):
        reg = ModelRegistry()
        reg.register("v1", object())
        with pytest.raises(ValueError, match="already registered"):
            reg.register("v1", object())
        with pytest.raises(KeyError, match="unknown model version"):
            reg.get("nope")


class TestEngineSwap:
    def test_swap_completes_under_load_no_mixed_replies(self):
        engine = serve_model(versioned_pipeline("v1"), port=20100,
                             batch_size=4, version="v1")
        try:
            with _Load(engine.source.address) as load:
                time.sleep(0.1)
                res = engine.swap(
                    versioned_pipeline("v2"), "v2",
                    policy=CanaryPolicy(fraction=0.5, min_batches=3,
                                        decision_timeout_s=20))
                assert res.completed, res.reason
                # post-cutover replies are all v2
                s, body = _post(engine.source.address, {"x": -1})
                assert s == 200 and body["v"] == "v2"
            statuses = [s for s, _ in load.results]
            versions = {v for s, v in load.results if s == 200}
            assert statuses and all(s == 200 for s in statuses)
            assert versions <= {"v1", "v2"}
            assert engine.model_version == "v2"
            assert engine.swap_state == "idle"
            assert engine.swaps_completed == 1
            assert engine.swaps_rolled_back == 0
            assert engine.swap_events[-1].kind == "completed"
        finally:
            engine.stop()

    def test_swap_warms_up_before_cutover(self):
        warmed = threading.Event()
        pipe = versioned_pipeline("v2")

        def warmup(example):
            warmed.set()
            return 0
        pipe.warmup = warmup
        engine = serve_model(versioned_pipeline("v1"), port=20110,
                             batch_size=4, version="v1")
        try:
            res = engine.swap(pipe, "v2", warmup_example={"x": [0]},
                              policy=CanaryPolicy(fraction=0.0))
            assert res.completed
            assert warmed.is_set()
            assert engine.model_version == "v2"
        finally:
            engine.stop()

    def test_warmup_failure_rolls_back(self):
        pipe = versioned_pipeline("v2")

        def warmup(example):
            raise RuntimeError("compile exploded")
        pipe.warmup = warmup
        engine = serve_model(versioned_pipeline("v1"), port=20120,
                             batch_size=4, version="v1")
        try:
            res = engine.swap(pipe, "v2", warmup_example={"x": [0]})
            assert res.rolled_back
            assert "warmup_failed" in res.reason
            assert engine.model_version == "v1"
            assert engine.swap_state == "rolled_back"
            assert engine.swaps_rolled_back == 1
            # still serving on the old version
            assert _post(engine.source.address, {"x": 5})[1]["v"] == "v1"
        finally:
            engine.stop()

    def test_warmup_requiring_example_without_one_rolls_back(self):
        pipe = versioned_pipeline("v2")
        pipe.warmup = lambda example: 0
        engine = serve_model(versioned_pipeline("v1"), port=20130,
                             batch_size=4, version="v1")
        try:
            res = engine.swap(pipe, "v2")   # no warmup_example
            assert res.rolled_back
            assert "requires an example" in res.reason
        finally:
            engine.stop()

    def test_decision_timeout_rolls_back_without_traffic(self):
        # no load -> the canary never sees a batch -> the safe default
        # is rollback, not a promote on zero evidence
        engine = serve_model(versioned_pipeline("v1"), port=20140,
                             batch_size=4, version="v1")
        try:
            res = engine.swap(
                versioned_pipeline("v2"), "v2",
                policy=CanaryPolicy(fraction=0.5, min_batches=2,
                                    decision_timeout_s=0.5))
            assert res.rolled_back
            assert res.reason.startswith("breach:decision_timeout")
            # the reason is self-explanatory: observed evidence counts
            # vs the promote threshold travel in the string itself
            assert "canary_ok=0/2 needed" in res.reason
            assert engine.model_version == "v1"
        finally:
            engine.stop()

    def test_second_swap_while_swapping_raises(self):
        engine = serve_model(versioned_pipeline("v1"), port=20150,
                             batch_size=4, version="v1")
        try:
            started = threading.Event()
            outcome = {}

            def slow_swap():
                pipe = versioned_pipeline("v2")

                def warmup(example):
                    started.set()
                    time.sleep(1.0)
                    return 0
                pipe.warmup = warmup
                outcome["res"] = engine.swap(
                    pipe, "v2", warmup_example={"x": [0]},
                    policy=CanaryPolicy(fraction=0.0))
            t = threading.Thread(target=slow_swap, daemon=True)
            t.start()
            assert started.wait(5)
            with pytest.raises(SwapInProgress):
                engine.swap(versioned_pipeline("v3"), "v3")
            t.join(timeout=10)
            assert outcome["res"].completed
        finally:
            engine.stop()

    def test_registry_records_swap_events(self):
        reg = ModelRegistry()
        reg.register("v1", versioned_pipeline("v1"))
        reg.register("v2", versioned_pipeline("v2"))
        engine = serve_model(reg.get("v1"), port=20160, batch_size=4,
                             version="v1")
        try:
            from mmlspark_tpu.serving.lifecycle import execute_swap
            res = execute_swap(engine, reg.get("v2"), "v2",
                               policy=CanaryPolicy(fraction=0.0),
                               registry=reg)
            assert res.completed
            assert [e.kind for e in reg.events] == ["completed"]
            assert reg.events[0].to_version == "v2"
        finally:
            engine.stop()

    def test_healthz_reports_lifecycle_fields(self):
        engine = serve_model(versioned_pipeline("v1"), port=20170,
                             batch_size=4, version="v1")
        try:
            assert _post(engine.source.address, {"x": 1})[0] == 200
            with urllib.request.urlopen(
                    engine.source.address + "/healthz", timeout=5) as r:
                stats = json.loads(r.read())
            m = stats["metrics"]
            assert m["model_version"] == "v1"
            assert m["swap_state"] == "idle"
            assert m["swaps_completed"] == 0
            assert m["swaps_rolled_back"] == 0
        finally:
            engine.stop()


class TestPartialFit:
    @pytest.fixture(scope="class")
    def blobs(self):
        rng = np.random.default_rng(1)
        n, d = 600, 6
        X = rng.normal(size=(n, d))
        w = rng.normal(size=d)
        y = (X @ w > 0).astype(np.float64)
        return X, y

    def test_partial_fit_none_model_is_fit(self, blobs):
        X, y = blobs
        t = DataTable({"features": X, "label": y})
        est = TPULogisticRegression(maxIter=50)
        a = est.partial_fit(t)
        b = est.fit(t)
        for key in ("W", "b"):
            np.testing.assert_array_equal(a.get("weights")[key],
                                          b.get("weights")[key])

    def test_partial_fit_deterministic(self, blobs):
        X, y = blobs
        t = DataTable({"features": X, "label": y})
        est = TPULogisticRegression(maxIter=50)
        base = est.fit(t)
        m1 = est.partial_fit(t, base)
        m2 = est.partial_fit(t, base)
        for key in ("W", "b"):
            np.testing.assert_array_equal(m1.get("weights")[key],
                                          m2.get("weights")[key])

    def test_incremental_batches_converge_to_full_refit_selection(
            self, blobs):
        # the online-refresh property: warm start + incremental batches
        # reaches the same SELECTION (predicted labels) as a full refit
        X, y = blobs
        full_t = DataTable({"features": X, "label": y})
        est = TPULogisticRegression(maxIter=200, stepSize=0.5)
        full = est.fit(full_t)
        pred_full = np.asarray(full.transform(full_t)["prediction"])
        m = est.fit(DataTable({"features": X[:200], "label": y[:200]}))
        for _epoch in range(2):
            for lo in range(0, len(y), 200):
                m = est.partial_fit(
                    DataTable({"features": X[lo:lo + 200],
                               "label": y[lo:lo + 200]}), m)
        # stats frozen at the INITIAL (first-200-rows) fit, never
        # re-derived: they cannot equal the full-table fit's
        assert not np.array_equal(m.get("weights")["mu"],
                                  full.get("weights")["mu"])
        pred_inc = np.asarray(m.transform(full_t)["prediction"])
        assert (pred_inc == pred_full).mean() >= 0.99

    def test_standardization_stats_frozen(self, blobs):
        X, y = blobs
        est = TPULogisticRegression(maxIter=20)
        base = est.fit(DataTable({"features": X, "label": y}))
        shifted = DataTable({"features": X + 10.0, "label": y})
        m = est.partial_fit(shifted, base)
        np.testing.assert_array_equal(m.get("weights")["mu"],
                                      base.get("weights")["mu"])
        np.testing.assert_array_equal(m.get("weights")["sd"],
                                      base.get("weights")["sd"])

    def test_empty_batch_is_a_noop(self, blobs):
        # an empty refresh window must not NaN the weights
        X, y = blobs
        est = TPULogisticRegression(maxIter=10)
        base = est.fit(DataTable({"features": X, "label": y}))
        empty = DataTable({"features": np.zeros((0, X.shape[1])),
                           "label": np.zeros(0)})
        m = est.partial_fit(empty, base)
        assert m is base
        lin = TPULinearRegression(maxIter=10)
        lbase = lin.fit(DataTable({"features": X,
                                   "label": X[:, 0].astype(np.float64)}))
        assert lin.partial_fit(empty, lbase) is lbase

    def test_label_outside_warm_classes_rejected(self, blobs):
        X, y = blobs
        est = TPULogisticRegression(maxIter=10)
        base = est.fit(DataTable({"features": X, "label": y}))
        bad = DataTable({"features": X[:10],
                         "label": np.full(10, 5.0)})
        with pytest.raises(ValueError, match="classes"):
            est.partial_fit(bad, base)

    def test_linear_partial_fit_converges(self, blobs):
        X, _ = blobs
        rng = np.random.default_rng(3)
        w = rng.normal(size=X.shape[1])
        y = X @ w + rng.normal(scale=0.05, size=len(X))
        t = DataTable({"features": X, "label": y})
        est = TPULinearRegression(maxIter=200)
        full = est.fit(t)
        m = est.fit(DataTable({"features": X[:300], "label": y[:300]}))
        for _epoch in range(3):
            for lo in range(0, len(y), 300):
                m = est.partial_fit(
                    DataTable({"features": X[lo:lo + 300],
                               "label": y[lo:lo + 300]}), m)
        pf = np.asarray(full.transform(t)["prediction"])
        pi = np.asarray(m.transform(t)["prediction"])
        assert np.corrcoef(pf, pi)[0, 1] > 0.999


class TestDriftMonitor:
    def test_in_distribution_traffic_shows_no_drift(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(2000, 5))
        dm = DriftMonitor.from_matrix(X)
        dm.observe(X[:500])
        dm.observe(X[500:900])
        s = dm.summary()
        assert s["rows"] == 900
        assert s["max_abs_mean_delta_sigma"] < 0.3
        assert 0.7 < s["max_var_ratio"] < 1.3
        assert s["null_rate"] == 0.0

    def test_shifted_traffic_flags_the_right_feature(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(2000, 5))
        dm = DriftMonitor.from_matrix(X)
        served = X[:400].copy()
        served[:, 3] += 5.0
        dm.observe(served)
        s = dm.summary()
        assert s["max_abs_mean_delta_sigma"] > 3.0
        assert s["worst_feature"] == 3

    def test_null_rate_counts_nan_and_inf(self):
        X = np.zeros((100, 2))
        dm = DriftMonitor.from_matrix(np.random.default_rng(1).normal(
            size=(100, 2)))
        X[:10, 0] = np.nan
        X[:5, 1] = np.inf
        dm.observe(X)
        assert dm.summary()["null_rate"] == pytest.approx(15 / 200)

    def test_batched_observe_matches_one_shot(self):
        rng = np.random.default_rng(2)
        ref = rng.normal(size=(500, 3))
        X = rng.normal(loc=0.3, size=(400, 3))
        a = DriftMonitor.from_matrix(ref)
        b = DriftMonitor.from_matrix(ref)
        a.observe(X)
        for lo in range(0, 400, 64):
            b.observe(X[lo:lo + 64])
        sa, sb = a.snapshot(), b.snapshot()
        np.testing.assert_allclose(sa["mean"], sb["mean"], rtol=1e-10)
        np.testing.assert_allclose(sa["var"], sb["var"], rtol=1e-8)

    def test_model_drift_monitor_hook(self):
        rng = np.random.default_rng(4)
        X = rng.normal(size=(300, 4))
        y = (X[:, 0] > 0).astype(np.float64)
        model = TPULogisticRegression(maxIter=20).fit(
            DataTable({"features": X, "label": y}))
        dm = model.drift_monitor()
        dm.observe(X)
        assert dm.summary()["max_abs_mean_delta_sigma"] < 0.2


class TestServingDriftExport:
    def test_drift_rides_healthz(self):
        import jax
        from mmlspark_tpu.models.tpu_model import TPUModel
        from mmlspark_tpu.serving.fleet import json_scoring_pipeline
        rng = np.random.default_rng(0)
        Xfit = rng.normal(size=(256, 8)).astype(np.float32)
        dm = DriftMonitor.from_matrix(Xfit)
        W = rng.normal(size=(8, 3)).astype(np.float32)
        model = TPUModel(
            modelFn=lambda w, ins: list(ins.values())[0] @ w["W"],
            weights={"W": W}, inputCol="features", outputCol="scores",
            batchSize=16)
        del jax
        engine = serve_model(
            json_scoring_pipeline(model, drift_monitor=dm),
            port=20180, batch_size=16, version="v1")
        try:
            for i in range(4):
                status, body = _post(
                    engine.source.address,
                    {"features": (Xfit[i] + 2.0).tolist()})
                assert status == 200 and "prediction" in body
            m = engine.metrics()
            drift = m["pipeline_stage"]["drift"]
            assert drift["rows"] == 4
            assert drift["max_abs_mean_delta_sigma"] > 0.5
        finally:
            engine.stop()
