"""torch checkpoint -> flax import fidelity tests.

The reference ingests externally-trained graphs (CNTKModel.scala:147
deserializes a trained CNTK Function; ModelDownloader.scala:209 fetches
zoo CNNs). Here: torch "twin" models are trained briefly IN TORCH (so the
weights were genuinely not produced by this framework), exported as
state_dicts, imported, and verified to reproduce torch's outputs; then an
imported model is published through the zoo and driven by ImageFeaturizer
for inference + transfer learning.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
import torch.nn as tnn  # noqa: E402

from mmlspark_tpu.core.schema import ImageSchema  # noqa: E402
from mmlspark_tpu.core.table import DataTable  # noqa: E402
from mmlspark_tpu.downloader import LocalRepo, ModelDownloader  # noqa: E402
from mmlspark_tpu.importers import (  # noqa: E402
    import_torch_checkpoint, load_torch_file,
)
from mmlspark_tpu.models.networks import build_network  # noqa: E402
from mmlspark_tpu.stages.featurizer import ImageFeaturizer  # noqa: E402


# -- torch twins (torchvision-style naming) ---------------------------------


class TBlock(tnn.Module):
    def __init__(self, cin, cout, stride):
        super().__init__()
        self.conv1 = tnn.Conv2d(cin, cout, 3, stride, 1, bias=False)
        self.bn1 = tnn.BatchNorm2d(cout)
        self.conv2 = tnn.Conv2d(cout, cout, 3, 1, 1, bias=False)
        self.bn2 = tnn.BatchNorm2d(cout)
        self.downsample = None
        if stride != 1 or cin != cout:
            self.downsample = tnn.Sequential(
                tnn.Conv2d(cin, cout, 1, stride, bias=False),
                tnn.BatchNorm2d(cout))

    def forward(self, x):
        idt = x if self.downsample is None else self.downsample(x)
        y = torch.relu(self.bn1(self.conv1(x)))
        y = self.bn2(self.conv2(y))
        return torch.relu(idt + y)


class TResNet(tnn.Module):
    def __init__(self, stages=(2, 2, 2), width=16, classes=10):
        super().__init__()
        self.conv1 = tnn.Conv2d(3, width, 3, 1, 1, bias=False)
        self.bn1 = tnn.BatchNorm2d(width)
        cin = width
        for s, n in enumerate(stages):
            cout = width * 2 ** s
            blocks = []
            for b in range(n):
                stride = 2 if (s > 0 and b == 0) else 1
                blocks.append(TBlock(cin, cout, stride))
                cin = cout
            setattr(self, f"layer{s + 1}", tnn.Sequential(*blocks))
        self.n_stages = len(stages)
        self.fc = tnn.Linear(cin, classes)

    def forward(self, x):
        x = torch.relu(self.bn1(self.conv1(x)))
        for s in range(self.n_stages):
            x = getattr(self, f"layer{s + 1}")(x)
        x = x.mean(dim=(2, 3))
        return self.fc(x)


class TConvNet(tnn.Module):
    def __init__(self, convs=(16, 16), dense=(32,), classes=10):
        super().__init__()
        cin = 3
        for i, c in enumerate(convs):
            setattr(self, f"conv{i}", tnn.Conv2d(cin, c, 3, 1, 1))
            cin = c
        self.n_convs = len(convs)
        self.n_dense = len(dense)
        flat = cin * (16 // 2 ** len(convs)) ** 2
        for i, d in enumerate(dense):
            setattr(self, f"dense{i}", tnn.Linear(flat, d))
            flat = d
        self.head = tnn.Linear(flat, classes)

    def forward(self, x):
        for i in range(self.n_convs):
            x = torch.relu(getattr(self, f"conv{i}")(x))
            x = torch.max_pool2d(x, 2, 2)
        x = x.flatten(1)
        for i in range(self.n_dense):
            x = torch.relu(getattr(self, f"dense{i}")(x))
        return self.head(x)


class TBiLSTM(tnn.Module):
    """torch twin of BiLSTMTagger (notebook-304's pretrained family)."""

    def __init__(self, vocab=30, embed=8, hidden=6, tags=4):
        super().__init__()
        self.embed = tnn.Embedding(vocab, embed)
        self.lstm = tnn.LSTM(embed, hidden, batch_first=True,
                             bidirectional=True)
        self.head = tnn.Linear(2 * hidden, tags)

    def forward(self, tokens):
        h, _ = self.lstm(self.embed(tokens))
        return self.head(h)


class TTBlock(tnn.Module):
    """GPT-2-shaped pre-LN decoder block, fused qkv, tanh-gelu."""

    def __init__(self, d, heads):
        super().__init__()
        self.ln1 = tnn.LayerNorm(d, eps=1e-6)
        self.qkv = tnn.Linear(d, 3 * d)
        self.proj = tnn.Linear(d, d)
        self.ln2 = tnn.LayerNorm(d, eps=1e-6)
        self.mlp_up = tnn.Linear(d, 4 * d)
        self.mlp_down = tnn.Linear(4 * d, d)
        self.heads = heads

    def forward(self, x):
        b, l, d = x.shape
        hd = d // self.heads
        q, k, v = self.qkv(self.ln1(x)).chunk(3, dim=-1)
        q = q.view(b, l, self.heads, hd).transpose(1, 2)
        k = k.view(b, l, self.heads, hd).transpose(1, 2)
        v = v.view(b, l, self.heads, hd).transpose(1, 2)
        a = tnn.functional.scaled_dot_product_attention(
            q, k, v, is_causal=True)
        x = x + self.proj(a.transpose(1, 2).reshape(b, l, d))
        y = tnn.functional.gelu(self.mlp_up(self.ln2(x)),
                                approximate="tanh")
        return x + self.mlp_down(y)


class TTransformer(tnn.Module):
    def __init__(self, vocab=50, d=16, depth=2, heads=4, max_len=10):
        super().__init__()
        self.embed = tnn.Embedding(vocab, d)
        self.pos_embed = tnn.Parameter(torch.randn(max_len, d) * 0.02)
        for i in range(depth):
            setattr(self, f"block_{i}", TTBlock(d, heads))
        self.depth = depth
        self.ln_f = tnn.LayerNorm(d, eps=1e-6)
        self.lm_head = tnn.Linear(d, vocab)

    def forward(self, tokens):
        x = self.embed(tokens) + self.pos_embed[:tokens.shape[1]]
        for i in range(self.depth):
            x = getattr(self, f"block_{i}")(x)
        return self.lm_head(self.ln_f(x))


class TMLP(tnn.Module):
    def __init__(self, dims=(20, 16, 8), classes=3):
        super().__init__()
        self.dense0 = tnn.Linear(dims[0], dims[1])
        self.dense1 = tnn.Linear(dims[1], dims[2])
        self.head = tnn.Linear(dims[2], classes)

    def forward(self, x):
        return self.head(torch.relu(self.dense1(torch.relu(self.dense0(x)))))


def _train_briefly(model, x, y, steps=5):
    """A few real SGD steps in torch so the exported weights (incl. BN
    running stats) were genuinely produced outside this framework."""
    opt = torch.optim.SGD(model.parameters(), lr=0.05)
    loss_fn = tnn.CrossEntropyLoss()
    model.train()
    for _ in range(steps):
        opt.zero_grad()
        loss = loss_fn(model(x), y)
        loss.backward()
        opt.step()
    model.eval()
    return model


RESNET_SPEC = {"type": "resnet", "stage_sizes": [2, 2, 2], "width": 16,
               "num_classes": 10}


@pytest.fixture(scope="module")
def trained_torch_resnet():
    torch.manual_seed(0)
    model = TResNet(stages=(2, 2, 2), width=16, classes=10)
    x = torch.randn(32, 3, 32, 32)
    y = torch.randint(0, 10, (32,))
    return _train_briefly(model, x, y)


class TestTorchImportFidelity:
    def test_resnet_outputs_match(self, trained_torch_resnet):
        model = trained_torch_resnet
        variables = import_torch_checkpoint(
            model.state_dict(), RESNET_SPEC,
            validate_input_shape=[32, 32, 3])
        xt = torch.randn(4, 3, 32, 32)
        with torch.no_grad():
            ref = model(xt).numpy()
        mod = build_network(RESNET_SPEC)
        got = np.asarray(mod.apply(
            variables, jnp.asarray(xt.permute(0, 2, 3, 1).numpy())))
        np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-4)

    def test_convnet_outputs_match(self):
        torch.manual_seed(1)
        model = TConvNet(convs=(16, 16), dense=(32,), classes=10)
        x = torch.randn(16, 3, 16, 16)
        y = torch.randint(0, 10, (16,))
        _train_briefly(model, x, y, steps=3)
        spec = {"type": "convnet", "conv_features": [16, 16],
                "dense_features": [32], "num_classes": 10}
        variables = import_torch_checkpoint(
            model.state_dict(), spec, validate_input_shape=[16, 16, 3])
        xt = torch.randn(4, 3, 16, 16)
        with torch.no_grad():
            ref = model(xt).numpy()
        got = np.asarray(build_network(spec).apply(
            variables, jnp.asarray(xt.permute(0, 2, 3, 1).numpy())))
        np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-4)

    def test_mlp_outputs_match(self):
        torch.manual_seed(2)
        model = TMLP(dims=(20, 16, 8), classes=3).eval()
        spec = {"type": "mlp", "features": [16, 8], "num_classes": 3}
        variables = import_torch_checkpoint(model.state_dict(), spec)
        xt = torch.randn(8, 20)
        with torch.no_grad():
            ref = model(xt).numpy()
        got = np.asarray(build_network(spec).apply(
            variables, jnp.asarray(xt.numpy())))
        np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-4)

    def test_bilstm_outputs_match(self):
        # the pretrained Bi-LSTM ingestion path (notebook-304 parity):
        # gate packing (i,f,g,o), kernel transposes, and the summed
        # ih+hh biases must reproduce torch's per-token outputs exactly
        torch.manual_seed(3)
        model = TBiLSTM(vocab=30, embed=8, hidden=6, tags=4).eval()
        spec = {"type": "bilstm", "vocab_size": 30, "embed_dim": 8,
                "hidden": 6, "num_tags": 4}
        variables = import_torch_checkpoint(
            model.state_dict(), spec, validate_input_shape=[7])
        toks = torch.randint(0, 30, (3, 7))
        with torch.no_grad():
            ref = model(toks).numpy()
        got = np.asarray(build_network(spec).apply(
            variables, jnp.asarray(toks.numpy())))
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_transformer_outputs_match(self):
        # GPT-2-shaped decoder ingestion: fused-qkv packing, pre-LN,
        # causal attention, and tanh-gelu must reproduce torch logits
        torch.manual_seed(4)
        model = TTransformer(vocab=50, d=16, depth=2, heads=4,
                             max_len=10).eval()
        spec = {"type": "transformer", "vocab_size": 50, "dim": 16,
                "depth": 2, "heads": 4, "max_len": 10}
        variables = import_torch_checkpoint(
            model.state_dict(), spec, validate_input_shape=[10])
        toks = torch.randint(0, 50, (2, 10))
        with torch.no_grad():
            ref = model(toks).numpy()
        got = np.asarray(build_network(spec).apply(
            variables, jnp.asarray(toks.numpy())))
        np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-3)

    def test_pt_file_roundtrip(self, trained_torch_resnet, tmp_path):
        path = str(tmp_path / "resnet.pt")
        torch.save(trained_torch_resnet.state_dict(), path)
        sd = load_torch_file(path)
        variables = import_torch_checkpoint(
            sd, RESNET_SPEC, validate_input_shape=[32, 32, 3])
        assert "batch_stats" in variables

    def test_strict_rejects_unused_keys(self, trained_torch_resnet):
        sd = dict(trained_torch_resnet.state_dict())
        sd["mystery.weight"] = torch.zeros(3)
        with pytest.raises(ValueError, match="not consumed"):
            import_torch_checkpoint(sd, RESNET_SPEC)

    def test_missing_key_reported(self):
        with pytest.raises(KeyError, match="missing"):
            import_torch_checkpoint({"conv1.weight": torch.zeros(8, 3, 3, 3)},
                                    RESNET_SPEC)


class TestImportedZooModel:
    """Publish torch-trained weights through the zoo and run them with
    ImageFeaturizer: pretrained inference + transfer learning on weights
    this repo did not train (VERDICT item 4; ref: ImageFeaturizer.scala
    setModel(ModelSchema) + ModelDownloader flow)."""

    @pytest.fixture(scope="class")
    def zoo(self, trained_torch_resnet, tmp_path_factory):
        tmp = tmp_path_factory.mktemp("torch_zoo")
        variables = import_torch_checkpoint(
            trained_torch_resnet.state_dict(), RESNET_SPEC,
            validate_input_shape=[32, 32, 3])
        repo = LocalRepo(str(tmp / "repo"))
        mod = build_network(RESNET_SPEC)
        schema = repo.publish(
            "ResNet_cifar_torch", RESNET_SPEC, variables,
            dataset="CIFAR", model_type="image", input_shape=[32, 32, 3],
            layer_names=mod.feature_layers())
        dl = ModelDownloader(str(tmp / "cache"), repo=repo)
        return dl, schema, trained_torch_resnet

    def _image_table(self, imgs):
        rows = [ImageSchema.make_row(f"img{i}", im, "RGB")
                for i, im in enumerate(imgs)]
        return DataTable({"image": rows})

    def test_featurizer_runs_imported_model(self, zoo):
        dl, schema, _ = zoo
        feat = ImageFeaturizer.from_model_schema(
            schema, dl, cutOutputLayers=1)   # cut head -> pooled features
        rng = np.random.default_rng(0)
        t = self._image_table(
            [rng.integers(0, 255, (32, 32, 3)).astype(np.uint8)
             for _ in range(4)])
        out = feat.transform(t)
        assert out["features"].shape == (4, 64)   # width*4 pooled

    def test_head_logits_match_torch(self, zoo):
        # cutOutputLayers=0 keeps the head: full pretrained inference must
        # agree with torch on the same images
        dl, schema, tmodel = zoo
        feat = ImageFeaturizer.from_model_schema(
            schema, dl, cutOutputLayers=0, scaleImage=True)
        rng = np.random.default_rng(1)
        imgs = [rng.integers(0, 255, (32, 32, 3)).astype(np.uint8)
                for _ in range(3)]
        out = feat.transform(self._image_table(imgs))
        xt = torch.tensor(np.stack(imgs), dtype=torch.float32) \
            .permute(0, 3, 1, 2) / 255.0
        with torch.no_grad():
            ref = tmodel(xt).numpy()
        np.testing.assert_allclose(out["features"], ref,
                                   rtol=1e-3, atol=1e-4)

    def test_transfer_learning_on_imported_features(self, zoo):
        # bright vs dark images, classified from pretrained features by a
        # GBDT head — the notebook-305 transfer-learning shape
        dl, schema, _ = zoo
        feat = ImageFeaturizer.from_model_schema(
            schema, dl, cutOutputLayers=1)
        rng = np.random.default_rng(2)
        imgs, labels = [], []
        for i in range(40):
            base = 40 if i % 2 == 0 else 180
            imgs.append(np.clip(rng.normal(base, 30, (32, 32, 3)), 0, 255)
                        .astype(np.uint8))
            labels.append(float(i % 2))
        t = feat.transform(self._image_table(imgs))
        t = t.with_column("label", np.asarray(labels))
        from mmlspark_tpu.gbdt import TPUBoostClassifier
        model = TPUBoostClassifier(numIterations=15, maxBin=32).fit(t)
        acc = (model.transform(t)["prediction"] == np.asarray(labels)).mean()
        assert acc > 0.9
