"""Windowed SLO engine tests: WindowedCounter/WindowedHistogram
(including multithreaded hammers — no lost updates, buckets expire
exactly once), golden-value burn-rate math (fast burn fires at 14.4x,
stays quiet on slow noise, resolves when the window drains), the
flight recorder, the tools/check_metrics.py static audit, and the
end-to-end chaos acceptance: an error-rate spike flips /healthz to
degraded with a named burn-rate alert, emits grammar-valid
serving_slo_* families, auto-captures a flight-recorder bundle, and
resolves after recovery.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from mmlspark_tpu.core.metrics import WindowedCounter, WindowedHistogram
from mmlspark_tpu.core.slo import (
    SLO, AlertEvent, BurnRateRule, SLOMonitor, default_rules,
)
from mmlspark_tpu.core.flightrecorder import FlightRecorder
from mmlspark_tpu.core.trace import Tracer
from mmlspark_tpu.serving.server import serve_model
from mmlspark_tpu.stages.basic import Lambda

from test_observability import validate_prom_text


class _FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# windowed primitives
# ---------------------------------------------------------------------------


class TestWindowedCounter:
    def test_windowed_totals(self):
        clock = _FakeClock(100.0)
        c = WindowedCounter(bucket_s=1.0, horizon_s=10.0, clock=clock)
        c.inc(2)
        clock.advance(3)
        c.inc(5)
        assert c.total(1) == 5            # current bucket only
        assert c.total(10) == 7
        assert c.cumulative == 7
        clock.advance(8)                  # first bucket ages out of 10s
        assert c.total(10) == 5
        assert c.rate(10) == pytest.approx(0.5)
        assert c.cumulative == 7          # cumulative never decays

    def test_bucket_expires_exactly_once_on_wrap(self):
        clock = _FakeClock(0.0)
        c = WindowedCounter(bucket_s=1.0, horizon_s=4.0, clock=clock)
        c.inc(3)                          # epoch 0
        clock.advance(c.n_slots * 1.0)    # same SLOT, new epoch
        c.inc(1)
        assert c.total(1) == 1, "stale slot must rezero, not add"
        assert c.cumulative == 4

    def test_series_oldest_first_with_gaps(self):
        clock = _FakeClock(50.0)
        c = WindowedCounter(bucket_s=1.0, horizon_s=10.0, clock=clock)
        c.inc(1)
        clock.advance(2)
        c.inc(4)
        series = c.series(4)
        assert [v for _, v in series] == [0.0, 1.0, 0.0, 4.0]
        assert series[0][0] < series[-1][0]

    def test_hammer_no_lost_updates_under_rotation(self):
        """8 threads inc through a real clock with 2ms buckets — many
        rotations happen mid-run; the cumulative count and the
        full-horizon windowed total must both be exact."""
        c = WindowedCounter(bucket_s=0.002, horizon_s=60.0)
        n_threads, n_incs = 8, 4000

        def work(_t):
            for _ in range(n_incs):
                c.inc()

        threads = [threading.Thread(target=work, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.cumulative == n_threads * n_incs
        assert c.total(60.0) == n_threads * n_incs


class TestWindowedHistogram:
    def test_windowed_snapshot_and_percentile(self):
        clock = _FakeClock(100.0)
        h = WindowedHistogram(bucket_s=1.0, horizon_s=20.0, clock=clock)
        h.observe(10.0)
        clock.advance(5)
        for _ in range(99):
            h.observe(1.0)
        h.observe(400.0)
        snap = h.snapshot(3)
        assert snap["count"] == 100       # the old 10.0 aged out of 3s
        assert snap["max"] == 400.0
        assert h.percentile(50, 3) <= 2.0
        assert h.percentile(99.9, 3) >= 100.0
        full = h.snapshot(20)
        assert full["count"] == 101
        # prometheus-compatible shape
        assert sum(snap["counts"]) == snap["count"]
        assert len(snap["bounds"]) == len(snap["counts"])

    def test_bucket_expires_exactly_once_on_wrap(self):
        clock = _FakeClock(0.0)
        h = WindowedHistogram(bucket_s=1.0, horizon_s=3.0, clock=clock)
        h.observe(5.0)
        clock.advance(h.n_slots * 1.0)
        h.observe(7.0)
        snap = h.snapshot(1)
        assert snap["count"] == 1 and snap["sum"] == 7.0

    def test_hammer_no_lost_updates(self):
        h = WindowedHistogram(bucket_s=0.002, horizon_s=60.0)
        n_threads, n_obs = 8, 3000
        stop = threading.Event()
        bad = []

        def reader():
            while not stop.is_set():
                snap = h.snapshot(60.0)
                if sum(snap["counts"]) != snap["count"]:
                    bad.append(snap)

        rt = threading.Thread(target=reader)
        rt.start()

        def work(seed):
            for i in range(n_obs):
                h.observe(float((i + seed) % 13))

        threads = [threading.Thread(target=work, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stop.set()
        rt.join()
        assert not bad, f"torn snapshots: {bad[:2]}"
        snap = h.snapshot(60.0)
        total = n_threads * n_obs
        assert snap["count"] == total
        expected = sum(float((i + s) % 13) for s in range(n_threads)
                       for i in range(n_obs))
        assert snap["sum"] == expected   # small ints: f64-exact


# ---------------------------------------------------------------------------
# burn-rate math (golden values)
# ---------------------------------------------------------------------------


def _monitor(clock, min_events=4, label_cap=16):
    """Availability 99.9% with the workbook fast/slow rules scaled to
    test-sized windows: fast 14.4x over 60s/10s, slow 6x over 60s/30s."""
    return SLOMonitor(
        slos=[SLO("availability", target=0.999)],
        rules=[BurnRateRule("fast_burn", 60.0, 10.0, 14.4,
                            min_events=min_events),
               BurnRateRule("slow_burn", 60.0, 30.0, 6.0,
                            min_events=min_events)],
        windows=(10.0, 60.0), label_cap=label_cap,
        bucket_s=1.0, hist_bucket_s=1.0, horizon_s=120.0, clock=clock)


class TestBurnRateGolden:
    def test_fast_burn_fires_at_14_4x(self):
        """Golden value: a 2% error rate against a 0.1% budget is a
        20x burn — over threshold in BOTH windows — and the measured
        burn rate is exactly errors/total/budget."""
        clock = _FakeClock()
        mon = _monitor(clock)
        for i in range(1000):             # 2% errors, spread over 8s
            mon.record(i % 50 != 0, 10.0)
            if i % 125 == 124:
                clock.advance(1)
        fired = mon.evaluate()
        names = [a.name for a in fired]
        assert "availability:fast_burn" in names
        alert = next(a for a in fired
                     if a.name == "availability:fast_burn")
        assert alert.burn_short == pytest.approx(20.0, rel=0.05)
        assert alert.burn_long == pytest.approx(20.0, rel=0.05)
        assert mon.degraded

    def test_quiet_on_slow_noise(self):
        """0.5% errors = 5x burn: below the 14.4x fast gate AND below
        the 6x slow gate — no alert, not degraded."""
        clock = _FakeClock()
        mon = _monitor(clock)
        for i in range(2000):             # 0.5% errors
            mon.record(i % 200 != 0, 10.0)
            if i % 250 == 249:
                clock.advance(1)
        assert mon.evaluate() == []
        assert not mon.degraded
        assert mon.burn_rate(mon.slos[0], 10.0) == pytest.approx(
            5.0, rel=0.1)

    def test_min_events_guard(self):
        """One error at tiny traffic is a huge burn RATE but must not
        page: min_events gates the blip."""
        clock = _FakeClock()
        mon = _monitor(clock, min_events=4)
        mon.record(False, 10.0)
        mon.record(True, 10.0)
        assert mon.evaluate() == []
        assert mon.burn_rate(mon.slos[0], 10.0) > 100

    def test_resolves_when_window_drains(self):
        clock = _FakeClock()
        mon = _monitor(clock)
        events = []
        mon.record_event = events.append
        for i in range(200):              # 10% errors — hard burn
            mon.record(i % 10 != 0, 10.0)
        assert mon.evaluate(), "burn did not fire"
        assert mon.degraded
        # recovery: the error events age out of the short window
        clock.advance(11)
        for _ in range(50):
            mon.record(True, 10.0)
        mon.evaluate()
        assert not any(a.name == "availability:fast_burn"
                       for a in mon.alerts.active())
        kinds = [e.kind for e in events
                 if isinstance(e, AlertEvent)]
        assert "alert_fired" in kinds and "alert_resolved" in kinds
        stats = mon.alerts.stats()
        assert stats["fired_total"] >= 1
        assert stats["resolved_total"] >= 1

    def test_no_refire_while_active(self):
        clock = _FakeClock()
        mon = _monitor(clock)
        for i in range(200):
            mon.record(i % 5 != 0, 10.0)
        assert mon.evaluate()
        fired_total = mon.alerts.stats()["fired_total"]
        for i in range(100):              # still burning
            mon.record(i % 5 != 0, 10.0)
        assert mon.evaluate() == []       # same identity: no re-fire
        assert mon.alerts.stats()["fired_total"] == fired_total

    def test_latency_slo_slow_requests_spend_budget(self):
        clock = _FakeClock()
        mon = SLOMonitor(
            slos=[SLO("latency_p99", "latency", target=0.99,
                      latency_threshold_ms=100.0)],
            rules=[BurnRateRule("fast_burn", 60.0, 10.0, 14.4,
                                min_events=4)],
            windows=(10.0, 60.0), bucket_s=1.0, hist_bucket_s=1.0,
            horizon_s=120.0, clock=clock)
        for i in range(500):              # 20% slow vs 1% budget = 20x
            mon.record(True, 500.0 if i % 5 == 0 else 10.0)
        fired = mon.evaluate()
        assert [a.name for a in fired] == ["latency_p99:fast_burn"]
        assert fired[0].burn_short == pytest.approx(20.0, rel=0.05)

    def test_per_model_streams_capped_and_alert_named(self):
        clock = _FakeClock()
        mon = _monitor(clock, label_cap=2)
        for m in ("m0", "m1", "m2", "m3"):
            for i in range(100):
                # m1 burns; engine-level stream untouched
                mon.record(not (m == "m1" and i % 5 == 0), 10.0,
                           model=m, include_engine=False)
        labels = mon.model_labels()
        assert len(labels) <= 3           # 2 named + _other
        assert "_other" in labels
        fired = mon.evaluate()
        assert any(a.name == "availability:fast_burn:m1"
                   for a in fired)
        # the engine-level stream saw nothing
        assert mon.error_rate(60.0) == 0.0

    def test_default_rules_are_the_workbook_pair(self):
        rules = {r.name: r for r in default_rules()}
        assert rules["fast_burn"].factor == 14.4
        assert rules["fast_burn"].short_window_s == 300.0
        assert rules["slow_burn"].factor == 6.0

    def test_horizon_clamp_copies_rules_not_mutates(self):
        """Review regression: clamping rules to the monitor horizon
        must not mutate the caller's (possibly shared) rule objects —
        a second monitor sizing its horizon FROM the same rules must
        still see the full 6h window."""
        rule = BurnRateRule("slow_burn", 21600.0, 1800.0, 6.0)
        mon = SLOMonitor(rules=[rule], horizon_s=3600.0)
        assert mon.rules[0].long_window_s == 3600.0   # clamped copy
        assert rule.long_window_s == 21600.0          # caller untouched
        mon2 = SLOMonitor(rules=[rule], horizon_s=None)
        assert mon2.horizon_s == 21600.0
        assert mon2.rules[0].long_window_s == 21600.0


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_bundle_is_self_contained_and_json_safe(self):
        clock = _FakeClock()
        mon = _monitor(clock)
        mon.record(False, 50.0)
        tracer = Tracer(enabled=True)
        tr = tracer.new_trace("request")
        tr.root.error("boom")
        tracer.finish(tr)
        rec = FlightRecorder(min_interval_s=0.0)
        try:
            rec.attach_tracer(tracer, label="engine test")
            rec.attach_slo("engine", mon)
            demo_event = type(
                "DemoEvent", (),
                {"kind": "demo", "at": 1.0,
                 "__repr__": lambda s: "DemoEvent(demo)"})()
            rec.add_event_source("events", lambda: [demo_event])
            rec.add_stats_source("engine", lambda: {"qps": 10})
            bundle = rec.dump_bundle("unit")
            text = json.dumps(bundle)     # fully JSON-safe
            assert "boom" in text
            assert bundle["slo"]["engine"]["status"]["degraded"] \
                is False
            assert bundle["slo"]["engine"]["series"]["errors"]
            assert bundle["stats"]["engine"] == {"qps": 10}
            events = bundle["traces"]["traceEvents"]
            assert any(e.get("ph") == "M" for e in events)
        finally:
            rec.close()

    def test_trigger_rate_limited_and_async(self):
        clock = _FakeClock()
        rec = FlightRecorder(min_interval_s=30.0, clock=clock)
        try:
            t1 = rec.trigger("one")
            assert t1 is not None       # capture scheduled (a thread)
            assert rec.trigger("two") is None        # suppressed
            clock.advance(31)
            t3 = rec.trigger("three")
            assert t3 is not None
            # captures run OFF the triggering thread (the breaker-trip
            # / SLO-tick hot paths); join to observe the results
            t1.join(timeout=10)
            t3.join(timeout=10)
            stats = rec.stats()
            assert stats["triggers_seen"] == 3
            assert stats["triggers_captured"] == 2
            assert stats["triggers_rate_limited"] == 1
            assert len(rec.bundles) == 2
            assert [b["reason"] for b in rec.bundles] == ["one", "three"]
        finally:
            rec.close()

    def test_log_ring_bounded_and_captured(self):
        from mmlspark_tpu.core.logging_utils import get_logger
        rec = FlightRecorder(min_interval_s=0.0, log_capacity=32)
        try:
            logger = get_logger("slo-test")
            for i in range(100):
                logger.warning("chaos event %d", i)
            bundle = rec.dump_bundle("logs")
            msgs = [r["msg"] for r in bundle["logs"]]
            assert len(msgs) <= 32
            assert "chaos event 99" in msgs
            assert "chaos event 0" not in msgs       # bounded ring
        finally:
            rec.close()

    def test_circuit_on_open_fires_only_on_closed_to_open(self):
        """Review regression: a sustained outage re-trips the breaker
        from HALF_OPEN every cooldown; firing on_open each time would
        churn the recorder's bounded bundle deque until the ORIGINAL
        incident's bundle is evicted. Only closed->open fires."""
        from mmlspark_tpu.utils.resilience import CircuitBreaker
        opened = []
        clock = _FakeClock()
        b = CircuitBreaker(failure_threshold=1, cooldown=5.0,
                           clock=clock)
        b.on_open = opened.append
        b.record_failure()                 # CLOSED -> OPEN
        assert len(opened) == 1
        clock.advance(6)                   # cooldown elapses
        assert b.allow()                   # HALF_OPEN probe admitted
        b.record_failure()                 # probe fails: re-trip
        assert b.state == CircuitBreaker.OPEN
        assert len(opened) == 1, "half-open re-trip must not re-fire"

    def test_detach_by_prefix(self):
        rec = FlightRecorder(min_interval_s=0.0)
        try:
            rec.add_stats_source("engine@a", lambda: 1)
            rec.add_stats_source("engine@a:swap_events", lambda: 2)
            rec.add_stats_source("engine@b", lambda: 3)
            # review regression: one address being a string-prefix of
            # another (port 1890 vs 18900) must NOT cross-detach
            rec.add_stats_source("engine@http://h:1890", lambda: 4)
            rec.add_stats_source("engine@http://h:18900", lambda: 5)
            rec.detach("engine@a")
            rec.detach("engine@http://h:1890")
            assert sorted(rec.dump_bundle("x")["stats"]) == [
                "engine@b", "engine@http://h:18900"]
        finally:
            rec.close()

    def test_shared_monitor_rewires_to_second_engines_recorder(self):
        """Review regression: engine.stop() must uninstall the
        slo.on_fire hook it installed, so a shared SLOMonitor reused
        by a later engine routes breach bundles to THAT engine's
        recorder — not the stopped one's."""
        def echo(table):
            return table.with_column("reply",
                                     [b"ok" for _ in table["id"]])
        mon_args = dict(
            slos=[SLO("availability", target=0.999)],
            rules=[BurnRateRule("fast_burn", 8.0, 2.0, 14.4,
                                min_events=1)],
            windows=(2.0, 8.0), horizon_s=30.0)
        mon = SLOMonitor(**mon_args)
        rec_a = FlightRecorder(min_interval_s=0.0)
        rec_b = FlightRecorder(min_interval_s=0.0)
        try:
            a = serve_model(Lambda.apply(echo), port=19670,
                            batch_size=4, tracing=False, slo=mon,
                            flight_recorder=rec_a)
            assert mon.on_fire is not None
            a.stop()
            assert mon.on_fire is None, \
                "stop() must uninstall the hook it installed"
            b = serve_model(Lambda.apply(echo), port=19672,
                            batch_size=4, tracing=False, slo=mon,
                            flight_recorder=rec_b)
            try:
                for _ in range(5):
                    mon.record(False, 10.0)
                mon.evaluate()
                deadline = time.monotonic() + 5
                while not rec_b.bundles and \
                        time.monotonic() < deadline:
                    time.sleep(0.05)
                assert rec_b.stats()["triggers_captured"] >= 1, \
                    "breach must reach the SECOND engine's recorder"
                assert rec_a.stats()["triggers_captured"] == 0, \
                    "stopped engine's recorder must see nothing"
            finally:
                b.stop()
        finally:
            rec_a.close()
            rec_b.close()

    def test_engine_stop_releases_every_recorder_hook(self):
        """Review regression: a stopped engine must leave NOTHING on a
        (process-lived) recorder — tracer attachment included — or a
        long-lived process accumulates dead engines' closures and
        dump_bundle keeps exporting their buffers forever."""
        def echo(table):
            return table.with_column("reply",
                                     [b"ok" for _ in table["id"]])
        rec = FlightRecorder(min_interval_s=0.0)
        try:
            engine = serve_model(Lambda.apply(echo), port=19660,
                                 batch_size=4,
                                 tracer=Tracer(enabled=True),
                                 flight_recorder=rec)
            stats = rec.stats()
            assert stats["tracers"] == 1
            assert stats["slos"] and stats["event_sources"]
            engine.stop()
            stats = rec.stats()
            assert stats["tracers"] == 0, stats
            assert stats["slos"] == [] and stats["event_sources"] == []
            assert rec.dump_bundle("post-stop")["stats"] == {}
        finally:
            rec.close()


# ---------------------------------------------------------------------------
# tools/check_metrics.py — the static exposition audit
# ---------------------------------------------------------------------------


class TestCheckMetrics:
    def test_shipped_expositions_clean(self):
        from tools.check_metrics import main
        assert main() == 0

    def test_catches_bad_counter_suffix(self):
        from tools.check_metrics import audit_source
        out = audit_source('r.counter("requests_count", "help", 1)')
        assert any("_total" in v.message for v in out)

    def test_catches_missing_help(self):
        from tools.check_metrics import audit_source
        out = audit_source('r.gauge("depth", "", 1)')
        assert any("HELP" in v.message for v in out)

    def test_catches_bad_histogram_suffix(self):
        from tools.check_metrics import audit_source
        out = audit_source('r.histogram("latency", "help", h)')
        assert any("unit suffix" in v.message for v in out)

    def test_catches_uncapped_model_label(self):
        from tools.check_metrics import audit_source
        out = audit_source(
            'r.gauge("per_model_qps", "help", 1, {"model": m})')
        assert any("CAPPED_FAMILIES" in v.message for v in out)

    def test_catches_undeclared_dynamic_name(self):
        from tools.check_metrics import audit_source
        out = audit_source('r.counter(f"x_{n}_total", "help", 1)')
        assert any("DYNAMIC_OK" in v.message for v in out)

    def test_capped_family_passes(self):
        from tools.check_metrics import audit_source
        assert audit_source(
            'r.histogram("serving_model_latency_ms", "help", h, '
            '{"model": m})') == []


# ---------------------------------------------------------------------------
# debug endpoints: strict query validation (satellite)
# ---------------------------------------------------------------------------


def _get(url, timeout=5):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, json.loads(r.read())


class TestDebugEndpointValidation:
    @pytest.fixture()
    def engine(self):
        def echo(table):
            return table.with_column("reply",
                                     [b"ok" for _ in table["id"]])
        rec = FlightRecorder(min_interval_s=0.0)
        engine = serve_model(Lambda.apply(echo), port=19620,
                             batch_size=4, tracer=Tracer(enabled=True),
                             flight_recorder=rec)
        yield engine
        engine.stop()
        rec.close()

    @pytest.mark.parametrize("query", ["limit=abc", "limit=-1",
                                       "limit=1.5", "limit="])
    def test_bad_limit_is_400_not_500(self, engine, query):
        for path in ("/debug/traces", "/debug/bundle"):
            with pytest.raises(urllib.error.HTTPError) as exc:
                _get(f"{engine.source.address}{path}?{query}&confirm=1")
            assert exc.value.code == 400, \
                f"{path}?{query} -> {exc.value.code}"
            body = json.loads(exc.value.read())
            assert "limit" in body["error"]

    def test_bundle_requires_confirm(self, engine):
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(engine.source.address + "/debug/bundle")
        assert exc.value.code == 400
        assert "confirm" in json.loads(exc.value.read())["error"]
        status, bundle = _get(
            engine.source.address + "/debug/bundle?confirm=1&limit=5")
        assert status == 200
        assert bundle["bundle_version"] == 1
        assert "traces" in bundle and "slo" in bundle

    def test_good_limit_still_works(self, engine):
        status, payload = _get(
            engine.source.address + "/debug/traces?limit=2")
        assert status == 200
        assert "traceEvents" in payload


# ---------------------------------------------------------------------------
# the end-to-end chaos acceptance
# ---------------------------------------------------------------------------


class TestChaosSLOEndToEnd:
    def test_error_spike_degrades_alerts_bundles_and_resolves(self):
        """The acceptance bar: an injected error-rate spike on one
        engine flips /healthz to degraded with a NAMED active
        burn-rate alert, emits serving_slo_* families that pass the
        text-format grammar validator, auto-captures a flight-recorder
        bundle containing the offending traces + the alert + the
        windowed series — and the alert RESOLVES after recovery."""
        def good(table):
            return table.with_column(
                "reply", [b"ok" for _ in table["id"]])

        def bad(table):
            raise RuntimeError("injected chaos: engine poisoned")

        # test-sized windows: fast burn over 8s/2s, quarter-second
        # buckets, so the whole fire->resolve cycle fits in seconds
        mon = SLOMonitor(
            slos=[SLO("availability", target=0.999)],
            rules=[BurnRateRule("fast_burn", 8.0, 2.0, 14.4,
                                min_events=3)],
            windows=(2.0, 8.0), bucket_s=0.25, hist_bucket_s=0.5,
            horizon_s=30.0)
        rec = FlightRecorder(min_interval_s=0.0)
        tracer = Tracer(enabled=True)
        engine = serve_model(Lambda.apply(good), port=19640,
                             batch_size=4, max_wait_ms=2.0,
                             tracer=tracer, slo=mon,
                             flight_recorder=rec,
                             slo_eval_interval_s=0.1)
        addr = engine.source.address

        def post(x):
            req = urllib.request.Request(
                addr, data=json.dumps({"x": x}).encode(),
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=10) as r:
                    return r.status
            except urllib.error.HTTPError as e:
                return e.code

        try:
            # phase 1: healthy traffic
            for i in range(20):
                assert post(i) == 200
            status, health = _get(addr + "/healthz")
            assert health["status"] == "ok"
            assert health["slo"]["degraded"] is False

            # phase 2: error spike — every request 500s
            engine.pipeline = Lambda.apply(bad)
            for i in range(15):
                assert post(i) == 500
            deadline = time.monotonic() + 5
            health = None
            while time.monotonic() < deadline:
                _, health = _get(addr + "/healthz")
                if health["status"] == "degraded":
                    break
                time.sleep(0.1)
            assert health is not None and \
                health["status"] == "degraded", health
            active = health["slo"]["active_alerts"]
            assert any(a["name"] == "availability:fast_burn"
                       for a in active), active
            alert = next(a for a in active
                         if a["name"] == "availability:fast_burn")
            assert alert["burn_short"] > 14.4

            # /metrics: grammar-valid serving_slo_* families
            text = urllib.request.urlopen(
                addr + "/metrics", timeout=5).read().decode()
            types, samples = validate_prom_text(text)
            names = {n for n, _l, _v in samples}
            for required in ("serving_slo_degraded",
                             "serving_slo_burn_rate",
                             "serving_slo_error_rate",
                             "serving_slo_latency_p99_ms",
                             "serving_slo_target",
                             "serving_slo_alert_active",
                             "serving_slo_alerts_fired_total"):
                assert required in names, f"missing {required}"
            degraded = next(v for n, _l, v in samples
                            if n == "serving_slo_degraded")
            assert degraded == 1
            active_series = [(l, v) for n, l, v in samples
                             if n == "serving_slo_alert_active"]
            assert any(l.get("slo") == "availability"
                       and l.get("rule") == "fast_burn"
                       and v == 1 for l, v in active_series)

            # the flight recorder auto-captured the post-mortem
            # (capture runs on its own daemon thread — poll briefly)
            assert rec.stats()["triggers_captured"] >= 1
            cap_deadline = time.monotonic() + 5
            while not rec.bundles and time.monotonic() < cap_deadline:
                time.sleep(0.05)
            assert rec.bundles, "auto-capture never landed"
            bundle = rec.bundles[-1]
            assert bundle["reason"].startswith(
                "slo_breach:availability:fast_burn")
            # ... containing the offending traces (error roots) ...
            ev = bundle["traces"]["traceEvents"]
            assert any(e.get("args", {}).get("status") == "error"
                       for e in ev), "bundle lost the error traces"
            # ... the alert ...
            slo_key = next(iter(bundle["slo"]))
            st = bundle["slo"][slo_key]["status"]
            assert any(a["name"] == "availability:fast_burn"
                       for a in st["active_alerts"])
            # ... and the windowed series with the error spike (the
            # bundle snapshots at FIRE time — at least the rule's
            # min_events errors are already in the series)
            series = bundle["slo"][slo_key]["series"]
            assert sum(v for _, v in series["errors"]) >= 3
            json.dumps(bundle)            # self-contained JSON

            # phase 3: recovery — the short window drains, the alert
            # resolves, /healthz returns to ok
            engine.pipeline = Lambda.apply(good)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                assert post(999) == 200
                _, health = _get(addr + "/healthz")
                if health["status"] == "ok" and \
                        not health["slo"]["active_alerts"]:
                    break
                time.sleep(0.2)
            assert health["status"] == "ok", health
            assert health["slo"]["active_alerts"] == []
            assert health["slo"]["resolved_total"] >= 1
            # the registry-style event trail: fired AND resolved both
            # visible in the alert history
            hist = mon.alerts.history()
            assert any(not a.active for a in hist)
        finally:
            engine.stop()
            rec.close()
