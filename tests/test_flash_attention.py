"""Pallas flash attention numerics vs the dense reference.

The kernel must reproduce ring_attention.attention exactly (same online
m/l/o algebra) across causal masking, shard offsets, ragged lengths and
fully-masked rows — interpret mode on CPU."""

import numpy as np
import pytest

import jax.numpy as jnp

from mmlspark_tpu.ops.flash_attention import flash_attention
from mmlspark_tpu.parallel.ring_attention import attention


def _qkv(b, lq, lk, h, d, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    mk = lambda l: jnp.asarray(  # noqa: E731
        rng.normal(size=(b, l, h, d)), dtype)
    return mk(lq), mk(lk), mk(lk)


@pytest.mark.parametrize("lq,lk,causal", [
    (64, 64, False),
    (64, 64, True),
    (100, 100, True),      # ragged: not a block multiple
    (300, 520, False),     # multi-block kv, rectangular
    (520, 300, True),      # multi-block q
])
def test_matches_dense(lq, lk, causal):
    q, k, v = _qkv(2, lq, lk, 3, 16, seed=lq + lk)
    ref = attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_shard_offsets_match_dense():
    # causal masking of a sequence shard: global positions via offsets
    q, k, v = _qkv(1, 64, 64, 2, 8, seed=7)
    ref = attention(q, k, v, causal=True, q_offset=64, k_offset=0)
    got = flash_attention(q, k, v, causal=True, q_offset=64, k_offset=0,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_fully_masked_rows_zero():
    # keys strictly in the future of every query -> all rows masked;
    # both paths must return zeros, not NaN
    q, k, v = _qkv(1, 32, 32, 2, 8, seed=9)
    ref = attention(q, k, v, causal=True, q_offset=0, k_offset=1000)
    got = flash_attention(q, k, v, causal=True, q_offset=0,
                          k_offset=1000, interpret=True)
    assert np.all(np.asarray(got) == 0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref))


def test_gradients_match_dense():
    # the kernel sits in the training path (TransformerBlock), so its
    # custom_vjp backward (dense recompute) must match dense grads
    import jax
    q, k, v = _qkv(1, 48, 48, 2, 8, seed=11)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       interpret=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)


@pytest.mark.skipif(
    __import__("jax").default_backend() not in ("tpu", "axon"),
    reason="compiled Mosaic kernel needs a real TPU (tests pin CPU)")
def test_compiled_kernel_on_tpu():
    # the non-interpret path: first Mosaic lowering must not wait for
    # production — run this file directly on a TPU host to exercise it
    q, k, v = _qkv(1, 1024, 1024, 4, 32, seed=5)
    ref = attention(q, k, v, causal=True)   # also flash (>=512), compiled
    got = flash_attention(q, k, v, causal=True, interpret=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)


def test_bfloat16_inputs():
    q, k, v = _qkv(1, 96, 96, 2, 16, seed=3, dtype=jnp.bfloat16)
    ref = attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True, interpret=True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32),
        rtol=2e-2, atol=2e-2)


def test_wide_head_dim_block_caps():
    """D > 128 halves the v5e block caps (the 1024 blocks overflow the
    16 MB scoped-vmem limit in the backward at D=160); _blocks/_lse_pad
    must agree on the resulting padding, and fwd+bwd must stay correct
    at a wide head dim."""
    import jax
    from mmlspark_tpu.ops.flash_attention import _blocks, _lse_pad

    for d in (64, 128, 160, 256):
        bq, _, pad_q, _ = _blocks(700, 700, d)
        assert _lse_pad(700, d) == 700 + pad_q

    q, k, v = _qkv(1, 300, 300, 2, 160, seed=13)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       interpret=True) ** 2)

    def loss_dense(q, k, v):
        from mmlspark_tpu.parallel.ring_attention import dense_attention
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)
