import numpy as np
import pytest

import jax
import jax.numpy as jnp

import flax.linen as nn

from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.models.tpu_model import TPUModel
from mmlspark_tpu.parallel import mesh as mesh_lib


class TinyMLP(nn.Module):
    features: int = 8
    out: int = 3

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(self.features)(x)
        x = nn.relu(x)
        return nn.Dense(self.out)(x)


def _make_model(in_dim=4, batch_size=16):
    module = TinyMLP()
    params = module.init(jax.random.PRNGKey(0), jnp.ones((1, in_dim)))
    model = TPUModel.from_flax(module, params,
                               inputCol="features", outputCol="scores",
                               batchSize=batch_size)
    return module, params, model


def test_basic_inference():
    module, params, model = _make_model()
    rng = np.random.default_rng(0)
    feats = rng.normal(size=(10, 4)).astype(np.float32)
    t = DataTable({"features": feats})
    out = model.transform(t)
    assert out["scores"].shape == (10, 3)
    expected = np.asarray(module.apply(params, jnp.asarray(feats)))
    np.testing.assert_allclose(out["scores"], expected, rtol=1e-4, atol=1e-4)


def test_batching_padding_correct():
    # 10 rows with batch 4 and an 8-device mesh: padding paths exercised
    module, params, model = _make_model(batch_size=4)
    rng = np.random.default_rng(1)
    feats = rng.normal(size=(10, 4)).astype(np.float32)
    t = DataTable({"features": feats})
    out = model.transform(t)
    expected = np.asarray(module.apply(params, jnp.asarray(feats)))
    np.testing.assert_allclose(out["scores"], expected, rtol=1e-4, atol=1e-4)


def test_sharded_over_mesh():
    module, params, model = _make_model()
    model.set_mesh(mesh_lib.make_mesh({"data": 8}))
    rng = np.random.default_rng(2)
    feats = rng.normal(size=(32, 4)).astype(np.float32)
    out = model.transform(DataTable({"features": feats}))
    expected = np.asarray(module.apply(params, jnp.asarray(feats)))
    np.testing.assert_allclose(out["scores"], expected, rtol=1e-4, atol=1e-4)


def test_feed_fetch_dicts():
    class TwoHead(nn.Module):
        @nn.compact
        def __call__(self, x):
            h = nn.Dense(4)(x)
            return {"a": nn.Dense(2)(h), "b": nn.Dense(5)(h)}

    module = TwoHead()
    params = module.init(jax.random.PRNGKey(0), jnp.ones((1, 3)))
    model = TPUModel.from_flax(
        module, params,
        feedDict={"x": "feats"},
        fetchDict={"out_a": "a", "out_b": "b"})
    feats = np.random.default_rng(0).normal(size=(6, 3)).astype(np.float32)
    out = model.transform(DataTable({"feats": feats}))
    assert out["out_a"].shape == (6, 2)
    assert out["out_b"].shape == (6, 5)


def test_bfloat16_path():
    module, params, model = _make_model()
    model.set("computeDtype", "bfloat16")
    feats = np.random.default_rng(0).normal(size=(8, 4)).astype(np.float32)
    out = model.transform(DataTable({"features": feats}))
    expected = np.asarray(module.apply(params, jnp.asarray(feats)))
    np.testing.assert_allclose(out["scores"], expected, rtol=0.05, atol=0.05)


def test_vector_list_column():
    module, params, model = _make_model()
    rng = np.random.default_rng(3)
    feats = [rng.normal(size=4) for _ in range(5)]
    t = DataTable({"features": feats})
    out = model.transform(t)
    assert out["scores"].shape == (5, 3)


def test_save_load_roundtrip(tmp_path):
    module, params, model = _make_model()
    feats = np.random.default_rng(4).normal(size=(6, 4)).astype(np.float32)
    t = DataTable({"features": feats})
    out1 = model.transform(t)

    p = str(tmp_path / "model")
    model.save(p)
    from mmlspark_tpu.core.stage import load_stage
    model2 = load_stage(p)
    out2 = model2.transform(t)
    np.testing.assert_allclose(out1["scores"], out2["scores"],
                               rtol=1e-5, atol=1e-5)


def test_missing_output_raises():
    module, params, model = _make_model()
    model.set("fetchDict", {"y": "nonexistent"})
    feats = np.zeros((2, 4), dtype=np.float32)
    with pytest.raises(KeyError):
        model.transform(DataTable({"features": feats}))


def test_image_to_model_e2e():
    """images -> resize -> unroll -> TPUModel: the notebook-301 shape."""
    from mmlspark_tpu.core.schema import ImageSchema
    from mmlspark_tpu.stages.image import ImageTransformer, UnrollImage
    from mmlspark_tpu.core.stage import Pipeline

    rng = np.random.default_rng(5)
    rows = [ImageSchema.make_row(
        f"i_{i}.png", rng.integers(0, 256, (12, 12, 3), dtype=np.uint8))
        for i in range(6)]
    t = DataTable({"image": rows})

    in_dim = 8 * 8 * 3
    module = TinyMLP()
    params = module.init(jax.random.PRNGKey(1), jnp.ones((1, in_dim)))
    model = TPUModel.from_flax(module, params, inputCol="unrolled",
                               outputCol="scores")
    pipe = Pipeline([
        ImageTransformer().resize(8, 8),
        UnrollImage(),
        model,
    ])
    out = pipe.fit(t).transform(t)
    assert out["scores"].shape == (6, 3)


def test_int_token_model_inputs_stay_integer():
    # integer-token models (BiLSTM/Transformer) must receive int32 ids,
    # not float-coerced values (regression: embed rejects float input)
    from mmlspark_tpu.models.networks import build_network

    spec = {"type": "bilstm", "vocab_size": 20, "embed_dim": 4,
            "hidden": 4, "num_tags": 3}
    module = build_network(spec)
    variables = module.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 6), jnp.int32))
    model = TPUModel.from_flax(module, variables, inputCol="tokens",
                               outputCol="tags", batchSize=4)
    toks = np.random.default_rng(0).integers(0, 20, size=(10, 6))
    out = model.transform(DataTable({"tokens": toks.astype(np.int64)}))
    assert out["tags"].shape == (10, 6, 3)
    # bfloat16 compute must also leave token ids alone
    model.set("computeDtype", "bfloat16")
    out2 = model.transform(DataTable({"tokens": toks.astype(np.int64)}))
    assert out2["tags"].shape == (10, 6, 3)


class TestShapeBuckets:
    """The serving compile-cache contract: explicit warmup compiles one
    executable per bucket, and steady-state traffic at ANY mix of batch
    sizes triggers ZERO further compiles (the recompile guard of the
    serving hot path — one stray XLA compile costs seconds through a
    real-chip tunnel)."""

    def _one_device_model(self, batch_size=64, dim=12):
        module, params, _ = None, None, None
        m = TinyMLP()
        params = m.init(jax.random.PRNGKey(0), jnp.ones((1, dim)))
        model = TPUModel.from_flax(m, params, inputCol="features",
                                   outputCol="scores",
                                   batchSize=batch_size)
        # 1-device mesh = the single-chip serving topology (the CI
        # 8-device mesh pads every batch to a multiple of 8, which
        # would mask a lost bucket)
        model.set_mesh(mesh_lib.make_mesh(
            {"data": 1}, devices=[jax.devices()[0]]))
        return model, dim

    def test_bucket_sizes_cover_batch_size(self):
        model, _ = self._one_device_model(batch_size=64)
        assert model.bucket_sizes() == [8, 16, 32, 64]
        model.set("batchSize", 48)        # non-power-of-two cap kept
        assert model.bucket_sizes() == [8, 16, 32, 48]

    def test_warmup_compiles_each_bucket_once(self):
        model, dim = self._one_device_model()
        compiles = model.warmup(
            {"features": np.zeros((1, dim), np.float32)})
        assert compiles == len(model.bucket_sizes())
        # warm again: everything cached
        assert model.warmup(
            {"features": np.zeros((1, dim), np.float32)}) == 0

    def test_steady_state_zero_recompiles_across_mixed_batch_sizes(self):
        model, dim = self._one_device_model()
        model.warmup({"features": np.zeros((1, dim), np.float32)})
        before = model.jit_cache_misses
        rng = np.random.default_rng(0)
        for rows in [1, 3, 8, 9, 17, 33, 64, 5, 50, 64, 2, 40, 31, 12]:
            t = DataTable({"features": rng.normal(
                size=(rows, dim)).astype(np.float32)})
            out = model.transform(t)
            assert len(out) == rows
        assert model.jit_cache_misses == before, (
            f"steady-state serving recompiled "
            f"{model.jit_cache_misses - before} time(s) across mixed "
            f"batch sizes — the bucket layer lost its shape cache")

    def test_metrics_expose_pad_device_and_misses(self):
        model, dim = self._one_device_model()
        model.transform(DataTable({"features": np.zeros(
            (4, dim), np.float32)}))
        m = model.metrics()
        assert m["jit_cache_misses"] >= 1
        assert m["pad_ms"]["count"] >= 1
        assert m["device_ms"]["count"] >= 1
