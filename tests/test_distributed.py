"""Multi-process jax.distributed rendezvous test.

The reference fakes a cluster by making local[*] partitions act as nodes
and running the real socket rendezvous + native allreduce ring in one
machine (ref: LightGBMUtils.scala:110-118, :235-249). The TPU-native
equivalent launches real OS processes that rendezvous at the
jax.distributed coordinator, build one global device mesh, shard a table
per host, and psum across every device of every process — giving
parallel/distributed.py actual execution coverage (VERDICT item 6).
"""

import os
import socket
import subprocess
import sys

import pytest

WORKER = os.path.join(os.path.dirname(__file__), "dist_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.parametrize("nproc", [2])
def test_multiprocess_rendezvous_and_psum(nproc):
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(port), str(pid), str(nproc)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env)
        for pid in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=180)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"distributed workers hung; partial: {outs}")

    for rc, out, err in outs:
        assert rc == 0, f"worker failed (rc={rc}):\n{out}\n{err}"
        assert f"OK" in out

    # every process must report the same global psum: sum(0..4n-1)
    n_rows = 4 * nproc
    expect = n_rows * (n_rows - 1) / 2
    shards = {}
    trained = {}
    for rc, out, err in outs:
        for line in out.splitlines():
            if line.startswith("PSUM"):
                _, pid, val = line.split()
                assert float(val) == expect, line
            if line.startswith("SHARD"):
                _, pid, vals = line.split()
                shards[int(pid)] = vals
            if line.startswith("TRAIN"):
                _, pid, vals = line.split()
                trained[int(pid)] = vals
    # host-sharded training ran and produced identical replicated params
    assert len(trained) == nproc
    assert len(set(trained.values())) == 1, trained
    # host shards are disjoint row ranges
    assert len(shards) == nproc
    all_rows = ",".join(shards[i] for i in range(nproc))
    assert all_rows == ",".join(str(i) for i in range(n_rows))
