"""Multi-process jax.distributed rendezvous test.

The reference fakes a cluster by making local[*] partitions act as nodes
and running the real socket rendezvous + native allreduce ring in one
machine (ref: LightGBMUtils.scala:110-118, :235-249). The TPU-native
equivalent launches real OS processes that rendezvous at the
jax.distributed coordinator, build one global device mesh, shard a table
per host, and psum across every device of every process — giving
parallel/distributed.py actual execution coverage (VERDICT item 6).
"""

import os
import socket
import subprocess
import sys

import pytest

WORKER = os.path.join(os.path.dirname(__file__), "dist_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.parametrize("nproc", [2])
def test_multiprocess_rendezvous_and_psum(nproc, tmp_path):
    import jax
    if jax.__version_info__ < (0, 5, 0):
        pytest.skip("jax < 0.5 CPU backend: 'Multiprocess computations "
                    "aren't implemented on the CPU backend'")
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    # remote-checkpoint seam: an in-process WebDAV server the workers
    # write/resume checkpoints through (the shared-HDFS analog)
    from mmlspark_tpu.testing.webdav import serve_webdav
    dav_server, dav_url = serve_webdav(str(tmp_path / "dav_ckpt"))
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(port), str(pid), str(nproc),
             dav_url],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env)
        for pid in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"distributed workers hung; partial: {outs}")
    finally:
        dav_server.shutdown()
        dav_server.server_close()

    for rc, out, err in outs:
        assert rc == 0, f"worker failed (rc={rc}):\n{out}\n{err}"
        assert f"OK" in out

    # every process must report the same global psum: sum(0..4n-1)
    n_rows = 4 * nproc
    expect = n_rows * (n_rows - 1) / 2
    shards = {}
    trained = {}
    streamed = {}
    gbdt = {}
    fp_gbdt = {}
    fp_csr = {}
    vote_gbdt = {}
    f64bin = {}
    devfeed = {}
    webdav_ck = {}
    for rc, out, err in outs:
        for line in out.splitlines():
            if line.startswith("PSUM"):
                _, pid, val = line.split()
                assert float(val) == expect, line
            if line.startswith("SHARD"):
                _, pid, vals = line.split()
                shards[int(pid)] = vals
            if line.startswith("TRAIN"):
                _, pid, vals = line.split()
                trained[int(pid)] = vals
            if line.startswith("STREAM"):
                _, pid, vals = line.split()
                streamed[int(pid)] = vals
            if line.startswith("DEVFEED"):
                _, pid, vals = line.split()
                devfeed[int(pid)] = vals
            if line.startswith("GBDT"):
                _, pid, vals = line.split()
                gbdt[int(pid)] = vals
            if line.startswith("FPGBDT"):
                _, pid, vals = line.split()
                fp_gbdt[int(pid)] = vals
            if line.startswith("FPCSR"):
                _, pid, vals = line.split()
                fp_csr[int(pid)] = vals
            if line.startswith("VOTEGBDT"):
                _, pid, vals = line.split()
                vote_gbdt[int(pid)] = vals
            if line.startswith("F64BIN"):
                _, pid, vals = line.split()
                f64bin[int(pid)] = vals
            if line.startswith("WEBDAVCKPT"):
                _, pid, vals = line.split()
                webdav_ck[int(pid)] = vals
    # multi-host checkpoint/resume on the NON-file (webdav://) scheme:
    # every host saw the first run's remote checkpoint (step > 0) and
    # the resumed run converged to identical replicated params
    assert len(webdav_ck) == nproc, webdav_ck
    assert len(set(webdav_ck.values())) == 1, webdav_ck
    _wd_digest, wd_step = next(iter(webdav_ck.values())).split(",")
    assert int(wd_step) > 0, webdav_ck
    # host-sharded training ran and produced identical replicated params
    assert len(trained) == nproc
    assert len(set(trained.values())) == 1, trained
    # ragged multi-host STREAMING training also converged identically
    # (hosts truncate to the min shard count so steps agree)
    assert len(streamed) == nproc
    assert len(set(streamed.values())) == 1, streamed
    # DEVICE-RESIDENT multi-host feed: identical replicated params on
    # every host AND bit-exact across re-runs (deterministic on-device
    # shuffle from the shared seed key); trailing ,1 = determinism flag
    assert len(devfeed) == nproc
    assert len(set(devfeed.values())) == 1, devfeed
    assert all(v.endswith(",1") for v in devfeed.values()), devfeed
    # multi-host GBDT grew identical forests from disjoint row shards,
    # and the model predicts the global data well (digest,auc_ok)
    assert len(gbdt) == nproc
    assert len(set(gbdt.values())) == 1, gbdt
    assert all(v.endswith(",1") for v in gbdt.values()), gbdt
    # multi-host FEATURE-parallel: byte-identical forests from
    # feature shards of the global mesh (full data on every host)
    assert len(fp_gbdt) == nproc
    assert len(set(fp_gbdt.values())) == 1, fp_gbdt
    assert all(v.endswith(",1") for v in fp_gbdt.values()), fp_gbdt
    # feature-parallel with CSR input (digest hashes the sparse buffers;
    # trailing ,1 = the forest also predicts the data well)
    assert len(fp_csr) == nproc
    assert len(set(fp_csr.values())) == 1, fp_csr
    assert all(v.endswith(",1") for v in fp_csr.values()), fp_csr
    # multi-host VOTING-parallel: byte-identical forests from row shards
    assert len(vote_gbdt) == nproc
    assert len(set(vote_gbdt.values())) == 1, vote_gbdt
    assert all(v.endswith(",1") for v in vote_gbdt.values()), vote_gbdt
    # f64-faithful multi-host binning: (boundary_digest, forest_digest,
    # f32_unsafe) agree across hosts, the f32-unsafe flag is set, and
    # the agreed boundaries equal a single-host f64 fit byte-for-byte
    assert len(f64bin) == nproc
    assert len(set(f64bin.values())) == 1, f64bin
    b_digest, _, unsafe = next(iter(f64bin.values())).split(",")
    assert unsafe == "1", f64bin
    import hashlib
    import numpy as np
    from mmlspark_tpu.gbdt.binning import BinMapper
    grng = np.random.default_rng(11)
    grng.normal(size=(400, 6))          # replay the worker's draws
    f24 = 2.0 ** 24
    ux = np.stack([f24 + np.arange(400, dtype=np.float64) * 0.25,
                   grng.normal(size=400)], axis=1)
    expect_digest = hashlib.sha256(
        b"".join(u.tobytes() for u in BinMapper.fit(
            ux, max_bin=15).upper_bounds)).hexdigest()[:16]
    assert b_digest == expect_digest, \
        "multi-host agreed bin boundaries differ from single-host f64 " \
        "fit (the f32-wire quantization bug)"
    # host shards are disjoint row ranges
    assert len(shards) == nproc
    all_rows = ",".join(shards[i] for i in range(nproc))
    assert all_rows == ",".join(str(i) for i in range(n_rows))


def test_cross_process_serving_fleet():
    """Serving across REAL OS processes: one ServingEngine per process
    (the reference's per-executor JVMSharedServer,
    ref: DistributedHTTPSource.scala:96-266). Asserts the reply-routing
    invariant (every answer returns through the process that accepted
    the request) and the fleet-wide counter aggregate."""
    import json
    import urllib.request

    worker = os.path.join(os.path.dirname(__file__), "serving_worker.py")
    nworkers, per_worker = 3, 8
    procs, addrs = [], {}
    try:
        for wid in range(nworkers):
            p = subprocess.Popen(
                [sys.executable, worker, str(_free_port()), str(wid)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
            procs.append(p)
            line = p.stdout.readline().strip()   # blocks until READY
            tag, wid_s, addr = line.split()
            assert tag == "READY" and int(wid_s) == wid, line
            addrs[wid] = addr

        def post(addr, payload):
            req = urllib.request.Request(
                addr, data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                return json.loads(r.read())

        # spray every worker; replies must come from the SAME worker
        for wid, addr in addrs.items():
            for i in range(per_worker):
                rep = post(addr, {"x": wid * 100 + i})
                assert rep == {"echo": wid * 100 + i, "worker": wid}, rep

        counters = {}
        for wid, addr in addrs.items():
            assert post(addr, {"__shutdown__": True}) == {"bye": wid}
        for wid, p in enumerate(procs):
            out, err = p.communicate(timeout=30)
            assert p.returncode == 0, f"worker {wid} rc={p.returncode}\n{err}"
            for line in out.splitlines():
                if line.startswith("COUNTERS"):
                    _, wid_s, seen, acc, ans = line.split()
                    counters[int(wid_s)] = (int(seen), int(acc), int(ans))
        assert len(counters) == nworkers
        total = per_worker * nworkers + nworkers   # incl. shutdown posts
        assert sum(c[0] for c in counters.values()) == total, counters
        assert sum(c[2] for c in counters.values()) == total, counters
        for wid, (seen, acc, ans) in counters.items():
            assert seen == acc == ans == per_worker + 1, counters
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
