"""Shared-memory columnar transport (io/shm.py): ring slot lifecycle,
bit-parity with the in-body msgpack codec, crash-safety (generation
tags, quarantine, dead-owner reaping), the fleet client's
shm -> HTTP+msgpack -> per-row JSON fallback ladder, and the
SIGKILL failure envelope across real OS processes."""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from mmlspark_tpu.io import columnar as C
from mmlspark_tpu.io import shm as S

pytestmark = pytest.mark.skipif(
    not S.shm_available(), reason="no POSIX shared memory on this host")


COLS = {
    "f32": np.array([[1.5, -2.25], [np.nan, np.inf], [-np.inf, 0.0]],
                    dtype=np.float32),
    "f64": np.array([1.0, np.nan, -1e300]),
    "i64": np.array([1, -2, 2**40], dtype=np.int64),
    "flag": np.array([True, False, True]),
    "s": ["héllo", None, "𝔘nicode\n\"quoted\""],
    "toks": [["a", "bb"], [], ["𝔠", ""]],
}


@pytest.fixture()
def ring():
    r = S.ShmRing(nslots=2, slot_bytes=1 << 16)
    yield r
    r.close()
    S.close_attachments()


def _tpu_model(dim=8, classes=4):
    from mmlspark_tpu.models.tpu_model import TPUModel
    rng = np.random.default_rng(3)
    W = rng.normal(size=(dim, classes)).astype(np.float32)
    return TPUModel.from_fn(
        lambda w, ins: list(ins.values())[0] @ w["W"], {"W": W},
        inputCol="features", outputCol="scores", batchSize=32)


class TestShmRing:
    def test_roundtrip_parity_all_types(self, ring):
        ctrl, ct, token = ring.write(COLS)
        assert ct == C.CT_SHM_COLUMNS
        try:
            got = S.decode_control(ctrl)
            oracle = C.decode_columnar(
                "msgpack", C.encode_columns(COLS)[0])
            assert got.codec == "shm"
            assert got.n_rows == oracle.n_rows == 3
            for k in ("f32", "f64", "i64"):
                np.testing.assert_array_equal(got.columns[k],
                                              oracle.columns[k])
                assert got.columns[k].dtype == oracle.columns[k].dtype
            assert list(np.asarray(got.columns["flag"], bool)) == \
                list(np.asarray(oracle.columns["flag"], bool))
            assert got.columns["s"] == oracle.columns["s"]
            assert [list(t) for t in got.columns["toks"]] == \
                [list(t) for t in oracle.columns["toks"]]
        finally:
            ring.release(token)

    def test_numeric_columns_are_views_into_the_segment(self, ring):
        arr = np.arange(64, dtype=np.float32).reshape(8, 8)
        ctrl, _, token = ring.write({"f": arr})
        try:
            dec = S.decode_control(ctrl).columns["f"]
            assert dec.base is not None          # a view, not a copy
            assert not dec.flags.owndata
            np.testing.assert_array_equal(dec, arr)
        finally:
            ring.release(token)

    def test_content_type_negotiates_to_shm_codec(self, ring):
        ctrl, ct, token = ring.write({"x": np.ones(2)})
        try:
            assert C.negotiate({"Content-Type": ct}) == "shm"
            # the engine-side decoder table route
            b = C.decode_columnar("shm", ctrl)
            assert b.codec == "shm" and b.n_rows == 2
        finally:
            ring.release(token)

    def test_stale_generation_raises(self, ring):
        ctrl_old, _, token = ring.write({"x": np.ones(3)})
        ring.release(token)
        # the slot recycles under a new generation; the old control
        # message must be refused, never decoded against the new frame
        ctrl_new, _, token2 = ring.write({"y": np.zeros(5)})
        try:
            with pytest.raises(C.CodecError, match="stale shm slot"):
                S.decode_control(ctrl_old)
            assert S.decode_control(ctrl_new).n_rows == 5
        finally:
            ring.release(token2)

    def test_backpressure_when_all_slots_in_flight(self, ring):
        t1 = ring.write({"x": np.ones(1)})[2]
        t2 = ring.write({"x": np.ones(1)})[2]
        with pytest.raises(S.ShmBackpressure):
            ring.write({"x": np.ones(1)})
        ring.release(t1)
        t3 = ring.write({"x": np.ones(1)})[2]
        ring.release(t2)
        ring.release(t3)

    def test_capacity_failure_returns_the_slot(self, ring):
        big = np.zeros(1 << 18)     # 2 MiB frame vs 64 KiB slots
        with pytest.raises(S.ShmCapacity):
            ring.write({"x": big})
        # the claimed slot went straight back to the free list
        tokens = [ring.write({"x": np.ones(1)})[2] for _ in range(2)]
        for t in tokens:
            ring.release(t)

    def test_unclean_release_quarantines_the_slot(self):
        r = S.ShmRing(nslots=1, slot_bytes=1 << 12)
        try:
            token = r.write({"x": np.ones(1)})[2]
            r.release(token, clean=False)
            # quarantined: a reader might still hold views on the frame
            with pytest.raises(S.ShmBackpressure):
                r.write({"x": np.ones(1)})
            # after the cooldown the slot recycles
            with r._lock:
                r._quarantine[:] = [(t, 0.0) for t, _ in r._quarantine]
            r.release(r.write({"x": np.ones(1)})[2])
        finally:
            r.close()
            S.close_attachments()

    def test_nonexistent_segment_raises_codec_error(self):
        ctrl = json.dumps({"v": 1, "seg": "psm_does_not_exist_xyz",
                           "slot": 0, "off": 16, "len": 64, "gen": 1,
                           "pid": 0}).encode()
        with pytest.raises(C.CodecError, match="not attachable"):
            S.decode_control(ctrl)

    @pytest.mark.parametrize("bad", [
        b"", b"not json", b"{}", b'{"seg": 7}',
    ])
    def test_malformed_control_raises_codec_error(self, bad):
        with pytest.raises(C.CodecError):
            S.decode_control(bad)

    def test_out_of_bounds_frame_refused(self, ring):
        ctrl, _, token = ring.write({"x": np.ones(2)})
        try:
            c = json.loads(ctrl)
            c["len"] = ring.nslots * (S._SLOT_HDR.size
                                      + ring.slot_bytes) + 64
            with pytest.raises(C.CodecError, match="exceeds segment"):
                S.decode_control(json.dumps(c).encode())
        finally:
            ring.release(token)

    def test_close_unlinks_the_segment(self):
        r = S.ShmRing(nslots=1, slot_bytes=1 << 12)
        name = r.name
        assert os.path.exists(f"/dev/shm/{name}")
        r.close()
        assert not os.path.exists(f"/dev/shm/{name}")


class TestShmChecker:
    def _tools(self):
        import importlib
        import sys as _sys
        tools = os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools")
        if tools not in _sys.path:
            _sys.path.insert(0, tools)
        return importlib.import_module("check_fusion_kernels")

    def test_shipped_shm_hot_paths_clean(self):
        chk = self._tools()
        assert S.SHM_REGISTRY, "shm hot paths must be registered"
        violations = chk.check_shm_transport()
        assert violations == [], violations

    def test_checker_catches_unacknowledged_copies(self):
        chk = self._tools()

        def copying_path(arr, mv):
            mv[:arr.nbytes] = arr.tobytes()

        def sanctioned_path(body):
            return bytes(body)  # shm:copy-ok — control message

        S.register_shm_kernel(copying_path, "test.copying_path")
        S.register_shm_kernel(sanctioned_path, "test.sanctioned_path")
        try:
            violations = chk.check_shm_transport()
            assert any("test.copying_path" in v and ".tobytes" in v
                       for v in violations), violations
            assert not any("test.sanctioned_path" in v
                           for v in violations), violations
        finally:
            S.SHM_REGISTRY.pop(copying_path.__code__, None)
            S.SHM_REGISTRY.pop(sanctioned_path.__code__, None)

    def test_checker_catches_leaked_slot_acquire(self):
        chk = self._tools()

        def leaky(self, columns):
            slot = self._claim_slot()
            return self.encode(slot, columns)   # a raise leaks the slot

        def paired(self, columns):
            slot = self._claim_slot()
            try:
                return self.encode(slot, columns)
            except Exception:
                self.release(slot)
                raise

        S.register_shm_kernel(leaky, "test.leaky")
        S.register_shm_kernel(paired, "test.paired")
        try:
            violations = chk.check_shm_transport()
            assert any("test.leaky" in v and "leaks the slot" in v
                       for v in violations), violations
            assert not any("test.paired" in v for v in violations), \
                violations
        finally:
            S.SHM_REGISTRY.pop(leaky.__code__, None)
            S.SHM_REGISTRY.pop(paired.__code__, None)


class TestShmFleetTransport:
    def test_fleet_shm_bit_parity_and_slot_recycling(self):
        from mmlspark_tpu.serving.fleet import (
            ServingFleet, json_scoring_pipeline,
        )
        fleet = ServingFleet(json_scoring_pipeline(_tpu_model()),
                             n_engines=2, base_port=20310,
                             batch_size=8, workers=1,
                             shm_transport=True)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(5, 8))
        x[0, 0] = np.nan
        try:
            out = fleet.post_columns({"features": x})
            assert fleet._shm_ok is True
            ring = fleet._shm_ring
            assert ring is not None
            # every slot released once the replies landed
            assert sorted(ring._free) == list(range(ring.nslots))
            # bit parity against the per-row JSON oracle
            for i, row in enumerate(x):
                ref = fleet.post({"features": list(map(float, row))})
                assert out["prediction"][i] == ref["prediction"]
            text = fleet.metrics_text()
            assert "serving_shm_batches_total" in text
            assert 'codec="shm"' in text
            assert fleet._shm_fallbacks == 0
        finally:
            fleet.stop_all()
        assert fleet._shm_ring is None

    def test_old_engine_falls_down_the_whole_ladder(self):
        """A pre-shm, pre-columnar engine parses the shm control
        message as an ordinary JSON request and 500s; the msgpack body
        also fails; the rows replay as per-row JSON — correct answers,
        both fast rungs pinned down for a cooldown."""
        from mmlspark_tpu.serving.fleet import ServingFleet
        from mmlspark_tpu.stages.basic import Lambda

        def old_handle(table):   # the pre-columnar protocol, verbatim
            rows = [json.loads(r["entity"].decode())
                    for r in table["request"]]
            return table.with_column(
                "reply", [{"prediction": float(sum(r["features"]))}
                          for r in rows])

        fleet = ServingFleet(Lambda.apply(old_handle), n_engines=1,
                             base_port=20330, batch_size=8, workers=1,
                             shm_transport=True)
        try:
            out = fleet.post_columns({"features": np.ones((3, 4))})
            assert out["prediction"] == [4.0, 4.0, 4.0]
            assert fleet._shm_ok is False
            assert fleet._columnar_ok is False
            assert fleet._shm_fallbacks >= 1
            # verdicts remembered: the next call goes straight to JSON
            seen0 = fleet.engines[0].source.requests_seen
            out = fleet.post_columns({"features": np.ones((3, 4))})
            assert out["prediction"] == [4.0, 4.0, 4.0]
            assert fleet.engines[0].source.requests_seen - seen0 == 3
        finally:
            fleet.stop_all()

    def test_shm_pin_is_a_cooldown_not_a_life_sentence(self):
        from mmlspark_tpu.serving.fleet import (
            ServingFleet, json_scoring_pipeline,
        )
        fleet = ServingFleet(json_scoring_pipeline(_tpu_model()),
                             n_engines=1, base_port=20350,
                             batch_size=8, workers=1,
                             shm_transport=True)
        rng = np.random.default_rng(1)
        x = rng.normal(size=(2, 8))
        try:
            fleet._shm_ok = False
            fleet._shm_retry_at = time.monotonic() + 999
            fleet.post_columns({"features": x})
            # pinned: no ring was ever created for the HTTP body path
            assert fleet._shm_ring is None
            assert fleet._shm_ok is False
            # cooldown expired: the next call re-probes shm and un-pins
            fleet._shm_retry_at = 0.0
            out = fleet.post_columns({"features": x})
            assert len(out["prediction"]) == 2
            assert fleet._shm_ok is True
            assert fleet._shm_ring is not None
        finally:
            fleet.stop_all()

    def test_backpressure_rides_http_without_a_cooldown(self):
        from mmlspark_tpu.serving.fleet import (
            ServingFleet, json_scoring_pipeline,
        )
        fleet = ServingFleet(json_scoring_pipeline(_tpu_model()),
                             n_engines=1, base_port=20370,
                             batch_size=8, workers=1,
                             shm_transport=True)
        rng = np.random.default_rng(2)
        x = rng.normal(size=(2, 8))
        try:
            ring = S.ShmRing(nslots=1, slot_bytes=1 << 12)
            ring._claim_slot()          # every slot in flight
            fleet._shm_ring = ring
            out = fleet.post_columns({"features": x})
            assert len(out["prediction"]) == 2
            # a full ring is a transient local condition: one HTTP
            # fallback, but the shm rung stays up for the next call
            assert fleet._shm_fallbacks == 1
            assert fleet._shm_ok is True
            assert fleet._shm_retry_at == 0.0
        finally:
            fleet.stop_all()


# ---------------------------------------------------------------------------
# the failure envelope: SIGKILL across real OS processes
# ---------------------------------------------------------------------------

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_WORKER = os.path.join(_REPO, "tests", "serving_worker.py")

_OWNER_SCRIPT = """
import sys, time
import numpy as np
sys.path.insert(0, {repo!r})
from mmlspark_tpu.io import shm as S
r = S.ShmRing(nslots=2, slot_bytes=1 << 14)
ctrl, ct, tok = r.write({{"x": np.arange(8.0)}})
print(ctrl.decode("ascii"), flush=True)
time.sleep(120)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _fd_count() -> int:
    return len(os.listdir("/proc/self/fd"))


class TestShmFailureEnvelope:
    def test_survivor_reaps_dead_owner_segment(self):
        """The client is SIGKILL'd mid-flight: the engine (survivor)
        can still decode the in-flight frame, and the opportunistic
        reaper unlinks the orphaned segment once the owner is gone."""
        p = subprocess.Popen(
            [sys.executable, "-c", _OWNER_SCRIPT.format(repo=_REPO)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        try:
            ctrl = p.stdout.readline().strip().encode()
            assert ctrl, p.stderr.read()
            name = json.loads(ctrl)["seg"]
            assert os.path.exists(f"/dev/shm/{name}")
            batch = S.decode_control(ctrl)    # cross-process attach
            np.testing.assert_array_equal(batch.columns["x"],
                                          np.arange(8.0))
            del batch                         # drop the segment views
            p.send_signal(signal.SIGKILL)
            p.wait(timeout=30)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                S.reap_dead_owners(force=True)
                if not os.path.exists(f"/dev/shm/{name}"):
                    break
                time.sleep(0.2)
            assert not os.path.exists(f"/dev/shm/{name}")
        finally:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
            S.close_attachments()

    def test_sigkill_engine_under_shm_load(self):
        """Kill one of three engine processes mid-shm-load: requests
        fail over to the surviving attach-capable engines, availability
        holds >= 99% with zero wrong replies, no fd leak in the client,
        and the placement plane reassigns off the dead engine."""
        from mmlspark_tpu.serving.fleet import ServingFleet
        nworkers, dim = 3, 8
        procs, addrs = [], []
        for wid in range(nworkers):
            port = _free_port()
            p = subprocess.Popen(
                [sys.executable, _WORKER, str(port), str(wid),
                 "--scorer", "linear", "--dim", str(dim),
                 "--batch-size", "32"],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True)
            procs.append(p)
            addrs.append(None)
        fleet = None
        try:
            for wid, p in enumerate(procs):
                line = p.stdout.readline().strip()
                parts = line.split()
                assert parts and parts[0] == "READY", line
                addrs[wid] = parts[2]
            fleet = ServingFleet.connect(addrs, wait_ready_s=60.0,
                                         failure_threshold=2,
                                         breaker_cooldown=1.0,
                                         tracing=False,
                                         shm_transport=True)
            ctl = fleet.attach_placement()
            rng = np.random.default_rng(3)
            rows = rng.normal(size=(4, dim)).astype(np.float32)
            expected = fleet.post_columns({"features": rows})
            assert len(expected["prediction"]) == 4
            assert fleet._shm_ok is True      # engines attach the ring
            fd0 = _fd_count()

            results = {"ok": 0, "failed": 0, "wrong": 0}
            lock = threading.Lock()
            stop = threading.Event()

            def client():
                while not stop.is_set():
                    try:
                        rep = fleet.post_columns({"features": rows},
                                                 timeout=30)
                        ok = rep == expected
                        with lock:
                            results["ok" if ok else "wrong"] += 1
                    except Exception:  # noqa: BLE001
                        with lock:
                            results["failed"] += 1

            threads = [threading.Thread(target=client, daemon=True)
                       for _ in range(4)]
            for t in threads:
                t.start()
            time.sleep(1.0)
            procs[0].send_signal(signal.SIGKILL)
            ctl.record_request("lin")
            ctl.rebuild(force=True)
            assert ctl.assignments()["lin"]   # planned somewhere
            ctl.mark_engine_dead(0)           # confirmed death
            assert 0 not in ctl.assignments()["lin"]
            time.sleep(3.0)
            stop.set()
            for t in threads:
                t.join(timeout=10)
            total = sum(results.values())
            assert total > 20, results
            availability = results["ok"] / total
            assert availability >= 0.99, (availability, results)
            assert results["wrong"] == 0, results
            # the survivors still decode shm frames after the kill
            assert fleet._shm_ok is True
            # no fd leak through the kill + failover churn
            assert _fd_count() - fd0 < 20
            ring_name = fleet._shm_ring.name
            fleet.stop_all()
            fleet = None
            # the owner unlinked its ring on teardown
            assert not os.path.exists(f"/dev/shm/{ring_name}")
        finally:
            if fleet is not None:
                fleet.stop_all()
            for p in procs:
                if p.poll() is None:
                    p.kill()
                p.wait(timeout=30)
