"""Installable-artifact tests: wheel build, clean-venv install, CLI.

The reference ships installable artifacts as a first-class output
(ref: src/project/build.scala:86-97 — sbt packages/publishes every
module; src/codegen/src/main/scala/CodeGen.scala:44-92 zips the
PySpark and R packages). The parity bar here: `pip wheel` from the
checkout produces a wheel (native .so compiled in when the toolchain
exists), the wheel installs into a CLEAN venv, and the installed
package — imported far from the repo — runs a pipeline, loads the
native library, and exposes the console scripts.

The wheel build + venv install run ONCE per session (session-scoped
fixture); the CLI tests drive the installed console scripts, which is
also the manifest-consumer contract (VERDICT r4 #8)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="session")
def installed_venv(tmp_path_factory):
    """Build the wheel, create a clean venv (system-site so the baked-in
    jax/numpy resolve without network), pip-install the wheel."""
    root = tmp_path_factory.mktemp("pkg")
    dist = root / "dist"
    r = subprocess.run(
        [sys.executable, "-m", "pip", "wheel", REPO, "-w", str(dist),
         "--no-deps", "--no-build-isolation", "-q"],
        capture_output=True, text=True, timeout=900)
    assert r.returncode == 0, f"wheel build failed:\n{r.stderr[-3000:]}"
    wheels = list(dist.glob("mmlspark_tpu-*.whl"))
    assert len(wheels) == 1, list(dist.iterdir())

    venv = root / "venv"
    subprocess.run(
        [sys.executable, "-m", "venv", str(venv)],
        check=True, timeout=300)
    py = venv / "bin" / "python"
    # the image's deps (jax, numpy, ...) live in the PARENT environment
    # (itself a virtualenv, so --system-site-packages would skip it);
    # expose them to the clean venv via a .pth — our package itself is
    # still imported only from the wheel install
    parent_sites = [p for p in sys.path if p.endswith("site-packages")]
    site_dir = subprocess.run(
        [str(py), "-c",
         "import sysconfig; print(sysconfig.get_paths()['purelib'])"],
        capture_output=True, text=True, check=True,
        timeout=60).stdout.strip()
    with open(os.path.join(site_dir, "parent-deps.pth"), "w") as f:
        f.write("\n".join(parent_sites) + "\n")
    r = subprocess.run(
        [str(py), "-m", "pip", "install", "--no-deps", "-q",
         str(wheels[0])],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, f"pip install failed:\n{r.stderr[-3000:]}"
    return venv, wheels[0]


def _run_in_venv(venv, code=None, argv=None, cwd=None, timeout=300):
    """Run python-code or a console script inside the venv, from a
    NON-repo cwd so imports cannot leak from the checkout."""
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["MMLSPARK_TPU_PLATFORM"] = "cpu"   # keep CLI tests off the chip
    if code is not None:
        cmd = [str(venv / "bin" / "python"), "-c", code]
    else:
        cmd = [str(venv / "bin" / argv[0])] + list(argv[1:])
    return subprocess.run(
        cmd, capture_output=True, text=True, timeout=timeout,
        cwd=cwd or str(venv), env=env)


def test_wheel_contains_native_sources(installed_venv):
    """The wheel must carry the native sources (self-provision path);
    the .so itself is present when the build toolchain compiled it."""
    import zipfile
    _venv, wheel = installed_venv
    names = zipfile.ZipFile(wheel).namelist()
    assert any(n.endswith("native/src/mml_native.cpp") for n in names)
    assert any(n.endswith("native/CMakeLists.txt") for n in names)
    # when the image has the build toolchain, the compiled library must
    # be inside the wheel, not left behind in the checkout; toolchainless
    # images ship sources only (loader falls back to numpy)
    import shutil
    if shutil.which("cmake") is not None:
        assert any(n.endswith("native/lib/libmml_native.so")
                   for n in names), "native .so missing from wheel"


def test_installed_package_runs_pipeline(installed_venv):
    """Import from the INSTALLED location (repo not on sys.path), fit
    and apply a small pipeline, confirm the native lib binds."""
    venv, _ = installed_venv
    code = """
import jax
jax.config.update("jax_platforms", "cpu")
import os, sys
assert not any(p.startswith("%s") for p in sys.path if p), sys.path
import numpy as np
import mmlspark_tpu as mt
assert "%s" not in os.path.abspath(mt.__file__)
from mmlspark_tpu.stages.dataprep import CleanMissingData
t = mt.DataTable({"f0": np.asarray([1.0, np.nan, 3.0], np.float32),
                  "label": np.asarray([0, 1, 0], np.int32)})
m = CleanMissingData(inputCols=["f0"], cleaningMode="Mean").fit(t)
out = m.transform(t)
assert not np.isnan(np.asarray(out["f0"])).any()
from mmlspark_tpu.native.loader import get_lib
lib = get_lib()
print("native:", "loaded" if lib is not None else "fallback")
print("OK", mt.__file__)
""" % (REPO, REPO)
    r = _run_in_venv(venv, code=code)
    assert r.returncode == 0, f"stdout:{r.stdout}\nstderr:{r.stderr[-3000:]}"
    assert "OK" in r.stdout
    # when the wheel carries the .so, the installed copy must bind it;
    # toolchainless images legitimately run the numpy fallback
    import shutil
    if shutil.which("cmake") is not None:
        assert "native: loaded" in r.stdout, r.stdout
    else:
        assert "native:" in r.stdout, r.stdout


def test_console_script_stages_and_describe(installed_venv):
    venv, _ = installed_venv
    r = _run_in_venv(venv, argv=["mmlspark-tpu", "stages"])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "TPUBoostClassifier" in r.stdout
    r = _run_in_venv(venv, argv=["mmlspark-tpu", "describe",
                                 "TPUBoostClassifier"])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "numLeaves" in r.stdout


def test_console_script_codegen(installed_venv, tmp_path):
    venv, _ = installed_venv
    out = tmp_path / "gen"
    r = _run_in_venv(venv, argv=["mmlspark-tpu-codegen", str(out)])
    assert r.returncode == 0, r.stderr[-2000:]
    counts = json.loads(r.stdout.strip().splitlines()[-1])
    assert counts["stages"] > 50
    assert (out / "manifest.json").exists()


def test_cli_run_score_roundtrip(installed_venv, tmp_path):
    """Train + save + score the flagship pipeline shape from a JSON
    spec and CSV data — no Python written by the user."""
    venv, _ = installed_venv
    rng = np.random.default_rng(0)
    n = 400
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.int32)
    csv_path = tmp_path / "train.csv"
    with open(csv_path, "w") as f:
        f.write("f0,f1,f2,f3,label\n")
        for i in range(n):
            f.write(",".join(str(v) for v in x[i]) + f",{y[i]}\n")
    spec = {
        "pipeline": [
            {"stage": "FastVectorAssembler",
             "params": {"inputCols": ["f0", "f1", "f2", "f3"],
                        "outputCol": "features"}},
            {"stage": "TPUBoostClassifier",
             "params": {"featuresCol": "features", "labelCol": "label",
                        "numIterations": 5, "numLeaves": 7}},
        ]
    }
    spec_path = tmp_path / "pipe.json"
    spec_path.write_text(json.dumps(spec))
    model_dir = tmp_path / "model"
    r = _run_in_venv(venv, argv=[
        "mmlspark-tpu", "run", str(spec_path), "--data", str(csv_path),
        "--save", str(model_dir)], timeout=600)
    assert r.returncode == 0, f"stdout:{r.stdout}\nstderr:{r.stderr[-3000:]}"
    assert model_dir.exists()

    out_csv = tmp_path / "scored.csv"
    r = _run_in_venv(venv, argv=[
        "mmlspark-tpu", "score", "--model", str(model_dir),
        "--data", str(csv_path), "--out", str(out_csv)], timeout=600)
    assert r.returncode == 0, f"stdout:{r.stdout}\nstderr:{r.stderr[-3000:]}"
    with open(out_csv) as f:
        header = f.readline().strip().split(",")
    assert "prediction" in header
    # sanity: the model actually learned the synthetic rule
    import csv as _csv
    with open(out_csv) as f:
        rows = list(_csv.DictReader(f))
    preds = np.asarray([float(r["prediction"]) for r in rows])
    assert (preds == y[:len(preds)]).mean() > 0.9


def test_cli_serve_scores_over_http(installed_venv, tmp_path):
    """`mmlspark-tpu serve` on a saved model answers HTTP scoring
    requests — the zero-Python serving path."""
    import time
    import urllib.request
    venv, _ = installed_venv
    # build + save a tiny model through the CLI itself
    rng = np.random.default_rng(1)
    n = 200
    x = rng.normal(size=(n, 3)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32)
    csv_path = tmp_path / "t.csv"
    with open(csv_path, "w") as f:
        f.write("a,b,c,label\n")
        for i in range(n):
            f.write(",".join(str(v) for v in x[i]) + f",{y[i]}\n")
    spec_path = tmp_path / "p.json"
    spec_path.write_text(json.dumps({"pipeline": [
        {"stage": "FastVectorAssembler",
         "params": {"inputCols": ["a", "b", "c"],
                    "outputCol": "features"}},
        {"stage": "TPUBoostClassifier",
         "params": {"featuresCol": "features", "labelCol": "label",
                    "numIterations": 3, "numLeaves": 5}},
    ]}))
    model_dir = tmp_path / "m"
    r = _run_in_venv(venv, argv=[
        "mmlspark-tpu", "run", str(spec_path), "--data", str(csv_path),
        "--save", str(model_dir)], timeout=600)
    assert r.returncode == 0, r.stderr[-3000:]

    port = 18931
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["MMLSPARK_TPU_PLATFORM"] = "cpu"
    proc = subprocess.Popen(
        [str(venv / "bin" / "mmlspark-tpu"), "serve",
         "--model", str(model_dir), "--port", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=str(venv), env=env)
    try:
        deadline = time.time() + 120
        body = json.dumps(
            {"a": 1.5, "b": 0.0, "c": 0.0, "label": 0}).encode()
        last = None
        while time.time() < deadline:
            try:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/", data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=10) as resp:
                    reply = json.loads(resp.read())
                break
            except OSError as e:
                last = e
                time.sleep(1.0)
        else:
            raise AssertionError(f"server never answered: {last}")
        # the engine replies with the reply column's VALUE per row
        assert float(reply) == 1.0, reply
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_cli_import_onnx_then_score(installed_venv, tmp_path):
    """ONNX file -> saved stage -> scored table, all through console
    scripts from the installed wheel (zero Python written)."""
    from tests import onnx_writer as ow
    venv, _ = installed_venv
    rng = np.random.default_rng(5)
    w = rng.normal(scale=0.3, size=(4, 3)).astype(np.float32)
    b = rng.normal(size=3).astype(np.float32)
    nodes = [ow.node("Gemm", ["input", "w", "b"], ["output"],
                     alpha=1.0, beta=1.0)]
    onnx_path = tmp_path / "lin.onnx"
    onnx_path.write_bytes(ow.model(
        nodes, {"w": w, "b": b}, ("input", 1, ["N", 4]), "output"))

    model_dir = tmp_path / "onnx_model"
    r = _run_in_venv(venv, argv=[
        "mmlspark-tpu", "import-onnx", str(onnx_path),
        "--out", str(model_dir)], timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    info = json.loads(r.stdout.strip().splitlines()[0])
    assert info["ops"] == {"Gemm": 1}

    x = rng.normal(size=(6, 4)).astype(np.float32)
    npz_path = tmp_path / "in.npz"   # vector columns ship as npz
    np.savez(npz_path, images=x)
    out_dir = tmp_path / "scored"
    r = _run_in_venv(venv, argv=[
        "mmlspark-tpu", "score", "--model", str(model_dir),
        "--data", str(npz_path), "--out", str(out_dir)], timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    scored = np.load(out_dir / "columns.npz")["scores"]
    np.testing.assert_allclose(scored, x @ w + b, rtol=1e-5, atol=1e-6)
