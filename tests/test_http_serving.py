"""HTTP client + serving tests.

Reference strategy: HTTPSuite / DistributedHTTPSuite start real servers
and POST to them (ref: SURVEY.md §4 "Streaming/serving tests"); we do the
same with the threaded serving engine.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.io.http import (
    CustomInputParser, CustomOutputParser, HTTPSchema, HTTPTransformer,
    JSONInputParser, JSONOutputParser, SimpleHTTPTransformer,
)
from mmlspark_tpu.io.minibatch import (
    DynamicMiniBatchTransformer, FixedMiniBatchTransformer, FlattenBatch,
    TimeIntervalMiniBatchTransformer,
)
from mmlspark_tpu.serving import (
    HTTPSource, ServingEngine, SharedSingleton, SharedVariable, serve_model,
)
from mmlspark_tpu.stages.basic import Lambda


@pytest.fixture(scope="module")
def echo_server():
    """A serving engine that echoes {'x': v} -> {'doubled': 2v}."""
    def handle(table):
        replies = []
        for req in table["request"]:
            body = json.loads(req["entity"].decode())
            replies.append({"doubled": body["x"] * 2})
        return table.with_column("reply", replies)

    engine = serve_model(Lambda.apply(handle), port=18950, batch_size=8)
    yield engine
    engine.stop()


def _post(addr, payload, timeout=10):
    req = urllib.request.Request(
        addr, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


class TestServing:
    def test_single_request(self, echo_server):
        status, body = _post(echo_server.source.address, {"x": 21})
        assert status == 200
        assert body == {"doubled": 42}

    def test_concurrent_requests_route_correctly(self, echo_server):
        results = {}
        def client(i):
            _, body = _post(echo_server.source.address, {"x": i})
            results[i] = body["doubled"]
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(24)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results == {i: 2 * i for i in range(24)}

    def test_counters(self, echo_server):
        before = echo_server.source.requests_answered
        _post(echo_server.source.address, {"x": 1})
        assert echo_server.source.requests_answered == before + 1

    def test_pipeline_error_returns_500(self):
        def boom(table):
            raise RuntimeError("kaboom")
        engine = serve_model(Lambda.apply(boom), port=18980, batch_size=4)
        try:
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                _post(engine.source.address, {"x": 1})
            assert exc_info.value.code == 500
        finally:
            engine.stop()

    def test_poison_row_isolated_from_batch(self):
        # one poison request must NOT 500 its batchmates: the engine
        # retries the failed batch per-row
        # (ref: SimpleHTTPTransformer.scala:104-150 error split)
        def handle(table):
            replies = []
            for req in table["request"]:
                body = json.loads(req["entity"].decode())
                if body.get("boom"):
                    raise RuntimeError("poison row")
                replies.append({"ok": body["x"]})
            return table.with_column("reply", replies)

        engine = serve_model(Lambda.apply(handle), port=18985, batch_size=8)
        try:
            results: dict = {}

            def client(i):
                payload = {"boom": True} if i == 3 else {"x": i}
                try:
                    results[i] = _post(engine.source.address, payload)[1]
                except urllib.error.HTTPError as e:
                    results[i] = e.code

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert results[3] == 500
            for i in range(6):
                if i != 3:
                    assert results[i] == {"ok": i}, results
        finally:
            engine.stop()

    def test_poison_rows_isolated_multi_worker(self):
        # satellite coverage: poison isolation must hold when workers>1
        # drains the queue from several loop threads concurrently —
        # interleaved poison and healthy rows across racing micro-batches,
        # and healthy batchmates NEVER receive a 500
        def handle(table):
            replies = []
            for req in table["request"]:
                body = json.loads(req["entity"].decode())
                if body.get("boom"):
                    raise RuntimeError("poison row")
                replies.append({"ok": body["x"]})
            return table.with_column("reply", replies)

        engine = serve_model(Lambda.apply(handle), port=19050,
                             batch_size=4, workers=3)
        try:
            results: dict = {}
            poison = {i for i in range(24) if i % 4 == 0}

            def client(i):
                payload = {"boom": True, "x": i} if i in poison \
                    else {"x": i}
                try:
                    results[i] = _post(engine.source.address, payload,
                                       timeout=30)[1]
                except urllib.error.HTTPError as e:
                    results[i] = e.code

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(24)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for i in range(24):
                if i in poison:
                    assert results[i] == 500, (i, results[i])
                else:
                    assert results[i] == {"ok": i}, (i, results[i])
        finally:
            engine.stop()

    def test_healthz_endpoint(self, echo_server):
        # GET /healthz: liveness + counters without touching the scoring
        # path (the failover probe target)
        url = f"{echo_server.source.address}/healthz"
        with urllib.request.urlopen(url, timeout=5) as r:
            body = json.loads(r.read())
        assert r.status == 200
        assert body["status"] == "ok"
        for key in ("seen", "accepted", "answered", "rejected",
                    "parked", "queue_depth"):
            assert key in body, body
        # non-healthz GETs are 404, POST routing unaffected
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{echo_server.source.address}/other", timeout=5)
        assert ei.value.code == 404
        assert _post(echo_server.source.address, {"x": 2})[1] == \
            {"doubled": 4}

    def test_error_col_splits_rows(self):
        # pipelines can flag per-row failures via an 'error' column
        # instead of raising (the errorCol convention of the reference)
        def handle(table):
            replies, errors = [], []
            for req in table["request"]:
                body = json.loads(req["entity"].decode())
                if body["x"] < 0:
                    replies.append(None)
                    errors.append(f"negative x {body['x']}")
                else:
                    replies.append({"ok": body["x"]})
                    errors.append(None)
            return (table.with_column("reply", replies)
                    .with_column("error", errors))

        engine = serve_model(Lambda.apply(handle), port=18990, batch_size=8)
        try:
            assert _post(engine.source.address, {"x": 5})[1] == {"ok": 5}
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(engine.source.address, {"x": -1})
            assert ei.value.code == 500
        finally:
            engine.stop()

    def test_two_engines_two_ports(self):
        # the documented multi-host story: one serving engine per host
        # behind a load balancer — two engines, same pipeline, different
        # ports; replies route through the engine that accepted them
        def handle(table):
            return table.with_column("reply", [
                {"via": "pipeline",
                 "x": json.loads(r["entity"].decode())["x"]}
                for r in table["request"]])

        # ephemeral ports (port=0, bound address read back from the
        # socket): a fixed port pair flaked under ambient load when
        # another process grabbed one of the ports mid-test
        e1 = serve_model(Lambda.apply(handle), port=0, batch_size=4)
        e2 = serve_model(Lambda.apply(handle), port=0, batch_size=4)
        try:
            assert e1.source.port != e2.source.port
            assert e1.source.port > 0 and e2.source.port > 0
            results = {}

            def client(i):
                # round-robin "load balancer"
                engine = e1 if i % 2 == 0 else e2
                results[i] = _post(engine.source.address, {"x": i})[1]["x"]

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(10)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert results == {i: i for i in range(10)}
            assert e1.source.requests_answered >= 5
            assert e2.source.requests_answered >= 5
        finally:
            e1.stop()
            e2.stop()

    def test_serving_fleet_round_robin(self):
        # one engine per host behind a balancer, N ports in simulation
        # (ref: DistributedHTTPSource.scala per-executor servers)
        from mmlspark_tpu.serving import ServingFleet

        def handle(table):
            return table.with_column("reply", [
                {"echo": json.loads(r["entity"].decode())["x"]}
                for r in table["request"]])

        fleet = ServingFleet(Lambda.apply(handle), n_engines=3,
                             base_port=18700, batch_size=4)
        try:
            results = [fleet.post({"x": i})["echo"] for i in range(9)]
            assert results == list(range(9))
            c = fleet.counters()
            assert c["answered"] == 9
            # round-robin really spread the load
            per_engine = [e.source.requests_answered
                          for e in fleet.engines]
            assert per_engine == [3, 3, 3], per_engine
        finally:
            fleet.stop_all()

    def test_partition_consolidator(self):
        from mmlspark_tpu.serving import PartitionConsolidator
        import numpy as np
        t = DataTable({"x": np.arange(10.0)})
        # single host: pass-through
        assert len(PartitionConsolidator().transform(t)) == 10
        # simulated 2-host fleet: each host keeps its own range
        a = PartitionConsolidator(hostCount=2, hostIndex=0).transform(t)
        b = PartitionConsolidator(hostCount=2, hostIndex=1).transform(t)
        assert len(a) + len(b) == 10
        assert list(a["x"]) + list(b["x"]) == list(map(float, range(10)))

    def test_port_scan_on_conflict(self, echo_server):
        # same base port: must scan to the next free one
        src2 = HTTPSource(port=echo_server.source.port)
        try:
            assert src2.port != echo_server.source.port
        finally:
            src2.close()

    def test_shared_variable_and_singleton(self):
        calls = []
        sv = SharedVariable(lambda: calls.append(1) or "v")
        assert sv.get() == "v" and sv.get() == "v"
        assert len(calls) == 1
        a = SharedSingleton.get_or_create("k1", lambda: object())
        b = SharedSingleton.get_or_create("k1", lambda: object())
        assert a is b


class TestHTTPClient:
    def test_http_transformer_roundtrip(self, echo_server):
        addr = echo_server.source.address
        reqs = [HTTPSchema.request(
            addr, "POST", json.dumps({"x": v}).encode(),
            {"Content-Type": "application/json"}) for v in (3, 4)]
        t = DataTable({"req": reqs})
        out = HTTPTransformer(inputCol="req", outputCol="resp",
                              concurrency=2).transform(t)
        bodies = [json.loads(r["entity"]) for r in out["resp"]]
        assert bodies == [{"doubled": 6}, {"doubled": 8}]

    def test_connection_error_becomes_row(self):
        t = DataTable({"req": [HTTPSchema.request(
            "http://127.0.0.1:1/nothing", "POST", b"{}")]})
        out = HTTPTransformer(inputCol="req", outputCol="resp",
                              handlingStrategy="basic").transform(t)
        assert out["resp"][0]["statusLine"]["statusCode"] == 0

    def test_simple_http_transformer(self, echo_server):
        t = DataTable({"x": [{"x": 1}, {"x": 2}]})
        out = SimpleHTTPTransformer(
            inputCol="x", outputCol="parsed",
            url=echo_server.source.address).transform(t)
        assert list(out["parsed"]) == [{"doubled": 2}, {"doubled": 4}]
        assert all(e is None for e in out["HTTPTransformer_errors"])

    def test_simple_http_transformer_error_col(self):
        t = DataTable({"x": [{"x": 1}]})
        out = SimpleHTTPTransformer(
            inputCol="x", outputCol="parsed", timeout=2.0,
            url="http://127.0.0.1:1/none").transform(t)
        assert out["HTTPTransformer_errors"][0] is not None

    def test_custom_parsers(self, echo_server):
        addr = echo_server.source.address
        t = DataTable({"x": [7.0]})
        inp = CustomInputParser(udf=lambda v: HTTPSchema.request(
            addr, "POST", json.dumps({"x": v}).encode(),
            {"Content-Type": "application/json"}))
        outp = CustomOutputParser(
            udf=lambda r: json.loads(r["entity"])["doubled"])
        out = SimpleHTTPTransformer(
            inputCol="x", outputCol="y", inputParser=inp,
            outputParser=outp).transform(t)
        assert out["y"][0] == 14.0

    def test_json_parsers_standalone(self):
        t = DataTable({"v": [{"a": 1}]})
        reqs = JSONInputParser(url="http://example.invalid",
                               inputCol="v",
                               outputCol="req").transform(t)
        assert json.loads(reqs["req"][0]["entity"]) == {"a": 1}
        resp_t = DataTable({"resp": [HTTPSchema.response(
            200, "OK", b'{"b": 2}')]})
        parsed = JSONOutputParser(inputCol="resp",
                                  outputCol="out").transform(resp_t)
        assert parsed["out"][0] == {"b": 2}


class TestMiniBatch:
    def test_fixed_roundtrip(self):
        t = DataTable({"a": np.arange(7).astype(float),
                       "s": [f"r{i}" for i in range(7)]})
        batched = FixedMiniBatchTransformer(batchSize=3).transform(t)
        assert len(batched) == 3
        assert [len(b) for b in batched["a"]] == [3, 3, 1]
        flat = FlattenBatch().transform(batched)
        np.testing.assert_allclose(list(flat["a"]),
                                   np.arange(7).astype(float))
        assert list(flat["s"]) == [f"r{i}" for i in range(7)]

    def test_dynamic_respects_shards(self):
        t = DataTable({"a": np.arange(8).astype(float)}).repartition(4)
        batched = DynamicMiniBatchTransformer().transform(t)
        assert len(batched) == 4

    def test_time_interval_windows(self):
        t = DataTable({"ts": np.asarray([0, 10, 2000, 2010, 9000]),
                       "v": np.arange(5).astype(float)})
        batched = TimeIntervalMiniBatchTransformer(
            millisToWait=500, timestampCol="ts").transform(t)
        assert [len(b) for b in batched["v"]] == [2, 2, 1]

    def test_flatten_broadcasts_scalar_columns(self):
        # regression: a per-batch scalar (e.g. error struct) must be
        # broadcast to every exploded row, not erased to None
        t = DataTable({"vals": [[1.0, 2.0], [3.0]],
                       "err": ["batch0_err", None]})
        flat = FlattenBatch().transform(t)
        assert list(flat["err"]) == ["batch0_err", "batch0_err", None]

    def test_batched_simple_http_keeps_errors(self):
        from mmlspark_tpu.io.http import SimpleHTTPTransformer
        from mmlspark_tpu.io.minibatch import FixedMiniBatchTransformer
        t = DataTable({"x": [{"x": 1}, {"x": 2}]})
        sh = SimpleHTTPTransformer(
            inputCol="x", outputCol="parsed", timeout=2.0,
            url="http://127.0.0.1:1/none")
        sh.set_mini_batcher(FixedMiniBatchTransformer(batchSize=2))
        out = sh.transform(t)
        # every flattened row must carry the batch's error
        assert all(e is not None for e in out["HTTPTransformer_errors"])

    def test_api_path_routing(self):
        src = HTTPSource(port=19040, api_path="/score")
        try:
            import urllib.error
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(f"http://127.0.0.1:{src.port}/other", {"x": 1},
                      timeout=5)
            assert ei.value.code == 404
        finally:
            src.close()

    def test_flatten_empty(self):
        t = DataTable({"a": np.asarray([]), "b": []})
        batched = FixedMiniBatchTransformer(batchSize=2).transform(t)
        flat = FlattenBatch().transform(batched)
        assert len(flat) == 0
        assert "a" in flat.column_names


class TestServingThroughput:
    """Serving performance floor (bench.py bench_serving measures the
    real-chip number; this guards the machinery from regressing into
    per-request recompiles or serialized batching on any backend)."""

    @pytest.mark.slow   # wall-clock floor: meaningless on a contended host
    def test_fleet_qps_floor(self):
        import concurrent.futures
        import time as _time

        import jax
        from mmlspark_tpu.models.networks import build_network
        from mmlspark_tpu.models.tpu_model import TPUModel
        from mmlspark_tpu.serving.fleet import (
            ServingFleet, json_scoring_pipeline,
        )

        dim, n_req, clients = 32, 60, 6
        module = build_network({"type": "mlp", "features": [32],
                                "num_classes": 4})
        weights = {"params": module.init(
            jax.random.PRNGKey(0), np.zeros((1, dim), np.float32))["params"]}
        model = TPUModel(modelFn=lambda w, ins: module.apply(
            {"params": w["params"]}, list(ins.values())[0]),
            weights=weights, inputCol="features", outputCol="scores",
            batchSize=64, computeDtype="float32")

        fleet = ServingFleet(json_scoring_pipeline(model), n_engines=2,
                             base_port=18880, batch_size=64, workers=2)
        payload = {"features": [0.1] * dim}

        def timed_post(addr):
            t0 = _time.perf_counter()
            status, body = _post(addr, payload, 60)
            return status, body, _time.perf_counter() - t0

        try:
            for addr in fleet.addresses:          # warmup compiles
                _post(addr, payload, timeout=60)
            lat = []
            t0 = _time.perf_counter()
            with concurrent.futures.ThreadPoolExecutor(clients) as ex:
                futs = [ex.submit(timed_post, fleet.addresses[i % 2])
                        for i in range(n_req)]
                for f in concurrent.futures.as_completed(futs):
                    status, body, dt = f.result()
                    assert status == 200 and "prediction" in body
                    lat.append(dt)
            wall = _time.perf_counter() - t0
        finally:
            fleet.stop_all()
        qps = n_req / wall
        p99 = float(np.quantile(lat, 0.99))
        # floors sized to catch a 2x machinery regression (per-request
        # recompiles, serialized batching, lost micro-batch overlap)
        # while riding out shared-host noise: the same config measures
        # ~150+ qps / p99 well under 0.5 s on an otherwise idle 1-core
        # CI host (VERDICT r4 weak #2/#5: the old >=10 floor let a 10x
        # regression ship, and p99 was unobserved — the round-4 history
        # shows a bucketing bug that took p99 2.3s -> 0.3s)
        assert qps >= 40, f"serving throughput collapsed: {qps:.1f} qps"
        assert p99 <= 1.5, (
            f"serving tail latency blew up: p99 {p99:.2f}s "
            f"(p50 {float(np.quantile(lat, 0.5)):.2f}s)")
    def test_ragged_batches_bound_compiled_shapes(self):
        """Mechanism guard, host-speed independent: scoring 20 DIFFERENT
        ragged batch sizes must stay within the power-of-two bucket
        count (log2(batchSize)+O(1) compiled shapes). Losing bucketing
        means one XLA compile per ragged size — seconds per shape
        through a real-chip tunnel even though a CPU CI host shrugs it
        off, which is exactly how the round-4 p99=2.3s serving bug
        shipped. (Deliberately disabling _bucket makes this fail with
        20 shapes.)"""
        import jax
        from mmlspark_tpu.models.networks import build_network
        from mmlspark_tpu.models.tpu_model import TPUModel

        dim = 16
        module = build_network({"type": "mlp", "features": [16],
                                "num_classes": 3})
        weights = {"params": module.init(
            jax.random.PRNGKey(0), np.zeros((1, dim), np.float32))["params"]}
        model = TPUModel(modelFn=lambda w, ins: module.apply(
            {"params": w["params"]}, list(ins.values())[0]),
            weights=weights, inputCol="features", outputCol="scores",
            batchSize=64, computeDtype="float32")
        # 1-device mesh = the real single-chip serving topology: the
        # 8-device CI mesh would pad every batch to a multiple of 8 in
        # shard_batch and mask a lost bucket
        from mmlspark_tpu.parallel import mesh as mesh_lib
        model.set_mesh(mesh_lib.make_mesh(
            {"data": 1}, devices=[jax.devices()[0]]))
        rng = np.random.default_rng(0)
        for rows in range(1, 21):                 # 20 ragged sizes
            t = DataTable({"features": rng.normal(
                size=(rows, dim)).astype(np.float32)})
            out = model.transform(t)
            assert len(out) == rows
        compiled = model._jitted.get("run")
        assert compiled is not None
        n_shapes = compiled._cache_size()
        # sizes 1..20 bucket to {8, 16, 32}: 3 shapes; allow slack
        assert n_shapes <= 6, (
            f"batch bucketing lost: {n_shapes} distinct compiled "
            f"shapes for 20 ragged batch sizes")


class TestAdaptiveBatcher:
    """The adaptive micro-batcher contract: flush on batch-full OR
    deadline (whichever first), padded rows never leak into replies,
    and the /healthz metrics export carries the latency histograms."""

    def test_deadline_triggered_flush(self):
        # a lone request must NOT wait for batch_size rows: the
        # max_wait_ms deadline flushes a 1-row batch
        def handle(table):
            return table.with_column("reply", [
                {"echo": json.loads(r["entity"].decode())["x"]}
                for r in table["request"]])

        engine = serve_model(Lambda.apply(handle), port=19200,
                             batch_size=64, max_wait_ms=30.0)
        try:
            import time as _time
            t0 = _time.perf_counter()
            status, body = _post(engine.source.address, {"x": 7})
            dt = _time.perf_counter() - t0
            assert status == 200 and body == {"echo": 7}
            # deadline (30 ms) + service, nowhere near a full-batch wait
            assert dt < 5.0, f"deadline flush took {dt:.2f}s"
            assert engine.batches_processed >= 1
            assert engine.hists["batch_rows"].summary()["max"] == 1.0
        finally:
            engine.stop()

    def test_max_batch_triggered_flush(self):
        # batch_size concurrent requests must flush IMMEDIATELY on
        # filling the batch, long before a (deliberately huge) deadline
        import time as _time
        done = threading.Event()

        def handle(table):
            return table.with_column("reply", [
                {"echo": json.loads(r["entity"].decode())["x"]}
                for r in table["request"]])

        engine = serve_model(Lambda.apply(handle), port=19205,
                             batch_size=4, max_wait_ms=10_000.0)
        try:
            results = {}

            def client(i):
                results[i] = _post(engine.source.address, {"x": i},
                                   timeout=30)[1]["echo"]

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(4)]
            t0 = _time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = _time.perf_counter() - t0
            assert results == {i: i for i in range(4)}
            # a deadline-only flush would have taken >= 10 s
            assert wall < 5.0, f"max-batch flush took {wall:.1f}s"
        finally:
            engine.stop()
            done.set()

    def test_pad_and_mask_correctness(self):
        # bucket padding must never leak: N concurrent requests with
        # DISTINCT payloads each get exactly their own model output,
        # and exactly N replies exist (padded rows are sliced off)
        import jax
        from mmlspark_tpu.models.networks import build_network
        from mmlspark_tpu.models.tpu_model import TPUModel
        from mmlspark_tpu.serving.fleet import json_scoring_pipeline

        dim = 8
        module = build_network({"type": "mlp", "features": [16],
                                "num_classes": 5})
        weights = {"params": module.init(
            jax.random.PRNGKey(0),
            np.zeros((1, dim), np.float32))["params"]}
        model = TPUModel(modelFn=lambda w, ins: module.apply(
            {"params": w["params"]}, list(ins.values())[0]),
            weights=weights, inputCol="features", outputCol="scores",
            batchSize=64, computeDtype="float32")
        rng = np.random.default_rng(3)
        feats = rng.normal(size=(5, dim)).astype(np.float32)   # pads to 8
        expected = np.asarray(module.apply(
            {"params": weights["params"]}, feats)).argmax(-1)

        engine = serve_model(json_scoring_pipeline(model), port=19210,
                             batch_size=64, max_wait_ms=50.0)
        try:
            results = {}

            def client(i):
                results[i] = _post(
                    engine.source.address,
                    {"features": feats[i].tolist()},
                    timeout=60)[1]["prediction"]

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(5)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert results == {i: int(expected[i]) for i in range(5)}, (
                f"padded-batch replies wrong: {results} vs {expected}")
            # exactly the accepted requests were answered — no padded
            # phantom replies
            assert engine.source.requests_answered == 5
        finally:
            engine.stop()

    def test_healthz_exports_latency_histograms(self):
        def handle(table):
            return table.with_column(
                "reply", [{"ok": 1} for _ in table["request"]])

        engine = serve_model(Lambda.apply(handle), port=19215,
                             batch_size=8, max_wait_ms=5.0)
        try:
            _post(engine.source.address, {"x": 1})
            with urllib.request.urlopen(
                    f"{engine.source.address}/healthz", timeout=5) as r:
                body = json.loads(r.read())
            m = body["metrics"]
            for key in ("queue_wait_ms", "pipeline_ms", "respond_ms",
                        "batch_rows"):
                assert key in m, m
            assert m["queue_wait_ms"]["count"] >= 1
            assert m["pipeline_ms"]["count"] >= 1
            assert m["batches_processed"] >= 1
        finally:
            engine.stop()

    def test_split_pipeline_decode_runs_on_batcher(self):
        # a pipeline exposing prepare_batch/execute_prepared must see
        # its decode stage run (decode_ms histogram fills) and still
        # answer correctly
        calls = []

        def decode(table):
            calls.append(len(table))
            return [json.loads(r["entity"].decode())["x"]
                    for r in table["request"]]

        def execute(table, xs):
            return table.with_column("reply", [{"doubled": 2 * x}
                                               for x in xs])

        lam = Lambda.apply(
            lambda table: execute(table, decode(table)))
        lam.prepare_batch = decode
        lam.execute_prepared = execute
        engine = serve_model(lam, port=19220, batch_size=8,
                             max_wait_ms=5.0)
        try:
            assert _post(engine.source.address, {"x": 4})[1] == \
                {"doubled": 8}
            assert engine.hists["decode_ms"].summary()["count"] >= 1
            assert calls, "prepare_batch never ran"
        finally:
            engine.stop()

    def test_get_batch_adaptive_embedder_api(self):
        # the packaged adaptive drain for embedders running their own
        # loop: flushes on max_rows, reports per-request queue waits,
        # and returns empty cleanly on an idle queue
        src = HTTPSource(port=19230)
        try:
            results = {}

            def client(i):
                try:
                    results[i] = _post(
                        f"http://127.0.0.1:{src.port}/", {"x": i},
                        timeout=10)[1]
                except Exception as e:  # noqa: BLE001
                    results[i] = repr(e)

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(3)]
            for t in threads:
                t.start()
            deadline = __import__("time").time() + 5
            got = 0
            while got < 3 and __import__("time").time() < deadline:
                table, ids, waits = src.get_batch_adaptive(
                    max_rows=3, max_wait_s=0.05)
                assert len(ids) == len(table) == len(waits)
                assert all(w >= 0.0 for w in waits)
                for rid in ids:
                    src.respond(rid, HTTPSchema.response(
                        200, "OK", b'{"ok": 1}',
                        {"Content-Type": "application/json"}))
                got += len(ids)
            for t in threads:
                t.join(timeout=10)
            assert got == 3
            assert results == {i: {"ok": 1} for i in range(3)}, results
            # idle queue: clean empty drain
            table, ids, waits = src.get_batch_adaptive(
                max_rows=3, max_wait_s=0.01, poll_s=0.01)
            assert ids == [] and waits == [] and len(table) == 0
        finally:
            src.close()
