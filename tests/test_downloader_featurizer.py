"""ModelDownloader + ImageFeaturizer tests
(ref strategy: downloader DownloaderSuite + image-featurizer
ImageFeaturizerSuite — fetch from repo, verify, featurize tiny images)."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mmlspark_tpu.core.schema import ImageSchema
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.downloader import LocalRepo, ModelDownloader, ModelSchema
from mmlspark_tpu.models.networks import build_network
from mmlspark_tpu.stages.featurizer import ImageFeaturizer


@pytest.fixture(scope="module")
def zoo(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("zoo")
    repo = LocalRepo(str(tmp / "repo"))
    spec = {"type": "resnet", "stage_sizes": [1, 1, 1], "width": 8,
            "num_classes": 10}
    mod = build_network(spec)
    variables = mod.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    schema = repo.publish("ResNet_tiny", spec, variables, dataset="CIFAR",
                          model_type="image", input_shape=[32, 32, 3],
                          layer_names=mod.feature_layers())
    dl = ModelDownloader(str(tmp / "cache"), repo=repo)
    return repo, dl, schema


def _image_table(n=6, hw=(32, 32), seed=0):
    rng = np.random.default_rng(seed)
    rows = [ImageSchema.make_row(
        f"img{i}", rng.integers(0, 255, (*hw, 3)).astype(np.uint8), "RGB")
        for i in range(n)]
    return DataTable({"image": rows})


class TestModelDownloader:
    def test_download_and_verify(self, zoo):
        _, dl, schema = zoo
        s2 = dl.download_by_name("ResNet_tiny")
        assert s2.sha256 == schema.sha256
        assert s2.network_spec["type"] == "resnet"

    def test_cached_fetch_without_repo(self, zoo):
        _, dl, _ = zoo
        dl.download_by_name("ResNet_tiny")
        dl2 = ModelDownloader(dl.local.path, repo=None)
        assert dl2.download_by_name("ResNet_tiny").name == "ResNet_tiny"

    def test_unknown_model_raises(self, zoo):
        _, dl, _ = zoo
        with pytest.raises(KeyError):
            dl.download_by_name("NoSuchModel")

    def test_load_variables_shapes(self, zoo):
        _, dl, _ = zoo
        v = dl.load_variables("ResNet_tiny")
        assert "params" in v

    def test_corruption_detected(self, zoo, tmp_path):
        repo = LocalRepo(str(tmp_path / "r2"))
        spec = {"type": "mlp", "features": [4], "num_classes": 2}
        mod = build_network(spec)
        variables = mod.init(jax.random.PRNGKey(0), jnp.zeros((1, 8)))
        schema = repo.publish("m", spec, variables, input_shape=[8])
        blob = repo.blob_path(schema)
        with open(blob, "r+b") as f:
            f.seek(0)
            f.write(b"corrupted!")
        with pytest.raises(IOError, match="sha256"):
            repo.read_blob(schema)

    def test_list_models(self, zoo):
        _, dl, _ = zoo
        names = [s.name for s in dl.list_models()]
        assert "ResNet_tiny" in names


class TestImageFeaturizer:
    def test_featurize_cut1(self, zoo):
        _, dl, schema = zoo
        feat = ImageFeaturizer.from_model_schema(schema, dl,
                                                 cutOutputLayers=1)
        out = feat.transform(_image_table())
        f = out["features"]
        assert f.shape == (6, 32)  # pool layer of width-8 resnet: 8*4
        assert np.isfinite(f).all()

    def test_deeper_cut_gives_spatial_features(self, zoo):
        _, dl, schema = zoo
        feat = ImageFeaturizer.from_model_schema(schema, dl,
                                                 cutOutputLayers=2)
        out = feat.transform(_image_table())
        assert out["features"].shape[1] > 32

    def test_keep_head(self, zoo):
        _, dl, schema = zoo
        feat = ImageFeaturizer.from_model_schema(schema, dl,
                                                 cutOutputLayers=0)
        out = feat.transform(_image_table())
        assert out["features"].shape == (6, 10)  # logits

    def test_resizes_nonconforming_images(self, zoo):
        _, dl, schema = zoo
        feat = ImageFeaturizer.from_model_schema(schema, dl)
        out = feat.transform(_image_table(hw=(48, 64)))
        assert out["features"].shape == (6, 32)

    def test_schema_propagation(self, zoo):
        _, dl, schema = zoo
        feat = ImageFeaturizer.from_model_schema(schema, dl)
        t = _image_table(2)
        out_schema = feat.transform_schema(t.schema)
        assert "features" in out_schema.names

    def test_save_load_roundtrip(self, zoo, tmp_path):
        _, dl, schema = zoo
        feat = ImageFeaturizer.from_model_schema(schema, dl,
                                                 cutOutputLayers=1)
        t = _image_table(3)
        ref = feat.transform(t)["features"]
        path = str(tmp_path / "featurizer")
        feat.save(path)
        feat2 = ImageFeaturizer.load(path)
        np.testing.assert_allclose(feat2.transform(t)["features"], ref,
                                   atol=1e-5)

    def test_cut_too_deep_raises(self, zoo):
        _, dl, schema = zoo
        feat = ImageFeaturizer.from_model_schema(schema, dl,
                                                 cutOutputLayers=99)
        with pytest.raises(ValueError, match="feature layers"):
            feat.transform(_image_table(2))


class TestImageFeaturizerPipeline:
    """The pipelined transform: partial batches pad to ``batchSize``
    (one compiled shape, ever) and the prefetch/readback overlap must
    not reorder or corrupt rows."""

    def test_mixed_table_sizes_zero_steady_state_recompiles(self, zoo):
        _, dl, schema = zoo
        feat = ImageFeaturizer.from_model_schema(
            schema, dl, cutOutputLayers=1, batchSize=4)
        feat.transform(_image_table(6))   # warm: the ONE compile
        assert feat.jit_cache_misses == 1
        for n in (3, 7, 4, 1, 9):         # partial + exact + multi-batch
            out = feat.transform(_image_table(n, seed=n))
            assert out["features"].shape[0] == n
        assert feat.jit_cache_misses == 1, (
            "partial/mixed batch sizes must reuse the padded-bucket "
            "compile, not trigger fresh XLA compiles")

    def test_partial_batch_matches_single_batch(self, zoo):
        # 6 rows at batchSize=4 (padded partial last batch) must equal
        # the same rows at batchSize=8 (one full-table batch): padding
        # rows are sliced off and never leak into valid outputs
        _, dl, schema = zoo
        t = _image_table(6, seed=11)
        f_split = ImageFeaturizer.from_model_schema(
            schema, dl, cutOutputLayers=1, batchSize=4).transform(t)
        f_whole = ImageFeaturizer.from_model_schema(
            schema, dl, cutOutputLayers=1, batchSize=8).transform(t)
        np.testing.assert_allclose(f_split["features"],
                                   f_whole["features"], atol=1e-5)

    def test_weights_shipped_once(self, zoo):
        _, dl, schema = zoo
        feat = ImageFeaturizer.from_model_schema(
            schema, dl, cutOutputLayers=1, batchSize=4)
        feat.transform(_image_table(4))
        dev = feat._device_weights
        assert dev is not None
        feat.transform(_image_table(4, seed=1))
        assert feat._device_weights is dev   # reused, not re-put
        feat.set("weights", feat.get("weights"))
        assert feat._device_weights is None  # param change invalidates

    def test_empty_table(self, zoo):
        _, dl, schema = zoo
        feat = ImageFeaturizer.from_model_schema(
            schema, dl, cutOutputLayers=1, batchSize=4)
        out = feat.transform(_image_table(0))
        assert out["features"].shape[0] == 0
