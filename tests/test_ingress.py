"""Columnar serving ingress (io/columnar.py): codec round trips,
bit-parity with the JSON oracle, per-request poison isolation,
content-type negotiation fallback, the swap/recompile/roundtrip
discipline on the columnar path, and the ingress static checker."""

import json
import sys
import threading
import urllib.error

import numpy as np
import pytest

from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.io import columnar as C


def make_request(body: bytes, codec: str = None) -> dict:
    headers = ({"Content-Type": C.CODEC_CONTENT_TYPES[codec]}
               if codec else {"Content-Type": "application/json"})
    return {"requestLine": {"method": "POST", "uri": "/"},
            "headers": headers, "entity": body}


def request_table(items) -> DataTable:
    """items: list of (body, codec|None) -> the engine's batch table."""
    reqs = [make_request(b, c) for b, c in items]
    return DataTable({"id": [f"r{i}" for i in range(len(items))],
                      "request": reqs})


def reply_of(out: DataTable, i: int):
    return out["reply"][i]["prediction"]


@pytest.fixture()
def pyarrow_masked(monkeypatch):
    """Simulate a container without pyarrow: the inline imports in
    io/columnar.py must fall back (msgpack string loop) or raise a
    clean CodecError (arrow codec)."""
    monkeypatch.setitem(sys.modules, "pyarrow", None)
    yield


class TestCodecRoundTrip:
    COLS = {
        "f32": np.array([[1.5, -2.25], [np.nan, np.inf],
                         [-np.inf, 0.0]], dtype=np.float32),
        "f64": np.array([1.0, np.nan, -1e300]),
        "i64": np.array([1, -2, 2**40], dtype=np.int64),
        "i32": np.array([7, 8, 9], dtype=np.int32),
        "flag": np.array([True, False, True]),
        "s": ["héllo", None, "𝔘nicode\n\"quoted\""],
        "toks": [["a", "bb"], [], ["𝔠", ""]],
    }

    @pytest.mark.parametrize("codec", ["msgpack", "arrow"])
    def test_roundtrip_all_types(self, codec):
        body, ct = C.encode_columns(self.COLS, codec=codec)
        assert ct == C.CODEC_CONTENT_TYPES[codec]
        b = C.decode_columnar(codec, body)
        assert b.n_rows == 3
        np.testing.assert_array_equal(b.columns["f32"], self.COLS["f32"])
        assert b.columns["f32"].dtype == np.float32
        np.testing.assert_array_equal(b.columns["f64"], self.COLS["f64"])
        assert list(b.columns["i64"]) == list(self.COLS["i64"])
        assert list(b.columns["i32"]) == [7, 8, 9]
        assert list(np.asarray(b.columns["flag"], bool)) == \
            [True, False, True]
        assert b.columns["s"] == self.COLS["s"]
        assert [list(t) for t in b.columns["toks"]] == self.COLS["toks"]

    def test_zero_copy_numeric_view(self):
        arr = np.arange(32, dtype=np.float32).reshape(4, 8)
        body, _ = C.encode_columns({"f": arr})
        dec = C.decode_columnar("msgpack", body).columns["f"]
        # a view into the body buffer, not a copy
        assert dec.base is not None
        np.testing.assert_array_equal(dec, arr)

    def test_roundtrip_fuzz(self):
        rng = np.random.default_rng(0)
        alphabet = ["w", "éé", "𝔴ord", "", "x" * 50]
        for it in range(8):
            n = int(rng.integers(1, 40))
            cols = {
                "a": rng.normal(size=n),
                "b": rng.normal(size=(n, int(rng.integers(1, 9)))
                                ).astype(np.float32),
                "i": rng.integers(-1000, 1000, n),
                "s": [None if rng.random() < 0.2
                      else alphabet[int(rng.integers(len(alphabet)))]
                      for _ in range(n)],
                "t": [[alphabet[int(j)] for j in
                       rng.integers(0, len(alphabet),
                                    int(rng.integers(0, 5)))]
                      for _ in range(n)],
            }
            for codec in ("msgpack", "arrow"):
                b = C.decode_columnar(
                    codec, C.encode_columns(cols, codec=codec)[0])
                assert b.n_rows == n
                np.testing.assert_array_equal(b.columns["a"], cols["a"])
                np.testing.assert_array_equal(b.columns["b"], cols["b"])
                assert list(b.columns["i"]) == list(cols["i"])
                assert b.columns["s"] == cols["s"]
                assert [list(x) for x in b.columns["t"]] == cols["t"]

    def test_empty_batch_roundtrip(self):
        body, _ = C.encode_columns({"f": np.zeros((0, 4))})
        b = C.decode_columnar("msgpack", body)
        assert b.n_rows == 0 and b.columns["f"].shape == (0, 4)

    @pytest.mark.parametrize("bad", [
        b"", b"garbage-not-a-frame", b"MCOL", b"MCOL\x01\xff\xff\xff\xff",
    ])
    def test_malformed_raises_codec_error(self, bad):
        with pytest.raises(C.CodecError):
            C.decode_columnar("msgpack", bad)

    def test_truncated_buffer_raises(self):
        body, _ = C.encode_columns({"f": np.ones((8, 4))})
        with pytest.raises(C.CodecError):
            C.decode_columnar("msgpack", body[:len(body) - 16])

    def test_corrupt_string_offsets_raise(self):
        # descending offsets must be rejected, not produce garbage
        body, _ = C.encode_columns({"s": ["abc", "de"]})
        mutated = bytearray(body)
        # find the offsets buffer: int32 [0, 3, 5] in the payload
        pat = np.array([0, 3, 5], np.int32).tobytes()
        i = bytes(mutated).index(pat)
        mutated[i:i + 12] = np.array([5, 3, 0], np.int32).tobytes()
        with pytest.raises(C.CodecError):
            C.decode_columnar("msgpack", bytes(mutated))

    def test_negotiate(self):
        assert C.negotiate(None) == "json"
        assert C.negotiate({}) == "json"
        assert C.negotiate({"Content-Type": "text/plain"}) == "json"
        assert C.negotiate(
            {"Content-Type": "application/json; charset=utf-8"}) == "json"
        assert C.negotiate(
            {"content-type": C.CT_MSGPACK_COLUMNS}) == "msgpack"
        assert C.negotiate(
            {"CONTENT-TYPE": C.CT_ARROW_STREAM + "; x=1"}) == "arrow"

    def test_unknown_codec_rejected(self):
        with pytest.raises(C.CodecError):
            C.decode_columnar("nope", b"x")
        with pytest.raises(C.CodecError):
            C.encode_columns({"a": np.ones(2)}, codec="nope")

    def test_msgpack_header_json_fallback(self, monkeypatch):
        """Without msgpack installed the frame header serializes as
        JSON (flag byte 0) and decodes identically."""
        monkeypatch.setattr(C, "_msgpack", lambda: None)
        cols = {"f": np.arange(6, dtype=np.float64).reshape(3, 2),
                "s": ["a", None, "b"]}
        body, _ = C.encode_columns(cols)
        assert body[4] == 0    # JSON header flag
        b = C.decode_columnar("msgpack", body)
        np.testing.assert_array_equal(b.columns["f"], cols["f"])
        assert b.columns["s"] == cols["s"]

    def test_pyarrow_masked_fallbacks(self, pyarrow_masked):
        cols = {"f": np.ones((3, 2), np.float32), "s": ["x", None, "z"],
                "t": [["a"], [], ["b", "c"]]}
        body, _ = C.encode_columns(cols)      # msgpack needs no pyarrow
        b = C.decode_columnar("msgpack", body)
        assert b.columns["s"] == cols["s"]    # fallback string loop
        assert [list(t) for t in b.columns["t"]] == cols["t"]
        np.testing.assert_array_equal(b.columns["f"], cols["f"])
        with pytest.raises(C.CodecError):
            C.encode_columns(cols, codec="arrow")
        with pytest.raises(C.CodecError):
            C.decode_columnar("arrow", b"ARROW1")

    def test_staging_pool_ring_reuse(self):
        pool = C.StagingPool(depth=3)
        a = np.arange(8, dtype=np.float32).reshape(2, 4)
        outs = [pool.pad("k", a, 8) for _ in range(4)]
        assert all(o.shape == (8, 4) for o in outs)
        for o in outs:
            np.testing.assert_array_equal(o[:2], a)
            np.testing.assert_array_equal(o[2:], np.tile(a[-1], (6, 1)))
        assert outs[3] is outs[0]       # ring wrapped
        assert outs[1] is not outs[0]
        # full bucket passes through untouched (no copy)
        full = np.ones((8, 4), np.float32)
        assert pool.pad("k", full, 8) is full
        with pytest.raises(ValueError):
            pool.pad("k", a[:0], 8)     # nothing to edge-pad from

    def test_assemble_column_fast_and_fallback(self):
        b1 = C.ColumnarBatch({"x": np.arange(3.0)}, 3)
        b2 = C.ColumnarBatch({"x": np.arange(2.0) + 10}, 2)
        out = C.assemble_column([b1, b2], "x", 5)
        assert isinstance(out, np.ndarray)
        np.testing.assert_array_equal(out, [0, 1, 2, 10, 11])
        # single request: the zero-copy view itself
        assert C.assemble_column([b1], "x", 3) is b1.columns["x"]
        # mixed with a JSON row dict -> list fallback, JSON semantics
        out = C.assemble_column([b1, {"x": 7.0}], "x", 4)
        assert out == [0.0, 1.0, 2.0, 7.0]
        # a batch missing the column fills None (JSON .get semantics)
        out = C.assemble_column([{"x": 1.0}, C.ColumnarBatch({}, 2)],
                                "x", 3)
        assert out == [1.0, None, None]
        # per-request width mismatch is a CodecError, not a ValueError
        w1 = C.ColumnarBatch({"x": np.ones((2, 3))}, 2)
        w2 = C.ColumnarBatch({"x": np.ones((2, 4))}, 2)
        with pytest.raises(C.CodecError):
            C.assemble_column([w1, w2], "x", 4)

    def test_object_dtype_numeric_list_refused_client_side(self):
        # a None inside a numeric list would otherwise serialize raw
        # CPython heap pointers (object-array tobytes) onto the wire —
        # must refuse at encode time with an actionable message
        with pytest.raises(C.CodecError, match="NaN"):
            C.encode_columns({"x": [1.0, None, 2.0]})
        with pytest.raises(C.CodecError):
            C.encode_columns({"x": [[1.0, 2.0], [3.0]]})  # ragged

    def test_columns_to_rows(self):
        rows = C.columns_to_rows({"a": np.array([1.5, 2.5]),
                                  "s": ["x", "y"],
                                  "v": np.array([[1, 2], [3, 4]])})
        assert rows == [{"a": 1.5, "s": "x", "v": [1, 2]},
                        {"a": 2.5, "s": "y", "v": [3, 4]}]


# ---------------------------------------------------------------------------
# scoring-path parity (no HTTP: the scorer stages driven directly)
# ---------------------------------------------------------------------------


def _tpu_model(dim=8, classes=4):
    from mmlspark_tpu.models.tpu_model import TPUModel
    rng = np.random.default_rng(3)
    W = rng.normal(size=(dim, classes)).astype(np.float32)
    return TPUModel.from_fn(
        lambda w, ins: list(ins.values())[0] @ w["W"], {"W": W},
        inputCol="features", outputCol="scores", batchSize=32)


class TestTPUModelColumnarParity:
    def test_bit_parity_json_vs_columnar(self):
        from mmlspark_tpu.serving.fleet import json_scoring_pipeline
        model = _tpu_model()
        stage = json_scoring_pipeline(model)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(6, 8))
        x[0, 0] = np.nan
        x[1, 1] = np.inf
        x[2, 2] = -np.inf
        json_out = stage.transform(request_table(
            [(json.dumps({"features": list(map(float, row))}).encode(),
              None) for row in x]))
        json_preds = [reply_of(json_out, i) for i in range(6)]
        for codec in ("msgpack", "arrow"):
            body, _ = C.encode_columns({"features": x}, codec=codec)
            out = stage.transform(request_table([(body, codec)]))
            assert reply_of(out, 0) == json_preds, codec

    def test_mixed_codec_batch(self):
        from mmlspark_tpu.serving.fleet import json_scoring_pipeline
        stage = json_scoring_pipeline(_tpu_model())
        rng = np.random.default_rng(1)
        x = rng.normal(size=(4, 8))
        mp_body, _ = C.encode_columns({"features": x[:2]})
        ar_body, _ = C.encode_columns({"features": x[2:3]},
                                      codec="arrow")
        js_body = json.dumps(
            {"features": list(map(float, x[3]))}).encode()
        out = stage.transform(request_table(
            [(mp_body, "msgpack"), (ar_body, "arrow"), (js_body, None)]))
        ref = stage.transform(request_table([(C.encode_columns(
            {"features": x})[0], "msgpack")]))
        flat = (reply_of(out, 0) + reply_of(out, 1)
                + [reply_of(out, 2)])
        assert flat == reply_of(ref, 0)

    def test_zero_row_request(self):
        from mmlspark_tpu.serving.fleet import json_scoring_pipeline
        stage = json_scoring_pipeline(_tpu_model())
        body, _ = C.encode_columns(
            {"features": np.zeros((0, 8), np.float64)})
        out = stage.transform(request_table([(body, "msgpack")]))
        assert reply_of(out, 0) == []

    def test_prepare_rejects_malformed_and_mismatched(self):
        from mmlspark_tpu.serving.fleet import json_scoring_pipeline
        stage = json_scoring_pipeline(_tpu_model())
        rng = np.random.default_rng(2)
        good = C.encode_columns({"features": rng.normal(size=(3, 8))})[0]
        bad_frame = b"MCOL\x01\xff\xff\xff\xffgarbage"
        wrong_dim = C.encode_columns(
            {"features": rng.normal(size=(2, 5))})[0]
        missing = C.encode_columns({"other": rng.normal(size=(2, 8))})[0]
        prepped = stage.prepare_batch(request_table(
            [(good, "msgpack"), (bad_frame, "msgpack"),
             (wrong_dim, "msgpack"), (missing, "msgpack")]))
        assert set(prepped.rejects) == {"r1", "r2", "r3"}
        assert prepped.payload.shape == (3, 8)
        assert prepped.spans == [(0, 3, "msgpack")]
        # the engine dispatches the FILTERED table; execute must align
        filtered = request_table([(good, "msgpack")])
        out = stage.execute_prepared(filtered, prepped)
        assert len(reply_of(out, 0)) == 3


def _fused_fixture():
    from mmlspark_tpu.core.stage import Pipeline
    from mmlspark_tpu.automl.featurize import Featurize
    from mmlspark_tpu.stages.dataprep import (
        CleanMissingData, StandardScaler,
    )
    from mmlspark_tpu.models.linear import TPULogisticRegression
    rng = np.random.default_rng(0)
    n = 64
    table = DataTable({
        "a": rng.normal(size=n).astype(np.float64),
        "b": np.where(rng.random(n) < 0.2, np.nan, rng.normal(size=n)),
        "cat": [f"l{int(i)}" for i in rng.integers(0, 4, n)],
        "toks": [[f"w{int(t)}" for t in rng.integers(0, 9, 3)]
                 for _ in range(n)],
        "label": rng.integers(0, 2, n).astype(np.float64),
    })
    pm = Pipeline(stages=[
        CleanMissingData(inputCols=["b"], outputCols=["b"]),
        Featurize(featureColumns=["a", "b", "cat", "toks"],
                  numberOfFeatures=16),
        StandardScaler(inputCol="features", outputCol="features"),
        TPULogisticRegression(featuresCol="features", labelCol="label",
                              maxIter=5),
    ]).fit(table)
    return pm, table


ADVERSARIAL_ROWS = [
    {"a": 0.5, "b": None, "cat": "l1", "toks": ["w1", "w2"]},
    {"a": float("nan"), "b": 2.0, "cat": "zzz-unseen", "toks": []},
    {"a": -1.0, "b": float("inf"), "cat": None, "toks": ["𝔘ni", "códe"]},
    {"a": 3, "b": 1, "cat": "l0", "toks": ["w3"]},   # int-typed numerics
]

ADVERSARIAL_COLS = {
    "a": np.array([0.5, np.nan, -1.0, 3.0]),
    "b": np.array([np.nan, 2.0, np.inf, 1.0]),
    "cat": ["l1", "zzz-unseen", None, "l0"],
    "toks": [["w1", "w2"], [], ["𝔘ni", "códe"], ["w3"]],
}


class TestFusedColumnarParity:
    @pytest.fixture(scope="class")
    def fused_stage(self):
        from mmlspark_tpu.serving.fleet import json_scoring_pipeline
        pm, table = _fused_fixture()
        stage = json_scoring_pipeline(pm, batch_size=32)
        stage.warmup(table.drop("label").take(2))
        return stage

    def test_bit_parity_adversarial_rows(self, fused_stage):
        json_out = fused_stage.transform(request_table(
            [(json.dumps(r).encode(), None) for r in ADVERSARIAL_ROWS]))
        json_preds = [reply_of(json_out, i)
                      for i in range(len(ADVERSARIAL_ROWS))]
        for codec in ("msgpack", "arrow"):
            body, _ = C.encode_columns(ADVERSARIAL_COLS, codec=codec)
            out = fused_stage.transform(request_table([(body, codec)]))
            assert reply_of(out, 0) == json_preds, codec

    def test_int_vs_float_dtype_parity(self, fused_stage):
        # i64 columns must score exactly like the f64 encoding of the
        # same values (both cast to f32 at the device boundary)
        base = {"a": np.array([1.0, 2.0]), "b": np.array([0.0, 3.0]),
                "cat": ["l0", "l1"], "toks": [["w1"], ["w2"]]}
        as_int = dict(base, a=np.array([1, 2], np.int64),
                      b=np.array([0, 3], np.int64))
        o1 = fused_stage.transform(request_table(
            [(C.encode_columns(base)[0], "msgpack")]))
        o2 = fused_stage.transform(request_table(
            [(C.encode_columns(as_int)[0], "msgpack")]))
        assert reply_of(o1, 0) == reply_of(o2, 0)

    def test_zero_recompiles_and_one_roundtrip(self, fused_stage):
        scorer = fused_stage.scorer
        body, _ = C.encode_columns(ADVERSARIAL_COLS)
        fused_stage.transform(request_table([(body, "msgpack")]))
        misses0 = scorer.jit_cache_miss_count()
        trips0, batches0 = scorer.device_roundtrips, scorer.batches_scored
        for _ in range(5):
            out = fused_stage.transform(request_table(
                [(body, "msgpack")]))
        assert scorer.jit_cache_miss_count() == misses0, \
            "columnar steady state must not recompile"
        db = scorer.batches_scored - batches0
        assert scorer.device_roundtrips - trips0 <= db
        assert db == 5

    def test_first_bad_request_cannot_reject_batchmates(self, fused_stage):
        """Mismatch-guard reference is the last SUCCESSFUL batch, not
        whichever request decodes first: after any good batch, a
        wrong-shaped request ordered FIRST in a micro-batch rejects
        alone while its well-formed batch-mates score."""
        scorer = fused_stage.scorer
        good_body, _ = C.encode_columns(ADVERSARIAL_COLS)
        fused_stage.transform(request_table([(good_body, "msgpack")]))
        assert scorer._confirmed_shapes   # reference latched
        bad_cols = dict(ADVERSARIAL_COLS,
                        a=np.ones((4, 3)))   # wrong trailing shape
        bad_body, _ = C.encode_columns(bad_cols)
        prepped = fused_stage.prepare_batch(request_table(
            [(bad_body, "msgpack"), (good_body, "msgpack")]))
        assert set(prepped.rejects) == {"r0"}, prepped.rejects
        assert prepped.spans == [(0, 4, "msgpack")]

    def test_staging_buffers_reused(self, fused_stage):
        scorer = fused_stage.scorer
        body, _ = C.encode_columns(ADVERSARIAL_COLS)
        for _ in range(scorer._staging.depth + 2):
            fused_stage.transform(request_table([(body, "msgpack")]))
        stats = scorer._staging.stats()
        assert stats["reuses"] > 0, stats


# ---------------------------------------------------------------------------
# engine-level behaviors over real HTTP
# ---------------------------------------------------------------------------


class TestPoisonedColumnarRequest:
    def test_poisoned_request_400s_alone_in_full_bucket(self):
        from mmlspark_tpu.core.trace import Tracer
        from mmlspark_tpu.serving.fleet import (
            ServingFleet, json_scoring_pipeline,
        )
        model = _tpu_model()
        tracer = Tracer(enabled=True)
        fleet = ServingFleet(json_scoring_pipeline(model), n_engines=1,
                             base_port=19700, batch_size=8, workers=1,
                             max_wait_ms=25.0, tracer=tracer)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(1, 8))
        good, ct = C.encode_columns({"features": x})
        poison = b"MCOL\x01\x10\x00\x00\x00not-a-real-header"
        results = {}

        def post(i, body):
            try:
                results[i] = ("ok", fleet.post(body, timeout=30,
                                               content_type=ct))
            except urllib.error.HTTPError as e:
                results[i] = ("http", e.code, json.loads(e.read()))
            except Exception as e:  # noqa: BLE001
                results[i] = ("err", repr(e))

        try:
            fleet.post(good, content_type=ct)   # warm the live path
            # a full bucket: 7 good + the poison interleaved in the
            # middle, posted concurrently so they share a micro-batch
            threads = []
            for i in range(8):
                body = poison if i == 3 else good
                t = threading.Thread(target=post, args=(i, body))
                threads.append(t)
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert results[3][0] == "http" and results[3][1] == 400, \
                results[3]
            assert "error" in results[3][2]
            for i in range(8):
                if i == 3:
                    continue
                assert results[i][0] == "ok", (i, results[i])
                assert "prediction" in results[i][1]
            # the poisoned request's trace finalized as an ERROR with
            # the codec message; batch-mates' traces are clean
            err_traces = [t for t in tracer.buffer.traces()
                          if t.root.attrs.get("codec_error")]
            assert err_traces, "poisoned trace must be tail-kept"
            assert all(t.root.status == "error" for t in err_traces)
        finally:
            fleet.stop_all()


class TestNegotiationFallback:
    def test_columnar_client_vs_json_only_engine(self):
        from mmlspark_tpu.serving.fleet import ServingFleet
        from mmlspark_tpu.stages.basic import Lambda

        def old_handle(table):   # the pre-columnar protocol, verbatim
            rows = [json.loads(r["entity"].decode())
                    for r in table["request"]]
            return table.with_column(
                "reply", [{"prediction": float(sum(r["features"]))}
                          for r in rows])

        fleet = ServingFleet(Lambda.apply(old_handle), n_engines=1,
                             base_port=19750, batch_size=8, workers=1)
        try:
            x = np.ones((3, 4))
            out = fleet.post_columns({"features": x})
            assert out["prediction"] == [4.0, 4.0, 4.0]
            # verdict remembered: later calls skip the doomed attempt
            assert fleet._columnar_ok is False
            seen0 = fleet.engines[0].source.requests_seen
            out = fleet.post_columns({"features": x})
            assert out["prediction"] == [4.0, 4.0, 4.0]
            # 3 JSON row requests, no wasted columnar POST
            assert fleet.engines[0].source.requests_seen - seen0 == 3
        finally:
            fleet.stop_all()

    def test_json_pin_is_a_cooldown_not_a_life_sentence(self):
        """A transient failure that mimicked a negotiation reject must
        not degrade the client to per-row JSON forever: after the
        cooldown the next call re-probes columnar and un-pins."""
        import time as _time
        from mmlspark_tpu.serving.fleet import (
            ServingFleet, json_scoring_pipeline,
        )
        fleet = ServingFleet(json_scoring_pipeline(_tpu_model()),
                             n_engines=1, base_port=19790,
                             batch_size=8, workers=1)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(2, 8))
        try:
            # simulate a mis-pin (e.g. a transient 500 + JSON success)
            fleet._columnar_ok = False
            fleet._columnar_retry_at = _time.monotonic() + 999
            seen0 = fleet.engines[0].source.requests_seen
            fleet.post_columns({"features": x})
            # pinned: per-row JSON requests, no columnar attempt
            assert fleet.engines[0].source.requests_seen - seen0 == 2
            assert fleet._columnar_ok is False
            # cooldown expired: the next call re-probes and un-pins
            fleet._columnar_retry_at = 0.0
            seen1 = fleet.engines[0].source.requests_seen
            out = fleet.post_columns({"features": x})
            assert len(out["prediction"]) == 2
            assert fleet.engines[0].source.requests_seen - seen1 == 1
            assert fleet._columnar_ok is True
        finally:
            fleet.stop_all()

    def test_both_directions_on_columnar_engine(self):
        from mmlspark_tpu.serving.fleet import (
            ServingFleet, json_scoring_pipeline,
        )
        model = _tpu_model()
        fleet = ServingFleet(json_scoring_pipeline(model), n_engines=1,
                             base_port=19780, batch_size=8, workers=1)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(3, 8))
        try:
            # direction 1: columnar client -> columnar engine fast path
            out = fleet.post_columns({"features": x})
            assert len(out["prediction"]) == 3
            assert fleet._columnar_ok is True
            # direction 2: a plain JSON client keeps working unchanged
            body = fleet.post({"features": list(map(float, x[0]))})
            assert body["prediction"] == out["prediction"][0]
        finally:
            fleet.stop_all()


class TestColumnarSwapDiscipline:
    def test_swap_under_columnar_load_zero_recompiles(self):
        """A lifecycle swap on the columnar path: warmup compiles every
        bucket off the hot path, steady-state columnar traffic through
        the swap triggers ZERO recompiles on either version, and the
        one-roundtrip-per-batch contract holds throughout."""
        from mmlspark_tpu.serving.fleet import (
            ServingFleet, json_scoring_pipeline,
        )
        from mmlspark_tpu.serving.lifecycle import CanaryPolicy
        pm, table = _fused_fixture()
        stage_v1 = json_scoring_pipeline(pm, batch_size=32)
        scorer_v1 = stage_v1.scorer
        fleet = ServingFleet(stage_v1, n_engines=1, base_port=19800,
                             batch_size=32, workers=1, version="v1")
        engine = fleet.engines[0]
        warm_example = table.drop("label").take(2)
        body, ct = C.encode_columns(ADVERSARIAL_COLS)
        try:
            stage_v1.warmup(warm_example)
            ref = fleet.post(body, content_type=ct)["prediction"]
            misses_v1 = scorer_v1.jit_cache_miss_count()

            stage_v2 = json_scoring_pipeline(
                _fused_fixture()[0], batch_size=32)
            scorer_v2 = stage_v2.scorer
            stop = threading.Event()
            errors = []

            def load():
                while not stop.is_set():
                    try:
                        out = fleet.post(body, timeout=30,
                                         content_type=ct)
                        assert len(out["prediction"]) == 4
                    except Exception as e:  # noqa: BLE001
                        errors.append(repr(e))

            t = threading.Thread(target=load)
            t.start()
            try:
                res = engine.swap(
                    stage_v2, "v2", warmup_example=warm_example,
                    policy=CanaryPolicy(fraction=0.5, min_batches=2,
                                        decision_timeout_s=30))
            finally:
                stop.set()
                t.join(timeout=30)
            assert res.completed, res.reason
            misses_v2 = scorer_v2.jit_cache_miss_count()
            # steady state AFTER the swap: both counters flat
            for _ in range(4):
                out = fleet.post(body, content_type=ct)
                assert out["prediction"] == ref or \
                    len(out["prediction"]) == 4
            assert scorer_v1.jit_cache_miss_count() == misses_v1
            assert scorer_v2.jit_cache_miss_count() == misses_v2, \
                "post-swap columnar traffic must not recompile"
            assert not errors, errors[:3]
            for s in (scorer_v1, scorer_v2):
                assert s.device_roundtrips <= s.batches_scored
        finally:
            fleet.stop_all()


# ---------------------------------------------------------------------------
# the ingress static checker
# ---------------------------------------------------------------------------


class TestIngressChecker:
    def _tools(self):
        import importlib
        import os
        import sys as _sys
        sys_path = os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools")
        if sys_path not in _sys.path:
            _sys.path.insert(0, sys_path)
        return importlib.import_module("check_fusion_kernels")

    def test_shipped_ingress_kernels_clean(self):
        chk = self._tools()
        assert C.INGRESS_REGISTRY, "decode kernels must be registered"
        violations = chk.check_ingress_kernels()
        assert violations == [], violations

    def test_checker_catches_per_row_iteration(self):
        chk = self._tools()

        def bad_decode(body):
            out = []
            for i in range(len(body)):
                out.append(float(body[i]))
            return out

        C.register_ingress_kernel(bad_decode, "test.bad_decode")
        try:
            violations = chk.check_ingress_kernels()
            assert any("test.bad_decode" in v and "iteration" in v
                       for v in violations), violations
        finally:
            C.INGRESS_REGISTRY.pop(bad_decode.__code__, None)

    def test_checker_catches_boxing_and_honors_whitelist(self):
        chk = self._tools()

        def boxy(arr):
            return arr.tolist()

        def ok_loop(cols):
            out = {}
            for name in cols:  # ingress:row-ok — per-column
                out[name] = cols[name]
            return out

        C.register_ingress_kernel(boxy, "test.boxy")
        C.register_ingress_kernel(ok_loop, "test.ok_loop")
        try:
            violations = chk.check_ingress_kernels()
            assert any("test.boxy" in v and "boxing" in v
                       for v in violations), violations
            assert not any("test.ok_loop" in v for v in violations), \
                violations
        finally:
            C.INGRESS_REGISTRY.pop(boxy.__code__, None)
            C.INGRESS_REGISTRY.pop(ok_loop.__code__, None)


# ---------------------------------------------------------------------------
# the throughput floor (slow: wall-clock on a contended host)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestColumnarIngressFloor:
    def test_columnar_at_least_2x_json_rows_per_s(self):
        """The acceptance floor: single-replica rows/sec >= 2x the JSON
        oracle on the same engine, host ingress phases < 20% of request
        p50, zero steady-state recompiles (BENCH_r11 measures ~60x on
        this container; 2x is the pinned floor)."""
        import concurrent.futures
        from mmlspark_tpu.core.metrics import (
            ingress_decode_histograms, ingress_histograms,
        )
        from mmlspark_tpu.serving.fleet import (
            ServingFleet, json_scoring_pipeline,
        )
        model = _tpu_model(dim=64, classes=8)
        model.warmup({"features": np.zeros((1, 64), np.float32)})
        fleet = ServingFleet(json_scoring_pipeline(model), n_engines=1,
                             base_port=19850, batch_size=32, workers=2)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(32, 64))
        json_body = json.dumps(
            {"features": list(map(float, x[0]))}).encode()
        col_body, ct = C.encode_columns({"features": x})

        def spray(body, content_type, n, rows_per_req):
            lat = []

            def post(_):
                t0 = __import__("time").perf_counter()
                out = fleet.post(body, timeout=30,
                                 content_type=content_type)
                assert "prediction" in out
                return (__import__("time").perf_counter() - t0) * 1e3
            post(0)
            t0 = __import__("time").perf_counter()
            with concurrent.futures.ThreadPoolExecutor(8) as ex:
                for r in ex.map(post, range(n)):
                    lat.append(r)
            wall = __import__("time").perf_counter() - t0
            return (n * rows_per_req / wall,
                    float(np.percentile(lat, 50)))

        try:
            json_rps, _ = spray(json_body, "application/json", 160, 1)
            misses0 = model.jit_cache_misses
            # process-wide histograms: reset so the host-fraction is
            # measured on the columnar workload alone
            for h in ingress_histograms().values():
                h.reset()
            for h in ingress_decode_histograms().values():
                h.reset()
            model._hists["pad_ms"].reset()
            col_rps, col_p50 = spray(col_body, ct, 80, 32)
            assert model.jit_cache_misses == misses0
            ratio = col_rps / json_rps
            assert ratio >= 2.0, \
                f"columnar {col_rps:.0f} rows/s vs JSON " \
                f"{json_rps:.0f} rows/s = {ratio:.2f}x < 2x floor"
            ih = ingress_histograms()
            decode = ingress_decode_histograms().get("msgpack")
            host_ms = (ih["negotiate"].summary().get("p50", 0.0)
                       + ih["assemble"].summary().get("p50", 0.0)
                       + (decode.summary().get("p50", 0.0)
                          if decode else 0.0))
            stage = fleet.metrics()["aggregate"].get(
                "pipeline_stage", {})
            host_ms += stage.get("pad_ms", {}).get("p50", 0.0) or 0.0
            assert host_ms < 0.2 * col_p50, \
                f"host phases {host_ms:.3f}ms vs p50 {col_p50:.2f}ms"
        finally:
            fleet.stop_all()
