import numpy as np
import pytest

from mmlspark_tpu.core.params import (
    ArrayParam, FloatParam, HasInputCol, HasOutputCol, PyTreeParam, StageParam,
    TableParam, UDFParam,
)
from mmlspark_tpu.core.stage import (
    Pipeline, PipelineModel, PipelineStage, Transformer, load_stage,
)
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.testing.datagen import make_basic_table
from mmlspark_tpu.testing.equality import assert_table_equal


class WeightsHolder(Transformer):
    weights = PyTreeParam("model weights", default=None)
    scale = FloatParam("scale", default=1.0)

    def transform(self, table):
        return table


class ArrayHolder(Transformer):
    arr = ArrayParam("an array", default=None)

    def transform(self, table):
        return table


class TableHolder(Transformer):
    ref_table = TableParam("a table", default=None)

    def transform(self, table):
        return table


class StageHolder(Transformer):
    inner = StageParam("inner stage", default=None)

    def transform(self, table):
        return self.get("inner").transform(table)


def _global_udf(x):
    return x * 2


class UdfHolder(Transformer):
    fn = UDFParam("a function", default=None)

    def transform(self, table):
        return table


def test_simple_roundtrip(tmp_path):
    s = WeightsHolder(scale=2.5)
    p = str(tmp_path / "s")
    s.save(p)
    s2 = load_stage(p)
    assert type(s2) is WeightsHolder
    assert s2.get("scale") == 2.5
    assert s2.uid == s.uid


def test_pytree_roundtrip(tmp_path):
    tree = {"dense": {"kernel": np.ones((3, 4)), "bias": np.zeros(4)},
            "layers": [np.arange(3.0), np.arange(2.0)]}
    s = WeightsHolder(weights=tree)
    p = str(tmp_path / "w")
    s.save(p)
    s2 = load_stage(p)
    w = s2.get("weights")
    np.testing.assert_array_equal(w["dense"]["kernel"], tree["dense"]["kernel"])
    np.testing.assert_array_equal(w["layers"][1], tree["layers"][1])


def test_ndarray_roundtrip(tmp_path):
    arr = np.random.default_rng(0).normal(size=(5, 7)).astype(np.float32)
    s = ArrayHolder(arr=arr)
    p = str(tmp_path / "a")
    s.save(p)
    s2 = load_stage(p)
    np.testing.assert_array_equal(s2.get("arr"), arr)
    assert s2.get("arr").dtype == np.float32


def test_table_param_roundtrip(tmp_path):
    t = make_basic_table()
    s = TableHolder(ref_table=t)
    p = str(tmp_path / "t")
    s.save(p)
    s2 = load_stage(p)
    assert_table_equal(s2.get("ref_table"), t)


def test_nested_stage_roundtrip(tmp_path):
    inner = WeightsHolder(scale=7.0)
    s = StageHolder(inner=inner)
    p = str(tmp_path / "n")
    s.save(p)
    s2 = load_stage(p)
    assert s2.get("inner").get("scale") == 7.0


def test_udf_roundtrip(tmp_path):
    s = UdfHolder(fn=_global_udf)
    p = str(tmp_path / "u")
    s.save(p)
    s2 = load_stage(p)
    assert s2.get("fn")(21) == 42


def test_pipeline_roundtrip(tmp_path):
    from tests.test_params_stage import AddConstant, MeanShift
    t = make_basic_table()
    pipe = Pipeline([
        AddConstant(inputCol="numbers", outputCol="plus", amount=5.0),
        MeanShift(inputCol="plus", outputCol="centered"),
    ])
    pm = pipe.fit(t)
    out1 = pm.transform(t)

    pipe_path = str(tmp_path / "pipe")
    pipe.save(pipe_path)
    pipe2 = load_stage(pipe_path)
    out2 = pipe2.fit(t).transform(t)
    assert_table_equal(out1, out2)

    pm_path = str(tmp_path / "pm")
    pm.save(pm_path)
    pm2 = load_stage(pm_path)
    out3 = pm2.transform(t)
    assert_table_equal(out1, out3)


def test_overwrite_behavior(tmp_path):
    s = WeightsHolder(scale=1.0)
    p = str(tmp_path / "x")
    s.save(p)
    s.save(p)  # overwrite ok by default
    with pytest.raises(FileExistsError):
        s.save(p, overwrite=False)
