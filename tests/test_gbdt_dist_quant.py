"""Comm-efficient quantized-histogram distributed GBDT (PR 19).

Pins the three contracts the quantized engine ships with:

* **Quantization accuracy** — hist_bits=16 holdout AUC within 0.005 of
  the f32 engine on the HIGGS shape (28 dense features), and the f32
  default is untouched (hist_bits=32 is bit-identical to leaving the
  knob off).
* **Reduce-scatter split search** — ``hist_comm='reduce_scatter'``
  grows the SAME forest as the psum oracle, bitwise, for both f32 and
  quantized histograms (integer accumulation makes the quantized pin
  exact on any device count; the f32 pin holds because per-cell
  reduction order is the only difference and XLA's ring keeps f32
  addition commutative per element).
* **Wire accounting** — the ring comm model halves (better) modeled
  bytes at hist_bits=16, the counters flow through the Prometheus
  exposition with bounded labels, and the fusion-kernel checker audits
  the quantized histogram kernels under the no-silent-f64-upcast rule.
"""
import os
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp                                   # noqa: E402

from mmlspark_tpu.core import metrics as MC               # noqa: E402
from mmlspark_tpu.core.table import DataTable             # noqa: E402
from mmlspark_tpu.gbdt.booster import (                   # noqa: E402
    comm_payload_model, resolve_hist_method, train,
)
from mmlspark_tpu.parallel import mesh as mesh_lib        # noqa: E402


def _auc(y, p):
    """Rank AUC by hand (no sklearn dependency on the hot path)."""
    order = np.argsort(p, kind="stable")
    ranks = np.empty(len(p))
    ranks[order] = np.arange(1, len(p) + 1)
    n_pos = int((y == 1).sum())
    n_neg = len(y) - n_pos
    return (ranks[y == 1].sum() - n_pos * (n_pos + 1) / 2) / (
        n_pos * n_neg)


def _higgs_shape(n=6000, seed=7):
    """HIGGS-shaped synthetic binary task: 28 dense f32 features,
    nonlinear boundary, label noise."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 28)).astype(np.float32)
    logit = (X[:, 0] + 0.6 * X[:, 1] * X[:, 2]
             + 0.4 * np.sin(2 * X[:, 3]) - 0.3 * X[:, 4] ** 2 + 0.3)
    y = (logit + rng.normal(scale=0.5, size=n) > 0).astype(np.float32)
    return X, y


_KW = {"objective": "binary", "num_iterations": 6, "num_leaves": 15,
       "max_bin": 63, "min_data_in_leaf": 5}

_FOREST_KEYS = ("feature", "bin_threshold", "left", "right",
                "value", "count")


def _assert_forests_identical(a, b):
    for k in _FOREST_KEYS:
        np.testing.assert_array_equal(a.trees[k], b.trees[k], err_msg=k)


@pytest.fixture(scope="module")
def higgs_split():
    X, y = _higgs_shape()
    cut = 4096
    return X[:cut], y[:cut], X[cut:], y[cut:]


@pytest.fixture(scope="module")
def dist_forests(higgs_split, cpu_mesh_devices):
    """One training sweep shared by every pin below: serial/sharded x
    f32/q16 x psum/reduce_scatter on the same HIGGS-shaped data."""
    Xtr, ytr, _, _ = higgs_split
    mesh = mesh_lib.make_mesh()
    dp = {**_KW, "parallelism": "data"}
    return {
        "serial_f32": train(_KW, Xtr, ytr),
        "serial_q16": train({**_KW, "hist_bits": 16}, Xtr, ytr),
        "psum_f32": train({**dp, "hist_comm": "psum"}, Xtr, ytr,
                          mesh=mesh),
        "rs_f32": train({**dp, "hist_comm": "reduce_scatter"}, Xtr, ytr,
                        mesh=mesh),
        "psum_q16": train({**dp, "hist_bits": 16, "hist_comm": "psum"},
                          Xtr, ytr, mesh=mesh),
        "rs_q16": train({**dp, "hist_bits": 16,
                         "hist_comm": "reduce_scatter"}, Xtr, ytr,
                        mesh=mesh),
    }


class TestQuantizedAccuracy:
    def test_q16_auc_within_0005_of_f32(self, higgs_split, dist_forests):
        _, _, Xte, yte = higgs_split
        auc32 = _auc(yte, dist_forests["serial_f32"].predict(Xte))
        auc16 = _auc(yte, dist_forests["serial_q16"].predict(Xte))
        assert auc32 > 0.80, "f32 baseline failed to learn"
        assert abs(auc32 - auc16) < 0.005, (auc32, auc16)

    def test_f32_default_bit_identical_to_explicit_32(self, higgs_split,
                                                      dist_forests):
        # the unquantized engine must be byte-for-byte untouched:
        # hist_bits=32 (explicit) == knob absent (default)
        Xtr, ytr, _, _ = higgs_split
        b32 = train({**_KW, "hist_bits": 32}, Xtr, ytr)
        _assert_forests_identical(dist_forests["serial_f32"], b32)

    def test_q8_learns(self, higgs_split):
        Xtr, ytr, Xte, yte = higgs_split
        b8 = train({**_KW, "hist_bits": 8}, Xtr, ytr)
        # 8-bit rounding noise costs real AUC at 6 trees — the pinned
        # 0.005 accuracy contract is 16-bit only; 8-bit just has to
        # keep learning the signal
        assert _auc(yte, b8.predict(Xte)) > 0.70

    def test_q16_sharded_matches_serial(self, dist_forests):
        # stochastic rounding is keyed on GLOBAL row ids
        # (row0 = axis_index * shard_rows), so the integer histograms —
        # hence split structure and counts — are shard-invariant
        # bitwise; leaf values go through the quantization scale
        # delta = sum(|g|)/Q whose f32 sum is reassociated by the psum,
        # so values match to a couple of ULPs, not bitwise
        ser, dp = dist_forests["serial_q16"], dist_forests["psum_q16"]
        for k in ("feature", "bin_threshold", "left", "right", "count"):
            np.testing.assert_array_equal(ser.trees[k], dp.trees[k],
                                          err_msg=k)
        np.testing.assert_allclose(ser.trees["value"], dp.trees["value"],
                                   rtol=1e-5, atol=1e-7)


class TestReduceScatter:
    def test_f32_rs_matches_psum_oracle(self, dist_forests):
        _assert_forests_identical(dist_forests["psum_f32"],
                                  dist_forests["rs_f32"])

    def test_q16_rs_matches_psum_oracle(self, dist_forests):
        _assert_forests_identical(dist_forests["psum_q16"],
                                  dist_forests["rs_q16"])

    def test_q16_rs_reproducible(self, higgs_split, dist_forests,
                                 cpu_mesh_devices):
        Xtr, ytr, _, _ = higgs_split
        again = train({**_KW, "parallelism": "data", "hist_bits": 16,
                       "hist_comm": "reduce_scatter"}, Xtr, ytr,
                      mesh=mesh_lib.make_mesh())
        _assert_forests_identical(dist_forests["rs_q16"], again)

    def test_auto_comm_resolution(self, dist_forests):
        # auto -> reduce_scatter ONLY for quantized data-parallel
        assert dist_forests["serial_q16"].params["hist_comm"] == "psum"
        assert dist_forests["psum_f32"].params["hist_comm"] == "psum"

    def test_voting_composes_with_quantized_wire(self, cpu_mesh_devices):
        # PV-tree voting with k >= F sees every feature's candidate
        # slice; the voted slices ride the same int16 wire, so the
        # voted forest's STRUCTURE matches data-parallel bitwise.
        # Leaf values keep voting's standing contract (equal up to f32
        # reassociation between the sliced and full gain programs)
        rng = np.random.default_rng(3)
        X = rng.normal(size=(4096, 10)).astype(np.float32)
        y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0).astype(np.float32)
        mesh = mesh_lib.make_mesh()
        kw = {"objective": "binary", "num_iterations": 4,
              "num_leaves": 4, "max_bin": 31, "hist_bits": 16}
        bd = train({**kw, "parallelism": "data", "hist_comm": "psum"},
                   X, y, mesh=mesh)
        bv = train({**kw, "parallelism": "voting", "top_k": 10},
                   X, y, mesh=mesh)
        for k in ("feature", "bin_threshold", "left", "right", "count"):
            np.testing.assert_array_equal(bd.trees[k], bv.trees[k],
                                          err_msg=k)
        np.testing.assert_allclose(bd.trees["value"], bv.trees["value"],
                                   rtol=1e-4, atol=1e-6)


class TestHistKnobValidation:
    def test_auto_routes_pallas_only_on_tpu(self):
        assert resolve_hist_method("auto", "tpu", 255) == "pallas"
        assert resolve_hist_method("auto", "axon", 255) == "pallas"
        assert resolve_hist_method("auto", "cpu", 255) == "scatter"
        assert resolve_hist_method("auto", "gpu", 255) == "scatter"
        # explicit requests are honored (pallas runs interpret off-TPU)
        assert resolve_hist_method("scatter", "tpu", 255) == "scatter"
        assert resolve_hist_method("pallas", "cpu", 255) == "pallas"

    def test_pallas_beyond_vmem_tiling_degrades_to_onehot(self):
        assert resolve_hist_method("pallas", "tpu", 4095) == "onehot"

    def test_unsupported_hist_bits_fails_actionably(self):
        X = np.zeros((64, 2), np.float32)
        y = np.zeros(64, np.float32)
        with pytest.raises(ValueError, match="hist_bits=12"):
            train({"objective": "regression", "hist_bits": 12}, X, y)

    def test_quantized_onehot_fails_actionably(self):
        X = np.zeros((64, 2), np.float32)
        y = np.zeros(64, np.float32)
        with pytest.raises(ValueError, match="onehot"):
            train({"objective": "regression", "hist_bits": 16,
                   "hist_method": "onehot"}, X, y)

    def test_quantized_feature_parallel_fails(self, cpu_mesh_devices):
        X = np.zeros((64, 2), np.float32)
        y = np.zeros(64, np.float32)
        with pytest.raises(ValueError, match="feature"):
            train({"objective": "regression", "hist_bits": 16,
                   "parallelism": "feature"}, X, y,
                  mesh=mesh_lib.make_mesh())

    def test_reduce_scatter_needs_data_parallel(self, cpu_mesh_devices):
        X = np.zeros((64, 2), np.float32)
        y = np.zeros(64, np.float32)
        with pytest.raises(ValueError, match="reduce_scatter"):
            train({"objective": "regression",
                   "hist_comm": "reduce_scatter",
                   "parallelism": "voting"}, X, y,
                  mesh=mesh_lib.make_mesh())

    def test_estimator_plumbs_hist_knobs(self):
        from mmlspark_tpu.gbdt.estimators import TPUBoostClassifier
        rng = np.random.default_rng(0)
        X = rng.normal(size=(256, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float64)
        t = DataTable({"features": X, "label": y})
        clf = TPUBoostClassifier(numIterations=3, histBits=16,
                                 histComm="auto")
        model = clf.fit(t)
        assert model._booster.params["hist_bits"] == 16
        # serial fit: auto must stay psum
        assert model._booster.params["hist_comm"] == "psum"


class TestCommModel:
    def test_quantized_wire_halves_psum_bytes(self):
        a32 = comm_payload_model("data", "psum", 32, 10, 31, 28, 255,
                                 4, 20, 10000)
        a16 = comm_payload_model("data", "psum", 16, 10, 31, 28, 255,
                                 4, 20, 10000)
        assert a32["psum"] == pytest.approx(2 * (
            a16["psum"] - 10 * 2 * 12 * 3 / 4))   # minus scale psums

    def test_reduce_scatter_divides_wire_by_device_count(self):
        # the histogram tensor crosses the wire once (S*(D-1)/D) vs the
        # allreduce's 2*S*(D-1)/D, and only owned features ship onward
        # f32 pair: no per-tree scale psums, so the identity is exact
        # (F=32 divides D=4 -> no feature padding)
        ps = comm_payload_model("data", "psum", 32, 10, 31, 32, 255,
                                4, 20, 10000)
        rs = comm_payload_model("data", "reduce_scatter", 32, 10, 31,
                                32, 255, 4, 20, 10000)
        assert rs["psum_scatter"] == pytest.approx(ps["psum"] / 2)
        ps16 = comm_payload_model("data", "psum", 16, 10, 31, 32, 255,
                                  4, 20, 10000)
        rs16 = comm_payload_model("data", "reduce_scatter", 16, 10, 31,
                                  32, 255, 4, 20, 10000)
        # slightly under 2x at the same bit width: the (3, B) leaf-total
        # psum rides along so the split table keeps psum's association
        assert sum(rs16.values()) < sum(ps16.values()) / 1.8

    def test_q16_total_at_least_2x_under_f32(self):
        f32 = sum(comm_payload_model("data", "psum", 32, 10, 31, 28,
                                     255, 4, 20, 10000).values())
        q16 = sum(comm_payload_model("data", "reduce_scatter", 16, 10,
                                     31, 28, 255, 4, 20, 10000).values())
        assert f32 / q16 >= 2.0

    def test_single_device_models_zero(self):
        z = comm_payload_model("data", "psum", 16, 10, 31, 28, 255,
                               1, 20, 10000)
        assert sum(z.values()) == 0

    def test_unknown_collective_rejected(self):
        with pytest.raises(ValueError, match="all_reduce"):
            MC.gbdt_comm_add("all_reduce", 1.0)

    def test_train_records_comm_bytes(self, dist_forests):
        info = dist_forests["rs_q16"].train_info
        assert info["comm_bytes"]["psum_scatter"] > 0
        assert info["comm_bytes"]["all_gather"] > 0
        assert "comm_bytes" not in dist_forests["serial_f32"].train_info

    def test_exposition_carries_new_families(self, dist_forests):
        from mmlspark_tpu.core.prometheus import (PromRenderer,
                                                  process_families)
        assert sum(MC.gbdt_comm_counters().values()) > 0, \
            "dist_forests fixture should have recorded comm bytes"
        MC.gbdt_hist_histograms()["build"].observe(1.25)
        r = PromRenderer()
        process_families(r)
        text = r.render()
        assert 'gbdt_comm_bytes_total{collective="psum_scatter"}' in text
        assert 'gbdt_hist_phase_ms_bucket{phase="build"' in text
        assert "# HELP gbdt_comm_bytes_total" in text


def _bad_quant_kernel(hist):
    # deliberately violates the no-silent-f64-upcast rule
    return hist.astype(jnp.float64).cumsum(axis=-1)


class TestQuantHistCheckerRules:
    @pytest.fixture(autouse=True)
    def _tools_path(self):
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        yield
        sys.path.pop(0)

    def test_quanthist_names_get_f64_rule(self):
        import check_fusion_kernels as chk
        assert chk.is_quantized_kernel("gbdt.quanthist.build_histogram")
        assert chk.is_quantized_kernel("gbdt.quanthist.hist_kernel")
        assert not chk.is_quantized_kernel("gbdt.tree.predict_trees")

    def test_quanthist_kernels_registered_and_clean(self):
        import check_fusion_kernels as chk
        from mmlspark_tpu.core.fusion import KERNEL_REGISTRY
        chk.register_known_callees()
        names = set(KERNEL_REGISTRY.values())
        for want in ("gbdt.quanthist.build_histogram",
                     "gbdt.quanthist.hist_scatter",
                     "gbdt.quanthist.stats_block",
                     "gbdt.quanthist.hist_kernel",
                     "gbdt.quanthist.hist_kernel_nibble"):
            assert want in names, f"{want} not in kernel audit"
        import inspect
        import textwrap
        for code, name in list(KERNEL_REGISTRY.items()):
            if not name.startswith("gbdt.quanthist."):
                continue
            lines, first = inspect.getsourcelines(code)
            src = textwrap.dedent("".join(lines))
            assert chk._check_source(name, src, first, lines) == []

    def test_checker_catches_f64_upcast_in_quant_kernel(self):
        import inspect
        import textwrap
        import check_fusion_kernels as chk
        lines, first = inspect.getsourcelines(_bad_quant_kernel)
        src = textwrap.dedent("".join(lines))
        bad = chk._check_source("gbdt.quanthist.bad", src, first, lines)
        assert any("float64" in v for v in bad), bad
        # same source under a NON-quantized name passes the f64 rule
        ok = chk._check_source("gbdt.other.bad", src, first, lines)
        assert not any("float64" in v for v in ok)
