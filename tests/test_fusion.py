"""Whole-pipeline fusion (core/fusion.py): parity, liveness pruning,
DeviceTable invalidation, serving integration, and the static
no-host-round-trip kernel check.

Parity contract under test (see docs/pipeline_fusion.md):

- fused vs ``transform_staged`` (the same device kernels dispatched one
  stage at a time with host round trips): BIT-IDENTICAL — XLA
  elementwise ops and identically shaped dots are deterministic, so
  fusing them into one program must not change a single bit;
- fused vs the legacy host path (``PipelineModel.transform``): stages
  whose math is exact in f32 (featurize's selects/compares/counts, the
  scaler's elementwise standardize) are bit-identical too; matmul-
  bearing model stages agree exactly on predictions and to f32
  rounding on probabilities (the host path computes in numpy f64).
"""

import json
import urllib.request

import numpy as np
import pytest

from mmlspark_tpu.core import fusion as FZ
from mmlspark_tpu.core.stage import Pipeline, PipelineModel, Transformer
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.automl.featurize import Featurize
from mmlspark_tpu.models.linear import (
    TPULinearRegression, TPULogisticRegression,
)
from mmlspark_tpu.models.tpu_model import TPUModel
from mmlspark_tpu.stages.basic import DropColumns, Lambda, SelectColumns
from mmlspark_tpu.stages.dataprep import (
    CleanMissingData, FastVectorAssembler, StandardScaler, ValueIndexer,
)


def _raw_table(n=300, seed=0, unseen=False):
    """Raw-rows table: numerics (one with NaN/inf), 12-level string,
    token lists, int column — the serving-shaped input mix."""
    rng = np.random.default_rng(seed)
    num2 = rng.normal(size=n)
    num2[rng.random(n) < 0.15] = np.nan
    num2[rng.random(n) < 0.03] = np.inf
    cats = [f"lvl{int(i)}" for i in rng.integers(0, 12, n)]
    if unseen:
        cats[0] = "NEVER_SEEN"
        cats[1] = None
    return DataTable({
        "num1": rng.normal(size=n),
        "num2": num2,
        "icol": rng.integers(-5, 5, n),
        "cat": cats,
        "toks": [[f"w{int(t)}" for t in rng.integers(0, 40, 5)]
                 for _ in range(n)],
        "label": (rng.random(n) > 0.5).astype(float),
    })


FEATURE_COLS = ["num1", "num2", "icol", "cat", "toks"]


def _fit_logistic_pipeline(table, one_hot=False):
    return Pipeline(stages=[
        Featurize(featureColumns=FEATURE_COLS, numberOfFeatures=32,
                  oneHotEncodeCategoricals=one_hot),
        StandardScaler(inputCol="features", outputCol="features"),
        TPULogisticRegression(featuresCol="features", labelCol="label",
                              maxIter=25),
    ]).fit(table)


def _assert_tables_equal(a: DataTable, b: DataTable, cols=None,
                         exact=True):
    cols = cols or a.column_names
    for c in cols:
        x, y = np.asarray(a[c]), np.asarray(b[c])
        assert x.dtype == y.dtype, f"{c}: {x.dtype} != {y.dtype}"
        if exact:
            assert np.array_equal(x, y, equal_nan=True), \
                f"column {c} differs (max|d|=" \
                f"{np.nanmax(np.abs(x - y)) if x.size else 0})"
        else:
            assert np.allclose(x, y, rtol=1e-5, atol=1e-6,
                               equal_nan=True), f"column {c} differs"


# ---------------------------------------------------------------------------
# fused vs staged vs legacy parity
# ---------------------------------------------------------------------------


class TestFusedParity:
    def test_featurize_fused_bit_identical_to_host(self):
        """Featurize's kernels are exact in f32: the fused program must
        reproduce the host columnar path BIT-IDENTICALLY — NaN/inf
        imputation, unseen + None levels, int/float dtypes."""
        table = _raw_table(seed=1)
        fm = Featurize(featureColumns=FEATURE_COLS,
                       numberOfFeatures=32).fit(table)
        pm = PipelineModel(stages=[fm])
        fused = pm.fused()
        scoring = _raw_table(n=150, seed=2, unseen=True)
        host = pm.transform(scoring)
        dev = fused.transform(scoring)
        _assert_tables_equal(host, dev, cols=["features"])

    def test_featurize_onehot_fused_bit_identical(self):
        table = _raw_table(seed=3)
        fm = Featurize(featureColumns=FEATURE_COLS, numberOfFeatures=16,
                       oneHotEncodeCategoricals=True).fit(table)
        pm = PipelineModel(stages=[fm])
        scoring = _raw_table(n=100, seed=4, unseen=True)
        _assert_tables_equal(pm.transform(scoring),
                             pm.fused().transform(scoring),
                             cols=["features"])

    def test_logistic_pipeline_fused_vs_staged_bit_identical(self):
        """The acceptance invariant: one fused XLA program ==
        stage-at-a-time device dispatch, bit for bit, across NaN/inf
        rows, unseen levels, and mixed dtypes."""
        table = _raw_table(seed=5)
        pm = _fit_logistic_pipeline(table)
        fused = pm.fused()
        scoring = _raw_table(n=200, seed=6, unseen=True)
        out_f = fused.transform(scoring)
        out_s = fused.transform_staged(scoring)
        _assert_tables_equal(
            out_f, out_s,
            cols=["features", "rawPrediction", "probability",
                  "prediction"])

    def test_logistic_pipeline_fused_vs_legacy(self):
        """vs the legacy f64 host path: features bit-identical,
        predictions exact, probabilities to f32 rounding."""
        table = _raw_table(seed=7)
        pm = _fit_logistic_pipeline(table)
        scoring = _raw_table(n=200, seed=8, unseen=True)
        legacy = pm.transform(scoring)
        out = pm.fused().transform(scoring)
        _assert_tables_equal(legacy, out, cols=["features"])
        assert np.array_equal(np.asarray(legacy["prediction"]),
                              np.asarray(out["prediction"]))
        assert np.allclose(np.asarray(legacy["probability"]),
                           np.asarray(out["probability"]), atol=1e-5)
        # schema/dtype parity with the host path
        assert out.schema["prediction"].tag == \
            legacy.schema["prediction"].tag
        assert np.asarray(out["probability"]).dtype == np.float64

    def test_linear_regression_pipeline(self):
        table = _raw_table(seed=9)
        pm = Pipeline(stages=[
            Featurize(featureColumns=["num1", "num2", "icol"],
                      numberOfFeatures=8),
            TPULinearRegression(featuresCol="features",
                                labelCol="label", maxIter=25),
        ]).fit(table)
        fused = pm.fused()
        scoring = _raw_table(n=120, seed=10)
        out_f = fused.transform(scoring)
        _assert_tables_equal(out_f, fused.transform_staged(scoring),
                             cols=["features", "prediction"])
        legacy = pm.transform(scoring)
        assert np.allclose(np.asarray(legacy["prediction"]),
                           np.asarray(out_f["prediction"]), atol=1e-4)

    def test_gbdt_pipeline_fused_forest_traversal(self):
        from mmlspark_tpu.gbdt.estimators import TPUBoostClassifier
        table = _raw_table(seed=11)
        pm = Pipeline(stages=[
            Featurize(featureColumns=["num1", "num2", "icol"],
                      numberOfFeatures=8),
            TPUBoostClassifier(featuresCol="features", labelCol="label",
                               numIterations=8, numLeaves=7,
                               minDataInLeaf=4),
        ]).fit(table)
        fused = pm.fused()
        scoring = _raw_table(n=150, seed=12)
        plan = fused.plan_for(scoring.schema)
        assert len(plan.segments) == 1, plan.describe()
        out_f = fused.transform(scoring)
        _assert_tables_equal(
            out_f, fused.transform_staged(scoring),
            cols=["rawPrediction", "probability", "prediction"])
        legacy = pm.transform(scoring)
        assert np.array_equal(np.asarray(legacy["prediction"]),
                              np.asarray(out_f["prediction"]))
        assert np.allclose(np.asarray(legacy["probability"]),
                           np.asarray(out_f["probability"]), atol=1e-5)

    def test_value_indexer_assembler_tpu_model_segment(self):
        """ValueIndexer (host Feed) -> assembler -> TPUModel forward in
        ONE segment; mixed host/device pipeline with a trailing host
        stage still works."""
        table = _raw_table(seed=13)
        vi = ValueIndexer(inputCol="cat", outputCol="cat_ix").fit(table)
        asm = FastVectorAssembler(inputCols=["num1", "cat_ix"],
                                  outputCol="fv")
        W = np.asarray([[1.0, -1.0], [0.5, 0.25]], np.float32)
        tm = TPUModel.from_fn(
            lambda w, ins: list(ins.values())[0] @ w["W"],
            {"W": W}, inputCol="fv", outputCol="scores")
        pm = PipelineModel(stages=[vi, asm, tm])
        fused = pm.fused()
        plan = fused.plan_for(table.schema)
        assert len(plan.segments) == 1, plan.describe()
        out_f = fused.transform(table)
        legacy = pm.transform(table)
        assert np.allclose(np.asarray(legacy["scores"]),
                           np.asarray(out_f["scores"]), atol=1e-5)
        _assert_tables_equal(out_f, fused.transform_staged(table),
                             cols=["cat_ix", "fv", "scores"])

    def test_clean_missing_fuses(self):
        table = _raw_table(seed=14)
        pm = Pipeline(stages=[
            CleanMissingData(inputCols=["num2"], outputCols=["num2c"],
                             cleaningMode="Mean"),
            FastVectorAssembler(inputCols=["num1", "num2c"],
                                outputCol="fv"),
            StandardScaler(inputCol="fv", outputCol="fv"),
        ]).fit(table)
        fused = pm.fused()
        plan = fused.plan_for(table.schema)
        assert len(plan.segments) == 1
        out_f = fused.transform(table)
        _assert_tables_equal(out_f, fused.transform_staged(table),
                             cols=["num2c", "fv"])
        legacy = pm.transform(table)
        assert np.allclose(np.asarray(legacy["fv"]),
                           np.asarray(out_f["fv"]), atol=1e-6)

    def test_host_only_stage_breaks_segment_but_output_matches(self):
        """A Lambda between device stages forces two segments with a
        host hop; outputs still match the legacy path."""
        table = _raw_table(seed=15)

        def bump(t):
            return t.with_column(
                "num1b", np.asarray(t["num1"], np.float64) + 1.0)

        pm = Pipeline(stages=[
            Lambda(transformFunc=bump),
            Featurize(featureColumns=["num1b", "num2"],
                      numberOfFeatures=8),
            StandardScaler(inputCol="features", outputCol="features"),
        ]).fit(table)
        fused = pm.fused()
        out_f = fused.transform(table)
        legacy = pm.transform(table)
        _assert_tables_equal(legacy, out_f, cols=["features"])


# ---------------------------------------------------------------------------
# column liveness + pruning
# ---------------------------------------------------------------------------


class _SpyStage(Transformer):
    """Records the column set it receives; declares its column flow so
    pruning may act across it."""

    def _post_init(self):
        self.seen_columns = None

    def transform(self, table):
        self.seen_columns = list(table.column_names)
        return table

    def reads_columns(self, schema):
        return ["features"]

    def writes_columns(self, schema):
        return []


class TestColumnPruning:
    def test_liveness_basic(self):
        table = _raw_table(n=20)
        fm = Featurize(featureColumns=FEATURE_COLS,
                       numberOfFeatures=8).fit(table)
        lr = TPULogisticRegression(featuresCol="features",
                                   labelCol="label", maxIter=2)
        model = lr.fit(fm.transform(table))
        stages = [fm, model, SelectColumns(cols=["prediction"])]
        needed = FZ.column_liveness(stages, table.schema)
        # entering the model: only features (+passthrough prediction
        # target) survive the Select
        assert needed[1] is not None
        assert "toks" not in needed[1]
        assert "features" in needed[1]
        # entering Select: just prediction
        assert needed[2] == {"prediction"}

    def test_transform_prunes_dead_intermediates_with_parity(self):
        """The satellite: intermediate columns nothing downstream reads
        are dropped mid-pipeline; final output is IDENTICAL."""
        table = _raw_table(n=80, seed=20)
        fm = Featurize(featureColumns=FEATURE_COLS,
                       numberOfFeatures=8).fit(table)
        lr_model = TPULogisticRegression(
            featuresCol="features", labelCol="label",
            maxIter=5).fit(fm.transform(table))
        spy = _SpyStage()
        pm = PipelineModel(stages=[
            fm, lr_model, spy,
            SelectColumns(cols=["prediction", "probability"])])
        out = pm.transform(table)
        assert out.column_names == ["prediction", "probability"]
        # the wide hashed 'features' matrix was consumed by the model
        # and nothing after the spy reads it except the spy's declared
        # 'features' read; raw inputs (toks/cat/nums) were pruned
        assert "toks" not in spy.seen_columns
        assert "cat" not in spy.seen_columns
        assert "features" in spy.seen_columns
        # parity vs the unpruned stage-at-a-time walk
        ref = table
        for st in pm.get_stages():
            ref = st.transform(ref)
        _assert_tables_equal(ref, out,
                             cols=["prediction", "probability"])

    def test_unknown_stage_disables_pruning(self):
        """A Lambda (unknown column flow) must keep every column
        flowing — even ones its transform_schema doesn't mention."""
        table = _raw_table(n=40, seed=21)

        def adds_col(t):
            return t.with_column("invented",
                                 np.arange(len(t), dtype=np.float64))

        picked = {}

        def check(t):
            picked["cols"] = list(t.column_names)
            return t

        pm = PipelineModel(stages=[
            Lambda(transformFunc=adds_col),
            Lambda(transformFunc=check),
            DropColumns(cols=["num1"])])
        out = pm.transform(table)
        assert "invented" in picked["cols"]
        assert "invented" in out.column_names

    def test_fit_with_unknown_tail_keeps_estimator_outputs(self):
        """Regression: an Estimator whose transform_schema is the
        identity (Featurize) makes the forward schema walk blind to its
        model's output column; with an unknown stage downstream the
        liveness recovery branch must NOT trust that walk and prune
        'features' away before the Lambda that reads it."""
        table = _raw_table(n=60, seed=24)
        seen = {}

        def probe(t):
            seen["cols"] = list(t.column_names)
            assert "features" in t.column_names
            return t

        pm = Pipeline(stages=[
            Featurize(featureColumns=["num1", "num2"],
                      numberOfFeatures=4),
            DropColumns(cols=["icol"]),       # declared stage between
            Lambda(transformFunc=probe),      # unknown: reads features
            DropColumns(cols=["label"]),      # Lambda not last, so fit
        ]).fit(table)                         # actually runs the probe
        assert "features" in seen["cols"]
        out = pm.transform(table)
        assert "features" in out.column_names

    def test_fit_prunes_but_models_identical(self):
        table = _raw_table(n=120, seed=22)
        pipe = Pipeline(stages=[
            Featurize(featureColumns=FEATURE_COLS, numberOfFeatures=8),
            TPULogisticRegression(featuresCol="features",
                                  labelCol="label", maxIter=5)])
        pm = pipe.fit(table)
        scoring = _raw_table(n=50, seed=23)
        out = pm.transform(scoring)
        # refit through the raw (pre-pruning) loop for parity
        fm = pipe.get_stages()[0].fit(table)
        lr = pipe.get_stages()[1].fit(fm.transform(table))
        ref = lr.transform(fm.transform(scoring))
        _assert_tables_equal(ref, out,
                             cols=["features", "prediction",
                                   "probability"])


# ---------------------------------------------------------------------------
# DeviceTable
# ---------------------------------------------------------------------------


class TestDeviceTable:
    def test_columns_ship_once_across_transforms(self):
        table = _raw_table(n=60, seed=30)
        pm = _fit_logistic_pipeline(table)
        fused = pm.fused()
        fused.transform(table)
        plan = fused.plan_for(table.schema)
        ships1 = plan.device_table.stats()["column_ships"]
        fused.transform(table)
        stats = plan.device_table.stats()
        assert stats["column_ships"] == ships1, \
            "same table re-shipped columns"
        assert stats["column_hits"] > 0

    def test_consts_invalidate_on_stage_mutation(self):
        """The keyed-invalidation contract: mutating a stage param
        re-ships exactly that stage's consts and the new values take
        effect; an unchanged stage's consts stay cached."""
        table = _raw_table(n=60, seed=31)
        pm = _fit_logistic_pipeline(table)
        fused = pm.fused()
        out1 = fused.transform(table)
        scaler_model = pm.get_stages()[1]
        lr_model = pm.get_stages()[2]
        w = {k: np.array(v) for k, v in lr_model.get("weights").items()}
        w["b"] = np.array(w["b"])
        w["b"][1] += 5.0   # shift ONE class bias -> probabilities move
        lr_model.set("weights", w)
        out2 = fused.transform(table)
        assert not np.allclose(np.asarray(out1["probability"]),
                               np.asarray(out2["probability"]))
        # legacy host path agrees with the refreshed consts
        legacy = pm.transform(table)
        assert np.array_equal(np.asarray(legacy["prediction"]),
                              np.asarray(out2["prediction"]))
        # and the epoch key changed only for the mutated stage
        assert FZ.stage_epoch(lr_model) > 0
        ep_before = FZ.stage_epoch(scaler_model)
        fused.transform(table)
        assert FZ.stage_epoch(scaler_model) == ep_before

    def test_zero_steady_state_recompiles(self):
        table = _raw_table(n=60, seed=32)
        pm = _fit_logistic_pipeline(table)
        fused = pm.fused()
        fused.transform(table)
        misses = fused.jit_cache_misses
        for _ in range(3):
            fused.transform(table)
        assert fused.jit_cache_misses == misses
        # a different row count is a new shape -> one new compile, then
        # flat again
        small = table.slice(0, 32)
        fused.transform(small)
        misses2 = fused.jit_cache_misses
        assert misses2 == misses + 1
        fused.transform(small)
        assert fused.jit_cache_misses == misses2


# ---------------------------------------------------------------------------
# serving integration
# ---------------------------------------------------------------------------


def _post(address, payload, timeout=15):
    req = urllib.request.Request(
        address, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


class TestFusedServing:
    def test_pipeline_scoring_end_to_end(self):
        from mmlspark_tpu.serving.fleet import json_scoring_pipeline
        from mmlspark_tpu.serving.server import serve_model
        table = _raw_table(n=200, seed=40)
        pm = _fit_logistic_pipeline(table)
        scorer = json_scoring_pipeline(pm, batch_size=32)
        example = {"num1": [0.1], "num2": [1.0], "icol": [2],
                   "cat": ["lvl3"], "toks": [["w1", "w2"]]}
        compiles = scorer.warmup(example)
        assert compiles == len(scorer.scorer.fused.bucket_sizes())
        assert scorer.warmup(example) == 0   # idempotent: fully warm
        m0 = scorer.jit_cache_miss_count()
        rt0 = scorer.scorer.device_roundtrips
        engine = serve_model(scorer, port=19410, batch_size=32,
                             workers=2)
        try:
            payload = {"num1": 0.4, "num2": float("nan"), "icol": 1,
                       "cat": "lvl7", "toks": ["w3", "w9"]}
            replies = [_post(engine.source.address, payload)
                       for _ in range(6)]
            assert all("prediction" in r for r in replies)
            # the raw-row reply matches the batch-transform verdict
            row = DataTable({k: [v] for k, v in payload.items()})
            expect = float(np.asarray(
                pm.fused().transform(row)["prediction"])[0])
            assert float(replies[0]["prediction"]) == expect
        finally:
            engine.stop()
        assert scorer.jit_cache_miss_count() == m0, \
            "steady-state serving recompiled a fused program"
        scored = scorer.scorer.batches_scored - 0
        trips = scorer.scorer.device_roundtrips - rt0
        assert trips <= scored - 0 or trips <= scored, \
            (trips, scored)
        # at most one device round trip per scored batch
        assert scorer.scorer.device_roundtrips - rt0 <= \
            scorer.scorer.batches_scored

    def test_swap_fused_pipeline_zero_recompiles(self):
        """Lifecycle swap of a fused pipeline: the incoming pipeline
        warms every bucket off the hot path; steady-state traffic never
        compiles — through and after the cutover."""
        from mmlspark_tpu.serving.fleet import json_scoring_pipeline
        from mmlspark_tpu.serving.lifecycle import CanaryPolicy
        from mmlspark_tpu.serving.server import serve_model
        table = _raw_table(n=200, seed=41)
        pm1 = _fit_logistic_pipeline(table)
        pm2 = _fit_logistic_pipeline(_raw_table(n=200, seed=42))
        s1 = json_scoring_pipeline(pm1, batch_size=32)
        s2 = json_scoring_pipeline(pm2, batch_size=32)
        example = {"num1": [0.1], "num2": [1.0], "icol": [2],
                   "cat": ["lvl3"], "toks": [["w1", "w2"]]}
        s1.warmup(example)
        engine = serve_model(s1, port=19420, batch_size=32, workers=2,
                             version="v1")
        try:
            payload = {"num1": 0.4, "num2": 0.2, "icol": 1,
                       "cat": "lvl7", "toks": ["w3"]}
            for _ in range(4):
                _post(engine.source.address, payload)
            m1 = s1.jit_cache_miss_count()
            # steady background load so the canary sees batches
            import threading
            stop = threading.Event()

            def pump():
                while not stop.is_set():
                    try:
                        _post(engine.source.address, payload, timeout=5)
                    except Exception:  # noqa: BLE001 — load only
                        pass

            pumps = [threading.Thread(target=pump, daemon=True)
                     for _ in range(3)]
            for t in pumps:
                t.start()
            try:
                res = engine.swap(
                    s2, "v2", warmup_example=example,
                    policy=CanaryPolicy(fraction=0.5, min_batches=2,
                                        decision_timeout_s=20))
            finally:
                stop.set()
                for t in pumps:
                    t.join(timeout=5)
            assert res.completed, res.reason
            warm = len(s2.scorer.fused.bucket_sizes())
            m2_after_swap = s2.jit_cache_miss_count()
            assert m2_after_swap == warm, \
                "swap warmup did not cover every bucket exactly once"
            for _ in range(6):
                r = _post(engine.source.address, payload)
                assert "prediction" in r
            assert s1.jit_cache_miss_count() == m1
            assert s2.jit_cache_miss_count() == m2_after_swap, \
                "post-cutover traffic recompiled the fused pipeline"
            assert engine.model_version == "v2"
        finally:
            engine.stop()


class TestFusedScorerEdges:
    """Regressions from review: host-only plans must not double-run,
    late-appearing JSON keys must not be dropped, multi-segment tails
    must not retrace per batch size, vector reply columns must encode."""

    def _req_table(self, payloads):
        reqs = [{"entity": json.dumps(p).encode()} for p in payloads]
        return DataTable({"id": [str(i) for i in range(len(reqs))],
                          "request": reqs})

    def test_host_only_pipeline_single_run(self):
        """A pipeline with no fused segment (Lambda-wrapped scoring):
        prepare() runs it once; execute() must NOT run it again."""
        from mmlspark_tpu.serving.fleet import json_scoring_pipeline
        calls = {"n": 0}

        def score(t):
            calls["n"] += 1
            return t.with_column(
                "prediction",
                np.asarray(t["x"], np.float64) * 2.0)

        pm = PipelineModel(stages=[Lambda(transformFunc=score)])
        scorer = json_scoring_pipeline(pm, batch_size=16)
        out = scorer.scorer.transform(self._req_table([{"x": 3.0}]))
        assert out["reply"][0] == {"prediction": 6}
        assert calls["n"] == 1, "host-only pipeline ran twice per batch"

    def test_late_json_key_is_not_dropped(self):
        """A field the first batch omitted must still reach the
        pipeline when later requests supply it."""
        from mmlspark_tpu.serving.fleet import json_scoring_pipeline
        table = _raw_table(n=100, seed=50)
        pm = Pipeline(stages=[
            CleanMissingData(inputCols=["num2"], outputCols=["num2"]),
            FastVectorAssembler(inputCols=["num1", "num2"],
                                outputCol="fv"),
            TPULinearRegression(featuresCol="fv", labelCol="label",
                                maxIter=5),
        ]).fit(table)
        scorer = json_scoring_pipeline(pm, batch_size=16)
        sc = scorer.scorer
        # first batch omits num2 entirely -> pinned names lack it (the
        # request itself fails: a required field is absent — in
        # production the engine turns that into per-row 500s)
        with pytest.raises(Exception):
            sc.transform(self._req_table([{"num1": 1.0}]))
        # later batch supplies num2: its value must flow (two requests
        # differing only in num2 must score differently)
        o1 = sc.transform(self._req_table([{"num1": 1.0, "num2": 0.0}]))
        o2 = sc.transform(self._req_table([{"num1": 1.0, "num2": 9.0}]))
        v1 = o1["reply"][0]["prediction"]
        v2 = o2["reply"][0]["prediction"]
        assert v1 != v2, "late-appearing JSON key was silently dropped"

    def test_multi_segment_tail_zero_steady_state_recompiles(self):
        """A host Lambda between two device runs: the tail segment must
        see bucket-padded shapes too, so ragged micro-batch sizes never
        retrace on the hot path."""
        from mmlspark_tpu.serving.fleet import json_scoring_pipeline
        table = _raw_table(n=150, seed=51)

        def rename(t):
            return t.with_column(
                "fx", np.asarray(t["features"], np.float32))

        pm = Pipeline(stages=[
            Featurize(featureColumns=["num1", "num2"],
                      numberOfFeatures=8),
            Lambda(transformFunc=rename),          # host hop
            StandardScaler(inputCol="fx", outputCol="fx"),
            TPULogisticRegression(featuresCol="fx", labelCol="label",
                                  maxIter=5),
        ]).fit(table)
        scorer = json_scoring_pipeline(pm, batch_size=16)
        sc = scorer.scorer
        plan = None
        # warm, then hammer ragged sizes: misses must stay flat
        scorer.warmup({"num1": [0.1], "num2": [0.2]})
        m0 = scorer.jit_cache_miss_count()
        for size in (1, 3, 5, 7, 2, 6):
            rows = [{"num1": 0.1 * i, "num2": 0.2} for i in range(size)]
            out = sc.transform(self._req_table(rows))
            assert len(out["reply"]) == size
        assert scorer.jit_cache_miss_count() == m0, \
            "ragged batch sizes retraced a tail segment"

    def test_vector_reply_column(self):
        from mmlspark_tpu.serving.fleet import json_scoring_pipeline
        table = _raw_table(n=100, seed=52)
        pm = _fit_logistic_pipeline(table)
        scorer = json_scoring_pipeline(pm, batch_size=16,
                                       reply_col="probability",
                                       reply_field="probs")
        row = {"num1": 0.3, "num2": 0.1, "icol": 1, "cat": "lvl2",
               "toks": ["w1"]}
        out = scorer.scorer.transform(self._req_table([row]))
        probs = out["reply"][0]["probs"]
        assert isinstance(probs, list) and len(probs) == 2
        assert abs(sum(probs) - 1.0) < 1e-5

    def test_drift_monitor_rejected_for_pipelines(self):
        from mmlspark_tpu.core.metrics import DriftMonitor
        from mmlspark_tpu.serving.fleet import json_scoring_pipeline
        table = _raw_table(n=60, seed=53)
        pm = _fit_logistic_pipeline(table)
        dm = DriftMonitor(np.zeros(3), np.ones(3))
        with pytest.raises(ValueError, match="drift_monitor"):
            json_scoring_pipeline(pm, drift_monitor=dm)


# ---------------------------------------------------------------------------
# the static kernel check (CI guard for the no-host-round-trip invariant)
# ---------------------------------------------------------------------------


def _bad_kernel(consts, env):
    x = env["a"]
    return {"out": np.asarray(x) + 1}


def _ok_kernel(consts, env):
    return {"out": env["a"] + consts["b"]}


class TestKernelStaticCheck:
    def test_shipped_kernels_are_clean(self):
        import sys, os
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        import check_fusion_kernels as chk
        n = chk.register_representative_pipelines()
        n += chk.register_known_callees()
        assert n >= 12, "expected every fusable stage family + the " \
            "known kernel callees (forest walk, objectives) registered"
        violations = chk.check_registered_kernels()
        assert violations == [], "\n".join(violations)

    def test_checker_catches_host_roundtrip(self):
        import sys, os
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        import inspect
        import check_fusion_kernels as chk
        lines, first = inspect.getsourcelines(_bad_kernel)
        import textwrap
        bad = chk._check_source("bad", textwrap.dedent("".join(lines)),
                                first, lines)
        assert bad, "checker missed an np.asarray host round trip"
        lines, first = inspect.getsourcelines(_ok_kernel)
        ok = chk._check_source("ok", textwrap.dedent("".join(lines)),
                               first, lines)
        assert ok == []
