"""Closed-loop continuous training (serving/controlplane.py) + chaos.

The tentpole suite for the drift -> refit -> shadow -> canary ->
cutover loop: autonomous promotion under injected distribution shift,
poisoned refits (label flip / NaN) quarantined with evidence bundles,
trainer-death isolation (serving frozen, /healthz degraded but 200),
SIGKILL mid-cutover on a fleet (>=99% availability, zero wrong
replies, ordered registry timeline), replay-window consistency under
concurrent append+replay, and the check_control_loop AST audit.
"""

import importlib.util
import json
import os
import threading
import time
import types
import urllib.request

import numpy as np
import pytest

from mmlspark_tpu.core.metrics import DriftMonitor
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.io.ooc import ReplayWindow
from mmlspark_tpu.models.linear import TPULogisticRegression
from mmlspark_tpu.serving import (
    CanaryPolicy, ContinuousTrainer, GatePolicy, ModelRegistry,
    RefitPolicy, ServingFleet, TriggerPolicy, json_scoring_pipeline,
    serve_model,
)
from mmlspark_tpu.stages.basic import Lambda

D = 6
RNG_SEED = 7


def _blobs(n=600, d=D, seed=RNG_SEED, shift=0.0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)) + shift
    w = np.linspace(1.0, -1.0, d)
    y = (X @ w > shift * w.sum()).astype(np.float64)
    return X, y


def _post(addr, payload, timeout=10.0):
    req = urllib.request.Request(
        addr, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get(addr, path, timeout=10.0):
    with urllib.request.urlopen(addr + path, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _serve_linear(port, maxIter=60):
    """A fitted logistic model behind HTTP with its fit-time drift
    monitor attached — the standard continuous-training target."""
    X, y = _blobs()
    est = TPULogisticRegression(maxIter=maxIter)
    base = est.fit(DataTable({"features": X, "label": y}))
    dm = DriftMonitor.from_matrix(
        X, feature_names=[f"f{i}" for i in range(D)])
    pipe = json_scoring_pipeline(base, drift_monitor=dm)
    engine = serve_model(pipe, port=port, batch_size=16, workers=2,
                         version="base")
    return engine, est, (X, y)


def _partial_fit_refit(est):
    """The canonical refit hook: warm-start partial_fit over the
    materialized window, fresh drift monitor rebuilt from the window,
    rewrapped for serving."""
    def refit(window, active):
        tab = window.materialize()
        m = est.partial_fit(tab, getattr(active, "model", None))
        ndm = DriftMonitor.from_matrix(
            np.asarray(tab["features"]),
            feature_names=[f"f{i}" for i in range(D)])
        return json_scoring_pipeline(m, drift_monitor=ndm)
    return refit


def _trainer(engine, refit, **kw):
    kw.setdefault("triggers", TriggerPolicy(
        max_mean_delta_sigma=2.0, min_window_rows=64,
        cooldown_s=0.3, watch_slo_alerts=False))
    kw.setdefault("gate", GatePolicy(shadow_rows=256, min_rows=32))
    kw.setdefault("canary", CanaryPolicy(
        fraction=0.5, min_batches=3, decision_timeout_s=20))
    kw.setdefault("warmup_example", {"features": [0.0] * D})
    kw.setdefault("poll_interval_s", 0.05)
    return ContinuousTrainer(engine, refit, **kw)


class _Traffic:
    """Background shifted-traffic stream against one engine."""

    def __init__(self, addr, shift=3.0, n_threads=2):
        self.addr = addr
        self.shift = shift
        self.ok = 0
        self.errors = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(target=self._run, args=(i,), daemon=True)
            for i in range(n_threads)]

    def _run(self, tid):
        rng = np.random.default_rng(1000 + tid)
        while not self._stop.is_set():
            x = rng.normal(size=D) + self.shift
            try:
                status, _ = _post(self.addr, {"features": list(x)},
                                  timeout=10)
                with self._lock:
                    self.ok += status == 200
            except Exception:  # noqa: BLE001 — availability metric
                with self._lock:
                    self.errors += 1
            time.sleep(0.002)

    def __enter__(self):
        for t in self._threads:
            t.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=10)


def _wait(pred, timeout=30.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


# ---------------------------------------------------------------------------
# replay window (satellite: concurrent append+replay consistency)
# ---------------------------------------------------------------------------


class TestReplayWindow:
    def _chunk(self, value, rows=17):
        return DataTable({
            "features": np.full((rows, D), float(value)),
            "label": np.full(rows, float(value))})

    def test_bounded_eviction_keeps_newest(self):
        win = ReplayWindow(max_rows=50)
        for i in range(10):
            win.append(self._chunk(i, rows=17))
        assert win.rows <= 50
        assert win.appended_rows == 170
        assert win.evicted_chunks > 0
        tab = win.snapshot().materialize()
        # only the NEWEST chunks survive eviction (17*3 > 50, so the
        # window holds the last two whole chunks)
        assert set(np.asarray(tab["label"])) == {8.0, 9.0}

    def test_single_oversized_chunk_is_kept(self):
        win = ReplayWindow(max_rows=10)
        win.append(self._chunk(1, rows=64))
        assert win.rows == 64    # never evict down to an empty window

    def test_snapshot_is_immutable_and_replayable(self):
        win = ReplayWindow(max_rows=1000)
        win.append(self._chunk(1))
        win.append(self._chunk(2))
        snap = win.snapshot()
        win.append(self._chunk(3))
        # the snapshot replays the SAME bounded view twice, unaffected
        # by appends that landed after it was taken
        for _ in range(2):
            tab = snap.materialize()
            assert len(tab) == 34
            assert set(np.asarray(tab["label"])) == {1.0, 2.0}

    def test_tail_returns_newest_rows_in_order(self):
        win = ReplayWindow(max_rows=1000)
        for i in range(5):
            win.append(self._chunk(i, rows=10))
        tail = win.tail(25)
        vals = [float(t["label"][0]) for t in tail]
        # newest whole chunks under the row cap (20 <= 25 < 30),
        # oldest-to-newest order preserved for concat
        assert vals == [3.0, 4.0]
        assert win.tail(1)[0]["label"][0] == 4.0    # >=1 chunk always

    def test_concurrent_append_replay_never_torn(self):
        """The control loop reads (snapshot + tail) while the ingest
        driver appends: every replay must see whole chunks only (a
        chunk is homogeneous here — any mixed-value chunk is a tear)
        and stay within the bound."""
        win = ReplayWindow(max_rows=400)
        stop = threading.Event()
        tears = []
        bounds = []

        def writer():
            i = 0
            while not stop.is_set():
                win.append(self._chunk(i % 97, rows=23))
                i += 1

        def reader():
            while not stop.is_set():
                snap = win.snapshot()
                total = 0
                for chunk in snap.chunks(prefetch_depth=0):
                    col = np.asarray(chunk["label"])
                    if len(set(col.tolist())) > 1:
                        tears.append(col)
                    total += len(col)
                bounds.append(total)
                for t in win.tail(100):
                    col = np.asarray(t["label"])
                    if len(set(col.tolist())) > 1:
                        tears.append(col)

        threads = [threading.Thread(target=writer)] + [
            threading.Thread(target=reader) for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(1.0)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not tears, f"torn chunk observed: {tears[:1]}"
        assert bounds and max(bounds) <= 400 + 23, max(bounds)
        # eviction really ran while replays were in flight
        assert win.evicted_chunks > 0


# ---------------------------------------------------------------------------
# the AST audit (satellite: check_control_loop)
# ---------------------------------------------------------------------------


def _load_checker(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
            "tools", "check_fusion_kernels.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestControlLoopAudit:
    def test_shipped_control_loop_clean(self):
        mod = _load_checker("cfk_cl_pos")
        assert mod.check_control_loop() == []

    def test_state_write_outside_funnel_flagged(self):
        mod = _load_checker("cfk_cl_neg1")
        bad = (
            "class T:\n"
            "    def _transition(self, s, e):\n"
            "        self.state = s\n"
            "        self._record(e)\n"
            "    def _record(self, e):\n"
            "        self.registry.record_event(e)\n"
            "    def handle(self):\n"
            "        self.state = 'degraded'\n")
        v = mod.check_control_loop_source(bad, name="bad")
        assert len(v) == 1 and "'handle'" in v[0]
        assert "_transition" in v[0]

    def test_refit_call_outside_trainer_thread_flagged(self):
        mod = _load_checker("cfk_cl_neg2")
        bad = (
            "class T:\n"
            "    def _transition(self, s, e):\n"
            "        self.state = s\n"
            "        self._record(e)\n"
            "    def _record(self, e):\n"
            "        self.registry.record_event(e)\n"
            "    def _batcher_helper(self):\n"
            "        return self.est.partial_fit(self.tab)\n"
            "    def _cycle(self):\n"
            "        return self.refit(self.win, self.active)\n")
        v = mod.check_control_loop_source(bad, name="bad")
        assert len(v) == 1 and "partial_fit" in v[0]
        assert "_batcher_helper" in v[0]    # _cycle is allowlisted

    def test_unrecorded_transition_flagged(self):
        mod = _load_checker("cfk_cl_neg3")
        bad = (
            "class T:\n"
            "    def _transition(self, s, e):\n"
            "        self.state = s\n"    # forgets to record
            "    def _record(self, e):\n"
            "        self.registry.record_event(e)\n")
        v = mod.check_control_loop_source(bad, name="bad")
        assert len(v) == 1
        assert "timeline" in v[0]

    def test_recorder_without_registry_flagged(self):
        mod = _load_checker("cfk_cl_neg4")
        bad = (
            "class T:\n"
            "    def _transition(self, s, e):\n"
            "        self.state = s\n"
            "        self._record(e)\n"
            "    def _record(self, e):\n"
            "        self.history.append(e)\n")    # never record_event
        v = mod.check_control_loop_source(bad, name="bad")
        assert len(v) == 1
        assert "record_event" in v[0]


# ---------------------------------------------------------------------------
# the chaos soak (tentpole acceptance)
# ---------------------------------------------------------------------------


class TestContinuousLoopSoak:
    def test_autonomous_drift_refit_canary_cutover(self):
        """Injected distribution shift -> drift trigger -> incremental
        refit on the trainer thread -> shadow gate pass -> canary ->
        cutover, fully autonomous; the registry timeline holds every
        decision in order and the steady-state serving path compiles
        nothing."""
        import jax.monitoring as jmon
        engine, est, (X, y) = _serve_linear(20200)
        registry = ModelRegistry()
        tr = _trainer(engine, _partial_fit_refit(est),
                      registry=registry)
        compile_events = []
        watching = {"on": False}
        jmon.register_event_listener(
            lambda name, **kw: compile_events.append(name)
            if watching["on"] and "compil" in name else None)
        try:
            tr.start()
            with _Traffic(engine.source.address, shift=3.0) as load:
                # labeled shifted rows arrive out of band
                Xs, ys = _blobs(n=400, seed=11, shift=3.0)
                for lo in range(0, 400, 50):
                    tr.ingest(DataTable({
                        "features": Xs[lo:lo + 50],
                        "label": ys[lo:lo + 50]}))
                assert _wait(lambda: tr.promotions >= 1, timeout=60), \
                    f"no promotion: {tr.status()} {tr.history}"
                assert engine.model_version == "ct-1"
                assert load.errors == 0, \
                    f"{load.errors} failed during the loop"
                # zero steady-state recompiles on the serving path
                watching["on"] = True
                for i in range(30):
                    status, body = _post(
                        engine.source.address,
                        {"features": list(Xs[i % len(Xs)])})
                    assert status == 200 and "prediction" in body
                watching["on"] = False
                assert compile_events == [], compile_events
            # drift watch restarted: the promoted pipeline's fresh
            # monitor took over and the loop settled (no retrigger spin)
            assert tr.cycles == 1, tr.status()
            # every decision on ONE ordered registry timeline
            kinds = [(type(e).__name__, e.kind)
                     for e in registry.events]
            expected = [("RetrainEvent", "loop_started"),
                        ("RetrainEvent", "triggered"),
                        ("RetrainEvent", "refit_ok"),
                        ("ShadowEvent", "shadow_pass"),
                        ("PromoteEvent", "promote_started"),
                        ("SwapEvent", "completed"),
                        ("PromoteEvent", "promoted")]
            it = iter(kinds)
            assert all(k in it for k in expected), (expected, kinds)
            ats = [e.at for e in registry.events]
            assert ats == sorted(ats)
            trig = next(e for e in registry.events
                        if getattr(e, "kind", "") == "triggered")
            assert trig.reason.startswith("drift:")
            assert ">=" in trig.reason    # observed vs threshold
            # the exposition carries the loop + per-feature drift
            text = engine.metrics_text()
            assert "serving_controlplane_promotions_total 1" in text
            assert 'serving_drift_score{feature="' in text
            assert "serving_controlplane_phase_ms" in text
        finally:
            tr.stop()
            engine.stop()
        # loop_stopped landed too (stop() transitions through the
        # funnel like everything else)
        assert registry.events[-1].kind == "loop_stopped"

    def test_poisoned_refit_label_flip_quarantined(self):
        """A label-flipped refit produces a confidently-wrong model:
        the quality gate quarantines it — never promoted — and the
        evidence bundle carries the gate verdict."""
        engine, est, (X, y) = _serve_linear(20210)
        registry = ModelRegistry()

        def poisoned(window, active):
            tab = window.materialize()
            flipped = DataTable({
                "features": np.asarray(tab["features"]),
                "label": 1.0 - np.asarray(tab["label"])})
            return json_scoring_pipeline(
                TPULogisticRegression(maxIter=200).fit(flipped))

        tr = _trainer(engine, poisoned, registry=registry)
        try:
            tr.start()
            Xs, ys = _blobs(n=300, seed=13)
            tr.ingest(DataTable({"features": Xs, "label": ys}))
            tr.trigger_now("poison-drill")
            assert _wait(lambda: tr.quarantines >= 1, timeout=60), \
                tr.status()
            assert tr.promotions == 0
            assert engine.model_version == "base"    # never promoted
            q = tr.quarantined["ct-1"]
            assert q["verdict"]["pass"] is False
            assert q["verdict"]["reason"].startswith(
                "gate:quality_delta")
            assert q["verdict"]["quality_candidate"] < \
                q["verdict"]["quality_baseline"]
            # the flight-recorder bundle contains the gate verdict
            bundle = q["bundle"]
            assert bundle is not None
            assert bundle["reason"].startswith("quarantine:ct-1:gate")
            recorded = [ev for evs in bundle["events"].values()
                        for ev in evs
                        if ev.get("kind") == "quarantined"]
            assert recorded, bundle["events"].keys()
            assert recorded[0]["stats"]["quality_delta"] < -0.02
            # and the timeline shows fail, not promote
            kinds = [getattr(e, "kind", "") for e in registry.events]
            assert "quarantined" in kinds
            assert "promoted" not in kinds
        finally:
            tr.stop()
            engine.stop()

    def test_poisoned_refit_nan_quarantined(self):
        """A NaN-emitting candidate dies at the nan_rate floor."""
        engine, est, _ = _serve_linear(20220)

        class _NaNModel:
            def predict(self, X):
                return np.full(len(X), np.nan)

        tr = _trainer(engine,
                      lambda w, a: types.SimpleNamespace(
                          model=_NaNModel()))
        try:
            tr.start()
            Xs, ys = _blobs(n=200, seed=17)
            tr.ingest(DataTable({"features": Xs, "label": ys}))
            tr.trigger_now("nan-drill")
            assert _wait(lambda: tr.quarantines >= 1, timeout=60), \
                tr.status()
            verdict = tr.quarantined["ct-1"]["verdict"]
            assert verdict["reason"].startswith("gate:nan_rate")
            assert verdict["nan_rate"] == 1.0
            assert engine.model_version == "base"
        finally:
            tr.stop()
            engine.stop()

    def test_refit_failures_open_circuit_serving_frozen(self):
        """Repeated refit failures: retries with backoff inside the
        cycle, then the circuit opens — /healthz degrades (HTTP 200),
        serving continues on the frozen model."""
        engine, est, (X, y) = _serve_linear(20230)
        attempts = []

        def broken(window, active):
            attempts.append(1)
            raise RuntimeError("trainer backend down")

        tr = _trainer(
            engine, broken,
            refit_policy=RefitPolicy(max_attempts=2, backoff_s=0.01,
                                     circuit_after=2,
                                     circuit_reset_s=120.0))
        try:
            tr.start()
            Xs, ys = _blobs(n=200, seed=19)
            tr.ingest(DataTable({"features": Xs, "label": ys}))
            tr.trigger_now("fail-1")
            assert _wait(lambda: tr.refit_failures >= 1, timeout=30)
            tr.trigger_now("fail-2")
            assert _wait(lambda: tr.circuit_open, timeout=30), \
                tr.status()
            assert len(attempts) == 4    # 2 cycles x 2 attempts
            st = tr.status()
            assert st["state"] == "degraded" and st["degraded"]
            # training death never takes serving down: frozen model
            # still answers, /healthz says degraded with HTTP 200
            status, body = _post(engine.source.address,
                                 {"features": list(X[0])})
            assert status == 200 and "prediction" in body
            hstatus, health = _get(engine.source.address, "/healthz")
            assert hstatus == 200
            assert health["status"] == "degraded"
            assert health["controlplane"]["circuit_open"]
            assert engine.model_version == "base"
            kinds = [getattr(e, "kind", "") for e in tr.history]
            assert "circuit_open" in kinds
            assert kinds.count("refit_failed") == 2
        finally:
            tr.stop()
            engine.stop()

    def test_trainer_death_isolation(self):
        """Chaos: the trainer thread dies abruptly. The engine keeps
        serving the frozen model; /healthz reports the control plane
        degraded but stays HTTP 200."""
        engine, est, (X, y) = _serve_linear(20240)
        tr = _trainer(engine, _partial_fit_refit(est))
        try:
            tr.start()
            assert _wait(lambda: tr.status()["trainer_alive"],
                         timeout=10)
            tr.kill_trainer()
            assert _wait(
                lambda: not tr.status()["trainer_alive"], timeout=10)
            st = tr.status()
            assert st["degraded"]
            # request path unaffected: replies keep flowing promptly
            t0 = time.perf_counter()
            for i in range(20):
                status, body = _post(engine.source.address,
                                     {"features": list(X[i])})
                assert status == 200 and "prediction" in body
            assert (time.perf_counter() - t0) < 10
            hstatus, health = _get(engine.source.address, "/healthz")
            assert hstatus == 200
            assert health["status"] == "degraded"
            assert health["controlplane"]["trainer_alive"] is False
        finally:
            tr.stop()
            engine.stop()

    def test_sigkill_mid_cutover_fleet_stays_available(self):
        """SIGKILL (engine.kill(), the in-process crash analog) lands
        mid-canary during an autonomous promotion: the fleet fails over
        (>=99% availability), every reply is correct (zero wrong
        replies), and the registry timeline stays consistent and
        ordered — the cycle ends in quarantine with the swap evidence,
        never a phantom promote."""
        def versioned(version):
            def handle(table):
                return table.with_column("reply", [
                    {"echo": json.loads(r["entity"].decode())["x"],
                     "v": version}
                    for r in table["request"]])
            return Lambda.apply(handle)

        fleet = ServingFleet(versioned("v1"), n_engines=2,
                             base_port=20260, batch_size=4, workers=1,
                             max_wait_ms=2.0, failure_threshold=3,
                             breaker_cooldown=30.0)
        registry = ModelRegistry()
        engine = fleet.engines[0]
        tr = _trainer(
            engine, lambda w, a: versioned("v2"),
            registry=registry,
            predict_fn=lambda pipe, Xm: np.zeros(len(Xm)),
            # a long canary keeps the cutover IN FLIGHT so the kill
            # lands mid-swap; the timeout bounds the test
            canary=CanaryPolicy(fraction=0.5, min_batches=10_000,
                                decision_timeout_s=3.0),
            warmup_example=None)
        results = {}
        stop_load = threading.Event()

        def client(cid, n=400):
            for j in range(n):
                if stop_load.is_set():
                    return
                key = cid * 100000 + j
                try:
                    body = fleet.post({"x": key}, timeout=5.0)
                    results[key] = (body.get("echo") == key
                                    and body.get("v") in ("v1", "v2"))
                except Exception:  # noqa: BLE001 — availability metric
                    results[key] = False

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        try:
            tr.start()
            Xs, ys = _blobs(n=200, seed=23)
            tr.ingest(DataTable({"features": Xs,
                                 "label": np.zeros(200)}))
            for t in threads:
                t.start()
            tr.trigger_now("chaos-drill")
            assert _wait(lambda: engine.swap_state == "canary",
                         timeout=30), (engine.swap_state, tr.status())
            engine.kill()    # SIGKILL mid-cutover
            # the cycle must complete: canary cannot promote on a dead
            # engine — decision timeout -> rollback -> quarantine
            assert _wait(lambda: tr.quarantines + tr.promotions >= 1,
                         timeout=30), tr.status()
            for t in threads:
                t.join(timeout=60)
            assert not any(t.is_alive() for t in threads)
        finally:
            stop_load.set()
            tr.stop()
            fleet.stop_all()
        total = len(results)
        ok = sum(results.values())
        assert total >= 1000
        assert ok / total >= 0.99, f"availability {ok}/{total}"
        # zero wrong replies is implied by ok counting echo+version
        # correctness, not just HTTP success
        assert tr.promotions == 0
        assert tr.quarantines == 1
        reason = tr.quarantined["ct-1"]["verdict"]["reason"]
        assert reason.startswith("canary:breach:")
        # consistent ordered timeline: every decision present, in
        # order, with the rolled-back swap between promote_started and
        # quarantined
        kinds = [(type(e).__name__, getattr(e, "kind", ""))
                 for e in registry.events]
        expected = [("RetrainEvent", "triggered"),
                    ("RetrainEvent", "refit_ok"),
                    ("ShadowEvent", "shadow_pass"),
                    ("PromoteEvent", "promote_started"),
                    ("SwapEvent", "rolled_back"),
                    ("QuarantineEvent", "quarantined")]
        it = iter(kinds)
        assert all(k in it for k in expected), (expected, kinds)
        ats = [e.at for e in registry.events]
        assert ats == sorted(ats)

    def test_idempotent_recovery_after_restart(self):
        """A restarted trainer resumes the version sequence from the
        registry (no collisions) and carries quarantine verdicts
        through state_dict()/load_state()."""
        engine, est, _ = _serve_linear(20250)
        registry = ModelRegistry()
        registry.register("ct-3", object())    # survived the crash
        tr1 = _trainer(engine, _partial_fit_refit(est),
                       registry=registry)
        tr1.quarantined["ct-2"] = {
            "verdict": {"pass": False, "reason": "gate:nan_rate"},
            "bundle": None, "at": 0.0}
        tr1.quarantines = 1
        try:
            tr1.start()
            state = tr1.state_dict()
            tr1.stop()
            # "engine restart": a fresh trainer on the same registry
            tr2 = _trainer(engine, _partial_fit_refit(est),
                           registry=registry, state=state)
            tr2._sync_version_counter()
            # next version continues PAST both the registry (ct-3) and
            # the carried counter — never reissues a burned name
            assert tr2._next_version() == "ct-4"
            assert tr2.quarantines == 1
            assert tr2.quarantined["ct-2"]["verdict"]["reason"] == \
                "gate:nan_rate"
            # and a start() on the restarted trainer is idempotent
            # about the baseline registration
            tr2.start()
            assert registry.versions().count("base") == 1
            tr2.stop()
        finally:
            engine.stop()


# ---------------------------------------------------------------------------
# Prometheus exposition (satellite: per-feature drift + loop families)
# ---------------------------------------------------------------------------


class TestDriftExposition:
    def test_per_feature_scores_capped_with_overflow_fold(self):
        from mmlspark_tpu.core.prometheus import (
            DRIFT_FEATURE_CAP, PromRenderer, drift_families,
        )
        d = DRIFT_FEATURE_CAP + 9
        mon = DriftMonitor(np.zeros(d), np.ones(d),
                           feature_names=[f"f{i}" for i in range(d)])
        X = np.zeros((200, d))
        X[:, 3] = 5.0    # f3 is the drifted feature
        mon.observe(X)
        r = PromRenderer()
        drift_families(r, mon)
        text = r.render()
        lines = [ln for ln in text.splitlines()
                 if ln.startswith("serving_drift_score{")]
        # top-K + exactly one _other fold, never one-per-feature
        assert len(lines) == DRIFT_FEATURE_CAP + 1, lines
        assert sum('feature="_other"' in ln for ln in lines) == 1
        f3 = [ln for ln in lines if 'feature="f3"' in ln]
        assert f3 and float(f3[0].split()[-1]) == pytest.approx(
            5.0, rel=0.01)

    def test_few_features_no_overflow_series(self):
        from mmlspark_tpu.core.prometheus import (
            PromRenderer, drift_families,
        )
        mon = DriftMonitor(np.zeros(4), np.ones(4),
                           feature_names=list("abcd"))
        mon.observe(np.ones((10, 4)))
        r = PromRenderer()
        drift_families(r, mon)
        text = r.render()
        assert 'feature="a"' in text
        # no overflow fold when everything fits under the cap (the
        # HELP line may mention it; no SERIES must carry it)
        assert 'serving_drift_score{feature="_other"}' not in text

    def test_controlplane_families_render(self):
        from mmlspark_tpu.core.prometheus import (
            PromRenderer, controlplane_families,
        )
        fake = types.SimpleNamespace(status=lambda: {
            "state": "idle", "degraded": False, "circuit_open": False,
            "cycles": 3, "refits": 2, "refit_failures": 1,
            "promotions": 2, "quarantines": 1, "last_trigger": "drift:x",
            "window": {"rows": 128}})
        r = PromRenderer()
        controlplane_families(r, fake)
        text = r.render()
        assert "serving_controlplane_promotions_total 2" in text
        assert "serving_controlplane_quarantines_total 1" in text
        assert "serving_controlplane_degraded 0" in text
        assert "serving_controlplane_window_rows 128" in text
        assert 'state="idle"' in text
        assert "serving_controlplane_phase_ms" in text
