"""Worker process for the multi-process jax.distributed test.

Launched N times by tests/test_distributed.py — the TPU-native analog of
the reference's distributed-without-a-cluster pattern (ref:
LightGBMUtils.scala:110-118 local[*] partitions-as-nodes; SURVEY §4):
real separate processes rendezvous at a coordinator, assemble one global
device mesh, and run a psum across it.

Usage: python dist_worker.py <coordinator_port> <process_id> <n_processes>
"""

import os
import sys

import jax

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# CPU backend with 2 virtual devices per process, configured before any
# backend use (env vars don't work here — sitecustomize pins the platform)
from mmlspark_tpu.utils.jax_compat import set_cpu_device_count  # noqa: E402

set_cpu_device_count(2)


def main() -> None:
    port, pid, nproc = (int(a) for a in sys.argv[1:4])

    import numpy as np
    import jax.numpy as jnp
    from jax import lax
    from mmlspark_tpu.utils.jax_compat import shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from mmlspark_tpu.core.table import DataTable
    from mmlspark_tpu.parallel import distributed as dist

    info = dist.initialize(f"127.0.0.1:{port}", num_processes=nproc,
                           process_id=pid)
    assert info.process_count == nproc, info
    assert info.global_device_count == 2 * nproc, info
    assert info.is_coordinator == (pid == 0)

    # host-partitioned feeding: each process keeps its own row range
    # (replaces HDFS staging + scp, ref: CNTKLearner.scala:123-140)
    n_rows = 4 * nproc
    table = DataTable({"x": np.arange(n_rows, dtype=np.float64)})
    local = dist.shard_table_for_host(table, info)
    local_x = np.asarray(local["x"], dtype=np.float32)
    print(f"SHARD {pid} {','.join(str(int(v)) for v in local_x)}",
          flush=True)

    # one global mesh over every device of every process; psum rides the
    # collective backend exactly like histogram/gradient allreduce
    mesh = Mesh(np.array(jax.devices()), ("data",))
    sharding = NamedSharding(mesh, P("data"))
    global_x = jax.make_array_from_process_local_data(sharding, local_x)

    total = jax.jit(shard_map(
        lambda v: lax.psum(jnp.sum(v), "data"),
        mesh=mesh, in_specs=P("data"), out_specs=P()))(global_x)
    print(f"PSUM {pid} {float(total):.1f}", flush=True)

    # host-sharded TPULearner training across the processes: each host
    # feeds its local rows, the global batch is assembled per step via
    # make_array_from_process_local_data, gradients allreduce over the
    # global mesh (the mpirun-cntk analog, CommandBuilders.scala:241)
    from mmlspark_tpu.models.learner import TPULearner

    rng = np.random.default_rng(7)   # same global data on every host
    gx = rng.normal(size=(64, 6)).astype(np.float32)
    gy = (gx[:, 0] + gx[:, 1] > 0).astype(np.int64)
    full = DataTable({"features": gx, "label": gy})
    local = dist.shard_table_for_host(full, info)

    learner = TPULearner(
        networkSpec={"type": "mlp", "features": [8], "num_classes": 2},
        epochs=6, batchSize=8 * nproc, learningRate=0.1,
        computeDtype="float32", logEvery=1000,
        meshAxes={"data": info.global_device_count})
    model = learner.fit(local)
    # every host must end with IDENTICAL (replicated) trained params
    leaf = np.asarray(jax.tree_util.tree_leaves(
        model.get("weights"))[0]).ravel()[:3]
    print(f"TRAIN {pid} {','.join(f'{v:.6f}' for v in leaf)}", flush=True)

    # DEVICE-RESIDENT multi-host feed: each process device_puts its local
    # shard into a row-sharded global array; the epoch permutation is
    # derived on device from the shared seed key so hosts agree without
    # communicating (learner.py run_chunk). Every host must end with
    # identical replicated params, and a re-run with the same seed must
    # reproduce them exactly (on-device shuffle determinism).
    def fit_device_feed():
        dl = TPULearner(
            networkSpec={"type": "mlp", "features": [8], "num_classes": 2},
            epochs=6, batchSize=8 * nproc, learningRate=0.1,
            computeDtype="float32", logEvery=1000, dataFeed="device",
            meshAxes={"data": info.global_device_count})
        dmodel = dl.fit(local)
        return np.concatenate([
            np.asarray(leaf_arr).ravel()
            for leaf_arr in jax.tree_util.tree_leaves(
                dmodel.get("weights"))])

    dw1 = fit_device_feed()
    dw2 = fit_device_feed()
    det = int(np.array_equal(dw1, dw2))
    print(f"DEVFEED {pid} {','.join(f'{v:.6f}' for v in dw1[:3])},{det}",
          flush=True)

    # STREAMING multi-host: each host feeds a RAGGED shard stream (40 vs
    # 36 rows); hosts allgather their counts and truncate to the global
    # minimum so step counts agree (VERDICT r2 item 5 — the restriction
    # learner.py used to raise NotImplementedError for)
    my_rows = 40 if pid == 0 else 40 - 4 * pid
    lo = sum(40 if q == 0 else 40 - 4 * q for q in range(pid))
    rows = np.arange(lo, lo + my_rows)
    sx = gx[rows % 64]
    sy = gy[rows % 64]
    shards = [DataTable({"features": sx[k:k + 16], "label": sy[k:k + 16]})
              for k in range(0, my_rows, 16)]
    stream_learner = TPULearner(
        networkSpec={"type": "mlp", "features": [8], "num_classes": 2},
        epochs=4, batchSize=8 * nproc, learningRate=0.1,
        computeDtype="float32", logEvery=1000,
        meshAxes={"data": info.global_device_count})
    smodel = stream_learner.fit(shards)
    leaf = np.asarray(jax.tree_util.tree_leaves(
        smodel.get("weights"))[0]).ravel()[:3]
    print(f"STREAM {pid} {','.join(f'{v:.6f}' for v in leaf)}", flush=True)

    # multi-host GBDT: every process feeds its LOCAL row shard; bin
    # boundaries come from allgathered samples and histograms psum over
    # the global mesh (the LightGBM worker-partition + allreduce-ring
    # flow, ref: TrainUtils.scala:188-214). Hosts must grow IDENTICAL
    # forests.
    import hashlib
    from mmlspark_tpu.gbdt.booster import train as gbdt_train

    grng = np.random.default_rng(11)
    GX = grng.normal(size=(400, 6))
    GY = (GX[:, 0] + 0.5 * GX[:, 1] > 0).astype(float)
    rows_lo, rows_hi = pid * 200, (pid + 1) * 200
    booster = gbdt_train(
        {"objective": "binary", "num_iterations": 5, "num_leaves": 7,
         "max_bin": 15, "min_data_in_leaf": 5, "parallelism": "data",
         "hist_method": "scatter"},
        GX[rows_lo:rows_hi], GY[rows_lo:rows_hi])
    digest = hashlib.sha256(
        booster.model_to_string().encode()).hexdigest()[:16]
    auc_ok = int(np.mean((booster.predict(GX) > 0.5) == GY) > 0.9)
    print(f"GBDT {pid} {digest},{auc_ok}", flush=True)

    # multi-host FEATURE-parallel: every process holds the FULL dataset
    # (LightGBM's feature-parallel layout) and owns a feature shard of
    # the global mesh; forests must be byte-identical across hosts
    # (ref: TrainParams.scala:26 tree_learner=feature across executors)
    fp = gbdt_train(
        {"objective": "binary", "num_iterations": 5, "num_leaves": 7,
         "max_bin": 15, "min_data_in_leaf": 5, "parallelism": "feature",
         "hist_method": "scatter"},
        GX, GY)
    fp_digest = hashlib.sha256(
        fp.model_to_string().encode()).hexdigest()[:16]
    fp_ok = int(np.mean((fp.predict(GX) > 0.5) == GY) > 0.9)
    print(f"FPGBDT {pid} {fp_digest},{fp_ok}", flush=True)

    # multi-host VOTING-parallel: local row shards like data-parallel,
    # candidate-sized per-split collective (PV-tree across hosts)
    vt = gbdt_train(
        {"objective": "binary", "num_iterations": 5, "num_leaves": 7,
         "max_bin": 15, "min_data_in_leaf": 5, "parallelism": "voting",
         "top_k": 6, "hist_method": "scatter"},
        GX[rows_lo:rows_hi], GY[rows_lo:rows_hi])
    vt_digest = hashlib.sha256(
        vt.model_to_string().encode()).hexdigest()[:16]
    vt_ok = int(np.mean((vt.predict(GX) > 0.5) == GY) > 0.9)
    print(f"VOTEGBDT {pid} {vt_digest},{vt_ok}", flush=True)

    # multi-host feature-parallel with SPARSE input: the dataset digest
    # hashes the CSR buffers (densifying would defeat the sparse path);
    # forests must still be byte-identical across hosts
    from mmlspark_tpu.core.sparse import CSRMatrix
    dense_for_csr = GX.copy()
    dense_for_csr[np.abs(dense_for_csr) < 0.6] = 0.0   # ~45% sparse
    csr_X = CSRMatrix.from_dense(dense_for_csr.astype(np.float32))
    fps = gbdt_train(
        {"objective": "binary", "num_iterations": 4, "num_leaves": 7,
         "max_bin": 15, "min_data_in_leaf": 5, "parallelism": "feature",
         "hist_method": "scatter"},
        csr_X, GY)
    fps_digest = hashlib.sha256(
        fps.model_to_string().encode()).hexdigest()[:16]
    # 0.80 floor: zeroing |x|<0.6 costs signal — single-process serial
    # training on the same CSR data also lands at 0.8275
    fps_ok = int(np.mean((fps.predict(csr_X) > 0.5) == GY) > 0.80)
    print(f"FPCSR {pid} {fps_digest},{fps_ok}", flush=True)

    # f64-faithful multi-host binning: a feature at 2^24 scale whose
    # distinct values collapse under an f32 wire. The agreed boundaries
    # must equal a single-host f64 BinMapper fit on the concatenated
    # data byte-for-byte (the parent test recomputes and compares), and
    # the trained forests must agree across hosts with f32_unsafe set.
    from mmlspark_tpu.gbdt.booster import _multihost_mapper
    f24 = 2.0 ** 24
    UX = np.stack([
        f24 + np.arange(400, dtype=np.float64) * 0.25,   # f32-unsafe
        grng.normal(size=400)], axis=1)
    UY = ((UX[:, 0] - f24) * 0.04 + UX[:, 1] > 5.0).astype(float)
    u_mapper = _multihost_mapper(UX[rows_lo:rows_hi], False, 15, 2, nproc)
    b_digest = hashlib.sha256(
        b"".join(u.tobytes() for u in u_mapper.upper_bounds)
    ).hexdigest()[:16]
    ub = gbdt_train(
        {"objective": "binary", "num_iterations": 4, "num_leaves": 7,
         "max_bin": 15, "min_data_in_leaf": 5, "parallelism": "data",
         "hist_method": "scatter"},
        UX[rows_lo:rows_hi], UY[rows_lo:rows_hi])
    u_digest = hashlib.sha256(
        ub.model_to_string().encode()).hexdigest()[:16]
    unsafe = int(bool(ub.params.get("f32_unsafe")))
    print(f"F64BIN {pid} {b_digest},{u_digest},{unsafe}", flush=True)

    # multi-host checkpoint/resume on a REMOTE (webdav://) filesystem:
    # the coordinator writes checkpoints over HTTP PUT, every host
    # resumes from the same remote step (the shared-FS requirement
    # learner.py:452-463 enforces — previously only file:// could
    # satisfy it; ref: CNTKLearner.scala:18-67 dataTransfer=hdfs)
    if len(sys.argv) > 4 and sys.argv[4].startswith("webdav://"):
        from mmlspark_tpu.models.learner import _latest_checkpoint
        ck = f"{sys.argv[4]}/ckpt"
        mk = lambda epochs: TPULearner(  # noqa: E731
            networkSpec={"type": "mlp", "features": [8],
                         "num_classes": 2},
            epochs=epochs, batchSize=8 * nproc, learningRate=0.1,
            computeDtype="float32", logEvery=1000,
            checkpointDir=ck, checkpointEvery=2, resume=True,
            meshAxes={"data": info.global_device_count})
        mk(2).fit(local)
        latest = _latest_checkpoint(ck)       # visible from EVERY host
        step1 = int(latest.rsplit("step_", 1)[1]) if latest else -1
        m2 = mk(4).fit(local)                 # resumes mid-training
        leaf = np.concatenate([
            np.asarray(a).ravel()
            for a in jax.tree_util.tree_leaves(m2.get("weights"))])
        wd_digest = hashlib.sha256(
            np.round(leaf, 6).tobytes()).hexdigest()[:16]
        print(f"WEBDAVCKPT {pid} {wd_digest},{step1}", flush=True)

    print(f"OK {pid}", flush=True)


if __name__ == "__main__":
    main()
