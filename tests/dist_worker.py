"""Worker process for the multi-process jax.distributed test.

Launched N times by tests/test_distributed.py — the TPU-native analog of
the reference's distributed-without-a-cluster pattern (ref:
LightGBMUtils.scala:110-118 local[*] partitions-as-nodes; SURVEY §4):
real separate processes rendezvous at a coordinator, assemble one global
device mesh, and run a psum across it.

Usage: python dist_worker.py <coordinator_port> <process_id> <n_processes>
"""

import os
import sys

import jax

# CPU backend with 2 virtual devices per process, configured before any
# backend use (env vars don't work here — sitecustomize pins the platform)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> None:
    port, pid, nproc = (int(a) for a in sys.argv[1:4])

    import numpy as np
    import jax.numpy as jnp
    from jax import lax, shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from mmlspark_tpu.core.table import DataTable
    from mmlspark_tpu.parallel import distributed as dist

    info = dist.initialize(f"127.0.0.1:{port}", num_processes=nproc,
                           process_id=pid)
    assert info.process_count == nproc, info
    assert info.global_device_count == 2 * nproc, info
    assert info.is_coordinator == (pid == 0)

    # host-partitioned feeding: each process keeps its own row range
    # (replaces HDFS staging + scp, ref: CNTKLearner.scala:123-140)
    n_rows = 4 * nproc
    table = DataTable({"x": np.arange(n_rows, dtype=np.float64)})
    local = dist.shard_table_for_host(table, info)
    local_x = np.asarray(local["x"], dtype=np.float32)
    print(f"SHARD {pid} {','.join(str(int(v)) for v in local_x)}",
          flush=True)

    # one global mesh over every device of every process; psum rides the
    # collective backend exactly like histogram/gradient allreduce
    mesh = Mesh(np.array(jax.devices()), ("data",))
    sharding = NamedSharding(mesh, P("data"))
    global_x = jax.make_array_from_process_local_data(sharding, local_x)

    total = jax.jit(shard_map(
        lambda v: lax.psum(jnp.sum(v), "data"),
        mesh=mesh, in_specs=P("data"), out_specs=P()))(global_x)
    print(f"PSUM {pid} {float(total):.1f}", flush=True)

    # host-sharded TPULearner training across the processes: each host
    # feeds its local rows, the global batch is assembled per step via
    # make_array_from_process_local_data, gradients allreduce over the
    # global mesh (the mpirun-cntk analog, CommandBuilders.scala:241)
    from mmlspark_tpu.models.learner import TPULearner

    rng = np.random.default_rng(7)   # same global data on every host
    gx = rng.normal(size=(64, 6)).astype(np.float32)
    gy = (gx[:, 0] + gx[:, 1] > 0).astype(np.int64)
    full = DataTable({"features": gx, "label": gy})
    local = dist.shard_table_for_host(full, info)

    learner = TPULearner(
        networkSpec={"type": "mlp", "features": [8], "num_classes": 2},
        epochs=6, batchSize=8 * nproc, learningRate=0.1,
        computeDtype="float32", logEvery=1000,
        meshAxes={"data": info.global_device_count})
    model = learner.fit(local)
    # every host must end with IDENTICAL (replicated) trained params
    leaf = np.asarray(jax.tree_util.tree_leaves(
        model.get("weights"))[0]).ravel()[:3]
    print(f"TRAIN {pid} {','.join(f'{v:.6f}' for v in leaf)}", flush=True)
    print(f"OK {pid}", flush=True)


if __name__ == "__main__":
    main()
