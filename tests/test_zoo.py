"""Multi-model serving plane suite (serving/zoo.py + admission.py):
model-key routing, lazy activation, LRU eviction under count/bytes/
memory pressure, registry lookup/list consistency under concurrent
churn, tenant quotas + priority shedding, the mixed-tenant model-churn
chaos drill, and the warmup-example validation satellite.

The 256-model floor (bounded p99 under churn, zero steady-state
recompiles on resident models) is slow-marked; ``bench.py zoo`` runs
the full-scale measurement.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.core.warmup import (
    check_warmup_example, warn_warmup_example,
)
from mmlspark_tpu.serving import (
    AdmissionController, HTTPSource, ModelRegistry, ModelZoo,
    ServingEngine, ServingFleet, TenantQuota,
)
from mmlspark_tpu.serving.admission import request_identity
from mmlspark_tpu.serving.fleet import ServingUnavailable
from mmlspark_tpu.serving.zoo import (
    FAILED, LOADING, RESIDENT, UNLOADED, model_key_of,
)
from mmlspark_tpu.stages.basic import Lambda


def echo_stage(tag, delay=0.0, batch_log=None):
    """A tiny serving stage that stamps its model tag into every reply
    (and optionally logs each batch it sees) — the instrument for the
    no-mixed-model and routing assertions."""
    def handle(table):
        if delay:
            time.sleep(delay)
        if batch_log is not None:
            batch_log.append((tag, len(table)))
        replies = []
        for r in table["request"]:
            row = json.loads(r["entity"].decode()) if r.get("entity") \
                else {}
            replies.append({"served_by": tag, "x": row.get("x")})
        return table.with_column("reply", replies)
    return Lambda.apply(handle)


def fresh_zoo(n_models=4, max_resident=None, delay=0.0,
              batch_log=None, **kw):
    kw.setdefault("memory_probe", None)
    zoo = ModelZoo(max_resident=max_resident, **kw)
    for i in range(n_models):
        zoo.register_factory(
            f"m{i}", "v1",
            (lambda i=i: echo_stage(f"m{i}", delay=delay,
                                    batch_log=batch_log)))
    return zoo


def post(addr, body, headers=None, path="/", timeout=30.0):
    """(status, parsed body, response headers) — HTTPError unwrapped."""
    req = urllib.request.Request(
        addr + path, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read())
        except Exception:  # noqa: BLE001
            body = {}
        return e.code, body, dict(e.headers)


# ---------------------------------------------------------------------------
# request routing keys
# ---------------------------------------------------------------------------


class TestModelKeyOf:
    def test_header_case_insensitive(self):
        req = {"requestLine": {"uri": "/"},
               "headers": {"x-MoDeL": "m@v3"}}
        assert model_key_of(req) == "m@v3"

    def test_url_path(self):
        req = {"requestLine": {"uri": "/models/scorer@v2"}, "headers": {}}
        assert model_key_of(req) == "scorer@v2"

    def test_url_path_urlencoded(self):
        req = {"requestLine": {"uri": "/models/scorer%40v2"},
               "headers": {}}
        assert model_key_of(req) == "scorer@v2"

    def test_query_param(self):
        req = {"requestLine": {"uri": "/?model=m1"}, "headers": {}}
        assert model_key_of(req) == "m1"

    def test_header_wins_over_path(self):
        req = {"requestLine": {"uri": "/models/b@v1"},
               "headers": {"X-Model": "a@v1"}}
        assert model_key_of(req) == "a@v1"

    def test_unkeyed(self):
        assert model_key_of({"requestLine": {"uri": "/"},
                             "headers": {}}) is None
        assert model_key_of(None) is None


# ---------------------------------------------------------------------------
# registry lookup/list consistency (the race-hardening satellite)
# ---------------------------------------------------------------------------


class TestRegistryConsistency:
    def test_lookup_triple_and_list(self):
        reg = ModelRegistry()
        reg.register("v1", echo_stage("a"), metadata={"note": "n"})
        obj, state, meta = reg.lookup("v1")
        assert obj is not None and state == "registered"
        assert meta["note"] == "n" and meta["precision"] == "f32"
        rows = reg.list()
        assert rows[0]["version"] == "v1" and rows[0]["loaded"]
        with pytest.raises(KeyError):
            reg.lookup("nope")

    def test_base_registry_hammer(self):
        """lookup/list racing register must always see complete
        entries (metadata carries the auto precision/aot keys the
        moment the version is visible at all)."""
        reg = ModelRegistry()
        stop = threading.Event()
        errors = []

        def writer():
            for i in range(200):
                reg.register(f"v{i}", echo_stage(f"v{i}"))
            stop.set()

        def reader():
            while not stop.is_set():
                for row in reg.list():
                    if "precision" not in row["metadata"]:
                        errors.append(f"torn metadata: {row}")
                for v in reg.versions():
                    obj, state, meta = reg.lookup(v)
                    if obj is None or state != "registered" \
                            or "precision" not in meta:
                        errors.append(f"torn lookup: {v}")

        threads = [threading.Thread(target=reader) for _ in range(3)]
        threads.append(threading.Thread(target=writer))
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors[:3]
        assert len(reg.versions()) == 200

    def test_zoo_lookup_hammer_under_churn(self):
        """The zoo's (handle, state, metadata) triples stay consistent
        while models churn through load/evict: RESIDENT always comes
        with a live handle, every other state with none."""
        zoo = fresh_zoo(n_models=6, max_resident=2)
        stop = threading.Event()
        errors = []

        def churn():
            for i in range(60):
                zoo.get(f"m{i % 6}", timeout=30)
            stop.set()

        def reader():
            while not stop.is_set():
                for i in range(6):
                    handle, state, meta = zoo.lookup(f"m{i}@v1")
                    if state == RESIDENT:
                        if handle is None or handle.pipeline is None:
                            errors.append(f"resident without handle m{i}")
                    elif handle is not None:
                        errors.append(f"{state} with handle m{i}")
                for row in zoo.list():
                    if row["loaded"] != (row["state"] == RESIDENT):
                        errors.append(f"torn list row: {row}")

        threads = [threading.Thread(target=reader) for _ in range(3)]
        threads.append(threading.Thread(target=churn))
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        try:
            assert not errors, errors[:3]
            assert zoo.evictions > 0          # churn actually churned
            assert zoo.evictions_with_outstanding == 0
        finally:
            zoo.close()


# ---------------------------------------------------------------------------
# the zoo cache itself
# ---------------------------------------------------------------------------


class TestModelZoo:
    def test_lazy_load_states_and_audit(self):
        zoo = fresh_zoo(n_models=2)
        try:
            assert zoo.lookup("m0@v1")[1] == UNLOADED
            stage = zoo.get("m0")
            assert stage is not None
            assert zoo.lookup("m0@v1")[1] == RESIDENT
            kinds = [e.kind for e in zoo.events]
            assert kinds.count("register") == 2
            assert kinds.count("activate") == 1
            ev = [e for e in zoo.events if e.kind == "activate"][0]
            assert ev.model == "m0" and "ms" in ev.stats
        finally:
            zoo.close()

    def test_unknown_and_bare_name_latest(self):
        zoo = fresh_zoo(n_models=1)
        zoo.register_factory("m0", "v2", lambda: echo_stage("m0v2"))
        try:
            assert zoo.resolve("m0") == "m0@v2"     # latest wins
            assert zoo.resolve("m0@v1") == "m0@v1"
            assert zoo.resolve("nope") is None
            with pytest.raises(KeyError):
                zoo.get("nope")
        finally:
            zoo.close()

    def test_lru_eviction_count_cap(self):
        zoo = fresh_zoo(n_models=4, max_resident=2)
        try:
            for i in range(3):
                zoo.get(f"m{i}")
            zoo.enforce()
            # m0 is the LRU victim; m1/m2 stay
            assert zoo.lookup("m0@v1")[1] == UNLOADED
            assert zoo.lookup("m1@v1")[1] == RESIDENT
            assert zoo.lookup("m2@v1")[1] == RESIDENT
            evs = [e for e in zoo.events if e.kind == "evict"]
            assert len(evs) == 1 and evs[0].model == "m0"
            assert evs[0].reason == "lru:count_cap"
            # an evicted model reloads on demand (and re-evicts the
            # new LRU)
            assert zoo.get("m0") is not None
            assert zoo.lookup("m0@v1")[1] == RESIDENT
        finally:
            zoo.close()

    def test_bytes_cap_eviction(self):
        zoo = ModelZoo(max_resident_bytes=250, memory_probe=None)
        for i in range(3):
            zoo.register_factory(f"m{i}", "v1",
                                 (lambda i=i: echo_stage(f"m{i}")),
                                 metadata={"cost_bytes": 100})
        try:
            zoo.get("m0"), zoo.get("m1")
            assert zoo.stats()["resident_bytes"] == 200
            zoo.get("m2")                     # 300 > 250: LRU evicts
            zoo.enforce()
            assert zoo.lookup("m0@v1")[1] == UNLOADED
            assert zoo.stats()["resident_bytes"] == 200
        finally:
            zoo.close()

    def test_memory_pressure_probe_eviction(self):
        pressure = {"on": False}

        def probe():
            if pressure["on"]:
                return {"bytes_in_use": 95, "bytes_limit": 100}
            return {"bytes_in_use": 10, "bytes_limit": 100}

        zoo = ModelZoo(memory_probe=probe, memory_headroom=0.9)
        for i in range(3):
            zoo.register_factory(f"m{i}", "v1",
                                 (lambda i=i: echo_stage(f"m{i}")))
        try:
            for i in range(3):
                zoo.get(f"m{i}")
            zoo.enforce()
            assert zoo.stats()["by_state"][RESIDENT] == 3   # no pressure
            pressure["on"] = True
            zoo.enforce()
            # sheds down to (but never below) ONE resident model
            assert zoo.stats()["by_state"][RESIDENT] == 1
            assert zoo.lookup("m2@v1")[1] == RESIDENT       # MRU kept
            reasons = {e.reason for e in zoo.events
                       if e.kind == "evict"}
            assert reasons == {"lru:memory_pressure"}
        finally:
            zoo.close()

    def test_eviction_never_hits_outstanding(self):
        zoo = fresh_zoo(n_models=2, max_resident=1)
        try:
            zoo.get("m0")
            handle, state, _ = zoo.acquire("m0")   # a batch in flight
            assert state == RESIDENT
            zoo.get("m1")                          # over the cap
            zoo.enforce()
            # m0 (LRU) has an outstanding batch: m1 is the only
            # eligible victim even though it is MRU
            assert zoo.lookup("m0@v1")[1] == RESIDENT
            handle.release()
            zoo.enforce()
            assert zoo.lookup("m0@v1")[1] == UNLOADED
            assert zoo.evictions_with_outstanding == 0
        finally:
            zoo.close()

    def test_eviction_never_hits_awaited_model(self):
        # regression for the demand > capacity livelock: a model with
        # requests parked AWAITING its activation must not be the LRU
        # victim the instant it activates — it would evict before the
        # batcher's flush poll, reload, and starve its requests to the
        # activation timeout (seen as 280 load/evict events per second
        # in the churn drill under host contention)
        zoo = fresh_zoo(n_models=3, max_resident=1)
        try:
            zoo.add_waiter("m0")   # a batcher parks BEFORE activation
            zoo.get("m0")
            zoo.get("m1")          # 2 residents > cap; m1's post-load
            zoo.enforce()          # enforce must spare awaited m0
            # m0 is LRU but awaited; m1 is MRU: neither evictable
            assert zoo.lookup("m0@v1")[1] == RESIDENT
            assert not zoo.evict("m0")     # manual evict refuses too
            zoo.remove_waiter("m0")
            zoo.enforce()
            assert zoo.lookup("m0@v1")[1] == UNLOADED
            assert zoo.evictions_with_outstanding == 0
        finally:
            zoo.close()

    def test_pin_exempts_from_eviction(self):
        zoo = fresh_zoo(n_models=3, max_resident=1)
        try:
            zoo.get("m0")
            zoo.pin("m0")
            zoo.get("m1")
            zoo.get("m2")
            zoo.enforce()
            assert zoo.lookup("m0@v1")[1] == RESIDENT
            assert not zoo.evict("m0")    # manual evict refuses too
            zoo.pin("m0", pinned=False)
            assert zoo.evict("m0")
        finally:
            zoo.close()

    def test_memory_probe_none_disables_live_signal(self):
        # regression: memory_probe=None must mean the live signal is
        # OFF (CPU tests, hosts where preallocation makes bytes_in_use
        # meaningless) — it used to silently substitute the default
        # device_memory_stats probe
        zoo = ModelZoo(memory_probe=None)
        try:
            assert zoo.memory_probe is None
        finally:
            zoo.close()
        zoo2 = ModelZoo()          # default: the live probe is wired
        try:
            assert zoo2.memory_probe is not None
        finally:
            zoo2.close()

    def test_event_log_bounded_under_churn(self):
        # regression: the inherited registry event log was append-only
        # — a churning cache in an always-on process must not grow the
        # audit trail forever
        zoo = fresh_zoo(n_models=2, max_resident=1)
        zoo.events_cap = 16
        try:
            for _ in range(30):
                zoo.get("m0")
                zoo.enforce()
                zoo.get("m1")
                zoo.enforce()
            assert len(zoo.events) <= 16
            assert zoo.events[-1].kind in ("activate", "evict")
        finally:
            zoo.close()

    def test_scan_orders_versions_naturally(self, tmp_path):
        # regression: lexicographic os.listdir order registers v9
        # AFTER v12, so bare-name latest would silently serve v9
        for v in ("v1", "v9", "v10", "v12"):
            d = tmp_path / "m" / v
            d.mkdir(parents=True)
            (d / "manifest.json").write_text(
                '{"kind": "model", "precision": "f32", "buckets": [8]}')
        zoo = ModelZoo(artifact_root=str(tmp_path), memory_probe=None)
        try:
            assert zoo.resolve("m") == "m@v12"
        finally:
            zoo.close()

    def test_lost_load_requeued_by_watchdog(self):
        # regression: an entry stuck LOADING (queued load lost to a
        # loader death or a close() race) must recover — acquire's
        # watchdog requeues overdue loads instead of 503ing forever
        zoo = fresh_zoo(n_models=1)
        try:
            with zoo._lock:
                e = zoo._entries["m0@v1"]
                e.state = LOADING          # simulate the lost load
                e.loading_since = time.monotonic() - 999
            assert zoo.get("m0", timeout=10) is not None
        finally:
            zoo.close()

    def test_single_oversized_model_never_self_evicts(self):
        # regression: a SOLE resident model whose cost exceeds a cap
        # must not evict itself right after every activation — a
        # load/evict livelock that never serves the request that
        # triggered the load. Brief overshoot beats thrash.
        zoo = ModelZoo(max_resident_bytes=100, memory_probe=None)
        zoo.register_factory("big", "v1", lambda: echo_stage("big"),
                             metadata={"cost_bytes": 500})
        try:
            assert zoo.get("big", timeout=10) is not None
            for _ in range(3):
                zoo.enforce()
            assert zoo.lookup("big@v1")[1] == RESIDENT
            assert zoo.evictions == 0
            assert zoo.activations == 1
        finally:
            zoo.close()

    def test_load_failure_cooldown_and_retry(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("weights corrupt")
            return echo_stage("ok")

        zoo = ModelZoo(memory_probe=None, failure_cooldown_s=0.1)
        zoo.register_factory("m", "v1", flaky)
        try:
            with pytest.raises(RuntimeError, match="weights corrupt"):
                zoo.get("m", timeout=10)
            assert zoo.lookup("m@v1")[1] == FAILED
            assert zoo.load_failures == 1
            assert [e.kind for e in zoo.events].count("load_failed") == 1
            time.sleep(0.15)                  # cooldown over: retried
            assert zoo.get("m", timeout=10) is not None
            assert zoo.lookup("m@v1")[1] == RESIDENT
        finally:
            zoo.close()


# ---------------------------------------------------------------------------
# the model-routed engine
# ---------------------------------------------------------------------------


@pytest.fixture
def zoo_engine():
    zoo = fresh_zoo(n_models=4)
    source = HTTPSource(port=19700)
    engine = ServingEngine(source, zoo=zoo, batch_size=8,
                           max_wait_ms=2.0, tracing=False).start()
    yield engine, zoo, source.address
    engine.stop()
    zoo.close()


class TestZooEngine:
    def test_routes_by_header_and_path(self, zoo_engine):
        engine, zoo, addr = zoo_engine
        code, body, headers = post(addr, {"x": 1}, {"X-Model": "m1"})
        assert code == 200 and body["served_by"] == "m1"
        assert headers.get("X-Model") == "m1@v1"
        code, body, headers = post(addr, {"x": 2}, path="/models/m2@v1")
        assert code == 200 and body["served_by"] == "m2"
        assert headers.get("X-Model") == "m2@v1"

    def test_unkeyed_400_unknown_404(self, zoo_engine):
        engine, zoo, addr = zoo_engine
        code, body, _ = post(addr, {"x": 1})
        assert code == 400 and "no model specified" in body["error"]
        code, body, _ = post(addr, {"x": 1}, {"X-Model": "ghost"})
        assert code == 404 and "unknown model" in body["error"]
        with engine._stats_lock:
            rej = dict(engine.rejections)
        assert rej == {"no_model": 1, "unknown_model": 1}

    def test_zoo_fault_rejects_group_alone(self, zoo_engine):
        # regression: a zoo fault while acquiring ONE model's handle
        # (e.g. the loader thread failing to spawn) must 500 that
        # group alone — other models keep serving and the batcher
        # thread survives
        engine, zoo, addr = zoo_engine
        real = zoo.acquire

        def flaky(spec):
            if spec.startswith("m3"):
                raise RuntimeError("loader thread spawn failed")
            return real(spec)

        zoo.acquire = flaky
        try:
            code, body, _ = post(addr, {"x": 1}, {"X-Model": "m3"})
            assert code == 500 and "routing error" in body["error"]
            code, body, _ = post(addr, {"x": 2}, {"X-Model": "m1"})
            assert code == 200 and body["served_by"] == "m1"
            with engine._stats_lock:
                assert engine.rejections.get("routing_error") == 1
        finally:
            zoo.acquire = real

    def test_default_pipeline_serves_unkeyed(self):
        zoo = fresh_zoo(n_models=1)
        source = HTTPSource(port=19710)
        engine = ServingEngine(source, echo_stage("default"), zoo=zoo,
                               tracing=False).start()
        try:
            code, body, headers = post(source.address, {"x": 1})
            assert code == 200 and body["served_by"] == "default"
            assert "X-Model" not in headers    # default path: no label
            code, body, _ = post(source.address, {"x": 1},
                                 {"X-Model": "m0"})
            assert code == 200 and body["served_by"] == "m0"
        finally:
            engine.stop()
            zoo.close()

    def test_no_mixed_model_batches_under_concurrency(self):
        batch_log = []
        zoo = fresh_zoo(n_models=4, batch_log=batch_log, delay=0.002)
        source = HTTPSource(port=19720)
        engine = ServingEngine(source, zoo=zoo, batch_size=8,
                               max_wait_ms=4.0, workers=2,
                               tracing=False).start()
        results = []
        lock = threading.Lock()

        def client(tid):
            for i in range(10):
                model = f"m{(tid + i) % 4}"
                code, body, headers = post(source.address, {"x": i},
                                           {"X-Model": model})
                with lock:
                    results.append((model, code, body, headers))

        try:
            threads = [threading.Thread(target=client, args=(t,))
                       for t in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert len(results) == 80
            for model, code, body, headers in results:
                assert code == 200
                # the reply-mixing check: every reply's model/version
                # matches its request, body AND header
                assert body["served_by"] == model
                assert headers.get("X-Model") == f"{model}@v1"
            # micro-batches really batched (not all 1-row) yet never
            # mixed: each stage only ever saw its own tag
            assert all(tag in (f"m{i}" for i in range(4))
                       for tag, _n in batch_log)
            assert any(n > 1 for _tag, n in batch_log)
        finally:
            engine.stop()
            zoo.close()

    def test_cold_activation_does_not_block_resident_models(self):
        zoo = ModelZoo(memory_probe=None)
        zoo.register_factory("fast", "v1", lambda: echo_stage("fast"))
        zoo.register_factory(
            "cold", "v1",
            lambda: (time.sleep(0.8), echo_stage("cold"))[1])
        source = HTTPSource(port=19730)
        engine = ServingEngine(source, zoo=zoo, max_wait_ms=2.0,
                               tracing=False).start()
        try:
            assert post(source.address, {"x": 0},
                        {"X-Model": "fast"})[0] == 200
            cold_result = {}

            def cold_client():
                cold_result["r"] = post(source.address, {"x": 1},
                                        {"X-Model": "cold"},
                                        timeout=30)

            t = threading.Thread(target=cold_client)
            t.start()
            time.sleep(0.05)          # the cold activation is in flight
            lat = []
            for i in range(5):
                t0 = time.perf_counter()
                code, body, _ = post(source.address, {"x": i},
                                     {"X-Model": "fast"})
                lat.append(time.perf_counter() - t0)
                assert code == 200 and body["served_by"] == "fast"
            # resident traffic never waits behind the 0.8s activation
            assert max(lat) < 0.5, lat
            t.join(timeout=30)
            code, body, _ = cold_result["r"]
            assert code == 200 and body["served_by"] == "cold"
        finally:
            engine.stop()
            zoo.close()

    def test_activation_timeout_sheds_503(self):
        zoo = ModelZoo(memory_probe=None)
        zoo.register_factory(
            "slow", "v1",
            lambda: (time.sleep(1.5), echo_stage("slow"))[1])
        source = HTTPSource(port=19740)
        engine = ServingEngine(source, zoo=zoo, max_wait_ms=2.0,
                               activation_timeout_s=0.2,
                               tracing=False).start()
        try:
            code, body, headers = post(source.address, {"x": 1},
                                       {"X-Model": "slow"}, timeout=30)
            assert code == 503 and "activating" in body["error"]
            assert headers.get("Retry-After")
            # the activation itself still completes in the background;
            # a later request is served
            zoo.get("slow", timeout=30)
            code, body, _ = post(source.address, {"x": 2},
                                 {"X-Model": "slow"})
            assert code == 200 and body["served_by"] == "slow"
        finally:
            engine.stop()
            zoo.close()


# ---------------------------------------------------------------------------
# admission: tenant quotas + priority tiers
# ---------------------------------------------------------------------------


class TestAdmission:
    def test_request_identity(self):
        req = {"headers": {"x-tenant": "acme", "X-PRIORITY": "0"}}
        assert request_identity(req) == ("acme", 0)
        assert request_identity({"headers": {}}) == ("default", 1)
        # malformed priority keeps the default; out-of-range clamps
        assert request_identity(
            {"headers": {"X-Priority": "zz"}})[1] == 1
        assert request_identity(
            {"headers": {"X-Priority": "99"}})[1] == 2

    def test_decide_quota_and_priority(self):
        adm = AdmissionController(
            quotas={"noisy": TenantQuota(0.0, burst=2)},
            priority_pressure_limits={2: 0})
        assert adm.decide("noisy", 1, 0) is None
        assert adm.decide("noisy", 1, 0) is None
        assert adm.decide("noisy", 1, 0) == "quota"   # burst spent
        assert adm.decide("calm", 1, 0) is None       # unlimited
        assert adm.decide("calm", 2, 1) == "priority"  # pressure > 0
        assert adm.decide("calm", 2, 0) is None       # at the limit: ok
        assert adm.decide("calm", 0, 10**6) is None   # high never sheds
        stats = adm.stats()
        assert stats["shed"] == {"quota": 1, "priority": 1}
        assert stats["shed_by_tenant"]["noisy"] == 1

    def test_quota_429_over_http_no_failover(self):
        zoo = fresh_zoo(n_models=1)
        adm = AdmissionController(
            quotas={"noisy": TenantQuota(0.0, burst=1)})
        fleet = ServingFleet(n_engines=2, base_port=19750, zoo=zoo,
                             admission=adm, tracing=False)
        try:
            assert fleet.post({"x": 1}, model="m0",
                              tenant="noisy")["served_by"] == "m0"
            # quota spent: 429 surfaces (a fleet-wide quota must NOT
            # fail over — the next replica would just spend it too)
            with pytest.raises(urllib.error.HTTPError) as err:
                fleet.post({"x": 2}, model="m0", tenant="noisy")
            assert err.value.code == 429
            # another tenant is unaffected
            assert fleet.post({"x": 3}, model="m0",
                              tenant="calm")["served_by"] == "m0"
            total_rej = sum(e.rejections.get("quota", 0)
                            for e in fleet.engines)
            assert total_rej == 1
        finally:
            fleet.stop_all()
            zoo.close()

    def test_pressure_counts_source_backlog(self):
        # regression: the dispatch queue alone is bounded by the
        # in-flight token count (workers + pipeline_depth - 1), which
        # left the default tier-2 pressure limit (8) unreachable; the
        # source-queue backlog is where real overload shows
        from mmlspark_tpu.serving.server import _ParkedRequest
        zoo = fresh_zoo(n_models=1)
        source = HTTPSource(port=19767)
        engine = ServingEngine(source, zoo=zoo, tracing=False)
        try:
            assert engine._pressure() == 0
            for i in range(10):
                source.queue.put_nowait(
                    _ParkedRequest(f"r{i}", {"headers": {}}))
            assert engine._pressure() == 10    # > the default limit 8
        finally:
            source.close()
            zoo.close()

    def test_unknown_model_does_not_spend_quota(self):
        # regression: routing runs BEFORE admission — a burst of
        # mistyped model names answers 404 without draining the
        # tenant's token bucket, so its well-formed traffic still
        # serves
        zoo = fresh_zoo(n_models=1)
        adm = AdmissionController(
            quotas={"acme": TenantQuota(0.0, burst=1)})
        source = HTTPSource(port=19765)
        engine = ServingEngine(source, zoo=zoo, admission=adm,
                               tracing=False).start()
        try:
            for i in range(3):
                code, _body, _ = post(source.address, {"x": i},
                                      {"X-Model": "ghost",
                                       "X-Tenant": "acme"})
                assert code == 404
            # the single burst token is still there for a real model
            code, body, _ = post(source.address, {"x": 9},
                                 {"X-Model": "m0", "X-Tenant": "acme"})
            assert code == 200 and body["served_by"] == "m0"
            # ... and spent now: the next request is the 429
            code, _body, _ = post(source.address, {"x": 10},
                                  {"X-Model": "m0",
                                   "X-Tenant": "acme"})
            assert code == 429
        finally:
            engine.stop()
            zoo.close()

    def test_low_priority_sheds_503_under_pressure(self):
        zoo = fresh_zoo(n_models=1)
        # limit -1: any pressure (>= 0) sheds tier 2 — the
        # deterministic stand-in for a saturated dispatch queue
        adm = AdmissionController(priority_pressure_limits={2: -1})
        source = HTTPSource(port=19760)
        engine = ServingEngine(source, zoo=zoo, admission=adm,
                               tracing=False).start()
        try:
            code, body, headers = post(
                source.address, {"x": 1},
                {"X-Model": "m0", "X-Priority": "2"})
            assert code == 503 and "priority" in body["error"]
            assert headers.get("Retry-After")
            code, body, _ = post(source.address, {"x": 1},
                                 {"X-Model": "m0", "X-Priority": "0"})
            assert code == 200
        finally:
            engine.stop()
            zoo.close()


# ---------------------------------------------------------------------------
# the chaos drill: model churn under mixed-tenant load
# ---------------------------------------------------------------------------


class TestZooChurnDrill:
    def test_churn_mixed_tenants_availability_and_no_mixing(self):
        """Models churn in and out (cache 3 of 12) under mixed-tenant
        concurrent load: availability >= 99%, every reply's
        model/version matches its request, and no eviction ever hits a
        model with outstanding batches."""
        zoo = fresh_zoo(n_models=12, max_resident=3, delay=0.001)
        fleet = ServingFleet(n_engines=2, base_port=19770, zoo=zoo,
                             batch_size=8, max_wait_ms=2.0,
                             tracing=False)
        results = []
        lock = threading.Lock()
        rng = np.random.default_rng(7)
        picks = rng.integers(0, 12, size=240)

        def client(tid):
            tenant = "alpha" if tid % 2 == 0 else "beta"
            for i in range(30):
                model = f"m{picks[tid * 30 + i]}"
                try:
                    body = fleet.post({"x": i}, model=model,
                                      tenant=tenant, timeout=60)
                    with lock:
                        results.append((model, 200, body))
                except urllib.error.HTTPError as e:
                    with lock:
                        results.append((model, e.code, None))
                except ServingUnavailable:
                    # fleet-level unavailability (both circuits open)
                    # is a FAILED request, measured by the
                    # availability floor — not a dead client thread
                    with lock:
                        results.append((model, 503, None))

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(8)]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert len(results) == 240
            ok = [r for r in results if r[1] == 200]
            availability = len(ok) / len(results)
            assert availability >= 0.99, (
                f"availability {availability:.3f}; "
                f"failures {[r for r in results if r[1] != 200][:5]}")
            # zero cross-model mixing: every reply names its request's
            # model
            for model, _code, body in ok:
                assert body["served_by"] == model, (model, body)
            # the drill actually churned, and no eviction ever touched
            # a model with batches in flight
            assert zoo.evictions > 0
            assert zoo.evictions_with_outstanding == 0
            # the cache may briefly overshoot the cap while waiter/
            # outstanding protection covers just-activated models
            # (documented: overshoot beats livelock); once traffic
            # stops, enforce converges it back under the cap
            for _ in range(20):
                zoo.enforce()
                if zoo.stats()["by_state"].get(RESIDENT, 0) <= 3:
                    break
                time.sleep(0.05)
            assert zoo.stats()["by_state"].get(RESIDENT, 0) <= 3
        finally:
            fleet.stop_all()
            zoo.close()


# ---------------------------------------------------------------------------
# the AOT artifact store as the distribution format
# ---------------------------------------------------------------------------


class TestZooAOTArtifacts:
    def test_artifact_scan_activate_serve(self, tmp_path):
        """An AOT artifact directory (serving/aot.py) is the zoo's
        distribution format: scan() discovers it, first request
        activates via the AOT load path (zero jit traces at request
        time), and the activation wall is recorded in the audit
        event."""
        import jax
        from mmlspark_tpu.models.networks import build_network
        from mmlspark_tpu.models.tpu_model import TPUModel
        from mmlspark_tpu.serving.aot import export_model

        module = build_network({"type": "mlp", "features": [8],
                                "num_classes": 3})
        x0 = np.zeros((1, 4), np.float32)
        weights = {"params": module.init(jax.random.PRNGKey(0),
                                         x0)["params"]}
        # from_flax: the model fn must survive pickling into the
        # artifact's lazy fallback (a test-local lambda would not)
        model = TPUModel.from_flax(module, weights,
                                   inputCol="features",
                                   outputCol="scores", batchSize=8)
        art_dir = tmp_path / "scorer" / "v1"
        export_model(model, {"features": x0}, str(art_dir),
                     version="v1")

        zoo = ModelZoo(artifact_root=str(tmp_path), memory_probe=None)
        try:
            assert zoo.resolve("scorer") == "scorer@v1"
            _handle, state, meta = zoo.lookup("scorer@v1")
            assert state == UNLOADED and meta["aot"] is True
            assert meta["buckets"] == [8]
            assert zoo.stats()["models"][0]["cost_bytes"] > 0

            source = HTTPSource(port=19780)
            engine = ServingEngine(source, zoo=zoo,
                                   tracing=False).start()
            try:
                code, body, headers = post(
                    source.address, {"features": [0.5, 0.1, 0.2, 0.9]},
                    {"X-Model": "scorer"}, timeout=120)
                assert code == 200 and "prediction" in body
                assert headers.get("X-Model") == "scorer@v1"
                misses_after_activate = None
                for e in zoo.events:
                    if e.kind == "activate":
                        assert e.stats["aot"] is True
                        assert e.stats["ms"] > 0
                        misses_after_activate = True
                assert misses_after_activate
                # steady state: more requests, zero new jit traces on
                # the AOT-loaded replica
                loaded = zoo.get("scorer")
                misses0 = loaded.jit_cache_miss_count()
                for i in range(4):
                    code, _b, _h = post(
                        source.address,
                        {"features": [0.1 * i] * 4},
                        {"X-Model": "scorer@v1"})
                    assert code == 200
                assert loaded.jit_cache_miss_count() == misses0
            finally:
                engine.stop()
        finally:
            zoo.close()


# ---------------------------------------------------------------------------
# warmup-example validation (the PR 11 footnote satellite)
# ---------------------------------------------------------------------------


class _DummyWarmupModel:
    """Pure-host stand-in exposing the warmup_transform contract."""

    jit_cache_misses = 0

    def bucket_sizes(self):
        return [4]

    def transform(self, table):
        return table


class TestWarmupExampleValidation:
    def test_all_none_column_flagged(self):
        table = DataTable({"a": [None], "b": [1.5]})
        msgs = check_warmup_example(table)
        assert len(msgs) == 1 and "'a'" in msgs[0]
        assert "OBJECT" in msgs[0] and "nan" in msgs[0].lower()

    def test_mixed_none_is_fine(self):
        # None mixed with real values infers the value dtype — only
        # ALL-None columns poison the warmed schema
        table = DataTable({"a": [None, 1.5], "b": ["x", None]})
        assert check_warmup_example(table) == []

    def test_live_column_mismatch_flagged(self):
        table = DataTable({"a": [1.0], "zz": [2.0]})
        msgs = check_warmup_example(table, live_columns=["a", "b"])
        assert len(msgs) == 2
        assert any("missing live request column(s) ['b']" in m
                   for m in msgs)
        assert any("['zz'] never seen" in m for m in msgs)

    def test_clean_example_silent(self):
        import warnings as W
        table = DataTable({"a": [1.0], "b": ["s"]})
        with W.catch_warnings():
            W.simplefilter("error")
            assert warn_warmup_example(
                table, live_columns=["a", "b"]) == []

    def test_warmup_transform_warns_at_warmup_time(self):
        from mmlspark_tpu.core.warmup import warmup_transform
        with pytest.warns(RuntimeWarning, match="all-None"):
            warmup_transform(_DummyWarmupModel(),
                             {"a": [None], "b": [1.0]})


# ---------------------------------------------------------------------------
# the CI-feasible scale floor (full scale lives in bench.py zoo)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestZooFloor:
    def test_256_models_one_fleet_bounded_p99(self):
        """>= 256 distinct versioned models behind one fleet under
        mixed traffic: availability >= 99%, bounded p99, evictions
        under a 64-model cache with zero availability loss, zero
        steady-state recompiles on a resident jitted model."""
        import jax
        from mmlspark_tpu.models.networks import build_network
        from mmlspark_tpu.models.tpu_model import TPUModel
        from mmlspark_tpu.serving.fleet import json_scoring_pipeline

        zoo = ModelZoo(max_resident=64, memory_probe=None,
                       label_cardinality_cap=64)
        n_models = 256
        for i in range(n_models):
            zoo.register_factory(f"m{i:03d}", f"v{i % 4}",
                                 (lambda i=i: echo_stage(f"m{i:03d}")))
        # one REAL jitted model rides along: the recompile guard
        module = build_network({"type": "mlp", "features": [16],
                                "num_classes": 4})
        x0 = np.zeros((1, 8), np.float32)
        weights = {"params": module.init(jax.random.PRNGKey(0),
                                         x0)["params"]}
        model = TPUModel(
            modelFn=lambda w, ins: module.apply(
                {"params": w["params"]}, list(ins.values())[0]),
            weights=weights, inputCol="features", outputCol="scores",
            batchSize=8, computeDtype="float32")
        zoo.register_factory(
            "jitted", "v1", lambda: json_scoring_pipeline(model),
            metadata={"warmup_example": {"features": x0}})
        zoo.pin("jitted")       # resident model: must never recompile
        zoo.get("jitted", timeout=120)
        misses_warm = int(model.jit_cache_misses)

        fleet = ServingFleet(n_engines=2, base_port=19800, zoo=zoo,
                             batch_size=8, max_wait_ms=2.0,
                             tracing=False)
        results = []
        lock = threading.Lock()
        rng = np.random.default_rng(3)
        picks = rng.integers(0, n_models, size=960)

        def client(tid):
            tenant = f"t{tid % 3}"
            for i in range(60):
                idx = picks[tid * 60 + i]
                if i % 10 == 5:
                    model_key, payload = "jitted", {
                        "features": [0.1] * 8}
                else:
                    model_key = f"m{idx:03d}"
                    payload = {"x": int(idx)}
                t0 = time.perf_counter()
                try:
                    body = fleet.post(payload, model=model_key,
                                      tenant=tenant, timeout=120)
                    with lock:
                        results.append(
                            (model_key, 200, body,
                             time.perf_counter() - t0))
                except urllib.error.HTTPError as e:
                    with lock:
                        results.append((model_key, e.code, None,
                                        time.perf_counter() - t0))
                except ServingUnavailable:
                    with lock:
                        results.append((model_key, 503, None,
                                        time.perf_counter() - t0))

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(16)]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=600)
            assert len(results) == 960
            ok = [r for r in results if r[1] == 200]
            availability = len(ok) / len(results)
            assert availability >= 0.99, f"availability {availability}"
            distinct = {m for m, c, _b, _l in ok if m.startswith("m")}
            assert len(distinct) >= 200       # the zoo really multiplexed
            for model_key, _c, body, _l in ok:
                if model_key == "jitted":
                    assert "prediction" in body
                else:
                    assert body["served_by"] == model_key
            lat = sorted(r[3] for r in ok)
            p99 = lat[int(0.99 * len(lat))]
            # CI-feasible bound on this throttled 2-core container;
            # bench.py zoo measures the real number
            assert p99 < 30.0, f"p99 {p99:.2f}s"
            assert zoo.evictions > 0
            assert zoo.evictions_with_outstanding == 0
            # zero steady-state recompiles on the resident jitted model
            assert int(model.jit_cache_misses) == misses_warm
        finally:
            fleet.stop_all()
            zoo.close()
