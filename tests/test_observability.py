"""Observability layer tests: span/trace model, tail-sampled buffer,
Chrome trace-event export, end-to-end serving traces (queue_wait ->
decode -> device -> respond covering the request wall), Prometheus
text-exposition grammar, structured JSON logging, the metrics
thread-safety hammer, and the metrics()-vs-swap() consistent-snapshot
regression.
"""

import json
import logging
import math
import re
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from mmlspark_tpu.core.metrics import DriftMonitor, LatencyHistogram
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.core.trace import (
    Span, TraceBuffer, Tracer, current_span, to_chrome_trace, use_span,
)
from mmlspark_tpu.serving.server import serve_model
from mmlspark_tpu.stages.basic import Lambda


# ---------------------------------------------------------------------------
# Prometheus text-format grammar validator (format 0.0.4)
# ---------------------------------------------------------------------------

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_LABEL = r"[a-zA-Z_][a-zA-Z0-9_]*"


def _parse_labels(s):
    labels = {}
    i = 0
    while i < len(s):
        m = re.match(_LABEL, s[i:])
        assert m, f"bad label name at {s[i:]!r}"
        name = m.group(0)
        i += m.end()
        assert s[i] == "=", f"expected '=' at {s[i:]!r}"
        i += 1
        assert s[i] == '"', f"expected opening quote at {s[i:]!r}"
        i += 1
        val = []
        while True:
            c = s[i]
            if c == "\\":
                nxt = s[i + 1]
                assert nxt in '\\"n', f"illegal escape \\{nxt}"
                val.append({"\\": "\\", '"': '"', "n": "\n"}[nxt])
                i += 2
            elif c == '"':
                i += 1
                break
            else:
                assert c != "\n", "raw newline in label value"
                val.append(c)
                i += 1
        labels[name] = "".join(val)
        if i < len(s):
            assert s[i] == ",", f"expected ',' at {s[i:]!r}"
            i += 1
    return labels


def validate_prom_text(text):
    """Grammar-check one exposition: HELP/TYPE lines, sample syntax,
    label escaping, histogram bucket ordering/monotonicity and the
    +Inf == _count contract. Returns (types, samples)."""
    types, helps, samples = {}, set(), []
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            m = re.match(rf"# HELP ({_NAME}) .*$", line)
            assert m, f"bad HELP line: {line!r}"
            helps.add(m.group(1))
            continue
        if line.startswith("# TYPE "):
            m = re.match(
                rf"# TYPE ({_NAME}) "
                r"(counter|gauge|histogram|summary|untyped)$", line)
            assert m, f"bad TYPE line: {line!r}"
            types[m.group(1)] = m.group(2)
            continue
        assert not line.startswith("#"), f"unknown comment: {line!r}"
        m = re.match(
            rf"^({_NAME})(?:\{{(.*)\}})? (\S+)(?: (\d+))?$", line)
        assert m, f"bad sample line: {line!r}"
        name, labelstr, value = m.group(1), m.group(2), m.group(3)
        labels = _parse_labels(labelstr) if labelstr else {}
        if value == "+Inf":
            v = math.inf
        elif value == "-Inf":
            v = -math.inf
        else:
            v = float(value)   # raises on malformed numbers
        samples.append((name, labels, v))

    def family(name):
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in types:
                return name[:-len(suffix)]
        return name

    for name, labels, _v in samples:
        base = family(name)
        assert base in types, f"sample {name} has no # TYPE"
        assert base in helps, f"sample {name} has no # HELP"

    for hist_name in [n for n, t in types.items() if t == "histogram"]:
        groups, counts = {}, {}
        for name, labels, v in samples:
            if name == hist_name + "_bucket":
                key = tuple(sorted((k, lv) for k, lv in labels.items()
                                   if k != "le"))
                groups.setdefault(key, []).append((labels["le"], v))
            elif name == hist_name + "_count":
                counts[tuple(sorted(labels.items()))] = v
        assert groups, f"histogram {hist_name} has no buckets"
        for key, buckets in groups.items():
            les = [math.inf if le == "+Inf" else float(le)
                   for le, _ in buckets]
            vals = [v for _, v in buckets]
            assert les == sorted(les), \
                f"{hist_name}{key}: le not ascending: {les}"
            assert math.isinf(les[-1]), \
                f"{hist_name}{key}: missing +Inf bucket"
            assert all(a <= b for a, b in zip(vals, vals[1:])), \
                f"{hist_name}{key}: cumulative counts not monotone"
            assert counts.get(key) == vals[-1], \
                f"{hist_name}{key}: _count != +Inf bucket"
    return types, samples


# ---------------------------------------------------------------------------
# span/trace model
# ---------------------------------------------------------------------------


class TestSpanModel:
    def test_ids_unique_and_trace_assembly(self):
        tracer = Tracer(enabled=True)
        tr = tracer.new_trace("request")
        s1 = tracer.start_span("queue_wait", tr)
        s2 = tracer.start_span("device", tr)
        ids = {tr.root.span_id, s1.span_id, s2.span_id}
        assert len(ids) == 3
        assert s1.trace_id == tr.trace_id
        assert s1.parent_id == tr.root.span_id
        assert [s.name for s in tr.spans()] == \
            ["request", "queue_wait", "device"]

    def test_incoming_trace_id_honored_and_clamped(self):
        tracer = Tracer(enabled=True)
        assert tracer.new_trace("r", trace_id="abc-123").trace_id \
            == "abc-123"
        long = "x" * 500
        assert len(tracer.new_trace("r", trace_id=long).trace_id) == 64

    def test_finish_idempotent_and_duration(self):
        tracer = Tracer(enabled=True)
        tr = tracer.new_trace("op", start=100.0)
        tr.root.finish(100.25)
        tr.root.finish(999.0)   # second finish is a no-op
        assert tr.duration_ms == pytest.approx(250.0)
        tracer.finish(tr)
        tracer.finish(tr)       # idempotent: buffered once
        assert tracer.buffer.stats()["added"] == 1

    def test_error_and_links(self):
        tracer = Tracer(enabled=True)
        tr = tracer.new_trace("request")
        span = tracer.start_span("device", tr)
        span.link("t1", "s1").link("t2", "s2")
        span.error("boom").finish()
        assert span.status == "error"
        assert span.attrs["error"] == "boom"
        assert span.links == [("t1", "s1"), ("t2", "s2")]

    def test_current_span_context(self):
        tracer = Tracer(enabled=True)
        tr = tracer.new_trace("op")
        assert current_span() is None
        with use_span(tr.root):
            assert current_span() is tr.root
        assert current_span() is None

    def test_emit_retroactive_span(self):
        tracer = Tracer(enabled=True)
        tr = tracer.new_trace("gbdt.train")
        span = tracer.emit("bin", 10.0, 10.5, trace=tr,
                           attrs={"rows": 7})
        assert span.duration_ms == pytest.approx(500.0)
        assert span.attrs["rows"] == 7
        # standalone emit buffers a single-span trace
        tracer.emit("automl.featurize_fit", time.perf_counter() - 0.01)
        assert tracer.buffer.stats()["added"] == 1

    def test_disabled_tracer_emits_nothing(self):
        tracer = Tracer(enabled=False)
        assert tracer.emit("x", 0.0) is None
        with tracer.trace_block("y") as tr:
            assert tr is None
        assert tracer.buffer.stats()["added"] == 0


class TestTraceBuffer:
    @staticmethod
    def _mk(tracer, dur_ms, error=False):
        tr = tracer.new_trace("t", start=0.0)
        if error:
            tr.root.error()
        tracer.finish(tr, end=dur_ms / 1e3)
        return tr

    def test_capacity_bound(self):
        tracer = Tracer(enabled=True,
                        buffer=TraceBuffer(capacity=32))
        for _ in range(300):
            self._mk(tracer, 1.0)
        stats = tracer.buffer.stats()
        assert stats["added"] == 300
        assert stats["buffered"] <= 32 + 8   # main ring + protected cap

    def test_error_traces_survive_eviction(self):
        tracer = Tracer(enabled=True, buffer=TraceBuffer(capacity=16))
        err = self._mk(tracer, 1.0, error=True)
        for _ in range(200):
            self._mk(tracer, 1.0)
        kept = tracer.buffer.traces()
        assert any(t is err for t in kept), \
            "error trace evicted by bulk traffic"
        assert tracer.buffer.stats()["errors_kept"] == 1

    def test_slow_tail_kept(self):
        tracer = Tracer(enabled=True, buffer=TraceBuffer(
            capacity=16, slow_percentile=90.0))
        for _ in range(64):         # establish the 1 ms baseline
            self._mk(tracer, 1.0)
        slow = self._mk(tracer, 500.0)
        for _ in range(100):        # bulk traffic evicts the main ring
            self._mk(tracer, 1.0)
        assert any(t is slow for t in tracer.buffer.traces()), \
            "slow-percentile trace evicted"
        assert tracer.buffer.stats()["slow_kept"] >= 1

    def test_limit_and_clear(self):
        tracer = Tracer(enabled=True, buffer=TraceBuffer(capacity=64))
        for _ in range(10):
            self._mk(tracer, 1.0)
        assert len(tracer.buffer.traces(limit=3)) == 3
        assert tracer.buffer.traces(limit=0) == []
        tracer.buffer.clear()
        assert tracer.buffer.traces() == []


class TestChromeExport:
    def test_export_structure_and_json_round_trip(self):
        tracer = Tracer(enabled=True)
        tr = tracer.new_trace("request")
        tracer.start_span("device", tr).set("rows", 4).finish()
        tracer.finish(tr)
        payload = to_chrome_trace(tracer.buffer.traces())
        text = json.dumps(payload)       # must be JSON-serializable
        loaded = json.loads(text)
        events = loaded["traceEvents"]
        assert loaded["displayTimeUnit"] == "ms"
        assert len(events) == 2
        for ev in events:
            # the Chrome trace-event contract for complete events
            assert ev["ph"] == "X"
            assert isinstance(ev["name"], str) and ev["name"]
            assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
            assert isinstance(ev["pid"], int)
            assert isinstance(ev["tid"], int)
            assert "trace_id" in ev["args"]

    def test_shared_batch_span_deduped(self):
        tracer = Tracer(enabled=True)
        tr1 = tracer.new_trace("request")
        tr2 = tracer.new_trace("request")
        shared = tracer.start_span("device", tr1)
        shared.link(tr1.trace_id, tr1.root.span_id)
        shared.link(tr2.trace_id, tr2.root.span_id)
        tr2.add(shared)
        shared.finish()
        tracer.finish(tr1)
        tracer.finish(tr2)
        events = to_chrome_trace(tracer.buffer.traces())["traceEvents"]
        assert len([e for e in events if e["name"] == "device"]) == 1
        device = next(e for e in events if e["name"] == "device")
        assert len(device["args"]["links"]) == 2


# ---------------------------------------------------------------------------
# end-to-end serving traces
# ---------------------------------------------------------------------------


def _scoring_pipeline(sleep_s=0.002):
    """A split-pipeline echo scorer (no jax): decode parses JSON on the
    batcher thread, execute 'scores' on the worker — shaped like
    json_scoring_pipeline so the queue_wait/decode/device/respond span
    chain is exercised."""
    def decode(table):
        return [json.loads(r["entity"].decode())["x"]
                for r in table["request"]]

    def execute(table, xs):
        time.sleep(sleep_s)
        return table.with_column("reply", [{"y": v * 2} for v in xs])

    lam = Lambda.apply(lambda t: execute(t, decode(t)))
    lam.prepare_batch = decode
    lam.execute_prepared = execute
    lam.jit_cache_miss_count = lambda: 0
    lam.bucket_for = lambda rows: 8
    return lam


def _post(addr, payload, headers=None, timeout=10):
    req = urllib.request.Request(
        addr, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read()), dict(r.headers)


def _union_coverage(trace):
    """Fraction of the root interval covered by the union of child
    span intervals (shared batch spans count once)."""
    root = trace.root
    ivs = sorted(
        (max(s.start, root.start), min(s.end, root.end))
        for s in trace.spans()
        if s is not root and s.end is not None)
    covered, cur_a, cur_b = 0.0, None, None
    for a, b in ivs:
        if b <= a:
            continue
        if cur_b is None or a > cur_b:
            if cur_b is not None:
                covered += cur_b - cur_a
            cur_a, cur_b = a, b
        else:
            cur_b = max(cur_b, b)
    if cur_b is not None:
        covered += cur_b - cur_a
    dur = root.end - root.start
    return covered / dur if dur > 0 else 0.0


@pytest.fixture()
def traced_engine():
    tracer = Tracer(enabled=True)
    engine = serve_model(_scoring_pipeline(), port=19460, batch_size=8,
                         max_wait_ms=20.0, tracer=tracer, version="v3")
    yield engine, tracer
    engine.stop()


class TestServingTracing:
    def _spray(self, engine, n=16):
        threads = [threading.Thread(
            target=_post, args=(engine.source.address, {"x": i}))
            for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        time.sleep(0.2)

    def test_trace_id_propagation(self, traced_engine):
        engine, tracer = traced_engine
        status, body, headers = _post(
            engine.source.address, {"x": 21},
            headers={"X-Trace-Id": "trace-prop-1"})
        assert status == 200 and body == {"y": 42}
        assert headers.get("X-Trace-Id") == "trace-prop-1"
        time.sleep(0.2)
        ids = [t.trace_id for t in tracer.buffer.traces()]
        assert "trace-prop-1" in ids
        # server-issued ids also flow back to the client
        _, _, headers2 = _post(engine.source.address, {"x": 1})
        assert headers2.get("X-Trace-Id")

    def test_span_chain_covers_request_wall(self, traced_engine):
        """The acceptance bar: spans (queue_wait -> decode -> device ->
        respond) account for >= 90% of the request's measured wall."""
        engine, tracer = traced_engine
        self._spray(engine, 16)
        traces = [t for t in tracer.buffer.traces()
                  if t.root.name == "request" and not t.is_error]
        assert traces, "no completed request traces"
        names_required = {"queue_wait", "decode", "device", "respond"}
        checked = 0
        for tr in traces:
            names = {s.name for s in tr.spans()}
            assert names_required <= names, \
                f"missing spans: {names_required - names}"
            cov = _union_coverage(tr)
            assert cov >= 0.90, (
                f"span chain covers only {cov:.1%} of the request wall "
                f"({[(s.name, round(s.duration_ms, 3)) for s in tr.spans()]})")
            checked += 1
        assert checked >= 16

    def test_batch_join_span_shared_with_version(self, traced_engine):
        engine, tracer = traced_engine
        self._spray(engine, 16)
        traces = [t for t in tracer.buffer.traces()
                  if t.root.name == "request"]
        by_device = {}
        for tr in traces:
            for s in tr.spans():
                if s.name == "device":
                    by_device.setdefault(s.span_id, []).append(tr)
                    assert s.attrs["model_version"] == "v3"
                    assert s.attrs["bucket"] == 8
                    assert "jit_cache_miss" in s.attrs
        # with 16 concurrent requests into batch_size=8 / 20 ms windows,
        # at least one micro-batch joined >1 request
        multi = {sid: trs for sid, trs in by_device.items()
                 if len(trs) > 1}
        assert multi, "no multi-request micro-batch formed"
        for sid, trs in multi.items():
            span = next(s for s in trs[0].spans() if s.span_id == sid)
            assert span.attrs["rows"] == len(trs), \
                "device span rows != joined traces"
            assert len(span.links) == len(trs), \
                "device span must link every joined request root"
            root_ids = {t.root.span_id for t in trs}
            assert {s for _, s in span.links} == root_ids

    def test_error_trace_kept_and_marked(self, traced_engine):
        engine, tracer = traced_engine
        bad = Lambda.apply(lambda t: (_ for _ in ()).throw(
            RuntimeError("kaboom")))
        engine.pipeline = bad
        with pytest.raises(urllib.error.HTTPError):
            _post(engine.source.address, {"x": 1})
        time.sleep(0.2)
        errs = [t for t in tracer.buffer.traces() if t.is_error]
        assert errs, "500 request produced no error trace"
        assert errs[-1].root.attrs.get("http_status", 500) >= 500

    def test_debug_traces_endpoint(self, traced_engine):
        engine, tracer = traced_engine
        self._spray(engine, 8)
        raw = urllib.request.urlopen(
            engine.source.address + "/debug/traces", timeout=5).read()
        payload = json.loads(raw)
        assert payload["displayTimeUnit"] == "ms"
        assert len(payload["traceEvents"]) > 0
        limited = json.loads(urllib.request.urlopen(
            engine.source.address + "/debug/traces?limit=2",
            timeout=5).read())
        # count ROOT events: shared batch spans carry their primary
        # trace's id, so counting distinct arg ids would over-count
        roots = [e for e in limited["traceEvents"]
                 if e["name"] == "request"]
        assert 0 < len(roots) <= 2

    def test_tracing_disabled_is_silent(self):
        engine = serve_model(_scoring_pipeline(), port=19480,
                             batch_size=8, tracing=False)
        try:
            status, body, headers = _post(engine.source.address, {"x": 2})
            assert status == 200 and body == {"y": 4}
            assert "X-Trace-Id" not in headers
            assert engine.traces() == []
            payload = json.loads(urllib.request.urlopen(
                engine.source.address + "/debug/traces",
                timeout=5).read())
            assert payload["traceEvents"] == []
        finally:
            engine.stop()


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------


class TestMetricsExposition:
    def test_engine_metrics_endpoint_grammar(self, traced_engine):
        engine, _tracer = traced_engine
        # drift monitor riding the pipeline -> drift gauges on /metrics
        monitor = DriftMonitor.from_matrix(
            np.random.default_rng(0).normal(size=(64, 4)))
        monitor.observe(np.random.default_rng(1).normal(size=(32, 4)))
        engine._active.pipeline.drift_monitor = monitor
        for _ in range(4):
            _post(engine.source.address, {"x": 5})
        # make sure the process-wide phase families have content
        from mmlspark_tpu.core import metrics as MC
        MC.gbdt_train_histograms()["bin"].observe(3.0)
        MC.automl_histograms()["tune_trials"].observe(8.0)
        raw = urllib.request.urlopen(
            engine.source.address + "/metrics", timeout=5)
        assert raw.headers.get("Content-Type", "").startswith(
            "text/plain")
        text = raw.read().decode()
        types, samples = validate_prom_text(text)
        names = {n for n, _l, _v in samples}
        for required in (
                "serving_requests_answered_total",
                "serving_batches_processed_total",
                "serving_swaps_completed_total",
                "serving_swaps_rolled_back_total",
                "serving_model_info",
                "serving_queue_wait_ms_bucket",
                "serving_pipeline_ms_bucket",
                "serving_jit_cache_misses_total",
                "serving_drift_max_abs_mean_delta_sigma",
                "gbdt_train_phase_ms_bucket",
                "automl_phase_ms_bucket",
                "trace_buffer_traces",
        ):
            assert required in names, f"/metrics missing {required}"
        assert types["serving_queue_wait_ms"] == "histogram"
        info = next(l for n, l, _v in samples
                    if n == "serving_model_info")
        assert info["version"] == "v3"
        assert info["swap_state"] == "idle"
        # the trace_* series must report the ENGINE's tracer buffer
        # (this fixture uses an isolated Tracer, not the global one)
        added = next(v for n, _l, v in samples
                     if n == "trace_traces_added_total")
        assert added > 0

    def test_zoo_metrics_grammar_and_cardinality_cap_at_256(self):
        """The multi-model plane's families pass the grammar validator,
        and the per-model label space stays HARD-CAPPED with 256
        registered models: at most ``label_cardinality_cap`` named
        latency series (+ ``_other``), at most that many
        ``serving_model_info{model=...}`` rows, while
        ``serving_zoo_*`` state gauges still count all 256."""
        from mmlspark_tpu.serving import ModelZoo, ServingEngine
        from mmlspark_tpu.serving.server import HTTPSource
        cap = 64
        zoo = ModelZoo(max_resident=16, memory_probe=None,
                       label_cardinality_cap=cap)
        for i in range(256):
            zoo.register_factory(
                f"m{i:03d}", f"v{i % 8}",
                (lambda i=i: _scoring_pipeline()))
        # a few models actually resident + latency observed for ALL
        # 256 names (the worst-case label pressure)
        for i in range(4):
            zoo.get(f"m{i:03d}")
        for i in range(256):
            zoo.observe_latency(f"m{i:03d}", 1.0 + i % 7)
        source = HTTPSource(port=19690)
        engine = ServingEngine(source, zoo=zoo, tracing=False).start()
        try:
            text = urllib.request.urlopen(
                engine.source.address + "/metrics",
                timeout=5).read().decode()
        finally:
            engine.stop()
            zoo.close()
        types, samples = validate_prom_text(text)
        assert types["serving_model_latency_ms"] == "histogram"
        lat_models = {l["model"] for n, l, _v in samples
                      if n == "serving_model_latency_ms_bucket"}
        assert "_other" in lat_models
        assert len(lat_models) <= cap + 1, len(lat_models)
        info_models = {l["model"] for n, l, _v in samples
                       if n == "serving_model_info" and "model" in l}
        assert 0 < len(info_models) <= cap
        # resident rows always have labeled series (they're the ones
        # an operator is debugging)
        for i in range(4):
            assert f"m{i:03d}" in info_models
        # the full population is still countable — by state, uncapped
        by_state = {l["state"]: v for n, l, v in samples
                    if n == "serving_zoo_models"}
        assert sum(by_state.values()) == 256
        registered = next(v for n, _l, v in samples
                          if n == "serving_zoo_registered_models")
        assert registered == 256

    def test_fleet_metrics_text_grammar(self):
        from mmlspark_tpu.serving.fleet import ServingFleet
        tracer = Tracer(enabled=True)
        fleet = ServingFleet(_scoring_pipeline(), n_engines=2,
                             base_port=19500, batch_size=8,
                             tracer=tracer)
        try:
            for i in range(6):
                fleet.post({"x": i})
            text = fleet.metrics_text()
        finally:
            fleet.stop_all()
        types, samples = validate_prom_text(text)
        names = {n for n, _l, _v in samples}
        assert "serving_fleet_transport_errors_total" in names
        engines = {l.get("engine") for n, l, _v in samples
                   if n == "serving_requests_answered_total"}
        assert engines == {"0", "1"}
        # fleet traces: the shared tracer saw both engines' traffic
        chrome = fleet.traces()
        assert len(chrome["traceEvents"]) > 0

    def test_label_escaping(self):
        from mmlspark_tpu.core.prometheus import PromRenderer
        r = PromRenderer()
        r.info("weird_info", "escaping check",
               {"v": 'a"b\\c\nd', "ok": "plain"})
        types, samples = validate_prom_text(r.render())
        assert samples[0][1]["v"] == 'a"b\\c\nd'

    def test_histogram_rendering_exact(self):
        from mmlspark_tpu.core.prometheus import PromRenderer
        hist = LatencyHistogram()
        for v in (0.04, 0.6, 3.0, 3.0, 1e9):
            hist.observe(v)
        r = PromRenderer()
        r.histogram("lat_ms", "check", hist)
        types, samples = validate_prom_text(r.render())
        buckets = [(l["le"], v) for n, l, v in samples
                   if n == "lat_ms_bucket"]
        assert buckets[0] == ("0.05", 1)
        assert buckets[-1] == ("+Inf", 5)
        total = next(v for n, _l, v in samples if n == "lat_ms_count")
        assert total == 5
        s = next(v for n, _l, v in samples if n == "lat_ms_sum")
        assert s == pytest.approx(0.04 + 0.6 + 6.0 + 1e9)


# ---------------------------------------------------------------------------
# structured logging
# ---------------------------------------------------------------------------


class TestStructuredLogging:
    def test_json_formatter_plain_record(self):
        from mmlspark_tpu.core.logging_utils import JsonFormatter
        rec = logging.LogRecord("mmlspark_tpu.serving", logging.WARNING,
                                __file__, 1, "shed %d rows", (7,), None)
        out = json.loads(JsonFormatter().format(rec))
        assert out["msg"] == "shed 7 rows"
        assert out["level"] == "WARNING"
        assert out["logger"] == "mmlspark_tpu.serving"
        assert "\n" not in JsonFormatter().format(rec)
        assert "trace_id" not in out

    def test_json_formatter_carries_trace_and_version(self):
        from mmlspark_tpu.core.logging_utils import JsonFormatter
        tracer = Tracer(enabled=True)
        tr = tracer.new_trace("request", trace_id="log-corr-1")
        span = tracer.start_span("device", tr)
        span.set("model_version", "v12")
        rec = logging.LogRecord("mmlspark_tpu.serving", logging.INFO,
                                __file__, 1, "batch ok", (), None)
        with use_span(span):
            out = json.loads(JsonFormatter().format(rec))
        assert out["trace_id"] == "log-corr-1"
        assert out["span_id"] == span.span_id
        assert out["model_version"] == "v12"

    def test_log_format_config_switch(self):
        from mmlspark_tpu.core import config
        from mmlspark_tpu.core.logging_utils import (
            JsonFormatter, configure,
        )
        root = logging.getLogger("mmlspark_tpu")

        def owned():
            # configure() only restyles handlers it created — an
            # embedder's handlers keep their own formatters
            return [h for h in root.handlers
                    if getattr(h, "_mmlspark_tpu_owned", False)]

        foreign = logging.StreamHandler()
        foreign_fmt = logging.Formatter("APP %(message)s")
        foreign.setFormatter(foreign_fmt)
        root.addHandler(foreign)
        config.set_config("log_format", "json")
        try:
            configure(force=True)
            assert owned(), "configure() created no owned handler"
            assert all(isinstance(h.formatter, JsonFormatter)
                       for h in owned())
            assert foreign.formatter is foreign_fmt, \
                "embedder's formatter was clobbered"
        finally:
            root.removeHandler(foreign)
            config.set_config("log_format", "text")
            configure(force=True)
        assert not any(isinstance(h.formatter, JsonFormatter)
                       for h in owned())

    def test_json_formatter_exception_one_line(self):
        import sys
        from mmlspark_tpu.core.logging_utils import JsonFormatter
        try:
            raise ValueError("inner")
        except ValueError:
            rec = logging.LogRecord("mmlspark_tpu", logging.ERROR,
                                    __file__, 1, "failed", (),
                                    sys.exc_info())
        line = JsonFormatter().format(rec)
        assert "\n" not in line
        assert "inner" in json.loads(line)["exc"]


# ---------------------------------------------------------------------------
# thread-safety hammer (satellite: core/metrics audit)
# ---------------------------------------------------------------------------


class TestMetricsThreadSafety:
    N_THREADS, N_OBS = 8, 4000

    def _hammer(self, fn):
        threads = [threading.Thread(target=fn, args=(t,))
                   for t in range(self.N_THREADS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def test_histogram_concurrent_observe_no_lost_updates(self):
        hist = LatencyHistogram()

        def work(seed):
            for i in range(self.N_OBS):
                hist.observe(float((i + seed) % 97))

        self._hammer(work)
        snap = hist.snapshot()
        total = self.N_THREADS * self.N_OBS
        assert snap["count"] == total
        assert sum(snap["counts"]) == total
        # all values are small integers -> the f64 sum is exact
        expected = sum(float((i + s) % 97) for s in range(self.N_THREADS)
                       for i in range(self.N_OBS))
        assert snap["sum"] == expected

    def test_snapshot_internally_consistent_under_load(self):
        hist = LatencyHistogram()
        stop = threading.Event()
        bad = []

        def reader():
            while not stop.is_set():
                snap = hist.snapshot()
                if sum(snap["counts"]) != snap["count"]:
                    bad.append(snap)
                summary = hist.summary()
                if summary.get("count") and summary["p50"] > \
                        summary["max"] + 1e-9:
                    bad.append(summary)

        rt = threading.Thread(target=reader)
        rt.start()

        def work(seed):
            for i in range(self.N_OBS):
                hist.observe(float(i % 53))

        self._hammer(work)
        stop.set()
        rt.join()
        assert not bad, f"inconsistent snapshots: {bad[:3]}"

    def test_concurrent_merge_and_reset(self):
        src = [LatencyHistogram() for _ in range(self.N_THREADS)]
        agg = LatencyHistogram()

        def work(t):
            for i in range(self.N_OBS):
                src[t].observe(1.0)

        self._hammer(work)
        threads = [threading.Thread(target=agg.merge, args=(h,))
                   for h in src]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert agg.snapshot()["count"] == self.N_THREADS * self.N_OBS
        agg.reset()
        assert agg.snapshot()["count"] == 0

    def test_drift_monitor_concurrent_observe(self):
        monitor = DriftMonitor(np.zeros(4), np.ones(4))
        rows_per = 50

        def work(seed):
            rng = np.random.default_rng(seed)
            for _ in range(rows_per):
                monitor.observe(rng.normal(size=(4, 4)))

        self._hammer(work)
        snap = monitor.snapshot()
        assert snap["rows"] == self.N_THREADS * rows_per * 4


# ---------------------------------------------------------------------------
# satellite fix: consistent metrics()/healthz snapshot under swap()
# ---------------------------------------------------------------------------


class TestSwapMetricsConsistency:
    def test_snapshot_never_tears_under_swap_loop(self):
        """Hammer metrics() while swaps cut over: in every snapshot the
        (model_version, swap_state, swaps_completed) triple must be
        mutually consistent — version vK with state idle implies
        exactly K completed swaps; draining implies K-1."""
        from mmlspark_tpu.serving.lifecycle import CanaryPolicy

        def echo(table):
            return table.with_column(
                "reply", [b"ok" for _ in table["id"]])

        engine = serve_model(Lambda.apply(echo), port=19520,
                             batch_size=4, tracing=False, version="v0")
        violations = []
        stop = threading.Event()

        def poll():
            while not stop.is_set():
                m = engine.metrics()
                state = m["swap_state"]
                k = int(m["model_version"][1:])
                done = m["swaps_completed"]
                if state in ("idle", "warming", "canary") and done != k:
                    violations.append((state, k, done))
                elif state == "draining" and done != k - 1:
                    violations.append((state, k, done))

        pollers = [threading.Thread(target=poll) for _ in range(3)]
        for t in pollers:
            t.start()
        try:
            policy = CanaryPolicy(fraction=0.0, drain_timeout_s=1.0)
            for i in range(1, 120):
                res = engine.swap(Lambda.apply(echo), f"v{i}",
                                  policy=policy)
                assert res.completed, res
        finally:
            stop.set()
            for t in pollers:
                t.join()
            engine.stop()
        assert not violations, \
            f"{len(violations)} torn snapshots, e.g. {violations[:5]}"


# ---------------------------------------------------------------------------
# training-side traces
# ---------------------------------------------------------------------------


class TestTrainingTraces:
    def test_gbdt_train_emits_phase_spans(self):
        from mmlspark_tpu.core import trace as trace_mod
        from mmlspark_tpu.gbdt.booster import train
        tracer = Tracer(enabled=True)
        trace_mod.set_tracer(tracer)
        try:
            rng = np.random.default_rng(0)
            X = rng.normal(size=(400, 5)).astype(np.float32)
            y = (X[:, 0] > 0).astype(np.float64)
            train({"objective": "binary", "num_iterations": 3,
                   "num_leaves": 7, "max_bin": 15}, X, y)
        finally:
            trace_mod.set_tracer(None)
        traces = [t for t in tracer.buffer.traces()
                  if t.root.name == "gbdt.train"]
        assert traces, "train() produced no trace"
        names = {s.name for s in traces[-1].spans()}
        assert "bin" in names and "fetch" in names
        assert "first_iter" in names or "boost" in names
        assert "bin_path" in traces[-1].root.attrs

    def test_automl_featurize_and_tune_emit_spans(self):
        from mmlspark_tpu.automl.featurize import Featurize
        from mmlspark_tpu.core import trace as trace_mod
        tracer = Tracer(enabled=True)
        trace_mod.set_tracer(tracer)
        try:
            rng = np.random.default_rng(0)
            table = DataTable({
                "a": rng.normal(size=200),
                "color": [f"c{i % 3}" for i in range(200)]})
            model = Featurize(featureColumns=["a", "color"]).fit(table)
            model.transform(table)
        finally:
            trace_mod.set_tracer(None)
        names = [t.root.name for t in tracer.buffer.traces()]
        assert "automl.featurize_fit" in names
        assert "automl.featurize_transform" in names

    def test_learner_fit_emits_step_spans(self):
        from mmlspark_tpu.core import trace as trace_mod
        from mmlspark_tpu.models.learner import TPULearner
        tracer = Tracer(enabled=True)
        trace_mod.set_tracer(tracer)
        try:
            rng = np.random.default_rng(0)
            x = rng.normal(size=(64, 8)).astype(np.float32)
            y = rng.integers(0, 2, 64).astype(np.int64)
            learner = TPULearner(
                networkSpec={"type": "mlp", "features": [8],
                             "num_classes": 2},
                epochs=1, batchSize=32, logEvery=1000,
                computeDtype="float32", memoryStatsEvery=1,
                traceAnnotations=True)
            learner.fit(DataTable({"features": x, "label": y}))
        finally:
            trace_mod.set_tracer(None)
        fits = [t for t in tracer.buffer.traces()
                if t.root.name == "learner.fit"]
        assert fits, "fit() produced no trace"
        steps = [s for s in fits[-1].spans() if s.name == "learner.step"]
        assert len(steps) == 2    # 64 rows / batch 32
        assert fits[-1].root.attrs["feed"] == "host"
        # CPU backends report no memory stats; the sampler must be a
        # silent no-op there (samples appear on real accelerators)
        assert isinstance(learner.memory_samples, list)
