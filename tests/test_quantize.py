"""Int8 post-training quantization + AOT serving executables.

Accuracy floors pin int8-vs-f32 prediction agreement on the CSV-harness
datasets (sklearn breast-cancer / digits / diabetes — the same real
datasets tests/test_benchmarks.py pins its metric floors on), AOT
artifacts must reproduce the in-process JIT path bit-for-bit per bucket
with ZERO jit traces at request time, and an f32 -> int8 rolling swap
under load must keep ``jit_cache_misses`` flat and availability >= 99%
while the precision/aot labels stay auditable end to end
(docs/quantized_inference.md).
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from mmlspark_tpu.core.table import DataTable


def _mlp_and_weights(features, num_classes, dim, seed=0):
    import jax
    from mmlspark_tpu.models.networks import build_network
    module = build_network({"type": "mlp", "features": list(features),
                            "num_classes": num_classes})
    x0 = np.zeros((1, dim), np.float32)
    return module, module.init(jax.random.PRNGKey(seed), x0)


def _softmax(z):
    e = np.exp(z - z.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


class TestInt8Primitives:
    def test_per_channel_scales_and_roundtrip(self):
        from mmlspark_tpu.core.quantize import (
            per_channel_scales, quantize_weight,
        )
        rng = np.random.default_rng(0)
        W = rng.normal(size=(32, 5)) * np.array([1.0, 0.1, 10.0, 1e-30, 3.0])
        s = per_channel_scales(W)
        assert s.shape == (5,)
        assert (s > 0).all()         # dead channel clamped, not zero
        wq, ws = quantize_weight(W)
        assert wq.dtype == np.int8
        assert np.abs(wq).max() <= 127
        # dequantized weights within half a quantization step
        err = np.abs(wq.astype(np.float64) * ws - W)
        assert (err <= ws * 0.5 + 1e-12).all()

    def test_int8_matmul_device_matches_host_mirror(self):
        """Integer accumulation is exact, so the jitted device kernel
        and the numpy host mirror must agree bit-for-bit."""
        import jax
        from mmlspark_tpu.core.quantize import (
            act_scale, int8_matmul, int8_matmul_host, quantize_weight,
        )
        rng = np.random.default_rng(1)
        X = rng.normal(size=(64, 16)).astype(np.float32)
        W = rng.normal(size=(16, 7))
        wq, ws = quantize_weight(W)
        xs = act_scale(np.abs(X).max())
        dev = np.asarray(jax.jit(int8_matmul)(X, wq, xs, ws))
        host = int8_matmul_host(X, wq, xs, ws)
        assert np.array_equal(dev, host)
        # and it approximates the f32 matmul
        rel = np.abs(dev - X @ W).max() / np.abs(X @ W).max()
        assert rel < 0.05, rel

    def test_int8_dot_lowers_to_integer_matmul(self):
        """The kernel must lower as an int8 x int8 -> int32 dot_general
        (the MXU integer path), not a dequantize-then-f32-matmul."""
        import jax
        import jax.numpy as jnp
        from mmlspark_tpu.core.quantize import int8_matmul
        txt = jax.jit(int8_matmul).lower(
            jnp.zeros((8, 4)), jnp.zeros((4, 3), jnp.int8),
            jnp.float32(0.1), jnp.zeros((3,))).as_text()
        assert "tensor<8x4xi8>" in txt and "tensor<8x3xi32>" in txt

    def test_nan_rows_propagate_not_corrupt(self):
        """A NaN feature must yield NaN output from the int8 kernel —
        exactly like the f32 oracle — never a confident finite score
        (an int accumulator can't carry NaN; the epilogue re-injects)."""
        import jax
        from mmlspark_tpu.core.quantize import (
            act_scale, int8_matmul, int8_matmul_host, quantize_weight,
        )
        rng = np.random.default_rng(2)
        X = rng.normal(size=(8, 4)).astype(np.float32)
        X[2, 1] = np.nan
        wq, ws = quantize_weight(rng.normal(size=(4, 3)))
        xs = act_scale(1.0)
        for out in (np.asarray(jax.jit(int8_matmul)(X, wq, xs, ws)),
                    int8_matmul_host(X, wq, xs, ws)):
            assert np.isnan(out[2]).all(), out[2]
            assert np.isfinite(out[[0, 1, 3, 4, 5, 6, 7]]).all()

    def test_calibrator_percentile_and_thread_safety(self):
        from mmlspark_tpu.core.quantize import ActivationCalibrator
        cal = ActivationCalibrator(percentile=99.0)
        x = np.zeros(1000)
        x[-1] = 100.0               # outlier the percentile clips
        cal.observe("a", x)
        assert cal.amax()["a"] < 100.0
        exact = ActivationCalibrator()
        threads = [threading.Thread(
            target=lambda i=i: exact.observe("a", np.full(10, float(i))))
            for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert exact.amax()["a"] == 15.0


class TestAccuracyFloors:
    """Int8-vs-f32 agreement on the CSV-harness datasets: >= 99.5%
    top-1 agreement, bounded probability max-abs-err (idle-host
    measurements: breast-cancer 100% / 0.077, digits MLP 99.94% /
    0.079, diabetes max-rel-err 0.8%)."""

    def test_logistic_breast_cancer_agreement(self):
        from sklearn.datasets import load_breast_cancer
        from mmlspark_tpu.models.linear import TPULogisticRegression
        X, y = load_breast_cancer(return_X_y=True)
        t = DataTable({"features": X.astype(np.float64),
                       "label": y.astype(np.float64)})
        m = TPULogisticRegression(maxIter=150).fit(t)
        q = m.quantize(t)
        assert q.get("precision") == "int8"
        assert m.get("precision") == "f32"   # oracle untouched
        pf, pq = m.transform(t), q.transform(t)
        agree = (np.asarray(pf["prediction"])
                 == np.asarray(pq["prediction"])).mean()
        assert agree >= 0.995, agree
        perr = np.abs(np.asarray(pf["probability"])
                      - np.asarray(pq["probability"])).max()
        assert perr <= 0.15, perr

    def test_mlp_digits_agreement(self):
        import jax
        import jax.numpy as jnp
        import optax
        from sklearn.datasets import load_digits
        from mmlspark_tpu.models.networks import build_network
        from mmlspark_tpu.models.tpu_model import TPUModel
        X, y = load_digits(return_X_y=True)
        X = (X / 16.0).astype(np.float32)
        module = build_network({"type": "mlp", "features": [64, 32],
                                "num_classes": 10})
        params = module.init(jax.random.PRNGKey(0), X[:1])
        opt = optax.adam(1e-2)
        state = opt.init(params)

        @jax.jit
        def step(params, state, xb, yb):
            def loss(p):
                return optax.softmax_cross_entropy_with_integer_labels(
                    module.apply(p, xb), yb).mean()
            up, state2 = opt.update(jax.grad(loss)(params), state)
            return optax.apply_updates(params, up), state2

        xb, yb = jnp.asarray(X), jnp.asarray(y)
        for _ in range(60):
            params, state = step(params, state, xb, yb)
        m = TPUModel.from_flax(module, params, inputCol="features",
                               outputCol="scores", batchSize=256)
        q = m.quantize({"features": X[:256]})
        t = DataTable({"features": X})
        sf = np.asarray(m.transform(t)["scores"])
        sq = np.asarray(q.transform(t)["scores"])
        assert (sf.argmax(-1) == y).mean() >= 0.97   # real model, not noise
        agree = (sf.argmax(-1) == sq.argmax(-1)).mean()
        assert agree >= 0.995, agree
        assert np.abs(_softmax(sf) - _softmax(sq)).max() <= 0.15

    def test_linear_regression_diabetes_error_bound(self):
        from sklearn.datasets import load_diabetes
        from mmlspark_tpu.models.linear import TPULinearRegression
        X, y = load_diabetes(return_X_y=True)
        t = DataTable({"features": X, "label": y})
        m = TPULinearRegression(maxIter=200).fit(t)
        q = m.quantize(t)
        pf = np.asarray(m.transform(t)["prediction"])
        pq = np.asarray(q.transform(t)["prediction"])
        rel = np.abs(pf - pq).max() / np.abs(pf).max()
        assert rel <= 0.03, rel

    def test_quantized_model_save_load_roundtrip(self, tmp_path):
        """Quantized models must survive persistence (the lifecycle
        refresh flows save/load models): int8 arrays, scales, and the
        precision param all round-trip; predictions identical."""
        from sklearn.datasets import load_breast_cancer
        from mmlspark_tpu.core.serialize import load_stage, save_stage
        from mmlspark_tpu.models.linear import TPULogisticRegression
        X, y = load_breast_cancer(return_X_y=True)
        t = DataTable({"features": X, "label": y.astype(np.float64)})
        q = TPULogisticRegression(maxIter=50).fit(t).quantize(t)
        d = str(tmp_path / "qmodel")
        save_stage(q, d)
        q2 = load_stage(d)
        assert q2.get("precision") == "int8"
        assert q2.get("weights")["wq"].dtype == np.int8
        assert np.array_equal(np.asarray(q.transform(t)["prediction"]),
                              np.asarray(q2.transform(t)["prediction"]))

    def test_quantize_requires_flax_or_dense(self):
        from mmlspark_tpu.models.linear import TPULogisticRegressionModel
        from mmlspark_tpu.models.tpu_model import TPUModel
        m = TPUModel.from_fn(lambda w, ins: list(ins.values())[0],
                             {"w": np.ones(1)}, inputCol="x")
        with pytest.raises(ValueError, match="flax"):
            m.quantize({"x": np.ones((4, 2), np.float32)})
        sparse_model = TPULogisticRegressionModel(
            weights={"W": np.ones((4, 2)), "b": np.zeros(2)})
        with pytest.raises(ValueError, match="dense"):
            sparse_model.quantize(DataTable({"features": np.ones((4, 4))}))


class TestFusedQuantizedPipeline:
    def _fitted(self, n=4000, maxiter=60):
        from mmlspark_tpu.core.stage import Pipeline
        from mmlspark_tpu.models.linear import TPULogisticRegression
        from mmlspark_tpu.stages.dataprep import StandardScaler
        rng = np.random.default_rng(0)
        X = rng.normal(size=(n, 12))
        y = (X[:, 0] - 0.5 * X[:, 3]
             + 0.2 * rng.normal(size=n) > 0).astype(np.float64)
        t = DataTable({"features": X, "label": y})
        pm = Pipeline(stages=[
            StandardScaler(inputCol="features", outputCol="features"),
            TPULogisticRegression(featuresCol="features",
                                  labelCol="label", maxIter=maxiter),
        ]).fit(t)
        return pm, t

    def test_quantized_fused_bit_identical_to_staged_and_accurate(self):
        pm, t = self._fitted()
        fused = pm.fused(batch_size=64)
        qfused = fused.quantize(t.slice(0, 512))
        assert fused.precision == "f32"
        assert qfused.precision == "int8"
        out_q = qfused.transform(t)
        out_staged = qfused.transform_staged(t)
        # the PR 9 numerics contract holds for int8 segments too:
        # fused == stage-at-a-time bit-identical
        for c in ("rawPrediction", "probability", "prediction"):
            assert np.array_equal(np.asarray(out_q[c]),
                                  np.asarray(out_staged[c])), c
        out_f = fused.transform(t)
        agree = (np.asarray(out_f["prediction"])
                 == np.asarray(out_q["prediction"])).mean()
        assert agree >= 0.99, agree

    def test_quantized_serving_discipline(self):
        """Buckets, warmup, monotone jit_cache_misses, and the
        precision label survive quantization."""
        pm, t = self._fitted(n=512, maxiter=20)
        fused = pm.fused(batch_size=64)
        qfused = fused.quantize(t.slice(0, 128))
        assert qfused.bucket_sizes() == fused.bucket_sizes()
        compiles = qfused.warmup(t.slice(0, 1))
        assert compiles > 0
        before = qfused.jit_cache_misses
        qfused.transform(t.slice(0, 64))
        assert qfused.jit_cache_misses == before, \
            "steady-state quantized transform recompiled"
        assert qfused.metrics()["precision"] == "int8"

    def test_percentile_forwards_to_stage_hooks(self):
        """fused.quantize(calib, percentile=...) must reach the stage
        calibrators: a tighter clip percentile yields a smaller
        activation scale than the exact-max default."""
        pm, t = self._fitted(n=512, maxiter=10)
        fused = pm.fused(batch_size=64)
        # make the clip percentile matter: one outlier row
        X = np.asarray(t["features"]).copy()
        X[0] *= 50.0
        spiky = DataTable({"features": X, "label": t["label"]})
        exact = fused.quantize(spiky)
        clipped = fused.quantize(spiky, percentile=99.0)
        s_exact = exact.stages[-1].get("weights")["x_scale"]
        s_clip = clipped.stages[-1].get("weights")["x_scale"]
        assert s_clip < s_exact, (s_clip, s_exact)

    def test_serving_scorer_warmup_records_histogram(self):
        """The fused serving scorer's warmup must land per-bucket
        samples in model_warmup_ms too (the shared core/warmup.py
        loop), not just the batch-path warmups."""
        from mmlspark_tpu.core import metrics as MC
        from mmlspark_tpu.serving.fleet import json_scoring_pipeline
        pm, t = self._fitted(n=256, maxiter=10)
        stage = json_scoring_pipeline(pm, batch_size=32)
        hist = MC.warmup_histograms()["model_warmup_ms"]
        before = hist.summary().get("count", 0)
        compiles = stage.warmup(t.slice(0, 1))
        assert compiles > 0
        assert hist.summary()["count"] - before == \
            len(stage.scorer.fused.bucket_sizes())

    def test_quantize_without_quantizable_stage_raises(self):
        from mmlspark_tpu.core.fusion import FusedPipelineModel
        from mmlspark_tpu.stages.dataprep import StandardScaler
        t = DataTable({"features": np.ones((8, 2))})
        scaler = StandardScaler(inputCol="features",
                                outputCol="features").fit(t)
        with pytest.raises(ValueError, match="no quantizable"):
            FusedPipelineModel([scaler]).quantize(t)


class TestWarmupHistogram:
    def test_warmup_records_per_bucket_and_exports(self):
        import jax
        from mmlspark_tpu.core import metrics as MC
        from mmlspark_tpu.core.prometheus import PromRenderer, \
            process_families
        from mmlspark_tpu.models.tpu_model import TPUModel
        module, weights = _mlp_and_weights([16], 4, 8)
        m = TPUModel.from_flax(module, weights, inputCol="features",
                               outputCol="scores", batchSize=32)
        hist = MC.warmup_histograms()["model_warmup_ms"]
        before = hist.summary().get("count", 0)
        m.warmup({"features": np.zeros((1, 8), np.float32)})
        after = hist.summary()["count"]
        assert after - before == len(m.bucket_sizes())
        r = PromRenderer()
        process_families(r)
        assert "serving_model_warmup_ms_bucket" in r.render()


@pytest.fixture(scope="module")
def aot_artifact(tmp_path_factory):
    """One exported f32 MLP artifact shared by the AOT tests."""
    from mmlspark_tpu.models.tpu_model import TPUModel
    from mmlspark_tpu.serving import aot
    module, weights = _mlp_and_weights([64, 32], 10, 16)
    m = TPUModel.from_flax(module, weights, inputCol="features",
                           outputCol="scores", batchSize=64)
    art = str(tmp_path_factory.mktemp("aot") / "model_v1")
    manifest = aot.export_model(
        m, {"features": np.zeros((1, 16), np.float32)}, art,
        version="v1")
    return m, art, manifest


class TestAOTExportLoad:
    def test_manifest_and_artifact_layout(self, aot_artifact):
        _, art, manifest = aot_artifact
        assert manifest["kind"] == "tpu_model"
        assert manifest["format"] in ("jax_export", "trace_cache")
        assert manifest["precision"] == "f32"
        assert manifest["buckets"] == [8, 16, 32, 64]
        for name in ("manifest.json", "programs.pkl", "weights.pkl",
                     "model_fn.pkl", "example.pkl",
                     "example_request.json"):
            assert os.path.exists(os.path.join(art, name)), name

    def test_loaded_bit_identical_to_jit_zero_traces(self, aot_artifact):
        """The AOT acceptance contract: per-bucket outputs bit-identical
        to the in-process JIT path, with ZERO jit traces on the loaded
        model — at load, at warmup, and at request time."""
        import jax
        from mmlspark_tpu.models.tpu_model import TPUModel
        from mmlspark_tpu.parallel import mesh as mesh_lib
        from mmlspark_tpu.serving import aot
        m, art, _ = aot_artifact
        loaded = aot.load_model(art)
        assert loaded.aot is True
        # reference: same weights, same single-device mesh, jit path
        ref = TPUModel(modelFn=m.get("modelFn"),
                       weights=m.get("weights"), inputCol="features",
                       outputCol="scores", batchSize=64)
        ref.set_mesh(mesh_lib.make_mesh(
            {"data": 1}, devices=[jax.devices()[0]]))
        rng = np.random.default_rng(3)
        for b in (8, 32, 64):
            X = rng.normal(size=(b, 16)).astype(np.float32)
            t = DataTable({"features": X})
            a = np.asarray(loaded.transform(t)["scores"])
            r = np.asarray(ref.transform(t)["scores"])
            assert np.array_equal(a, r), f"bucket {b} diverged"
        assert loaded.warmup(
            {"features": np.zeros((1, 16), np.float32)}) == 0
        assert loaded.jit_cache_misses == 0, \
            "AOT-loaded model traced at request time"

    def test_unseen_shape_falls_back_and_counts(self, aot_artifact):
        """A shape the artifact never exported must still serve (lazy
        jit fallback) and must COUNT as a cache miss — the recompile
        guard stays meaningful on AOT replicas."""
        from mmlspark_tpu.serving import aot
        _, art, _ = aot_artifact
        loaded = aot.load_model(art)
        # 48 features instead of 16 would break the model; use a row
        # count above batchSize's bucket cap instead: cap bucket = 64,
        # still exported. Use a fresh model with batchSize raised so a
        # 128-bucket was never exported.
        loaded.set("batchSize", 128)
        X = np.zeros((100, 16), np.float32)
        out = loaded.transform(DataTable({"features": X}))
        assert np.asarray(out["scores"]).shape[0] == 100
        assert loaded.jit_cache_misses >= 1

    def test_quantized_model_roundtrip(self, tmp_path):
        from mmlspark_tpu.serving import aot
        from mmlspark_tpu.models.tpu_model import TPUModel
        module, weights = _mlp_and_weights([32], 4, 8)
        m = TPUModel.from_flax(module, weights, inputCol="features",
                               outputCol="scores", batchSize=16)
        rng = np.random.default_rng(0)
        calib = rng.normal(size=(64, 8)).astype(np.float32)
        q = m.quantize({"features": calib})
        art = str(tmp_path / "q_v1")
        manifest = aot.export_model(q, {"features": calib[:1]}, art,
                                    version="v1-int8")
        assert manifest["precision"] == "int8"
        loaded = aot.load_model(art)
        assert loaded.get("precision") == "int8"
        t = DataTable({"features": calib})
        a = np.asarray(loaded.transform(t)["scores"])
        import jax
        from mmlspark_tpu.parallel import mesh as mesh_lib
        q1 = q
        q1.set_mesh(mesh_lib.make_mesh({"data": 1},
                                       devices=[jax.devices()[0]]))
        r = np.asarray(q1.transform(t)["scores"])
        assert np.array_equal(a, r)
        assert loaded.jit_cache_misses == 0

    def test_pipeline_artifact_serves_end_to_end(self, tmp_path):
        """Pipeline-kind artifact: the fused serving programs load
        pre-compiled, the scorer warms with zero compiles, and replies
        match the in-process scorer."""
        from mmlspark_tpu.core.stage import Pipeline
        from mmlspark_tpu.models.linear import TPULogisticRegression
        from mmlspark_tpu.serving import aot
        from mmlspark_tpu.serving.fleet import json_scoring_pipeline
        from mmlspark_tpu.stages.dataprep import StandardScaler
        rng = np.random.default_rng(0)
        X = rng.normal(size=(1024, 6))
        y = (X[:, 0] > 0).astype(np.float64)
        t = DataTable({"features": X, "label": y})
        pm = Pipeline(stages=[
            StandardScaler(inputCol="features", outputCol="features"),
            TPULogisticRegression(featuresCol="features",
                                  labelCol="label", maxIter=30),
        ]).fit(t)
        example = DataTable({"features": X[:1]})
        art = str(tmp_path / "pipe_v1")
        manifest = aot.export_model(pm.fused(batch_size=32), example,
                                    art, version="v1")
        assert manifest["kind"] == "pipeline"
        loaded = aot.load_model(art)
        assert loaded.aot is True
        stage = json_scoring_pipeline(loaded)
        assert stage.aot is True
        # serving warmup through the exact hot path: zero compiles
        assert stage.warmup(example) == 0
        assert loaded.jit_cache_misses == 0
        # replies match the in-process (jit) scorer
        ref_stage = json_scoring_pipeline(pm)
        body = json.dumps({"features": [float(v) for v in X[1]]}).encode()
        req = DataTable({"id": ["r1"], "request": [{"entity": body}]})
        got = stage.transform(req)["reply"][0]
        want = ref_stage.transform(req)["reply"][0]
        assert got == want
        assert loaded.jit_cache_misses == 0, \
            "AOT pipeline traced at request time"


class TestQuantSwapChaos:
    def test_f32_to_int8_rolling_swap_under_load(self):
        """The acceptance drill: an f32 -> int8 rollout under live load
        keeps availability >= 99% and ``jit_cache_misses`` flat outside
        the swap's own warmup, and every audit surface (healthz,
        serving_model_info, registry, SwapEvent) shows the precision
        flip."""
        import jax
        from mmlspark_tpu.models.networks import build_network
        from mmlspark_tpu.models.tpu_model import TPUModel
        from mmlspark_tpu.serving.fleet import (
            ServingFleet, json_scoring_pipeline,
        )
        from mmlspark_tpu.serving.lifecycle import (
            CanaryPolicy, ModelRegistry,
        )
        dim = 8
        module = build_network({"type": "mlp", "features": [16],
                                "num_classes": 4})
        x0 = np.zeros((1, dim), np.float32)
        m = TPUModel.from_flax(
            module, module.init(jax.random.PRNGKey(0), x0),
            inputCol="features", outputCol="scores", batchSize=16)
        rng = np.random.default_rng(0)
        calib = rng.normal(size=(64, dim)).astype(np.float32)
        q = m.quantize({"features": calib})
        m.warmup({"features": x0})
        registry = ModelRegistry()
        registry.register("v1", json_scoring_pipeline(m))
        registry.register("v1-int8", json_scoring_pipeline(q))
        assert registry.metadata("v1-int8")["precision"] == "int8"
        fleet = ServingFleet(registry.get("v1"), n_engines=2,
                             base_port=19720, batch_size=16,
                             max_wait_ms=2.0, version="v1")
        payload = {"features": [0.1] * dim}
        results = {}
        try:
            for _ in range(8):
                assert "prediction" in fleet.post(payload)
            misses_f32 = m.jit_cache_misses

            def client(cid):
                for j in range(30):
                    try:
                        results[(cid, j)] = "prediction" in fleet.post(
                            payload, timeout=10)
                    except Exception:  # noqa: BLE001
                        results[(cid, j)] = False

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            report = fleet.rolling_swap(
                registry.get("v1-int8"), "v1-int8",
                warmup_example={"features": x0},
                policy=CanaryPolicy(fraction=0.5, min_batches=2,
                                    decision_timeout_s=30))
            for t in threads:
                t.join(timeout=60)
            assert report["ok"], report
            misses_int8 = q.jit_cache_misses
            for _ in range(8):       # post-swap steady state on int8
                assert "prediction" in fleet.post(payload)
            assert m.jit_cache_misses == misses_f32, \
                "f32 model recompiled during the int8 rollout"
            assert q.jit_cache_misses == misses_int8, \
                "int8 model compiled on the hot path after its warmup"
            assert misses_int8 > 0
            agg = fleet.metrics()["aggregate"]
            assert agg["precisions"] == ["int8", "int8"]
            for engine in fleet.engines:
                _, snap = engine._lifecycle_snapshot()
                assert snap["precision"] == "int8"
                assert snap["model_version"] == "v1-int8"
                info = [ln for ln in engine.metrics_text().splitlines()
                        if ln.startswith("serving_model_info")]
                assert any('precision="int8"' in ln for ln in info)
                event = engine.swap_events[-1]
                assert event.from_precision == "f32"
                assert event.to_precision == "int8"
        finally:
            fleet.stop_all()
        ok = sum(results.values())
        assert ok / len(results) >= 0.99, f"availability {ok}/{len(results)}"


class TestKernelAuditQuantized:
    def _chk(self):
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        import check_fusion_kernels as chk
        return chk

    def test_f64_upcast_caught_in_quantized_kernel(self):
        chk = self._chk()
        src = ("def bad(consts, env):\n"
               "    acc = env['x']\n"
               "    return {'y': acc.astype(jnp.float64) * consts['s']}\n")
        violations = chk._check_source("quantize.poison", src, 1,
                                       src.splitlines(True))
        assert any("f64 upcast" in v for v in violations), violations

    def test_f64_rule_scoped_to_quantized_kernels(self):
        chk = self._chk()
        src = ("def fine(consts, env):\n"
               "    return {'y': env['x'].astype(jnp.float64)}\n")
        violations = chk._check_source("SomeStage:uid", src, 1,
                                       src.splitlines(True))
        assert violations == [], violations

    def test_registered_quantized_kernels_clean(self):
        chk = self._chk()
        chk.register_known_callees()
        from mmlspark_tpu.core.fusion import KERNEL_REGISTRY
        names = set(KERNEL_REGISTRY.values())
        assert "quantize.int8_matmul" in names
        assert "quantize.quantize_act" in names
        violations = [v for v in chk.check_registered_kernels()
                      if "quantize" in v]
        assert violations == [], violations
