import numpy as np
import pytest

import jax

from mmlspark_tpu.core.schema import ImageSchema
from mmlspark_tpu.core.stage import load_stage
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.models.learner import TPULearner
from mmlspark_tpu.parallel import mesh as mesh_lib
from mmlspark_tpu.testing.datagen import generate_classification_table


def _toy_table(n=256, d=16, classes=4, seed=0):
    return generate_classification_table(n, d, classes, seed=seed)


def _accuracy(model, table, label_col="label"):
    out = model.transform(table)
    pred = np.argmax(out["scores"], axis=1)
    return float(np.mean(pred == np.asarray(table[label_col])))


def test_mlp_learns_separable_data():
    t = _toy_table()
    learner = TPULearner(
        networkSpec={"type": "mlp", "features": [32], "num_classes": 4},
        epochs=8, batchSize=64, learningRate=0.05, optimizer="momentum",
        computeDtype="float32", logEvery=1000)
    model = learner.fit(t)
    acc = _accuracy(model, t)
    assert acc > 0.9, f"accuracy {acc}"
    assert learner.history, "loss history should be recorded"


def test_dp_mesh_training_matches_quality():
    t = _toy_table(seed=1)
    learner = TPULearner(
        networkSpec={"type": "mlp", "features": [32], "num_classes": 4},
        epochs=8, batchSize=64, learningRate=0.05,
        computeDtype="float32", logEvery=1000)
    learner.set_mesh(mesh_lib.make_mesh({"data": 8}))
    model = learner.fit(t)
    assert _accuracy(model, t) > 0.9


def test_fsdp_sharding():
    t = _toy_table(seed=2)
    learner = TPULearner(
        networkSpec={"type": "mlp", "features": [32], "num_classes": 4},
        epochs=6, batchSize=64, learningRate=0.05,
        computeDtype="float32", paramSharding="fsdp", logEvery=1000)
    learner.set_mesh(mesh_lib.make_mesh({"data": 2, "fsdp": 4}))
    model = learner.fit(t)
    assert _accuracy(model, t) > 0.85


def test_convnet_on_images():
    rng = np.random.default_rng(0)
    n = 64
    # class-dependent mean images
    labels = rng.integers(0, 2, n)
    imgs = (rng.normal(size=(n, 8, 8, 3)) + labels[:, None, None, None] * 2.0)
    imgs = np.clip((imgs + 3) * 40, 0, 255).astype(np.uint8)
    rows = [ImageSchema.make_row(f"i{i}.png", imgs[i]) for i in range(n)]
    t = DataTable({"image": rows, "label": labels.astype(np.int64)})
    learner = TPULearner(
        featuresCol="image",
        networkSpec={"type": "convnet", "conv_features": [8],
                     "dense_features": [16], "num_classes": 2},
        epochs=25, batchSize=32, learningRate=0.1,
        computeDtype="float32", logEvery=1000)
    model = learner.fit(t)
    acc = _accuracy(model, t)
    assert acc > 0.9, f"accuracy {acc}"


def test_resnet_batchnorm_smoke():
    rng = np.random.default_rng(1)
    n = 32
    labels = rng.integers(0, 2, n)
    imgs = rng.normal(size=(n, 8, 8, 3)).astype(np.float32)
    t = DataTable({"features": imgs.reshape(n, -1), "label": labels})
    learner = TPULearner(
        networkSpec={"type": "resnet", "stage_sizes": [1], "width": 8,
                     "num_classes": 2},
        inputShape=[8, 8, 3],
        epochs=1, batchSize=16, computeDtype="float32", logEvery=1000)
    model = learner.fit(t)
    out = model.transform(t)
    assert out["scores"].shape == (n, 2)
    assert np.all(np.isfinite(out["scores"]))


def test_regression_mse():
    rng = np.random.default_rng(3)
    x = rng.normal(size=(256, 8)).astype(np.float32)
    w = rng.normal(size=8)
    y = (x @ w).astype(np.float32)
    t = DataTable({"features": x, "label": y})
    learner = TPULearner(
        networkSpec={"type": "mlp", "features": [32], "num_classes": 1},
        loss="mse", epochs=20, batchSize=64, learningRate=0.01,
        optimizer="adam", computeDtype="float32", logEvery=1000)
    model = learner.fit(t)
    pred = model.transform(t)["scores"][:, 0]
    resid = np.mean((pred - y) ** 2) / np.var(y)
    assert resid < 0.2, f"relative mse {resid}"


def test_streaming_shard_ingestion():
    # shard iterator feed: datasets that never materialize in one table
    # (the HDFS-staged feed analog, ref: CNTKLearner.scala:123-140)
    t = _toy_table()
    shards = list(t.shards(4))
    learner = TPULearner(
        networkSpec={"type": "mlp", "features": [32], "num_classes": 4},
        epochs=8, batchSize=64, learningRate=0.05, optimizer="momentum",
        computeDtype="float32", logEvery=1000)
    model = learner.fit(shards)                 # list of shard tables
    acc = _accuracy(model, t)
    assert acc > 0.9, f"accuracy {acc}"

    learner2 = TPULearner(
        networkSpec={"type": "mlp", "features": [32], "num_classes": 4},
        epochs=8, batchSize=64, learningRate=0.05, optimizer="momentum",
        computeDtype="float32", logEvery=1000)
    model2 = learner2.fit(lambda: iter(t.shards(3)))   # callable factory
    assert _accuracy(model2, t) > 0.9


def test_profile_dir_emits_trace(tmp_path):
    from mmlspark_tpu.utils.profiling import trace_files
    t = _toy_table()
    trace_dir = str(tmp_path / "prof")
    learner = TPULearner(
        networkSpec={"type": "mlp", "features": [8], "num_classes": 4},
        epochs=1, batchSize=64, computeDtype="float32",
        logEvery=1000, profileDir=trace_dir)
    learner.fit(t)
    assert trace_files(trace_dir), "no xplane trace emitted by training"


def test_checkpoint_resume(tmp_path):
    t = _toy_table(seed=4)
    ck = str(tmp_path / "ckpt")
    # constant schedule so the interrupted run's lr trajectory matches the
    # full run's (cosine depends on total_steps, which differs)
    common = dict(
        networkSpec={"type": "mlp", "features": [16], "num_classes": 4},
        epochs=4, batchSize=64, learningRate=0.05, computeDtype="float32",
        schedule="constant",
        checkpointDir=ck, checkpointEvery=4, logEvery=1000, seed=9)
    full = TPULearner(**common).fit(t)

    # simulate crash: train with same config but stop early via epochs=2
    import shutil
    shutil.rmtree(ck)
    partial_learner = TPULearner(**{**common, "epochs": 2})
    partial_learner.fit(t)
    # now resume with the full epoch budget; should fast-forward & finish
    resumed = TPULearner(**common).fit(t)

    f = np.asarray(full.transform(t)["scores"])
    r = np.asarray(resumed.transform(t)["scores"])
    np.testing.assert_allclose(f, r, rtol=1e-3, atol=1e-3)


def test_corrupt_checkpoint_falls_back_to_previous(tmp_path):
    """A corrupt/truncated newest checkpoint must not kill resume:
    fit() logs, falls back to the PREVIOUS checkpoint, and finishes
    (the all-corrupt -> fresh-init twin runs against webdav in
    tests/test_remote_fs.py)."""
    import os
    t = _toy_table(seed=4)
    ck = str(tmp_path / "ckpt")
    common = dict(
        networkSpec={"type": "mlp", "features": [16], "num_classes": 4},
        epochs=2, batchSize=64, learningRate=0.05, computeDtype="float32",
        schedule="constant",
        checkpointDir=ck, checkpointEvery=4, logEvery=1000, seed=9)
    TPULearner(**common).fit(t)               # 8 steps -> ckpts @ 4, 8
    steps = sorted(d for d in os.listdir(ck) if d.startswith("step_"))
    assert len(steps) >= 2, "need >= 2 checkpoints for the fallback"
    # truncate the NEWEST checkpoint's leaves mid-file (crash-mid-save)
    newest = os.path.join(ck, steps[-1], "leaves.npz")
    with open(newest, "r+b") as f:
        f.truncate(os.path.getsize(newest) // 2)
    prev_step = int(steps[-2].rsplit("_", 1)[1])
    newest_step = int(steps[-1].rsplit("_", 1)[1])
    # logEvery=1: every step lands in history, so the first logged
    # step IS the resume point
    resumed_learner = TPULearner(**{**common, "epochs": 4,
                                    "logEvery": 1})
    model = resumed_learner.fit(t)            # no raise: previous ckpt
    assert model is not None
    assert resumed_learner.history, "training never ran"
    first = min(h["step"] for h in resumed_learner.history)
    # resumed from the PREVIOUS checkpoint: past it, not past the
    # corrupt newest one (which a successful load would skip to)
    assert prev_step < first <= newest_step, (
        first, prev_step, newest_step)


def test_learned_model_roundtrip(tmp_path):
    t = _toy_table(seed=5)
    learner = TPULearner(
        networkSpec={"type": "mlp", "features": [16], "num_classes": 4},
        epochs=2, batchSize=64, computeDtype="float32", logEvery=1000)
    model = learner.fit(t)
    out1 = model.transform(t)["scores"]
    p = str(tmp_path / "m")
    model.save(p)
    model2 = load_stage(p)
    out2 = model2.transform(t)["scores"]
    np.testing.assert_allclose(out1, out2, rtol=1e-5, atol=1e-5)


def test_bilstm_tagger_smoke():
    rng = np.random.default_rng(0)
    n, T, V, K = 32, 12, 50, 3
    toks = rng.integers(0, V, size=(n, T)).astype(np.float32)
    # simple rule: tag = token mod K
    tags = (toks.astype(np.int64) % K)
    t = DataTable({"features": toks, "label": tags.astype(np.int64)})
    learner = TPULearner(
        networkSpec={"type": "bilstm", "vocab_size": V, "embed_dim": 16,
                     "hidden": 16, "num_tags": K},
        loss="token_cross_entropy",
        epochs=40, batchSize=16, learningRate=0.02, optimizer="adam",
        computeDtype="float32", logEvery=1000)
    model = learner.fit(t)
    out = model.transform(t)
    scores = np.asarray(out["scores"])
    assert scores.shape == (n, T, K)
    acc = float(np.mean(np.argmax(scores, -1) == tags))
    assert acc > 0.8, f"token accuracy {acc}"


def test_device_feed_matches_host_quality():
    t = _toy_table(seed=6)
    common = dict(
        networkSpec={"type": "mlp", "features": [32], "num_classes": 4},
        epochs=8, batchSize=64, learningRate=0.05,
        computeDtype="float32", logEvery=1000)
    learner = TPULearner(**common, dataFeed="device")
    learner.set_mesh(mesh_lib.make_mesh({"data": 8}))
    model = learner.fit(t)
    assert _accuracy(model, t) > 0.9
    # device feed reports XLA cost-analysis FLOPs for MFU auditing
    assert learner.timing.get("model_flops_per_step", 0) > 0
    assert "tflops_per_sec_per_chip" in learner.timing


def test_device_feed_checkpoint_resume(tmp_path):
    t = _toy_table(seed=7)
    ck = str(tmp_path / "ckpt")
    common = dict(
        networkSpec={"type": "mlp", "features": [16], "num_classes": 4},
        epochs=4, batchSize=64, learningRate=0.05, computeDtype="float32",
        schedule="constant", dataFeed="device",
        checkpointDir=ck, checkpointEvery=4, logEvery=1000, seed=9)
    full = TPULearner(**common).fit(t)

    import shutil
    shutil.rmtree(ck)
    TPULearner(**{**common, "epochs": 2}).fit(t)
    resumed = TPULearner(**common).fit(t)

    f = np.asarray(full.transform(t)["scores"])
    r = np.asarray(resumed.transform(t)["scores"])
    np.testing.assert_allclose(f, r, rtol=1e-3, atol=1e-3)


def test_device_feed_rejects_streaming_and_remainder_is_masked():
    t = _toy_table(n=100, seed=8)  # 100 rows, batch 64 -> padded batch
    learner = TPULearner(
        networkSpec={"type": "mlp", "features": [16], "num_classes": 4},
        epochs=6, batchSize=64, learningRate=0.05, computeDtype="float32",
        logEvery=1000, dataFeed="device")
    model = learner.fit(t)
    assert _accuracy(model, t) > 0.8
    shards = [t.slice(0, 50), t.slice(50, 100)]
    bad = TPULearner(
        networkSpec={"type": "mlp", "features": [16], "num_classes": 4},
        epochs=1, batchSize=64, dataFeed="device")
    with pytest.raises(ValueError, match="device"):
        bad.fit(shards)
