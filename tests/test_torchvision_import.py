"""Published-checkpoint ingestion: the torchvision resnet18 layout.

The reference's inference story is anchored on REAL published zoo models
(ref: ModelDownloader.scala:209, CNTKModel.scala:147). This image has no
network egress, so these tests pin the two things that make a real
download work on arrival:

1. LAYOUT: the torchvision resnet18 state_dict manifest (102 tensors +
   20 num_batches_tracked, exact key names and shapes) — asserted
   against an in-test twin built with plain torch to torchvision's
   published architecture.
2. NUMERICS: the flax ImageNet ResNet reproduces the torch twin's eval
   forward (7x7/s2/p3 stem, -inf-padded 3x3/s2 maxpool, BasicBlocks
   with downsample) to float tolerance at 224x224, through .pth AND
   .safetensors round-trips.
"""

import json
import struct

import numpy as np
import pytest

import jax

from mmlspark_tpu.importers.torch_import import (
    TORCHVISION_RESNET18_SPEC, _torchvision_manifest,
    import_torchvision_resnet, load_safetensors_file,
)

torch = pytest.importorskip("torch")

from mmlspark_tpu.testing.torch_models import build_torch_resnet18  # noqa: E402


def _write_safetensors(path, tensors):
    """Minimal safetensors writer for the round-trip test."""
    header, blobs, off = {}, [], 0
    for name, t in tensors.items():
        a = np.ascontiguousarray(t.detach().numpy())
        if a.dtype == np.int64:
            dt = "I64"
        else:
            a = a.astype(np.float32)
            dt = "F32"
        header[name] = {"dtype": dt, "shape": list(a.shape),
                        "data_offsets": [off, off + a.nbytes]}
        blobs.append(a.tobytes())
        off += a.nbytes
    hj = json.dumps(header).encode()
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(hj)))
        f.write(hj)
        for b in blobs:
            f.write(b)


@pytest.fixture(scope="module")
def twin():
    torch.manual_seed(0)
    model = build_torch_resnet18().eval()
    # non-trivial batch stats (fresh BN stats are exactly 0/1 — run a
    # few training batches so the import has something real to carry)
    model.train()
    with torch.no_grad():
        for _ in range(3):
            model(torch.randn(4, 3, 224, 224))
    model.eval()
    return model


class TestLayoutManifest:
    def test_twin_state_dict_matches_published_manifest(self, twin):
        sd = twin.state_dict()
        manifest = _torchvision_manifest([2, 2, 2, 2], 1000)
        got = {k: tuple(v.shape) for k, v in sd.items()
               if not k.endswith("num_batches_tracked")}
        assert got == manifest
        # the published torchvision resnet18 state_dict: 102 tensors +
        # 20 num_batches_tracked = 122 entries
        assert len(sd) == 122
        nbt = [k for k in sd if k.endswith("num_batches_tracked")]
        assert len(nbt) == 20

    def test_wrong_checkpoint_rejected_with_keys(self, twin):
        sd = dict(twin.state_dict())
        sd.pop("layer3.0.downsample.0.weight")
        sd["unexpected.weight"] = torch.zeros(3)
        with pytest.raises(ValueError) as e:
            import_torchvision_resnet(sd)
        msg = str(e.value)
        assert "layer3.0.downsample.0.weight" in msg
        assert "unexpected.weight" in msg


class TestNumericsFidelity:
    def test_forward_matches_torch(self, twin):
        variables = import_torchvision_resnet(twin.state_dict())
        from mmlspark_tpu.models.networks import build_network
        module = build_network(TORCHVISION_RESNET18_SPEC)

        rng = np.random.default_rng(0)
        x = rng.normal(size=(2, 224, 224, 3)).astype(np.float32)
        with torch.no_grad():
            want = twin(torch.from_numpy(
                np.transpose(x, (0, 3, 1, 2)))).numpy()
        got = np.asarray(module.apply(variables, x, train=False))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_pth_and_safetensors_roundtrip(self, twin, tmp_path):
        pth = str(tmp_path / "resnet18.pth")
        sft = str(tmp_path / "resnet18.safetensors")
        torch.save(twin.state_dict(), pth)
        _write_safetensors(sft, twin.state_dict())

        v1 = import_torchvision_resnet(pth)
        v2 = import_torchvision_resnet(sft)
        for a, b in zip(jax.tree_util.tree_leaves(v1),
                        jax.tree_util.tree_leaves(v2)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_featurizer_layer_cutting(self, twin):
        """Transfer learning on the imported backbone: cut at the pooled
        embedding (the 305-notebook flow, ImageFeaturizer.scala:91-141)."""
        from mmlspark_tpu.models.networks import build_network
        variables = import_torchvision_resnet(twin.state_dict())
        module = build_network(TORCHVISION_RESNET18_SPEC)
        rng = np.random.default_rng(1)
        x = rng.normal(size=(2, 224, 224, 3)).astype(np.float32)
        emb = np.asarray(module.apply(variables, x, train=False,
                                      capture="pool"))
        assert emb.shape == (2, 512)
        # the head is a plain affine map of the embedding
        W = np.asarray(variables["params"]["head"]["kernel"])
        b = np.asarray(variables["params"]["head"]["bias"])
        logits = np.asarray(module.apply(variables, x, train=False))
        np.testing.assert_allclose(emb @ W + b, logits,
                                   rtol=2e-3, atol=2e-3)
