"""udfs + plot helpers + FastVectorAssembler
(ref: src/udf/src/main/scala/udfs.scala:15-29,
src/plot/src/main/python/plot.py,
src/core/spark/.../FastVectorAssembler.scala:23)."""

import os

import numpy as np
import pytest

from mmlspark_tpu import plot, udfs
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.stages import FastVectorAssembler, UDFTransformer


class TestUdfs:
    def test_to_vector(self):
        v = udfs.to_vector([1, 2, 3])
        assert v.dtype == np.float64 and list(v) == [1, 2, 3]

    def test_get_value_at(self):
        assert udfs.get_value_at(1)([5.0, 7.0, 9.0]) == 7.0

    def test_with_udf_transformer(self):
        t = DataTable({"vec": np.asarray([[1.0, 2.0], [3.0, 4.0]])})
        out = UDFTransformer(inputCol="vec", outputCol="second",
                             udf=udfs.get_value_at(1)).transform(t)
        assert list(out["second"]) == [2.0, 4.0]

    def test_table_helpers(self):
        t = DataTable({"arr": [[1.0, 2.0], [3.0, 4.0]]})
        t2 = udfs.table_to_vector(t, "arr", "vec")
        assert t2["vec"].shape == (2, 2)
        t3 = udfs.table_get_value_at(t2, "vec", "v0", 0)
        assert list(t3["v0"]) == [1.0, 3.0]


class TestPlot:
    def test_confusion_matrix_saves(self, tmp_path):
        t = DataTable({"y": np.asarray([0, 0, 1, 1, 1.0]),
                       "yhat": np.asarray([0, 1, 1, 1, 0.0])})
        p = str(tmp_path / "cm.png")
        plot.confusion_matrix(t, "y", "yhat", path=p)
        assert os.path.getsize(p) > 0

    def test_roc_saves(self, tmp_path):
        rng = np.random.default_rng(0)
        y = rng.integers(0, 2, 200).astype(float)
        score = y * 0.6 + rng.random(200) * 0.4
        t = DataTable({"y": y, "score": score})
        p = str(tmp_path / "roc.png")
        plot.roc(t, "y", "score", path=p)
        assert os.path.getsize(p) > 0


class TestFastVectorAssembler:
    def test_assembles_scalars_and_vectors(self):
        t = DataTable({"a": np.asarray([1.0, 2.0]),
                       "v": np.asarray([[3.0, 4.0], [5.0, 6.0]]),
                       "b": np.asarray([7.0, 8.0])})
        out = FastVectorAssembler(inputCols=["a", "v", "b"],
                                  outputCol="features").transform(t)
        np.testing.assert_allclose(out["features"],
                                   [[1, 3, 4, 7], [2, 5, 6, 8]])

    def test_schema(self):
        t = DataTable({"a": np.asarray([1.0]), "b": np.asarray([2.0])})
        stage = FastVectorAssembler(inputCols=["a", "b"])
        schema = stage.transform_schema(t.schema)
        assert "features" in schema.names

    def test_requires_input_cols(self):
        t = DataTable({"a": np.asarray([1.0])})
        with pytest.raises(ValueError, match="inputCols"):
            FastVectorAssembler().transform(t)
