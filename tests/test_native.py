"""Native runtime library tests.

The native library is an accelerator with mandatory numpy fallbacks
(ref pattern: NativeLoader extracting .so's, NativeLoader.java:28); these
tests verify (a) native results bit-match or closely match the Python
reference implementations, and (b) everything still works with native
disabled.
"""

import io

import numpy as np
import pytest

from mmlspark_tpu.gbdt.binning import BinMapper
from mmlspark_tpu.native import loader
from mmlspark_tpu.ops.image_ops import resize_host, unroll_host

needs_native = pytest.mark.skipif(not loader.available(),
                                  reason="native library unavailable")


def _img(shape=(37, 53, 3), seed=0):
    return np.random.default_rng(seed).integers(
        0, 255, shape).astype(np.uint8)


@needs_native
class TestNativeImageOps:
    def test_resize_matches_jax_downscale(self):
        img = _img()
        rn = loader.resize_u8(img, 16, 24)
        rp = np.clip(np.round(resize_host(img, 16, 24)), 0,
                     255).astype(np.uint8)
        assert np.abs(rn.astype(int) - rp.astype(int)).max() <= 1

    def test_resize_matches_jax_upscale(self):
        img = _img((16, 20, 1))
        rn = loader.resize_u8(img, 32, 48)
        rp = np.clip(np.round(resize_host(img, 32, 48)), 0,
                     255).astype(np.uint8)
        assert np.abs(rn.astype(int) - rp.astype(int)).max() <= 1

    def test_unroll_exact(self):
        img = _img()
        ref = img.transpose(2, 0, 1).astype(np.float64).ravel()
        assert np.array_equal(loader.unroll_chw(img), ref)

    def test_unroll_host_uses_native(self):
        img = _img()
        ref = img.transpose(2, 0, 1).astype(np.float64).ravel()
        assert np.array_equal(unroll_host(img), ref)


@needs_native
class TestNativeDecode:
    def test_png_roundtrip_exact(self):
        from PIL import Image
        img = _img((24, 31, 3))
        buf = io.BytesIO()
        Image.fromarray(img).save(buf, format="PNG")
        dec = loader.decode_image(buf.getvalue())
        assert np.array_equal(dec, img)

    def test_jpeg_close(self):
        from PIL import Image
        yy, xx = np.mgrid[0:64, 0:64]
        smooth = np.stack([yy * 2, xx * 2, yy + xx], -1).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(smooth).save(buf, format="JPEG", quality=95)
        dec = loader.decode_image(buf.getvalue())
        assert dec.shape == smooth.shape
        assert np.abs(dec.astype(int) - smooth.astype(int)).mean() < 3

    def test_garbage_returns_none(self):
        assert loader.decode_image(b"not an image at all") is None

    def test_io_decode_image_uses_native_bgr(self):
        from PIL import Image
        from mmlspark_tpu.io.image import decode_image
        img = _img((8, 9, 3))
        buf = io.BytesIO()
        Image.fromarray(img).save(buf, format="PNG")
        bgr = decode_image(buf.getvalue())
        assert np.array_equal(bgr, img[:, :, ::-1])


@needs_native
class TestNativeBinning:
    def test_apply_bins_matches_numpy(self):
        rng = np.random.default_rng(1)
        X = rng.normal(size=(5000, 8))
        X[::17, 3] = np.nan
        X[:, 5] = np.round(X[:, 5])  # few distinct values
        m = BinMapper.fit(X, max_bin=64)
        native_bins = loader.apply_bins(X, m.upper_bounds)
        # numpy reference (bypassing the native fast path in transform)
        ref = np.empty(X.shape, dtype=np.int32)
        for j, ub in enumerate(m.upper_bounds):
            col = X[:, j]
            b = np.searchsorted(ub, col, side="left")
            b[np.isnan(col)] = 0
            ref[:, j] = b
        assert np.array_equal(native_bins, ref)

    def test_constant_feature(self):
        X = np.ones((100, 2))
        m = BinMapper.fit(X, max_bin=8)
        out = loader.apply_bins(X, m.upper_bounds)
        assert (out == 0).all()


class TestFallback:
    def test_gbdt_training_identical_with_and_without_native(self):
        import os
        from mmlspark_tpu.gbdt import train
        rng = np.random.default_rng(0)
        X = rng.normal(size=(300, 5))
        y = (X[:, 0] > 0).astype(float)
        b1 = train({"objective": "binary", "num_iterations": 5}, X, y)
        # numpy-only binning path
        mapper = BinMapper.fit(X, max_bin=255)
        ref = np.empty(X.shape, dtype=np.int32)
        for j, ub in enumerate(mapper.upper_bounds):
            col = X[:, j]
            bb = np.searchsorted(ub, col, side="left")
            bb[np.isnan(col)] = 0
            ref[:, j] = bb
        if loader.available():
            assert np.array_equal(mapper.transform(X), ref)
        p1 = b1.predict(X)
        assert np.isfinite(p1).all()
