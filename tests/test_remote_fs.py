"""Writable remote filesystem: webdav:// round-trips end-to-end.

VERDICT r4 missing #3: the registry advertised remote schemes but only
read-only backends existed, while multi-host checkpoint/resume REQUIRES
a shared filesystem and ModelDownloader.publish had no remote target.
These tests run every consumer of the seam against a genuine in-process
WebDAV server (mmlspark_tpu.testing.webdav): raw FS round-trip, learner
checkpoint/resume, ModelDownloader publish+download, read_binary_files.
(ref: src/core/hadoop/.../HadoopUtils.scala; CNTKLearner.scala:18-67
dataTransfer=hdfs; ModelDownloader.scala:54-124 HDFSRepo.)

The MULTI-host resume check lives in tests/test_distributed.py
(WEBDAVCKPT): two OS processes share one webdav endpoint, the
coordinator writes, both resume from the same remote step.
"""

import os

import numpy as np
import pytest

from mmlspark_tpu.testing.webdav import serve_webdav
from mmlspark_tpu.utils.filesystem import (
    WebDAVFileSystem, get_filesystem, read_bytes, write_bytes,
)


@pytest.fixture()
def dav(tmp_path):
    root = tmp_path / "store"
    server, base = serve_webdav(str(root))
    yield base, str(root)
    server.shutdown()
    server.server_close()


class TestWebDAVFileSystem:
    def test_roundtrip_and_exists(self, dav):
        base, _root = dav
        url = f"{base}/a/b/data.bin"
        payload = os.urandom(4096)
        assert not get_filesystem(url).exists(url)
        write_bytes(url, payload)          # creates a/ and a/b/ (MKCOL)
        assert get_filesystem(url).exists(url)
        assert read_bytes(url) == payload

    def test_overwrite(self, dav):
        base, _ = dav
        url = f"{base}/f.txt"
        write_bytes(url, b"one")
        write_bytes(url, b"two")
        assert read_bytes(url) == b"two"

    def test_list_recursive_and_pattern(self, dav):
        base, _ = dav
        write_bytes(f"{base}/d/x.npy", b"1")
        write_bytes(f"{base}/d/sub/y.npy", b"2")
        write_bytes(f"{base}/d/sub/z.txt", b"3")
        fs = get_filesystem(base)
        all_files = fs.list_files(f"{base}/d")
        assert {u.rsplit("/", 1)[1] for u in all_files} == \
            {"x.npy", "y.npy", "z.txt"}
        npys = fs.list_files(f"{base}/d", pattern="*.npy")
        assert {u.rsplit("/", 1)[1] for u in npys} == {"x.npy", "y.npy"}
        shallow = fs.list_files(f"{base}/d", recursive=False)
        assert {u.rsplit("/", 1)[1] for u in shallow} == {"x.npy"}
        # listing a missing dir is empty, not an error (resume-from-
        # nothing path)
        assert fs.list_files(f"{base}/nothere") == []

    def test_delete(self, dav):
        base, _ = dav
        fs = get_filesystem(base)
        write_bytes(f"{base}/gone/f1", b"x")
        write_bytes(f"{base}/gone/f2", b"y")
        fs.delete_path(f"{base}/gone/")
        assert fs.list_files(f"{base}/gone") == []
        assert not fs.exists(f"{base}/gone/f1")

    def test_traversal_rejected(self, dav):
        base, root = dav
        fs = get_filesystem(base)
        with pytest.raises(Exception):
            fs.write_bytes(f"{base}/../escape.txt", b"x")
        assert not os.path.exists(
            os.path.join(os.path.dirname(root), "escape.txt"))

    def test_depth1_fallback_when_infinity_refused(self, tmp_path):
        """Apache mod_dav refuses Depth: infinity by default (RFC 4918
        §9.1 allows it) — recursive listing must fall back to manual
        Depth-1 recursion over collections."""
        server, base = serve_webdav(str(tmp_path / "s"),
                                    allow_depth_infinity=False)
        try:
            write_bytes(f"{base}/d/x.npy", b"1")
            write_bytes(f"{base}/d/sub/deep/y.npy", b"2")
            fs = get_filesystem(base)
            got = {u.rsplit("/", 1)[1]
                   for u in fs.list_files(f"{base}/d")}
            assert got == {"x.npy", "y.npy"}
        finally:
            server.shutdown()
            server.server_close()

    def test_registry_schemes(self):
        assert isinstance(get_filesystem("webdav://h/x"),
                          WebDAVFileSystem)
        assert isinstance(get_filesystem("webdavs://h/x"),
                          WebDAVFileSystem)


class TestLearnerRemoteCheckpoint:
    def test_checkpoint_resume_on_webdav(self, dav):
        """Train with checkpointDir on webdav://, then resume: the
        second learner starts from the remote step (not 0) and finishes
        with usable weights; stale checkpoints prune to 3."""
        from mmlspark_tpu.core.table import DataTable
        from mmlspark_tpu.models.learner import (
            TPULearner, _latest_checkpoint,
        )
        base, _ = dav
        ck = f"{base}/ckpt"
        rng = np.random.default_rng(0)
        x = rng.normal(size=(64, 6)).astype(np.float32)
        y = (x[:, 0] > 0).astype(np.int64)
        table = DataTable({"features": x, "label": y})

        def mk(epochs):
            return TPULearner(
                networkSpec={"type": "mlp", "features": [8],
                             "num_classes": 2},
                epochs=epochs, batchSize=16, learningRate=0.1,
                computeDtype="float32", logEvery=1000,
                checkpointDir=ck, checkpointEvery=2, resume=True)

        mk(3).fit(table)                       # 12 steps, saves over PUT
        latest = _latest_checkpoint(ck)
        assert latest is not None and latest.startswith("webdav://")
        step1 = int(latest.rsplit("step_", 1)[1])
        assert step1 == 12
        # pruning kept at most 3 step dirs remote
        from mmlspark_tpu.models.learner import _remote_steps
        assert 1 <= len(_remote_steps(ck)) <= 3

        learner2 = mk(6)
        model2 = learner2.fit(table)
        # resume skipped the already-run steps: every logged step of
        # the second run is past the first run's 12
        assert learner2.history, "no training history"
        assert min(h["step"] for h in learner2.history) > 12, \
            learner2.history[:3]
        latest2 = _latest_checkpoint(ck)
        assert int(latest2.rsplit("step_", 1)[1]) == 24
        preds = model2.transform(table)
        acc = (np.asarray(preds["scores"]).argmax(-1) == y).mean()
        assert acc > 0.8

    def test_corrupt_remote_checkpoint_falls_back(self, dav):
        """A corrupt remote checkpoint must not kill the fit: resume
        logs the failure and falls back (here: fresh init — the corrupt
        step is the only one), training from step 0 instead of raising
        mid-fit. The local-dir twin (incl. previous-checkpoint
        fallback) lives in tests/test_learner.py."""
        from mmlspark_tpu.core.table import DataTable
        from mmlspark_tpu.models.learner import TPULearner
        base, root = dav
        ck = f"{base}/bad"
        write_bytes(f"{ck}/step_00000004/leaves.npz", b"not-an-npz")
        write_bytes(f"{ck}/step_00000004/treedef.json", b"{}")
        rng = np.random.default_rng(1)
        table = DataTable({
            "features": rng.normal(size=(32, 4)).astype(np.float32),
            "label": (rng.normal(size=32) > 0).astype(np.int64)})
        learner = TPULearner(
            networkSpec={"type": "mlp", "features": [4],
                         "num_classes": 2},
            epochs=1, batchSize=16, computeDtype="float32",
            checkpointDir=ck, resume=True)
        model = learner.fit(table)            # no raise
        assert model is not None
        assert learner.history, "training never ran"
        # fresh init: the run did NOT fast-forward past the corrupt
        # step-4 checkpoint (resume from it would start at step 5)
        assert min(h["step"] for h in learner.history) < 4, \
            learner.history[:3]


class TestDownloaderRemotePublish:
    def test_publish_fetch_roundtrip(self, dav):
        """Publish a model blob to the webdav repo, list it, download
        it through ModelDownloader with sha256 verification."""
        from mmlspark_tpu.downloader import HTTPRepo, ModelDownloader
        base, _ = dav
        repo = HTTPRepo(f"{base}/zoo")
        blob = os.urandom(2048)
        schema = repo.publish(
            "tiny_model", {"type": "mlp", "features": [4]},
            blob=blob, model_type="classification", dataset="synthetic")
        assert schema.sha256
        # a FRESH repo object sees the published index remotely
        repo2 = HTTPRepo(f"{base}/zoo")
        names = [s.name for s in repo2.list_schemas()]
        assert names == ["tiny_model"]
        got = repo2.read_blob(repo2.get_schema("tiny_model"))
        assert got == blob

    def test_download_caches_locally(self, dav, tmp_path):
        from mmlspark_tpu.downloader import HTTPRepo, ModelDownloader
        base, _ = dav
        repo = HTTPRepo(f"{base}/zoo")
        blob = b"m" * 512
        repo.publish("m1", {"type": "mlp"}, blob=blob)
        dl = ModelDownloader(local_path=str(tmp_path / "cache"),
                             repo=HTTPRepo(f"{base}/zoo"))
        schema = dl.download_by_name("m1")
        assert dl.local.read_blob(schema) == blob

    def test_corrupted_remote_blob_rejected(self, dav):
        from mmlspark_tpu.downloader import HTTPRepo
        base, _ = dav
        repo = HTTPRepo(f"{base}/zoo", retries=1)
        repo.publish("m2", {"type": "mlp"}, blob=b"good-bytes")
        # tamper with the stored blob AFTER publish
        write_bytes(f"{base}/zoo/m2.msgpack", b"evil-bytes")
        with pytest.raises(IOError, match="sha256"):
            repo.read_blob(repo.get_schema("m2"))


class TestBinaryFilesRemote:
    def test_read_binary_files_webdav(self, dav):
        from mmlspark_tpu.io.binary import read_binary_files
        base, _ = dav
        write_bytes(f"{base}/blobs/a.bin", b"AAA")
        write_bytes(f"{base}/blobs/deep/b.bin", b"BBBB")
        write_bytes(f"{base}/blobs/deep/c.txt", b"CC")
        table = read_binary_files(f"{base}/blobs", pattern="*.bin")
        got = {r["value"]["path"].rsplit("/", 1)[1]:
               bytes(r["value"]["bytes"])
               for r in table.rows()}
        assert got == {"a.bin": b"AAA", "b.bin": b"BBBB"}


class TestWebDAVEncoding:
    def test_names_with_spaces_roundtrip(self, dav):
        """webdav paths are PLAIN names; percent-encoding happens on
        the wire only — write, exists, list, and read a name with
        spaces (hrefs come back encoded)."""
        base, _ = dav
        url = f"{base}/dir with space/my file.bin"
        write_bytes(url, b"spacey")
        fs = get_filesystem(url)
        assert fs.exists(url)
        assert read_bytes(url) == b"spacey"
        listed = fs.list_files(f"{base}/dir with space")
        assert listed == [url]
        # the listed URL round-trips straight back into read_bytes
        assert read_bytes(listed[0]) == b"spacey"
