"""Accuracy benchmarks as regression tests against a checked-in CSV.

ref: Benchmarks.scala:15-60 + benchmarks_VerifyLightGBMClassifier.csv —
the reference pins per-dataset metric values (e.g. breast-cancer AUC
0.9925) and fails on drift. Here: real local datasets (sklearn's bundled
breast-cancer / digits / wine / diabetes — digits are real 8x8
handwritten images) plus deterministic synthetics, for both the GBDT
engine and the TPULearner DNN path. On mismatch BenchmarkComparer writes
<csv>.observed for easy promotion.
"""

import os

import numpy as np
import pytest

from mmlspark_tpu.gbdt import train
from mmlspark_tpu.testing.benchmarks import BenchmarkComparer

# minutes of single-core training per case: excluded from the
# tier-1 wall budget, run via the full suite / -m slow
pytestmark = pytest.mark.slow

HERE = os.path.dirname(__file__)
CLF_CSV = os.path.join(HERE, "resources", "benchmarks_classifier.csv")
REG_CSV = os.path.join(HERE, "resources", "benchmarks_regressor.csv")
DNN_CSV = os.path.join(HERE, "resources", "benchmarks_learner.csv")


def _auc(y, p):
    from sklearn.metrics import roc_auc_score
    return roc_auc_score(y, p)


def _holdout(X, y, n_train, seed=0):
    idx = np.random.default_rng(seed).permutation(len(y))
    tr, te = idx[:n_train], idx[n_train:]
    return X[tr], y[tr], X[te], y[te]


class TestClassifierBenchmarks:
    """Six binary datasets, AUC pinned at 2 decimals — the
    benchmarks_VerifyLightGBMClassifier.csv analog."""

    def test_auc_floors(self):
        from sklearn.datasets import (
            load_breast_cancer, load_digits, load_wine, make_classification,
        )
        cmp_ = BenchmarkComparer(CLF_CSV, precision=2)
        params = {"objective": "binary", "num_iterations": 100}

        def run(name, X, y, n_train):
            Xtr, ytr, Xte, yte = _holdout(np.asarray(X, np.float64),
                                          np.asarray(y, np.float64),
                                          n_train)
            b = train(params, Xtr, ytr)
            cmp_.record(name, _auc(yte, b.predict(Xte)))

        X, y = load_breast_cancer(return_X_y=True)
        run("breast_cancer", X, y, 400)

        X, y = load_digits(return_X_y=True)
        run("digits_lt5", X, (y < 5).astype(float), 1300)

        X, y = load_wine(return_X_y=True)
        run("wine_class0", X, (y == 0).astype(float), 130)

        X, y = make_classification(
            n_samples=2000, n_features=20, n_informative=8, flip_y=0.05,
            random_state=7)
        run("synthetic_hard", X, y.astype(float), 1500)

        X, y = make_classification(
            n_samples=800, n_features=10, n_informative=3, flip_y=0.25,
            class_sep=0.5, random_state=11)
        run("synthetic_noisy", X, y.astype(float), 600)

        rng = np.random.default_rng(3)
        X = rng.normal(size=(1200, 6))
        y = ((X[:, 0] * X[:, 1] + 0.5 * X[:, 2] > 0)).astype(float)
        run("interaction", X, y, 900)

        cmp_.verify()


class TestRegressorBenchmarks:
    def test_regression_metrics(self):
        from sklearn.datasets import load_diabetes, make_friedman1
        cmp_ = BenchmarkComparer(REG_CSV, precision=2)

        X, y = load_diabetes(return_X_y=True)
        Xtr, ytr, Xte, yte = _holdout(X, y, 350)
        b = train({"objective": "regression", "num_iterations": 200,
                   "min_data_in_leaf": 10}, Xtr, ytr)
        p = b.predict(Xte)
        cmp_.record("diabetes_r2", 1 - ((p - yte) ** 2).mean() / yte.var())

        X, y = make_friedman1(n_samples=1500, noise=1.0, random_state=5)
        Xtr, ytr, Xte, yte = _holdout(X, y, 1200)
        b = train({"objective": "regression", "num_iterations": 200,
                   "min_data_in_leaf": 10}, Xtr, ytr)
        p = b.predict(Xte)
        cmp_.record("friedman1_r2", 1 - ((p - yte) ** 2).mean() / yte.var())

        # quantile coverage (the notebook-106 quantile-regression shape)
        X, y = load_diabetes(return_X_y=True)
        b = train({"objective": "quantile", "alpha": 0.9,
                   "num_iterations": 100, "min_data_in_leaf": 10}, X, y)
        cmp_.record("diabetes_q90_coverage", (y <= b.predict(X)).mean())

        cmp_.verify()


class TestLearnerBenchmark:
    """Real-image E2E: sklearn digits (real 8x8 handwritten images)
    trained through TPULearner to a pinned holdout accuracy — the
    notebook-401 'train to a stated accuracy on real data' proof."""

    def test_digits_convnet_accuracy(self):
        from sklearn.datasets import load_digits

        from mmlspark_tpu.core.table import DataTable
        from mmlspark_tpu.models.learner import TPULearner

        X, y = load_digits(return_X_y=True)
        X = (X / 16.0).astype(np.float32)          # real pixel data
        Xtr, ytr, Xte, yte = _holdout(X, y.astype(np.int64), 1400)

        learner = TPULearner(
            networkSpec={"type": "convnet", "conv_features": [16, 16],
                         "dense_features": [64], "num_classes": 10,
                         "kernel": [3, 3]},
            inputShape=[8, 8, 1], epochs=30, batchSize=128,
            learningRate=0.05, computeDtype="float32", logEvery=10_000,
            seed=0)
        model = learner.fit(DataTable({"features": Xtr, "label": ytr}))
        out = model.transform(DataTable({"features": Xte}))
        acc = float((np.argmax(out["scores"], axis=1) == yte).mean())

        # precision=2 (+-0.01): tight enough that a broken optimizer or
        # feed-order bug fails, loose enough for backend math jitter
        # (VERDICT r4 weak #2: +-0.1 would miss a broken optimizer)
        cmp_ = BenchmarkComparer(DNN_CSV, precision=2)
        cmp_.record("digits_convnet_holdout_acc", acc)
        cmp_.verify()
        assert acc > 0.93, f"accuracy floor: {acc}"
