"""One TRACED serving-host process for the cross-process trace test.

The PR 12 serving_worker proves reply routing across real OS processes;
this worker proves TRACE routing: it runs a Tracer-enabled engine, and
on shutdown writes its whole trace buffer as Chrome trace-event JSON
(with the per-process ``process_name`` metadata) to the path given on
the command line. The parent test drives a fleet CLIENT
(``ServingFleet.connect``) through failover + hedging against several
of these workers, then reassembles ONE trace from the client's and the
workers' exported buffers (``core.trace.merge_chrome_traces``).

The scorer stalls when the request names THIS worker id
(``{"stall_worker": <wid>, "stall_s": 0.8}``), so the parent can make
exactly one leg slow — the deterministic hedge trigger.

Usage: python traced_worker.py <port> <worker_id> <dump_path>
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> None:
    port, wid, dump_path = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    from mmlspark_tpu.core.trace import Tracer
    from mmlspark_tpu.serving.server import HTTPSource, ServingEngine
    from mmlspark_tpu.stages.basic import Lambda

    stop = threading.Event()

    def handle(table):
        replies = []
        for r in table["request"]:
            body = json.loads(r["entity"].decode())
            if body.get("__shutdown__"):
                stop.set()
                replies.append({"bye": wid})
                continue
            if body.get("stall_worker") == wid:
                time.sleep(float(body.get("stall_s", 0.8)))
            replies.append({"echo": body["x"], "worker": wid,
                            "pid": os.getpid()})
        return table.with_column("reply", replies)

    tracer = Tracer(enabled=True)
    source = HTTPSource(host="127.0.0.1", port=port)
    engine = ServingEngine(source, Lambda.apply(handle), batch_size=8,
                           tracer=tracer, slo=False,
                           flight_recorder=False).start()
    print(f"READY {wid} {source.address} {os.getpid()}", flush=True)

    stop.wait(timeout=120)
    time.sleep(0.5)   # let the shutdown reply + stalled batches flush
    with open(dump_path, "w", encoding="utf-8") as f:
        json.dump(engine.export_traces(), f)
    print(f"DUMPED {wid} {dump_path}", flush=True)
    engine.stop()


if __name__ == "__main__":
    main()
