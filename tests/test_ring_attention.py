"""Ring attention / Ulysses sequence-parallelism tests.

Long-context support is new capability beyond the reference
(ref: SURVEY.md §5 — it has none); correctness bar: seq-parallel
attention must match dense attention to float tolerance in BOTH forward
and backward on the virtual 8-device mesh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from mmlspark_tpu.utils.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

from mmlspark_tpu.models.networks import Transformer
from mmlspark_tpu.parallel import mesh as mesh_lib
from mmlspark_tpu.parallel.ring_attention import (
    attention, make_seq_parallel_attention, make_seq_parallel_train_step,
    ring_attention, seq_parallel_apply, ulysses_attention,
)


def _qkv(B=2, L=64, H=8, D=16, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(rng.normal(size=(B, L, H, D)), jnp.float32)
                 for _ in range(3))


@pytest.fixture(scope="module")
def seq_mesh(cpu_mesh_devices):
    return mesh_lib.make_mesh({"seq": 8})


class TestForward:
    @pytest.mark.parametrize("kind", ["ring", "ulysses"])
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, seq_mesh, kind, causal):
        q, k, v = _qkv()
        ref = attention(q, k, v, causal=causal)
        fn = make_seq_parallel_attention(seq_mesh, kind=kind,
                                         causal=causal)
        out = fn(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=5e-5)

    def test_ulysses_requires_divisible_heads(self, seq_mesh):
        q, k, v = _qkv(H=4)  # 4 heads, 8 devices
        fn = make_seq_parallel_attention(seq_mesh, kind="ulysses")
        with pytest.raises(ValueError, match="divisible"):
            fn(q, k, v)

    def test_long_sequence_shards(self, seq_mesh):
        # 1024 tokens over 8 devices = 128/device
        q, k, v = _qkv(B=1, L=1024, H=8, D=8)
        ref = attention(q, k, v, causal=True)
        out = make_seq_parallel_attention(seq_mesh, causal=True)(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=5e-5)


class TestBackward:
    def test_ring_vjp_matches_dense(self, cpu_mesh_devices):
        mesh = mesh_lib.make_mesh({"seq": 4},
                                  devices=jax.devices()[:4])
        q, k, v = _qkv(B=1, L=16, H=2, D=8)
        w = jnp.asarray(np.random.default_rng(9).normal(
            size=(1, 16, 2, 8)), jnp.float32)

        def local_loss(q, k, v, w):
            out = ring_attention(q, k, v, axis_name="seq", causal=True)
            return jnp.sum(out * w)  # local; global loss = implicit sum

        gf = jax.jit(shard_map(
            lambda q, k, v, w: jax.grad(local_loss, argnums=(0, 1, 2))(
                q, k, v, w),
            mesh=mesh, in_specs=(P(None, "seq"),) * 4,
            out_specs=(P(None, "seq"),) * 3, check_vma=False))
        gq, gk, gv = gf(q, k, v, w)

        def dense_loss(q, k, v):
            return jnp.sum(attention(q, k, v, causal=True) * w)

        gq_r, gk_r, gv_r = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
        for a, b in [(gq, gq_r), (gk, gk_r), (gv, gv_r)]:
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-5)


class TestTransformerSeqParallel:
    def _model_pair(self, L, impl="ring", num_classes=0):
        kw = dict(vocab_size=64, dim=32, depth=2, heads=8, max_len=L,
                  num_classes=num_classes)
        return (Transformer(**kw),
                Transformer(seq_axis="seq", seq_impl=impl, **kw))

    @pytest.mark.parametrize("impl", ["ring", "ulysses"])
    def test_lm_logits_match_dense(self, seq_mesh, impl):
        L = 64
        dense, sp = self._model_pair(L, impl)
        tokens = jnp.asarray(np.random.default_rng(0).integers(
            0, 64, (2, L)), jnp.int32)
        variables = dense.init(jax.random.PRNGKey(0), tokens)
        ref = dense.apply(variables, tokens)
        out = seq_parallel_apply(sp, variables, tokens, seq_mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=5e-5)

    def test_classifier_pooling_matches(self, seq_mesh):
        L = 64
        dense, sp = self._model_pair(L, num_classes=5)
        tokens = jnp.asarray(np.random.default_rng(1).integers(
            0, 64, (2, L)), jnp.int32)
        variables = dense.init(jax.random.PRNGKey(0), tokens)
        ref = dense.apply(variables, tokens)
        out = seq_parallel_apply(sp, variables, tokens, seq_mesh)
        assert out.shape == (2, 5)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=5e-5)

    def test_global_seq_exceeding_max_len_raises(self, seq_mesh):
        # regression: dynamic_slice would silently clamp pos embeddings
        sp = Transformer(vocab_size=16, dim=16, depth=1, heads=4,
                         max_len=32, seq_axis="seq")
        dense = Transformer(vocab_size=16, dim=16, depth=1, heads=4,
                            max_len=32)
        variables = dense.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 32), jnp.int32))
        tokens = jnp.zeros((1, 64), jnp.int32)  # 64 global > max_len=32
        with pytest.raises(ValueError, match="max_len"):
            seq_parallel_apply(sp, variables, tokens, seq_mesh)

    def test_transformer_trains_via_tpu_learner(self, cpu_mesh_devices):
        # regression: registry network must be usable through TPULearner
        # (int_input capability flag, not a class-name special case)
        from mmlspark_tpu.core.table import DataTable
        from mmlspark_tpu.models.learner import TPULearner
        rng = np.random.default_rng(0)
        toks = rng.integers(0, 16, size=(32, 8)).astype(np.float64)
        labels = rng.integers(0, 16, size=(32, 8)).astype(np.int64)
        t = DataTable({"features": toks, "label": labels})
        learner = TPULearner(
            networkSpec={"type": "transformer", "vocab_size": 16,
                         "dim": 16, "depth": 1, "heads": 4,
                         "max_len": 8},
            loss="token_cross_entropy", epochs=1, batchSize=16,
            computeDtype="float32")
        model = learner.fit(t)
        out = model.transform(t)
        assert np.isfinite(np.asarray(out["scores"][0])).all()

    def test_train_step_loss_decreases(self, cpu_mesh_devices):
        import optax
        mesh = mesh_lib.make_mesh({"data": 2, "seq": 4})
        L = 32
        dense, sp = self._model_pair(L)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, 64, (4, L)), jnp.int32)
        targets = jnp.roll(tokens, -1, axis=1)
        params = dense.init(jax.random.PRNGKey(0), tokens)
        opt = optax.adam(1e-2)
        opt_state = opt.init(params)
        step = make_seq_parallel_train_step(sp, mesh, opt)
        losses = []
        for _ in range(10):
            params, opt_state, loss = step(params, opt_state, tokens,
                                           targets)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.9

    def test_train_step_grad_matches_dense(self, cpu_mesh_devices):
        """One step of the seq-parallel trainer == one dense step."""
        import optax
        mesh = mesh_lib.make_mesh({"data": 2, "seq": 4})
        L = 32
        dense, sp = self._model_pair(L)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, 64, (4, L)), jnp.int32)
        targets = jnp.roll(tokens, -1, axis=1)
        params = dense.init(jax.random.PRNGKey(0), tokens)
        opt = optax.sgd(0.1)
        step = make_seq_parallel_train_step(sp, mesh, opt)
        p_sp, _, loss_sp = step(params, opt.init(params), tokens, targets)

        def dense_loss(p):
            logits = dense.apply(p, tokens)
            ll = jax.nn.log_softmax(logits.astype(jnp.float32))
            picked = jnp.take_along_axis(ll, targets[..., None], axis=-1)
            return -picked.mean()

        loss_ref, g = jax.value_and_grad(dense_loss)(params)
        updates, _ = opt.update(g, opt.init(params), params)
        p_ref = optax.apply_updates(params, updates)
        np.testing.assert_allclose(float(loss_sp), float(loss_ref),
                                   atol=1e-5)
        errs = jax.tree_util.tree_map(
            lambda a, b: float(jnp.abs(a - b).max()), p_sp, p_ref)
        assert max(jax.tree_util.tree_leaves(errs)) < 1e-5


class TestRingFlash:
    """Every ring hop through the Pallas flash kernel
    (ring_flash_attention): no (Lq, Lk_local) score tensor exists in
    forward or backward; numerics match the dense ring."""

    def _mapped(self, mesh, causal, grad=False):
        from mmlspark_tpu.parallel.ring_attention import (
            ring_flash_attention,
        )

        def fwd(q, k, v):
            return ring_flash_attention(q, k, v, axis_name="seq",
                                        causal=causal, interpret=True)

        if grad:
            def loss(q, k, v):
                out = fwd(q, k, v)
                # local sums add up to the global loss under shard_map
                return jnp.sum(out ** 2)
            run = shard_map(
                jax.grad(loss, argnums=(0, 1, 2)), mesh=mesh,
                in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
                out_specs=(P(None, "seq"),) * 3, check_vma=False)
        else:
            run = shard_map(
                fwd, mesh=mesh,
                in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
                out_specs=P(None, "seq"), check_vma=False)
        return jax.jit(run)

    @pytest.mark.parametrize("causal", [True, False])
    def test_forward_matches_dense_ring(self, seq_mesh, causal):
        q, k, v = _qkv(L=64)
        ref = attention(q, k, v, causal=causal)
        out = self._mapped(seq_mesh, causal)(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=5e-5)

    @pytest.mark.parametrize("causal", [True, False])
    def test_grads_match_dense(self, seq_mesh, causal):
        q, k, v = _qkv(L=32)

        def dense_loss(q, k, v):
            from mmlspark_tpu.parallel.ring_attention import (
                dense_attention,
            )
            return jnp.sum(dense_attention(q, k, v, causal=causal) ** 2)

        ref = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
        got = self._mapped(seq_mesh, causal, grad=True)(q, k, v)
        for r, g2 in zip(ref, got):
            np.testing.assert_allclose(np.asarray(g2), np.asarray(r),
                                       atol=2e-3, rtol=2e-3)

    def test_no_dense_scores_in_jaxpr(self, seq_mesh):
        """The point of the exercise: the traced ring step must contain
        no (B, H, Lq, Lk) or (Lq, Lk)-shaped intermediate. Every >=2D
        f32 aval in the jaxpr whose trailing dims are (Lq_local,
        Lk_local) would be a dense score block."""
        import re
        from mmlspark_tpu.parallel.ring_attention import (
            ring_flash_attention,
        )
        # L_local (2048) far above the flash block sizes (256), so a
        # dense per-hop score block would be unmistakable in the avals
        B, L, H, D = 1, 16384, 2, 16
        l_loc = L // 8

        def fwd(q, k, v):
            return ring_flash_attention(q, k, v, axis_name="seq",
                                        causal=True, interpret=True)

        run = shard_map(
            fwd, mesh=seq_mesh,
            in_specs=(P(None, "seq"),) * 3, out_specs=P(None, "seq"),
            check_vma=False)
        q = jnp.zeros((B, L, H, D), jnp.float32)
        txt = str(jax.make_jaxpr(run)(q, q, q))
        hits = re.findall(rf"f32\[(?:\d+,)*{l_loc},{l_loc}\]", txt)
        assert not hits, f"dense (Lq, Lk) scores in ring jaxpr: {hits[:3]}"
