import os

import numpy as np
import pytest

from mmlspark_tpu.core.schema import ImageSchema
from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.stages.image import ImageSetAugmenter, ImageTransformer, UnrollImage
from mmlspark_tpu.testing.fuzzing import (
    TestObject, register_test_object, run_experiment_fuzzing,
    run_serialization_fuzzing,
)


def _img_table(n=4, h=16, w=20, c=3, seed=0):
    rng = np.random.default_rng(seed)
    rows = [ImageSchema.make_row(
        f"img_{i}.png", rng.integers(0, 256, (h, w, c), dtype=np.uint8))
        for i in range(n)]
    return DataTable({"image": rows})


def _ragged_img_table():
    rng = np.random.default_rng(1)
    rows = [ImageSchema.make_row(
        f"r_{i}.png", rng.integers(0, 256, (10 + i, 12, 3), dtype=np.uint8))
        for i in range(3)]
    return DataTable({"image": rows})


def test_resize_uniform_batch():
    t = _img_table()
    out = ImageTransformer().resize(8, 8).transform(t)
    img = out["image"][0]
    assert img[ImageSchema.HEIGHT] == 8 and img[ImageSchema.WIDTH] == 8
    assert img[ImageSchema.DATA].shape == (8, 8, 3)


def test_resize_ragged_host_path():
    t = _ragged_img_table()
    out = ImageTransformer().resize(8, 8).transform(t)
    assert all(r[ImageSchema.DATA].shape == (8, 8, 3) for r in out["image"])


def test_batch_and_host_paths_agree():
    t = _img_table()
    stage = ImageTransformer().resize(8, 10).flip(1)
    out_batch = stage.transform(t)

    # force host path by making ops "unbatchable" via center_crop
    stage_host = ImageTransformer().resize(8, 10).flip(1).center_crop(8, 10)
    out_host = stage_host.transform(t)
    for rb, rh in zip(out_batch["image"], out_host["image"]):
        # center_crop of same size is identity, so outputs should agree
        np.testing.assert_allclose(
            rb[ImageSchema.DATA].astype(int),
            rh[ImageSchema.DATA].astype(int), atol=1)


def test_crop_flip_threshold():
    t = _img_table()
    out = ImageTransformer().crop(2, 3, 6, 8).transform(t)
    assert out["image"][0][ImageSchema.DATA].shape == (6, 8, 3)

    src = t["image"][0][ImageSchema.DATA]
    flipped = ImageTransformer().flip(1).transform(t)["image"][0][ImageSchema.DATA]
    np.testing.assert_array_equal(flipped, src[:, ::-1, :])

    th = ImageTransformer().threshold(128, 255).transform(t)
    td = th["image"][0][ImageSchema.DATA]
    assert set(np.unique(td)).issubset({0, 255})


def test_gray_conversion():
    t = _img_table()
    out = ImageTransformer().color_format("BGR2GRAY").transform(t)
    img = out["image"][0]
    assert img[ImageSchema.CHANNELS] == 1
    assert img[ImageSchema.MODE] == "GRAY"


def test_blur_reduces_variance():
    t = _img_table()
    out = ImageTransformer().blur(5, 5).transform(t)
    v_in = np.var(t["image"][0][ImageSchema.DATA].astype(float))
    v_out = np.var(out["image"][0][ImageSchema.DATA].astype(float))
    assert v_out < v_in


def test_gaussian_kernel():
    t = _img_table()
    out = ImageTransformer().gaussian_kernel(5, 1.0).transform(t)
    assert out["image"][0][ImageSchema.DATA].shape == (16, 20, 3)


def test_unroll_order_matches_chw():
    t = _img_table(n=1, h=2, w=3, c=3)
    out = UnrollImage().transform(t)
    vec = out["unrolled"][0]
    img = t["image"][0][ImageSchema.DATA]
    expected = img.transpose(2, 0, 1).astype(np.float64).ravel()
    np.testing.assert_array_equal(vec, expected)
    assert vec.dtype == np.float64


def test_augmenter_doubles_rows():
    t = _img_table(n=3)
    out = ImageSetAugmenter(flipLeftRight=True, flipUpDown=False).transform(t)
    assert len(out) == 6
    out2 = ImageSetAugmenter(flipLeftRight=True, flipUpDown=True).transform(t)
    assert len(out2) == 12


def test_transform_schema_validates():
    t = DataTable({"x": [1, 2, 3]})
    with pytest.raises((TypeError, KeyError)):
        ImageTransformer().resize(4, 4).transform_schema(t.schema)


def test_io_roundtrip(tmp_path):
    import cv2
    from mmlspark_tpu.io import read_binary_files, read_images

    d = tmp_path / "imgs"
    d.mkdir()
    rng = np.random.default_rng(0)
    for i in range(3):
        cv2.imwrite(str(d / f"a_{i}.png"),
                    rng.integers(0, 256, (10, 12, 3), dtype=np.uint8))
    (d / "junk.txt").write_text("not an image")

    t = read_images(str(d))
    assert len(t) == 3
    img = t["image"][0]
    assert img[ImageSchema.DATA].shape == (10, 12, 3)
    assert img[ImageSchema.MODE] == "BGR"

    b = read_binary_files(str(d))
    assert len(b) == 4  # includes junk.txt

    bp = read_binary_files(str(d), pattern="*.txt")
    assert len(bp) == 1


def test_zip_inspection(tmp_path):
    import zipfile
    import cv2
    from mmlspark_tpu.io import read_images

    rng = np.random.default_rng(0)
    img_path = tmp_path / "x.png"
    cv2.imwrite(str(img_path), rng.integers(0, 256, (8, 8, 3), dtype=np.uint8))
    with zipfile.ZipFile(tmp_path / "arch.zip", "w") as zf:
        zf.write(img_path, "inner/y.png")
    t = read_images(str(tmp_path))
    assert len(t) == 2  # x.png + zipped y.png


# fuzzing registration ------------------------------------------------------

register_test_object(
    lambda: TestObject(ImageTransformer().resize(8, 8), _img_table()),
    ImageTransformer)
register_test_object(
    lambda: TestObject(UnrollImage(), _img_table()), UnrollImage)
register_test_object(
    lambda: TestObject(ImageSetAugmenter(), _img_table()), ImageSetAugmenter)


def test_image_stage_fuzzing():
    for factory_cls in (ImageTransformer, UnrollImage, ImageSetAugmenter):
        from mmlspark_tpu.testing.fuzzing import FUZZING_REGISTRY
        for factory in FUZZING_REGISTRY[factory_cls.__name__]:
            obj = factory()
            run_experiment_fuzzing(obj)
            run_serialization_fuzzing(obj)
