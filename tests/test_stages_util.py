"""Utility + data-prep stage tests (ref style: pipeline-stages suites —
construct stage, transform tiny inline table, assert values/schema)."""

import numpy as np
import pytest

from mmlspark_tpu.core.table import DataTable
from mmlspark_tpu.stages.basic import (
    Cacher, CheckpointData, ClassBalancer, DropColumns, Explode, Lambda,
    RenameColumn, Repartition, SelectColumns, TextPreprocessor, Timer,
    UDFTransformer,
)
from mmlspark_tpu.stages.dataprep import (
    CleanMissingData, DataConversion, EnsembleByKey, MultiColumnAdapter,
    PartitionSample, SummarizeData, ValueIndexer,
)


@pytest.fixture
def basic_table():
    return DataTable({
        "a": [1.0, 2.0, np.nan, 4.0],
        "b": ["x", "y", "x", "z"],
        "lists": [[1, 2], [3], [4, 5, 6], [7]],
    })


class TestBasicStages:
    def test_drop_select_rename(self, basic_table):
        assert DropColumns(cols=["lists"]).transform(
            basic_table).column_names == ["a", "b"]
        assert SelectColumns(cols=["b"]).transform(
            basic_table).column_names == ["b"]
        out = RenameColumn(inputCol="a", outputCol="alpha").transform(
            basic_table)
        assert "alpha" in out.column_names and "a" not in out.column_names

    def test_cacher_identity(self, basic_table):
        out = Cacher().transform(basic_table)
        assert out.to_rows()[1]["b"] == "y"

    def test_repartition(self, basic_table):
        out = Repartition(n=2).transform(basic_table)
        assert out.num_shards == 2
        assert len(out.shards()) == 2

    def test_explode(self, basic_table):
        out = Explode(inputCol="lists", outputCol="item").transform(
            basic_table)
        assert len(out) == 7
        assert out.to_rows()[0]["item"] == 1

    def test_lambda(self, basic_table):
        stage = Lambda.apply(lambda t: t.filter(
            np.asarray([True, False, True, False])))
        assert len(stage.transform(basic_table)) == 2

    def test_udf_transformer_single_and_multi(self, basic_table):
        out = UDFTransformer(inputCol="b", outputCol="b_up",
                             udf=str.upper).transform(basic_table)
        assert list(out["b_up"]) == ["X", "Y", "X", "Z"]
        out2 = UDFTransformer(
            inputCols=["a", "b"], outputCol="joined",
            udf=lambda a, b: f"{b}{a}").transform(basic_table)
        assert out2["joined"][0] == "x1.0"

    def test_class_balancer(self, basic_table):
        model = ClassBalancer(inputCol="b").fit(basic_table)
        w = model.transform(basic_table)["weight"]
        # 'x' appears twice -> weight 1; 'y'/'z' once -> weight 2
        np.testing.assert_allclose(w, [1.0, 2.0, 1.0, 2.0])

    def test_text_preprocessor_longest_match(self):
        t = DataTable({"s": ["abcd", "ab"]})
        out = TextPreprocessor(
            inputCol="s", outputCol="s",
            map={"ab": "1", "abc": "2"}).transform(t)
        # longest match first: "abcd" -> "2d", not "1cd"
        assert list(out["s"]) == ["2d", "1"]

    def test_timer_wraps_transformer(self, basic_table):
        out = Timer(stage=DropColumns(cols=["lists"])).transform(
            basic_table)
        assert out.column_names == ["a", "b"]

    def test_timer_wraps_estimator(self, basic_table):
        timed = Timer(stage=ClassBalancer(inputCol="b"))
        model = timed.fit(basic_table)
        assert "weight" in model.transform(basic_table).column_names

    def test_timer_emits_profiler_trace(self, basic_table, tmp_path):
        # SURVEY §5: Timer upgrades the reference's wall-clock logging
        # (Timer.scala:54) to a real jax.profiler xplane trace
        from mmlspark_tpu.utils.profiling import trace_files

        class _Jitted(DropColumns):
            def transform(self, table):
                import jax, jax.numpy as jnp  # noqa: E401
                jax.jit(lambda v: v * 2)(jnp.ones(8)).block_until_ready()
                return super().transform(table)

        trace_dir = str(tmp_path / "trace")
        Timer(stage=_Jitted(cols=["lists"]),
              traceDir=trace_dir).transform(basic_table)
        assert trace_files(trace_dir), "no xplane trace emitted"

    def test_timer_in_pipeline_fits_once(self, basic_table):
        # regression: Timer must be an Estimator so the pipeline stores
        # the FITTED inner model, not a refit-on-transform wrapper
        from mmlspark_tpu.core.stage import Pipeline
        from mmlspark_tpu.stages.dataprep import ValueIndexer
        pipe = Pipeline([Timer(stage=ValueIndexer(inputCol="b",
                                                  outputCol="bi"))])
        model = pipe.fit(basic_table)
        test_t = DataTable({"b": ["z", "x"]})  # different level set
        out = model.transform(test_t)
        # train levels were x,y,z -> z=2, x=0 (NOT refit on test data)
        np.testing.assert_allclose(out["bi"], [2.0, 0.0])

    def test_explode_empty_keeps_schema(self):
        t = DataTable({"lists": [[], []], "k": [1.0, 2.0]})
        out = Explode(inputCol="lists", outputCol="item").transform(t)
        assert len(out) == 0
        assert "k" in out.column_names and "item" in out.column_names

    def test_checkpoint_data(self, basic_table, tmp_path):
        stage = CheckpointData(diskIncluded=True,
                               checkpointDir=str(tmp_path))
        out = stage.transform(basic_table)
        assert len(out) == 4
        import os
        assert any(p.startswith("checkpoint_")
                   for p in os.listdir(tmp_path))


class TestValueIndexer:
    def test_index_and_metadata(self, basic_table):
        model = ValueIndexer(inputCol="b", outputCol="b_idx").fit(
            basic_table)
        out = model.transform(basic_table)
        np.testing.assert_allclose(out["b_idx"], [0, 1, 0, 2])
        assert out.schema["b_idx"].meta["levels"] == ["x", "y", "z"]
        assert out.schema["b_idx"].meta["categorical"] is True

    def test_unindex_roundtrip(self, basic_table):
        model = ValueIndexer(inputCol="b", outputCol="b_idx").fit(
            basic_table)
        t = model.transform(basic_table)
        back = model.unindex(t, "b_idx", "b_back")
        assert list(back["b_back"]) == ["x", "y", "x", "z"]

    def test_unknown_value_maps_negative(self, basic_table):
        model = ValueIndexer(inputCol="b", outputCol="i").fit(basic_table)
        t2 = DataTable({"b": ["q"]})
        assert model.transform(t2)["i"][0] == -1

    def test_save_load(self, basic_table, tmp_path):
        model = ValueIndexer(inputCol="b", outputCol="i").fit(basic_table)
        model.save(str(tmp_path / "vi"))
        from mmlspark_tpu.stages.dataprep import ValueIndexerModel
        m2 = ValueIndexerModel.load(str(tmp_path / "vi"))
        assert m2.get("levels") == ["x", "y", "z"]


class TestCleanMissingData:
    def test_mean_impute(self, basic_table):
        model = CleanMissingData(inputCols=["a"], outputCols=["a"],
                                 cleaningMode="Mean").fit(basic_table)
        out = model.transform(basic_table)
        np.testing.assert_allclose(out["a"][2], (1 + 2 + 4) / 3)

    def test_median_impute(self, basic_table):
        model = CleanMissingData(inputCols=["a"], outputCols=["a"],
                                 cleaningMode="Median").fit(basic_table)
        assert model.transform(basic_table)["a"][2] == 2.0

    def test_custom_impute(self, basic_table):
        model = CleanMissingData(inputCols=["a"], outputCols=["a_c"],
                                 cleaningMode="Custom",
                                 customValue=-1.0).fit(basic_table)
        out = model.transform(basic_table)
        assert out["a_c"][2] == -1.0
        assert np.isnan(out["a"][2])  # original untouched


class TestDataConversion:
    def test_numeric_casts(self):
        t = DataTable({"x": [1.5, 2.5]})
        out = DataConversion(cols=["x"], convertTo="integer").transform(t)
        assert out["x"].dtype == np.int32
        out = DataConversion(cols=["x"], convertTo="string").transform(t)
        assert list(out["x"]) == ["1.5", "2.5"]

    def test_to_categorical(self):
        t = DataTable({"x": ["b", "a", "b"]})
        out = DataConversion(cols=["x"],
                             convertTo="toCategorical").transform(t)
        assert out.schema["x"].meta.get("categorical")
        np.testing.assert_allclose(out["x"], [1, 0, 1])

    def test_date_parse(self):
        t = DataTable({"d": ["2026-07-29 10:00:00"]})
        out = DataConversion(cols=["d"], convertTo="date").transform(t)
        assert out["d"][0].year == 2026


class TestSummarizeData:
    def test_stats_shape_and_values(self, basic_table):
        s = SummarizeData().transform(basic_table)
        assert list(s["Feature"]) == ["a", "b", "lists"]
        row_a = s.to_rows()[0]
        assert row_a["Missing_Value_Count"] == 1.0
        assert row_a["Min"] == 1.0 and row_a["Max"] == 4.0
        assert "Median" in row_a

    def test_subset_flags(self, basic_table):
        s = SummarizeData(percentiles=False, sample=False).transform(
            basic_table)
        assert "Median" not in s.column_names


class TestPartitionSample:
    def test_head(self, basic_table):
        assert len(PartitionSample(mode="Head", count=2).transform(
            basic_table)) == 2

    def test_random_sample_fraction(self):
        t = DataTable({"x": np.arange(1000).astype(float)})
        out = PartitionSample(mode="RandomSample", percent=0.3,
                              rs_seed=1).transform(t)
        assert 200 < len(out) < 400

    def test_assign_to_partition(self, basic_table):
        out = PartitionSample(mode="AssignToPartition",
                              numParts=2).transform(basic_table)
        assert set(np.unique(out["Partition"])) <= {0, 1}


class TestEnsembleByKey:
    def test_scalar_mean_collapse(self):
        t = DataTable({"k": ["a", "a", "b"], "v": [1.0, 3.0, 5.0]})
        out = EnsembleByKey(keys=["k"], cols=["v"]).transform(t)
        rows = {r["k"]: r["v_avg"] for r in out.to_rows()}
        assert rows == {"a": 2.0, "b": 5.0}

    def test_vector_mean_no_collapse(self):
        t = DataTable({"k": ["a", "a"],
                       "v": np.asarray([[1.0, 2.0], [3.0, 4.0]])})
        out = EnsembleByKey(keys=["k"], cols=["v"],
                            collapseGroup=False).transform(t)
        assert len(out) == 2
        np.testing.assert_allclose(out.to_rows()[0]["v_avg"], [2.0, 3.0])


class TestMultiColumnAdapter:
    def test_applies_stage_per_column(self):
        from mmlspark_tpu.stages.text import Tokenizer
        t = DataTable({"s1": ["a b", "c d"], "s2": ["e f", "g h"]})
        out = MultiColumnAdapter(
            baseStage=Tokenizer(), inputCols=["s1", "s2"],
            outputCols=["t1", "t2"]).transform(t)
        assert out["t1"][0] == ["a", "b"]
        assert out["t2"][1] == ["g", "h"]

    def test_estimator_base_keeps_train_state(self):
        # regression: fitted per-column state must come from fit()'s
        # table, not the scoring table (train/serve skew)
        train_t = DataTable({"c": ["a", "b", "c", "a"]})
        model = MultiColumnAdapter(
            baseStage=ValueIndexer(), inputCols=["c"],
            outputCols=["ci"]).fit(train_t)
        test_t = DataTable({"c": ["c", "c", "b", "x"]})
        out = model.transform(test_t)
        np.testing.assert_allclose(out["ci"], [2, 2, 1, -1])

    def test_estimator_base_transform_without_fit_raises(self):
        t = DataTable({"c": ["a"]})
        with pytest.raises(TypeError, match="fit"):
            MultiColumnAdapter(baseStage=ValueIndexer(),
                               inputCols=["c"],
                               outputCols=["ci"]).transform(t)
