"""Multi-host fabric drills: real 2-process ``jax.distributed`` groups
on this box (tests/multihost_worker.py), the bounded-rendezvous failure
envelope, and the honest multi-machine floor gate.

The existing tests/test_distributed.py psum drill skips on jax < 0.5
("multiprocess computations aren't implemented on the CPU backend") —
that predates the gloo CPU-collectives backend
``parallel.distributed.initialize`` now configures, which is exactly
what makes a 2-process group's allgather/psum run for real here.
"""

import hashlib
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(port: int, pid: int, nproc: int, *extra: str):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    return subprocess.Popen(
        [sys.executable, WORKER, str(port), str(pid), str(nproc),
         *extra],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env)


def _single_group_oracle():
    """Replay the 2-host run in THIS process: the same per-host
    quantile-sketch summaries merged in process order (through the
    to_wire/from_wire roundtrip the collective pays), frozen into the
    mapper, and the forest grown over a 2-device local mesh with the
    same global row order — the single-group oracle the multi-host
    forest must match bit-for-bit."""
    import jax

    from mmlspark_tpu.gbdt.binning import BinMapper
    from mmlspark_tpu.gbdt.booster import train as gbdt_train
    from mmlspark_tpu.gbdt.sketch import QuantileSketch
    from mmlspark_tpu.parallel import mesh as mesh_lib

    grng = np.random.default_rng(11)
    GX = grng.normal(size=(400, 6))
    GY = (GX[:, 0] + 0.5 * GX[:, 1] > 0).astype(float)

    wires = []
    for pid in range(2):
        lo, hi = pid * 200, (pid + 1) * 200
        sks = [QuantileSketch() for _ in range(6)]
        for blk in (GX[lo:lo + 100], GX[lo + 100:hi]):
            for j, sk in enumerate(sks):
                sk.update(blk[:, j])
        wires.append(np.stack([sk.to_wire(512) for sk in sks]))
    merged = [QuantileSketch.from_wire(wires[0][j]) for j in range(6)]
    for j, sk in enumerate(merged):
        sk.merge(QuantileSketch.from_wire(wires[1][j]))
    mapper = BinMapper.fit_streaming([], max_bin=15, sketches=merged)
    bin_digest = hashlib.sha256(
        b"".join(u.tobytes() for u in mapper.upper_bounds)
    ).hexdigest()[:16]

    shards = [(GX[k:k + 100], GY[k:k + 100]) for k in range(0, 400, 100)]
    mesh = mesh_lib.make_mesh({"data": 2}, devices=jax.devices()[:2])
    params = {"objective": "binary", "num_iterations": 5,
              "num_leaves": 7, "max_bin": 15, "min_data_in_leaf": 5,
              "parallelism": "data", "hist_method": "scatter",
              "bin_fit": "sketch"}
    booster = gbdt_train(params, shards, bin_mapper=mapper, mesh=mesh)
    forest_digest = hashlib.sha256(
        booster.model_to_string().encode()).hexdigest()[:16]
    # the quantized reduce-scatter oracle: integer histograms make the
    # 2-device local replay exactly the 2-process group's arithmetic
    qbooster = gbdt_train(
        {**params, "hist_bits": 16, "hist_comm": "reduce_scatter"},
        shards, bin_mapper=mapper, mesh=mesh)
    q_digest = hashlib.sha256(
        qbooster.model_to_string().encode()).hexdigest()[:16]
    return forest_digest, bin_digest, q_digest


class TestProcessGroupDrill:
    def test_two_process_sketch_gbdt_and_serving_jit(self):
        """The tier-1 fabric drill: a REAL 2-process jax.distributed
        group rendezvouses on this box; the multi-host sketch-binned
        GBDT forest is bit-identical across hosts AND to the
        single-group oracle; the explicit-shardings serving jit runs
        under the group with its batch dim sharded across processes."""
        port = _free_port()
        procs = [_spawn(port, pid, 2) for pid in range(2)]
        outs = []
        try:
            for p in procs:
                out, err = p.communicate(timeout=240)
                outs.append((p.returncode, out, err))
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail(f"fabric workers hung; partial: {outs}")

        digests, bins, jits, totals = {}, {}, {}, {}
        qdigests, comm = {}, {}
        for rc, out, err in outs:
            assert rc == 0, f"worker failed (rc={rc}):\n{out}\n{err}"
            assert "OK" in out, out
            for line in out.splitlines():
                if line.startswith("DIGEST"):
                    _, pid, digest, bdig, acc_ok = line.split()
                    digests[int(pid)] = digest
                    bins[int(pid)] = bdig
                    assert acc_ok == "1", line
                if line.startswith("QDIGEST"):
                    _, pid, qdig, qacc_ok = line.split()
                    qdigests[int(pid)] = qdig
                    assert qacc_ok == "1", line
                if line.startswith("COMM"):
                    _, pid, tag, ps, rs, ag = line.split()
                    comm[(int(pid), tag)] = (float(ps) + float(rs)
                                             + float(ag))
                if line.startswith("SERVEJIT"):
                    _, pid, ok, total = line.split()
                    jits[int(pid)] = ok
                    totals[int(pid)] = total
        # bit-identical across the group
        assert len(digests) == 2 and len(set(digests.values())) == 1, \
            digests
        assert len(set(bins.values())) == 1, bins
        # PR 19: the quantized reduce-scatter forest is also
        # bit-identical across the group...
        assert len(qdigests) == 2 \
            and len(set(qdigests.values())) == 1, qdigests
        # ... and its modeled collective wire is >=2x under f32 psum's
        for pid in (0, 1):
            assert comm[(pid, "f32")] >= 2.0 * comm[(pid, "q16")], comm
        # explicit-shardings jit ran under the group on every member,
        # and both members fetched the same replicated global reduction
        assert jits == {0: "1", 1: "1"}, jits
        assert len(set(totals.values())) == 1, totals
        # ... and bit-identical to the single-group oracle (pinned)
        oracle_forest, oracle_bins, oracle_q = _single_group_oracle()
        assert bins[0] == oracle_bins, (
            "multi-host agreed sketch cuts differ from the single-group "
            "merged-sketch oracle")
        assert digests[0] == oracle_forest, (
            "multi-host sketch-binned forest is not bit-identical to "
            "the single-group oracle")
        assert qdigests[0] == oracle_q, (
            "multi-host quantized reduce-scatter forest is not "
            "bit-identical to the single-group oracle")

    def test_member_death_raises_cleanly_within_timeout(self):
        """Member death during rendezvous: the survivor gets a clean
        ProcessGroupError within the BOUNDED timeout (exit code 7 from
        the worker) — never a hang."""
        port = _free_port()
        survivor = _spawn(port, 0, 2, "--timeout-s", "10")
        dead = _spawn(port, 1, 2, "--die-before-rendezvous")
        t0 = time.monotonic()
        try:
            d_out, _ = dead.communicate(timeout=30)
            s_out, s_err = survivor.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            survivor.kill()
            dead.kill()
            pytest.fail("member-death rendezvous hung past the bounded "
                        "timeout")
        wall = time.monotonic() - t0
        assert dead.returncode == 3 and "DIED 1" in d_out
        assert survivor.returncode == 7, (
            f"survivor rc={survivor.returncode}:\n{s_out}\n{s_err}")
        assert "GROUP_ERROR 0" in s_out, s_out
        # bounded: the 10 s rendezvous timeout plus interpreter startup
        assert wall < 90, f"took {wall:.1f}s"


class TestProcessGroupGate:
    def test_single_process_gate(self):
        """The honest multi-machine gate: outside a group,
        in_process_group() is False and require_process_group raises
        the actionable ProcessGroupError (floors SKIP on it instead of
        faking multi-host numbers)."""
        from mmlspark_tpu.parallel import distributed as dist
        assert not dist.in_process_group()
        with pytest.raises(dist.ProcessGroupError,
                           match="process_count=1"):
            dist.require_process_group(2)
        info = dist.require_process_group(1)   # trivially satisfied
        assert info.process_count == 1
