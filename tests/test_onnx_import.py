"""ONNX ingestion tests (importers/onnx_import.py).

The correctness bar mirrors the torchvision-import suite: an ONNX
resnet18 file — genuine protobuf bytes produced by an independent
writer (tests/onnx_writer.py), not by the reader's own code — must
predict identically to a same-weights torch model through TPUModel
(ref: ModelDownloader.scala:209 — the zoo serves real published CNNs).
"""

import numpy as np
import pytest

from mmlspark_tpu.importers.onnx_import import (
    OnnxApply, import_onnx_model, load_onnx, onnx_summary,
)
from tests import onnx_writer as ow


@pytest.fixture(scope="module")
def resnet18_file(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("onnx") / "resnet18.onnx")
    weights = ow.resnet18_onnx(path, num_classes=10, width=8, seed=3)
    return path, weights


def _torch_resnet18(weights, num_classes=10, width=8):
    """torchvision-architecture resnet18 built from plain torch.nn,
    loaded with the generated weights — the ground truth."""
    import torch
    import torch.nn as nn

    class BasicBlock(nn.Module):
        def __init__(self, cin, cout, stride):
            super().__init__()
            self.conv1 = nn.Conv2d(cin, cout, 3, stride, 1, bias=False)
            self.bn1 = nn.BatchNorm2d(cout)
            self.conv2 = nn.Conv2d(cout, cout, 3, 1, 1, bias=False)
            self.bn2 = nn.BatchNorm2d(cout)
            self.downsample = None
            if stride != 1 or cin != cout:
                self.downsample = nn.Sequential(
                    nn.Conv2d(cin, cout, 1, stride, bias=False),
                    nn.BatchNorm2d(cout))

        def forward(self, x):
            idn = x if self.downsample is None else self.downsample(x)
            y = torch.relu(self.bn1(self.conv1(x)))
            y = self.bn2(self.conv2(y))
            return torch.relu(y + idn)

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = nn.Conv2d(3, width, 7, 2, 3, bias=False)
            self.bn1 = nn.BatchNorm2d(width)
            self.maxpool = nn.MaxPool2d(3, 2, 1)
            cin = width
            for li, (cout, stride) in enumerate(
                    [(width, 1), (2 * width, 2), (4 * width, 2),
                     (8 * width, 2)]):
                blocks = []
                for blk in range(2):
                    blocks.append(BasicBlock(
                        cin, cout, stride if blk == 0 else 1))
                    cin = cout
                setattr(self, f"layer{li + 1}", nn.Sequential(*blocks))
            self.fc = nn.Linear(8 * width, num_classes)

        def forward(self, x):
            x = self.maxpool(torch.relu(self.bn1(self.conv1(x))))
            x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
            x = x.mean(dim=(2, 3))
            return self.fc(x)

    net = Net().eval()
    state = {}
    for k, v in weights.items():
        # the ONNX conv weights carry no bias; names already match
        # torch's state_dict convention by construction
        state[k] = torch.from_numpy(np.asarray(v))
    missing, unexpected = net.load_state_dict(state, strict=False)
    # only num_batches_tracked counters may be missing
    assert all("num_batches_tracked" in m for m in missing), missing
    assert not unexpected, unexpected
    return net


class TestWireParsing:
    def test_summary(self, resnet18_file):
        path, weights = resnet18_file
        s = onnx_summary(path)
        assert s["ops"]["Conv"] == 20          # 16 block + 3 downsample + stem
        assert s["ops"]["BatchNormalization"] == 20
        assert s["ops"]["Add"] == 8
        assert s["ops"]["Gemm"] == 1
        assert s["num_initializers"] == len(weights)
        assert s["inputs"] == ["input"]
        assert s["outputs"] == ["output"]

    def test_initializer_roundtrip(self, resnet18_file):
        path, weights = resnet18_file
        graph = load_onnx(path)
        for name, arr in weights.items():
            np.testing.assert_array_equal(graph.initializers[name], arr)

    def test_unsupported_op_rejected(self, tmp_path):
        blob = ow.model([ow.node("Einsum", ["x"], ["y"])], {}, "x", "y")
        p = tmp_path / "bad.onnx"
        p.write_bytes(blob)
        with pytest.raises(ValueError, match="Einsum"):
            load_onnx(str(p))

    def test_not_onnx_rejected(self, tmp_path):
        p = tmp_path / "junk.onnx"
        p.write_bytes(b"\x00\x01\x02")
        with pytest.raises(ValueError):
            load_onnx(str(p))


class TestExecution:
    def test_resnet18_matches_torch(self, resnet18_file):
        path, weights = resnet18_file
        net = _torch_resnet18(weights)
        import torch
        rng = np.random.default_rng(0)
        x = rng.normal(size=(4, 3, 64, 64)).astype(np.float32)
        with torch.no_grad():
            ref = net(torch.from_numpy(x)).numpy()
        graph = load_onnx(path)
        out = np.asarray(OnnxApply(graph)(
            {k: np.asarray(v) for k, v in graph.initializers.items()},
            {"images": x}))
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)

    def test_through_tpu_model(self, resnet18_file):
        from mmlspark_tpu.core.table import DataTable
        path, weights = resnet18_file
        net = _torch_resnet18(weights)
        import torch
        rng = np.random.default_rng(1)
        x = rng.normal(size=(6, 3, 32, 32)).astype(np.float32)
        with torch.no_grad():
            ref = net(torch.from_numpy(x)).numpy()
        model = import_onnx_model(path, batch_size=4,
                                  input_shape=[3, 32, 32])
        table = DataTable({"images": x.reshape(6, -1)})
        out = np.asarray(model.transform(table)["scores"])
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)
        assert np.array_equal(out.argmax(1), ref.argmax(1))

    def test_pool_variants_and_clip(self, tmp_path):
        """AveragePool/Reshape/Clip ops against torch semantics —
        Reshape's target is an int64 initializer (the torch.onnx.export
        pattern) and the whole graph runs JITTED through TPUModel, the
        path where a traced shape tensor could not concretize."""
        import torch
        from mmlspark_tpu.core.table import DataTable
        rng = np.random.default_rng(2)
        x = rng.normal(size=(2, 3, 8, 8)).astype(np.float32)
        nodes = [
            ow.node("AveragePool", ["input"], ["ap"], kernel_shape=[2, 2],
                    strides=[2, 2], pads=[0, 0, 0, 0]),
            ow.node("Clip", ["ap"], ["cl"], min=-0.5, max=0.5),
            ow.node("Reshape", ["cl", "shape"], ["output"]),
        ]
        inits = {"shape": np.asarray([0, -1], np.int64)}  # 0 = keep dim
        p = tmp_path / "pool.onnx"
        p.write_bytes(ow.model(nodes, inits, "input", "output"))
        graph = load_onnx(str(p))
        ref = torch.clamp(
            torch.nn.functional.avg_pool2d(torch.from_numpy(x), 2, 2),
            -0.5, 0.5).flatten(1).numpy()
        out = np.asarray(OnnxApply(graph)(
            {"shape": inits["shape"]}, {"images": x}))
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
        # jitted path: TPUModel compiles the executor; weights (incl.
        # the shape initializer) become tracers
        model = import_onnx_model(str(p), batch_size=2,
                                  input_shape=[3, 8, 8])
        out2 = np.asarray(model.transform(
            DataTable({"images": x.reshape(2, -1)}))["scores"])
        np.testing.assert_allclose(out2, ref, rtol=1e-5, atol=1e-6)

    def test_float16_bit_pattern_payload(self, tmp_path):
        """FLOAT16 int32_data carries uint16 BIT PATTERNS per spec —
        reinterpreted, not value-cast."""
        import struct as _struct
        vals = np.asarray([1.0, -2.5, 0.5], np.float16)
        bits = vals.view(np.uint16)
        # hand-encode a TensorProto with int32_data (field 5, varints)
        body = b""
        body += ow._int_field(1, 3)                  # dims = [3]
        body += ow._int_field(2, 10)                 # data_type FLOAT16
        for b in bits:
            body += ow._int_field(5, int(b))         # int32_data
        body += ow._ld(8, b"w")                      # name
        nodes = [ow.node("Identity", ["input"], ["output"])]
        graph = b"".join([ow._ld(1, n) for n in nodes]) \
            + ow._ld(5, body) \
            + ow._ld(11, ow._value_info("input")) \
            + ow._ld(12, ow._value_info("output"))
        blob = ow._int_field(1, 8) + ow._ld(7, graph)
        p = tmp_path / "f16.onnx"
        p.write_bytes(blob)
        graph_p = load_onnx(str(p))
        np.testing.assert_array_equal(
            graph_p.initializers["w"].astype(np.float32),
            vals.astype(np.float32))

    def test_truncated_file_fails_fast(self, resnet18_file, tmp_path):
        path, _ = resnet18_file
        with open(path, "rb") as f:
            blob = f.read()
        p = tmp_path / "trunc.onnx"
        p.write_bytes(blob[:len(blob) // 2])
        with pytest.raises(ValueError):
            load_onnx(str(p))


class TestDownloaderPublish:
    def test_publish_and_reload(self, resnet18_file, tmp_path):
        """ONNX models publish through ModelDownloader like every zoo
        model: blob + sha256 schema, reload, predict."""
        from mmlspark_tpu.downloader import LocalRepo
        path, _ = resnet18_file
        repo = LocalRepo(str(tmp_path / "repo"))
        with open(path, "rb") as f:
            blob = f.read()
        repo.publish(
            "onnx_resnet18",
            {"format": "onnx", "onnx_summary": onnx_summary(path)},
            blob=blob, model_type="classification")
        got = repo.get_schema("onnx_resnet18")
        assert got.network_spec["onnx_summary"]["ops"]["Conv"] == 20
        blob2 = repo.read_blob(got, verify=True)
        assert blob2 == blob
        # reload from the repo blob and execute
        p2 = tmp_path / "reload.onnx"
        p2.write_bytes(blob2)
        model = import_onnx_model(str(p2))
        assert model is not None


class TestOpVariants:
    """Per-op parity for paths the resnet graph doesn't exercise."""

    def _run(self, tmp_path, nodes, inits, x, name="g.onnx"):
        p = tmp_path / name
        p.write_bytes(ow.model(nodes, inits, "input", "output"))
        graph = load_onnx(str(p))
        return np.asarray(OnnxApply(graph)(
            {k: np.asarray(v) for k, v in graph.initializers.items()},
            {"images": x}))

    def test_matmul_and_constant(self, tmp_path):
        rng = np.random.default_rng(5)
        x = rng.normal(size=(4, 6)).astype(np.float32)
        w = rng.normal(size=(6, 3)).astype(np.float32)
        c = np.asarray([1.0, 2.0, 3.0], np.float32)
        nodes = [
            ow.node("MatMul", ["input", "w"], ["mm"]),
            ow.node("Constant", [], ["c"], value=c),
            ow.node("Add", ["mm", "c"], ["output"]),
        ]
        out = self._run(tmp_path, nodes, {"w": w}, x)
        np.testing.assert_allclose(out, x @ w + c, rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("transA,transB", [(0, 0), (0, 1), (1, 0)])
    def test_gemm_transpose_variants(self, tmp_path, transA, transB):
        rng = np.random.default_rng(6)
        A = rng.normal(size=(5, 4)).astype(np.float32)
        x = A.T if transA else A
        B = rng.normal(size=(4, 3)).astype(np.float32)
        w = B.T if transB else B
        bias = rng.normal(size=3).astype(np.float32)
        nodes = [ow.node("Gemm", ["input", "w", "b"], ["output"],
                         alpha=1.0, beta=0.5, transA=transA,
                         transB=transB)]
        out = self._run(tmp_path, nodes, {"w": w, "b": bias}, x)
        np.testing.assert_allclose(out, A @ B + 0.5 * bias,
                                   rtol=1e-5, atol=1e-6)


def _torch_bilstm(vocab, embed, hidden, tags, seed=0):
    import torch
    import torch.nn as nn
    torch.manual_seed(seed)

    class Tagger(nn.Module):
        def __init__(self):
            super().__init__()
            self.embed = nn.Embedding(vocab, embed)
            self.lstm = nn.LSTM(embed, hidden, batch_first=True,
                                bidirectional=True)
            self.fc = nn.Linear(2 * hidden, tags)

        def forward(self, ids):
            h, _ = self.lstm(self.embed(ids))
            return self.fc(h)

    return Tagger()


class TestBiLSTMImport:
    """The notebook-304 flagship imported from a GENUINE ONNX file —
    the reference's arbitrary-graph ingestion bar (ref: src/cntk-model/
    src/main/scala/CNTKModel.scala:147): recurrent ops, integer inputs,
    a symbolic (dim_param) batch axis, and an int64_data-stored Reshape
    target containing -1 (signed varint decode)."""

    V, E, H, TAGS, T = 50, 16, 24, 7, 12

    @pytest.fixture(scope="class")
    def bilstm_file(self, tmp_path_factory):
        net = _torch_bilstm(self.V, self.E, self.H, self.TAGS)
        sd = {k: v.detach().numpy() for k, v in net.state_dict().items()}
        path = tmp_path_factory.mktemp("onnx") / "bilstm.onnx"
        ow.bilstm_onnx(str(path), sd, seq_len=self.T)
        return str(path), net

    def test_summary_and_flags(self, bilstm_file):
        path, _ = bilstm_file
        s = onnx_summary(path)
        assert s["ops"]["LSTM"] == 1
        assert s["opset"] == 17
        graph = load_onnx(path)
        apply_fn = OnnxApply(graph)
        assert apply_fn.int_input          # INT64 token input declared
        model = import_onnx_model(path, batch_size=4)
        # input_shape inferred from the declared (N, T) input: (T,)
        assert model.get("modelFn").input_shape == (self.T,)

    def test_matches_torch(self, bilstm_file):
        import torch
        path, net = bilstm_file
        rng = np.random.default_rng(7)
        ids = rng.integers(0, self.V, size=(5, self.T))
        with torch.no_grad():
            ref = net(torch.from_numpy(ids)).numpy()
        graph = load_onnx(path)
        out = np.asarray(OnnxApply(graph)(
            {k: np.asarray(v) for k, v in graph.initializers.items()},
            {"tokens": ids.astype(np.int32)}))
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=1e-5)

    def test_through_tpu_model_dynamic_batch(self, bilstm_file):
        """Jitted serving path at TWO batch sizes (the dim_param
        contract), int32 token feed, argmax parity with torch."""
        import torch
        from mmlspark_tpu.core.table import DataTable
        path, net = bilstm_file
        model = import_onnx_model(path, batch_size=4)
        rng = np.random.default_rng(8)
        for n in (3, 6):
            ids = rng.integers(0, self.V, size=(n, self.T))
            with torch.no_grad():
                ref = net(torch.from_numpy(ids)).numpy()
            out = np.asarray(model.transform(
                DataTable({"images": ids.astype(np.int32)}))["scores"])
            np.testing.assert_allclose(out, ref, rtol=2e-4, atol=1e-5)
            assert np.array_equal(out.argmax(-1), ref.argmax(-1))

    def test_forward_only_lstm(self, tmp_path):
        """Unidirectional LSTM against torch (separate graph: direction
        attr, no reverse weights)."""
        import torch
        import torch.nn as nn
        torch.manual_seed(3)
        lstm = nn.LSTM(self.E, self.H, batch_first=False)
        X = np.random.default_rng(9).normal(
            size=(self.T, 4, self.E)).astype(np.float32)
        with torch.no_grad():
            ref, (hT, cT) = lstm(torch.from_numpy(X))
        sd = {k: v.detach().numpy() for k, v in lstm.state_dict().items()}
        W = ow._iofc(sd["weight_ih_l0"])[None]
        R = ow._iofc(sd["weight_hh_l0"])[None]
        B = np.concatenate([ow._iofc(sd["bias_ih_l0"]),
                            ow._iofc(sd["bias_hh_l0"])])[None]
        nodes = [ow.node("LSTM", ["input", "W", "R", "B"],
                         ["y", "yh", "yc"], hidden_size=self.H),
                 ow.node("Squeeze", ["y", "sq_axes"], ["output"])]
        inits = {"W": W, "R": R, "B": B,
                 "sq_axes": np.asarray([1], np.int64)}
        p = tmp_path / "lstm_fwd.onnx"
        p.write_bytes(ow.model(nodes, inits, "input", "output"))
        graph = load_onnx(str(p))
        out = np.asarray(OnnxApply(graph)(
            {k: np.asarray(v) for k, v in graph.initializers.items()},
            {"input": X}))
        np.testing.assert_allclose(out, ref.numpy(), rtol=2e-4, atol=1e-5)


class TestOpMatrix:
    """Each newly supported op against a numpy/torch reference."""

    def _run(self, tmp_path, nodes, inits, x, opset=17, int_names=()):
        p = tmp_path / "g.onnx"
        p.write_bytes(ow.model(nodes, inits, "input", "output",
                               opset=opset, int_data_names=int_names))
        graph = load_onnx(str(p))
        return np.asarray(OnnxApply(graph)(
            {k: np.asarray(v) for k, v in graph.initializers.items()},
            {"input": x}))

    @pytest.mark.parametrize("op,ref", [
        ("Sigmoid", lambda x: 1 / (1 + np.exp(-x))),
        ("Tanh", np.tanh),
        ("Neg", np.negative),
        ("Exp", np.exp),
        ("Sqrt", lambda x: np.sqrt(np.abs(x) + 1)),
        ("Relu", lambda x: np.maximum(x, 0)),
    ])
    def test_unary(self, tmp_path, op, ref):
        x = np.random.default_rng(1).normal(size=(3, 5)).astype(np.float32)
        if op == "Sqrt":
            x = np.abs(x) + 1
            ref = np.sqrt
        out = self._run(tmp_path, [ow.node(op, ["input"], ["output"])],
                        {}, x)
        np.testing.assert_allclose(out, ref(x), rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("op,ref", [
        ("Sub", np.subtract), ("Mul", np.multiply), ("Div", np.divide),
        ("Pow", np.power),
    ])
    def test_binary_broadcast(self, tmp_path, op, ref):
        rng = np.random.default_rng(2)
        x = (rng.normal(size=(4, 5)).astype(np.float32) + 3)
        w = (rng.normal(size=(5,)).astype(np.float32) / 4 + 2)
        out = self._run(tmp_path,
                        [ow.node(op, ["input", "w"], ["output"])],
                        {"w": w}, x)
        np.testing.assert_allclose(out, ref(x, w), rtol=1e-4, atol=1e-5)

    def test_leaky_relu(self, tmp_path):
        x = np.random.default_rng(3).normal(size=(6,)).astype(np.float32)
        out = self._run(
            tmp_path,
            [ow.node("LeakyRelu", ["input"], ["output"], alpha=0.1)],
            {}, x)
        np.testing.assert_allclose(
            out, np.where(x >= 0, x, 0.1 * x), rtol=1e-6)

    @pytest.mark.parametrize("axis", [-1, 1])
    def test_softmax_modern(self, tmp_path, axis):
        import torch
        x = np.random.default_rng(4).normal(size=(3, 4, 5)
                                            ).astype(np.float32)
        out = self._run(
            tmp_path,
            [ow.node("Softmax", ["input"], ["output"], axis=axis)],
            {}, x, opset=17)
        ref = torch.softmax(torch.from_numpy(x), dim=axis).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_softmax_legacy_flattens(self, tmp_path):
        """opset <= 12 Softmax: 2D-coerce at axis, softmax the block."""
        x = np.random.default_rng(5).normal(size=(2, 3, 4)
                                            ).astype(np.float32)
        out = self._run(
            tmp_path,
            [ow.node("Softmax", ["input"], ["output"], axis=1)],
            {}, x, opset=12)
        flat = x.reshape(2, 12)
        e = np.exp(flat - flat.max(1, keepdims=True))
        ref = (e / e.sum(1, keepdims=True)).reshape(x.shape)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_log_softmax(self, tmp_path):
        import torch
        x = np.random.default_rng(6).normal(size=(3, 7)).astype(np.float32)
        out = self._run(
            tmp_path,
            [ow.node("LogSoftmax", ["input"], ["output"], axis=-1)],
            {}, x)
        ref = torch.log_softmax(torch.from_numpy(x), dim=-1).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_concat_transpose(self, tmp_path):
        rng = np.random.default_rng(7)
        x = rng.normal(size=(2, 3, 4)).astype(np.float32)
        w = rng.normal(size=(2, 5, 4)).astype(np.float32)
        nodes = [ow.node("Concat", ["input", "w"], ["c"], axis=1),
                 ow.node("Transpose", ["c"], ["output"], perm=[2, 0, 1])]
        out = self._run(tmp_path, nodes, {"w": w}, x)
        ref = np.transpose(np.concatenate([x, w], 1), (2, 0, 1))
        np.testing.assert_allclose(out, ref, rtol=1e-6)

    def test_squeeze_unsqueeze_opset13_inputs(self, tmp_path):
        x = np.random.default_rng(8).normal(size=(3, 1, 5)
                                            ).astype(np.float32)
        nodes = [
            ow.node("Squeeze", ["input", "sq"], ["s"]),
            ow.node("Unsqueeze", ["s", "us"], ["output"]),
        ]
        inits = {"sq": np.asarray([1], np.int64),
                 "us": np.asarray([0, -1], np.int64)}
        out = self._run(tmp_path, nodes, inits, x, opset=13,
                        int_names=("sq", "us"))
        assert out.shape == (1, 3, 5, 1)
        np.testing.assert_allclose(out.reshape(3, 5), x.reshape(3, 5))

    def test_squeeze_unsqueeze_opset11_attrs(self, tmp_path):
        x = np.random.default_rng(9).normal(size=(3, 1, 5)
                                            ).astype(np.float32)
        nodes = [
            ow.node("Squeeze", ["input"], ["s"], axes=[1]),
            ow.node("Unsqueeze", ["s"], ["output"], axes=[2]),
        ]
        out = self._run(tmp_path, nodes, {}, x, opset=11)
        assert out.shape == (3, 5, 1)

    def test_slice_opset10_inputs(self, tmp_path):
        x = np.arange(60, dtype=np.float32).reshape(3, 4, 5)
        nodes = [ow.node("Slice",
                         ["input", "st", "en", "ax", "sp"], ["output"])]
        inits = {"st": np.asarray([1, 0], np.int64),
                 "en": np.asarray([3, (1 << 63) - 1], np.int64),
                 "ax": np.asarray([0, 2], np.int64),
                 "sp": np.asarray([1, 2], np.int64)}
        out = self._run(tmp_path, nodes, inits, x,
                        int_names=("st", "en", "ax", "sp"))
        np.testing.assert_allclose(out, x[1:3, :, ::2])

    def test_slice_negative_and_reverse(self, tmp_path):
        x = np.arange(24, dtype=np.float32).reshape(4, 6)
        nodes = [ow.node("Slice",
                         ["input", "st", "en", "ax", "sp"], ["output"])]
        inits = {"st": np.asarray([-1], np.int64),
                 "en": np.asarray([-(1 << 63), ], np.int64),
                 "ax": np.asarray([1], np.int64),
                 "sp": np.asarray([-2], np.int64)}
        out = self._run(tmp_path, nodes, inits, x,
                        int_names=("st", "en", "ax", "sp"))
        np.testing.assert_allclose(out, x[:, ::-2])

    def test_slice_opset9_attrs(self, tmp_path):
        x = np.arange(20, dtype=np.float32).reshape(4, 5)
        nodes = [ow.node("Slice", ["input"], ["output"],
                         starts=[1], ends=[3], axes=[0])]
        out = self._run(tmp_path, nodes, {}, x, opset=9)
        np.testing.assert_allclose(out, x[1:3])

    def test_gather_and_cast(self, tmp_path):
        x = np.random.default_rng(10).normal(size=(6, 3)
                                             ).astype(np.float32)
        idx = np.asarray([4, 0, 5], np.int64)
        nodes = [ow.node("Gather", ["input", "idx"], ["g"], axis=0),
                 ow.node("Cast", ["g"], ["output"], to=6)]  # -> int32
        out = self._run(tmp_path, nodes, {"idx": idx}, x)
        assert out.dtype == np.int32
        np.testing.assert_array_equal(out, x[idx].astype(np.int32))

    def test_reduce_mean(self, tmp_path):
        x = np.random.default_rng(11).normal(size=(2, 3, 4)
                                             ).astype(np.float32)
        nodes = [ow.node("ReduceMean", ["input"], ["output"],
                         axes=[1], keepdims=0)]
        out = self._run(tmp_path, nodes, {}, x)
        np.testing.assert_allclose(out, x.mean(1), rtol=1e-5, atol=1e-6)

    def test_shape_gather_concat_reshape_chain_jitted(self, tmp_path):
        """The torch.onnx.export dynamic-reshape idiom:
        Shape -> Gather -> Unsqueeze -> Concat -> Reshape. Shapes are
        static under jit, so the chain stays concrete — verified by
        running it through the JITTED TPUModel path."""
        from mmlspark_tpu.core.table import DataTable
        rng = np.random.default_rng(12)
        x = rng.normal(size=(4, 6)).astype(np.float32)
        nodes = [
            ow.node("Shape", ["input"], ["sh"]),
            ow.node("Gather", ["sh", "zero"], ["b"], axis=0),
            ow.node("Unsqueeze", ["b", "us0"], ["b1"]),
            ow.node("Concat", ["b1", "rest"], ["tgt"], axis=0),
            ow.node("Reshape", ["input", "tgt"], ["r"]),
            ow.node("Flatten", ["r"], ["output"], axis=1),
        ]
        inits = {"zero": np.asarray(0, np.int64),
                 "us0": np.asarray([0], np.int64),
                 "rest": np.asarray([2, 3], np.int64)}
        p = tmp_path / "chain.onnx"
        p.write_bytes(ow.model(
            nodes, inits, ("input", 1, ["N", 6]), "output",
            int_data_names=("us0", "rest")))
        model = import_onnx_model(str(p), batch_size=4)
        out = np.asarray(model.transform(
            DataTable({"images": x}))["scores"])
        np.testing.assert_allclose(out, x, rtol=1e-6)

    def test_negative_int64_data_initializer(self, tmp_path):
        """ADVICE r4: negative values stored as int64_data varints
        (not raw_data) must decode signed — 2^64-1 would overflow."""
        x = np.random.default_rng(13).normal(size=(2, 3, 4)
                                             ).astype(np.float32)
        nodes = [ow.node("Reshape", ["input", "shape"], ["output"])]
        inits = {"shape": np.asarray([0, -1], np.int64)}
        out = self._run(tmp_path, nodes, inits, x,
                        int_names=("shape",))
        assert out.shape == (2, 12)


class TestLoadValidation:
    """Semantics-changing attributes and out-of-range opsets fail AT
    LOAD with actionable errors (ADVICE r4: auto_pad/ceil_mode/dilations
    previously executed silently wrong)."""

    def _write(self, tmp_path, nodes, inits=None, opset=17):
        p = tmp_path / "v.onnx"
        p.write_bytes(ow.model(nodes, inits or {}, "input", "output",
                               opset=opset))
        return str(p)

    def test_auto_pad_rejected(self, tmp_path):
        p = self._write(tmp_path, [ow.node(
            "Conv", ["input", "w"], ["output"], kernel_shape=[3, 3],
            auto_pad="SAME_UPPER")],
            {"w": np.zeros((4, 3, 3, 3), np.float32)})
        with pytest.raises(ValueError, match="auto_pad"):
            load_onnx(p)

    def test_ceil_mode_rejected(self, tmp_path):
        p = self._write(tmp_path, [ow.node(
            "MaxPool", ["input"], ["output"], kernel_shape=[2, 2],
            ceil_mode=1)])
        with pytest.raises(ValueError, match="ceil_mode"):
            load_onnx(p)

    def test_maxpool_dilations_rejected(self, tmp_path):
        p = self._write(tmp_path, [ow.node(
            "MaxPool", ["input"], ["output"], kernel_shape=[2, 2],
            dilations=[2, 2])])
        with pytest.raises(ValueError, match="dilated"):
            load_onnx(p)

    def test_lstm_nondefault_activations_rejected(self, tmp_path):
        p = self._write(tmp_path, [ow.node(
            "LSTM", ["input", "W", "R"], ["output"], hidden_size=4,
            activations=["Relu", "Tanh", "Tanh"])],
            {"W": np.zeros((1, 16, 3), np.float32),
             "R": np.zeros((1, 16, 4), np.float32)})
        with pytest.raises(ValueError, match="activations"):
            load_onnx(p)

    def test_lstm_batch_major_layout_rejected(self, tmp_path):
        p = self._write(tmp_path, [ow.node(
            "LSTM", ["input", "W", "R"], ["output"], hidden_size=4,
            layout=1)],
            {"W": np.zeros((1, 16, 3), np.float32),
             "R": np.zeros((1, 16, 4), np.float32)})
        with pytest.raises(ValueError, match="layout"):
            load_onnx(p)

    @pytest.mark.parametrize("opset", [5, 40])
    def test_opset_out_of_range_rejected(self, tmp_path, opset):
        p = self._write(
            tmp_path, [ow.node("Relu", ["input"], ["output"])],
            opset=opset)
        with pytest.raises(ValueError, match="opset"):
            load_onnx(p)

    def test_reshape_allowzero_rejected(self, tmp_path):
        p = self._write(tmp_path, [ow.node(
            "Reshape", ["input", "s"], ["output"], allowzero=1)],
            {"s": np.asarray([1, -1], np.int64)})
        with pytest.raises(ValueError, match="allowzero"):
            load_onnx(p)

    def test_lstm_peephole_rejected(self, tmp_path):
        p = self._write(tmp_path, [ow.node(
            "LSTM", ["input", "W", "R", "", "", "", "", "P"], ["output"],
            hidden_size=4)],
            {"W": np.zeros((1, 16, 3), np.float32),
             "R": np.zeros((1, 16, 4), np.float32),
             "P": np.zeros((1, 12), np.float32)})
        with pytest.raises(ValueError, match="peephole"):
            load_onnx(p)

    def test_unsqueeze_attr_axes_in_new_opset_rejected(self, tmp_path):
        p = self._write(tmp_path, [ow.node(
            "Unsqueeze", ["input"], ["output"], axes=[0])], opset=13)
        with pytest.raises(ValueError, match="axes"):
            load_onnx(p)

    def test_reduce_mean_opset18_axes_input(self, tmp_path):
        x = np.random.default_rng(14).normal(size=(2, 3, 4)
                                             ).astype(np.float32)
        nodes = [ow.node("ReduceMean", ["input", "ax"], ["output"],
                         keepdims=0)]
        p = tmp_path / "rm18.onnx"
        p.write_bytes(ow.model(
            nodes, {"ax": np.asarray([2], np.int64)}, "input", "output",
            opset=18, int_data_names=("ax",)))
        graph = load_onnx(str(p))
        out = np.asarray(OnnxApply(graph)(
            {k: np.asarray(v) for k, v in graph.initializers.items()},
            {"input": x}))
        np.testing.assert_allclose(out, x.mean(2), rtol=1e-5, atol=1e-6)

    def test_conv3d_weight_rank_rejected_at_load(self, tmp_path):
        p = self._write(tmp_path, [ow.node(
            "Conv", ["input", "w"], ["output"])],  # rank via weights
            {"w": np.zeros((4, 3, 3, 3, 3), np.float32)})
        with pytest.raises(ValueError, match="rank 5"):
            load_onnx(p)

    def test_shape_start_end_attrs(self, tmp_path):
        x = np.zeros((2, 3, 4, 5), np.float32)
        nodes = [ow.node("Shape", ["input"], ["sh"], start=1, end=-1),
                 ow.node("Cast", ["sh"], ["output"], to=1)]
        p = tmp_path / "sh.onnx"
        p.write_bytes(ow.model(nodes, {}, "input", "output", opset=17))
        graph = load_onnx(str(p))
        out = np.asarray(OnnxApply(graph)({}, {"input": x}))
        np.testing.assert_array_equal(out, [3.0, 4.0])


class TestGRUAndConv1d:
    """Round-5 widening: GRU (torch exports linear_before_reset=1) and
    1-D conv/pool — common in audio/text ONNX files."""

    E, H, T, B = 12, 16, 10, 4

    def _gru_weights(self, gru, sd, bidirectional):
        def zrn(t):
            r, z, n = np.split(t, 3, axis=0)
            return np.concatenate([z, r, n], axis=0)
        sfx = ["", "_reverse"] if bidirectional else [""]
        W = np.stack([zrn(sd[f"weight_ih_l0{s}"]) for s in sfx])
        R = np.stack([zrn(sd[f"weight_hh_l0{s}"]) for s in sfx])
        Bb = np.stack([np.concatenate([zrn(sd[f"bias_ih_l0{s}"]),
                                       zrn(sd[f"bias_hh_l0{s}"])])
                       for s in sfx])
        return W, R, Bb

    @pytest.mark.parametrize("bidirectional", [False, True])
    def test_gru_matches_torch(self, tmp_path, bidirectional):
        import torch
        import torch.nn as nn
        torch.manual_seed(5)
        gru = nn.GRU(self.E, self.H, bidirectional=bidirectional)
        X = np.random.default_rng(20).normal(
            size=(self.T, self.B, self.E)).astype(np.float32)
        with torch.no_grad():
            ref, _ = gru(torch.from_numpy(X))
        sd = {k: v.detach().numpy() for k, v in gru.state_dict().items()}
        W, R, Bb = self._gru_weights(gru, sd, bidirectional)
        ndir = 2 if bidirectional else 1
        nodes = [ow.node(
            "GRU", ["input", "W", "R", "B"], ["y", "yh"],
            hidden_size=self.H, linear_before_reset=1,
            **({"direction": "bidirectional"} if bidirectional else {})),
            # (T, D, B, H) -> (T, B, D*H) to match torch's layout
            ow.node("Transpose", ["y"], ["yt"], perm=[0, 2, 1, 3]),
            ow.node("Reshape", ["yt", "shape"], ["output"])]
        inits = {"W": W, "R": R, "B": Bb,
                 "shape": np.asarray([0, 0, -1], np.int64)}
        p = tmp_path / "gru.onnx"
        p.write_bytes(ow.model(nodes, inits, "input", "output"))
        graph = load_onnx(str(p))
        out = np.asarray(OnnxApply(graph)(
            {k: np.asarray(v) for k, v in graph.initializers.items()},
            {"input": X}))
        np.testing.assert_allclose(out, ref.numpy(), rtol=2e-4,
                                   atol=1e-5)

    def test_gru_linear_before_reset_0(self, tmp_path):
        """The lbr=0 variant against a direct numpy recurrence."""
        rng = np.random.default_rng(21)
        E = H = 6
        T, B = 5, 3
        X = rng.normal(size=(T, B, E)).astype(np.float32)
        W = rng.normal(scale=0.3, size=(1, 3 * H, E)).astype(np.float32)
        R = rng.normal(scale=0.3, size=(1, 3 * H, H)).astype(np.float32)
        nodes = [ow.node("GRU", ["input", "W", "R"], ["y"],
                         hidden_size=H, linear_before_reset=0),
                 ow.node("Squeeze", ["y", "ax"], ["output"])]
        inits = {"W": W, "R": R, "ax": np.asarray([1], np.int64)}
        p = tmp_path / "gru0.onnx"
        p.write_bytes(ow.model(nodes, inits, "input", "output"))
        graph = load_onnx(str(p))
        out = np.asarray(OnnxApply(graph)(
            {k: np.asarray(v) for k, v in graph.initializers.items()},
            {"input": X}))

        def sigm(v):
            return 1 / (1 + np.exp(-v))
        h = np.zeros((B, H), np.float32)
        expect = []
        Wz, Wr, Wh = np.split(W[0], 3, axis=0)
        Rz, Rr, Rh = np.split(R[0], 3, axis=0)
        for t in range(T):
            z = sigm(X[t] @ Wz.T + h @ Rz.T)
            r = sigm(X[t] @ Wr.T + h @ Rr.T)
            hh = np.tanh(X[t] @ Wh.T + (r * h) @ Rh.T)
            h = (1 - z) * hh + z * h
            expect.append(h.copy())
        np.testing.assert_allclose(out, np.stack(expect),
                                   rtol=2e-4, atol=1e-5)

    def test_conv1d_and_pool1d_match_torch(self, tmp_path):
        import torch
        import torch.nn.functional as F
        rng = np.random.default_rng(22)
        x = rng.normal(size=(2, 3, 20)).astype(np.float32)
        w = rng.normal(scale=0.3, size=(5, 3, 4)).astype(np.float32)
        b = rng.normal(size=5).astype(np.float32)
        nodes = [
            ow.node("Conv", ["input", "w", "b"], ["c"],
                    kernel_shape=[4], strides=[2], pads=[1, 1],
                    dilations=[1], group=1),
            ow.node("Relu", ["c"], ["r"]),
            ow.node("MaxPool", ["r"], ["output"], kernel_shape=[2],
                    strides=[2], pads=[0, 0]),
        ]
        p = tmp_path / "c1d.onnx"
        p.write_bytes(ow.model(nodes, {"w": w, "b": b},
                               "input", "output"))
        graph = load_onnx(str(p))
        out = np.asarray(OnnxApply(graph)(
            {k: np.asarray(v) for k, v in graph.initializers.items()},
            {"input": x}))
        with torch.no_grad():
            ref = F.max_pool1d(torch.relu(F.conv1d(
                torch.from_numpy(x), torch.from_numpy(w),
                torch.from_numpy(b), stride=2, padding=1)), 2, 2)
        np.testing.assert_allclose(out, ref.numpy(), rtol=1e-4,
                                   atol=1e-5)

    def test_conv3d_rejected(self, tmp_path):
        nodes = [ow.node("Conv", ["input", "w"], ["output"],
                         kernel_shape=[3, 3, 3])]
        p = tmp_path / "c3d.onnx"
        p.write_bytes(ow.model(
            nodes, {"w": np.zeros((4, 3, 3, 3, 3), np.float32)},
            "input", "output"))
        with pytest.raises(ValueError, match="1-D/2-D"):
            load_onnx(str(p))

    def test_gru_nondefault_activations_rejected(self, tmp_path):
        nodes = [ow.node("GRU", ["input", "W", "R"], ["output"],
                         hidden_size=4,
                         activations=["Relu", "Tanh"])]
        p = tmp_path / "grubad.onnx"
        p.write_bytes(ow.model(
            nodes, {"W": np.zeros((1, 12, 3), np.float32),
                    "R": np.zeros((1, 12, 4), np.float32)},
            "input", "output"))
        with pytest.raises(ValueError, match="activations"):
            load_onnx(str(p))


class TestConstantVariants:
    """Constant value_* attribute spellings (opset 12+) and repeated
    float attributes."""

    def _run(self, tmp_path, nodes, x):
        p = tmp_path / "c.onnx"
        p.write_bytes(ow.model(nodes, {}, "input", "output"))
        graph = load_onnx(str(p))
        return np.asarray(OnnxApply(graph)({}, {"input": x}))

    def test_value_float_and_ints(self, tmp_path):
        x = np.ones((2, 3), np.float32)
        nodes = [
            ow.node("Constant", [], ["c"], value_float=2.5),
            ow.node("Mul", ["input", "c"], ["m"]),
            ow.node("Constant", [], ["shape"], value_ints=[3, 2]),
            ow.node("Reshape", ["m", "shape"], ["output"]),
        ]
        out = self._run(tmp_path, nodes, x)
        assert out.shape == (3, 2)
        np.testing.assert_allclose(out, 2.5)

    def test_value_int_scalar_gathers(self, tmp_path):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        nodes = [
            ow.node("Constant", [], ["i"], value_int=2),
            ow.node("Gather", ["input", "i"], ["output"], axis=0),
        ]
        out = self._run(tmp_path, nodes, x)
        np.testing.assert_allclose(out, x[2])

    def test_unsupported_constant_form_rejected(self, tmp_path):
        nodes = [ow.node("Constant", [], ["c"], value_string="oops")]
        p = tmp_path / "bad.onnx"
        p.write_bytes(ow.model(nodes, {}, "x", "c"))
        with pytest.raises(ValueError, match="constant"):
            load_onnx(str(p))

    def test_value_floats_list(self, tmp_path):
        """Repeated-float attribute (field 7 per onnx.proto) decodes
        as floats, not as a mis-numbered strings/graph field."""
        x = np.zeros((1, 3), np.float32)
        nodes = [
            ow.node("Constant", [], ["c"], value_floats=[1.5, -2.0, 0.25]),
            ow.node("Add", ["input", "c"], ["output"]),
        ]
        out = self._run(tmp_path, nodes, x)
        np.testing.assert_allclose(out, [[1.5, -2.0, 0.25]])


class TestMultiInput:
    """Multi-input graphs (two-tower scorers, sequence+mask) feed each
    graph input from its table column through TPUModel's feedDict."""

    def _two_tower(self, tmp_path):
        rng = np.random.default_rng(30)
        wu = rng.normal(scale=0.3, size=(6, 4)).astype(np.float32)
        wi = rng.normal(scale=0.3, size=(5, 4)).astype(np.float32)
        nodes = [
            ow.node("MatMul", ["user", "wu"], ["eu"]),
            ow.node("MatMul", ["item", "wi"], ["ei"]),
            ow.node("Mul", ["eu", "ei"], ["prod"]),
            ow.node("ReduceMean", ["prod"], ["score"],
                    axes=[1], keepdims=1),
        ]
        graph = b""
        for nd in nodes:
            graph += ow._ld(1, nd)
        for name, arr in (("wu", wu), ("wi", wi)):
            graph += ow._ld(5, ow.tensor(name, arr))
        graph += ow._ld(11, ow._value_info("user", 1, ["N", 6]))
        graph += ow._ld(11, ow._value_info("item", 1, ["N", 5]))
        graph += ow._ld(12, ow._value_info("score", 1, ["N", 1]))
        opset_b = ow._ld(1, b"") + ow._int_field(2, 17)
        blob = ow._int_field(1, 8) + ow._ld(8, opset_b) + ow._ld(7, graph)
        p = tmp_path / "tower.onnx"
        p.write_bytes(blob)
        return str(p), wu, wi

    def test_two_tower_scores(self, tmp_path):
        from mmlspark_tpu.core.table import DataTable
        path, wu, wi = self._two_tower(tmp_path)
        model = import_onnx_model(path, batch_size=4)
        rng = np.random.default_rng(31)
        u = rng.normal(size=(7, 6)).astype(np.float32)
        it = rng.normal(size=(7, 5)).astype(np.float32)
        out = np.asarray(model.transform(
            DataTable({"user": u, "item": it}))["scores"])
        ref = ((u @ wu) * (it @ wi)).mean(1, keepdims=True)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_feed_cols_override_and_save_load(self, tmp_path):
        from mmlspark_tpu.core.serialize import load_stage
        from mmlspark_tpu.core.table import DataTable
        path, wu, wi = self._two_tower(tmp_path)
        model = import_onnx_model(
            path, feed_cols={"user": "u_feats", "item": "i_feats"})
        rng = np.random.default_rng(32)
        u = rng.normal(size=(3, 6)).astype(np.float32)
        it = rng.normal(size=(3, 5)).astype(np.float32)
        table = DataTable({"u_feats": u, "i_feats": it})
        ref = np.asarray(model.transform(table)["scores"])
        model.save(str(tmp_path / "stage"))
        back = load_stage(str(tmp_path / "stage"))
        np.testing.assert_array_equal(
            np.asarray(back.transform(table)["scores"]), ref)

    def test_mixed_elem_classes_rejected(self, tmp_path):
        nodes = [ow.node("Gather", ["emb", "ids"], ["g"], axis=0),
                 ow.node("Mul", ["g", "scale"], ["out"])]
        graph = b""
        for nd in nodes:
            graph += ow._ld(1, nd)
        graph += ow._ld(5, ow.tensor(
            "emb", np.zeros((10, 4), np.float32)))
        graph += ow._ld(11, ow._value_info("ids", 7, ["N"]))      # int64
        graph += ow._ld(11, ow._value_info("scale", 1, ["N", 1]))  # f32
        graph += ow._ld(12, ow._value_info("out", 1, ["N", 4]))
        opset_b = ow._ld(1, b"") + ow._int_field(2, 17)
        blob = ow._int_field(1, 8) + ow._ld(8, opset_b) + ow._ld(7, graph)
        p = tmp_path / "mixed.onnx"
        p.write_bytes(blob)
        with pytest.raises(ValueError, match="element class"):
            import_onnx_model(str(p))

    def test_partial_shape_dict_still_infers(self, tmp_path):
        """A partial {input: shape} dict pins the listed inputs and
        still infers the rest from declared value infos."""
        from mmlspark_tpu.core.table import DataTable
        path, wu, wi = self._two_tower(tmp_path)
        model = import_onnx_model(
            path, input_shape={"user": (6,)})   # 'item' inferred (5,)
        shp = model.get("modelFn").input_shape
        assert shp == {"user": (6,), "item": (5,)}, shp

    def test_feed_cols_typo_rejected(self, tmp_path):
        path, _, _ = self._two_tower(tmp_path)
        with pytest.raises(ValueError, match="usr"):
            import_onnx_model(path, feed_cols={"usr": "u"})


class TestTransformerBlockImport:
    """A BERT-style encoder block from genuine ONNX bytes — exercises
    the round-5 op set as real exporters compose it: LayerNorm as a
    ReduceMean/Sub/Pow/Sqrt/Div chain, fused-QKV MatMul + Split,
    batched attention MatMuls with a Where-masked Softmax, and the
    erf-form GELU. Parity against an identically-parameterized torch
    module."""

    B, T, D, H = 2, 6, 16, 4

    def test_block_matches_torch(self, tmp_path):
        import torch
        D, H, T = self.D, self.H, self.T
        hd = D // H
        rng = np.random.default_rng(40)

        def w(shape, scale=0.25):
            return rng.normal(scale=scale, size=shape).astype(np.float32)

        inits = {
            "ln_g": w((D,), 1.0) * 0 + 1.0, "ln_b": w((D,), 0.1),
            "wqkv": w((D, 3 * D)), "bqkv": w((3 * D,), 0.05),
            "wo": w((D, D)), "bo": w((D,), 0.05),
            "ln2_g": w((D,), 1.0) * 0 + 1.0, "ln2_b": w((D,), 0.1),
            "w1": w((D, 4 * D)), "b1": w((4 * D,), 0.05),
            "w2": w((4 * D, D)), "b2": w((D,), 0.05),
            "eps": np.asarray([1e-5], np.float32),
            "half": np.asarray([0.5], np.float32),
            "one": np.asarray([1.0], np.float32),
            "sqrt2": np.asarray([np.sqrt(2.0)], np.float32),
            "scale": np.asarray([1.0 / np.sqrt(hd)], np.float32),
            "neg": np.asarray([-1e9], np.float32),
            "mask": np.tril(np.ones((T, T), bool)),
            "h_shape": np.asarray([0, 0, H, hd], np.int64),
            "m_shape": np.asarray([0, 0, D], np.int64),
            "two": np.asarray([2.0], np.float32),
        }

        def ln(x_in, g, b, prefix):
            return [
                ow.node("ReduceMean", [x_in], [f"{prefix}.mu"],
                        axes=[-1], keepdims=1),
                ow.node("Sub", [x_in, f"{prefix}.mu"], [f"{prefix}.c"]),
                ow.node("Pow", [f"{prefix}.c", "two"], [f"{prefix}.c2"]),
                ow.node("ReduceMean", [f"{prefix}.c2"], [f"{prefix}.v"],
                        axes=[-1], keepdims=1),
                ow.node("Add", [f"{prefix}.v", "eps"], [f"{prefix}.ve"]),
                ow.node("Sqrt", [f"{prefix}.ve"], [f"{prefix}.sd"]),
                ow.node("Div", [f"{prefix}.c", f"{prefix}.sd"],
                        [f"{prefix}.n"]),
                ow.node("Mul", [f"{prefix}.n", g], [f"{prefix}.ng"]),
                ow.node("Add", [f"{prefix}.ng", b], [f"{prefix}.out"]),
            ]

        nodes = []
        nodes += ln("x", "ln_g", "ln_b", "l1")
        nodes += [
            ow.node("MatMul", ["l1.out", "wqkv"], ["qkv0"]),
            ow.node("Add", ["qkv0", "bqkv"], ["qkv"]),
            ow.node("Split", ["qkv"], ["q", "k", "v"], axis=-1,
                    num_outputs=3),
        ]
        for nm in ("q", "k", "v"):
            nodes += [
                ow.node("Reshape", [nm, "h_shape"], [f"{nm}h"]),
                ow.node("Transpose", [f"{nm}h"], [f"{nm}t"],
                        perm=[0, 2, 1, 3]),          # (B, H, T, hd)
            ]
        nodes += [
            ow.node("Transpose", ["kt"], ["ktt"], perm=[0, 1, 3, 2]),
            ow.node("MatMul", ["qt", "ktt"], ["sc0"]),
            ow.node("Mul", ["sc0", "scale"], ["sc"]),
            ow.node("Where", ["mask", "sc", "neg"], ["scm"]),
            ow.node("Softmax", ["scm"], ["attn"], axis=-1),
            ow.node("MatMul", ["attn", "vt"], ["ctx"]),
            ow.node("Transpose", ["ctx"], ["ctxt"], perm=[0, 2, 1, 3]),
            ow.node("Reshape", ["ctxt", "m_shape"], ["ctxm"]),
            ow.node("MatMul", ["ctxm", "wo"], ["proj0"]),
            ow.node("Add", ["proj0", "bo"], ["proj"]),
            ow.node("Add", ["x", "proj"], ["res1"]),
        ]
        nodes += ln("res1", "ln2_g", "ln2_b", "l2")
        nodes += [
            ow.node("MatMul", ["l2.out", "w1"], ["m0"]),
            ow.node("Add", ["m0", "b1"], ["m1"]),
            # erf-form GELU: 0.5 * x * (1 + erf(x / sqrt(2)))
            ow.node("Div", ["m1", "sqrt2"], ["g0"]),
            ow.node("Erf", ["g0"], ["g1"]),
            ow.node("Add", ["g1", "one"], ["g2"]),
            ow.node("Mul", ["m1", "g2"], ["g3"]),
            ow.node("Mul", ["g3", "half"], ["gelu"]),
            ow.node("MatMul", ["gelu", "w2"], ["m2"]),
            ow.node("Add", ["m2", "b2"], ["m3"]),
            ow.node("Add", ["res1", "m3"], ["out"]),
        ]
        graph = b"".join(ow._ld(1, nd) for nd in nodes)
        for name, arr in inits.items():
            graph += ow._ld(5, ow.tensor(name, arr))
        graph += ow._ld(11, ow._value_info("x", 1, ["N", T, D]))
        graph += ow._ld(12, ow._value_info("out", 1, ["N", T, D]))
        blob = (ow._int_field(1, 8)
                + ow._ld(8, ow._ld(1, b"") + ow._int_field(2, 17))
                + ow._ld(7, graph))
        p = tmp_path / "block.onnx"
        p.write_bytes(blob)

        # torch twin with the SAME math
        def torch_ref(x):
            t = {k: torch.from_numpy(np.asarray(v))
                 for k, v in inits.items()}
            h = torch.nn.functional.layer_norm(
                x, (D,), t["ln_g"], t["ln_b"], eps=1e-5)
            qkv = h @ t["wqkv"] + t["bqkv"]
            q, k, v = qkv.split(D, dim=-1)
            def heads(z):
                return z.reshape(self.B, T, H, hd).permute(0, 2, 1, 3)
            q, k, v = heads(q), heads(k), heads(v)
            sc = (q @ k.transpose(-1, -2)) / np.sqrt(hd)
            sc = sc.masked_fill(~t["mask"], -1e9)
            ctx = torch.softmax(sc, dim=-1) @ v
            ctx = ctx.permute(0, 2, 1, 3).reshape(self.B, T, D)
            x = x + ctx @ t["wo"] + t["bo"]
            h2 = torch.nn.functional.layer_norm(
                x, (D,), t["ln2_g"], t["ln2_b"], eps=1e-5)
            m = h2 @ t["w1"] + t["b1"]
            m = torch.nn.functional.gelu(m)      # erf-form by default
            return x + m @ t["w2"] + t["b2"]

        x = rng.normal(size=(self.B, T, D)).astype(np.float32)
        with torch.no_grad():
            ref = torch_ref(torch.from_numpy(x)).numpy()
        graph_p = load_onnx(str(p))
        out = np.asarray(OnnxApply(graph_p)(
            {k: np.asarray(v) for k, v in graph_p.initializers.items()},
            {"x": x}))
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)

    def test_split_sizes_input_form(self, tmp_path):
        x = np.arange(24, dtype=np.float32).reshape(2, 12)
        nodes = [ow.node("Split", ["input", "sizes"],
                         ["a", "b", "c"], axis=1),
                 ow.node("Concat", ["c", "b", "a"], ["output"], axis=1)]
        inits = {"sizes": np.asarray([3, 4, 5], np.int64)}
        p = tmp_path / "sp.onnx"
        p.write_bytes(ow.model(nodes, inits, "input", "output",
                               int_data_names=("sizes",)))
        graph = load_onnx(str(p))
        out = np.asarray(OnnxApply(graph)(
            {k: np.asarray(v) for k, v in graph.initializers.items()},
            {"input": x}))
        ref = np.concatenate([x[:, 7:], x[:, 3:7], x[:, :3]], axis=1)
        np.testing.assert_allclose(out, ref)

    def test_expand_broadcast(self, tmp_path):
        x = np.arange(3, dtype=np.float32).reshape(3, 1)
        nodes = [ow.node("Expand", ["input", "shape"], ["output"])]
        inits = {"shape": np.asarray([2, 3, 4], np.int64)}
        p = tmp_path / "ex.onnx"
        p.write_bytes(ow.model(nodes, inits, "input", "output",
                               int_data_names=("shape",)))
        graph = load_onnx(str(p))
        out = np.asarray(OnnxApply(graph)(
            {k: np.asarray(v) for k, v in graph.initializers.items()},
            {"input": x}))
        assert out.shape == (2, 3, 4)
        np.testing.assert_allclose(out, np.broadcast_to(x, (2, 3, 4)))

    def test_split_uneven_num_outputs(self, tmp_path):
        """ONNX spec: with num_outputs on a non-divisible axis, chunks
        are ceil-sized with a smaller last one ([4,4,2] for 10/3)."""
        x = np.arange(20, dtype=np.float32).reshape(2, 10)
        nodes = [ow.node("Split", ["input"], ["a", "b", "c"], axis=1,
                         num_outputs=3),
                 ow.node("Concat", ["c", "a", "b"], ["output"], axis=1)]
        p = tmp_path / "spu.onnx"
        p.write_bytes(ow.model(nodes, {}, "input", "output", opset=18))
        graph = load_onnx(str(p))
        out = np.asarray(OnnxApply(graph)({}, {"input": x}))
        ref = np.concatenate([x[:, 8:], x[:, :4], x[:, 4:8]], axis=1)
        np.testing.assert_allclose(out, ref)


class TestReduceAndArg:
    """ReduceSum/Max/Min (opset-split axes forms), variadic Min/Max,
    ArgMax/ArgMin — the classifier-tail and pooling ops."""

    def _run(self, tmp_path, nodes, inits, x, opset=17, int_names=()):
        p = tmp_path / "r.onnx"
        p.write_bytes(ow.model(nodes, inits, "input", "output",
                               opset=opset, int_data_names=int_names))
        graph = load_onnx(str(p))
        return np.asarray(OnnxApply(graph)(
            {k: np.asarray(v) for k, v in graph.initializers.items()},
            {"input": x}))

    def test_reduce_sum_axes_input_opset13(self, tmp_path):
        x = np.random.default_rng(50).normal(size=(2, 3, 4)
                                             ).astype(np.float32)
        nodes = [ow.node("ReduceSum", ["input", "ax"], ["output"],
                         keepdims=0)]
        out = self._run(tmp_path, nodes,
                        {"ax": np.asarray([1], np.int64)}, x,
                        opset=13, int_names=("ax",))
        np.testing.assert_allclose(out, x.sum(1), rtol=1e-5, atol=1e-6)

    def test_reduce_max_min_attr_form(self, tmp_path):
        x = np.random.default_rng(51).normal(size=(3, 5)
                                             ).astype(np.float32)
        nodes = [ow.node("ReduceMax", ["input"], ["mx"],
                         axes=[1], keepdims=1),
                 ow.node("ReduceMin", ["input"], ["mn"],
                         axes=[1], keepdims=1),
                 ow.node("Sub", ["mx", "mn"], ["output"])]
        out = self._run(tmp_path, nodes, {}, x)
        ref = x.max(1, keepdims=True) - x.min(1, keepdims=True)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_variadic_min_max(self, tmp_path):
        rng = np.random.default_rng(52)
        x = rng.normal(size=(4,)).astype(np.float32)
        b = rng.normal(size=(4,)).astype(np.float32)
        c = rng.normal(size=(4,)).astype(np.float32)
        nodes = [ow.node("Max", ["input", "b", "c"], ["hi"]),
                 ow.node("Min", ["input", "b", "c"], ["lo"]),
                 ow.node("Sub", ["hi", "lo"], ["output"])]
        out = self._run(tmp_path, nodes, {"b": b, "c": c}, x)
        ref = np.maximum(np.maximum(x, b), c) - \
            np.minimum(np.minimum(x, b), c)
        np.testing.assert_allclose(out, ref, rtol=1e-6)

    def test_argmax_classifier_tail(self, tmp_path):
        """The common export ending: logits -> ArgMax class ids."""
        from mmlspark_tpu.core.table import DataTable
        rng = np.random.default_rng(53)
        w = rng.normal(scale=0.3, size=(6, 4)).astype(np.float32)
        nodes = [ow.node("MatMul", ["input", "w"], ["logits"]),
                 ow.node("ArgMax", ["logits"], ["output"],
                         axis=-1, keepdims=0)]
        p = tmp_path / "clf.onnx"
        p.write_bytes(ow.model(nodes, {"w": w},
                               ("input", 1, ["N", 6]), "output"))
        model = import_onnx_model(str(p), batch_size=4)
        x = rng.normal(size=(9, 6)).astype(np.float32)
        out = np.asarray(model.transform(
            DataTable({"images": x}))["scores"])
        np.testing.assert_array_equal(out, (x @ w).argmax(-1))

    def test_argmax_select_last_index_rejected(self, tmp_path):
        nodes = [ow.node("ArgMax", ["input"], ["output"],
                         select_last_index=1)]
        p = tmp_path / "bad.onnx"
        p.write_bytes(ow.model(nodes, {}, "input", "output"))
        with pytest.raises(ValueError, match="select_last_index"):
            load_onnx(str(p))

    def test_reduce_sum_attr_in_new_opset_rejected(self, tmp_path):
        nodes = [ow.node("ReduceSum", ["input"], ["output"], axes=[0])]
        p = tmp_path / "rs.onnx"
        p.write_bytes(ow.model(nodes, {}, "input", "output", opset=13))
        with pytest.raises(ValueError, match="opset 13"):
            load_onnx(str(p))
