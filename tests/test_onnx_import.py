"""ONNX ingestion tests (importers/onnx_import.py).

The correctness bar mirrors the torchvision-import suite: an ONNX
resnet18 file — genuine protobuf bytes produced by an independent
writer (tests/onnx_writer.py), not by the reader's own code — must
predict identically to a same-weights torch model through TPUModel
(ref: ModelDownloader.scala:209 — the zoo serves real published CNNs).
"""

import numpy as np
import pytest

from mmlspark_tpu.importers.onnx_import import (
    OnnxApply, import_onnx_model, load_onnx, onnx_summary,
)
from tests import onnx_writer as ow


@pytest.fixture(scope="module")
def resnet18_file(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("onnx") / "resnet18.onnx")
    weights = ow.resnet18_onnx(path, num_classes=10, width=8, seed=3)
    return path, weights


def _torch_resnet18(weights, num_classes=10, width=8):
    """torchvision-architecture resnet18 built from plain torch.nn,
    loaded with the generated weights — the ground truth."""
    import torch
    import torch.nn as nn

    class BasicBlock(nn.Module):
        def __init__(self, cin, cout, stride):
            super().__init__()
            self.conv1 = nn.Conv2d(cin, cout, 3, stride, 1, bias=False)
            self.bn1 = nn.BatchNorm2d(cout)
            self.conv2 = nn.Conv2d(cout, cout, 3, 1, 1, bias=False)
            self.bn2 = nn.BatchNorm2d(cout)
            self.downsample = None
            if stride != 1 or cin != cout:
                self.downsample = nn.Sequential(
                    nn.Conv2d(cin, cout, 1, stride, bias=False),
                    nn.BatchNorm2d(cout))

        def forward(self, x):
            idn = x if self.downsample is None else self.downsample(x)
            y = torch.relu(self.bn1(self.conv1(x)))
            y = self.bn2(self.conv2(y))
            return torch.relu(y + idn)

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = nn.Conv2d(3, width, 7, 2, 3, bias=False)
            self.bn1 = nn.BatchNorm2d(width)
            self.maxpool = nn.MaxPool2d(3, 2, 1)
            cin = width
            for li, (cout, stride) in enumerate(
                    [(width, 1), (2 * width, 2), (4 * width, 2),
                     (8 * width, 2)]):
                blocks = []
                for blk in range(2):
                    blocks.append(BasicBlock(
                        cin, cout, stride if blk == 0 else 1))
                    cin = cout
                setattr(self, f"layer{li + 1}", nn.Sequential(*blocks))
            self.fc = nn.Linear(8 * width, num_classes)

        def forward(self, x):
            x = self.maxpool(torch.relu(self.bn1(self.conv1(x))))
            x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
            x = x.mean(dim=(2, 3))
            return self.fc(x)

    net = Net().eval()
    state = {}
    for k, v in weights.items():
        # the ONNX conv weights carry no bias; names already match
        # torch's state_dict convention by construction
        state[k] = torch.from_numpy(np.asarray(v))
    missing, unexpected = net.load_state_dict(state, strict=False)
    # only num_batches_tracked counters may be missing
    assert all("num_batches_tracked" in m for m in missing), missing
    assert not unexpected, unexpected
    return net


class TestWireParsing:
    def test_summary(self, resnet18_file):
        path, weights = resnet18_file
        s = onnx_summary(path)
        assert s["ops"]["Conv"] == 20          # 16 block + 3 downsample + stem
        assert s["ops"]["BatchNormalization"] == 20
        assert s["ops"]["Add"] == 8
        assert s["ops"]["Gemm"] == 1
        assert s["num_initializers"] == len(weights)
        assert s["inputs"] == ["input"]
        assert s["outputs"] == ["output"]

    def test_initializer_roundtrip(self, resnet18_file):
        path, weights = resnet18_file
        graph = load_onnx(path)
        for name, arr in weights.items():
            np.testing.assert_array_equal(graph.initializers[name], arr)

    def test_unsupported_op_rejected(self, tmp_path):
        blob = ow.model([ow.node("LSTM", ["x"], ["y"])], {}, "x", "y")
        p = tmp_path / "bad.onnx"
        p.write_bytes(blob)
        with pytest.raises(ValueError, match="LSTM"):
            load_onnx(str(p))

    def test_not_onnx_rejected(self, tmp_path):
        p = tmp_path / "junk.onnx"
        p.write_bytes(b"\x00\x01\x02")
        with pytest.raises(ValueError):
            load_onnx(str(p))


class TestExecution:
    def test_resnet18_matches_torch(self, resnet18_file):
        path, weights = resnet18_file
        net = _torch_resnet18(weights)
        import torch
        rng = np.random.default_rng(0)
        x = rng.normal(size=(4, 3, 64, 64)).astype(np.float32)
        with torch.no_grad():
            ref = net(torch.from_numpy(x)).numpy()
        graph = load_onnx(path)
        out = np.asarray(OnnxApply(graph)(
            {k: np.asarray(v) for k, v in graph.initializers.items()},
            {"images": x}))
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)

    def test_through_tpu_model(self, resnet18_file):
        from mmlspark_tpu.core.table import DataTable
        path, weights = resnet18_file
        net = _torch_resnet18(weights)
        import torch
        rng = np.random.default_rng(1)
        x = rng.normal(size=(6, 3, 32, 32)).astype(np.float32)
        with torch.no_grad():
            ref = net(torch.from_numpy(x)).numpy()
        model = import_onnx_model(path, batch_size=4,
                                  input_shape=[3, 32, 32])
        table = DataTable({"images": x.reshape(6, -1)})
        out = np.asarray(model.transform(table)["scores"])
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)
        assert np.array_equal(out.argmax(1), ref.argmax(1))

    def test_pool_variants_and_clip(self, tmp_path):
        """AveragePool/Reshape/Clip ops against torch semantics —
        Reshape's target is an int64 initializer (the torch.onnx.export
        pattern) and the whole graph runs JITTED through TPUModel, the
        path where a traced shape tensor could not concretize."""
        import torch
        from mmlspark_tpu.core.table import DataTable
        rng = np.random.default_rng(2)
        x = rng.normal(size=(2, 3, 8, 8)).astype(np.float32)
        nodes = [
            ow.node("AveragePool", ["input"], ["ap"], kernel_shape=[2, 2],
                    strides=[2, 2], pads=[0, 0, 0, 0]),
            ow.node("Clip", ["ap"], ["cl"], min=-0.5, max=0.5),
            ow.node("Reshape", ["cl", "shape"], ["output"]),
        ]
        inits = {"shape": np.asarray([0, -1], np.int64)}  # 0 = keep dim
        p = tmp_path / "pool.onnx"
        p.write_bytes(ow.model(nodes, inits, "input", "output"))
        graph = load_onnx(str(p))
        ref = torch.clamp(
            torch.nn.functional.avg_pool2d(torch.from_numpy(x), 2, 2),
            -0.5, 0.5).flatten(1).numpy()
        out = np.asarray(OnnxApply(graph)(
            {"shape": inits["shape"]}, {"images": x}))
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
        # jitted path: TPUModel compiles the executor; weights (incl.
        # the shape initializer) become tracers
        model = import_onnx_model(str(p), batch_size=2,
                                  input_shape=[3, 8, 8])
        out2 = np.asarray(model.transform(
            DataTable({"images": x.reshape(2, -1)}))["scores"])
        np.testing.assert_allclose(out2, ref, rtol=1e-5, atol=1e-6)

    def test_float16_bit_pattern_payload(self, tmp_path):
        """FLOAT16 int32_data carries uint16 BIT PATTERNS per spec —
        reinterpreted, not value-cast."""
        import struct as _struct
        vals = np.asarray([1.0, -2.5, 0.5], np.float16)
        bits = vals.view(np.uint16)
        # hand-encode a TensorProto with int32_data (field 5, varints)
        body = b""
        body += ow._int_field(1, 3)                  # dims = [3]
        body += ow._int_field(2, 10)                 # data_type FLOAT16
        for b in bits:
            body += ow._int_field(5, int(b))         # int32_data
        body += ow._ld(8, b"w")                      # name
        nodes = [ow.node("Identity", ["input"], ["output"])]
        graph = b"".join([ow._ld(1, n) for n in nodes]) \
            + ow._ld(5, body) \
            + ow._ld(11, ow._value_info("input")) \
            + ow._ld(12, ow._value_info("output"))
        blob = ow._int_field(1, 8) + ow._ld(7, graph)
        p = tmp_path / "f16.onnx"
        p.write_bytes(blob)
        graph_p = load_onnx(str(p))
        np.testing.assert_array_equal(
            graph_p.initializers["w"].astype(np.float32),
            vals.astype(np.float32))

    def test_truncated_file_fails_fast(self, resnet18_file, tmp_path):
        path, _ = resnet18_file
        with open(path, "rb") as f:
            blob = f.read()
        p = tmp_path / "trunc.onnx"
        p.write_bytes(blob[:len(blob) // 2])
        with pytest.raises(ValueError):
            load_onnx(str(p))


class TestDownloaderPublish:
    def test_publish_and_reload(self, resnet18_file, tmp_path):
        """ONNX models publish through ModelDownloader like every zoo
        model: blob + sha256 schema, reload, predict."""
        from mmlspark_tpu.downloader import LocalRepo
        path, _ = resnet18_file
        repo = LocalRepo(str(tmp_path / "repo"))
        with open(path, "rb") as f:
            blob = f.read()
        repo.publish(
            "onnx_resnet18",
            {"format": "onnx", "onnx_summary": onnx_summary(path)},
            blob=blob, model_type="classification")
        got = repo.get_schema("onnx_resnet18")
        assert got.network_spec["onnx_summary"]["ops"]["Conv"] == 20
        blob2 = repo.read_blob(got, verify=True)
        assert blob2 == blob
        # reload from the repo blob and execute
        p2 = tmp_path / "reload.onnx"
        p2.write_bytes(blob2)
        model = import_onnx_model(str(p2))
        assert model is not None


class TestOpVariants:
    """Per-op parity for paths the resnet graph doesn't exercise."""

    def _run(self, tmp_path, nodes, inits, x, name="g.onnx"):
        p = tmp_path / name
        p.write_bytes(ow.model(nodes, inits, "input", "output"))
        graph = load_onnx(str(p))
        return np.asarray(OnnxApply(graph)(
            {k: np.asarray(v) for k, v in graph.initializers.items()},
            {"images": x}))

    def test_matmul_and_constant(self, tmp_path):
        rng = np.random.default_rng(5)
        x = rng.normal(size=(4, 6)).astype(np.float32)
        w = rng.normal(size=(6, 3)).astype(np.float32)
        c = np.asarray([1.0, 2.0, 3.0], np.float32)
        nodes = [
            ow.node("MatMul", ["input", "w"], ["mm"]),
            ow.node("Constant", [], ["c"], value=c),
            ow.node("Add", ["mm", "c"], ["output"]),
        ]
        out = self._run(tmp_path, nodes, {"w": w}, x)
        np.testing.assert_allclose(out, x @ w + c, rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("transA,transB", [(0, 0), (0, 1), (1, 0)])
    def test_gemm_transpose_variants(self, tmp_path, transA, transB):
        rng = np.random.default_rng(6)
        A = rng.normal(size=(5, 4)).astype(np.float32)
        x = A.T if transA else A
        B = rng.normal(size=(4, 3)).astype(np.float32)
        w = B.T if transB else B
        bias = rng.normal(size=3).astype(np.float32)
        nodes = [ow.node("Gemm", ["input", "w", "b"], ["output"],
                         alpha=1.0, beta=0.5, transA=transA,
                         transB=transB)]
        out = self._run(tmp_path, nodes, {"w": w, "b": bias}, x)
        np.testing.assert_allclose(out, A @ B + 0.5 * bias,
                                   rtol=1e-5, atol=1e-6)
