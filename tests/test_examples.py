"""Execute every example script end-to-end — the local analog of the
reference's notebook test harness (ref: tools/notebook/tester/
TestNotebooksLocally.py + NotebookTests.scala: every sample notebook must
run green in CI). Each example asserts its own quality bar internally."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(REPO, "examples")


@pytest.mark.parametrize("script", sorted(
    f for f in os.listdir(EXAMPLES) if f.endswith(".py")))
def test_example_runs(script):
    path = os.path.join(EXAMPLES, script)
    code = (
        "import jax;"
        "jax.config.update('jax_platforms','cpu');"
        "jax.config.update('jax_num_cpu_devices',8);"
        # runpy.run_path does NOT add the script's directory to sys.path
        # (direct execution does) — add it so `import _pathsetup` works
        f"import sys; sys.path.insert(0, {EXAMPLES!r});"
        f"import runpy; runpy.run_path({path!r}, run_name='__main__')")
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    res = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                         capture_output=True, text=True, timeout=600,
                         env=env)
    assert res.returncode == 0, (
        f"{script} failed:\nSTDOUT:\n{res.stdout[-3000:]}\n"
        f"STDERR:\n{res.stderr[-3000:]}")
