"""Execute every example script end-to-end — the local analog of the
reference's notebook test harness (ref: tools/notebook/tester/
TestNotebooksLocally.py + NotebookTests.scala: every sample notebook must
run green in CI). Each example asserts its own quality bar internally."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(REPO, "examples")


# minutes of single-core training each on a weak CI host: the heavy
# training examples ride in the full suite (-m slow), the cheap
# end-to-end ones (serving, remote storage, ...) stay in tier-1
HEAVY_EXAMPLES = {"106_quantile_regression.py", "301_pretrained_inference.py",
                  "304_bilstm_tagger.py", "305_transfer_learning.py",
                  "401_distributed_training.py", "long_context_lm.py"}


@pytest.mark.parametrize("script", [
    pytest.param(f, marks=pytest.mark.slow) if f in HEAVY_EXAMPLES
    else f
    for f in sorted(os.listdir(EXAMPLES)) if f.endswith(".py")])
def test_example_runs(script):
    path = os.path.join(EXAMPLES, script)
    code = (
        # one shared jax-version-compatible device-count setup (cwd is
        # the repo root, so the package imports without path games)
        "from mmlspark_tpu.utils.jax_compat import set_cpu_device_count;"
        "set_cpu_device_count(8);"
        # runpy.run_path does NOT add the script's directory to sys.path
        # (direct execution does) — add it so `import _pathsetup` works
        f"import sys; sys.path.insert(0, {EXAMPLES!r});"
        f"import runpy; runpy.run_path({path!r}, run_name='__main__')")
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    res = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                         capture_output=True, text=True, timeout=600,
                         env=env)
    assert res.returncode == 0, (
        f"{script} failed:\nSTDOUT:\n{res.stdout[-3000:]}\n"
        f"STDERR:\n{res.stderr[-3000:]}")
