"""Codegen tests (ref: codegen CodeGen.generateArtifacts — wrappers,
docs, and generated smoke tests for every stage, coverage structural)."""

import json
import os
import subprocess
import sys

import pytest

from mmlspark_tpu.codegen import (
    generate_artifacts, load_all_stages, param_manifest, stage_manifest,
    stage_markdown,
)


@pytest.fixture(scope="module")
def artifacts(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("generated"))
    counts = generate_artifacts(out)
    return out, counts


class TestManifest:
    def test_manifest_covers_all_registered_stages(self):
        manifest = stage_manifest()
        stages = load_all_stages()
        expected = {n for n, cls in stages.items()
                    if n not in ("Transformer", "Estimator", "Model")
                    and cls.__module__.startswith("mmlspark_tpu.")}
        assert set(manifest["stages"]) == expected

    def test_param_manifest_structure(self):
        from mmlspark_tpu.gbdt import TPUBoostClassifier
        params = {p["name"]: p for p in param_manifest(TPUBoostClassifier)}
        assert params["numIterations"]["type"] == "IntParam"
        assert params["numIterations"]["default"] == 100
        assert "choices" in params["objective"]
        assert params["validationData"]["is_complex"]

    def test_manifest_is_json_serializable(self):
        json.dumps(stage_manifest())


class TestGeneratedArtifacts:
    def test_doc_per_stage(self, artifacts):
        out, counts = artifacts
        docs = os.listdir(os.path.join(out, "docs"))
        assert counts["docs"] == counts["stages"]
        assert "index.md" in docs
        assert len([d for d in docs if d != "index.md"]) == counts["docs"]

    def test_doc_contains_param_table(self, artifacts):
        out, _ = artifacts
        md = open(os.path.join(out, "docs", "ValueIndexer.md")).read()
        assert "| `inputCol` |" in md
        assert "*Estimator*" in md

    def test_generated_smoke_tests_pass_under_pytest(self, artifacts):
        out, counts = artifacts
        assert counts["tests"] > 50
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        r = subprocess.run(
            [sys.executable, "-m", "pytest",
             os.path.join(out, "test_generated_smoke.py"), "-q",
             "-p", "no:cacheprovider"],
            capture_output=True, text=True, timeout=500,
            cwd="/root/repo", env=env)
        assert f"{counts['tests']} passed" in r.stdout, \
            r.stdout[-2000:] + r.stderr[-2000:]

    def test_markdown_escapes_pipes(self):
        stages = load_all_stages()
        md = stage_markdown("DataConversion", stages["DataConversion"])
        assert "# DataConversion" in md
