"""Cross-process trace propagation tests: traceparent inject/extract,
client-leg spans on the fleet client (failover + hedging under ONE
trace id, losing hedge leg cancelled-not-error), ingress continuation
as a child span, and the acceptance bar — a fleet request traversing
retry/hedge across engines in REAL OS processes reassembling into one
Chrome/perfetto export with per-process labels.
"""

import json
import os
import subprocess
import sys
import socket
import threading
import time

import pytest

from mmlspark_tpu.core.trace import (
    TraceContext, Tracer, extract_context, format_traceparent,
    merge_chrome_traces, parse_traceparent, to_chrome_trace, use_span,
)
from mmlspark_tpu.serving.fleet import ServingFleet
from mmlspark_tpu.serving.server import serve_model
from mmlspark_tpu.stages.basic import Lambda


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _sleepy_scorer():
    def handle(table):
        rows = [json.loads(r["entity"].decode())
                for r in table["request"]]
        out = []
        for r in rows:
            if r.get("sleep"):
                time.sleep(float(r["sleep"]))
            out.append({"y": r["x"] * 2})
        return table.with_column("reply", out)
    return Lambda.apply(handle)


# ---------------------------------------------------------------------------
# header format
# ---------------------------------------------------------------------------


class TestTraceparent:
    def test_format_parse_round_trip(self):
        hdr = format_traceparent("abcd1234", "ef567890")
        assert hdr == "00-abcd1234-ef567890-01"
        ctx = parse_traceparent(hdr)
        assert ctx.trace_id == "abcd1234"
        assert ctx.parent_id == "ef567890"
        assert ctx.sampled is True
        assert parse_traceparent(
            format_traceparent("ab", "cd",
                               sampled=False)).sampled is False

    def test_legacy_trace_id_with_dashes_survives(self):
        # legacy X-Trace-Id values may carry dashes; when such a trace
        # id rides a traceparent, the span id + flags still anchor
        # from the right
        hdr = format_traceparent("my-trace-1", "abc123")
        ctx = parse_traceparent(hdr)
        assert ctx.trace_id == "my-trace-1"
        assert ctx.parent_id == "abc123"

    @pytest.mark.parametrize("bad", [
        None, "", "garbage", "00-onlythree-01",
        "zz-abc-def-01",              # non-hex version
        "00-abc-nothex!-01",          # non-hex span id
        "00-abc-def-zz",              # non-hex flags
        "00-" + "x" * 70 + "-def-01",  # oversized trace id
        "00-0000-def-01",             # all-zero trace id
    ])
    def test_malformed_is_none(self, bad):
        assert parse_traceparent(bad) is None

    def test_extract_precedence_and_legacy_alias(self):
        # traceparent wins over the legacy header
        ctx = extract_context({
            "Traceparent": "00-tid1-def1-01",
            "X-Trace-Id": "legacy-id"})
        assert ctx.trace_id == "tid1" and ctx.parent_id == "def1"
        # legacy alone: id-only context (no remote parent)
        ctx = extract_context({"x-trace-id": "legacy-id"})
        assert ctx.trace_id == "legacy-id"
        assert ctx.parent_id is None
        assert extract_context({}) is None
        assert extract_context(None) is None

    def test_tracer_inject_extract_round_trip(self):
        tracer = Tracer(enabled=True)
        tr = tracer.new_trace("fleet.post")
        leg = tracer.start_span("client.post", tr)
        headers = tracer.inject(leg)
        ctx = Tracer.extract(headers)
        assert ctx.trace_id == tr.trace_id
        assert ctx.parent_id == leg.span_id
        # the legacy alias rides along for old engines
        assert headers["X-Trace-Id"] == tr.trace_id

    def test_continue_trace_parents_root(self):
        tracer = Tracer(enabled=True)
        ctx = TraceContext("tidX", "cafe01")
        tr = tracer.continue_trace("request", ctx)
        assert tr.trace_id == "tidX"
        assert tr.root.parent_id == "cafe01"
        fresh = tracer.continue_trace("request", None)
        assert fresh.root.parent_id is None


# ---------------------------------------------------------------------------
# Chrome export: process labels + merge
# ---------------------------------------------------------------------------


class TestChromeMerge:
    def test_process_name_metadata(self):
        tracer = Tracer(enabled=True)
        tr = tracer.new_trace("request")
        tracer.finish(tr)
        payload = to_chrome_trace(tracer.buffer.traces(),
                                  process_name="engine X pid=1")
        metas = [e for e in payload["traceEvents"] if e["ph"] == "M"]
        assert metas and metas[0]["name"] == "process_name"
        assert metas[0]["args"]["name"] == "engine X pid=1"
        assert payload["otherData"]["pid"] == os.getpid()

    def test_merge_dedups_spans_and_keeps_processes(self):
        tracer = Tracer(enabled=True)
        tr = tracer.new_trace("request")
        tracer.finish(tr)
        a = to_chrome_trace(tracer.buffer.traces(), process_name="A")
        b = to_chrome_trace(tracer.buffer.traces(), process_name="B")
        # fake a second process for b
        for ev in b["traceEvents"]:
            ev["pid"] = 99999
        b["otherData"]["pid"] = 99999
        merged = merge_chrome_traces(a, b)
        xs = [e for e in merged["traceEvents"] if e["ph"] == "X"]
        assert len(xs) == 2           # same span, two pids — both kept
        # merging the SAME payload twice dedups
        merged2 = merge_chrome_traces(a, a)
        xs2 = [e for e in merged2["traceEvents"] if e["ph"] == "X"]
        assert len(xs2) == 1
        metas = [e for e in merged["traceEvents"] if e["ph"] == "M"]
        assert len(metas) == 2
        assert str(os.getpid()) in merged["otherData"]["epochs"]


# ---------------------------------------------------------------------------
# fleet client legs (in-process)
# ---------------------------------------------------------------------------


class TestClientLegSpans:
    def test_embedder_span_continues_into_fleet_post(self):
        tracer = Tracer(enabled=True)
        fleet = ServingFleet(_sleepy_scorer(), n_engines=1,
                             base_port=19560, batch_size=4,
                             tracer=tracer, slo=False,
                             flight_recorder=False)
        try:
            outer = tracer.new_trace("embedder.op")
            with use_span(outer.root):
                fleet.post({"x": 1}, timeout=10)
            tracer.finish(outer)
            time.sleep(0.2)
            posts = [t for t in tracer.buffer.traces()
                     if t.root.name == "fleet.post"]
            assert posts
            assert posts[-1].trace_id == outer.trace_id
            assert posts[-1].root.parent_id == outer.root.span_id
        finally:
            fleet.stop_all()

    def test_hedged_legs_share_trace_and_loser_cancelled(self):
        """Satellite regression: ALL legs of one logical fleet.post
        share one trace id; the losing hedge leg is marked
        ``cancelled=true`` and NOT ``error`` (the shed-vs-error
        discipline applied to client spans)."""
        tracer = Tracer(enabled=True)
        fleet = ServingFleet(_sleepy_scorer(), n_engines=2,
                             base_port=19570, batch_size=4,
                             tracer=tracer, hedge_percentile=50,
                             hedge_min_s=0.05, slo=False,
                             flight_recorder=False)
        try:
            for i in range(20):       # establish the hedge threshold
                fleet.post({"x": i}, timeout=10)
            hedges0 = fleet.hedged_requests
            body = fleet.post({"x": 3, "sleep": 0.6}, timeout=15)
            assert body == {"y": 6}
            assert fleet.hedged_requests == hedges0 + 1
            time.sleep(0.8)           # let the losing leg's server
            #                           batch finish + buffer
            posts = [t for t in tracer.buffer.traces()
                     if t.root.name == "fleet.post"]
            hedged = [t for t in posts
                      if len([s for s in t.spans()
                              if s.name == "client.post"]) >= 2]
            assert hedged, "no hedged fleet.post trace buffered"
            tr = hedged[-1]
            legs = [s for s in tr.spans() if s.name == "client.post"]
            assert len(legs) == 2
            # one trace id across every leg (and the root)
            assert {s.trace_id for s in legs} == {tr.trace_id}
            # every leg is a SIBLING under the post root
            assert {s.parent_id for s in legs} == {tr.root.span_id}
            winners = [s for s in legs if not s.attrs.get("cancelled")]
            losers = [s for s in legs if s.attrs.get("cancelled")]
            assert len(winners) == 1 and len(losers) == 1
            assert losers[0].status != "error", \
                "losing hedge leg must be cancelled, not error"
            assert losers[0].attrs["cancelled"] is True
            # server-side request traces CONTINUE the same trace id,
            # parented on the client legs
            leg_ids = {s.span_id for s in legs}
            server = [t for t in tracer.buffer.traces()
                      if t.root.name == "request"
                      and t.trace_id == tr.trace_id]
            assert len(server) >= 1
            for st in server:
                assert st.root.parent_id in leg_ids
                assert st.root.attrs.get("remote_parent") is True
        finally:
            fleet.stop_all()

    def test_quota_429_is_shed_not_error_on_client_trace(self):
        """Review regression: a tenant-quota 429 is EXPECTED
        back-pressure — the client's fleet.post trace root must be
        shed=true, not error, or a hot tenant's 429 storm floods the
        client tracer's protected tail ring (the server-side
        shed-vs-error discipline, mirrored client-side)."""
        import urllib.error
        from mmlspark_tpu.serving.admission import (
            AdmissionController, TenantQuota,
        )
        from mmlspark_tpu.serving.zoo import ModelZoo
        tracer = Tracer(enabled=True)
        admission = AdmissionController(
            quotas={"greedy": TenantQuota(0.001, burst=1)})
        zoo = ModelZoo(memory_probe=None)
        zoo.register_factory("m", "v1", _sleepy_scorer)
        fleet = ServingFleet(n_engines=1, base_port=19590,
                             batch_size=4, tracer=tracer,
                             zoo=zoo, admission=admission,
                             slo=False, flight_recorder=False)
        try:
            fleet.post({"x": 1}, model="m@v1", tenant="greedy",
                       timeout=10)            # spends the only token
            with pytest.raises(urllib.error.HTTPError) as exc:
                fleet.post({"x": 2}, model="m@v1", tenant="greedy",
                           timeout=10)
            assert exc.value.code == 429
            time.sleep(0.2)
            posts = [t for t in tracer.buffer.traces()
                     if t.root.name == "fleet.post"
                     and t.root.attrs.get("http_status") == 429]
            assert posts, "429 fleet.post trace not buffered"
            assert posts[-1].root.attrs.get("shed") is True
            assert not posts[-1].is_error, \
                "quota 429 must be shed, not error"
        finally:
            fleet.stop_all()
            zoo.close()

    def test_failover_legs_share_trace_id(self):
        """A leg that fails at transport level and the replica that
        rescues it are siblings in ONE trace (the failed leg errored,
        the rescue leg ok)."""
        engine = serve_model(_sleepy_scorer(), port=19580,
                             batch_size=4, tracing=False, slo=False,
                             flight_recorder=False)
        dead = f"http://127.0.0.1:{_free_port()}"
        tracer = Tracer(enabled=True)
        fleet = ServingFleet.connect(
            [dead, engine.source.address], tracer=tracer,
            failure_threshold=1000)   # dead stays in rotation
        try:
            # round-robin: find the post whose FIRST candidate is the
            # dead address (start index advances by one per post)
            for _ in range(4):
                body = fleet.post({"x": 5}, timeout=10)
                assert body == {"y": 10}
            posts = [t for t in tracer.buffer.traces()
                     if t.root.name == "fleet.post"]
            multi = [t for t in posts
                     if len([s for s in t.spans()
                             if s.name == "client.post"]) == 2]
            assert multi, "no failover post captured"
            legs = [s for s in multi[-1].spans()
                    if s.name == "client.post"]
            assert {s.trace_id for s in legs} == {multi[-1].trace_id}
            statuses = sorted(s.status for s in legs)
            assert statuses == ["error", "ok"]
            assert multi[-1].root.attrs.get("failovers") == 1
        finally:
            fleet.stop_all()
            engine.stop()


# ---------------------------------------------------------------------------
# the acceptance bar: real OS processes, one reassembled trace
# ---------------------------------------------------------------------------


def test_cross_process_retry_hedge_one_trace(tmp_path):
    """One logical ``fleet.post`` traversing a transport-level retry
    (dead address) AND a hedge across TWO live engine processes
    reassembles into ONE trace: shared trace id, client legs as
    siblings, each process's server span parented on its leg — proven
    from the engines' EXPORTED buffers, merged into a single
    perfetto-loadable payload with per-process labels."""
    worker = os.path.join(os.path.dirname(__file__), "traced_worker.py")
    procs, addrs, pids, dumps = [], {}, {}, {}
    try:
        for wid in range(2):
            dump = str(tmp_path / f"worker{wid}.json")
            p = subprocess.Popen(
                [sys.executable, worker, str(_free_port()), str(wid),
                 dump],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True)
            procs.append(p)
            line = p.stdout.readline().strip()   # blocks until READY
            tag, wid_s, addr, pid_s = line.split()
            assert tag == "READY" and int(wid_s) == wid, line
            addrs[wid], pids[wid], dumps[wid] = addr, int(pid_s), dump

        dead = f"http://127.0.0.1:{_free_port()}"
        tracer = Tracer(enabled=True)
        fleet = ServingFleet.connect(
            [dead, addrs[0], addrs[1]], tracer=tracer,
            failure_threshold=1000,   # the dead leg stays in rotation
            hedge_percentile=50, hedge_min_s=0.05)

        # establish the hedge latency threshold with fast traffic
        for i in range(20):
            body = fleet.post({"x": i}, timeout=15)
            assert body["echo"] == i

        # now the target request: stall worker 0 so its leg hedges to
        # worker 1; issue a few so at least one post's round-robin
        # order starts at the dead address (retry) AND routes its
        # failover leg to the stalled worker (hedge)
        target = None
        for i in range(9):
            hedges0 = fleet.hedged_requests
            fleet.post({"x": 100 + i, "stall_worker": 0,
                        "stall_s": 0.8}, timeout=20)
            if fleet.hedged_requests == hedges0:
                continue
            time.sleep(0.1)
            posts = [t for t in tracer.buffer.traces()
                     if t.root.name == "fleet.post"]
            for t in posts:
                legs = [s for s in t.spans() if s.name == "client.post"]
                if len(legs) >= 3:    # dead + stalled + hedge
                    target = t
                    break
            if target is not None:
                break
        assert target is not None, \
            "no post traversed retry + hedge (3 client legs)"
        legs = [s for s in target.spans() if s.name == "client.post"]
        assert {s.trace_id for s in legs} == {target.trace_id}
        assert {s.parent_id for s in legs} == {target.root.span_id}
        errored = [s for s in legs if s.status == "error"]
        cancelled = [s for s in legs if s.attrs.get("cancelled")]
        assert errored, "the dead-address leg must be errored"
        assert cancelled, "the losing hedge leg must be cancelled"

        # let the stalled worker finish serving the abandoned leg so
        # its buffer holds the trace, then shut down + dump (each post
        # stops whichever worker answers; failover routes around the
        # already-stopped ones)
        time.sleep(1.2)
        for _ in range(2):
            try:
                fleet.post({"__shutdown__": True}, timeout=15)
            except Exception:  # noqa: BLE001 — both may already be down
                pass
        for wid, p in enumerate(procs):
            out, err = p.communicate(timeout=60)
            assert p.returncode == 0, \
                f"worker {wid} rc={p.returncode}\n{err}"
            assert f"DUMPED {wid}" in out, out

        exports = [json.load(open(dumps[wid])) for wid in (0, 1)]
        client_export = to_chrome_trace(
            tracer.buffer.traces(),
            process_name=f"fleet client pid={os.getpid()}")
        merged = merge_chrome_traces(client_export, *exports)

        tid = target.trace_id
        events = [e for e in merged["traceEvents"]
                  if e.get("ph") == "X"
                  and e.get("args", {}).get("trace_id") == tid]
        assert events, "merged export lost the target trace"
        # ≥2 engine processes + the client process on one timeline
        ev_pids = {e["pid"] for e in events}
        assert pids[0] in ev_pids and pids[1] in ev_pids, \
            f"trace must span both engine processes: {ev_pids}"
        assert os.getpid() in ev_pids
        assert len(ev_pids) >= 3
        # per-process labels present for every engine process
        metas = {e["pid"]: e["args"]["name"]
                 for e in merged["traceEvents"]
                 if e.get("ph") == "M" and e["name"] == "process_name"}
        assert pids[0] in metas and pids[1] in metas
        assert "engine" in metas[pids[0]]
        # server request roots parent onto client leg span ids
        leg_ids = {s.span_id for s in legs}
        server_roots = [e for e in events if e["name"] == "request"
                        and e["pid"] in (pids[0], pids[1])]
        assert len(server_roots) >= 2, \
            "both engines' server spans must be in the merged trace"
        for ev in server_roots:
            assert ev["args"].get("parent_id") in leg_ids, \
                "server root must be a child of a client leg"
        # the whole thing must be JSON-serializable (perfetto-loadable)
        json.dumps(merged)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
