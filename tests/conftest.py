"""Test configuration: force an 8-device virtual CPU mesh BEFORE any
backend initialization.

Distributed-without-a-cluster pattern (ref: SURVEY.md §4 — LightGBM tests
run local[*] with partitions as nodes): we fake a TPU pod with 8 virtual
CPU devices so all sharding/collective code paths run in CI on CPU.

Note: this image's site customization imports jax at interpreter start
and pins JAX_PLATFORMS=axon (the real TPU tunnel), so env vars are too
late — we must use jax.config.update before first backend use.
"""

import os
import sys

os.environ.setdefault("MMLSPARK_TPU_TEST_MODE", "1")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

from mmlspark_tpu.utils.jax_compat import set_cpu_device_count  # noqa: E402

set_cpu_device_count(8)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_mesh_devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs
