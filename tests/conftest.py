"""Test configuration: force an 8-device virtual CPU mesh BEFORE any
backend initialization.

Distributed-without-a-cluster pattern (ref: SURVEY.md §4 — LightGBM tests
run local[*] with partitions as nodes): we fake a TPU pod with 8 virtual
CPU devices so all sharding/collective code paths run in CI on CPU.

Note: this image's site customization imports jax at interpreter start
and pins JAX_PLATFORMS=axon (the real TPU tunnel), so env vars are too
late — we must use jax.config.update before first backend use.
"""

import os
import sys

os.environ.setdefault("MMLSPARK_TPU_TEST_MODE", "1")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

from mmlspark_tpu.utils.jax_compat import set_cpu_device_count  # noqa: E402

set_cpu_device_count(8)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_mesh_devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="module")
def forced_host_device_count():
    """The sharded-serving test module's forced-host-device-count
    recipe (docs/sharded_serving.md): this process already runs on 8
    virtual CPU devices (forced above, before backend init — it cannot
    change per module), so the fixture (1) asserts the in-process mesh
    is real and (2) exports the SAME count to the child processes the
    sharded tests spawn (serving workers, the AOT cold-start runner)
    via XLA_FLAGS + JAX_PLATFORMS, so their meshes match the exported
    artifacts'. Restores the environment afterwards so other modules'
    subprocess tests see what they always saw."""
    n = 8
    assert len(jax.devices()) >= n, \
        f"expected >={n} virtual devices, got {len(jax.devices())}"
    flag = f"--xla_force_host_platform_device_count={n}"
    old_flags = os.environ.get("XLA_FLAGS")
    old_platforms = os.environ.get("JAX_PLATFORMS")
    if flag not in (old_flags or ""):
        os.environ["XLA_FLAGS"] = ((old_flags + " ") if old_flags
                                   else "") + flag
    os.environ["JAX_PLATFORMS"] = "cpu"
    yield n
    for key, old in (("XLA_FLAGS", old_flags),
                     ("JAX_PLATFORMS", old_platforms)):
        if old is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = old
