"""Test configuration: force an 8-device virtual CPU mesh BEFORE jax import.

Distributed-without-a-cluster pattern (ref: SURVEY.md §4 — LightGBM tests
run local[*] with partitions as nodes): we fake a TPU pod with
``--xla_force_host_platform_device_count=8`` so all sharding/collective
code paths run in CI on CPU.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_mesh_devices():
    import jax
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs
